"""graftcheck v3: thread-topology inference and the lockset race rules.

Three layers, mirroring the suite convention:

1. **The shipped tree is clean** — shared-state-guard and check-then-act run
   whole-program over ``flink_ml_tpu`` with zero suppressions and zero
   findings, and the inferred topology names the real fleet roles
   (micro-batcher, model-version-poller, loadgen-collector, batch-readback).
2. **The analyzer works** — clean + seeded-dirty fixtures per rule:
   cross-thread unguarded write, inconsistent lockset, split check-then-act,
   pool-resolved spawn targets, the ``owned-by`` exemption (honored and
   *verified*), the ``serialized`` handoff mark, multi-instance self-races,
   and the interprocedural lock context that keeps ``_reap_locked``-style
   helpers quiet.
3. **The framework works** — the historical 5-node serving lock graph is a
   subgraph of the whole-program graph, changed-only reporting anchors race
   findings at the access site, and a facts-schema bump invalidates the
   warm cache.
"""
from __future__ import annotations

import os
import sys
import threading

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.graftcheck import Project, run_rules  # noqa: E402
import tools.graftcheck.rules  # noqa: F401, E402  (registration)
from tools.graftcheck.index import FACTS_VERSION  # noqa: E402
from tools.graftcheck.rules.lock_order import build_lock_graph, _lock_id  # noqa: E402
from tools.graftcheck.topology import (  # noqa: E402
    MAIN_ROLE,
    build_topology,
    lock_context,
    topology_for,
)

from tests.test_graftcheck import run_on, write_tree  # noqa: E402

RACE_RULES = ["shared-state-guard", "check-then-act"]


def project_on(root, files) -> Project:
    write_tree(root, files)
    return Project(str(root), ["flink_ml_tpu"])


# -----------------------------------------------------------------------------
# 1. shipped tree: clean, and the topology names the real fleet
# -----------------------------------------------------------------------------


def test_shipped_tree_clean_for_race_rules():
    result = run_rules(Project(REPO_ROOT, ["flink_ml_tpu"]), rules=RACE_RULES)
    assert result.findings == [], "\n".join(f.render() for f in result.findings)
    assert result.suppressed == []  # zero suppressions, by contract


def test_shipped_tree_topology_names_the_fleet_roles():
    project = Project(REPO_ROOT, ["flink_ml_tpu"])
    topo = topology_for(project)
    assert {
        "micro-batcher",
        "model-version-poller",
        "loadgen-collector",
        "batch-readback",
    } <= set(topo.roles)
    # pool / looped spawns are multi-instance; the singleton loops are not
    assert topo.is_multi("loadgen-collector")
    assert topo.is_multi("batch-readback")
    assert not topo.is_multi("micro-batcher")
    assert not topo.is_multi("model-version-poller")
    # role assignment crosses modules through the resolved call graph
    assert "micro-batcher" in topo.roles_of(
        "flink_ml_tpu.serving.batcher:MicroBatcher._reap_locked"
    )
    assert topo.roles_of("flink_ml_tpu.serving.registry:ModelVersionPoller.poll_once") >= {
        MAIN_ROLE,
        "model-version-poller",
    }
    assert "loadgen-collector" in topo.roles_of(
        "flink_ml_tpu.loadgen.generator:StepStats.note_completed"
    )
    # the controller ledger runs on the batcher thread (typed-attr resolution)
    assert "micro-batcher" in topo.roles_of(
        "flink_ml_tpu.serving.controller:GoodputLedger.add"
    )


def test_shipped_tree_lock_context_covers_locked_helpers():
    project = Project(REPO_ROOT, ["flink_ml_tpu"])
    ctx = lock_context(project.index, _lock_id)
    assert ctx["flink_ml_tpu.serving.batcher:MicroBatcher._reap_locked"] == {
        "flink_ml_tpu.serving.batcher.MicroBatcher._lock"
    }
    assert ctx["flink_ml_tpu.serving.controller:GoodputLedger._evict_locked"] == {
        "flink_ml_tpu.serving.controller.GoodputLedger._lock"
    }


def test_historical_serving_lock_graph_is_a_subgraph():
    """The PR 3/6 hand-scoped 5-node serving graph must survive, verbatim,
    inside the whole-program graph the deleted SCOPE allowlist gave way to."""
    project = Project(REPO_ROOT, ["flink_ml_tpu"])
    whole = build_lock_graph(project)
    historical = build_lock_graph(
        project, scope=("flink_ml_tpu/serving/", "flink_ml_tpu/metrics.py")
    )
    assert set(historical.nodes) <= set(whole.nodes)
    assert set(historical.edges) <= set(whole.edges)
    assert set(historical.nodes) >= {
        "flink_ml_tpu.serving.batcher.MicroBatcher._lock",
        "flink_ml_tpu.serving.registry.ModelRegistry._lock",
        "flink_ml_tpu.serving.server.InferenceServer._template_lock",
        "flink_ml_tpu.metrics.Histogram._lock",
        "flink_ml_tpu.metrics.MetricsRegistry._lock",
    }
    # ... and whole-program scoping actually added the new subsystems' locks
    assert {
        "flink_ml_tpu.serving.controller.AdaptiveController._lock",
        "flink_ml_tpu.serving.controller.GoodputLedger._lock",
        "flink_ml_tpu.serving.registry.ModelVersionPoller._lock",
        "flink_ml_tpu.loadgen.generator.StepStats._lock",
        "flink_ml_tpu.trace.SpanRecorder._lock",
        "flink_ml_tpu.config.Configuration._lock",
        "flink_ml_tpu.faults.FaultInjector._lock",
        "flink_ml_tpu.builder.batch_plan._POOL_LOCK",
    } <= set(whole.nodes)
    # the batcher's calls into the controller join the acyclicity contract
    assert (
        "flink_ml_tpu.serving.batcher.MicroBatcher._lock",
        "flink_ml_tpu.serving.controller.AdaptiveController._lock",
    ) in whole.edges
    assert whole.cycles() == []


# -----------------------------------------------------------------------------
# 2. topology inference units (synthetic two-thread module)
# -----------------------------------------------------------------------------

TWO_THREAD = {
    "flink_ml_tpu/race/twothread.py": """
        import threading

        def helper():
            return 1

        def worker():
            return helper()

        def main_entry():
            helper()
            t = threading.Thread(target=worker, name="worker-loop")
            t.start()
            return t
    """
}


def test_topology_two_thread_module(tmp_path):
    project = project_on(tmp_path, TWO_THREAD)
    topo = build_topology(project.index)
    assert set(topo.roles) == {"worker-loop"}
    assert not topo.is_multi("worker-loop")
    mod = "flink_ml_tpu.race.twothread"
    assert topo.roles_of(f"{mod}:worker") == {"worker-loop"}
    assert topo.roles_of(f"{mod}:main_entry") == {MAIN_ROLE}
    assert topo.roles_of(f"{mod}:helper") == {MAIN_ROLE, "worker-loop"}


def test_topology_resolves_self_method_spawn_target(tmp_path):
    files = {
        "flink_ml_tpu/race/cls.py": """
            import threading

            class Batcher:
                def __init__(self):
                    self._thread = threading.Thread(
                        target=self._loop, name=f"my-batcher[{id(self)}]"
                    )

                def _loop(self):
                    self._drain()

                def _drain(self):
                    pass
        """
    }
    project = project_on(tmp_path, files)
    topo = build_topology(project.index)
    # f-string literal head, trailing separator stripped
    assert "my-batcher" in topo.roles
    mod = "flink_ml_tpu.race.cls"
    assert topo.roles_of(f"{mod}:Batcher._drain") == {"my-batcher"}


def test_topology_resolves_pool_spawn_target(tmp_path):
    files = {
        "flink_ml_tpu/race/pool.py": """
            from concurrent.futures import ThreadPoolExecutor

            def work():
                return 1

            def run():
                with ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="tally-worker"
                ) as pool:
                    for _ in range(4):
                        pool.submit(work)
        """
    }
    project = project_on(tmp_path, files)
    topo = build_topology(project.index)
    assert "tally-worker" in topo.roles
    assert topo.is_multi("tally-worker")  # pools are multi-instance
    assert topo.roles_of("flink_ml_tpu.race.pool:work") == {"tally-worker"}


def test_topology_loop_spawn_is_multi_and_unresolved_targets_reported(tmp_path):
    files = {
        "flink_ml_tpu/race/many.py": """
            import threading

            def worker():
                return 1

            def run(fn):
                threads = [
                    threading.Thread(target=worker, name="collector")
                    for _ in range(8)
                ]
                threading.Thread(target=fn).start()  # param: unresolvable
                return threads
        """
    }
    project = project_on(tmp_path, files)
    topo = build_topology(project.index)
    assert topo.is_multi("collector")  # spawned in a comprehension
    assert any(ref == ["n", "fn"] for _rel, _line, ref in topo.unresolved_spawns)


def test_lock_context_intersection_semantics(tmp_path):
    files = {
        "flink_ml_tpu/race/ctx.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()

                def locked_only(self):
                    with self._lock:
                        self._helper()

                def mixed(self):
                    self._also()
                    with self._lock:
                        self._also()

                def _helper(self):
                    pass

                def _also(self):
                    pass
        """
    }
    project = project_on(tmp_path, files)
    ctx = lock_context(project.index, _lock_id)
    mod = "flink_ml_tpu.race.ctx"
    lock = f"{mod}.Box._lock"
    assert ctx[f"{mod}:Box._helper"] == {lock}  # every caller holds it
    assert ctx[f"{mod}:Box._also"] == set()  # one lock-free call site kills it


# -----------------------------------------------------------------------------
# 3. shared-state-guard fixtures
# -----------------------------------------------------------------------------

UNGUARDED = {
    "flink_ml_tpu/race/unguarded.py": """
        import threading

        class Worker:
            def __init__(self):
                self._count = 0
                self._thread = threading.Thread(target=self._loop, name="worker-loop")

            def _loop(self):
                self._count += 1

            def read(self):
                return self._count
    """
}


def test_cross_thread_unguarded_write_flags_with_roles(tmp_path):
    result = run_on(tmp_path, UNGUARDED, rules=["shared-state-guard"])
    assert len(result.findings) == 1
    f = result.findings[0]
    assert f.severity == "error" and result.exit_code == 1
    assert "Worker._count" in f.message and "empty lockset" in f.message
    # the inferred thread roles are named in the message
    assert "worker-loop" in f.message and "main" in f.message


INCONSISTENT = {
    "flink_ml_tpu/race/inconsistent.py": """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0
                self._thread = threading.Thread(target=self._loop, name="worker-loop")

            def _loop(self):
                with self._lock:
                    self._count += 1

            def read(self):
                return self._count
    """
}


def test_inconsistent_lockset_flags_at_the_unlocked_access(tmp_path):
    result = run_on(tmp_path, INCONSISTENT, rules=["shared-state-guard"])
    assert len(result.findings) == 1
    f = result.findings[0]
    assert "inconsistent lockset" in f.message and "Worker._lock" in f.message
    assert "read in Worker.read" in f.message
    # anchored at the unlocked access site (the `return self._count` line)
    assert f.path == "flink_ml_tpu/race/inconsistent.py"
    assert "worker-loop" in f.message


CLEAN = {
    "flink_ml_tpu/race/clean.py": """
        import queue
        import threading

        class Worker:
            def __init__(self, size):
                self._lock = threading.Lock()
                self._count = 0
                self.size = size                      # immutable after __init__
                self._inbox = queue.Queue()           # inherently safe
                self._wake = threading.Event()        # inherently safe
                self._thread = threading.Thread(target=self._loop, name="worker-loop")

            def _loop(self):
                with self._lock:
                    self._count += 1
                self._inbox.put(self.size)
                self._wake.set()

            def read(self):
                with self._lock:
                    return self._count
    """
}


def test_consistent_lockset_and_safe_shapes_are_clean(tmp_path):
    result = run_on(tmp_path, CLEAN, rules=RACE_RULES)
    assert result.findings == [], [f.render() for f in result.findings]


def test_single_role_state_is_not_flagged(tmp_path):
    # No spawn anywhere: only the main role exists, nothing can interleave.
    files = {
        "flink_ml_tpu/race/solo.py": """
            class Model:
                def __init__(self):
                    self.steps = 0

                def fit(self):
                    self.steps += 1
        """
    }
    result = run_on(tmp_path, files, rules=RACE_RULES)
    assert result.findings == []


def test_multi_instance_role_races_with_itself(tmp_path):
    files = {
        "flink_ml_tpu/race/poolrace.py": """
            from concurrent.futures import ThreadPoolExecutor

            class Tally:
                def __init__(self):
                    self.total = 0

                def bump(self):
                    self.total += 1

            def run(tally: Tally):
                with ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="tally-worker"
                ) as pool:
                    for _ in range(8):
                        pool.submit(tally.bump)
        """
    }
    result = run_on(tmp_path, files, rules=["shared-state-guard"])
    assert len(result.findings) == 1
    assert "Tally.total" in result.findings[0].message
    assert "tally-worker(multi)" in result.findings[0].message


def test_guarded_helper_called_under_lock_is_clean(tmp_path):
    # The interprocedural lock context: _drain touches state with no lexical
    # lock, but every resolved call site holds it.
    files = {
        "flink_ml_tpu/race/helper.py": """
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []
                    self._thread = threading.Thread(target=self._loop, name="worker-loop")

                def _loop(self):
                    with self._lock:
                        self._drain()

                def _drain(self):
                    self._items.clear()

                def push(self, x):
                    with self._lock:
                        self._items.append(x)
        """
    }
    result = run_on(tmp_path, files, rules=["shared-state-guard"])
    assert result.findings == [], [f.render() for f in result.findings]


# -- owned-by ----------------------------------------------------------------

OWNED_OK = {
    "flink_ml_tpu/race/owned.py": """
        import threading

        class Gauge:
            def __init__(self):
                self.level = 0  # graftcheck: owned-by=filler-loop
                self._thread = threading.Thread(target=self._fill, name="filler-loop")

            def _fill(self):
                self.level += 1

            def read(self):
                return self.level
    """
}


def test_owned_by_exempts_the_single_writer_field(tmp_path):
    result = run_on(tmp_path, OWNED_OK, rules=RACE_RULES)
    assert result.findings == [], [f.render() for f in result.findings]


def test_owned_by_violation_is_an_error(tmp_path):
    files = {
        "flink_ml_tpu/race/owned_bad.py": """
            import threading

            class Gauge:
                def __init__(self):
                    self.level = 0  # graftcheck: owned-by=filler-loop
                    self._thread = threading.Thread(target=self._fill, name="filler-loop")

                def _fill(self):
                    self.level += 1

                def reset(self):
                    self.level = 0  # main writes an owned field: violation
        """
    }
    result = run_on(tmp_path, files, rules=["shared-state-guard"])
    assert len(result.findings) == 1
    f = result.findings[0]
    assert "owned-by=filler-loop" in f.message and "violated" in f.message
    assert "main" in f.message


def test_owned_by_unknown_role_is_an_error(tmp_path):
    files = {
        "flink_ml_tpu/race/owned_typo.py": UNGUARDED[
            "flink_ml_tpu/race/unguarded.py"
        ].replace(
            "self._count = 0",
            "self._count = 0  # graftcheck: owned-by=wroker-loop",
        )
    }
    result = run_on(tmp_path, files, rules=["shared-state-guard"])
    assert len(result.findings) == 1
    assert "no such thread role" in result.findings[0].message


def test_owned_by_multi_role_owner_is_an_error(tmp_path):
    files = {
        "flink_ml_tpu/race/owned_multi.py": """
            from concurrent.futures import ThreadPoolExecutor

            class Tally:
                def __init__(self):
                    self.total = 0  # graftcheck: owned-by=tally-worker

                def bump(self):
                    self.total += 1

            def run(tally: Tally):
                with ThreadPoolExecutor(thread_name_prefix="tally-worker") as pool:
                    pool.submit(tally.bump)
        """
    }
    result = run_on(tmp_path, files, rules=["shared-state-guard"])
    assert len(result.findings) == 1
    assert "multi-instance role" in result.findings[0].message


def test_serialized_class_mark_exempts_handoff_types(tmp_path):
    files = {
        "flink_ml_tpu/race/handoff.py": """
            import threading

            class Envelope:  # graftcheck: serialized
                def __init__(self):
                    self.value = None

                def fill(self, v):
                    self.value = v

            class Child(Envelope):
                def refill(self, v):
                    self.value = v

            def worker(env: Envelope):
                env.fill(1)

            def launch(env: Envelope):
                threading.Thread(target=worker, args=(env,), name="filler").start()
                env.fill(0)
        """
    }
    result = run_on(tmp_path, files, rules=RACE_RULES)
    assert result.findings == [], [f.render() for f in result.findings]


# -----------------------------------------------------------------------------
# 4. check-then-act fixtures
# -----------------------------------------------------------------------------

CTA_DIRTY = {
    "flink_ml_tpu/race/cta.py": """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
                self._thread = threading.Thread(target=self._bump, name="bumper")

            def _bump(self):
                with self._lock:
                    room = self._n < 10
                if room:
                    with self._lock:
                        self._n += 1

            def read(self):
                with self._lock:
                    return self._n
    """
}


def test_check_then_act_split_regions_flag(tmp_path):
    result = run_on(tmp_path, CTA_DIRTY, rules=["check-then-act"])
    assert len(result.findings) == 1
    f = result.findings[0]
    assert f.severity == "error"
    assert "Counter._n" in f.message and "separate acquisition" in f.message
    assert "bumper" in f.message  # inferred roles named
    # every access is still consistently guarded: no shared-state finding
    guard = run_on(tmp_path, CTA_DIRTY, rules=["shared-state-guard"])
    assert guard.findings == []


def test_check_then_act_single_region_is_clean(tmp_path):
    files = {
        "flink_ml_tpu/race/cta_ok.py": """
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0
                    self._thread = threading.Thread(target=self._bump, name="bumper")

                def _bump(self):
                    with self._lock:
                        if self._n < 10:
                            self._n += 1

                def read(self):
                    with self._lock:
                        return self._n
        """
    }
    result = run_on(tmp_path, files, rules=["check-then-act"])
    assert result.findings == [], [f.render() for f in result.findings]


def test_check_then_act_skips_single_role_attrs(tmp_path):
    # Same split shape, but nothing else ever runs: no interleaving exists.
    files = {
        "flink_ml_tpu/race/cta_solo.py": """
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        room = self._n < 10
                    if room:
                        with self._lock:
                            self._n += 1
        """
    }
    result = run_on(tmp_path, files, rules=["check-then-act"])
    assert result.findings == []


# -----------------------------------------------------------------------------
# 5. framework: changed-only anchoring, cache invalidation
# -----------------------------------------------------------------------------

CROSS_FILE = {
    "flink_ml_tpu/race/state.py": """
        class Shared:
            def __init__(self):
                self.hits = 0

            def bump(self):
                self.hits += 1

            def read(self):
                return self.hits
    """,
    "flink_ml_tpu/race/spawner.py": """
        import threading

        from flink_ml_tpu.race.state import Shared

        def launch():
            shared = Shared()
            threading.Thread(target=shared.bump, name="bumper").start()
            return shared.read()
    """,
}


def test_changed_only_reports_at_the_access_site(tmp_path):
    """The race is only a race because of the spawn in spawner.py — but the
    finding anchors at the access site in state.py, so a changed set
    containing state.py reports it even though the conflicting evidence
    lives elsewhere."""
    result = run_on(tmp_path, CROSS_FILE, rules=["shared-state-guard"])
    assert len(result.findings) == 1
    assert result.findings[0].path == "flink_ml_tpu/race/state.py"
    narrowed = result.restricted_to({"flink_ml_tpu/race/state.py"})
    assert len(narrowed.findings) == 1 and narrowed.exit_code == 1
    elsewhere = result.restricted_to({"flink_ml_tpu/race/spawner.py"})
    assert elsewhere.findings == [] and elsewhere.exit_code == 0


def test_facts_version_bump_invalidates_the_cache(tmp_path, monkeypatch):
    from tools.graftcheck.cache import IndexCache

    write_tree(tmp_path, INCONSISTENT)
    cache_path = str(tmp_path / ".gc" / "cache.json")

    def run_with_cache():
        cache = IndexCache(cache_path)
        project = Project(str(tmp_path), ["flink_ml_tpu"], cache=cache)
        result = run_rules(project, rules=RACE_RULES)
        project.save_cache()
        return cache, result

    cache1, r1 = run_with_cache()
    assert cache1.misses > 0  # cold: everything extracted
    cache2, r2 = run_with_cache()
    assert cache2.misses == 0 and cache2.hits > 0  # warm: nothing re-parsed
    # a facts-schema bump (new spawn/attr-access facts) drops the whole cache
    monkeypatch.setattr("tools.graftcheck.cache.FACTS_VERSION", FACTS_VERSION + 1)
    cache3, r3 = run_with_cache()
    assert cache3.hits == 0 and cache3.misses > 0
    # the cache is a pure accelerator: findings identical on every run
    assert [f.message for f in r1.findings] == [f.message for f in r2.findings]
    assert [f.message for f in r2.findings] == [f.message for f in r3.findings]
    assert len(r1.findings) == 1


def test_race_rules_are_suppressible_like_any_rule(tmp_path):
    files = {
        "flink_ml_tpu/race/sup.py": UNGUARDED[
            "flink_ml_tpu/race/unguarded.py"
        ].replace(
            "self._count += 1",
            "self._count += 1  # graftcheck: disable=shared-state-guard",
        )
    }
    result = run_on(tmp_path, files, rules=["shared-state-guard"])
    # the finding anchors at the write (first offender) — suppressed there
    assert result.findings == []
    assert len(result.suppressed) == 1
