"""Batch transform fast path (builder/batch_plan.py) — the compiled-plan
contract:

- **bit-exact fusion**: every transformer exporting a KernelSpec produces
  fused results bit-identical to its per-stage ``transform``, alone and in
  chains, at reduction-sensitive widths (8/16/256);
- **chunked execution**: chunk/prefetch-depth sweeps reproduce the unchunked
  results bit-exactly, with one compile per distinct chunk signature;
- **fallback**: sparse/ragged inputs, spec-less stages mid-chain, and
  row-count-changing params (Bucketizer 'skip') run per-stage, bit-exactly;
- **plan lifecycle**: the plan caches across calls, invalidates on
  ``set_model_data`` / param changes, and ``batch.fastpath`` off is the
  classic path.
"""
import numpy as np
import pytest

from flink_ml_tpu.api.core import Transformer
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.builder import CompiledBatchPlan, PipelineModel
from flink_ml_tpu.config import Options, config
from flink_ml_tpu.linalg.vectors import SparseVector
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.models.feature.binarizer import Binarizer
from flink_ml_tpu.models.feature.bucketizer import Bucketizer
from flink_ml_tpu.models.feature.dct import DCT
from flink_ml_tpu.models.feature.elementwise_product import ElementwiseProduct
from flink_ml_tpu.models.feature.idf import IDFModel
from flink_ml_tpu.models.feature.imputer import ImputerModel
from flink_ml_tpu.models.feature.interaction import Interaction
from flink_ml_tpu.models.feature.kbins_discretizer import KBinsDiscretizerModel
from flink_ml_tpu.models.feature.normalizer import Normalizer
from flink_ml_tpu.models.feature.polynomial_expansion import PolynomialExpansion
from flink_ml_tpu.models.feature.standard_scaler import StandardScalerModel
from flink_ml_tpu.models.feature.vector_assembler import VectorAssembler
from flink_ml_tpu.models.feature.vector_slicer import VectorSlicer

SCOPE = "ml.batch[plan]"


@pytest.fixture(autouse=True)
def _reset_batch_config():
    yield
    config.unset(Options.BATCH_FASTPATH)
    config.unset(Options.BATCH_CHUNK_ROWS)
    config.unset(Options.BATCH_PREFETCH_DEPTH)


def _assert_frames_bitexact(a: DataFrame, b: DataFrame):
    assert a.get_column_names() == b.get_column_names()
    for name in a.get_column_names():
        ca, cb = a.column(name), b.column(name)
        if isinstance(ca, np.ndarray) or isinstance(cb, np.ndarray):
            ca, cb = np.asarray(ca), np.asarray(cb)
            assert ca.dtype == cb.dtype, (name, ca.dtype, cb.dtype)
            np.testing.assert_array_equal(ca, cb, err_msg=name)
        else:
            for va, vb in zip(ca, cb):
                if isinstance(va, SparseVector):
                    np.testing.assert_array_equal(va.to_array(), vb.to_array())
                else:
                    np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


def _transform_both(model: PipelineModel, df: DataFrame):
    """(per-stage result, fused result) for the same model + data, asserting
    the fused run actually rode a compiled plan."""
    config.set(Options.BATCH_FASTPATH, False)
    slow = model.transform(df)
    config.set(Options.BATCH_FASTPATH, True)
    model.invalidate_batch_plan()
    before = metrics.get(SCOPE, MLMetrics.BATCH_FUSED_ROWS, 0)
    fast = model.transform(df)
    # counted once per fused segment, so ≥ one plan's worth of rows
    assert metrics.get(SCOPE, MLMetrics.BATCH_FUSED_ROWS, 0) >= before + len(df)
    return slow, fast


def _vec_df(n, d, seed=7):
    return DataFrame.from_dict(
        {"input": np.random.default_rng(seed).normal(size=(n, d))}
    )


def _scaler(d, seed=0):
    rng = np.random.default_rng(seed)
    m = StandardScalerModel().set_input_col("input").set_output_col("output")
    m.set_with_mean(True)
    m.mean = rng.normal(size=d)
    m.std = np.abs(rng.normal(size=d)) + 0.5
    m.std[min(1, d - 1)] = 0.0  # exercise the zero-std guard in both paths
    return m


def _imputer_model(cols, seed=3):
    m = ImputerModel().set_input_cols(*cols).set_output_cols(
        *[f"{c}_f" for c in cols]
    )
    m.surrogates = np.random.default_rng(seed).normal(size=len(cols))
    return m


def _kbins_model(d, seed=4):
    rng = np.random.default_rng(seed)
    m = KBinsDiscretizerModel().set_input_col("input").set_output_col("output")
    # deliberately ragged per-dim edge counts to exercise the +inf padding
    m.bin_edges = [
        np.sort(rng.normal(size=3 + (i % 3)))
        for i in range(d)
    ]
    return m


def _idf_model(d, seed=5):
    m = IDFModel().set_input_col("input").set_output_col("output")
    m.idf = np.abs(np.random.default_rng(seed).normal(size=d))
    return m


class _Echo(Transformer):
    """Spec-less stage — forces a fallback segment in mixed chains."""

    def transform(self, *inputs):
        (df,) = inputs
        return df.clone()


# ---------------------------------------------------------------------------
# per-transformer bit-exact parity, widths 8/16/256
# ---------------------------------------------------------------------------
N = 203  # odd on purpose: no accidental alignment with chunk sizes


def _case_binarizer(d):
    return Binarizer().set_input_cols("input").set_output_cols("output").set_thresholds(0.2), _vec_df(N, d)


def _case_normalizer(d):
    return Normalizer().set_p(3.0).set_input_col("input").set_output_col("output"), _vec_df(N, d)


def _case_elementwise(d):
    s = np.random.default_rng(11).normal(size=d)
    return ElementwiseProduct().set_scaling_vec(s).set_input_col("input").set_output_col("output"), _vec_df(N, d)


def _case_dct(d):
    return DCT().set_input_col("input").set_output_col("output"), _vec_df(N, d)


def _case_poly(d):
    return PolynomialExpansion().set_degree(2).set_input_col("input").set_output_col("output"), _vec_df(N, d)


def _case_interaction(d):
    df = DataFrame.from_dict(
        {
            "a": np.random.default_rng(12).normal(size=N),
            "input": np.random.default_rng(13).normal(size=(N, d)),
        }
    )
    return Interaction().set_input_cols("a", "input").set_output_col("output"), df


def _case_slicer(d):
    idx = list(range(0, d, 2))
    return VectorSlicer().set_indices(*idx).set_input_col("input").set_output_col("output"), _vec_df(N, d)


def _case_scaler(d):
    return _scaler(d), _vec_df(N, d)


def _case_kbins(d):
    return _kbins_model(d), _vec_df(N, d)


def _case_idf(d):
    df = _vec_df(N, d)
    df.column("input")[np.random.default_rng(14).random((N, d)) < 0.3] = 0.0
    return _idf_model(d), df


def _case_imputer(_d):
    rng = np.random.default_rng(15)
    a, b = rng.normal(size=N), rng.normal(size=N)
    a[rng.random(N) < 0.2] = np.nan
    b[rng.random(N) < 0.2] = np.nan
    return _imputer_model(["a", "b"]), DataFrame.from_dict({"a": a, "b": b})


def _case_bucketizer(_d):
    x = np.random.default_rng(16).normal(size=N) * 3
    stage = (
        Bucketizer()
        .set_input_cols("x")
        .set_output_cols("b")
        .set_splits_array([[-2.0, -0.5, 0.5, 2.0]])
        .set_handle_invalid("keep")
    )
    return stage, DataFrame.from_dict({"x": x})


def _case_assembler(d):
    rng = np.random.default_rng(17)
    df = DataFrame.from_dict(
        {"a": rng.normal(size=N), "input": rng.normal(size=(N, d))}
    )
    stage = (
        VectorAssembler()
        .set_input_cols("a", "input")
        .set_input_sizes(1, d)
        .set_handle_invalid("keep")
        .set_output_col("output")
    )
    return stage, df


CASES = {
    "binarizer": (_case_binarizer, (8, 16, 256)),
    "normalizer": (_case_normalizer, (8, 16, 256)),
    "elementwise_product": (_case_elementwise, (8, 16, 256)),
    "dct": (_case_dct, (8, 16, 256)),
    "poly_expansion": (_case_poly, (8, 16)),  # 256 → 33k monomials: compile-bound
    "interaction": (_case_interaction, (8, 16, 256)),
    "vector_slicer": (_case_slicer, (8, 16, 256)),
    "standard_scaler": (_case_scaler, (8, 16, 256)),
    "kbins": (_case_kbins, (8, 16, 256)),
    "idf": (_case_idf, (8, 16, 256)),
    "imputer": (_case_imputer, (8,)),  # scalar columns: width-independent
    "bucketizer": (_case_bucketizer, (8,)),
    "assembler": (_case_assembler, (8, 16, 256)),
}


@pytest.mark.parametrize(
    "name,width",
    [(n, w) for n, (_, widths) in sorted(CASES.items()) for w in widths],
)
def test_fused_matches_per_stage_bitexact(name, width):
    make, _ = CASES[name]
    stage, df = make(width)
    slow, fast = _transform_both(PipelineModel([stage]), df)
    _assert_frames_bitexact(slow, fast)


# ---------------------------------------------------------------------------
# chains: multi-stage fusion, mixed spec/spec-less, sparse fallback
# ---------------------------------------------------------------------------
def _chain(d=16):
    rng = np.random.default_rng(21)
    scaler = _scaler(d)
    scaler.set_output_col("scaled")
    return [
        scaler,
        Normalizer().set_input_col("scaled").set_output_col("norm"),
        ElementwiseProduct()
        .set_scaling_vec(rng.normal(size=d))
        .set_input_col("norm")
        .set_output_col("prod"),
        Binarizer().set_input_cols("prod").set_output_cols("bin").set_thresholds(0.05),
    ]


def test_four_stage_chain_fused_bitexact():
    model = PipelineModel(_chain())
    slow, fast = _transform_both(model, _vec_df(N, 16))
    _assert_frames_bitexact(slow, fast)
    assert metrics.get(SCOPE, MLMetrics.BATCH_FUSED_STAGES) == 4
    assert metrics.get(SCOPE, MLMetrics.BATCH_FALLBACK_STAGES) == 0


def test_mixed_chain_spec_less_stage_breaks_segment_bitexact():
    stages = _chain()
    stages.insert(2, _Echo())  # scaler+normalizer | echo | product+binarizer
    model = PipelineModel(stages)
    slow, fast = _transform_both(model, _vec_df(N, 16))
    _assert_frames_bitexact(slow, fast)
    assert metrics.get(SCOPE, MLMetrics.BATCH_FUSED_STAGES) == 4
    assert metrics.get(SCOPE, MLMetrics.BATCH_FALLBACK_STAGES) == 1


def test_sparse_input_falls_back_bitexact():
    rng = np.random.default_rng(22)
    vecs = [
        SparseVector(16, np.sort(rng.choice(16, size=4, replace=False)), rng.normal(size=4))
        for _ in range(24)
    ]
    df = DataFrame(["input"], None, [vecs])
    stage = (
        ElementwiseProduct()
        .set_scaling_vec(rng.normal(size=16))
        .set_input_col("input")
        .set_output_col("output")
    )
    model = PipelineModel([stage])
    config.set(Options.BATCH_FASTPATH, False)
    slow = model.transform(df)
    config.set(Options.BATCH_FASTPATH, True)
    before = metrics.get(SCOPE, MLMetrics.BATCH_FALLBACK_SEGMENTS, 0)
    fast = model.transform(df)
    assert metrics.get(SCOPE, MLMetrics.BATCH_FALLBACK_SEGMENTS, 0) == before + 1
    _assert_frames_bitexact(slow, fast)


def test_bucketizer_skip_mode_has_no_spec_and_matches():
    """'skip' changes the row count — host territory; the plan must not fuse."""
    x = np.asarray([-9.0, 0.1, 0.7, 9.0])
    df = DataFrame.from_dict({"x": x})
    stage = (
        Bucketizer()
        .set_input_cols("x")
        .set_output_cols("b")
        .set_splits_array([[0.0, 0.5, 1.0]])
        .set_handle_invalid("skip")
    )
    assert stage.kernel_spec() is None
    assert CompiledBatchPlan.build([stage]) is None
    config.set(Options.BATCH_FASTPATH, True)
    out = PipelineModel([stage]).transform(df)
    np.testing.assert_array_equal(out["b"], [0.0, 1.0])


# ---------------------------------------------------------------------------
# chunked, double-buffered execution
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("depth", [1, 2, 3])
def test_chunked_prefetch_depths_bitexact(depth):
    model = PipelineModel(_chain())
    df = _vec_df(N, 16)
    config.set(Options.BATCH_FASTPATH, False)
    slow = model.transform(df)
    config.set(Options.BATCH_FASTPATH, True)
    config.set(Options.BATCH_CHUNK_ROWS, 64)  # 203 rows → 3 full + 1 remainder
    config.set(Options.BATCH_PREFETCH_DEPTH, depth)
    model.invalidate_batch_plan()
    before_chunks = metrics.get(SCOPE, MLMetrics.BATCH_FUSED_CHUNKS, 0)
    fast = model.transform(df)
    _assert_frames_bitexact(slow, fast)
    assert metrics.get(SCOPE, MLMetrics.BATCH_FUSED_CHUNKS, 0) == before_chunks + 4


def test_chunked_compiles_once_per_signature_and_caches_across_calls():
    model = PipelineModel(_chain())
    df = _vec_df(200, 16)
    config.set(Options.BATCH_FASTPATH, True)
    config.set(Options.BATCH_CHUNK_ROWS, 64)  # 3×64 + 8: two distinct signatures
    before = metrics.get(SCOPE, MLMetrics.BATCH_COMPILES, 0)
    model.transform(df)
    assert metrics.get(SCOPE, MLMetrics.BATCH_COMPILES, 0) == before + 2
    model.transform(df)  # same plan, same signatures: zero new compiles
    assert metrics.get(SCOPE, MLMetrics.BATCH_COMPILES, 0) == before + 2
    hist = metrics.get(SCOPE, MLMetrics.BATCH_CHUNK_MS)
    assert hist is not None and hist.count >= 8


def test_set_model_data_invalidates_cached_plan():
    d = 8
    model = PipelineModel([_scaler(d)])
    df = _vec_df(32, d)
    config.set(Options.BATCH_FASTPATH, True)
    out1 = model.transform(df)
    # swap in different model data through the official route
    replacement = _scaler(d, seed=99)
    model.set_model_data(*replacement.get_model_data())
    out2 = model.transform(df)
    assert not np.array_equal(np.asarray(out1["output"]), np.asarray(out2["output"]))
    config.set(Options.BATCH_FASTPATH, False)
    _assert_frames_bitexact(model.transform(df), out2)


def test_param_change_refreshes_plan():
    stage = Normalizer().set_p(2.0).set_input_col("input").set_output_col("output")
    model = PipelineModel([stage])
    df = _vec_df(32, 8)
    config.set(Options.BATCH_FASTPATH, True)
    out2 = model.transform(df)
    stage.set_p(1.0)
    out1 = model.transform(df)
    assert not np.array_equal(np.asarray(out2["output"]), np.asarray(out1["output"]))
    config.set(Options.BATCH_FASTPATH, False)
    _assert_frames_bitexact(model.transform(df), out1)


def test_fastpath_off_is_classic_path():
    model = PipelineModel(_chain())
    df = _vec_df(40, 16)
    config.set(Options.BATCH_FASTPATH, False)
    before = metrics.get(SCOPE, MLMetrics.BATCH_FUSED_ROWS, 0)
    model.transform(df)
    assert metrics.get(SCOPE, MLMetrics.BATCH_FUSED_ROWS, 0) == before


def test_empty_frame_runs_per_stage():
    model = PipelineModel([Normalizer().set_input_col("input").set_output_col("output")])
    df = DataFrame.from_dict({"input": np.zeros((0, 4))})
    config.set(Options.BATCH_FASTPATH, True)
    out = model.transform(df)
    assert len(out) == 0 and "output" in out.get_column_names()


# ---------------------------------------------------------------------------
# program partition: elementwise runs merge, reduction specs stay solo
# ---------------------------------------------------------------------------
def test_elementwise_runs_merge_reduction_specs_stay_solo():
    d = 16
    rng = np.random.default_rng(41)
    scaler = _scaler(d)
    scaler.set_output_col("scaled")
    ep = (
        ElementwiseProduct()
        .set_scaling_vec(rng.normal(size=d))
        .set_input_col("scaled")
        .set_output_col("prod")
    )
    binz = Binarizer().set_input_cols("prod").set_output_cols("bin").set_thresholds(0.1)
    norm = Normalizer().set_input_col("scaled").set_output_col("norm")
    dct = DCT().set_input_col("prod").set_output_col("freq")

    # scaler | normalizer (row-norm reduction) | ep+binarizer merge
    plan = CompiledBatchPlan.build(
        [scaler, norm, ep.set_input_col("norm"), binz]
    )
    (segment,) = plan.segments
    assert [len(p.specs) for p in segment.programs] == [1, 1, 2]

    # a DCT (matmul) splits an elementwise run: scaler+ep merge, dct solo
    ep2 = (
        ElementwiseProduct()
        .set_scaling_vec(rng.normal(size=d))
        .set_input_col("scaled")
        .set_output_col("prod")
    )
    plan2 = CompiledBatchPlan.build([_scaler(d).set_output_col("scaled"), ep2, dct])
    (segment2,) = plan2.segments
    assert [len(p.specs) for p in segment2.programs] == [2, 1]
    # and the merged plan is still bit-exact against per-stage
    model = PipelineModel([_scaler(d).set_output_col("scaled"), ep2, dct])
    slow, fast = _transform_both(model, _vec_df(N, d))
    _assert_frames_bitexact(slow, fast)


# ---------------------------------------------------------------------------
# binarizer dtype preservation (the upcast fix)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_binarizer_preserves_float_dtype(dtype):
    X = np.random.default_rng(31).normal(size=(16, 4)).astype(dtype)
    df = DataFrame.from_dict({"input": X})
    out = (
        Binarizer()
        .set_input_cols("input")
        .set_output_cols("output")
        .set_thresholds(0.0)
        .transform(df)
    )
    vals = out["output"]
    assert vals.dtype == dtype  # no float64 upcast round-trip
    np.testing.assert_array_equal(vals, (X > 0.0).astype(dtype))
