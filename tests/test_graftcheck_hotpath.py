"""The graftcheck v2 hot-path rules: recompile-hazard, host-sync,
blocking-under-lock and elementwise-claim, each proven on a clean and a
seeded-dirty fixture tree (the analyzer-works layer of the tier-1 gate; the
shipped-tree-clean layer lives in test_graftcheck.py).

These rules are the whole point of the v2 engine: every one of them needs the
cross-module call graph (transitive reaches, singleton/import/constructor/
return-type resolution) and the annotated-hot-root convention
(``# graftcheck: hot-root`` / ``readback`` / ``cold``) that per-file AST
walks could never see.
"""
from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.graftcheck import Project, run_rules  # noqa: E402
import tools.graftcheck.rules  # noqa: F401, E402  (registration)

from tests.test_graftcheck import run_on, write_tree  # noqa: E402


# -----------------------------------------------------------------------------
# recompile-hazard
# -----------------------------------------------------------------------------

RECOMPILE_DIRTY = """
    import jax

    @jax.jit
    def f(x, n):
        if n > 3:
            return x + 1
        return x

    def serve(xs):
        out = []
        for i in range(10):
            k = jax.jit(lambda v: v + i)
            out.append(k(xs))
            out.append(f(xs, i))
        return out

    def per_call(x):
        return jax.jit(lambda v: v * 2)(x)
"""

RECOMPILE_CLEAN = """
    import functools
    import jax
    from functools import partial

    @functools.cache
    def scale_kernel(factor):
        return jax.jit(lambda x: x * factor)   # memoized factory: fine

    @partial(jax.jit, static_argnums=1)
    def g(x, mode):
        if mode:                               # static arg: fine
            return x + 1
        return x

    @jax.jit
    def h(x):
        if x.shape[0] > 4:                     # shape metadata: fine
            return x[:4]
        return x

    module_level = jax.jit(lambda x: x + 1)    # constructed once: fine

    def serve(xs, n):
        k = scale_kernel(2.0)
        for i in range(n):
            xs = k(xs)
            xs = g(xs, True)
        return xs
"""


def test_recompile_hazard_dirty_fixture(tmp_path):
    result = run_on(
        tmp_path, {"flink_ml_tpu/ops/bad.py": RECOMPILE_DIRTY}, rules=["recompile-hazard"]
    )
    msgs = [f.message for f in result.findings]
    assert any("inside a loop" in m for m in msgs), msgs
    assert any("varying Python scalar(s) `i`" in m for m in msgs), msgs
    assert any("branches in Python on traced value(s) n" in m for m in msgs), msgs
    assert any("jit(f)(...)" in m for m in msgs), msgs
    assert all(f.severity == "error" for f in result.findings)
    assert result.exit_code == 1


def test_recompile_hazard_clean_fixture(tmp_path):
    result = run_on(
        tmp_path, {"flink_ml_tpu/ops/ok.py": RECOMPILE_CLEAN}, rules=["recompile-hazard"]
    )
    assert result.findings == [], [f.render() for f in result.findings]


def test_recompile_hazard_hot_region_construction(tmp_path):
    """jit construction reachable from a hot root flags even outside a loop —
    and a `# graftcheck: cold` mark on the lazy-build edge clears it."""
    dirty = {
        "flink_ml_tpu/serving/hot.py": """
            import jax

            class Server:
                def loop(self):  # graftcheck: hot-root
                    return self.plan()

                def plan(self):
                    return jax.jit(lambda v: v + 1)
        """
    }
    result = run_on(tmp_path, dirty, rules=["recompile-hazard"])
    assert len(result.findings) == 1
    assert "hot region" in result.findings[0].message
    clean = {
        "flink_ml_tpu/serving/hot.py": """
            import jax

            class Server:
                def loop(self):  # graftcheck: hot-root
                    return self.plan()

                def plan(self):  # graftcheck: cold
                    return jax.jit(lambda v: v + 1)
        """
    }
    result = run_on(tmp_path / "clean", clean, rules=["recompile-hazard"])
    assert result.findings == []


def test_recompile_hazard_out_of_scope_package(tmp_path):
    result = run_on(
        tmp_path, {"flink_ml_tpu/utils/x.py": RECOMPILE_DIRTY}, rules=["recompile-hazard"]
    )
    assert result.findings == []


# -----------------------------------------------------------------------------
# host-sync
# -----------------------------------------------------------------------------

HOST_SYNC_DIRTY = {
    "flink_ml_tpu/serving/loop.py": """
        from flink_ml_tpu.serving.helpers import finish

        class Batcher:
            def run(self):  # graftcheck: hot-root
                while True:
                    self._step()

            def _step(self):
                return finish(self._execute())

            def _execute(self):
                return object()
    """,
    "flink_ml_tpu/serving/helpers.py": """
        import numpy as np

        def finish(out):
            host = np.asarray(out)
            return out.item() + float(out)
    """,
}

HOST_SYNC_CLEAN = {
    "flink_ml_tpu/serving/loop.py": """
        from flink_ml_tpu.serving.helpers import finish, build

        class Batcher:
            def run(self):  # graftcheck: hot-root
                plan = build()
                return finish(self._execute())

            def _execute(self):
                return object()
    """,
    "flink_ml_tpu/serving/helpers.py": """
        import numpy as np

        def finish(out):  # graftcheck: readback
            return np.asarray(out).item()

        def build():  # graftcheck: cold
            import time
            probe = make_probe()
            return probe.item()

        def make_probe():
            return object()
    """,
}


def test_host_sync_dirty_fixture(tmp_path):
    result = run_on(tmp_path, HOST_SYNC_DIRTY, rules=["host-sync"])
    msgs = [f.message for f in result.findings]
    assert any(".item()" in m for m in msgs), msgs
    assert any("np.asarray(out)" in m for m in msgs), msgs
    assert any("float(out)" in m for m in msgs), msgs
    # findings anchor in the helper file, naming the root that reaches them
    assert all(f.path == "flink_ml_tpu/serving/helpers.py" for f in result.findings)
    assert all("Batcher.run" in f.message for f in result.findings)
    assert result.exit_code == 1


def test_host_sync_readback_and_cold_marks_exempt(tmp_path):
    result = run_on(tmp_path, HOST_SYNC_CLEAN, rules=["host-sync"])
    assert result.findings == [], [f.render() for f in result.findings]


def test_host_sync_without_roots_is_silent(tmp_path):
    files = {
        "flink_ml_tpu/serving/noroot.py": """
            def f(out):
                return out.item()
        """
    }
    result = run_on(tmp_path, files, rules=["host-sync"])
    assert result.findings == []


def test_host_sync_param_heuristics_scoped_to_device_tiers(tmp_path):
    """np.asarray/float on parameters only report in the device-adjacent
    tiers; .item() reports anywhere a hot root reaches."""
    files = {
        "flink_ml_tpu/serving/loop.py": """
            from flink_ml_tpu.api.frame import pack

            class B:
                def run(self):  # graftcheck: hot-root
                    return pack(self._go())

                def _go(self):
                    return object()
        """,
        "flink_ml_tpu/api/frame.py": """
            import numpy as np

            def pack(col):
                host = np.asarray(col)   # host-layer materialization: fine
                return host.item()       # device sync: flagged anywhere
        """,
    }
    result = run_on(tmp_path, files, rules=["host-sync"])
    assert [(".item()" in f.message) for f in result.findings] == [True]


def test_host_sync_reaches_nested_defs(tmp_path):
    files = {
        "flink_ml_tpu/builder/chunks.py": """
            class Plan:
                def run(self, arrs):  # graftcheck: hot-root
                    def readback(a):
                        return a.item()
                    return [readback(a) for a in arrs]
        """
    }
    result = run_on(tmp_path, files, rules=["host-sync"])
    assert len(result.findings) == 1 and ".item()" in result.findings[0].message


INGEST_DIRTY = {
    "flink_ml_tpu/builder/chunks.py": """
        import jax

        class Plan:
            def run(self, arrs):  # graftcheck: hot-root
                return [self._upload(a) for a in arrs]

            def _upload(self, a):
                return jax.device_put(a)   # per-call upload outside the boundary
    """,
}

INGEST_CLEAN = {
    "flink_ml_tpu/builder/chunks.py": """
        import jax

        class Plan:
            def run(self, arrs):  # graftcheck: hot-root
                return [self._upload(a) for a in arrs]

            def _upload(self, a):  # graftcheck: ingest
                return jax.device_put(a)   # THE blessed boundary
    """,
}


def test_host_sync_flags_device_put_in_hot_region(tmp_path):
    """A per-call jax.device_put inside the hot region (outside an ingest
    boundary) is the per-shard-upload leak the sharded fast paths forbid."""
    result = run_on(tmp_path, INGEST_DIRTY, rules=["host-sync"])
    assert len(result.findings) == 1, [f.render() for f in result.findings]
    assert "device_put" in result.findings[0].message
    assert "ingest" in result.findings[0].message


def test_host_sync_ingest_mark_blesses_device_put(tmp_path):
    result = run_on(tmp_path, INGEST_CLEAN, rules=["host-sync"])
    assert result.findings == [], [f.render() for f in result.findings]


def test_host_sync_ingest_mark_does_not_exempt_syncs(tmp_path):
    """The ingest boundary blesses uploads only — a device->host sync inside
    it still flags."""
    files = {
        "flink_ml_tpu/builder/chunks.py": """
            import jax

            class Plan:
                def run(self, arrs):  # graftcheck: hot-root
                    return [self._upload(a) for a in arrs]

                def _upload(self, a):  # graftcheck: ingest
                    probe = jax.device_put(a)
                    return probe.item()
        """,
    }
    result = run_on(tmp_path, files, rules=["host-sync"])
    assert len(result.findings) == 1, [f.render() for f in result.findings]
    assert ".item()" in result.findings[0].message


# -----------------------------------------------------------------------------
# blocking-under-lock
# -----------------------------------------------------------------------------

BLOCKING_DIRTY = {
    "flink_ml_tpu/serving/poller.py": """
        import threading
        import time
        import os
        import jax

        class Poller:
            def __init__(self):
                self._lock = threading.Lock()
                self._wake = threading.Event()
                # A second role acquires the lock: it is CONTENDED, so
                # blocking work under it convoys the other thread.
                self._thread = threading.Thread(target=self._loop, name="poller-loop")

            def _loop(self):
                with self._lock:
                    self.latest = None

            def poll(self):
                with self._lock:
                    time.sleep(0.05)
                    versions = self.scan()
                    self._wake.wait(1.0)
                return versions

            def scan(self):
                return os.listdir(self.directory)

            def warm(self, fn, args):
                with self._lock:
                    return jax.device_put(args)
    """,
}

BLOCKING_CLEAN = {
    "flink_ml_tpu/serving/poller.py": """
        import threading
        import time
        import os

        class Poller:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._thread = threading.Thread(target=self._loop)

            def claim(self):
                with self._cond:
                    self._cond.wait(0.05)   # waits on the HELD lock: releases it
                    return 1

            def poll(self):
                versions = self.scan()      # blocking work outside the lock
                with self._lock:
                    self.latest = versions
                time.sleep(0.05)            # sleep outside the lock
                return versions

            def scan(self):
                return os.listdir(self.directory)

            def close(self):
                with self._lock:
                    self.closed = True
                self._thread.join(1.0)      # join outside the lock

            def _loop(self):
                pass
    """,
}


def test_blocking_under_lock_dirty_fixture(tmp_path):
    result = run_on(tmp_path, BLOCKING_DIRTY, rules=["blocking-under-lock"])
    msgs = [f.message for f in result.findings]
    assert any("sleeps" in m and "time.sleep" in m for m in msgs), msgs
    # transitive: the call to scan() under the lock reaches os.listdir
    assert any("calls" in m and "os.listdir" in m for m in msgs), msgs
    assert any("waits" in m and "_wake" in m for m in msgs), msgs
    assert any("device_put" in m for m in msgs), msgs
    assert result.exit_code == 1


def test_blocking_under_lock_clean_fixture(tmp_path):
    result = run_on(tmp_path, BLOCKING_CLEAN, rules=["blocking-under-lock"])
    assert result.findings == [], [f.render() for f in result.findings]


def test_blocking_under_lock_runs_whole_package(tmp_path):
    """The serving-tier allowlist is gone: the same contended-lock fixture
    flags anywhere in the package (graftcheck v3 topology-driven scoping)."""
    files = {"flink_ml_tpu/iteration/x.py": BLOCKING_DIRTY["flink_ml_tpu/serving/poller.py"]}
    result = run_on(tmp_path, files, rules=["blocking-under-lock"])
    assert any("sleeps" in f.message for f in result.findings), [
        f.render() for f in result.findings
    ]


def test_blocking_under_uncontended_lock_is_quiet(tmp_path):
    """A lock only the main role ever takes convoys nobody: blocking under
    it is exempt — the inferred topology, not a path allowlist, decides."""
    files = {
        "flink_ml_tpu/iteration/y.py": """
            import threading
            import time

            class Builder:
                def __init__(self):
                    self._lock = threading.Lock()

                def build(self):
                    with self._lock:
                        time.sleep(0.05)   # main-role-only lock: no convoy
        """
    }
    result = run_on(tmp_path, files, rules=["blocking-under-lock"])
    assert result.findings == [], [f.render() for f in result.findings]


# -----------------------------------------------------------------------------
# elementwise-claim
# -----------------------------------------------------------------------------

EW_KERNELS = """
    import jax.numpy as jnp

    def scale_fn(x, s):
        return x * s

    def reduce_fn(x):
        return jnp.sum(x, axis=1)

    def chained_fn(x):
        return helper(x) + 1.0

    def helper(x):
        return x @ x.T

    def searchsorted_fn(x, splits):
        return jnp.searchsorted(splits, x)
"""


def _spec_module(fn_import: str, fn_call: str, elementwise: str) -> str:
    return f"""
        from flink_ml_tpu.ops.kernels import {fn_import}
        from flink_ml_tpu.servable.kernel_spec import KernelSpec

        class Stage:
            def transform(self, df):
                return {fn_import}

            def kernel_spec(self):
                def kfn(model, cols):
                    return {{"o": {fn_call}}}
                return KernelSpec(
                    input_cols=["i"], outputs=[("o", None)],
                    model_arrays={{}}, kernel_fn=kfn, elementwise={elementwise},
                )
    """


def test_elementwise_claim_dirty_direct_reduction(tmp_path):
    files = {
        "flink_ml_tpu/ops/kernels.py": EW_KERNELS,
        "flink_ml_tpu/models/feature/bad.py": _spec_module(
            "reduce_fn", 'reduce_fn(cols["i"])', "True"
        ),
    }
    result = run_on(tmp_path, files, rules=["elementwise-claim"])
    assert len(result.findings) == 1
    f = result.findings[0]
    assert "`reduce_fn`" in f.message and "`sum`" in f.message
    assert f.path == "flink_ml_tpu/models/feature/bad.py"
    assert result.exit_code == 1


def test_elementwise_claim_dirty_transitive_matmul(tmp_path):
    """The reduction hides one call down inside ops/kernels.py — and is the
    @ operator, not a named primitive."""
    files = {
        "flink_ml_tpu/ops/kernels.py": EW_KERNELS,
        "flink_ml_tpu/models/feature/bad.py": _spec_module(
            "chained_fn", 'chained_fn(cols["i"])', "True"
        ),
    }
    result = run_on(tmp_path, files, rules=["elementwise-claim"])
    assert len(result.findings) == 1
    assert "`matmul`" in result.findings[0].message


def _sparse_spec_module(fn_import: str, fn_call: str, elementwise: str) -> str:
    return f"""
        from flink_ml_tpu.ops.kernels import {fn_import}
        from flink_ml_tpu.servable.kernel_spec import KernelSpec

        class Stage:
            def transform(self, df):
                return {fn_import}

            def sparse_kernel_spec(self, known):
                def kfn(model, cols):
                    return {{"o": {fn_call}}}
                return KernelSpec(
                    input_cols=["i"], outputs=[("o", None)],
                    model_arrays={{}}, kernel_fn=kfn, elementwise={elementwise},
                )
    """


def test_elementwise_claim_covers_sparse_specs(tmp_path):
    """segment-sum is a reduction (index.REDUCTION_PRIMS): a sparse spec
    claiming elementwise over a gather-scale-segment-sum body would let the
    planner merge a margin fold into an elementwise run — flagged, through
    the ``sparse_kernel_spec`` hook like any ``kernel_spec``."""
    files = {
        "flink_ml_tpu/ops/kernels.py": EW_KERNELS + (
            "\n"
            "    def segment_sum(t):\n"
            "        return t\n"
            "\n"
            "    def sparse_head_fn(v, i, c):\n"
            "        return segment_sum(v * c[i])\n"
        ),
        "flink_ml_tpu/models/feature/sbad.py": _sparse_spec_module(
            "sparse_head_fn", 'sparse_head_fn(cols["v"], cols["i"], model["c"])', "True"
        ),
    }
    result = run_on(tmp_path, files, rules=["elementwise-claim"])
    assert len(result.findings) == 1
    assert "`sparse_head_fn`" in result.findings[0].message
    assert "`segment_sum`" in result.findings[0].message
    # the same spec WITHOUT the claim is fine — merely unmerged
    files["flink_ml_tpu/models/feature/sbad.py"] = _sparse_spec_module(
        "sparse_head_fn", 'sparse_head_fn(cols["v"], cols["i"], model["c"])', "False"
    )
    clean = run_on(tmp_path / "clean", files, rules=["elementwise-claim"])
    assert clean.findings == []


def test_elementwise_claim_clean_fixtures(tmp_path):
    files = {
        "flink_ml_tpu/ops/kernels.py": EW_KERNELS,
        # elementwise over genuinely elementwise bodies: fine
        "flink_ml_tpu/models/feature/ok.py": _spec_module(
            "scale_fn", 'scale_fn(cols["i"], 2.0)', "True"
        ),
        # searchsorted is per-element binary search, not a reduction
        "flink_ml_tpu/models/feature/ok2.py": _spec_module(
            "searchsorted_fn", 'searchsorted_fn(cols["i"], model["s"])', "True"
        ),
        # a reduction WITHOUT the elementwise claim: fine (merely unmerged)
        "flink_ml_tpu/models/feature/ok3.py": _spec_module(
            "reduce_fn", 'reduce_fn(cols["i"])', "False"
        ),
    }
    result = run_on(tmp_path, files, rules=["elementwise-claim"])
    assert result.findings == [], [f.render() for f in result.findings]


def test_elementwise_claim_skips_trees_without_kernels_module(tmp_path):
    files = {
        "flink_ml_tpu/models/feature/x.py": """
            class Stage:
                def kernel_spec(self):
                    return None
        """
    }
    result = run_on(tmp_path, files, rules=["elementwise-claim"])
    assert result.findings == []


# -----------------------------------------------------------------------------
# the shipped tree carries the annotation convention
# -----------------------------------------------------------------------------


def test_shipped_tree_declares_hot_roots_and_readbacks():
    """The annotated-hot-root convention is wired into the real fast paths —
    without roots, host-sync and the hot half of recompile-hazard are inert."""
    project = Project(REPO_ROOT, ["flink_ml_tpu"])
    index = project.index
    marks = {}
    for _f, node, ff in index.iter_functions():
        for mark in ff["marks"]:
            marks.setdefault(mark, []).append(node)
    assert "flink_ml_tpu.serving.batcher:MicroBatcher._loop" in marks["hot-root"]
    assert "flink_ml_tpu.serving.plan:CompiledServingPlan.dispatch" in marks["hot-root"]
    assert "flink_ml_tpu.builder.batch_plan:CompiledBatchPlan._run_fused" in marks["hot-root"]
    assert any("PlanExecution.finalize" in n for n in marks["readback"])
    assert any("readback_one" in n for n in marks["readback"])
    assert any("CompiledServingPlan.build" in n for n in marks["cold"])
    # the sharded fast paths' blessed upload boundaries (pod-scale fan-out)
    assert any("PlanSharding.put_batch" in n for n in marks["ingest"])
    assert any("PlanSharding.put_replicated" in n for n in marks["ingest"])
    assert any(":CompiledBatchPlan._run_fused.ingest" in n or "ingest" in n.rsplit(".", 1)[-1]
               for n in marks["ingest"])
    # and the hot region they span is non-trivial (the call graph resolves
    # through the server/plan/planner layers)
    reach = index.reachable(marks["hot-root"])
    assert "flink_ml_tpu.servable.planner:run_segment" in reach
