"""Persistent compiled-plan cache (servable/plancache.py, docs/plancache.md):

- **zero-compile resume**: a fresh "incarnation" (new plan objects, same
  cache dir) warms every bucket from serialized executables with the XLA
  compile seam poisoned, and serves bit-identically to the incarnation that
  compiled;
- **fail-open, never wrong**: corrupt, truncated, version-mismatched, or
  mid-deserialize-dying entries are quarantined (checkpoint-corrupt
  semantics) and the chain live-compiles — the request path never errors;
- **torn-write discipline**: a store killed mid-write (fault point
  ``plancache.write``) leaves only a ``.tmp`` orphan, never a visible entry;
  the next cache init sweeps it;
- **bounded**: LRU eviction keeps the entry tier under plancache.max.bytes;
- **inactive by default**: with no ``plancache.dir`` configured nothing
  changes — resolve returns None and every plan compiles live.
"""
import os

import numpy as np
import pytest

import flink_ml_tpu.servable.planner as planner
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.config import Options, config
from flink_ml_tpu.faults import InjectedFault, faults
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.servable import (
    LogisticRegressionModelServable,
    PipelineModelServable,
    StandardScalerModelServable,
)
from flink_ml_tpu.servable.plancache import (
    PlanCache,
    program_digest,
    resolve_plan_cache,
)
from flink_ml_tpu.serving import (
    CompiledServingPlan,
    InferenceServer,
    ServingConfig,
    pad_to,
    power_of_two_buckets,
)

DIM = 7  # distinctive width so jit caches don't collide with other tests
BUCKETS = power_of_two_buckets(8)


def _servable(seed=11, dim=DIM):
    rng = np.random.default_rng(seed)
    sc = StandardScalerModelServable().set_input_col("features").set_output_col("scaled")
    sc.mean = rng.normal(size=dim)
    sc.std = np.abs(rng.normal(size=dim)) + 0.5
    sc.set_with_mean(True)
    lr = LogisticRegressionModelServable().set_features_col("scaled")
    lr.coefficient = rng.normal(size=dim)
    return PipelineModelServable([sc, lr])


def _features(n, seed=3, dim=DIM):
    return DataFrame.from_dict(
        {"features": np.random.default_rng(seed).normal(size=(n, dim))}
    )


def _assert_frames_bitexact(a: DataFrame, b: DataFrame):
    assert a.get_column_names() == b.get_column_names()
    for name in a.get_column_names():
        ca, cb = np.asarray(a[name]), np.asarray(b[name])
        assert ca.dtype == cb.dtype, name
        np.testing.assert_array_equal(ca, cb, err_msg=name)


@pytest.fixture
def cache_dir(tmp_path):
    """Point the plan cache at a per-test dir; restore the config after."""
    d = str(tmp_path / "plancache")
    config.set(Options.PLANCACHE_DIR, d)
    try:
        yield d
    finally:
        config.unset(Options.PLANCACHE_DIR)
        config.unset(Options.PLANCACHE_MAX_BYTES)
        faults.reset()


def _pc(name: str, default=0):
    return metrics.get(MLMetrics.PLANCACHE_GROUP, name, default)


def _poison(monkeypatch):
    def blocked(lowered):
        raise AssertionError("XLA compile blocked — cache should have served this")

    monkeypatch.setattr(planner, "_compile_lowered", blocked)


def _entries(cache_dir):
    return sorted(n for n in os.listdir(cache_dir) if n.endswith(".plan"))


# ---------------------------------------------------------------------------
# resolution / defaults
# ---------------------------------------------------------------------------
class TestResolution:
    def test_inactive_without_dir(self):
        assert resolve_plan_cache() is None

    def test_enabled_flag_gates(self, cache_dir):
        assert resolve_plan_cache() is not None
        config.set(Options.PLANCACHE_ENABLED, False)
        try:
            assert resolve_plan_cache() is None
        finally:
            config.unset(Options.PLANCACHE_ENABLED)

    def test_plan_without_cache_compiles_live(self):
        plan = CompiledServingPlan.build(_servable(), scope="ml.serving[pc-off]")
        assert plan.plancache is None
        df = _features(4)
        plan.warmup(df.take([0]), BUCKETS)
        _assert_frames_bitexact(
            _servable().transform(pad_to(df, 4)), plan.execute(pad_to(df, 4))
        )


# ---------------------------------------------------------------------------
# the tentpole: zero-compile resume, bit-identical
# ---------------------------------------------------------------------------
class TestZeroCompileResume:
    def test_second_incarnation_serves_from_cache(self, cache_dir, monkeypatch):
        df = _features(5)
        template = df.take([0])

        plan1 = CompiledServingPlan.build(_servable(), scope="ml.serving[pc-inc1]")
        assert plan1.plancache is not None
        plan1.warmup(template, BUCKETS)
        stores = _pc(MLMetrics.PLANCACHE_STORES)
        assert stores > 0
        first = {b: plan1.execute(pad_to(df, b) if b >= len(df) else df.take(np.arange(b))) for b in BUCKETS}

        # "New incarnation": fresh plan objects over the same cache dir, with
        # the one XLA-compile seam poisoned — every bucket of every program
        # must come off the serialized executables.
        hits_before = _pc(MLMetrics.PLANCACHE_HITS)
        misses_before = _pc(MLMetrics.PLANCACHE_MISSES)
        _poison(monkeypatch)
        plan2 = CompiledServingPlan.build(_servable(), scope="ml.serving[pc-inc2]")
        plan2.warmup(template, BUCKETS)
        assert _pc(MLMetrics.PLANCACHE_MISSES) == misses_before  # zero compiles
        assert _pc(MLMetrics.PLANCACHE_HITS) - hits_before == stores
        for b in BUCKETS:
            padded = pad_to(df, b) if b >= len(df) else df.take(np.arange(b))
            _assert_frames_bitexact(first[b], plan2.execute(padded))

    def test_warmup_gauge_split(self, cache_dir):
        template = _features(1)
        scope = "ml.serving[pc-gauge1]"
        plan1 = CompiledServingPlan.build(_servable(), scope=scope)
        plan1.warmup(template, BUCKETS)
        # All-miss warmup: compile gauge carries (almost) the whole wall.
        assert metrics.get(scope, MLMetrics.SERVING_WARMUP_COMPILE_MS) > 0
        assert metrics.get(scope, MLMetrics.SERVING_WARMUP_CACHE_LOAD_MS) == 0.0
        assert plan1.last_warmup_cache["misses"] > 0
        assert plan1.last_warmup_cache["hits"] == 0

        scope2 = "ml.serving[pc-gauge2]"
        plan2 = CompiledServingPlan.build(_servable(), scope=scope2)
        plan2.warmup(template, BUCKETS)
        # All-hit warmup: the cache gauge carries the load time and the
        # hit/miss stats invert.
        assert metrics.get(scope2, MLMetrics.SERVING_WARMUP_CACHE_LOAD_MS) > 0
        assert plan2.last_warmup_cache["misses"] == 0
        assert plan2.last_warmup_cache["hits"] == plan1.last_warmup_cache["misses"]

    def test_server_resume_zero_serving_compiles(self, cache_dir, monkeypatch):
        cfg = ServingConfig(max_batch_size=8, max_delay_ms=0.1)
        template = _features(1)
        req = _features(5, seed=9)
        with InferenceServer(
            _servable(), name="pc-s1", serving_config=cfg, warmup_template=template
        ) as s1:
            r1 = s1.predict(req)
        _poison(monkeypatch)
        with InferenceServer(
            _servable(), name="pc-s2", serving_config=cfg, warmup_template=template
        ) as s2:
            r2 = s2.predict(req)
            assert metrics.get(s2.scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0) == 0
        _assert_frames_bitexact(r1.dataframe, r2.dataframe)


# ---------------------------------------------------------------------------
# corruption / mismatch / fault injection — fail-open, never wrong
# ---------------------------------------------------------------------------
class TestCorruptionFallback:
    def _warm_one(self, cache_dir, scope):
        plan = CompiledServingPlan.build(_servable(), scope=scope)
        plan.warmup(_features(1), [4])
        assert _entries(cache_dir)
        return plan

    def test_corrupt_entry_quarantined_and_served_live(self, cache_dir):
        self._warm_one(cache_dir, "ml.serving[pc-c1]")
        for name in _entries(cache_dir):
            path = os.path.join(cache_dir, name)
            raw = bytearray(open(path, "rb").read())
            raw[-3] ^= 0xFF  # flip payload bits: CRC must catch it
            open(path, "wb").write(bytes(raw))
        q_before = _pc(MLMetrics.PLANCACHE_QUARANTINED)
        plan2 = CompiledServingPlan.build(_servable(), scope="ml.serving[pc-c2]")
        plan2.warmup(_features(1), [4])
        df = pad_to(_features(3), 4)
        _assert_frames_bitexact(_servable().transform(df), plan2.execute(df))
        assert _pc(MLMetrics.PLANCACHE_QUARANTINED) > q_before
        assert any(
            name.endswith(".corrupt") for name in os.listdir(cache_dir)
        ), "quarantined entry kept for forensics"

    def test_truncated_entry_quarantined(self, cache_dir):
        self._warm_one(cache_dir, "ml.serving[pc-t1]")
        for name in _entries(cache_dir):
            path = os.path.join(cache_dir, name)
            raw = open(path, "rb").read()
            open(path, "wb").write(raw[: len(raw) // 2])  # torn file
        plan2 = CompiledServingPlan.build(_servable(), scope="ml.serving[pc-t2]")
        plan2.warmup(_features(1), [4])
        df = pad_to(_features(3), 4)
        _assert_frames_bitexact(_servable().transform(df), plan2.execute(df))
        assert not _entries(cache_dir) or _pc(MLMetrics.PLANCACHE_QUARANTINED) > 0

    def test_version_mismatch_quarantined(self, cache_dir):
        import json
        import struct
        import zlib

        self._warm_one(cache_dir, "ml.serving[pc-v1]")
        name = _entries(cache_dir)[0]
        path = os.path.join(cache_dir, name)
        raw = open(path, "rb").read()
        (hlen,) = struct.unpack(">I", raw[8:12])
        header = json.loads(raw[12: 12 + hlen])
        header["env"] = dict(header["env"], jaxlib="0.0.0-other")
        hb = json.dumps(header, sort_keys=True).encode()
        open(path, "wb").write(raw[:8] + struct.pack(">I", len(hb)) + hb + raw[12 + hlen:])
        q_before = _pc(MLMetrics.PLANCACHE_QUARANTINED)
        cache = resolve_plan_cache()
        digest = name[: -len(".plan")]
        assert cache.load(digest) is None
        assert _pc(MLMetrics.PLANCACHE_QUARANTINED) == q_before + 1

    def test_fault_plancache_load_quarantines_and_falls_back(self, cache_dir):
        """Deterministic fault at plancache.load: a warmup dying
        mid-deserialize quarantines the entry and live-compiles — the
        request path never sees an error."""
        self._warm_one(cache_dir, "ml.serving[pc-f1]")
        n_entries = len(_entries(cache_dir))
        q_before = _pc(MLMetrics.PLANCACHE_QUARANTINED)
        faults.arm("plancache.load", at=1)
        try:
            plan2 = CompiledServingPlan.build(_servable(), scope="ml.serving[pc-f2]")
            plan2.warmup(_features(1), [4])
            df = pad_to(_features(3), 4)
            _assert_frames_bitexact(_servable().transform(df), plan2.execute(df))
            fires = faults.fires("plancache.load")
        finally:
            faults.reset()
        assert fires == 1
        assert _pc(MLMetrics.PLANCACHE_QUARANTINED) == q_before + 1
        # The quarantined entry was re-stored by the live compile fallback.
        assert len(_entries(cache_dir)) == n_entries

    def test_fault_plancache_write_leaves_torn_tmp_only(self, cache_dir):
        """Deterministic fault at plancache.write: a store killed mid-write
        leaves a torn .tmp orphan, never a visible entry, and the compiled
        chain keeps serving; the next cache init sweeps the orphan."""
        errors_before = _pc(MLMetrics.PLANCACHE_STORE_ERRORS)
        faults.arm("plancache.write", at=1)
        try:
            plan = CompiledServingPlan.build(_servable(), scope="ml.serving[pc-w1]")
            plan.warmup(_features(1), [4])
            df = pad_to(_features(3), 4)
            _assert_frames_bitexact(_servable().transform(df), plan.execute(df))
            fires = faults.fires("plancache.write")
        finally:
            faults.reset()
        assert fires == 1
        assert _pc(MLMetrics.PLANCACHE_STORE_ERRORS) == errors_before + 1
        orphans = [n for n in os.listdir(cache_dir) if ".plan.tmp." in n]
        assert orphans, "torn tmp file left behind (the kill analogue)"
        # The torn write never became an entry for ITS program; later
        # programs of the same warmup stored normally.
        torn_digest = orphans[0].split(".plan.tmp.")[0]
        assert f"{torn_digest}.plan" not in _entries(cache_dir)
        # A new incarnation's cache init sweeps the orphan.
        swept_before = _pc(MLMetrics.PLANCACHE_TMP_SWEPT)
        PlanCache(cache_dir, max_bytes=1 << 30)
        assert not [n for n in os.listdir(cache_dir) if ".plan.tmp." in n]
        assert _pc(MLMetrics.PLANCACHE_TMP_SWEPT) > swept_before

    def test_store_serialize_failure_is_fail_open(self, cache_dir, monkeypatch):
        from jax.experimental import serialize_executable

        def broken(compiled):
            raise ValueError("Compilation does not support serialization")

        monkeypatch.setattr(serialize_executable, "serialize", broken)
        errors_before = _pc(MLMetrics.PLANCACHE_STORE_ERRORS)
        plan = CompiledServingPlan.build(_servable(), scope="ml.serving[pc-ser]")
        plan.warmup(_features(1), [4])
        df = pad_to(_features(3), 4)
        _assert_frames_bitexact(_servable().transform(df), plan.execute(df))
        assert _pc(MLMetrics.PLANCACHE_STORE_ERRORS) > errors_before
        assert not _entries(cache_dir)


# ---------------------------------------------------------------------------
# bounds / lifecycle
# ---------------------------------------------------------------------------
class TestBounds:
    def test_lru_eviction_respects_max_bytes(self, cache_dir):
        plan = CompiledServingPlan.build(_servable(), scope="ml.serving[pc-lru1]")
        plan.warmup(_features(1), BUCKETS)
        entry_bytes = max(
            os.path.getsize(os.path.join(cache_dir, n)) for n in _entries(cache_dir)
        )
        n_before = len(_entries(cache_dir))
        assert n_before >= 4
        # Rebuild the cache with room for ~2 entries: storing one more must
        # evict the stalest down to the bound.
        config.set(Options.PLANCACHE_MAX_BYTES, int(entry_bytes * 2.5))
        small = resolve_plan_cache()
        assert small.max_bytes < small.bytes_used()
        evicted_before = _pc(MLMetrics.PLANCACHE_EVICTED)
        small._enforce_budget()
        assert small.bytes_used() <= small.max_bytes
        assert _pc(MLMetrics.PLANCACHE_EVICTED) > evicted_before
        assert len(_entries(cache_dir)) < n_before
        assert _pc(MLMetrics.PLANCACHE_BYTES) <= small.max_bytes

    def test_hits_refresh_lru_recency(self, cache_dir):
        plan = CompiledServingPlan.build(_servable(), scope="ml.serving[pc-lru2]")
        plan.warmup(_features(1), [2, 4])
        names = _entries(cache_dir)
        oldest = os.path.join(cache_dir, names[0])
        past = os.path.getmtime(oldest) - 3600
        os.utime(oldest, (past, past))
        cache = resolve_plan_cache()
        digest = names[0][: -len(".plan")]
        assert cache.load(digest) is not None
        assert os.path.getmtime(oldest) > past + 1800  # touched on hit


# ---------------------------------------------------------------------------
# digest schema
# ---------------------------------------------------------------------------
class TestDigest:
    def _lowered(self, dim=DIM, rows=4):
        import jax
        import jax.numpy as jnp

        def f(models, cols):
            return {"out": cols["x"] * models["w"]}

        w = np.ones(dim, np.float32)
        return jax.jit(f).lower(
            {"w": w}, {"x": jax.ShapeDtypeStruct((rows, dim), jnp.float32)}
        )

    def test_deterministic_for_equal_programs(self):
        a = program_digest(self._lowered(), kind="exact")
        b = program_digest(self._lowered(), kind="exact")
        assert a == b

    def test_distinguishes_shape_kind_tier_and_topology(self):
        base = program_digest(self._lowered(), kind="exact")
        assert program_digest(self._lowered(rows=8), kind="exact") != base
        assert program_digest(self._lowered(), kind="fused") != base
        assert (
            program_digest(self._lowered(), kind="exact", fusion_key=("fast", True, 1.0))
            != base
        )
        assert (
            program_digest(self._lowered(), kind="exact", sharding_key=(4, 1))
            != base
        )
        assert program_digest(self._lowered(), kind="exact", replicated=True) != base


# ---------------------------------------------------------------------------
# sharded (SPMD) programs
# ---------------------------------------------------------------------------
class TestShardedPlans:
    def test_sharded_plan_resumes_from_cache(self, cache_dir, monkeypatch):
        import jax

        from flink_ml_tpu.servable.sharding import resolve_plan_sharding

        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices for a sharded plan")
        sharding = resolve_plan_sharding(2)
        buckets = sharding.serving_buckets(16)
        df = _features(max(buckets))
        template = df.take([0])

        plan1 = CompiledServingPlan.build(
            _servable(), scope="ml.serving[pc-sh1]", sharding=resolve_plan_sharding(2)
        )
        plan1.warmup(template, buckets)
        assert _pc(MLMetrics.PLANCACHE_STORES) > 0
        first = {b: plan1.execute(df.take(np.arange(b))) for b in buckets}

        misses_before = _pc(MLMetrics.PLANCACHE_MISSES)
        _poison(monkeypatch)
        plan2 = CompiledServingPlan.build(
            _servable(), scope="ml.serving[pc-sh2]", sharding=resolve_plan_sharding(2)
        )
        plan2.warmup(template, buckets)
        assert _pc(MLMetrics.PLANCACHE_MISSES) == misses_before
        for b in buckets:
            _assert_frames_bitexact(first[b], plan2.execute(df.take(np.arange(b))))


# ---------------------------------------------------------------------------
# continuous loop: the warm split
# ---------------------------------------------------------------------------
class TestLoopWarmSplit:
    def test_second_flip_warm_time_moves_to_cache_gauge(self, cache_dir, tmp_path):
        """Cross-version hits: version 2's chain programs have the same
        architecture as version 1's (weight values are arguments, not part
        of the key), so the second flip warms from cache and its warm time
        lands in ml.loop.warm.cache.ms — never booked as compile seconds."""
        from flink_ml_tpu.linalg.vectors import DenseVector
        from flink_ml_tpu.loop import ContinuousLearningLoop, ContinuousTrainer
        from flink_ml_tpu.models.classification.online_logistic_regression import (
            OnlineLogisticRegression,
        )
        from flink_ml_tpu.models.online import QueueBatchStream

        d = DIM
        rng = np.random.default_rng(0)

        def batch(seed):
            X = np.random.default_rng(seed).normal(size=(64, d))
            return {
                "features": X,
                "label": (X @ np.linspace(1, -1, d) > 0).astype(np.float64),
            }

        stream = QueueBatchStream()
        for i in range(2):
            stream.add(batch(i))
        scope = f"{MLMetrics.LOOP_GROUP}[pc-loop]"
        trainer = ContinuousTrainer(
            OnlineLogisticRegression()
            .set_initial_model_data(
                DataFrame(["coefficient"], None, [[DenseVector(np.zeros(d))]])
            )
            .set_global_batch_size(64),
            stream,
            str(tmp_path / "pub"),
            publish_every_versions=1,
            scope=scope,
        )
        server = InferenceServer(
            name="pc-loop",
            serving_config=ServingConfig(max_batch_size=8, max_delay_ms=0.5),
            warmup_template=DataFrame.from_dict(
                {"features": rng.normal(size=(1, d))}
            ),
        )
        loop = ContinuousLearningLoop(trainer, server, name="pc-loop")
        try:
            loop.run(publish_target=2, max_steps=4)
        finally:
            server.close()
        scraped = metrics.scope(scope)
        assert scraped[MLMetrics.LOOP_SWAPPED] == 2
        # The second flip loaded every chain program from the first flip's
        # stores: its warm time is cache-load, not compile.
        assert scraped[MLMetrics.LOOP_WARM_CACHE_MS] > 0.0
        assert _pc(MLMetrics.PLANCACHE_HITS) > 0


# ---------------------------------------------------------------------------
# batch tier
# ---------------------------------------------------------------------------
class TestBatchPlan:
    def test_batch_plan_resumes_from_cache(self, cache_dir, monkeypatch):
        from flink_ml_tpu.builder.batch_plan import CompiledBatchPlan
        from flink_ml_tpu.models.feature.standard_scaler import StandardScalerModel

        rng = np.random.default_rng(5)
        sc = StandardScalerModel().set_input_col("input").set_output_col("scaled")
        sc.set_with_mean(True)
        sc.mean = rng.normal(size=DIM)
        sc.std = np.abs(rng.normal(size=DIM)) + 0.5
        df = DataFrame.from_dict({"input": rng.normal(size=(64, DIM))})

        plan1 = CompiledBatchPlan.build([sc], scope="ml.batch[pc-1]")
        assert plan1.plancache is not None
        out1 = plan1.transform(df)
        assert _pc(MLMetrics.PLANCACHE_STORES) > 0

        _poison(monkeypatch)
        plan2 = CompiledBatchPlan.build([sc], scope="ml.batch[pc-2]")
        out2 = plan2.transform(df)
        _assert_frames_bitexact(out1, out2)
