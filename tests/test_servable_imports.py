"""Tier-1 shim for ``tools/check_servable_imports.py``.

The L1 guarantee from the reference (SURVEY.md §2.6): the servable/serving
tier is deployable without the training runtime. This test makes tier-1
enforce it — any import (even lazy, function-local) of ``iteration/``,
``execution/``, ``builder/`` or ``models/`` from ``flink_ml_tpu/servable/``
or ``flink_ml_tpu/serving/`` fails the suite.
"""
import importlib.util
import os

_TOOL = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
    "check_servable_imports.py",
)


def _load_tool():
    spec = importlib.util.spec_from_file_location("check_servable_imports", _TOOL)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_serving_tier_is_runtime_free():
    tool = _load_tool()
    problems, checked = tool.check()
    assert not problems, "\n".join(problems)
    # Both packages must actually be present in the sweep — an empty check
    # passing would be the guard silently rotting.
    assert any("servable" in f for f in checked)
    assert any(os.path.join("flink_ml_tpu", "serving") in f for f in checked)


def test_checker_catches_lazy_imports(tmp_path):
    """The guard must see function-local imports, not just module top-level."""
    tool = _load_tool()
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def transform(df):\n"
        "    from flink_ml_tpu.models.linear import compute_dots\n"
        "    import flink_ml_tpu.iteration.datacache as dc\n"
        "    from flink_ml_tpu import builder\n"
        "    return compute_dots\n"
    )
    found = sorted(m for _, m in tool._violations_in_file(str(bad)))
    assert found == [
        "flink_ml_tpu.builder",
        "flink_ml_tpu.iteration.datacache",
        "flink_ml_tpu.models.linear",
    ]


def test_checker_allows_runtime_free_imports(tmp_path):
    tool = _load_tool()
    good = tmp_path / "good.py"
    good.write_text(
        "import numpy as np\n"
        "from flink_ml_tpu.api.dataframe import DataFrame\n"
        "from flink_ml_tpu.ops.kernels import compute_dots\n"
        "from flink_ml_tpu.checkpoint import scan_numbered_dirs\n"
    )
    assert list(tool._violations_in_file(str(good))) == []
