"""Tests for the 17 stateless feature transformers (reference test shape: defaults,
transform correctness vs hand-computed values, save/load)."""
import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.linalg.vectors import DenseVector, SparseVector, Vectors
from flink_ml_tpu.models import STAGE_REGISTRY, get_stage_class
from flink_ml_tpu.models.feature.binarizer import Binarizer
from flink_ml_tpu.models.feature.bucketizer import Bucketizer
from flink_ml_tpu.models.feature.dct import DCT
from flink_ml_tpu.models.feature.elementwise_product import ElementwiseProduct
from flink_ml_tpu.models.feature.feature_hasher import FeatureHasher
from flink_ml_tpu.models.feature.hashing_tf import HashingTF
from flink_ml_tpu.models.feature.interaction import Interaction
from flink_ml_tpu.models.feature.ngram import NGram
from flink_ml_tpu.models.feature.normalizer import Normalizer
from flink_ml_tpu.models.feature.polynomial_expansion import PolynomialExpansion
from flink_ml_tpu.models.feature.random_splitter import RandomSplitter
from flink_ml_tpu.models.feature.sql_transformer import SQLTransformer
from flink_ml_tpu.models.feature.stop_words_remover import StopWordsRemover
from flink_ml_tpu.models.feature.tokenizer import RegexTokenizer, Tokenizer
from flink_ml_tpu.models.feature.vector_assembler import VectorAssembler
from flink_ml_tpu.models.feature.vector_slicer import VectorSlicer


def test_binarizer_scalar_and_vector():
    df = DataFrame.from_dict(
        {"a": np.asarray([0.5, 2.0]), "v": np.asarray([[1.0, 3.0], [2.0, 0.0]])}
    )
    out = (
        Binarizer()
        .set_input_cols("a", "v")
        .set_output_cols("ab", "vb")
        .set_thresholds(1.0, 1.5)
        .transform(df)
    )
    np.testing.assert_array_equal(out["ab"], [0.0, 1.0])
    np.testing.assert_array_equal(out["vb"], [[0.0, 1.0], [1.0, 0.0]])


def test_bucketizer_modes():
    df = DataFrame.from_dict({"x": np.asarray([-1.0, 0.5, 1.5, 99.0])})
    b = Bucketizer().set_input_cols("x").set_output_cols("b").set_splits_array([[0.0, 1.0, 2.0]])
    with pytest.raises(ValueError, match="invalid value"):
        b.transform(df)
    out_keep = b.set_handle_invalid("keep").transform(df)
    np.testing.assert_array_equal(out_keep["b"], [2.0, 0.0, 1.0, 2.0])
    out_skip = b.set_handle_invalid("skip").transform(df)
    np.testing.assert_array_equal(out_skip["b"], [0.0, 1.0])
    # right edge of last bucket is inclusive
    df2 = DataFrame.from_dict({"x": np.asarray([2.0])})
    np.testing.assert_array_equal(
        b.set_handle_invalid("error").transform(df2)["b"], [1.0]
    )


def test_dct_forward_inverse_round_trip():
    X = np.random.default_rng(0).normal(size=(4, 8))
    df = DataFrame.from_dict({"input": X})
    fwd = DCT().transform(df)
    # Parseval: orthonormal transform preserves norms (float32 compute on device)
    np.testing.assert_allclose(
        np.linalg.norm(fwd["output"], axis=1), np.linalg.norm(X, axis=1), atol=1e-5
    )
    back = DCT().set_inverse(True).set_input_col("output").set_output_col("rec").transform(fwd)
    np.testing.assert_allclose(back["rec"], X, atol=1e-5)


def test_elementwise_product_dense_and_sparse():
    df = DataFrame.from_dict({"input": np.asarray([[1.0, 2.0, 3.0]])})
    out = ElementwiseProduct().set_scaling_vec(DenseVector([2.0, 0.0, -1.0])).transform(df)
    np.testing.assert_array_equal(out["output"], [[2.0, 0.0, -3.0]])
    sv = Vectors.sparse(3, [0, 2], [5.0, 7.0])
    df2 = DataFrame(["input"], None, [[sv]])
    out2 = ElementwiseProduct().set_scaling_vec(DenseVector([2.0, 0.0, -1.0])).transform(df2)
    got = out2["input" if False else "output"][0]
    np.testing.assert_array_equal(got.to_array(), [10.0, 0.0, -7.0])


def test_feature_hasher_accumulates_and_is_stable():
    df = DataFrame.from_dict({"num": np.asarray([1.5]), "cat": ["red"]})
    fh = FeatureHasher().set_input_cols("num", "cat").set_num_features(16)
    out1 = fh.transform(df)["output"][0]
    out2 = fh.transform(df)["output"][0]
    assert out1.size() == 16
    np.testing.assert_array_equal(out1.to_array(), out2.to_array())
    assert out1.to_array().sum() == pytest.approx(2.5)  # 1.5 numeric + 1.0 categorical


def test_hashing_tf_counts_and_binary():
    df = DataFrame(["terms"], None, [[["a", "b", "a"]]])
    tf = HashingTF().set_input_col("terms").set_num_features(32)
    v = tf.transform(df)["output"][0]
    assert sorted(v.values.tolist()) == [1.0, 2.0]
    vb = tf.set_binary(True).transform(df)["output"][0]
    assert sorted(vb.values.tolist()) == [1.0, 1.0]


def test_interaction_cross_products():
    df = DataFrame.from_dict(
        {"a": np.asarray([2.0]), "v": np.asarray([[1.0, 3.0]]), "w": np.asarray([[5.0, 7.0]])}
    )
    out = Interaction().set_input_cols("a", "v", "w").transform(df)
    np.testing.assert_array_equal(out["output"], [[10.0, 14.0, 30.0, 42.0]])


def test_ngram():
    df = DataFrame(["terms"], None, [[["a", "b", "c", "d"], ["x"]]])
    out = NGram().set_input_col("terms").transform(df)
    assert out["output"][0] == ["a b", "b c", "c d"]
    assert out["output"][1] == []


def test_normalizer_p_norms():
    df = DataFrame.from_dict({"input": np.asarray([[3.0, 4.0]])})
    out2 = Normalizer().transform(df)
    np.testing.assert_allclose(out2["output"], [[0.6, 0.8]], atol=1e-7)
    out1 = Normalizer().set_p(1.0).transform(df)
    np.testing.assert_allclose(out1["output"], [[3 / 7, 4 / 7]], atol=1e-7)


def test_polynomial_expansion_degree2():
    df = DataFrame.from_dict({"input": np.asarray([[2.0, 3.0]])})
    out = PolynomialExpansion().transform(df)
    # combos: x, y, x^2, xy, y^2
    np.testing.assert_array_equal(out["output"], [[2.0, 3.0, 4.0, 6.0, 9.0]])


def test_random_splitter_proportions_and_disjoint():
    df = DataFrame.from_dict({"x": np.arange(10000.0)})
    parts = RandomSplitter().set_weights(4.0, 6.0).set_seed(7).transform(df)
    assert len(parts) == 2
    n0, n1 = len(parts[0]), len(parts[1])
    assert n0 + n1 == 10000
    assert abs(n0 / 10000 - 0.4) < 0.02
    assert not set(parts[0]["x"]) & set(parts[1]["x"])


def test_sql_transformer_select_where():
    df = DataFrame.from_dict({"v1": np.asarray([1.0, 4.0]), "v2": np.asarray([2.0, 5.0])})
    out = (
        SQLTransformer()
        .set_statement("SELECT *, (v1 + v2) AS v3 FROM __THIS__")
        .transform(df)
    )
    assert out.get_column_names() == ["v1", "v2", "v3"]
    np.testing.assert_array_equal(out["v3"], [3.0, 9.0])
    out2 = (
        SQLTransformer()
        .set_statement("SELECT v1 FROM __THIS__ WHERE v2 = 5.0")
        .transform(df)
    )
    np.testing.assert_array_equal(out2["v1"], [4.0])


def test_stop_words_remover_default_english():
    df = DataFrame(["tokens"], None, [[["The", "quick", "fox"], ["a", "b"]]])
    out = StopWordsRemover().set_input_cols("tokens").set_output_cols("filtered").transform(df)
    assert out["filtered"][0] == ["quick", "fox"]
    assert out["filtered"][1] == ["b"]
    # case sensitive keeps "The"
    out_cs = (
        StopWordsRemover()
        .set_input_cols("tokens")
        .set_output_cols("filtered")
        .set_case_sensitive(True)
        .transform(df)
    )
    assert out_cs["filtered"][0] == ["The", "quick", "fox"]


def test_tokenizers():
    df = DataFrame(["s"], None, [["Hello  World", "Foo-Bar baz"]])
    out = Tokenizer().set_input_col("s").set_output_col("t").transform(df)
    # Java split("\\s") keeps interior empty tokens from consecutive whitespace
    assert out["t"][0] == ["hello", "", "world"]
    assert out["t"][1] == ["foo-bar", "baz"]
    rt = (
        RegexTokenizer()
        .set_input_col("s")
        .set_output_col("t")
        .set_pattern(r"[\s\-]+")
        .transform(df)
    )
    assert rt["t"][1] == ["foo", "bar", "baz"]
    # gaps=False: pattern matches tokens
    rt2 = (
        RegexTokenizer()
        .set_input_col("s")
        .set_output_col("t")
        .set_pattern(r"\w+")
        .set_gaps(False)
        .transform(df)
    )
    assert rt2["t"][0] == ["hello", "world"]


def test_vector_assembler_modes():
    df = DataFrame(
        ["a", "v"],
        None,
        [np.asarray([1.0, np.nan]), np.asarray([[2.0, 3.0], [4.0, 5.0]])],
    )
    va = VectorAssembler().set_input_cols("a", "v").set_input_sizes(1, 2)
    with pytest.raises(ValueError, match="handleInvalid"):
        va.transform(df)
    out_keep = va.set_handle_invalid("keep").transform(df)
    np.testing.assert_array_equal(out_keep["output"][0], [1.0, 2.0, 3.0])
    assert np.isnan(out_keep["output"][1][0])
    out_skip = va.set_handle_invalid("skip").transform(df)
    assert len(out_skip) == 1


def test_vector_slicer_dense_and_sparse():
    df = DataFrame.from_dict({"input": np.asarray([[1.0, 2.0, 3.0, 4.0]])})
    out = VectorSlicer().set_indices(3, 0).transform(df)
    np.testing.assert_array_equal(out["output"], [[4.0, 1.0]])
    sv = Vectors.sparse(4, [1, 3], [5.0, 6.0])
    df2 = DataFrame(["input"], None, [[sv]])
    out2 = VectorSlicer().set_indices(3, 1).transform(df2)
    np.testing.assert_array_equal(out2["output"][0].to_array(), [6.0, 5.0])


def test_sql_transformer_compound_conditions_and_sandbox():
    df = DataFrame.from_dict({"v1": np.asarray([0.0, 2.0, 5.0]), "v2": np.asarray([9.0, 5.0, 1.0])})
    out = (
        SQLTransformer()
        .set_statement("SELECT v1 FROM __THIS__ WHERE v1 > 1 AND v2 < 6")
        .transform(df)
    )
    np.testing.assert_array_equal(out["v1"], [2.0, 5.0])
    out_or = (
        SQLTransformer()
        .set_statement("SELECT v1 FROM __THIS__ WHERE v1 > 4 OR v2 > 8")
        .transform(df)
    )
    np.testing.assert_array_equal(out_or["v1"], [0.0, 5.0])
    out_not = (
        SQLTransformer()
        .set_statement("SELECT v1 FROM __THIS__ WHERE NOT v1 = 2.0")
        .transform(df)
    )
    np.testing.assert_array_equal(out_not["v1"], [0.0, 5.0])
    # sandbox: attribute access / unknown identifiers rejected before eval
    for stmt in [
        "SELECT v1.__class__ FROM __THIS__",
        "SELECT open FROM __THIS__",
        "SELECT v1 FROM __THIS__ WHERE v1.__gt__(1)",
    ]:
        with pytest.raises(ValueError):
            SQLTransformer().set_statement(stmt).transform(df)


def test_numeric_list_columns_densify():
    """List-of-numeric-lists columns behave like 2-D vector columns."""
    df = DataFrame.from_dict({"v": [[1.0, 3.0], [2.0, 0.0]]})
    out = (
        Binarizer().set_input_cols("v").set_output_cols("b").set_thresholds(1.5).transform(df)
    )
    np.testing.assert_array_equal(out["b"], [[0.0, 1.0], [1.0, 0.0]])


def test_stateless_stages_save_load(tmp_path):
    """Every stateless stage round-trips its params through save/load."""
    stages = {
        "Binarizer": Binarizer().set_input_cols("a").set_output_cols("b").set_thresholds(0.5),
        "Normalizer": Normalizer().set_p(3.0),
        "NGram": NGram().set_n(4),
        "HashingTF": HashingTF().set_num_features(64),
        "SQLTransformer": SQLTransformer().set_statement("SELECT * FROM __THIS__"),
        "RegexTokenizer": RegexTokenizer().set_pattern("x+"),
    }
    for name, stage in stages.items():
        path = str(tmp_path / name)
        stage.save(path)
        loaded = type(stage).load(path)
        assert loaded.param_map_to_json() == stage.param_map_to_json(), name


def test_registry_resolves_all_stages():
    for name in STAGE_REGISTRY:
        cls = get_stage_class(name)
        assert cls.__name__ == name


def test_hashing_tf_numpy_bool_terms():
    # np.bool_ is neither bool nor np.integer; it must hash like the Java Boolean
    # branch (guava hashInt(1/0)), identically to a Python bool.
    df_np = DataFrame(["terms"], None, [[[np.bool_(True), np.bool_(False)]]])
    df_py = DataFrame(["terms"], None, [[[True, False]]])
    tf = HashingTF().set_input_col("terms").set_num_features(64)
    v_np = tf.transform(df_np)["output"][0]
    v_py = tf.transform(df_py)["output"][0]
    np.testing.assert_array_equal(v_np.indices, v_py.indices)
    np.testing.assert_array_equal(v_np.values, v_py.values)


def test_sql_transformer_global_aggregates():
    # Round-5 subset widening: COUNT/SUM/AVG/MIN/MAX over the whole table
    # (no GROUP BY); WHERE filters before aggregation; aggregates compose
    # with arithmetic; two-argument MIN/MAX stays elementwise.
    df = DataFrame.from_dict(
        {"v1": np.asarray([1.0, 4.0, 7.0]), "v2": np.asarray([2.0, 5.0, 8.0])}
    )
    out = (
        SQLTransformer()
        .set_statement(
            "SELECT COUNT(*) AS n, SUM(v1) AS s, AVG(v2) AS a, "
            "MIN(v1) AS lo, MAX(v2) AS hi FROM __THIS__"
        )
        .transform(df)
    )
    assert len(out) == 1
    assert out["n"][0] == 3
    np.testing.assert_allclose(out["s"], [12.0])
    np.testing.assert_allclose(out["a"], [5.0])
    np.testing.assert_allclose(out["lo"], [1.0])
    np.testing.assert_allclose(out["hi"], [8.0])

    # WHERE before aggregation + aggregate over an expression
    out2 = (
        SQLTransformer()
        .set_statement("SELECT SUM(v1 + v2) AS s FROM __THIS__ WHERE v1 > 1")
        .transform(df)
    )
    np.testing.assert_allclose(out2["s"], [24.0])

    # arithmetic of aggregates (the mean, spelled out)
    out3 = (
        SQLTransformer()
        .set_statement("SELECT SUM(v1) / COUNT(*) AS mean1 FROM __THIS__")
        .transform(df)
    )
    np.testing.assert_allclose(out3["mean1"], [4.0])

    # COUNT(expr) counts rows of the (filtered) table
    out4 = (
        SQLTransformer()
        .set_statement("SELECT COUNT(v1) AS n FROM __THIS__ WHERE v2 > 2")
        .transform(df)
    )
    assert out4["n"][0] == 2

    # two-argument MIN/MAX keeps the elementwise (LEAST/GREATEST) meaning
    out5 = (
        SQLTransformer()
        .set_statement("SELECT MIN(v1, v2) AS lo FROM __THIS__")
        .transform(df)
    )
    np.testing.assert_array_equal(out5["lo"], [1.0, 4.0, 7.0])


def test_sql_transformer_aggregate_errors():
    df = DataFrame.from_dict({"v1": np.asarray([1.0, 2.0])})
    # mixed aggregate and per-row items without GROUP BY
    with pytest.raises(ValueError, match="aggregate"):
        SQLTransformer().set_statement(
            "SELECT v1, SUM(v1) FROM __THIS__"
        ).transform(df)
    # nested aggregates
    with pytest.raises(ValueError, match="nested"):
        SQLTransformer().set_statement(
            "SELECT SUM(AVG(v1)) FROM __THIS__"
        ).transform(df)
    # JOIN / OVER: loud, specific rejections
    with pytest.raises(ValueError, match="JOIN"):
        SQLTransformer().set_statement(
            "SELECT v1 FROM __THIS__ JOIN other ON x = y"
        ).transform(df)
    # aggregate inside WHERE is outside the subset (no HAVING)
    with pytest.raises(ValueError):
        SQLTransformer().set_statement(
            "SELECT v1 FROM __THIS__ WHERE SUM(v1) > 1"
        ).transform(df)


def test_sql_transformer_aggregate_edge_cases():
    df = DataFrame.from_dict(
        {"v1": np.asarray([1.0, 4.0, 7.0]), "v2": np.asarray([2.0, 5.0, 8.0])}
    )
    # COUNT(1) idiom == COUNT(*) (no NULL in the subset)
    out = SQLTransformer().set_statement(
        "SELECT COUNT(1) AS n FROM __THIS__"
    ).transform(df)
    assert out["n"][0] == 3
    # a bare per-row column outside an aggregate is rejected, like real SQL
    with pytest.raises(ValueError, match="unknown identifier"):
        SQLTransformer().set_statement(
            "SELECT SUM(v1) + v2 AS x FROM __THIS__"
        ).transform(df)
    # empty filtered table: defined results, not numpy errors
    out2 = SQLTransformer().set_statement(
        "SELECT COUNT(*) AS n, SUM(v1) AS s, MIN(v1) AS lo, AVG(v1) AS a "
        "FROM __THIS__ WHERE v1 > 100"
    ).transform(df)
    assert out2["n"][0] == 0 and out2["s"][0] == 0.0
    assert np.isnan(out2["lo"][0]) and np.isnan(out2["a"][0])
    # aggregates (incl. 1-arg MIN) in WHERE: clean ValueError, not TypeError
    for stmt in (
        "SELECT v1 FROM __THIS__ WHERE v1 > MIN(v1)",
        "SELECT v1 FROM __THIS__ WHERE SUM(v1) > 1",
    ):
        with pytest.raises(ValueError, match="not allowed in WHERE"):
            SQLTransformer().set_statement(stmt).transform(df)
    # trailing clause after WHERE still gets the specific rejection
    with pytest.raises(ValueError, match="ORDER BY"):
        SQLTransformer().set_statement(
            "SELECT v1 FROM __THIS__ ORDER BY v1"
        ).transform(df)


def test_sql_transformer_group_by():
    # Round-5 second pass: GROUP BY over bare key columns; one row per
    # distinct key tuple, in key first-appearance order.
    df = DataFrame.from_dict(
        {
            "cat": np.asarray(["a", "b", "a", "c", "b", "a"]),
            "reg": np.asarray([1, 1, 2, 2, 1, 2]),
            "v": np.asarray([1.0, 2.0, 3.0, 4.0, 5.0, 6.0]),
        }
    )
    out = (
        SQLTransformer()
        .set_statement(
            "SELECT cat, COUNT(*) AS n, SUM(v) AS s, AVG(v) AS a, "
            "MAX(v) - MIN(v) AS spread FROM __THIS__ GROUP BY cat"
        )
        .transform(df)
    )
    np.testing.assert_array_equal(out["cat"], ["a", "b", "c"])
    np.testing.assert_array_equal(out["n"], [3, 2, 1])
    np.testing.assert_allclose(out["s"], [10.0, 7.0, 4.0])
    np.testing.assert_allclose(out["a"], [10.0 / 3, 3.5, 4.0])
    np.testing.assert_allclose(out["spread"], [5.0, 3.0, 0.0])

    # multi-key + WHERE before grouping + key aliasing + arithmetic of
    # aggregates; appearance order is of the FILTERED table
    out2 = (
        SQLTransformer()
        .set_statement(
            "SELECT cat, reg AS region, SUM(v) / COUNT(*) AS mean_v "
            "FROM __THIS__ WHERE v > 1 GROUP BY cat, reg"
        )
        .transform(df)
    )
    np.testing.assert_array_equal(out2["cat"], ["b", "a", "c"])
    np.testing.assert_array_equal(out2["region"], [1, 2, 2])
    np.testing.assert_allclose(out2["mean_v"], [3.5, 4.5, 4.0])

    # empty filtered table: zero groups, zero rows, every column keeps its
    # natural dtype (int counts, key dtypes) — schema must not depend on
    # whether the filter matched anything
    out3 = (
        SQLTransformer()
        .set_statement("SELECT cat, COUNT(*) AS n FROM __THIS__ WHERE v > 99 GROUP BY cat")
        .transform(df)
    )
    assert len(np.asarray(out3["n"])) == 0
    assert np.asarray(out3["n"]).dtype.kind == "i"
    assert np.asarray(out3["cat"]).dtype == np.asarray(df["cat"]).dtype

    # group keys are legal OUTSIDE aggregates within an aggregate item
    # (real-SQL semantics): per-group key value rides the arithmetic
    out4 = (
        SQLTransformer()
        .set_statement("SELECT reg, SUM(v) + reg AS s FROM __THIS__ GROUP BY reg")
        .transform(df)
    )
    np.testing.assert_allclose(out4["s"], [1.0 + 2.0 + 5.0 + 1, 3.0 + 4.0 + 6.0 + 2])


def test_sql_transformer_group_by_errors():
    df = DataFrame.from_dict(
        {"cat": np.asarray(["a", "b"]), "v": np.asarray([1.0, 2.0])}
    )
    # a non-key per-row item
    with pytest.raises(ValueError, match="group key or an aggregate"):
        SQLTransformer().set_statement(
            "SELECT v, SUM(v) FROM __THIS__ GROUP BY cat"
        ).transform(df)
    # key expressions are outside the subset
    with pytest.raises(ValueError, match="bare input column"):
        SQLTransformer().set_statement(
            "SELECT cat FROM __THIS__ GROUP BY cat + 1"
        ).transform(df)
    # HAVING stays rejected
    with pytest.raises(ValueError, match="HAVING"):
        SQLTransformer().set_statement(
            "SELECT cat, SUM(v) FROM __THIS__ GROUP BY cat HAVING SUM(v) > 1"
        ).transform(df)
