"""Benchmark harness tests (BenchmarkTest/DataGeneratorTest parity) and the
stage-completeness test (test_ml_lib_completeness.py:31 analogue): every stage in
the reference's library inventory must be present in the registry."""
import json
import os

import numpy as np
import pytest

from flink_ml_tpu.benchmark.benchmark import main, run_benchmark, run_config
from flink_ml_tpu.benchmark.datagenerator import (
    DenseVectorGenerator,
    DoubleGenerator,
    KMeansModelDataGenerator,
    LabeledPointWithWeightGenerator,
    RandomStringGenerator,
)
from flink_ml_tpu.models import STAGE_REGISTRY

DEMO_CONFIG = os.path.join(
    os.path.dirname(__file__), "..", "flink_ml_tpu", "benchmark", "benchmark-demo.json"
)


def test_dense_vector_generator_reproducible():
    gen = DenseVectorGenerator().set_col_names([["features"]]).set_num_values(50).set_vector_dim(3)
    gen.set_seed(2)
    df1, df2 = gen.generate(), gen.generate()
    assert df1.get_column_names() == ["features"]
    assert df1["features"].shape == (50, 3)
    np.testing.assert_array_equal(df1["features"], df2["features"])


def test_labeled_point_generator_arity():
    gen = (
        LabeledPointWithWeightGenerator()
        .set_col_names([["features", "label", "weight"]])
        .set_num_values(100)
        .set_vector_dim(4)
        .set_feature_arity(0)
        .set_label_arity(2)
    )
    df = gen.generate()
    assert set(np.unique(df["label"])) <= {0.0, 1.0}
    assert df["features"].min() >= 0.0 and df["features"].max() < 1.0
    assert df["weight"].shape == (100,)


def test_double_and_string_generators():
    d = DoubleGenerator().set_col_names([["x"]]).set_num_values(20).set_arity(3).generate()
    assert set(np.unique(d["x"])) <= {0.0, 1.0, 2.0}
    s = (
        RandomStringGenerator()
        .set_col_names([["s"]])
        .set_num_values(30)
        .set_num_distinct_values(5)
        .generate()
    )
    assert len(set(s["s"])) <= 5


def test_run_benchmark_kmeans_entry():
    entry = {
        "stage": {"className": "KMeans", "paramMap": {"k": 2, "maxIter": 3}},
        "inputData": {
            "className": "DenseVectorGenerator",
            "paramMap": {"seed": 2, "colNames": [["features"]], "numValues": 500, "vectorDim": 5},
        },
    }
    result = run_benchmark("KMeans-mini", entry)
    assert result["inputRecordNum"] == 500
    assert result["totalTimeMs"] > 0
    assert result["inputThroughput"] == pytest.approx(
        500 * 1000 / result["totalTimeMs"], rel=1e-3
    )


def test_run_benchmark_model_data_entry():
    entry = {
        "stage": {
            "className": "org.apache.flink.ml.clustering.kmeans.KMeansModel",
            "paramMap": {"k": 2},
        },
        "modelData": {
            "className": "KMeansModelDataGenerator",
            "paramMap": {"seed": 1, "arraySize": 2, "vectorDim": 5},
        },
        "inputData": {
            "className": "DenseVectorGenerator",
            "paramMap": {"seed": 2, "colNames": [["features"]], "numValues": 200, "vectorDim": 5},
        },
    }
    result = run_benchmark("KMeansModel-mini", entry)
    assert result["outputRecordNum"] == 200


def test_cli_output_file(tmp_path, capsys):
    out_file = str(tmp_path / "results.json")
    config = {
        "version": 1,
        "b1": {
            "stage": {"className": "StringIndexer", "paramMap": {"inputCols": ["s"], "outputCols": ["o"]}},
            "inputData": {
                "className": "RandomStringGenerator",
                "paramMap": {"seed": 1, "colNames": [["s"]], "numValues": 100},
            },
        },
    }
    cfg_path = str(tmp_path / "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump(config, f)
    assert main([cfg_path, "--output-file", out_file]) == 0
    with open(out_file) as f:
        results = json.load(f)
    assert results[0]["name"] == "b1" and "totalTimeMs" in results[0]


def test_bad_entry_reports_error(tmp_path):
    cfg = {
        "version": 1,
        "broken": {
            "stage": {"className": "KMeans", "paramMap": {"nonexistentParam": 1}},
            "inputData": {
                "className": "DenseVectorGenerator",
                "paramMap": {"colNames": [["features"]], "numValues": 10, "vectorDim": 2},
            },
        },
    }
    p = str(tmp_path / "c.json")
    with open(p, "w") as f:
        json.dump(cfg, f)
    results = run_config(p)
    assert "error" in results[0]


def test_demo_config_parses():
    results = run_config(DEMO_CONFIG)
    assert {r["name"] for r in results} >= {"KMeans-1", "KMeansModel-1"}
    for r in results:
        assert "error" not in r, r


# --- completeness (mirrors pyflink test_ml_lib_completeness.py:31) ------------

REFERENCE_STAGES = [
    # classification
    "LogisticRegression", "LogisticRegressionModel",
    "OnlineLogisticRegression", "OnlineLogisticRegressionModel",
    "LinearSVC", "LinearSVCModel",
    "NaiveBayes", "NaiveBayesModel",
    "Knn", "KnnModel",
    # clustering
    "KMeans", "KMeansModel", "OnlineKMeans", "OnlineKMeansModel",
    "AgglomerativeClustering",
    # regression
    "LinearRegression", "LinearRegressionModel",
    # evaluation
    "BinaryClassificationEvaluator",
    # stats
    "ChiSqTest", "ANOVATest", "FValueTest",
    # recommendation
    "Swing",
    # feature
    "Binarizer", "Bucketizer", "CountVectorizer", "CountVectorizerModel", "DCT",
    "ElementwiseProduct", "FeatureHasher", "HashingTF", "IDF", "IDFModel",
    "Imputer", "ImputerModel", "IndexToStringModel", "Interaction",
    "KBinsDiscretizer", "KBinsDiscretizerModel", "MaxAbsScaler",
    "MaxAbsScalerModel", "MinHashLSH", "MinHashLSHModel", "MinMaxScaler",
    "MinMaxScalerModel", "NGram", "Normalizer", "OneHotEncoder",
    "OneHotEncoderModel", "PolynomialExpansion", "RandomSplitter",
    "RegexTokenizer", "RobustScaler", "RobustScalerModel", "SQLTransformer",
    "StandardScaler", "StandardScalerModel", "OnlineStandardScaler",
    "OnlineStandardScalerModel", "StopWordsRemover", "StringIndexer",
    "StringIndexerModel", "Tokenizer", "UnivariateFeatureSelector",
    "UnivariateFeatureSelectorModel", "VarianceThresholdSelector",
    "VarianceThresholdSelectorModel", "VectorAssembler", "VectorIndexer",
    "VectorIndexerModel", "VectorSlicer",
]


def test_registry_covers_reference_inventory():
    missing = [s for s in REFERENCE_STAGES if s not in STAGE_REGISTRY]
    assert not missing, f"stages missing from the registry: {missing}"


def test_cli_profile_flag_writes_trace(tmp_path):
    """--profile emits a jax.profiler trace dir and records it per benchmark."""
    import json
    import os

    from flink_ml_tpu.benchmark.benchmark import main as bench_main

    config = {
        "version": 1,
        "KMeans-prof": {
            "stage": {
                "className": "KMeans",
                "paramMap": {"k": 2, "maxIter": 3, "seed": 1},
            },
            "inputData": {
                "className": "DenseVectorGenerator",
                "paramMap": {
                    "seed": 1,
                    "colNames": [["features"]],
                    "numValues": 200,
                    "vectorDim": 4,
                },
            },
        },
    }
    cfg = tmp_path / "cfg.json"
    cfg.write_text(json.dumps(config))
    out = tmp_path / "results.json"
    prof = tmp_path / "prof"
    rc = bench_main([str(cfg), "--output-file", str(out), "--profile", str(prof)])
    assert rc == 0
    (result,) = json.loads(out.read_text())
    assert "error" not in result, result
    assert result["fitTimeMs"] > 0 and result["transformTimeMs"] >= 0
    assert result["profileTrace"] == str(prof / "KMeans-prof")
    # the trace dir must contain an actual xplane dump
    found = [
        f for _, _, files in os.walk(prof) for f in files if f.endswith(".xplane.pb")
    ]
    assert found, "no profiler trace written"


def test_visualizer_renders_results(tmp_path):
    import json
    import subprocess
    import sys

    results = [
        {"name": "A", "inputThroughput": 100.0, "totalTimeMs": 10.0},
        {"name": "B", "inputThroughput": 250.0, "totalTimeMs": 4.0},
    ]
    rf = tmp_path / "r.json"
    rf.write_text(json.dumps(results))
    png = tmp_path / "out.png"
    import pathlib

    repo_root = pathlib.Path(__file__).resolve().parents[1]
    proc = subprocess.run(
        [
            sys.executable,
            str(repo_root / "bin" / "benchmark-results-visualize.py"),
            str(rf),
            "--output",
            str(png),
        ],
        capture_output=True,
        text=True,
        cwd=str(repo_root),
    )
    assert proc.returncode == 0, proc.stderr
    assert png.exists() and png.stat().st_size > 1000
