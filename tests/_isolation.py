"""Shared subprocess-with-retry containment for the XLA CPU
rendezvous-deadlock (see test_attention_isolated.py for the full story):
run a collective-heavy workload in its own 2-device child so a SIGABRT
kills a retryable, timeout-capped subprocess instead of the suite."""
import os
import re
import subprocess
import sys
import time

import pytest

ABORT_RCS = (-6, 134)  # SIGABRT raw / via shell
_TIMEOUT_S = 600
#: Total wall-time budget across ALL attempts: a deterministically hanging
#: child must report after ~one timeout's worth of wall clock, not retry
#: 4 x 600 s (ADVICE.md round 5).
_BUDGET_S = 600


def two_device_env(extra=None):
    """A child env pinned to a 2-participant virtual CPU mesh (two
    rendezvous participants on one core collapse the deadlock odds that
    eight have), off the TPU tunnel."""
    env = dict(os.environ)
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        env.get("XLA_FLAGS", ""),
    )
    env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=2").strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""  # sitecustomize honors cpu only with this cleared
    env.update(extra or {})
    return env


def run_contained(cmd, env, cwd, retries=3, what="isolated child", budget_s=_BUDGET_S):
    """Run ``cmd`` with retry on the known infra abort (or a hang past the
    timeout, which the XLA collective terminate flag does not always
    cover). A real failure reproduces deterministically in the child and
    fails the calling test with the child's output. Returns the passing
    CompletedProcess.

    Retries share one wall-clock ``budget_s``: each attempt's timeout is the
    time remaining, so a deterministically hanging child reports after
    ~``budget_s`` total instead of ``(1 + retries) * timeout``. Every retry
    is logged to stderr so a flaky-infra loop is visible between attempts."""
    deadline = time.monotonic() + budget_s
    last = None
    for attempt in range(1 + retries):
        remaining = deadline - time.monotonic()
        if attempt > 0 and remaining <= 1.0:
            print(
                f"[{what}] retry budget ({budget_s}s) exhausted after "
                f"{attempt} attempt(s)",
                file=sys.stderr,
                flush=True,
            )
            break
        try:
            last = subprocess.run(
                cmd,
                capture_output=True,
                text=True,
                env=env,
                cwd=cwd,
                timeout=min(_TIMEOUT_S, max(remaining, 1.0)),
            )
        except subprocess.TimeoutExpired as e:
            last = subprocess.CompletedProcess(
                e.cmd,
                -9,
                e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or ""),
                e.stderr.decode() if isinstance(e.stderr, bytes) else (e.stderr or ""),
            )
            print(
                f"[{what}] attempt {attempt + 1}/{1 + retries} timed out, retrying",
                file=sys.stderr,
                flush=True,
            )
            continue  # hang: retry like an abort
        if last.returncode == 0:
            return last
        if last.returncode not in ABORT_RCS:
            break  # a real failure: deterministic, no point retrying
        print(
            f"[{what}] attempt {attempt + 1}/{1 + retries} aborted "
            f"(rc={last.returncode}), retrying",
            file=sys.stderr,
            flush=True,
        )
    pytest.fail(
        f"{what} failed (rc={last.returncode}):\n"
        f"{last.stdout[-4000:]}\n{last.stderr[-2000:]}"
    )
