"""The typed-error contract at the batcher's delivery seam.

``typed-error-escape`` (tools/graftcheck) proves statically that every raise
lexically reachable from a request surface is typed — but errors carried
across the batcher's thread rendezvous (``req.error`` → ``result()``) are
invisible to the call graph. These tests pin the runtime half of that
contract: ``MicroBatcher._deliver_error`` is the single seam where every
batch failure lands, and it must hand clients either the original typed
error, the original injected fault, or a ``ServingExecutionError`` wrapping
anything else — never a raw untyped exception.
"""
from __future__ import annotations

import time

import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.faults import InjectedFault
from flink_ml_tpu.serving.batcher import _CLAIMED, MicroBatcher, PendingRequest
from flink_ml_tpu.serving.errors import (
    ServingError,
    ServingExecutionError,
    ServingOverloadedError,
)


def _req(rows=1):
    return DataFrame.from_dict({"x": np.ones((rows, 2), np.float32)})


class _Resp:
    def __init__(self, df, version, latency_ms, bucket):
        self.dataframe = df
        self.model_version = version
        self.latency_ms = latency_ms
        self.bucket = bucket


def _batcher(execute):
    return MicroBatcher(
        execute,
        max_batch_size=4,
        max_delay_ms=0.0,
        queue_capacity_rows=64,
        scope="ml.serving[t-errors]",
        response_factory=_Resp,
    )


def _run_failing_batch(error):
    """Run one batch whose execute raises ``error``; return what the client
    sees at the ``result()`` rendezvous."""

    def execute(padded):
        raise error

    batcher = _batcher(execute)
    req = PendingRequest(_req(1), deadline=time.perf_counter() + 30.0)
    req._state = _CLAIMED
    batcher._install_abandon(req)
    batcher._run_batch([req])
    return req


def test_untyped_execute_failure_is_wrapped_serving_execution_error():
    boom = RuntimeError("device fell over")
    req = _run_failing_batch(boom)
    with pytest.raises(ServingExecutionError) as exc_info:
        req.result()
    err = exc_info.value
    assert isinstance(err, ServingError)  # the blanket client contract
    assert err.__cause__ is boom and err.cause is boom
    assert "RuntimeError" in str(err) and "device fell over" in str(err)


def test_typed_errors_pass_through_the_seam_unwrapped():
    typed = ServingOverloadedError(8, 8)
    req = _run_failing_batch(typed)
    with pytest.raises(ServingOverloadedError) as exc_info:
        req.result()
    assert exc_info.value is typed  # same object: no double wrapping


def test_injected_faults_pass_through_for_the_chaos_bin():
    # loadgen counts InjectedFault in its own bin (generator.py); wrapping
    # it would misfile chaos-armed faults as unexpected typed errors.
    fault = InjectedFault("serving.exec", hit=1)
    req = _run_failing_batch(fault)
    with pytest.raises(InjectedFault) as exc_info:
        req.result()
    assert exc_info.value is fault


def test_every_waiter_of_a_failed_batch_gets_the_wrapped_error():
    def execute(padded):
        raise KeyError("missing column")

    batcher = _batcher(execute)
    reqs = [PendingRequest(_req(1), deadline=time.perf_counter() + 30.0) for _ in range(3)]
    for r in reqs:
        r._state = _CLAIMED
        batcher._install_abandon(r)
    batcher._run_batch(reqs)
    for r in reqs:
        assert isinstance(r.error, ServingExecutionError)
        assert isinstance(r.error.__cause__, KeyError)


def test_serving_execution_error_shape():
    cause = ValueError("bad")
    err = ServingExecutionError("batch execution failed", cause=cause)
    assert isinstance(err, ServingError)
    assert err.cause is cause and err.__cause__ is cause
    assert ServingExecutionError("no cause").cause is None
