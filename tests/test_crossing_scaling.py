"""The sparse roofline's multi-chip scaling claim, measured (tools/crossing_scaling.py).

docs/benchmarks.md argues the one-hot program's crossing term falls ~1/p²
per chip under p-way data parallelism (p divides both the per-shard entry
count and — once under the 16384 cap — the sub-batch row space). This pins
the claim to XLA's compiled per-chip cost analysis on the virtual mesh: the
SPMD executable's FLOP count must fall SUPERLINEARLY in p.
"""
import numpy as np
import pytest

from tools.crossing_scaling import markdown_table, measure_scaling


@pytest.fixture(scope="module")
def rows():
    # B=8192 keeps every local batch under the 16384 sub cap, so the whole
    # sweep sits in the quadratic regime (sub_batch == local_batch).
    return measure_scaling([1, 2, 4, 8], global_batch=8192, dim=1 << 16, nnz=8, K=8)


def test_cost_analysis_reports_flops(rows):
    for r in rows:
        assert np.isfinite(r["flops_per_chip"]) and r["flops_per_chip"] > 0, r


def test_per_chip_flops_fall_superlinearly(rows):
    # Superlinear: p * flops(p) strictly decreasing — each doubling of the
    # mesh cuts per-chip work by MORE than half.
    by_p = {r["p"]: r["flops_per_chip"] for r in rows}
    for p_small, p_big in [(1, 2), (2, 4), (4, 8)]:
        assert by_p[p_big] * p_big < by_p[p_small] * p_small * 0.95, (
            f"p={p_small}->{p_big}: per-chip flops fell sublinearly: {by_p}"
        )
    # End to end the fall approaches quadratic: 8 chips, > 8x1.5 less work each
    assert by_p[1] / by_p[8] > 12.0, by_p


def test_sub_batch_tracks_local_batch_in_quadratic_regime(rows):
    for r in rows:
        assert r["sub_batch"] == r["local_batch"], r


def test_markdown_table_renders(rows):
    table = markdown_table(rows)
    assert "per-chip GFLOP/step" in table and table.count("|") > 20
