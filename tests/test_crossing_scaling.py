"""The sparse roofline's multi-chip scaling claim, measured (tools/crossing_scaling.py).

docs/benchmarks.md argues the one-hot program's crossing term falls ~1/p²
per chip under p-way data parallelism (p divides both the per-shard entry
count and — once under the 16384 cap — the sub-batch row space). This pins
the claim to XLA's compiled per-chip cost analysis on the virtual mesh: the
SPMD executable's FLOP count must fall SUPERLINEARLY in p.
"""
import numpy as np
import pytest

from tools.crossing_scaling import markdown_table, measure_scaling


@pytest.fixture(scope="module")
def rows():
    # B=8192 keeps every local batch under the 16384 sub cap, so the whole
    # sweep sits in the quadratic regime (sub_batch == local_batch).
    return measure_scaling([1, 2, 4, 8], global_batch=8192, dim=1 << 16, nnz=8, K=8)


def test_cost_analysis_reports_flops(rows):
    for r in rows:
        assert np.isfinite(r["flops_per_chip"]) and r["flops_per_chip"] > 0, r


def test_per_chip_flops_fall_superlinearly(rows):
    # Superlinear: p * flops(p) strictly decreasing — each doubling of the
    # mesh cuts per-chip work by MORE than half.
    by_p = {r["p"]: r["flops_per_chip"] for r in rows}
    for p_small, p_big in [(1, 2), (2, 4), (4, 8)]:
        assert by_p[p_big] * p_big < by_p[p_small] * p_small * 0.95, (
            f"p={p_small}->{p_big}: per-chip flops fell sublinearly: {by_p}"
        )
    # End to end the fall approaches quadratic: 8 chips, > 8x1.5 less work each
    assert by_p[1] / by_p[8] > 12.0, by_p


def test_sub_batch_tracks_local_batch_in_quadratic_regime(rows):
    for r in rows:
        assert r["sub_batch"] == r["local_batch"], r


def test_markdown_table_renders(rows):
    table = markdown_table(rows)
    assert "per-chip GFLOP/step" in table and table.count("|") > 20


class TestWallClockCorroboration:
    """Round-5 (VERDICT r4 next #7): the falloff must hold in wall-clock, not
    just compiled FLOP counts — a memory-shaped crossing could in principle
    fall in FLOPs while time stalls. The virtual mesh gives relative falloff
    only (CPU ms are not TPU ms), so the band is generous."""

    @pytest.fixture(scope="class")
    def timed_rows(self):
        return measure_scaling(
            [1, 2, 4, 8], global_batch=8192, dim=1 << 16, nnz=8, K=8,
            time_steps=3,
        )

    def test_time_columns_present_and_positive(self, timed_rows):
        for r in timed_rows:
            assert r["per_chip_ms"] > 0 and r["wall_ms_per_step"] > 0, r

    def test_per_chip_time_falls_superlinearly(self, timed_rows):
        # The same superlinearity contract as the FLOP column, loosened for
        # host-timing noise: 8x the chips must cut per-chip TIME by >8x
        # (quadratic predicts ~16-25x; sublinear or linear fails).
        by_p = {r["p"]: r["per_chip_ms"] for r in timed_rows}
        assert by_p[1] / by_p[8] > 8.0, by_p

    @pytest.mark.slow
    def test_time_falloff_tracks_flop_falloff(self, timed_rows):
        # Tolerance band: measured time falloff within [1/3, 3]x of the
        # FLOP-predicted falloff at every p — catches an XLA rewrite that
        # changes the constants without failing on scheduler jitter.
        #
        # Gated behind -m slow (VERDICT r5): host timing on a contended
        # 1-core CI box can land outside any honest band at the small-p
        # steps, where one preempted slice dwarfs the measured ms. The
        # deterministic directional contract stays in tier-1 below.
        for r in timed_rows[1:]:
            flop_fall = timed_rows[0]["flops_per_chip"] / r["flops_per_chip"]
            time_fall = timed_rows[0]["per_chip_ms"] / r["per_chip_ms"]
            assert flop_fall / 3 < time_fall < flop_fall * 3, (
                f"p={r['p']}: time falloff {time_fall:.1f}x vs "
                f"FLOP falloff {flop_fall:.1f}x"
            )

    def test_time_falloff_direction_tracks_flop_falloff(self, timed_rows):
        # Deterministic tier-1 fallback for the banded check above: the
        # FLOP-predicted falloff is exact (compiled cost analysis), and the
        # measured time at the widest step (p=1 -> p=8, a predicted ~16-25x)
        # must at least FALL. A regression that flattens the crossing term
        # (time stalling while FLOPs drop) still fails; scheduler jitter,
        # which perturbs constants but cannot turn a 16x drop into a rise,
        # does not.
        by_p = {r["p"]: r for r in timed_rows}
        flop_fall = by_p[1]["flops_per_chip"] / by_p[8]["flops_per_chip"]
        time_fall = by_p[1]["per_chip_ms"] / by_p[8]["per_chip_ms"]
        assert flop_fall > 12.0, by_p  # exact: the superlinear FLOP contract
        assert time_fall > 1.0, (
            f"per-chip time did not fall at all across 1->8 chips "
            f"(time {time_fall:.2f}x vs FLOPs {flop_fall:.1f}x)"
        )

    def test_timed_markdown_table_renders(self, timed_rows):
        table = markdown_table(timed_rows)
        assert "measured per-chip ms" in table and "time fall" in table
