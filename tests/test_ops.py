"""Tests for losses, regularization, SGD, and distance measures.

Parity targets: the loss formulas of ``BinaryLogisticLoss/HingeLoss/LeastSquareLoss``
(flink-ml-lib common/lossfunc), ``RegularizationUtils.regularize:47`` coefficient
updates, SGD convergence semantics (SGD.java), and the three DistanceMeasures
(flink-ml-servable-core common/distance).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_ml_tpu.ops import (
    SGD,
    BinaryLogisticLoss,
    CosineDistance,
    DistanceMeasure,
    EuclideanDistance,
    HingeLoss,
    LeastSquareLoss,
    ManhattanDistance,
    regularize,
)

RNG = np.random.default_rng(7)


def _batch(n=16, d=5, binary=True):
    X = jnp.asarray(RNG.normal(size=(n, d)), jnp.float32)
    y = jnp.asarray(
        (RNG.random(n) > 0.5).astype(np.float32) if binary else RNG.normal(size=n),
        jnp.float32,
    )
    w = jnp.asarray(RNG.random(n).astype(np.float32) + 0.5)
    coef = jnp.asarray(RNG.normal(size=d), jnp.float32)
    return coef, X, y, w


@pytest.mark.parametrize("loss", [BinaryLogisticLoss.INSTANCE, HingeLoss.INSTANCE, LeastSquareLoss.INSTANCE])
def test_analytic_grad_matches_autograd(loss):
    coef, X, y, w = _batch(binary=not isinstance(loss, LeastSquareLoss))
    l_analytic, g_analytic = loss.loss_and_grad_sum(coef, X, y, w)
    l_auto, g_auto = jax.value_and_grad(loss.batch_loss_sum)(coef, X, y, w)
    np.testing.assert_allclose(l_analytic, l_auto, rtol=1e-5)
    np.testing.assert_allclose(g_analytic, g_auto, rtol=1e-4, atol=1e-5)


def test_logistic_loss_single_sample_formula():
    """w * log(1 + exp(-dot * (2y-1))) — BinaryLogisticLoss.java:50-56."""
    coef = jnp.asarray([1.0, -1.0])
    X = jnp.asarray([[2.0, 0.5]])
    w = jnp.asarray([1.5])
    dot = 2.0 - 0.5
    for y, ys in [(0.0, -1.0), (1.0, 1.0)]:
        got = float(BinaryLogisticLoss.INSTANCE.batch_loss_sum(coef, X, jnp.asarray([y]), w))
        np.testing.assert_allclose(got, 1.5 * np.log1p(np.exp(-dot * ys)), rtol=1e-6)


def test_hinge_loss_single_sample_formula():
    """w * max(0, 1 - ys*dot) — HingeLoss.java:48-53."""
    coef = jnp.asarray([1.0, 0.0])
    X = jnp.asarray([[0.3, 9.9]])
    w = jnp.asarray([2.0])
    got1 = float(HingeLoss.INSTANCE.batch_loss_sum(coef, X, jnp.asarray([1.0]), w))
    np.testing.assert_allclose(got1, 2.0 * (1 - 0.3), rtol=1e-6)
    got0 = float(HingeLoss.INSTANCE.batch_loss_sum(coef, X, jnp.asarray([0.0]), w))
    np.testing.assert_allclose(got0, 2.0 * (1 + 0.3), rtol=1e-6)


def test_least_square_loss_single_sample_formula():
    """w * 0.5 * (dot - y)^2 — LeastSquareLoss.java:47-50."""
    coef = jnp.asarray([2.0])
    X = jnp.asarray([[3.0]])
    got = float(LeastSquareLoss.INSTANCE.batch_loss_sum(coef, X, jnp.asarray([1.0]), jnp.asarray([0.5])))
    np.testing.assert_allclose(got, 0.5 * 0.5 * (6.0 - 1.0) ** 2, rtol=1e-6)


# --- regularization (RegularizationUtils.regularize:47) ----------------------


def test_regularize_l2_update():
    coef = jnp.asarray([1.0, -2.0])
    new, _ = regularize(coef, reg=0.1, elastic_net=0.0, learning_rate=0.5)
    np.testing.assert_allclose(new, coef * (1 - 0.5 * 0.1), rtol=1e-6)


def test_regularize_l1_update():
    coef = jnp.asarray([1.0, -2.0, 0.0])
    new, _ = regularize(coef, reg=0.1, elastic_net=1.0, learning_rate=0.5)
    np.testing.assert_allclose(new, coef - 0.5 * 0.1 * np.sign(coef), rtol=1e-6)


def test_regularize_elastic_net_update():
    coef = jnp.asarray([1.0, -2.0])
    reg, en, lr = 0.2, 0.3, 0.5
    new, _ = regularize(coef, reg=reg, elastic_net=en, learning_rate=lr)
    expected = coef - lr * (en * reg * np.sign(coef) + (1 - en) * reg * np.asarray(coef))
    np.testing.assert_allclose(new, expected, rtol=1e-6)


def test_regularize_zero_reg_identity():
    coef = jnp.asarray([1.0, -2.0])
    new, loss = regularize(coef, 0.0, 0.5, 0.1)
    np.testing.assert_array_equal(new, coef)
    assert float(loss) == 0.0


# --- SGD ---------------------------------------------------------------------


def test_sgd_linear_regression_converges_to_truth():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(512, 3)).astype(np.float32)
    w_true = np.asarray([2.0, -1.0, 0.5], np.float32)
    y = X @ w_true
    sgd = SGD(max_iter=300, learning_rate=0.05, global_batch_size=512, tol=0.0)
    coef = sgd.optimize(np.zeros(3), {"features": X, "labels": y}, LeastSquareLoss.INSTANCE)
    np.testing.assert_allclose(coef, w_true, atol=2e-2)


def test_sgd_tol_early_termination():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(64, 2)).astype(np.float32)
    y = X @ np.asarray([1.0, 1.0], np.float32)
    sgd = SGD(max_iter=5000, learning_rate=0.1, global_batch_size=64, tol=1e-4)
    sgd.optimize(np.zeros(2), {"features": X, "labels": y}, LeastSquareLoss.INSTANCE)
    assert 0 < len(sgd.loss_history) < 5000
    assert sgd.loss_history[-1] < 1e-4


def test_sgd_sample_weights_respected():
    """Duplicating a sample == doubling its weight (weighted-update semantics)."""
    X = np.asarray([[1.0, 0.0], [0.0, 1.0]], np.float32)
    y = np.asarray([1.0, 3.0], np.float32)
    w = np.asarray([2.0, 1.0], np.float32)
    sgd_w = SGD(max_iter=40, learning_rate=0.3, global_batch_size=8, tol=0.0)
    coef_weighted = sgd_w.optimize(
        np.zeros(2), {"features": X, "labels": y, "weights": w}, LeastSquareLoss.INSTANCE
    )
    X2 = np.asarray([[1.0, 0.0], [1.0, 0.0], [0.0, 1.0]], np.float32)
    y2 = np.asarray([1.0, 1.0, 3.0], np.float32)
    sgd_d = SGD(max_iter=40, learning_rate=0.3, global_batch_size=8, tol=0.0)
    coef_dup = sgd_d.optimize(np.zeros(2), {"features": X2, "labels": y2}, LeastSquareLoss.INSTANCE)
    np.testing.assert_allclose(coef_weighted, coef_dup, atol=1e-5)


def test_sgd_minibatch_offset_cycles():
    """global_batch < n: training still converges while cycling minibatches."""
    rng = np.random.default_rng(3)
    X = rng.normal(size=(100, 2)).astype(np.float32)
    y = X @ np.asarray([1.5, -0.5], np.float32)
    sgd = SGD(max_iter=400, learning_rate=0.05, global_batch_size=16, tol=0.0)
    coef = sgd.optimize(np.zeros(2), {"features": X, "labels": y}, LeastSquareLoss.INSTANCE)
    np.testing.assert_allclose(coef, [1.5, -0.5], atol=5e-2)


# --- distance measures -------------------------------------------------------


def test_euclidean_pairwise():
    pts = np.asarray([[0.0, 0.0], [3.0, 4.0]])
    cts = np.asarray([[0.0, 0.0], [6.0, 8.0]])
    d = np.asarray(EuclideanDistance().pairwise(jnp.asarray(pts), jnp.asarray(cts)))
    np.testing.assert_allclose(d, [[0.0, 10.0], [5.0, 5.0]], atol=1e-6)


def test_manhattan_pairwise():
    pts = np.asarray([[1.0, 2.0]])
    cts = np.asarray([[4.0, -2.0]])
    d = np.asarray(ManhattanDistance().pairwise(jnp.asarray(pts), jnp.asarray(cts)))
    np.testing.assert_allclose(d, [[7.0]], atol=1e-6)


def test_cosine_pairwise():
    pts = np.asarray([[1.0, 0.0]])
    cts = np.asarray([[0.0, 2.0], [3.0, 0.0]])
    d = np.asarray(CosineDistance().pairwise(jnp.asarray(pts), jnp.asarray(cts)))
    np.testing.assert_allclose(d, [[1.0, 0.0]], atol=1e-6)


def test_find_closest_first_minimum():
    """Ties resolve to the first index, like the reference's strict-< loop."""
    m = EuclideanDistance()
    pts = jnp.asarray([[1.0, 0.0]])
    cts = jnp.asarray([[0.0, 0.0], [2.0, 0.0]])  # equidistant
    assert int(m.find_closest(pts, cts)[0]) == 0


def test_get_instance_dispatch_and_error():
    assert isinstance(DistanceMeasure.get_instance("euclidean"), EuclideanDistance)
    assert isinstance(DistanceMeasure.get_instance("manhattan"), ManhattanDistance)
    assert isinstance(DistanceMeasure.get_instance("cosine"), CosineDistance)
    with pytest.raises(ValueError, match="not recognized"):
        DistanceMeasure.get_instance("chebyshev")


def test_sgd_fused_matches_host_loop():
    # The fused whole-run program (scan/while_loop) must produce exactly the same
    # trajectory as the per-epoch host loop (forced here via a no-op listener).
    from flink_ml_tpu.iteration import IterationListener

    rng = np.random.default_rng(11)
    X = rng.normal(size=(96, 5)).astype(np.float32)
    y = (rng.random(96) > 0.5).astype(np.float32)
    data = {"features": X, "labels": y}

    for tol in (0.0, 1e-3):
        fused = SGD(max_iter=25, global_batch_size=32, tol=tol, reg=0.05, elastic_net=0.3)
        coef_fused = fused.optimize(np.zeros(5), data, BinaryLogisticLoss.INSTANCE)
        host = SGD(
            max_iter=25, global_batch_size=32, tol=tol, reg=0.05, elastic_net=0.3,
            listeners=[IterationListener()],
        )
        coef_host = host.optimize(np.zeros(5), data, BinaryLogisticLoss.INSTANCE)
        np.testing.assert_allclose(coef_fused, coef_host, rtol=1e-6)
        # Loss history is recorded unconditionally (SGD.java:137-143 always
        # streams loss through the feedback edge) — maxIter-only runs included.
        if tol == 0.0:
            assert len(fused.loss_history) == 25
        assert len(fused.loss_history) == len(host.loss_history)
        np.testing.assert_allclose(fused.loss_history, host.loss_history, rtol=1e-5)


def test_sgd_fused_tol_stops_early_in_chunks():
    # A generous max_iter with a loose tol must not execute the full epoch
    # budget: the chunked fused path observes the on-device done flag between
    # chunks and stops, with loss_history ending at the first loss < tol.
    rng = np.random.default_rng(12)
    X = rng.normal(size=(128, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    sgd = SGD(max_iter=5000, global_batch_size=64, tol=0.5, learning_rate=0.5)
    sgd.optimize(np.zeros(4), {"features": X, "labels": y}, BinaryLogisticLoss.INSTANCE)
    assert 0 < len(sgd.loss_history) < 5000
    assert sgd.loss_history[-1] < 0.5
    assert all(loss >= 0.5 for loss in sgd.loss_history[:-1])


def test_dense_tp_matches_replicated():
    # Dense tensor parallelism (features column-sliced P(data, model), margin
    # psum over the model axis) must reproduce the replicated-coefficient
    # result on the same data axis.
    import jax

    from flink_ml_tpu.parallel.mesh import MeshContext, mesh_context

    rng = np.random.default_rng(9)
    d = 5  # not divisible by n_model=2: exercises column padding
    X = rng.normal(size=(96, d)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    data = {"features": X, "labels": y}
    kwargs = dict(max_iter=15, global_batch_size=32, tol=0.0, learning_rate=0.3,
                  reg=0.01, elastic_net=0.5)
    devices = jax.devices()[:8]
    with mesh_context(MeshContext(devices=devices[:4], n_data=4)) as ctx:
        want = SGD(ctx=ctx, **kwargs).optimize(
            np.zeros(d), data, BinaryLogisticLoss.INSTANCE
        )
    with mesh_context(MeshContext(devices=devices, n_data=4, n_model=2)) as ctx:
        got = SGD(ctx=ctx, **kwargs).optimize(
            np.zeros(d), data, BinaryLogisticLoss.INSTANCE
        )
    assert got.shape == (d,)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
