"""Online-training tests.

Parity targets (SURVEY.md §4 online-algo tests): stepwise minibatch feeding via an
in-memory source (InMemorySourceFunction analogue), per-model-version output
assertions, and model-version metric gauges scraped like InMemoryReporter
(OnlineKMeansTest.java:142-161, OnlineLogisticRegressionTest,
OnlineStandardScalerTest).
"""
import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.models.classification.online_logistic_regression import (
    OnlineLogisticRegression,
    OnlineLogisticRegressionModel,
)
from flink_ml_tpu.models.clustering.online_kmeans import OnlineKMeans, OnlineKMeansModel
from flink_ml_tpu.models.feature.standard_scaler import (
    OnlineStandardScaler,
    StandardScaler,
)
from flink_ml_tpu.models.online import QueueBatchStream
from flink_ml_tpu.ops.windows import CountTumblingWindows

RNG = np.random.default_rng(33)


def _lr_batch(n=64, d=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    y = (X @ np.linspace(1, -1, d) > 0).astype(np.float64)
    return {"features": X.astype(np.float64), "label": y}


def _init_lr_model_data(d=4):
    from flink_ml_tpu.linalg.vectors import DenseVector

    return DataFrame(["coefficient"], None, [[DenseVector(np.zeros(d))]])


class TestOnlineLogisticRegression:
    def test_param_defaults(self):
        olr = OnlineLogisticRegression()
        assert olr.get_alpha() == 0.1
        assert olr.get_beta() == 0.1
        assert olr.get_batch_strategy() == "count"
        assert olr.get_global_batch_size() == 32

    def test_stepwise_training_versions_and_gauges(self):
        stream = QueueBatchStream()
        olr = (
            OnlineLogisticRegression()
            .set_initial_model_data(_init_lr_model_data())
            .set_global_batch_size(64)
        )
        model = olr.fit(stream)
        assert model.model_version == 0  # init model only

        stream.add(_lr_batch(seed=1))
        assert model.advance() == 1
        assert model.model_version == 1
        coef_v1 = model.coefficient.copy()
        assert not np.allclose(coef_v1, 0.0)

        stream.add(_lr_batch(seed=2))
        stream.add(_lr_batch(seed=3))
        assert model.advance() == 2
        assert model.model_version == 3
        # gauges exported per version (InMemoryReporter parity)
        scope = model._metric_scope()
        assert metrics.get(scope, MLMetrics.VERSION) == 3
        assert metrics.get(scope, MLMetrics.TIMESTAMP) is not None

    def test_converges_with_batches(self):
        stream = QueueBatchStream()
        model = (
            OnlineLogisticRegression()
            .set_initial_model_data(_init_lr_model_data())
            .set_alpha(0.5)
            .fit(stream)
        )
        for i in range(30):
            stream.add(_lr_batch(n=128, seed=i))
        model.advance()
        test = _lr_batch(n=256, seed=99)
        df = DataFrame.from_dict(test)
        out = model.transform(df)
        acc = (out["prediction"] == test["label"]).mean()
        assert acc > 0.9, acc
        assert (out["version"] == model.model_version).all()

    def test_bounded_input_trains_eagerly(self):
        df = DataFrame.from_dict(_lr_batch(n=256, seed=7))
        model = (
            OnlineLogisticRegression()
            .set_initial_model_data(_init_lr_model_data())
            .set_global_batch_size(64)
            .fit(df)
        )
        assert model.model_version == 4  # 256/64 batches consumed eagerly

    def test_empty_batch_is_not_end_of_stream(self):
        stream = QueueBatchStream()
        model = (
            OnlineLogisticRegression()
            .set_initial_model_data(_init_lr_model_data())
            .fit(stream)
        )
        stream.add(DataFrame.from_dict({"features": np.zeros((0, 4)), "label": np.zeros(0)}))
        stream.add(_lr_batch(seed=1))
        assert model.advance() == 1  # empty frame skipped, real batch trained
        assert model.model_version == 1

    def test_advance_on_snapshot_hook_fires_per_version(self, tmp_path):
        """The per-version seam the continuous loop's publisher rides
        (loop/trainer.py): on_snapshot fires after each snapshot is applied,
        with the applied version and payload; a callback exception propagates
        with training state intact so a retry resumes at the NEXT version."""
        stream = QueueBatchStream()
        model = (
            OnlineLogisticRegression()
            .set_initial_model_data(_init_lr_model_data())
            .fit(stream)
        )
        seen = []

        def hook(version, payload):
            assert model.model_version == version  # applied BEFORE the hook
            seen.append((version, np.asarray(payload).copy()))

        stream.add(_lr_batch(seed=1))
        stream.add(_lr_batch(seed=2))
        assert model.advance(on_snapshot=hook) == 2
        assert [v for v, _ in seen] == [1, 2]
        np.testing.assert_array_equal(seen[-1][1], model.coefficient)

        stream.add(_lr_batch(seed=3))

        def boom(version, payload):
            raise RuntimeError("publisher crashed")

        with pytest.raises(RuntimeError, match="publisher crashed"):
            model.advance(on_snapshot=boom)
        assert model.model_version == 3  # the snapshot itself was applied
        stream.add(_lr_batch(seed=4))
        assert model.advance() == 1  # training continues at the next version
        assert model.model_version == 4

    def test_save_load_preserves_model_version(self, tmp_path):
        stream = QueueBatchStream()
        model = (
            OnlineLogisticRegression()
            .set_initial_model_data(_init_lr_model_data())
            .fit(stream)
        )
        stream.add(_lr_batch(seed=1))
        stream.add(_lr_batch(seed=2))
        model.advance()
        path = str(tmp_path / "olr")
        model.save(path)
        loaded = OnlineLogisticRegressionModel.load(path)
        assert loaded.model_version == model.model_version == 2
        np.testing.assert_allclose(loaded.coefficient, model.coefficient)

    def test_ftrl_l1_produces_sparsity(self):
        stream = QueueBatchStream()
        model = (
            OnlineLogisticRegression()
            .set_initial_model_data(_init_lr_model_data())
            .set_reg(1.0)
            .set_elastic_net(1.0)
            .fit(stream)
        )
        stream.add(_lr_batch(seed=1))
        model.advance()
        assert np.count_nonzero(model.coefficient) < model.coefficient.size


class TestOnlineKMeans:
    def test_stepwise_updates_move_centroids(self):
        stream = QueueBatchStream()
        okm = (
            OnlineKMeans()
            .set_k(2)
            .set_seed(1)
            .set_decay_factor(0.5)
            .set_random_initial_model_data(dim=2)
        )
        model = okm.fit(stream)
        c0 = model.centroids.copy()

        pts = np.concatenate(
            [RNG.normal([0, 0], 0.1, (32, 2)), RNG.normal([5, 5], 0.1, (32, 2))]
        )
        stream.add({"features": pts})
        assert model.advance() == 1
        assert not np.allclose(model.centroids, c0)
        assert model.weights.sum() > 0

        # more batches refine towards the true blob centers
        for seed in range(8):
            rng = np.random.default_rng(seed)
            pts = np.concatenate(
                [rng.normal([0, 0], 0.1, (32, 2)), rng.normal([5, 5], 0.1, (32, 2))]
            )
            stream.add({"features": pts})
        model.advance()
        got = model.centroids[np.argsort(model.centroids[:, 0])]
        np.testing.assert_allclose(got, [[0, 0], [5, 5]], atol=0.5)

    def test_transform_uses_latest_version(self):
        stream = QueueBatchStream()
        model = (
            OnlineKMeans().set_k(2).set_seed(3).set_random_initial_model_data(dim=2).fit(stream)
        )
        pts = np.concatenate(
            [RNG.normal([0, 0], 0.1, (16, 2)), RNG.normal([5, 5], 0.1, (16, 2))]
        )
        stream.add({"features": pts})
        model.advance()
        pred = model.transform(DataFrame.from_dict({"features": pts}))["prediction"]
        assert len(set(pred[:16])) == 1 and len(set(pred[16:])) == 1

    def test_requires_initial_model(self):
        with pytest.raises(RuntimeError, match="initial model"):
            OnlineKMeans().fit(QueueBatchStream())


class TestOnlineStandardScaler:
    def test_versions_per_window_and_cumulative_stats(self):
        df = DataFrame.from_dict({"input": np.arange(12.0)[:, None]})
        scaler = OnlineStandardScaler().set_windows(CountTumblingWindows.of(4))
        model = scaler.fit(df)
        # 12 rows / window=4 → 3 windows, versions 0,1,2 (0-based like the reference)
        assert model.version_history == [0, 1, 2]
        assert model.model_version == 2
        # cumulative stats equal the batch scaler on all 12 rows
        batch_model = StandardScaler().set_input_col("input").fit(
            DataFrame.from_dict({"input": np.arange(12.0)[:, None]})
        )
        np.testing.assert_allclose(model.mean, batch_model.mean, atol=1e-6)
        np.testing.assert_allclose(model.std, batch_model.std, atol=1e-6)

    def test_stepwise_feed_each_batch_is_window(self):
        stream = QueueBatchStream()
        model = OnlineStandardScaler().fit(stream)
        stream.add({"input": np.asarray([[1.0], [3.0]])})
        assert model.advance() == 1
        assert model.model_version == 0
        np.testing.assert_allclose(model.mean, [2.0])
        stream.add({"input": np.asarray([[5.0], [7.0]])})
        model.advance()
        assert model.model_version == 1
        np.testing.assert_allclose(model.mean, [4.0])  # cumulative over 4 rows

    def test_transform_appends_version_column(self):
        df = DataFrame.from_dict({"input": RNG.normal(size=(8, 3))})
        model = OnlineStandardScaler().fit(df)
        out = model.transform(df)
        assert (out["version"] == model.model_version).all()
        scaled = out["output"]
        np.testing.assert_allclose(scaled.std(axis=0, ddof=1), 1.0, atol=1e-4)


class TestBatchStandardScaler:
    def test_fit_transform_defaults(self):
        X = RNG.normal(2.0, 3.0, size=(100, 4))
        df = DataFrame.from_dict({"input": X})
        model = StandardScaler().fit(df)
        out = model.transform(df)["output"]
        # withStd only (default): scaled by sample std, mean NOT removed
        np.testing.assert_allclose(out.std(axis=0, ddof=1), 1.0, atol=1e-4)
        assert abs(out.mean()) > 0.1

    def test_with_mean_with_std(self):
        X = RNG.normal(5.0, 2.0, size=(50, 2))
        df = DataFrame.from_dict({"input": X})
        model = StandardScaler().set_with_mean(True).fit(df)
        out = model.transform(df)["output"]
        np.testing.assert_allclose(out.mean(axis=0), 0.0, atol=1e-5)
        np.testing.assert_allclose(out.std(axis=0, ddof=1), 1.0, atol=1e-4)

    def test_zero_std_maps_to_zero(self):
        X = np.ones((5, 2))
        model = StandardScaler().fit(DataFrame.from_dict({"input": X}))
        out = model.transform(DataFrame.from_dict({"input": X}))["output"]
        np.testing.assert_array_equal(out, 0.0)

    def test_empty_input_raises(self):
        df = DataFrame(["input"], None, [np.zeros((0, 2))])
        with pytest.raises(RuntimeError, match="training set is empty"):
            StandardScaler().fit(df)

    def test_save_load(self, tmp_path):
        X = RNG.normal(size=(20, 3))
        model = StandardScaler().fit(DataFrame.from_dict({"input": X}))
        path = str(tmp_path / "ss")
        model.save(path)
        from flink_ml_tpu.models.feature.standard_scaler import StandardScalerModel

        loaded = StandardScalerModel.load(path)
        np.testing.assert_allclose(loaded.mean, model.mean)
        np.testing.assert_allclose(loaded.std, model.std)


class TestModelDelayGating:
    """Row-wise max-allowed-model-delay enforcement
    (OnlineStandardScalerModel.processElement1: serve iff
    rowTs - maxAllowedModelDelayMs <= modelTs, else buffer)."""

    def _fit_event_time(self, delay_ms):
        from flink_ml_tpu.models.feature.standard_scaler import TIMESTAMP_COL
        from flink_ml_tpu.ops.windows import EventTimeTumblingWindows

        # 3 windows of 100ms: rows at t=0..99 -> v0, 100..199 -> v1, 200..299 -> v2
        ts = np.asarray([10.0, 50.0, 110.0, 150.0, 210.0, 250.0])
        df = DataFrame.from_dict({"input": np.arange(6.0)[:, None], TIMESTAMP_COL: ts})
        stream = QueueBatchStream()
        stream.add(df)
        model = (
            OnlineStandardScaler()
            .set_windows(EventTimeTumblingWindows.of(100))
            .set_max_allowed_model_delay_ms(delay_ms)
            .fit(stream)
        )
        return model, stream

    def test_rows_join_earliest_fresh_enough_version(self):
        from flink_ml_tpu.models.feature.standard_scaler import TIMESTAMP_COL

        model, stream = self._fit_event_time(delay_ms=100)
        model.advance(1)  # v0 arrives (window max ts = 50)
        assert model.model_version == 0 and model.model_timestamp == 50.0

        # rows at t: 100 (needs modelTs >= 0 -> v0 ok), 200 (needs >= 100 -> v1),
        # 260 (needs >= 160 -> v2)
        q = DataFrame.from_dict(
            {"input": np.asarray([[1.0], [2.0], [3.0]]), TIMESTAMP_COL: np.asarray([100.0, 200.0, 260.0])}
        )
        out = model.transform(q)
        assert len(out) == 3, "all rows servable after auto-advancing"
        np.testing.assert_array_equal(out["version"], [0, 1, 2])
        # original row order preserved
        np.testing.assert_array_equal([v[0] for v in out["input"]], [1.0, 2.0, 3.0])

    def test_too_new_rows_buffer_until_version_arrives(self):
        from flink_ml_tpu.models.feature.standard_scaler import TIMESTAMP_COL

        model, stream = self._fit_event_time(delay_ms=0)
        model.advance(1)  # v0 (ts=50); windows for v1/v2 still pending in stream
        # consume the rest of the already-added data so the stream is dry
        model.advance()
        assert model.model_version == 2 and model.model_timestamp == 250.0

        q = DataFrame.from_dict(
            {"input": np.asarray([[1.0], [2.0]]), TIMESTAMP_COL: np.asarray([240.0, 400.0])}
        )
        out = model.transform(q)
        assert len(out) == 1  # t=240 servable by v2; t=400 too new
        np.testing.assert_array_equal(out["version"], [2])
        assert model.pending_rows == 1

        # a fresher window arrives -> buffered row becomes servable
        stream.add(
            DataFrame.from_dict(
                {"input": np.asarray([[9.0], [9.5]]), TIMESTAMP_COL: np.asarray([410.0, 450.0])}
            )
        )
        served = model.serve_pending()
        assert served is not None and len(served) == 1
        assert model.pending_rows == 0
        assert served["version"][0] == model.model_version

    def test_no_timestamp_column_serves_everything(self):
        model, _ = self._fit_event_time(delay_ms=0)
        model.advance(1)
        out = model.transform(DataFrame.from_dict({"input": np.asarray([[1.0], [2.0]])}))
        assert len(out) == 2

    def test_model_timestamp_survives_save_load(self, tmp_path):
        from flink_ml_tpu.models.feature.standard_scaler import (
            TIMESTAMP_COL,
            OnlineStandardScalerModel,
        )

        model, _ = self._fit_event_time(delay_ms=0)
        model.advance()  # all 3 windows; model ts = 250
        model.save(str(tmp_path / "oss"))
        loaded = OnlineStandardScalerModel.load(str(tmp_path / "oss"))
        assert loaded.model_timestamp == 250.0
        assert loaded.model_version == 2
        q = DataFrame.from_dict(
            {"input": np.asarray([[1.0]]), TIMESTAMP_COL: np.asarray([200.0])}
        )
        out = loaded.transform(q)  # must serve, not buffer forever
        assert len(out) == 1 and loaded.pending_rows == 0

    def test_processing_time_windows_one_version_per_added_batch(self):
        from flink_ml_tpu.models.feature.standard_scaler import TIMESTAMP_COL
        from flink_ml_tpu.ops.windows import ProcessingTimeTumblingWindows

        # Even with an event-time column spanning many window widths, a
        # processing-time window on a feedable stream fires per added batch —
        # event timestamps are the wrong time domain for it.
        stream = QueueBatchStream()
        model = (
            OnlineStandardScaler()
            .set_windows(ProcessingTimeTumblingWindows.of(1))
            .fit(stream)
        )
        stream.add(
            DataFrame.from_dict(
                {
                    "input": np.arange(4.0)[:, None],
                    TIMESTAMP_COL: np.asarray([0.0, 5000.0, 10000.0, 15000.0]),
                }
            )
        )
        assert model.advance() == 1, "one version per added batch"

    def test_legacy_checkpoint_without_timestamp_loads_ungated(self, tmp_path):
        import json, os
        from flink_ml_tpu.models.feature.standard_scaler import (
            TIMESTAMP_COL,
            OnlineStandardScalerModel,
        )

        model, _ = self._fit_event_time(delay_ms=0)
        model.advance()
        path = str(tmp_path / "legacy")
        model.save(path)
        meta_path = os.path.join(path, "metadata")
        meta = json.load(open(meta_path))
        del meta["modelTimestamp"]  # simulate a pre-gating checkpoint
        json.dump(meta, open(meta_path, "w"))
        loaded = OnlineStandardScalerModel.load(path)
        assert loaded.model_timestamp == float("inf")
        q = DataFrame.from_dict(
            {"input": np.asarray([[1.0]]), TIMESTAMP_COL: np.asarray([1e12])}
        )
        assert len(loaded.transform(q)) == 1  # ungated, never buffered forever

    def test_pending_rows_survive_save_load(self, tmp_path):
        from flink_ml_tpu.models.feature.standard_scaler import (
            TIMESTAMP_COL,
            OnlineStandardScalerModel,
        )

        model, stream = self._fit_event_time(delay_ms=0)
        model.advance()
        q = DataFrame.from_dict(
            {"input": np.asarray([[7.0]]), TIMESTAMP_COL: np.asarray([400.0])}
        )
        model.transform(q)
        assert model.pending_rows == 1
        path = str(tmp_path / "with-pending")
        model.save(path)
        loaded = OnlineStandardScalerModel.load(path)
        assert loaded.pending_rows == 1
        pending = loaded._pending[0]
        np.testing.assert_array_equal(pending.column(TIMESTAMP_COL), [400.0])

    def test_pending_sparse_rows_survive_save_load(self, tmp_path):
        from flink_ml_tpu.linalg.vectors import SparseVector
        from flink_ml_tpu.models.feature.standard_scaler import (
            TIMESTAMP_COL,
            OnlineStandardScalerModel,
        )

        model, _ = self._fit_event_time(delay_ms=0)
        model.advance()
        q = DataFrame(
            ["input", TIMESTAMP_COL],
            None,
            [[SparseVector(1, [0], [7.0])], np.asarray([400.0])],
        )
        model.transform(q)
        assert model.pending_rows == 1
        path = str(tmp_path / "sparse-pending")
        model.save(path)
        loaded = OnlineStandardScalerModel.load(path)  # must not crash on pickle
        assert loaded.pending_rows == 1
        cell = loaded._pending[0].column("input")[0]
        np.testing.assert_array_equal(cell.to_array(), [7.0])


class TestOnlineModelPersistence:
    """Every online model's versioned state must survive save/load and keep
    serving identically (the model-data records carry modelVersion in the
    reference, e.g. LogisticRegressionModelData)."""

    def test_online_kmeans_save_load(self, tmp_path):
        stream = QueueBatchStream()
        model = (
            OnlineKMeans().set_k(2).set_seed(1).set_random_initial_model_data(dim=2).fit(stream)
        )
        pts = np.concatenate(
            [RNG.normal([0, 0], 0.1, (16, 2)), RNG.normal([5, 5], 0.1, (16, 2))]
        )
        stream.add({"features": pts})
        model.advance()
        path = str(tmp_path / "okm")
        model.save(path)
        loaded = OnlineKMeansModel.load(path)
        assert loaded.model_version == model.model_version
        np.testing.assert_allclose(loaded.centroids, model.centroids)
        df = DataFrame.from_dict({"features": pts})
        np.testing.assert_array_equal(
            loaded.transform(df)["prediction"], model.transform(df)["prediction"]
        )

    def test_online_lr_loaded_model_serves_identically(self, tmp_path):
        # (version/coefficient round-trip is covered by
        # test_save_load_preserves_model_version; this pins the serving path)
        stream = QueueBatchStream()
        model = (
            OnlineLogisticRegression()
            .set_initial_model_data(_init_lr_model_data())
            .set_global_batch_size(64)
            .fit(stream)
        )
        stream.add(_lr_batch(seed=2))
        model.advance()
        model.save(str(tmp_path / "olr"))
        loaded = OnlineLogisticRegressionModel.load(str(tmp_path / "olr"))
        X = _lr_batch(seed=3)["features"]
        df = DataFrame.from_dict({"features": X})
        np.testing.assert_array_equal(
            loaded.transform(df)["prediction"], model.transform(df)["prediction"]
        )

    def test_loaded_model_keeps_serving_without_stream(self, tmp_path):
        # A loaded model has no attached training stream: advance() is a no-op
        # and transform must not crash.
        stream = QueueBatchStream()
        model = (
            OnlineKMeans().set_k(2).set_seed(5).set_random_initial_model_data(dim=2).fit(stream)
        )
        stream.add({"features": RNG.normal(size=(8, 2))})
        model.advance()
        model.save(str(tmp_path / "m"))
        loaded = OnlineKMeansModel.load(str(tmp_path / "m"))
        assert loaded.advance() == 0
        out = loaded.transform(DataFrame.from_dict({"features": RNG.normal(size=(4, 2))}))
        assert len(out) == 4


class TestOnlineKillResume:
    """End-to-end kill/resume for online training (VERDICT r4 missing #2).

    Parity target: the reference checkpoints source offsets alongside operator
    state (Checkpoints.java:43-143; SGD's batch-offset state SGD.java:308-347),
    making unbounded training recoverable (UnboundedStreamIterationITCase).
    Here: the SnapshotDriver snapshots (version, batches_consumed, state,
    payload); "kill" = dropping the incarnation; "resume" = a fresh estimator
    with the same params + checkpoint dir and a source replaying from batch 0.
    Identity contract mirrors test_checkpoint.py: the resumed run must land on
    the *identical* model, with version continuity (no reuse, no gap).
    """

    # -- shared drivers --------------------------------------------------------
    @staticmethod
    def _feed(batches, close=True):
        stream = QueueBatchStream()
        for b in batches:
            stream.add(b)
        if close:
            stream.close()
        return stream

    def _lr_est(self, mgr=None, interval=1):
        est = (
            OnlineLogisticRegression()
            .set_initial_model_data(_init_lr_model_data())
            .set_global_batch_size(64)
        )
        if mgr is not None:
            est.set_checkpoint(mgr, interval)
        return est

    def _lr_batches(self, n=8):
        return [_lr_batch(n=64, seed=100 + i) for i in range(n)]

    def test_online_lr_kill_resume_identity_and_version_continuity(self, tmp_path):
        from flink_ml_tpu.checkpoint import CheckpointManager

        batches = self._lr_batches(8)
        clean = self._lr_est().fit(self._feed(batches))
        clean.advance()
        assert clean.model_version == 8

        # incarnation 1: checkpointing, killed after 5 versions
        mgr = CheckpointManager(str(tmp_path / "olr"))
        crashed = self._lr_est(mgr).fit(self._feed(batches[:5]))
        assert crashed.advance() == 5

        # incarnation 2: fresh estimator + manager, source replays from batch 0
        mgr2 = CheckpointManager(str(tmp_path / "olr"))
        resumed = self._lr_est(mgr2).fit(self._feed(batches))
        assert resumed.model_version == 5, "fit() restores the checkpointed version"
        np.testing.assert_array_equal(resumed.coefficient, crashed.coefficient)
        resumed.advance()
        assert resumed.model_version == 8
        assert resumed.version_history == [6, 7, 8], "continuity: no reuse, no gap"
        np.testing.assert_array_equal(resumed.coefficient, clean.coefficient)

    def test_online_lr_resume_with_interval_recomputes_tail(self, tmp_path):
        # interval=2: crash at version 5 restores version 4; batch 5 is
        # re-trained deterministically and the final model is still identical.
        from flink_ml_tpu.checkpoint import CheckpointManager

        batches = self._lr_batches(8)
        clean = self._lr_est().fit(self._feed(batches))
        clean.advance()

        mgr = CheckpointManager(str(tmp_path / "olr2"))
        crashed = self._lr_est(mgr, interval=2).fit(self._feed(batches[:5]))
        assert crashed.advance() == 5

        mgr2 = CheckpointManager(str(tmp_path / "olr2"))
        resumed = self._lr_est(mgr2, interval=2).fit(self._feed(batches))
        assert resumed.model_version == 4
        resumed.advance()
        assert resumed.model_version == 8
        assert resumed.version_history == [5, 6, 7, 8]
        np.testing.assert_array_equal(resumed.coefficient, clean.coefficient)

    def test_online_lr_lazy_skip_survives_stream_dry(self, tmp_path):
        # The replayed prefix may arrive incrementally: advance() while the
        # re-fed source is still short returns 0 (StreamDry) WITHOUT losing
        # the skip position; feeding the rest resumes cleanly.
        from flink_ml_tpu.checkpoint import CheckpointManager

        batches = self._lr_batches(6)
        mgr = CheckpointManager(str(tmp_path / "olr3"))
        crashed = self._lr_est(mgr).fit(self._feed(batches[:5]))
        assert crashed.advance() == 5

        mgr2 = CheckpointManager(str(tmp_path / "olr3"))
        stream = self._feed(batches[:3], close=False)  # partial replay so far
        resumed = self._lr_est(mgr2).fit(stream)
        assert resumed.advance() == 0  # still inside the consumed prefix
        assert resumed.model_version == 5
        for b in batches[3:]:
            stream.add(b)
        assert resumed.advance() == 1  # prefix skipped, batch 6 trained
        assert resumed.model_version == 6
        assert resumed.version_history == [6]

    def test_online_lr_fingerprint_guard_refuses_other_config(self, tmp_path):
        from flink_ml_tpu.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path / "fp"))
        crashed = self._lr_est(mgr).fit(self._feed(self._lr_batches(3)))
        crashed.advance()
        mgr2 = CheckpointManager(str(tmp_path / "fp"))
        other = self._lr_est(mgr2).set_alpha(0.9)
        with pytest.raises(ValueError, match="different\\s+run"):
            other.fit(self._feed(self._lr_batches(3)))

    def test_online_kmeans_kill_resume_identity(self, tmp_path):
        from flink_ml_tpu.checkpoint import CheckpointManager

        def kmeans_batches(n=8):
            out = []
            for i in range(n):
                rng = np.random.default_rng(200 + i)
                out.append(
                    {
                        "features": np.concatenate(
                            [rng.normal([0, 0], 0.1, (16, 2)), rng.normal([5, 5], 0.1, (16, 2))]
                        )
                    }
                )
            return out

        def est(mgr=None):
            e = (
                OnlineKMeans()
                .set_k(2)
                .set_seed(7)
                .set_decay_factor(0.7)
                .set_random_initial_model_data(dim=2)
            )
            if mgr is not None:
                e.set_checkpoint(mgr)
            return e

        batches = kmeans_batches(8)
        clean = est().fit(self._feed(batches))
        clean.advance()
        assert clean.model_version == 8

        mgr = CheckpointManager(str(tmp_path / "okm"))
        crashed = est(mgr).fit(self._feed(batches[:5]))
        assert crashed.advance() == 5

        mgr2 = CheckpointManager(str(tmp_path / "okm"))
        resumed = est(mgr2).fit(self._feed(batches))
        assert resumed.model_version == 5
        np.testing.assert_array_equal(resumed.centroids, crashed.centroids)
        resumed.advance()
        assert resumed.model_version == 8
        assert resumed.version_history == [6, 7, 8]
        np.testing.assert_array_equal(resumed.centroids, clean.centroids)
        np.testing.assert_array_equal(resumed.weights, clean.weights)

    def test_online_standard_scaler_kill_resume_identity(self, tmp_path):
        from flink_ml_tpu.checkpoint import CheckpointManager

        def scaler_batches(n=8):
            rng = np.random.default_rng(42)
            return [{"input": rng.normal(3.0, 2.0, size=(16, 3))} for _ in range(n)]

        def est(mgr=None):
            e = OnlineStandardScaler()
            if mgr is not None:
                e.set_checkpoint(mgr)
            return e

        batches = scaler_batches(8)
        clean = est().fit(self._feed(batches))
        clean.advance()
        assert clean.model_version == 7  # 0-based versions

        mgr = CheckpointManager(str(tmp_path / "oss"))
        crashed = est(mgr).fit(self._feed(batches[:5]))
        assert crashed.advance() == 5
        assert crashed.model_version == 4

        mgr2 = CheckpointManager(str(tmp_path / "oss"))
        resumed = est(mgr2).fit(self._feed(batches))
        assert resumed.model_version == 4, "0-based version restored"
        np.testing.assert_array_equal(resumed.mean, crashed.mean)
        resumed.advance()
        assert resumed.model_version == 7
        assert resumed.version_history == [5, 6, 7]
        np.testing.assert_array_equal(resumed.mean, clean.mean)
        np.testing.assert_array_equal(resumed.std, clean.std)

    def test_online_scaler_event_time_windows_resume_at_window_granularity(self, tmp_path):
        # The consumed offset counts *windows* (the stream the driver reads is
        # the window splitter), so resume works even when one added batch
        # splits into several versions.
        from flink_ml_tpu.checkpoint import CheckpointManager
        from flink_ml_tpu.models.feature.standard_scaler import TIMESTAMP_COL
        from flink_ml_tpu.ops.windows import EventTimeTumblingWindows

        ts = np.asarray([10.0, 110.0, 210.0, 310.0, 410.0, 510.0])
        df_cols = {"input": np.arange(6.0)[:, None], TIMESTAMP_COL: ts}

        def est(mgr=None):
            e = OnlineStandardScaler().set_windows(EventTimeTumblingWindows.of(100))
            if mgr is not None:
                e.set_checkpoint(mgr)
            return e

        clean = est().fit(self._feed([df_cols]))
        clean.advance()
        assert clean.model_version == 5  # 6 windows, 0-based

        mgr = CheckpointManager(str(tmp_path / "ossw"))
        crashed = est(mgr).fit(self._feed([df_cols]))
        assert crashed.advance(3) == 3  # kill after 3 of 6 windows
        assert crashed.model_version == 2

        mgr2 = CheckpointManager(str(tmp_path / "ossw"))
        resumed = est(mgr2).fit(self._feed([df_cols]))
        assert resumed.model_version == 2
        resumed.advance()
        assert resumed.model_version == 5
        assert resumed.version_history == [3, 4, 5]
        np.testing.assert_array_equal(resumed.mean, clean.mean)
        np.testing.assert_array_equal(resumed.std, clean.std)

    def test_different_initial_model_refuses_resume(self, tmp_path):
        # Initial model data is part of the run identity: warm-starting from
        # different coefficients with the same params must not silently
        # resume the old run's state.
        from flink_ml_tpu.checkpoint import CheckpointManager
        from flink_ml_tpu.linalg.vectors import DenseVector

        batches = self._lr_batches(3)
        mgr = CheckpointManager(str(tmp_path / "init"))
        self._lr_est(mgr).fit(self._feed(batches)).advance()

        other_init = DataFrame(["coefficient"], None, [[DenseVector(np.ones(4))]])
        mgr2 = CheckpointManager(str(tmp_path / "init"))
        other = (
            OnlineLogisticRegression()
            .set_initial_model_data(other_init)
            .set_global_batch_size(64)
            .set_checkpoint(mgr2)
        )
        with pytest.raises(ValueError, match="different\\s+run"):
            other.fit(self._feed(batches))

    def test_replay_shorter_than_offset_raises(self, tmp_path):
        # A closed source ending inside the consumed prefix is a replay-contract
        # violation, not a clean end of training.
        from flink_ml_tpu.checkpoint import CheckpointManager

        batches = self._lr_batches(5)
        mgr = CheckpointManager(str(tmp_path / "short"))
        crashed = self._lr_est(mgr).fit(self._feed(batches))
        assert crashed.advance() == 5

        mgr2 = CheckpointManager(str(tmp_path / "short"))
        resumed = self._lr_est(mgr2).fit(self._feed(batches[:2]))  # truncated replay
        with pytest.raises(ValueError, match="before the checkpointed offset"):
            resumed.advance()
