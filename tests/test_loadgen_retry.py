"""Client-side retry policy tests (flink_ml_tpu/loadgen/retry.py).

The well-behaved-overloaded-client contract: a typed overload is resubmitted
after the replica's own ``retry_after_ms`` drain estimate (jittered, capped,
bounded attempts), retries and hedges are counted as client-added load —
never as fresh arrivals — and the exhaustive-accounting invariant
(``fully_resolved``) survives every retry path.
"""
import threading

import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.loadgen import (
    Arrival,
    OpenLoopLoadGenerator,
    RetryPolicy,
    Schedule,
)
from flink_ml_tpu.serving.errors import ServingOverloadedError


def _overload(retry_after_ms=2.0, shed=True):
    return ServingOverloadedError(8, 8, retry_after_ms=retry_after_ms, shed=shed)


class TestRetryPolicy:
    def test_honors_retry_after_over_its_own_backoff(self):
        policy = RetryPolicy(3, backoff_ms=10.0, jitter=0.0)
        assert policy.delay_s(1, 50.0) == pytest.approx(0.050)
        assert policy.delay_s(1, None) == pytest.approx(0.010)

    def test_exponential_backoff_with_cap(self):
        policy = RetryPolicy(5, backoff_ms=10.0, backoff_max_ms=25.0, jitter=0.0)
        assert policy.delay_s(1, None) == pytest.approx(0.010)
        assert policy.delay_s(2, None) == pytest.approx(0.020)
        assert policy.delay_s(3, None) == pytest.approx(0.025)  # capped
        assert policy.delay_s(4, 1000.0) == pytest.approx(0.025)  # hint capped too

    def test_jitter_bounds(self):
        policy = RetryPolicy(3, backoff_ms=10.0, jitter=0.5, seed=7)
        for attempt in (1, 2, 3):
            d = policy.delay_s(attempt, 10.0)
            assert 0.010 <= d <= 0.015

    def test_ignores_hint_when_not_honoring(self):
        policy = RetryPolicy(3, backoff_ms=10.0, jitter=0.0, honor_retry_after=False)
        assert policy.delay_s(1, 500.0) == pytest.approx(0.010)


# ---------------------------------------------------------------------------
# generator integration: retries are client-added load, never arrivals
# ---------------------------------------------------------------------------
def _schedule(n=6, gap_s=0.001):
    entries = [Arrival(i * gap_s, 1, 0, 0) for i in range(n)]
    return Schedule(entries, meta={"steps": [(n / max(n * gap_s, 1e-9), n * gap_s)]})


class _Resp:
    def __init__(self):
        self.latency_ms = 1.0


class _Handle:
    def __init__(self, error=None):
        self._error = error

    def result(self):
        if self._error is not None:
            raise self._error
        return _Resp()


class _FlakyTarget:
    """Sheds the first ``shed_first`` attempts of every request (counting
    submit-time rejections), then serves. ``at_result`` moves the overload
    from submit time to ``result()`` — the async-replica shape."""

    def __init__(self, shed_first=1, at_result=False):
        self.shed_first = shed_first
        self.at_result = at_result
        self._lock = threading.Lock()
        self._attempts = {}
        self.submits = 0

    def submit(self, df, timeout_ms=None, priority=0):
        key = id(df)
        with self._lock:
            self.submits += 1
            n = self._attempts.get(key, 0)
            self._attempts[key] = n + 1
        if n < self.shed_first:
            if self.at_result:
                return _Handle(error=_overload())
            raise _overload()
        return _Handle()


def _run(target, *, attempts=3, n=6):
    gen = OpenLoopLoadGenerator(
        _schedule(n),
        lambda rows: DataFrame.from_dict({"features": np.zeros((rows, 2))}),
        collectors=2,
        retry=RetryPolicy(attempts, backoff_ms=0.1, jitter=0.0),
    )
    return gen.run(target)


class TestGeneratorRetries:
    def test_submit_time_sheds_are_retried_not_binned(self):
        n = 6
        target = _FlakyTarget(shed_first=1)
        report = _run(target, n=n)
        step = report.step(0)
        assert report.fully_resolved()
        assert step.arrivals == n  # retries never inflate arrivals
        assert step.completed == n
        assert step.retries == n  # one resubmission per arrival
        assert step.shed == 0 and step.rejected == 0
        assert target.submits == 2 * n

    def test_result_time_sheds_are_retried_on_the_collector(self):
        n = 4
        target = _FlakyTarget(shed_first=1, at_result=True)
        report = _run(target, n=n)
        step = report.step(0)
        assert report.fully_resolved()
        assert step.completed == n
        assert step.retries == n

    def test_exhausted_retries_bin_as_the_typed_overload(self):
        n = 3
        attempts = 2
        target = _FlakyTarget(shed_first=10)  # never recovers
        report = _run(target, attempts=attempts, n=n)
        step = report.step(0)
        assert report.fully_resolved()
        assert step.completed == 0
        assert step.shed == n  # final typed overload lands in its bin
        assert step.retries == attempts * n  # bounded attempts per arrival
        assert not step.unexpected

    def test_no_policy_keeps_the_old_immediate_binning(self):
        n = 3
        target = _FlakyTarget(shed_first=10)
        gen = OpenLoopLoadGenerator(
            _schedule(n),
            lambda rows: DataFrame.from_dict({"features": np.zeros((rows, 2))}),
            collectors=2,
        )
        report = gen.run(target)
        step = report.step(0)
        assert report.fully_resolved()
        assert step.shed == n
        assert step.retries == 0

    def test_stats_dict_carries_retry_and_hedge_bins(self):
        report = _run(_FlakyTarget(shed_first=1), n=2)
        d = report.step(0).as_dict()
        assert "retries" in d and "hedges" in d
        assert d["retries"] == 2
