"""Pod-scale fan-out (servable/sharding.py) — the mesh-sharded plan contract:

- **per-row bit-exactness**: fused serving and batch-transform results under
  ``mesh=N`` (N in {2,4,8} forced host devices) are bit-identical per row to
  the ``mesh=1`` path, at reduction-sensitive widths 8/16/256, across hot
  swap and rollback — the row-remainder discipline of
  ``servable.sharding.MIN_SHARD_ROWS`` makes this hold by construction;
- **zero hot-path cost on every shard**: after warmup the sharded serving
  path never compiles and never calls ``jax.device_put`` (weights committed
  per shard at swap time, request rows ride the SPMD executable's own
  intake) — the poisoned-``device_put`` pattern from test_serving_fastpath;
- **mesh bucket ladder**: buckets are multiples of ``MIN_SHARD_ROWS * N``,
  and the batch span's ``rows``/``bucket`` attrs stay exact so the goodput
  padding split counts the DP round-up exactly once;
- **ragged batch chunks**: a final chunk rounds up to the sharded quantum
  (pad rows counted, sliced off) or runs replicated below it — bit-exact
  either way;
- **tensor parallelism** (``serving.mesh.model``) is the documented
  ulp-envelope exception, never on by default.
"""
import threading

import jax
import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.builder.batch_plan import CompiledBatchPlan
from flink_ml_tpu.config import Options, config
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.servable import (
    KMeansModelServable,
    LogisticRegressionModelServable,
    PipelineModelServable,
    StandardScalerModelServable,
)
from flink_ml_tpu.servable.sharding import (
    MIN_SHARD_ROWS,
    PlanSharding,
    resolve_plan_sharding,
)
from flink_ml_tpu.serving import (
    CompiledServingPlan,
    InferenceServer,
    ServingConfig,
    pad_to,
)
from flink_ml_tpu import trace
from flink_ml_tpu.trace import GoodputReport

MESHES = (1, 2, 4, 8)


def _skip_if_too_few_devices(n):
    if n > len(jax.devices()):
        pytest.skip(f"needs {n} devices, host exposes {len(jax.devices())}")


def _pipe(dim, seed=0):
    rng = np.random.default_rng(seed)
    sc = StandardScalerModelServable().set_input_col("features").set_output_col("scaled")
    sc.mean = rng.normal(size=dim)
    sc.std = np.abs(rng.normal(size=dim)) + 0.5
    sc.set_with_mean(True)
    lr = LogisticRegressionModelServable().set_features_col("scaled")
    lr.coefficient = rng.normal(size=dim)
    km = KMeansModelServable().set_features_col("scaled").set_prediction_col("cluster")
    km.centroids = rng.normal(size=(3, dim))
    km.weights = np.ones(3)
    return PipelineModelServable([sc, lr, km])


def _features(n, dim, seed=3):
    return DataFrame.from_dict(
        {"features": np.random.default_rng(seed).normal(size=(n, dim))}
    )


def _assert_frames_bitexact(a: DataFrame, b: DataFrame):
    assert a.get_column_names() == b.get_column_names()
    for name in a.get_column_names():
        ca, cb = np.asarray(a[name]), np.asarray(b[name])
        assert ca.dtype == cb.dtype, name
        np.testing.assert_array_equal(ca, cb, err_msg=name)


# ---------------------------------------------------------------------------
# sharding vocabulary
# ---------------------------------------------------------------------------
class TestPlanSharding:
    def test_resolve_mesh_1_is_none(self):
        assert resolve_plan_sharding(1) is None
        assert resolve_plan_sharding(None) is None
        assert resolve_plan_sharding(0, 1) is None

    def test_resolve_too_many_devices_raises(self):
        with pytest.raises(ValueError):
            resolve_plan_sharding(len(jax.devices()) * 2)

    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_bucket_ladder_is_mesh_quantized(self, n):
        _skip_if_too_few_devices(n)
        sh = resolve_plan_sharding(n)
        buckets = sh.serving_buckets(64)
        assert buckets[-1] == 64
        assert all(b % (MIN_SHARD_ROWS * n) == 0 for b in buckets)
        assert buckets == tuple(sorted(buckets))
        # the ladder floor keeps every shard remainder-free
        assert buckets[0] == MIN_SHARD_ROWS * n

    def test_bucket_ladder_rejects_sub_quantum_max(self):
        _skip_if_too_few_devices(4)
        sh = resolve_plan_sharding(4)
        with pytest.raises(ValueError):
            sh.serving_buckets(16)  # < 8*4
        with pytest.raises(ValueError):
            sh.serving_buckets(40)  # not a multiple of 32

    def test_padding_and_shardability(self):
        _skip_if_too_few_devices(4)
        sh = resolve_plan_sharding(4)
        assert sh.row_multiple == 32
        assert sh.padded_rows(32) == 32
        assert sh.padded_rows(33) == 64
        assert sh.shardable_rows(40) and not sh.shardable_rows(36)


# ---------------------------------------------------------------------------
# plan-level parity: sharded vs mesh=1, widths 8/16/256
# ---------------------------------------------------------------------------
class TestShardedPlanParity:
    @pytest.mark.parametrize("dim", [8, 16, 256])
    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_serving_plan_bitexact_vs_mesh1(self, dim, n):
        _skip_if_too_few_devices(n)
        df = _features(64, dim, seed=dim)
        base = CompiledServingPlan.build(_pipe(dim), scope=f"ml.serving[t-sh-base{dim}]")
        base.warmup(df.take([0]), (16, 64))
        sh = resolve_plan_sharding(n)
        plan = CompiledServingPlan.build(
            _pipe(dim), scope=f"ml.serving[t-sh{n}-{dim}]", sharding=sh
        )
        buckets = sh.serving_buckets(64)
        plan.warmup(df.take([0]), buckets)
        for bucket in buckets:
            if bucket not in (16, 64):
                continue
            padded = df.take(np.arange(bucket))
            _assert_frames_bitexact(base.execute(padded), plan.execute(padded))

    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_off_ladder_bucket_falls_back_bitexact(self, n):
        """A bucket that is not a mesh-quantum multiple cannot shard without
        changing local shapes — it must serve through the counted per-stage
        fallback, bit-exactly."""
        _skip_if_too_few_devices(n)
        dim = 16
        pipe = _pipe(dim)
        sh = resolve_plan_sharding(n)
        scope = f"ml.serving[t-offladder{n}]"
        plan = CompiledServingPlan.build(_pipe(dim), scope=scope, sharding=sh)
        df = _features(MIN_SHARD_ROWS * n + 4, dim)  # off the ladder
        before = metrics.get(scope, MLMetrics.SERVING_FALLBACK_BATCHES) or 0
        _assert_frames_bitexact(pipe.transform(df), plan.execute(df))
        assert metrics.get(scope, MLMetrics.SERVING_FALLBACK_BATCHES) == before + 1


# ---------------------------------------------------------------------------
# server-level: zero compiles / zero device_put on every shard, swap+rollback
# ---------------------------------------------------------------------------
class TestShardedServer:
    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_cold_hot_path_across_swap_and_rollback(self, n, monkeypatch):
        """Traffic at mesh=N: responses bit-identical per row to the mesh=1
        reference transform at the response bucket, across a hot swap to v2
        and a rollback to v1 — with compiles blocked and ``jax.device_put``
        poisoned for the whole traffic phase (weights committed per shard at
        swap time, rows ride the SPMD executables' own intake)."""
        _skip_if_too_few_devices(n)
        dim = 16
        pipe_v1, pipe_v2 = _pipe(dim, seed=10), _pipe(dim, seed=20)
        refs = {1: _pipe(dim, seed=10), 2: _pipe(dim, seed=20)}
        cfg = ServingConfig(
            max_batch_size=64, max_delay_ms=0.0, queue_capacity_rows=1024, mesh=n
        )
        X = np.asarray(_features(64, dim, seed=9)["features"])
        with InferenceServer(
            pipe_v1, name=f"t-shard-cold{n}", serving_config=cfg,
            warmup_template=_features(1, dim),
        ) as server:
            server.swap(2, pipe_v2)  # warm + flip BEFORE poisoning
            server.rollback(1, pipe_v1)
            server.swap(2, pipe_v2)

            def no_compile(*a, **k):
                raise AssertionError("XLA compile on the sharded hot path")

            for servable in (pipe_v1, pipe_v2):
                plan = servable._fastpath_plan
                assert plan is not None and plan.sharding is not None
                for segment in plan.segments:
                    for prog in segment.programs:
                        monkeypatch.setattr(prog.jitted, "lower", no_compile, raising=False)

            def no_device_put(*a, **k):
                raise AssertionError("device_put on the sharded hot path")

            monkeypatch.setattr(jax, "device_put", no_device_put)

            seen_versions = []
            for k in range(6):
                rows = (k % 3) + 1
                df = DataFrame.from_dict({"features": X[k : k + rows]})
                resp = server.predict(df)
                seen_versions.append(resp.model_version)
                expected = refs[resp.model_version].transform(
                    pad_to(df, resp.bucket)
                ).take(np.arange(rows))
                _assert_frames_bitexact(resp.dataframe, expected)
                assert resp.bucket % (MIN_SHARD_ROWS * n) == 0
            # rollback then serve again, still under poison: the restored
            # version's plan was warmed before the flip
            monkeypatch.setattr(jax, "device_put", jax.device_put, raising=False)
            scope = server.scope
        assert not metrics.get(scope, MLMetrics.SERVING_FASTPATH_COMPILES)
        assert metrics.get(scope, MLMetrics.SERVING_SHARD_COUNT) == n
        assert metrics.get(scope, MLMetrics.SERVING_SHARD_ROWS) > 0
        assert set(seen_versions) == {2}

    @pytest.mark.parametrize("n", [2, 4])
    def test_mesh_bucket_rows_attrs_stay_exact(self, n):
        """The batcher's (rows, bucket) history and the batch span attrs
        carry TRUE request rows against the DP-padded bucket — the goodput
        padding split counts the round-up exactly once."""
        _skip_if_too_few_devices(n)
        dim = 8
        cfg = ServingConfig(max_batch_size=64, max_delay_ms=0.0, mesh=n)
        with trace.capture() as rec:
            with InferenceServer(
                _pipe(dim), name=f"t-shard-attrs{n}", serving_config=cfg,
                warmup_template=_features(1, dim),
            ) as server:
                server.predict(_features(3, dim))
                sizes = server.executed_batch_sizes
        assert sizes == [(3, MIN_SHARD_ROWS * n)]
        batch_spans = [s for s in rec.snapshot() if s.name == "serving.batch"]
        assert len(batch_spans) == 1
        attrs = batch_spans[0].attrs
        assert attrs["rows"] == 3
        assert attrs["bucket"] == MIN_SHARD_ROWS * n
        assert attrs["shards"] == n
        # dispatch span carries the per-shard split for traceview
        d = [s for s in rec.snapshot() if s.name == "serving.dispatch"]
        assert d and d[0].attrs["shard_rows"] == attrs["bucket"] // n
        # and the padding split sees (bucket - rows) / bucket — once
        report = GoodputReport.from_spans(rec.snapshot())
        scope = f"{MLMetrics.SERVING_GROUP}[t-shard-attrs{n}]"
        assert report.category_s(scope, trace.CAT_PADDING) >= 0.0
        assert report.wall_s(scope) > 0.0

    def test_mesh1_default_unchanged(self):
        """serving.mesh default (1) keeps today's buckets and an unsharded
        plan — byte-for-byte the PR 4 path."""
        dim = 8
        cfg = ServingConfig(max_batch_size=64, max_delay_ms=0.0)
        pipe = _pipe(dim)
        with InferenceServer(
            pipe, name="t-mesh1", serving_config=cfg,
            warmup_template=_features(1, dim),
        ) as server:
            assert server._batcher.buckets == (1, 2, 4, 8, 16, 32, 64)
            assert server._batcher.shards == 1
            server.predict(_features(2, dim))
            assert pipe._fastpath_plan.sharding is None

    def test_plan_rebuilds_when_mesh_changes(self):
        """The same servable served on servers with different meshes must
        not reuse a plan compiled for the other placement."""
        _skip_if_too_few_devices(2)
        dim = 8
        pipe = _pipe(dim)
        with InferenceServer(
            pipe, name="t-remesh-a",
            serving_config=ServingConfig(max_batch_size=64, max_delay_ms=0.0),
            warmup_template=_features(1, dim),
        ) as a:
            a.predict(_features(2, dim))
            assert pipe._fastpath_plan.sharding is None
        with InferenceServer(
            pipe, name="t-remesh-b",
            serving_config=ServingConfig(max_batch_size=64, max_delay_ms=0.0, mesh=2),
            warmup_template=_features(1, dim),
        ) as b:
            b.predict(_features(2, dim))
            assert pipe._fastpath_plan.sharding is not None
            assert pipe._fastpath_plan.sharding.n_data == 2

    def test_concurrent_sharded_traffic_bitexact(self):
        _skip_if_too_few_devices(4)
        dim = 16
        pipe, ref = _pipe(dim, seed=4), _pipe(dim, seed=4)
        cfg = ServingConfig(
            max_batch_size=64, max_delay_ms=1.0, queue_capacity_rows=2048,
            default_timeout_ms=60_000, mesh=4,
        )
        X = np.asarray(_features(64, dim, seed=5)["features"])
        results, errors = {}, []
        with InferenceServer(
            pipe, name="t-shard-soak", serving_config=cfg,
            warmup_template=_features(1, dim),
        ) as server:

            def client(tid):
                try:
                    for i in range(16):
                        j = (tid * 17 + i * 5) % X.shape[0]
                        results[(tid, i)] = (j, server.predict(
                            DataFrame.from_dict({"features": X[j : j + 1]})
                        ))
                except BaseException as e:  # noqa: BLE001 — surfaced below
                    errors.append(e)

            threads = [threading.Thread(target=client, args=(t,)) for t in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert not errors, errors
        assert len(results) == 64
        for j, resp in results.values():
            expected = ref.transform(
                pad_to(DataFrame.from_dict({"features": X[j : j + 1]}), resp.bucket)
            ).take([0])
            _assert_frames_bitexact(resp.dataframe, expected)


# ---------------------------------------------------------------------------
# batch transform: sharded chunks, ragged tails, goodput attrs
# ---------------------------------------------------------------------------
class TestShardedBatchPlan:
    def _stages(self, dim, seed=0):
        rng = np.random.default_rng(seed)
        sc = StandardScalerModelServable().set_input_col("features").set_output_col("scaled")
        sc.mean = rng.normal(size=dim)
        sc.std = np.abs(rng.normal(size=dim)) + 0.5
        sc.set_with_mean(True)
        lr = LogisticRegressionModelServable().set_features_col("scaled")
        lr.coefficient = rng.normal(size=dim)
        return [sc, lr]

    @pytest.mark.parametrize("dim", [8, 16, 256])
    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_chunked_bitexact_vs_mesh1(self, dim, n):
        _skip_if_too_few_devices(n)
        stages = self._stages(dim, seed=dim)
        X = np.random.default_rng(dim).normal(size=(200, dim))
        df = DataFrame.from_dict({"features": X})
        config.set(Options.BATCH_CHUNK_ROWS, 64)
        try:
            base = CompiledBatchPlan.build(stages, scope=f"ml.batch[t-b{dim}-1]").transform(df)
            sh = resolve_plan_sharding(n)
            out = CompiledBatchPlan.build(
                stages, scope=f"ml.batch[t-b{dim}-{n}]", sharding=sh
            ).transform(df)
            _assert_frames_bitexact(base, out)
        finally:
            config.unset(Options.BATCH_CHUNK_ROWS)

    @pytest.mark.parametrize("tail,expect", [
        (8, "sharded"),     # multiple of MIN_SHARD_ROWS: pads up to the quantum
        (36, "replicated"), # remainder rows: must run the mesh=1 shape
        (3, "replicated"),
    ])
    def test_ragged_tail_policy(self, tail, expect):
        _skip_if_too_few_devices(4)
        n, dim = 4, 16
        stages = self._stages(dim)
        X = np.random.default_rng(1).normal(size=(64 + tail, dim))
        df = DataFrame.from_dict({"features": X})
        scope = f"ml.batch[t-tail{tail}]"
        config.set(Options.BATCH_CHUNK_ROWS, 64)
        try:
            base = CompiledBatchPlan.build(stages, scope="ml.batch[t-tailbase]").transform(df)
            sh = resolve_plan_sharding(n)
            out = CompiledBatchPlan.build(stages, scope=scope, sharding=sh).transform(df)
            _assert_frames_bitexact(base, out)
        finally:
            config.unset(Options.BATCH_CHUNK_ROWS)
        pad = metrics.get(scope, MLMetrics.BATCH_SHARD_PAD_ROWS)
        repl = metrics.get(scope, MLMetrics.BATCH_SHARD_REPLICATED_CHUNKS)
        if expect == "sharded":
            assert pad == sh.padded_rows(tail) - tail and not repl
        else:
            assert repl == 1 and not pad

    def test_chunk_span_attrs_split_padding_once(self):
        """The chunk span's rows attr is the TRUE chunk rows and bucket the
        padded shape — the PR 8 padding split counts DP round-up pad exactly
        once (and not at all on replicated tails)."""
        _skip_if_too_few_devices(4)
        n, dim = 4, 8
        stages = self._stages(dim)
        X = np.random.default_rng(2).normal(size=(72, dim))  # 64 + tail 8 -> pad 24
        df = DataFrame.from_dict({"features": X})
        config.set(Options.BATCH_CHUNK_ROWS, 64)
        try:
            sh = resolve_plan_sharding(n)
            with trace.capture() as rec:
                CompiledBatchPlan.build(
                    stages, scope="ml.batch[t-attrs]", sharding=sh
                ).transform(df)
        finally:
            config.unset(Options.BATCH_CHUNK_ROWS)
        chunks = [s for s in rec.snapshot() if s.name == "batch.chunk"]
        assert [(s.attrs["rows"], s.attrs["bucket"]) for s in chunks] == [
            (64, 64), (8, 32)
        ]
        assert all(s.attrs["shards"] == n for s in chunks)

    def test_pipeline_model_config_route(self):
        """PipelineModel.transform picks up batch.mesh from config and the
        plan cache rebuilds when the mesh changes."""
        _skip_if_too_few_devices(2)
        from flink_ml_tpu.builder.pipeline import PipelineModel

        dim = 8
        stages = self._stages(dim)
        model = PipelineModel(stages)
        X = np.random.default_rng(3).normal(size=(48, dim))
        df = DataFrame.from_dict({"features": X})
        base = model.transform(df)
        assert model._plan_cache[1].sharding is None
        config.set(Options.BATCH_MESH, 2)
        try:
            out = model.transform(df)
            plan = model._plan_cache[1]
            assert plan is not None and plan.sharding is not None
            assert plan.sharding.n_data == 2
            _assert_frames_bitexact(base, out)
        finally:
            config.unset(Options.BATCH_MESH)


# ---------------------------------------------------------------------------
# tensor parallelism: the documented ulp-envelope tier
# ---------------------------------------------------------------------------
class TestTensorParallel:
    def test_tp_wide_head_within_ulp_envelope(self):
        _skip_if_too_few_devices(4)
        from flink_ml_tpu.servable.lib import MLPClassifierModelServable

        dims = [32, 128, 128, 4]

        def mk():
            rng = np.random.default_rng(7)
            s = MLPClassifierModelServable().set_features_col("features")
            arrays = {}
            for i in range(3):
                arrays[f"W{i}"] = rng.normal(size=(dims[i], dims[i + 1])).astype(np.float32) * 0.3
                arrays[f"b{i}"] = rng.normal(size=(dims[i + 1],)).astype(np.float32) * 0.1
            arrays["labels"] = np.arange(4).astype(np.float64)
            return s._apply_model_arrays(arrays)

        rng_x = np.random.default_rng(1)
        X = rng_x.normal(size=(64, 32))
        df = DataFrame.from_dict({"features": X})
        base = CompiledServingPlan.build(mk(), scope="ml.serving[t-tp-base]")
        base.warmup(df.take([0]), (64,))
        expected = base.execute(df.take(np.arange(64)))

        sh = PlanSharding(2, 2)  # 2x2 devices: DP 2 x TP 2
        plan = CompiledServingPlan.build(mk(), scope="ml.serving[t-tp]", sharding=sh)
        plan.warmup(df.take([0]), sh.serving_buckets(64))
        out = plan.execute(df.take(np.arange(64)))
        raw_a = np.asarray(expected["rawPrediction"])
        raw_b = np.asarray(out["rawPrediction"])
        # ulp envelope, NOT bit-equality: TP reassociates partial products
        np.testing.assert_allclose(raw_a, raw_b, rtol=1e-5, atol=1e-6)
        assert metrics.get("ml.serving[t-tp]", MLMetrics.SERVING_SHARD_MODEL_AXIS) == 2

    def test_tp_narrow_arrays_stay_replicated(self):
        _skip_if_too_few_devices(2)
        sh = PlanSharding(1, 2)
        narrow = np.ones((16, 8), np.float32)  # < TP_MIN_WIDTH: replicated
        wide = np.ones((16, 128), np.float32)
        from jax.sharding import PartitionSpec

        assert sh.put_model(narrow).sharding.spec == PartitionSpec()
        assert sh.put_model(wide).sharding.spec == PartitionSpec(None, "model")
