"""Sparse (padded-CSR) training and inference.

Ref SparseVector.java + BLAS.java:30-179 sparse branches: the reference's
linear models consume SparseVector end-to-end. Here the contract under test is
(a) sparse training/inference agrees with the densified path on narrow data,
and (b) Criteo-width data (d = 2^20) trains and serves without ever
materializing an [n, d] array.
"""
import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.iteration import IterationListener
from flink_ml_tpu.linalg.sparse_batch import SparseBatch
from flink_ml_tpu.linalg.vectors import SparseVector
from flink_ml_tpu.ops import SGD, BinaryLogisticLoss, HingeLoss, LeastSquareLoss


def _to_sparse_rows(X):
    rows = []
    for r in X:
        nz = np.nonzero(r)[0]
        rows.append(SparseVector(X.shape[1], nz, r[nz]))
    return rows


def _sparse_data(n, d, nnz, seed=0):
    """Random sparse rows; labels from a sparse ground-truth coefficient."""
    rng = np.random.default_rng(seed)
    idx = np.stack([rng.choice(d, nnz, replace=False) for _ in range(n)]).astype(np.int32)
    vals = rng.standard_normal((n, nnz)).astype(np.float32)
    w_true = np.zeros(d, np.float32)
    hot = rng.choice(d, 64, replace=False)
    w_true[hot] = rng.standard_normal(64)
    dots = np.sum(vals * w_true[idx], axis=1)
    y = (dots > 0).astype(np.float32)
    return idx, vals, y


class TestSparseBatch:
    def test_from_vectors_pads_and_round_trips(self):
        vecs = [
            SparseVector(10, [1, 7], [2.0, -1.0]),
            SparseVector(10, [0], [3.0]),
            SparseVector(10, [], []),
        ]
        batch = SparseBatch.from_vectors(vecs)
        assert batch.dim == 10 and batch.n == 3 and batch.width == 8  # padded to lane
        np.testing.assert_array_equal(batch.densify(), np.stack([v.to_array() for v in vecs]))
        got = batch.row(0)
        np.testing.assert_array_equal(got.indices, [1, 7])
        np.testing.assert_array_equal(got.values, [2.0, -1.0])

    def test_inconsistent_dims_rejected(self):
        with pytest.raises(ValueError, match="sizes"):
            SparseBatch.from_vectors([SparseVector(5, [0], [1.0]), SparseVector(6, [0], [1.0])])

    def test_explicit_zero_entries_round_trip(self):
        batch = SparseBatch.from_vectors([SparseVector(10, [3, 5], [0.0, 2.0])])
        got = batch.row(0)
        np.testing.assert_array_equal(got.indices, [3, 5])
        np.testing.assert_array_equal(got.values, [0.0, 2.0])

    def test_mixed_dense_sparse_column_packs(self):
        from flink_ml_tpu.linalg.vectors import DenseVector

        df = DataFrame.from_dict(
            {"features": [SparseVector(4, [0], [1.0]), DenseVector([0.0, 1.0, 0.0, 2.0])]}
        )
        assert df.is_sparse("features")
        batch = df.sparse_batch("features")
        np.testing.assert_array_equal(
            batch.densify(), [[1.0, 0, 0, 0], [0, 1.0, 0, 2.0]]
        )


class TestLossAndMult:
    @pytest.mark.parametrize(
        "loss", [BinaryLogisticLoss.INSTANCE, HingeLoss.INSTANCE, LeastSquareLoss.INSTANCE]
    )
    def test_mult_reproduces_gradient(self, loss):
        """X.T @ mult (the dot-level primitive) must equal loss_and_grad_sum."""
        import jax.numpy as jnp

        rng = np.random.default_rng(3)
        X = rng.standard_normal((32, 6)).astype(np.float32)
        y = rng.integers(0, 2, 32).astype(np.float32)
        w = rng.uniform(0.5, 2.0, 32).astype(np.float32)
        coef = rng.standard_normal(6).astype(np.float32)
        want_loss, want_grad = loss.loss_and_grad_sum(
            jnp.asarray(coef), jnp.asarray(X), jnp.asarray(y), jnp.asarray(w)
        )
        got_loss, mult = loss.loss_and_mult(jnp.asarray(X @ coef), jnp.asarray(y), jnp.asarray(w))
        np.testing.assert_allclose(got_loss, want_loss, rtol=1e-6)
        np.testing.assert_allclose(X.T @ np.asarray(mult), np.asarray(want_grad), rtol=1e-5, atol=1e-6)


class TestSparseSGD:
    def _narrow(self, seed=0):
        rng = np.random.default_rng(seed)
        X = rng.standard_normal((96, 12)).astype(np.float32)
        X[rng.random(X.shape) < 0.6] = 0.0  # sparsify
        y = (X @ rng.standard_normal(12) > 0).astype(np.float32)
        return X, y

    @pytest.mark.parametrize("tol", [0.0, 0.3])
    def test_sparse_matches_dense_fused(self, tol):
        X, y = self._narrow()
        batch = SparseBatch.from_vectors(_to_sparse_rows(X))
        kwargs = dict(max_iter=25, global_batch_size=32, tol=tol, learning_rate=0.4,
                      reg=0.01, elastic_net=0.5)
        dense = SGD(**kwargs).optimize(
            np.zeros(12, np.float32), {"features": X, "labels": y}, BinaryLogisticLoss.INSTANCE
        )
        sparse = SGD(**kwargs).optimize(
            np.zeros(12, np.float32),
            {"indices": batch.indices, "values": batch.values, "labels": y},
            BinaryLogisticLoss.INSTANCE,
        )
        np.testing.assert_allclose(sparse, dense, rtol=1e-4, atol=1e-6)

    def test_sparse_host_loop_matches_fused(self):
        X, y = self._narrow(seed=5)
        batch = SparseBatch.from_vectors(_to_sparse_rows(X))
        cols = {"indices": batch.indices, "values": batch.values, "labels": y}
        kwargs = dict(max_iter=10, global_batch_size=32, tol=0.0, learning_rate=0.4)
        fused = SGD(**kwargs).optimize(np.zeros(12, np.float32), cols, BinaryLogisticLoss.INSTANCE)
        # A listener forces the per-epoch host loop; same math, same result.
        host = SGD(listeners=[IterationListener()], **kwargs).optimize(
            np.zeros(12, np.float32), cols, BinaryLogisticLoss.INSTANCE
        )
        np.testing.assert_allclose(host, fused, rtol=1e-5, atol=1e-6)

    def test_sparse_streamed_matches_resident(self, tmp_path):
        from flink_ml_tpu.iteration import HostDataCache

        idx, vals, y = _sparse_data(n=128, d=512, nnz=8, seed=2)
        cols = {"indices": idx, "values": vals, "labels": y}
        kwargs = dict(max_iter=13, global_batch_size=32, tol=0.0, learning_rate=0.3)
        want = SGD(**kwargs).optimize(np.zeros(512, np.float32), cols, BinaryLogisticLoss.INSTANCE)
        cache = HostDataCache(memory_budget_bytes=2000, spill_dir=str(tmp_path))
        for a in range(0, 128, 24):
            cache.append({k: v[a : a + 24] for k, v in cols.items()})
        cache.finish()
        assert any("files" in e for e in cache._log), "budget should force spill"
        got = SGD(stream_window_rows=8, **kwargs).optimize(
            np.zeros(512, np.float32), cache, BinaryLogisticLoss.INSTANCE
        )
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestSparseLinearModels:
    def test_logistic_regression_sparse_end_to_end_wide(self):
        """Criteo-shaped: d = 2^20 would be ~2 GB densified at n=512; the sparse
        path trains and serves it without ever building [n, d]."""
        from flink_ml_tpu.models.classification.logistic_regression import LogisticRegression

        d = 1 << 20
        idx, vals, y = _sparse_data(n=512, d=d, nnz=8, seed=4)
        rows = [SparseVector(d, np.sort(r), v[np.argsort(r)]) for r, v in zip(idx, vals)]
        df = DataFrame.from_dict({"features": rows, "label": y.astype(np.float64)})
        est = (
            LogisticRegression()
            .set_max_iter(60)
            .set_global_batch_size(256)
            .set_learning_rate(1.0)
            .set_tol(0.0)
        )
        model = est.fit(df)
        assert model.coefficient.shape == (d,)
        out = model.transform(df)
        acc = np.mean(out.column("prediction") == y)
        assert acc > 0.8, f"sparse LR failed to learn: acc={acc}"
        raw = out.column("rawPrediction")
        assert raw.shape == (512, 2)

    def test_sparse_dense_transform_parity(self):
        """The same model must produce identical margins for a sparse column and
        its densified twin (LinearSVC + LinearRegression + LR servable)."""
        from flink_ml_tpu.models.classification.linearsvc import LinearSVCModel
        from flink_ml_tpu.models.regression.linear_regression import LinearRegressionModel

        rng = np.random.default_rng(9)
        X = rng.standard_normal((40, 16)).astype(np.float32)
        X[rng.random(X.shape) < 0.5] = 0.0
        coef = rng.standard_normal(16).astype(np.float32)
        df_dense = DataFrame.from_dict({"features": X})
        df_sparse = DataFrame.from_dict({"features": _to_sparse_rows(X)})
        assert df_sparse.is_sparse("features") and not df_dense.is_sparse("features")

        svc = LinearSVCModel()
        svc.coefficient = coef
        np.testing.assert_allclose(
            svc.transform(df_sparse).column("rawPrediction"),
            svc.transform(df_dense).column("rawPrediction"),
            rtol=1e-5,
            atol=1e-6,
        )
        lin = LinearRegressionModel()
        lin.coefficient = coef
        np.testing.assert_allclose(
            lin.transform(df_sparse).column("prediction"),
            lin.transform(df_dense).column("prediction"),
            rtol=1e-5,
            atol=1e-6,
        )

    def test_lr_sparse_fit_matches_dense_fit(self):
        from flink_ml_tpu.models.classification.logistic_regression import LogisticRegression

        rng = np.random.default_rng(11)
        X = rng.standard_normal((64, 10)).astype(np.float32)
        X[rng.random(X.shape) < 0.5] = 0.0
        y = (X @ rng.standard_normal(10) > 0).astype(np.float64)
        est = LogisticRegression().set_max_iter(15).set_global_batch_size(32).set_tol(0.0)
        dense_model = est.fit(DataFrame.from_dict({"features": X, "label": y}))
        sparse_model = est.fit(
            DataFrame.from_dict({"features": _to_sparse_rows(X), "label": y})
        )
        np.testing.assert_allclose(
            sparse_model.coefficient, dense_model.coefficient, rtol=1e-4, atol=1e-6
        )


class TestModelAxisSharding:
    """Tensor-parallel sparse SGD: coefficient sharded over the mesh's model
    axis, per-shard range-masked gather/scatter, margins psum'd over the model
    axis. Must match the replicated-coefficient result on the same data axis."""

    def _data(self, n=96, d=100, nnz=6, seed=13):
        rng = np.random.default_rng(seed)
        idx = np.stack([rng.choice(d, nnz, replace=False) for _ in range(n)]).astype(np.int32)
        vals = rng.standard_normal((n, nnz)).astype(np.float32)
        y = (np.sum(vals * rng.standard_normal(d).astype(np.float32)[idx], axis=1) > 0).astype(
            np.float32
        )
        return idx, vals, y

    @pytest.mark.parametrize("n_model", [2, 4])
    def test_tp_matches_replicated(self, n_model):
        import jax

        from flink_ml_tpu.parallel.mesh import MeshContext, mesh_context

        d = 100  # deliberately NOT divisible by n_model: exercises coef padding
        idx, vals, y = self._data(d=d)
        cols = {"indices": idx, "values": vals, "labels": y}
        kwargs = dict(max_iter=15, global_batch_size=32, tol=0.0, learning_rate=0.4,
                      reg=0.01, elastic_net=0.5)
        n_data = 8 // n_model
        devices = jax.devices()[:8]

        with mesh_context(MeshContext(devices=devices[:n_data], n_data=n_data)) as ctx:
            want = SGD(ctx=ctx, **kwargs).optimize(
                np.zeros(d, np.float32), cols, BinaryLogisticLoss.INSTANCE
            )
        with mesh_context(
            MeshContext(devices=devices, n_data=n_data, n_model=n_model)
        ) as ctx:
            got = SGD(ctx=ctx, **kwargs).optimize(
                np.zeros(d, np.float32), cols, BinaryLogisticLoss.INSTANCE
            )
        assert got.shape == (d,)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_tp_with_tol_early_stop(self):
        import jax

        from flink_ml_tpu.parallel.mesh import MeshContext, mesh_context

        idx, vals, y = self._data(d=64, seed=21)
        cols = {"indices": idx, "values": vals, "labels": y}
        kwargs = dict(max_iter=300, global_batch_size=96, tol=0.45, learning_rate=0.5)
        with mesh_context(MeshContext(devices=jax.devices()[:8], n_data=4, n_model=2)) as ctx:
            sgd = SGD(ctx=ctx, **kwargs)
            coef = sgd.optimize(np.zeros(64, np.float32), cols, BinaryLogisticLoss.INSTANCE)
        assert len(sgd.loss_history) < 300, "tol should stop early on the TP path"
        assert np.all(np.isfinite(coef))

    def test_tp_host_loop_matches_fused(self):
        # Listeners force the host loop; under n_model > 1 it must produce the
        # fused TP path's exact trajectory (same epoch math, same psums) —
        # the reference checkpoints/observes every training path (SGD.java:308).
        import jax

        from flink_ml_tpu.iteration import IterationListener
        from flink_ml_tpu.parallel.mesh import MeshContext, mesh_context

        idx, vals, y = self._data(d=64)
        cols = {"indices": idx, "values": vals, "labels": y}
        kwargs = dict(max_iter=12, global_batch_size=32, tol=0.0, learning_rate=0.4)
        with mesh_context(MeshContext(devices=jax.devices()[:8], n_data=4, n_model=2)) as ctx:
            fused = SGD(ctx=ctx, **kwargs).optimize(
                np.zeros(64, np.float32), cols, BinaryLogisticLoss.INSTANCE
            )
            host = SGD(ctx=ctx, listeners=[IterationListener()], **kwargs).optimize(
                np.zeros(64, np.float32), cols, BinaryLogisticLoss.INSTANCE
            )
        assert host.shape == (64,)
        np.testing.assert_allclose(host, fused, rtol=1e-5, atol=1e-7)

    def test_tp_streamed_matches_dp_streamed(self, tmp_path):
        import jax

        from flink_ml_tpu.iteration import HostDataCache
        from flink_ml_tpu.parallel.mesh import MeshContext, mesh_context

        d = 100  # not divisible by n_model: exercises streamed coef padding
        idx, vals, y = self._data(d=d, seed=31)
        cache = HostDataCache(memory_budget_bytes=2000, spill_dir=str(tmp_path))
        for a in range(0, len(y), 24):
            cache.append(
                {"indices": idx[a : a + 24], "values": vals[a : a + 24], "labels": y[a : a + 24]}
            )
        cache.finish()
        kwargs = dict(max_iter=11, global_batch_size=32, tol=0.0, learning_rate=0.3,
                      stream_window_rows=8)
        devices = jax.devices()[:8]
        with mesh_context(MeshContext(devices=devices[:4], n_data=4)) as ctx:
            want = SGD(ctx=ctx, **kwargs).optimize(
                np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE
            )
        with mesh_context(MeshContext(devices=devices, n_data=4, n_model=2)) as ctx:
            got = SGD(ctx=ctx, **kwargs).optimize(
                np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE
            )
        assert got.shape == (d,)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
