"""Supervised execution tests (flink_ml_tpu/execution/).

Restart-strategy parity with Flink ``RestartStrategies``, the retryable/fatal
error classifier, and ``Supervisor.run``/``run_stream`` semantics driven
through the deterministic fault-injection points. The train-to-identical-result
recovery-equivalence tests live in test_checkpoint.py.
"""
import numpy as np
import pytest

from flink_ml_tpu.checkpoint import (
    CheckpointCorruptError,
    CheckpointManager,
    FingerprintMismatchError,
)
from flink_ml_tpu.execution import (
    ErrorClassifier,
    ExponentialBackoffRestartStrategy,
    FailureKind,
    FailureRateRestartStrategy,
    FixedDelayRestartStrategy,
    NoRestartStrategy,
    RestartStrategies,
    RestartsExhaustedError,
    Supervisor,
)
from flink_ml_tpu.faults import InjectedFault, faults
from flink_ml_tpu.metrics import MLMetrics, metrics


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _fast_supervisor(strategy, **kw):
    """A supervisor with time injected out (no real sleeping in tests)."""
    kw.setdefault("clock", lambda: 0.0)
    kw.setdefault("sleep", lambda s: None)
    return Supervisor(strategy, **kw)


class TestRestartStrategies:
    def test_no_restart(self):
        assert NoRestartStrategy().next_restart(0.0) is None

    def test_fixed_delay_budget(self):
        s = FixedDelayRestartStrategy(2, delay_s=1.5)
        assert s.next_restart(0.0) == 1.5
        assert s.next_restart(1.0) == 1.5
        assert s.next_restart(2.0) is None  # budget spent
        s.reset()
        assert s.next_restart(3.0) == 1.5

    def test_exponential_backoff_sequence_and_cap(self):
        s = ExponentialBackoffRestartStrategy(
            initial_delay_s=1.0, max_delay_s=5.0, backoff_multiplier=2.0
        )
        assert [s.next_restart(float(t)) for t in range(4)] == [1.0, 2.0, 4.0, 5.0]

    def test_exponential_backoff_resets_after_clean_stretch(self):
        s = ExponentialBackoffRestartStrategy(
            initial_delay_s=1.0, max_delay_s=60.0, reset_threshold_s=10.0
        )
        assert s.next_restart(0.0) == 1.0
        assert s.next_restart(1.0) == 2.0
        s.record_success(5.0)  # only 4s clean: no reset
        assert s.next_restart(6.0) == 4.0
        s.record_success(20.0)  # 14s clean since last failure: reset
        assert s.next_restart(21.0) == 1.0

    def test_exponential_backoff_jitter_is_seeded(self):
        def delays(seed):
            s = ExponentialBackoffRestartStrategy(1.0, 64.0, jitter_factor=0.5, seed=seed)
            return [s.next_restart(0.0) for _ in range(5)]

        assert delays(3) == delays(3)
        for d, base in zip(delays(3), [1.0, 2.0, 4.0, 8.0, 16.0]):
            assert base * 0.5 <= d <= base * 1.5

    def test_exponential_backoff_max_restarts(self):
        s = ExponentialBackoffRestartStrategy(0.0, 1.0, max_restarts=1)
        assert s.next_restart(0.0) is not None
        assert s.next_restart(1.0) is None

    def test_failure_rate_window(self):
        s = FailureRateRestartStrategy(2, interval_s=10.0, delay_s=0.5)
        assert s.next_restart(0.0) == 0.5
        assert s.next_restart(1.0) == 0.5
        assert s.next_restart(2.0) is None  # 3 failures within 10s
        s.reset()
        assert s.next_restart(100.0) == 0.5
        # failures spread wider than the window never exhaust the budget
        assert s.next_restart(111.0) == 0.5
        assert s.next_restart(122.0) == 0.5

    def test_factory_parity(self):
        assert isinstance(RestartStrategies.no_restart(), NoRestartStrategy)
        assert isinstance(RestartStrategies.fixed_delay_restart(3, 1.0), FixedDelayRestartStrategy)
        assert isinstance(
            RestartStrategies.exponential_delay_restart(), ExponentialBackoffRestartStrategy
        )
        assert isinstance(
            RestartStrategies.failure_rate_restart(3, 60.0), FailureRateRestartStrategy
        )


class TestErrorClassifier:
    def test_builtin_rules(self, tmp_path):
        c = ErrorClassifier()
        retryable = [
            InjectedFault("iteration.epoch", 1),
            OSError("disk gone"),
            FileNotFoundError("spill file missing"),
            CheckpointCorruptError(3, str(tmp_path), "crc mismatch"),
            RuntimeError("all-reduce rendezvous timed out"),
            RuntimeError("DEADLINE_EXCEEDED: collective permute"),
        ]
        fatal = [
            FingerprintMismatchError("different run"),
            ValueError("shapes (3,) and (4,) not aligned"),
            TypeError("dtype float32 expected"),
            RuntimeError("some deterministic bug"),
        ]
        for e in retryable:
            assert c.classify(e) is FailureKind.RETRYABLE, e
        for e in fatal:
            assert c.classify(e) is FailureKind.FATAL, e

    def test_extra_types_override(self):
        class DeploymentBlip(Exception):
            pass

        c = ErrorClassifier(extra_retryable=[DeploymentBlip], extra_fatal=[OSError])
        assert c.is_retryable(DeploymentBlip())
        assert c.classify(OSError("now fatal")) is FailureKind.FATAL


class TestSupervisorRun:
    def test_flaky_fn_recovers_and_counts(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise InjectedFault("iteration.epoch", len(calls))
            return "done"

        sup = _fast_supervisor(FixedDelayRestartStrategy(5, 0.0), name="t-flaky")
        assert sup.run(flaky) == "done"
        assert sup.attempts == 3 and sup.restarts == 2
        scope = sup.metric_scope
        assert metrics.get(scope, MLMetrics.NUM_ATTEMPTS) == 3
        assert metrics.get(scope, MLMetrics.NUM_RESTARTS) == 2
        assert metrics.get(scope, MLMetrics.RECOVERY_MS) is not None

    def test_fatal_raises_immediately(self):
        calls = []

        def fatal():
            calls.append(1)
            raise ValueError("shape mismatch")

        sup = _fast_supervisor(FixedDelayRestartStrategy(5, 0.0), name="t-fatal")
        with pytest.raises(ValueError, match="shape mismatch"):
            sup.run(fatal)
        assert len(calls) == 1, "fatal failures must not consume restart budget"
        assert metrics.get(sup.metric_scope, MLMetrics.NUM_FATAL) == 1

    def test_budget_exhaustion_chains_restarts_exhausted(self):
        def always_fails():
            raise InjectedFault("iteration.epoch", 1)

        sup = _fast_supervisor(FixedDelayRestartStrategy(2, 0.0), name="t-exhaust")
        with pytest.raises(InjectedFault) as e:
            sup.run(always_fails)
        assert isinstance(e.value.__cause__, RestartsExhaustedError)
        assert len(e.value.__cause__.failures) == 3  # initial + 2 retries
        assert sup.attempts == 3

    def test_sleeps_the_strategy_delay(self):
        slept = []
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) == 1:
                raise InjectedFault("iteration.epoch", 1)
            return 42

        sup = Supervisor(
            FixedDelayRestartStrategy(1, 2.5),
            name="t-sleep",
            clock=lambda: 0.0,
            sleep=slept.append,
        )
        assert sup.run(flaky) == 42
        assert slept == [2.5]

    def test_failure_rate_exhaustion_through_fault_injection(self):
        """A crash-looping job exhausts the FailureRate budget: every epoch
        faults (prob=1.0), failures land back-to-back inside the interval."""
        from flink_ml_tpu.iteration import (
            IterationBodyResult,
            IterationConfig,
            iterate_bounded_until_termination,
        )

        def body(variables, epoch):
            (x,) = variables
            return IterationBodyResult([x + 1.0], outputs=[x])

        def job():
            return iterate_bounded_until_termination(
                [np.asarray(0.0)], body, config=IterationConfig(max_epochs=5)
            )

        faults.arm("iteration.epoch", prob=1.0, seed=0)
        t = iter(np.arange(0.0, 100.0, 0.5))
        sup = Supervisor(
            FailureRateRestartStrategy(3, interval_s=60.0, delay_s=0.0),
            name="t-rate",
            clock=lambda: float(next(t)),
            sleep=lambda s: None,
        )
        with pytest.raises(InjectedFault) as e:
            sup.run(job)
        assert isinstance(e.value.__cause__, RestartsExhaustedError)
        assert sup.attempts == 4  # initial + 3 allowed restarts, then exhausted


class TestSupervisorStream:
    def test_run_stream_resumes_unbounded_iteration(self, tmp_path):
        """iterate_unbounded under the supervisor: an injected epoch fault
        kills the generator; the restarted attempt restores the (epoch,
        variables) snapshot, skips the replayed source, and the caller sees
        every output exactly once (checkpoint_interval=1)."""
        from flink_ml_tpu.iteration import (
            IterationBodyResult,
            IterationConfig,
            iterate_unbounded,
        )

        batches = [np.asarray(float(i)) for i in range(6)]

        def body(variables, batch, epoch):
            (acc,) = variables
            acc = acc + batch
            return IterationBodyResult([acc], outputs=[float(acc)])

        def factory():
            mgr = CheckpointManager(str(tmp_path / "ub"))
            config = IterationConfig(checkpoint_interval=1, checkpoint_manager=mgr)
            return iterate_unbounded([np.asarray(0.0)], iter(batches), body, config=config)

        faults.arm("iteration.epoch", at=4)  # dies before epoch 3's body
        sup = _fast_supervisor(FixedDelayRestartStrategy(2, 0.0), name="t-stream")
        outputs = list(sup.run_stream(factory))
        assert sup.restarts == 1
        assert outputs == [0.0, 1.0, 3.0, 6.0, 10.0, 15.0], "exactly-once outputs"


class TestOnlineInflightReplay:
    """The online.step seam: a fault after the batch left the queue must not
    lose it — the SnapshotDriver redelivers the in-flight mini-batch on the
    supervised retry (the in-flight feedback-record snapshot analogue)."""

    def _est(self, mgr=None):
        from flink_ml_tpu.api.dataframe import DataFrame
        from flink_ml_tpu.models.classification.online_logistic_regression import (
            OnlineLogisticRegression,
        )

        init = DataFrame.from_dict(
            {"coefficient": np.zeros((1, 2)), "modelVersion": np.asarray([0])}
        )
        est = OnlineLogisticRegression().set_initial_model_data(init).set_global_batch_size(8)
        if mgr is not None:
            est.set_checkpoint(mgr, 1)
        return est

    def _batches(self, n=4):
        rng = np.random.default_rng(11)
        out = []
        for _ in range(n):
            X = rng.normal(size=(8, 2))
            out.append({"features": X, "label": (X[:, 0] > 0).astype(np.float64)})
        return out

    def test_online_step_fault_redelivers_inflight_batch(self, tmp_path):
        from flink_ml_tpu.models.online import QueueBatchStream

        batches = self._batches(4)

        def feed():
            s = QueueBatchStream()
            for b in batches:
                s.add(b)
            return s.close()

        clean = self._est().fit(feed())
        clean.advance()
        assert clean.model_version == 4

        mgr = CheckpointManager(str(tmp_path / "olr"))
        model = self._est(mgr).fit(feed())
        faults.arm("online.step", at=3)  # after batch 3 left the queue
        sup = _fast_supervisor(FixedDelayRestartStrategy(2, 0.0), name="t-online")
        applied = sup.run(model.advance)
        assert sup.restarts == 1
        # attempt 1 applied versions 1-2 then died on the in-flight batch 3;
        # the retried advance() redelivered it and applied versions 3-4.
        assert applied == 2
        assert model.model_version == 4, "the in-flight batch was replayed, not lost"
        np.testing.assert_array_equal(model.coefficient, clean.coefficient)
