"""Continuous-learning-loop tests (docs/continuous.md).

The closed train → publish → serve loop exercised as ONE system — the
ROADMAP item 3 scenario: an online FTRL trainer on a feedable stream,
versions published on a cadence, every flip AOT-warmed before activation,
drift scored on labelled tail traffic through the REAL serving path, and
automatic rollback to the newest intact older version on regression —
plus deterministic fault injection at the three loop seams
(``loop.publish``, ``loop.swap``, ``loop.rollback``) and a full kill/resume
(new incarnation, same checkpoint + publish dirs) recovery proof.
"""
import os

import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.checkpoint import CheckpointManager
from flink_ml_tpu.execution import Supervisor
from flink_ml_tpu.faults import InjectedFault, faults
from flink_ml_tpu.linalg.vectors import DenseVector
from flink_ml_tpu.loop import (
    ContinuousLearningLoop,
    ContinuousTrainer,
    DriftMonitor,
    RollbackImpossibleError,
    auc,
    logloss,
)
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.models.classification.online_logistic_regression import (
    OnlineLogisticRegression,
)
from flink_ml_tpu.models.online import QueueBatchStream
from flink_ml_tpu.serving import InferenceServer, ServingConfig
from flink_ml_tpu.serving.registry import quarantine_version

D = 8
_TRUE_W = np.linspace(1.0, -1.0, D)


@pytest.fixture(autouse=True)
def _reset_faults():
    faults.reset()
    yield
    faults.reset()


def _batch(n=64, seed=0, flip=False):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, D))
    y = (X @ _TRUE_W > 0).astype(np.float64)
    if flip:
        y = 1.0 - y
    return {"features": X.astype(np.float64), "label": y}


def _estimator(alpha=1.0, checkpoint_dir=None):
    est = (
        OnlineLogisticRegression()
        .set_initial_model_data(
            DataFrame(["coefficient"], None, [[DenseVector(np.zeros(D))]])
        )
        .set_alpha(alpha)
        .set_global_batch_size(64)
    )
    if checkpoint_dir is not None:
        est.set_checkpoint(CheckpointManager(str(checkpoint_dir)), interval=1)
    return est


def _server(name):
    return InferenceServer(
        name=name,
        serving_config=ServingConfig(max_batch_size=8, max_delay_ms=0.5),
        warmup_template=DataFrame.from_dict(
            {"features": _batch(1, seed=99)["features"]}
        ),
    )


def _eval_source():
    return DataFrame.from_dict(_batch(32, seed=7))


def _make_loop(tmp_path, name, *, publish_every=2, checkpoint_dir=None, stream=None):
    stream = stream if stream is not None else QueueBatchStream()
    scope = f"{MLMetrics.LOOP_GROUP}[{name}]"
    trainer = ContinuousTrainer(
        _estimator(checkpoint_dir=checkpoint_dir),
        stream,
        str(tmp_path / "pub"),
        publish_every_versions=publish_every,
        scope=scope,
    )
    server = _server(name)
    loop = ContinuousLearningLoop(
        trainer,
        server,
        eval_source=_eval_source,
        name=name,
        monitor=DriftMonitor(
            window=2, rel_threshold=0.2, min_scores=1, scope=scope
        ),
    )
    return loop, trainer, server, stream


def _serve_traffic(server, seed=123, requests=4, rows=4):
    """Client traffic through the real request path; returns
    (errors, served versions) — the zero-serving-errors probe."""
    X = _batch(requests * rows, seed=seed)["features"]
    errors, versions = 0, []
    for i in range(requests):
        try:
            resp = server.predict(
                DataFrame.from_dict({"features": X[i * rows : (i + 1) * rows]})
            )
            versions.append(resp.model_version)
        except Exception:
            errors += 1
    return errors, versions


class TestEndToEndScenario:
    def test_stream_to_versions_to_drift_to_rollback(self, tmp_path):
        """The acceptance scenario: stream in → ≥3 versions trained AND
        published AND served → drift injected via label-flipped training →
        automatic rollback to the newest good version — with (a) zero
        fast-path compiles on the serving path (every flip AOT-warmed before
        activation), (b) zero serving errors throughout, and (c) ml.loop.*
        metrics consistent with the injected schedule."""
        name = "t-loop-e2e"
        loop, trainer, server, stream = _make_loop(tmp_path, name)
        scope = loop.scope
        pub = trainer.publish_dir

        # --- phase 1: healthy stream, 3 versions published and served ------
        for i in range(6):
            stream.add(_batch(seed=i))
        reports = loop.run(publish_target=3, max_steps=10)
        assert trainer.published_versions == [2, 4, 6]
        assert server.model_version == 6
        swapped = [r.swapped for r in reports if r.swapped is not None]
        assert swapped == [2, 4, 6]
        errors, versions = _serve_traffic(server, seed=200)
        assert errors == 0 and set(versions) == {6}

        # --- phase 2: drift injected — flipped labels degrade the model ----
        for i in range(4):
            stream.add(_batch(seed=50 + i, flip=True))
        reports2 = loop.run(publish_target=4, max_steps=10)
        rollbacks = [r for r in reports2 if r.rolled_back_to is not None]
        assert len(rollbacks) == 1
        assert rollbacks[-1].rolled_back_to == 6  # reverted to N-1 (last good)
        assert server.model_version == 6
        # the bad version is quarantined on disk, invisible to any scan
        names = sorted(os.listdir(pub))
        assert "v-8.quarantined" in names and "v-8" not in names
        assert {"v-2", "v-4", "v-6"} <= set(names)

        # (a) every flip was AOT-warmed: zero serving-path compiles, and the
        # fast path genuinely served (fused batches happened)
        assert not metrics.get(server.scope, MLMetrics.SERVING_FASTPATH_COMPILES)
        assert metrics.get(server.scope, MLMetrics.SERVING_FUSED_BATCHES, 0) > 0

        # (b) zero serving errors during swaps and rollback — the eval
        # traffic above already rode every swap; a final probe serves from
        # the restored version
        errors, versions = _serve_traffic(server, seed=300)
        assert errors == 0 and set(versions) == {6}

        # (c) ml.loop.* metrics consistent with the injected schedule
        scraped = metrics.scope(scope)
        assert scraped[MLMetrics.LOOP_PUBLISHED] == 4  # v2, v4, v6, v8
        assert scraped[MLMetrics.LOOP_SWAPPED] == 4
        assert scraped[MLMetrics.LOOP_ROLLBACKS] == 1
        assert scraped[MLMetrics.LOOP_QUARANTINED] == 1
        assert scraped[MLMetrics.LOOP_DRIFT_REGRESSIONS] == 1
        hist = scraped[MLMetrics.LOOP_PUBLISH_TO_SERVE_MS]
        assert hist.count == 4  # one publish→serve latency per flip
        assert all(v >= 0.0 for v in hist.values())
        assert scraped[MLMetrics.LOOP_WARM_MS] > 0.0
        goodput = scraped[MLMetrics.LOOP_GOODPUT_FRACTION]
        assert 0.0 < goodput <= 1.0
        assert scraped[MLMetrics.LOOP_STEPS] == len(reports) + len(reports2)
        assert scraped[MLMetrics.LOOP_DRIFT_SCORE] > scraped[
            MLMetrics.LOOP_DRIFT_BASELINE
        ]  # the regression verdict's own evidence
        server.close()


class TestLoopFaultPoints:
    def test_loop_publish_fault_recovers_without_version_gap(self, tmp_path):
        """loop.publish killed mid-step: the supervised retry republishes the
        lagging version — no version reuse, no gap, publish counter exact."""
        name = "t-loop-fp-publish"
        loop, trainer, server, stream = _make_loop(
            tmp_path, name, publish_every=1
        )
        for i in range(3):
            stream.add(_batch(seed=i))
        faults.arm("loop.publish", at=1)
        sup = Supervisor(name=name)
        loop.run(publish_target=3, max_steps=10, supervisor=sup)
        assert sup.restarts == 1
        assert faults.fires("loop.publish") == 1
        assert trainer.published_versions == [1, 2, 3]
        assert sorted(os.listdir(trainer.publish_dir)) == ["v-1", "v-2", "v-3"]
        assert metrics.get(loop.scope, MLMetrics.LOOP_PUBLISHED) == 3
        errors, versions = _serve_traffic(server)
        assert errors == 0 and set(versions) == {3}
        server.close()

    def test_loop_swap_fault_keeps_serving_and_retry_flips(self, tmp_path):
        """loop.swap killed between publish and flip: the in-service version
        keeps serving through the fault; the retried step completes a flip to
        the newest published version with zero serving errors."""
        name = "t-loop-fp-swap"
        loop, trainer, server, stream = _make_loop(
            tmp_path, name, publish_every=1
        )
        stream.add(_batch(seed=0))
        loop.run(publish_target=1, max_steps=5)
        assert server.model_version == 1
        # arm the swap seam, feed more data, run supervised
        faults.arm("loop.swap", at=1)
        for i in range(1, 3):
            stream.add(_batch(seed=i))
        sup = Supervisor(name=name)
        loop.run(publish_target=3, max_steps=10, supervisor=sup)
        assert sup.restarts == 1
        assert faults.fires("loop.swap") == 1
        assert server.model_version == 3  # newest published won the retry flip
        errors, versions = _serve_traffic(server)
        assert errors == 0 and set(versions) == {3}
        server.close()

    def test_loop_rollback_fault_retry_completes_revert(self, tmp_path):
        """loop.rollback killed after the regression verdict: serving stays on
        the (bad but functional) version — zero errors — and the supervised
        retry finishes quarantine + revert to the last good version."""
        name = "t-loop-fp-rollback"
        loop, trainer, server, stream = _make_loop(tmp_path, name)
        for i in range(6):
            stream.add(_batch(seed=i))
        loop.run(publish_target=3, max_steps=10)
        assert server.model_version == 6
        faults.arm("loop.rollback", at=1)
        for i in range(6):
            stream.add(_batch(seed=50 + i, flip=True))
        sup = Supervisor(name=name)
        loop.run(publish_target=5, max_steps=12, supervisor=sup)
        assert sup.restarts >= 1
        assert faults.fires("loop.rollback") == 1
        # the revert landed: serving is on a good (pre-drift) version and at
        # least one bad version is quarantined on disk
        assert server.model_version <= 6
        assert any(
            n.endswith(".quarantined") for n in os.listdir(trainer.publish_dir)
        )
        assert metrics.get(loop.scope, MLMetrics.LOOP_ROLLBACKS, 0) >= 1
        errors, _ = _serve_traffic(server)
        assert errors == 0
        server.close()


class TestKillResume:
    def test_kill_resume_restores_checkpoint_and_last_good_version(self, tmp_path):
        """Hard kill mid-loop (no supervisor — the process-death analogue):
        a NEW incarnation pointed at the same checkpoint + publish dirs
        resumes training from the checkpointed version (no reuse, no gap) and
        serving from the last good published version, with zero serving
        errors across the whole recovery window."""
        ckpt = tmp_path / "ckpt"
        name1 = "t-loop-kill-1"
        loop1, trainer1, server1, stream1 = _make_loop(
            tmp_path, name1, publish_every=1, checkpoint_dir=ckpt
        )
        for i in range(4):
            stream1.add(_batch(seed=i))
        loop1.run(publish_target=2, max_steps=5)
        assert trainer1.published_versions == [1, 2]
        assert server1.model_version == 2
        # the kill: online.step fault with NO supervisor — propagates like a
        # process death between version 2 and version 3
        faults.arm("online.step", at=1)
        with pytest.raises(InjectedFault):
            loop1.step()
        faults.reset()
        # the serving half survives a trainer crash: still on v2, no errors
        errors, versions = _serve_traffic(server1)
        assert errors == 0 and set(versions) == {2}
        server1.close()

        # --- incarnation 2: same dirs, replayed stream ---------------------
        name2 = "t-loop-kill-2"
        stream2 = QueueBatchStream()
        for i in range(4):  # the replay-from-the-beginning contract
            stream2.add(_batch(seed=i))
        for i in range(4, 6):  # new traffic beyond the crash point
            stream2.add(_batch(seed=i))
        loop2, trainer2, server2, _ = _make_loop(
            tmp_path, name2, publish_every=1, checkpoint_dir=ckpt, stream=stream2
        )
        # recovery turn: serving comes back FIRST, from the last good
        # published version, before any new training happens
        report = loop2.step(train_versions=0)
        assert report.trained == 0
        assert server2.model_version == 2
        errors, versions = _serve_traffic(server2)
        assert errors == 0 and set(versions) == {2}
        # training resumes from the checkpoint: next version is 3 — the
        # replayed prefix is skipped, nothing reused, nothing lost
        loop2.run(publish_target=2, max_steps=8)
        assert trainer2.published_versions == [3, 4]
        assert trainer2.model.model_version == 4
        assert server2.model_version == 4
        assert sorted(os.listdir(trainer2.publish_dir)) == [
            "v-1", "v-2", "v-3", "v-4",
        ]
        errors, versions = _serve_traffic(server2)
        assert errors == 0 and set(versions) == {4}
        server2.close()


class TestDriftMonitor:
    def test_rolling_window_bounds_and_means(self):
        monitor = DriftMonitor(window=3, scope="ml.loop[t-dm]")
        for s in (1.0, 2.0, 3.0, 4.0):
            monitor.observe(1, s)
        assert monitor.count(1) == 3  # oldest dropped
        assert monitor.mean(1) == pytest.approx(3.0)
        assert monitor.mean(2) is None

    def test_loss_regression_thresholds(self):
        monitor = DriftMonitor(
            window=4, rel_threshold=0.5, min_scores=1, scope="ml.loop[t-dm2]"
        )
        monitor.observe(1, 0.2)
        monitor.observe(2, 0.25)  # within 1.5x baseline: fine
        assert not monitor.regressed(2, 1)
        monitor.observe(3, 0.5)  # 2.5x baseline: regressed
        assert monitor.regressed(3, 1)
        assert (
            metrics.get("ml.loop[t-dm2]", MLMetrics.LOOP_DRIFT_REGRESSIONS) == 1
        )

    def test_higher_is_better_direction(self):
        monitor = DriftMonitor(
            window=4,
            rel_threshold=0.1,
            min_scores=1,
            higher_is_better=True,
            scope="ml.loop[t-dm3]",
        )
        monitor.observe(1, 0.9)  # AUC-style baseline
        monitor.observe(2, 0.88)
        assert not monitor.regressed(2, 1)
        monitor.observe(3, 0.6)
        assert monitor.regressed(3, 1)

    def test_min_scores_guard_and_missing_baseline(self):
        monitor = DriftMonitor(
            window=4, rel_threshold=0.0, min_scores=2, scope="ml.loop[t-dm4]"
        )
        monitor.observe(1, 0.1)
        monitor.observe(2, 10.0)  # hugely worse, but only one observation
        assert not monitor.regressed(2, 1)
        assert not monitor.regressed(2, None)  # no baseline: never regress
        monitor.observe(2, 10.0)
        assert monitor.regressed(2, 1)

    def test_logloss_and_auc_helpers(self):
        y = np.array([0.0, 0.0, 1.0, 1.0])
        good = np.array([0.1, 0.2, 0.8, 0.9])
        bad = 1.0 - good
        assert logloss(y, good) < logloss(y, bad)
        assert auc(y, good) == 1.0
        assert auc(y, bad) == 0.0
        assert auc(y, np.full(4, 0.5)) == 0.5
        assert auc(np.zeros(4), good) == 0.5  # degenerate single-class window


class TestTrainerCadence:
    def test_publish_every_n_versions(self, tmp_path):
        stream = QueueBatchStream()
        for i in range(5):
            stream.add(_batch(seed=i))
        trainer = ContinuousTrainer(
            _estimator(),
            stream,
            str(tmp_path / "pub"),
            publish_every_versions=2,
            scope="ml.loop[t-cadence]",
        )
        trainer.start()
        trained, published = trainer.process()
        assert trained == 5
        assert published == [2, 4]
        assert sorted(os.listdir(trainer.publish_dir)) == ["v-2", "v-4"]

    def test_time_based_publish_trigger(self, tmp_path):
        stream = QueueBatchStream()
        for i in range(3):
            stream.add(_batch(seed=i))
        trainer = ContinuousTrainer(
            _estimator(),
            stream,
            str(tmp_path / "pub"),
            publish_every_versions=100,  # cadence never fires
            publish_every_s=10.0,
            scope="ml.loop[t-time]",
        )
        now = [1000.0]
        trainer.clock = lambda: now[0]
        trainer.start()
        trained, published = trainer.process(1)
        assert published == [1]  # nothing published yet: time trigger fires
        now[0] += 5.0
        trained, published = trainer.process(1)
        assert published == []  # inside the budget window
        now[0] += 6.0
        trained, published = trainer.process(0)
        # budget exceeded: the lag repair publishes the newest TRAINED
        # version (v2) even before any new training happens
        assert published == [2]
        assert trained == 0
        now[0] += 11.0
        trained, published = trainer.process(1)
        assert published == [3]  # v3 trains and the lapsed budget publishes it

    def test_quarantine_version_is_idempotent(self, tmp_path):
        d = str(tmp_path)
        os.makedirs(os.path.join(d, "v-3"))
        assert quarantine_version(d, 3).endswith("v-3.quarantined")
        assert quarantine_version(d, 3) is None  # already gone
        assert os.path.isdir(os.path.join(d, "v-3.quarantined"))

    def test_quarantine_version_concurrent_rollbacks_one_winner(self, tmp_path):
        """Two rollback controllers racing on the same bad version (a
        fleet-wide quarantine) must produce exactly ONE ``.quarantined`` dir —
        the rename is the arbiter; losers see None, never an error and never
        a double-rename of the winner's dir."""
        import threading

        d = str(tmp_path)
        os.makedirs(os.path.join(d, "v-7"))
        results, barrier = [], threading.Barrier(8)

        def race():
            barrier.wait()
            results.append(quarantine_version(d, 7))

        threads = [threading.Thread(target=race) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        winners = [r for r in results if r is not None]
        assert len(winners) == 1
        assert winners[0].endswith("v-7.quarantined")
        assert sorted(os.listdir(d)) == ["v-7.quarantined"]  # exactly one dir

    def test_rollback_impossible_without_older_version(self, tmp_path):
        name = "t-loop-noroll"
        loop, trainer, server, stream = _make_loop(
            tmp_path, name, publish_every=1
        )
        stream.add(_batch(seed=0))
        loop.run(publish_target=1, max_steps=3)
        with pytest.raises(RollbackImpossibleError):
            loop.controller.rollback(server.model_version)
        server.close()


class TestGoodputConsistency:
    def test_span_report_reproduces_ledger_fraction(self, tmp_path):
        """graftscope consistency: with tracing on, the ``GoodputReport``
        recomputed from the loop-scope spans reproduces the ledger-driven
        ``ml.loop.goodput.fraction`` gauge (two independent measurements of
        the same clock-bounded turns — equal up to the loop's span/metric
        bookkeeping, microseconds against millisecond-scale turns)."""
        from flink_ml_tpu import trace
        from flink_ml_tpu.trace import CAT_PRODUCTIVE, GoodputReport

        name = "t-loop-goodput"
        loop, trainer, server, stream = _make_loop(tmp_path, name)
        for i in range(4):
            stream.add(_batch(seed=i))
        with trace.capture() as recorder:
            loop.run(publish_target=2, max_steps=8)
        gauge = metrics.get(loop.scope, MLMetrics.LOOP_GOODPUT_FRACTION)
        assert 0.0 < gauge <= 1.0
        spans = recorder.snapshot()
        step_spans = [s for s in spans if s.name == "loop.step"]
        assert step_spans  # every turn traced
        assert {"loop.train", "loop.swap", "loop.evaluate", "loop.publish"} <= {
            s.name for s in spans if s.scope == loop.scope
        }
        report = GoodputReport.from_spans(spans)
        fraction = report.fraction(loop.scope)
        assert fraction is not None
        assert fraction == pytest.approx(gauge, abs=0.1)
        # the ledger-backed report published the per-category gauges too
        assert metrics.get(loop.scope, MLMetrics.goodput_ms(CAT_PRODUCTIVE)) > 0.0
        assert metrics.get(loop.scope, MLMetrics.GOODPUT_FRACTION) == pytest.approx(gauge)
        server.close()
