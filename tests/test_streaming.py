"""Larger-than-HBM streamed training (iteration/streaming.py).

The ListStateWithCache.java:43 role: training data cached on the host
(RAM + spill files) streams through HBM-sized windows. The contract under
test: a memory budget small enough to force disk spill must produce the
same trained model as the fully HBM-resident DeviceDataCache path.
"""
import numpy as np
import pytest

from flink_ml_tpu.iteration import DeviceDataCache, HostDataCache
from flink_ml_tpu.iteration.streaming import WindowSchedule
from flink_ml_tpu.ops import SGD, BinaryLogisticLoss
from flink_ml_tpu.parallel.mesh import get_mesh_context


def _make_data(n, d, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d) > 0).astype(np.float32)
    return X, y


def _fill_cache(cache, X, y, chunk=17, weights=None):
    for a in range(0, len(X), chunk):
        c = {"features": X[a : a + chunk], "labels": y[a : a + chunk]}
        if weights is not None:
            c["weights"] = weights[a : a + chunk]
        cache.append(c)
    cache.finish()
    return cache


def test_rows_random_access_across_spill(tmp_path):
    X, y = _make_data(100, 3)
    cache = _fill_cache(
        HostDataCache(memory_budget_bytes=600, spill_dir=str(tmp_path)), X, y, chunk=13
    )
    assert any("files" in e for e in cache._log), "budget should force spill"
    for start, stop in [(0, 100), (0, 0), (5, 5), (12, 14), (0, 13), (13, 26), (37, 91)]:
        got = cache.rows(start, stop)
        np.testing.assert_array_equal(got["features"], X[start:stop])
        np.testing.assert_array_equal(got["labels"], y[start:stop])
    with pytest.raises(IndexError):
        cache.rows(90, 101)


def test_misnamed_required_column_raises(tmp_path):
    # A cache built with 'label' (singular) must fail loudly, not silently
    # train against all-ones targets.
    X, y = _make_data(32, 3)
    cache = HostDataCache()
    cache.append({"features": X, "label": y})
    cache.finish()
    with pytest.raises(KeyError, match="labels"):
        SGD(stream_window_rows=8, max_iter=2, tol=0.0).optimize(
            np.zeros(3, np.float32), cache, BinaryLogisticLoss.INSTANCE
        )


def test_chunk_len_capped_by_max_iter():
    # A short training over a huge window must not pad its dispatch to a
    # mostly-inactive full-width scan.
    sched = WindowSchedule(local_rows=65_536, local_batch=64, window_rows=65_536, max_iter=5)
    assert sched.chunk_len == 5
    assert [len(s) for _, s in sched.runs] == [5]


def test_window_schedule_covers_all_epochs():
    sched = WindowSchedule(local_rows=10, local_batch=2, window_rows=4, max_iter=13)
    assert sched.window == 4 and sched.chunk_len == 2
    total = sum(len(s) for _, s in sched.runs)
    assert total == 13
    # offsets cycle 0,2,4,6,8 -> windows 0,0,1,1,2 each pass
    assert [j for j, _ in sched.runs][:5] == [0, 1, 2, 0, 1]
    for j, starts in sched.runs:
        assert len(starts) <= sched.chunk_len
        assert all(0 <= s <= sched.window - 2 for s in starts)


def _resident_coef(X, y, sgd_kwargs, weights=None):
    cols = {"features": X, "labels": y}
    cols["weights"] = weights if weights is not None else np.ones(len(X), np.float32)
    cache = DeviceDataCache(cols, ctx=get_mesh_context())
    return SGD(**sgd_kwargs).optimize(
        np.zeros(X.shape[1], np.float32), cache, BinaryLogisticLoss.INSTANCE
    )


def test_streamed_sgd_matches_resident_aligned(tmp_path):
    # 64 rows / 8 devices -> m=8 per shard; local batch 2 divides m evenly, so
    # the streamed path consumes exactly the resident rows/weights per epoch
    # (equality up to XLA fusion-order ULPs; exact at these shapes).
    X, y = _make_data(64, 5, seed=1)
    kwargs = dict(max_iter=11, global_batch_size=16, tol=0.0, learning_rate=0.3)
    want = _resident_coef(X, y, kwargs)
    cache = _fill_cache(
        HostDataCache(memory_budget_bytes=400, spill_dir=str(tmp_path)), X, y
    )
    assert any("files" in e for e in cache._log), "budget should force spill"
    got = SGD(stream_window_rows=4, **kwargs).optimize(
        np.zeros(5, np.float32), cache, BinaryLogisticLoss.INSTANCE
    )
    np.testing.assert_array_equal(got, want)


def test_streamed_sgd_matches_resident_ragged(tmp_path):
    # 52 rows -> m=7 with padding; batch does not divide the shard, so the
    # tail epoch goes through the mask path: same contributing rows/weights,
    # different zero-padding positions -> allclose, not bitwise.
    X, y = _make_data(52, 4, seed=2)
    w = np.random.default_rng(3).uniform(0.5, 2.0, 52).astype(np.float32)
    kwargs = dict(max_iter=9, global_batch_size=24, tol=0.0, learning_rate=0.2)
    want = _resident_coef(X, y, kwargs, weights=w)
    cache = _fill_cache(
        HostDataCache(memory_budget_bytes=300, spill_dir=str(tmp_path)), X, y, weights=w
    )
    got = SGD(stream_window_rows=3, **kwargs).optimize(
        np.zeros(4, np.float32), cache, BinaryLogisticLoss.INSTANCE
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_streamed_sgd_tol_early_stop(tmp_path):
    X, y = _make_data(64, 5, seed=4)
    kwargs = dict(max_iter=500, global_batch_size=64, tol=0.4, learning_rate=0.5)
    resident = SGD(**kwargs)
    want = resident.optimize(
        np.zeros(5, np.float32),
        {"features": X, "labels": y},
        BinaryLogisticLoss.INSTANCE,
    )
    cache = _fill_cache(HostDataCache(memory_budget_bytes=1 << 20), X, y)
    streamed = SGD(stream_window_rows=8, **kwargs)
    got = streamed.optimize(np.zeros(5, np.float32), cache, BinaryLogisticLoss.INSTANCE)
    assert len(streamed.loss_history) < 500, "tol should stop early"
    assert len(streamed.loss_history) == len(resident.loss_history)
    np.testing.assert_allclose(
        streamed.loss_history, resident.loss_history, rtol=1e-5
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_streamed_sgd_native_cache(tmp_path):
    from flink_ml_tpu.native import native_available

    if not native_available():
        pytest.skip("native toolchain unavailable")
    from flink_ml_tpu.native.cache import NativeDataCache

    X, y = _make_data(64, 5, seed=1)
    kwargs = dict(max_iter=11, global_batch_size=16, tol=0.0, learning_rate=0.3)
    want = _resident_coef(X, y, kwargs)
    cache = _fill_cache(
        NativeDataCache(memory_budget_bytes=400, spill_dir=str(tmp_path)), X, y
    )
    assert cache.spilled_chunks > 0, "budget should force spill into the C++ store"
    got = SGD(stream_window_rows=4, **kwargs).optimize(
        np.zeros(5, np.float32), cache, BinaryLogisticLoss.INSTANCE
    )
    np.testing.assert_array_equal(got, want)


def test_streamed_sgd_checkpoint_resume(tmp_path):
    from flink_ml_tpu.checkpoint import CheckpointManager

    X, y = _make_data(64, 5, seed=6)
    kwargs = dict(max_iter=12, global_batch_size=16, tol=0.0, learning_rate=0.3)
    cache = _fill_cache(HostDataCache(memory_budget_bytes=1 << 20), X, y)
    want = SGD(stream_window_rows=4, **kwargs).optimize(
        np.zeros(5, np.float32), cache, BinaryLogisticLoss.INSTANCE
    )

    ckdir = str(tmp_path / "ck")
    # First run checkpoints every 2 epochs; resume from its snapshots must land
    # on the identical coefficient (BoundedAllRoundCheckpointITCase parity).
    full = SGD(
        stream_window_rows=4,
        checkpoint_manager=CheckpointManager(ckdir),
        checkpoint_interval=2,
        **kwargs,
    )
    got = full.optimize(np.zeros(5, np.float32), cache, BinaryLogisticLoss.INSTANCE)
    np.testing.assert_array_equal(got, want)

    mgr = CheckpointManager(ckdir)
    steps = mgr.all_steps()
    assert len(steps) >= 2, "expected multiple checkpoints"
    # Simulate a crash after the second-to-last snapshot: resuming mid-run must
    # retrain the remaining epochs and land on the identical coefficient.
    import shutil

    shutil.rmtree(f"{ckdir}/ckpt-{steps[-1]}")
    resumed = SGD(
        stream_window_rows=4,
        checkpoint_manager=CheckpointManager(ckdir),
        checkpoint_interval=2,
        **kwargs,
    ).optimize(np.zeros(5, np.float32), cache, BinaryLogisticLoss.INSTANCE)
    np.testing.assert_array_equal(resumed, want)

    # Listeners need the host loop: loud error instead of silently dropping.
    class L:
        pass

    with pytest.raises(ValueError, match="listener"):
        SGD(stream_window_rows=4, listeners=[L()], **kwargs).optimize(
            np.zeros(5, np.float32), cache, BinaryLogisticLoss.INSTANCE
        )


def test_mlp_fit_stream_rejects_unknown_labels(tmp_path):
    from flink_ml_tpu.models.classification.mlp_classifier import MLPClassifier

    X, _ = _make_data(32, 4, seed=8)
    y = np.asarray([0.0, 1.0, 2.0, 1.0] * 8, np.float32)
    cache = _fill_cache(HostDataCache(), X, y)
    est = MLPClassifier().set_max_iter(2).set_global_batch_size(16).set_tol(0.0)
    with pytest.raises(ValueError, match="not in classes"):
        est.fit_stream(cache, classes=[0.0, 1.0], window_rows=4)


def test_mlp_fit_stream_matches_fit(tmp_path):
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.models.classification.mlp_classifier import MLPClassifier

    rng = np.random.default_rng(7)
    n, d = 64, 6
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = rng.integers(0, 3, n).astype(np.float64)

    est = (
        MLPClassifier()
        .set_hidden_layers(8)
        .set_max_iter(10)
        .set_global_batch_size(16)
        .set_tol(0.0)
        .set_seed(11)
    )
    df = DataFrame.from_dict({"features": X, "label": y})
    want = est.fit(df)

    cache = HostDataCache(memory_budget_bytes=500, spill_dir=str(tmp_path))
    _fill_cache(cache, X, y.astype(np.float32))
    assert any("files" in e for e in cache._log), "budget should force spill"
    got = est.fit_stream(cache, window_rows=4)

    np.testing.assert_array_equal(got.labels, want.labels)
    for (W1, b1), (W2, b2) in zip(got.params, want.params):
        np.testing.assert_allclose(W1, W2, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(b1, b2, rtol=1e-5, atol=1e-6)


def test_tp_streamed_checkpoint_resume_keeps_logical_dim(tmp_path):
    """A streamed TP checkpoint must store the unpadded coefficient, carry the
    mesh shape in its fingerprint, and resume to the right length."""
    import jax

    from flink_ml_tpu.checkpoint import CheckpointManager
    from flink_ml_tpu.parallel.mesh import MeshContext, mesh_context

    rng = np.random.default_rng(3)
    n, d, nnz = 96, 102, 6  # d not divisible by n_model
    idx = np.stack([rng.choice(d, nnz, replace=False) for _ in range(n)]).astype(np.int32)
    vals = rng.standard_normal((n, nnz)).astype(np.float32)
    y = (rng.random(n) > 0.5).astype(np.float32)
    cache = HostDataCache()
    cache.append({"indices": idx, "values": vals, "labels": y})
    cache.finish()
    kwargs = dict(max_iter=8, global_batch_size=32, tol=0.0, learning_rate=0.3,
                  stream_window_rows=8)
    ckdir = str(tmp_path / "tp-ck")
    devices = jax.devices()[:8]
    with mesh_context(MeshContext(devices=devices, n_data=4, n_model=2)) as ctx:
        got = SGD(
            ctx=ctx,
            checkpoint_manager=CheckpointManager(ckdir),
            checkpoint_interval=2,
            **kwargs,
        ).optimize(np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE)
    assert got.shape == (d,)

    mgr = CheckpointManager(ckdir)
    steps = mgr.all_steps()
    st = mgr.restore(steps[-1])
    assert np.asarray(st["coef"]).shape == (d,), "checkpoint must be unpadded"

    # A different mesh shape is a different job: the fingerprint must refuse.
    with mesh_context(MeshContext(devices=devices[:4], n_data=4)) as ctx:
        with pytest.raises(Exception, match="fingerprint|different"):
            SGD(
                ctx=ctx,
                checkpoint_manager=CheckpointManager(ckdir),
                checkpoint_interval=2,
                **kwargs,
            ).optimize(np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE)
