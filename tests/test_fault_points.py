"""Tier-1 shim for ``tools/check_fault_points.py``.

Every fault point registered in ``flink_ml_tpu.faults.FAULT_POINTS`` must
have a runtime ``faults.trip`` call site AND a test exercising it — this test
makes the tier-1 suite enforce that, so injection seams can't silently rot.
"""
import importlib.util
import os

_TOOL = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
    "check_fault_points.py",
)


def _load_tool():
    spec = importlib.util.spec_from_file_location("check_fault_points", _TOOL)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_every_fault_point_is_tripped_and_tested():
    problems, trip_sites = _load_tool().check()
    assert not problems, "\n".join(problems)
    assert trip_sites, "no fault points found at all — the registry is empty?"
