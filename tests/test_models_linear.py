"""Tests for LogisticRegression / LinearSVC / LinearRegression.

Mirrors the reference's per-algorithm test shape (SURVEY.md §4: param defaults,
fit+transform correctness, save/load round-trip, getModelData contents) from
``LogisticRegressionTest`` / ``LinearSVCTest`` / ``LinearRegressionTest``.
"""
import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.classification.linearsvc import LinearSVC, LinearSVCModel
from flink_ml_tpu.models.classification.logistic_regression import (
    LogisticRegression,
    LogisticRegressionModel,
)
from flink_ml_tpu.models.regression.linear_regression import (
    LinearRegression,
    LinearRegressionModel,
)
from flink_ml_tpu.utils import read_write as rw

RNG = np.random.default_rng(11)


def _binary_df(n=256, d=4):
    X = RNG.normal(size=(n, d))
    w_true = np.linspace(1.0, -1.0, d)
    y = (X @ w_true > 0).astype(np.float64)
    return DataFrame.from_dict({"features": X, "label": y}), y


def test_logistic_regression_param_defaults():
    lr = LogisticRegression()
    assert lr.get_features_col() == "features"
    assert lr.get_label_col() == "label"
    assert lr.get_prediction_col() == "prediction"
    assert lr.get_raw_prediction_col() == "rawPrediction"
    assert lr.get_max_iter() == 20
    assert lr.get_learning_rate() == 0.1
    assert lr.get_global_batch_size() == 32
    assert lr.get_tol() == 1e-6
    assert lr.get_reg() == 0.0
    assert lr.get_elastic_net() == 0.0


def test_logistic_regression_fit_transform():
    df, y = _binary_df()
    model = (
        LogisticRegression()
        .set_max_iter(60)
        .set_global_batch_size(256)
        .set_learning_rate(0.5)
        .fit(df)
    )
    out = model.transform(df)
    acc = (out["prediction"] == y).mean()
    assert acc > 0.95
    raw = out["rawPrediction"]
    np.testing.assert_allclose(raw.sum(axis=1), 1.0, atol=1e-5)  # [1-p, p]
    # prediction consistent with probability threshold
    np.testing.assert_array_equal(out["prediction"], (raw[:, 1] >= 0.5).astype(np.float64))


def test_logistic_regression_rejects_nonbinary_labels():
    df = DataFrame.from_dict(
        {"features": RNG.normal(size=(10, 2)), "label": np.arange(10.0)}
    )
    with pytest.raises(ValueError, match="binary labels"):
        LogisticRegression().fit(df)


def test_logistic_regression_save_load_round_trip(tmp_path):
    df, y = _binary_df(64)
    model = LogisticRegression().set_max_iter(10).fit(df)
    path = str(tmp_path / "lr_model")
    model.save(path)
    loaded = LogisticRegressionModel.load(path)
    np.testing.assert_allclose(loaded.coefficient, model.coefficient)
    out0, out1 = model.transform(df), loaded.transform(df)
    np.testing.assert_array_equal(out0["prediction"], out1["prediction"])
    # generic registry dispatch (ReadWriteUtils.loadStage:268 analogue)
    loaded2 = rw.load_stage(path)
    assert isinstance(loaded2, LogisticRegressionModel)


def test_logistic_regression_get_set_model_data():
    df, _ = _binary_df(64)
    model = LogisticRegression().set_max_iter(5).fit(df)
    (md,) = model.get_model_data()
    assert md.get_column_names() == ["coefficient"]
    fresh = LogisticRegressionModel().set_features_col("features")
    fresh.set_model_data(md)
    np.testing.assert_allclose(fresh.coefficient, model.coefficient)


def test_estimator_save_load(tmp_path):
    est = LogisticRegression().set_max_iter(7).set_reg(0.1)
    path = str(tmp_path / "lr_est")
    est.save(path)
    loaded = LogisticRegression.load(path)
    assert loaded.get_max_iter() == 7
    assert loaded.get_reg() == 0.1


def test_linearsvc_fit_transform_and_threshold():
    df, y = _binary_df()
    svc = LinearSVC().set_max_iter(60).set_global_batch_size(256).set_learning_rate(0.2)
    model = svc.fit(df)
    out = model.transform(df)
    assert (out["prediction"] == y).mean() > 0.95
    raw = out["rawPrediction"]
    np.testing.assert_allclose(raw[:, 0], -raw[:, 1], atol=1e-6)  # [dot, -dot]
    # threshold moves predictions (LinearSVCModel.predictOneDataPoint:177-180)
    model.set_threshold(1e9)
    out_hi = model.transform(df)
    assert (out_hi["prediction"] == 0.0).all()


def test_linearsvc_defaults():
    svc = LinearSVC()
    assert svc.get_threshold() == 0.0
    assert svc.get_max_iter() == 20


def test_linear_regression_fit_transform():
    X = RNG.normal(size=(256, 3))
    w_true = np.asarray([2.0, -1.0, 0.5])
    y = X @ w_true
    df = DataFrame.from_dict({"features": X, "label": y})
    model = (
        LinearRegression()
        .set_max_iter(200)
        .set_global_batch_size(256)
        .set_learning_rate(0.1)
        .set_tol(0.0)
        .fit(df)
    )
    np.testing.assert_allclose(model.coefficient, w_true, atol=5e-2)
    out = model.transform(df)
    np.testing.assert_allclose(out["prediction"], y, atol=0.2)


def test_linear_regression_save_load(tmp_path):
    X = RNG.normal(size=(32, 2))
    df = DataFrame.from_dict({"features": X, "label": X @ np.ones(2)})
    model = LinearRegression().set_max_iter(5).fit(df)
    path = str(tmp_path / "linreg")
    model.save(path)
    loaded = LinearRegressionModel.load(path)
    np.testing.assert_allclose(loaded.coefficient, model.coefficient)


def test_weight_col_used():
    """Weighted fit differs from unweighted when weights are informative."""
    X = np.vstack([np.eye(2), np.eye(2)])
    y = np.asarray([1.0, 0.0, 0.0, 1.0])
    w = np.asarray([10.0, 10.0, 0.1, 0.1])
    df = DataFrame.from_dict({"features": X, "label": y, "w": w})
    m_w = LogisticRegression().set_weight_col("w").set_max_iter(30).fit(df)
    m_u = LogisticRegression().set_max_iter(30).fit(df)
    assert not np.allclose(m_w.coefficient, m_u.coefficient)
