"""The graftcheck static-analysis suite — and tier-1's enforcement of it.

Three layers of coverage:

1. **The shipped tree is clean** — running every rule over ``flink_ml_tpu``
   in-process makes each invariant (layer map, jit purity, lock order, fault
   points, error hygiene) a tier-1 gate, replacing the two ad-hoc scripts
   this framework absorbed.
2. **The analyzer works** — per-rule fixture trees (clean + seeded
   violations) prove each rule actually fires; the lock-order fixture plants
   a synthetic A→B / B→A cycle and a self-deadlock and asserts detection.
3. **The framework works** — suppression comments, JSON schema, severity
   overrides, CLI exit codes.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.graftcheck import REGISTRY, Project, run_rules  # noqa: E402
from tools.graftcheck.engine import JSON_SCHEMA_VERSION, parse_suppressions  # noqa: E402
from tools.graftcheck.rules import layer_deps, lock_order  # noqa: E402

ALL_RULES = (
    "blocking-under-lock",
    "check-then-act",
    "elementwise-claim",
    "error-hygiene",
    "fault-points",
    "fusion-tier",
    "host-sync",
    "jit-purity",
    "kernel-cast-boundary",
    "kernel-spec-consistency",
    "layer-deps",
    "lock-order",
    "plan-key-completeness",
    "recompile-hazard",
    "registry-consistency",
    "shared-state-guard",
    "typed-error-escape",
)


def write_tree(root, files):
    """files: {relpath: source}. Creates package __init__s implicitly."""
    for rel, src in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src).lstrip("\n"))
        d = path.parent
        while d != root:
            init = d / "__init__.py"
            if not init.exists():
                init.write_text("")
            d = d.parent
    return root


def run_on(root, files, rules=None, **kw):
    write_tree(root, files)
    return run_rules(Project(str(root), ["flink_ml_tpu"]), rules=rules, **kw)


# -----------------------------------------------------------------------------
# 1. tier-1 gate: the shipped tree passes every rule
# -----------------------------------------------------------------------------


def test_registry_has_the_advertised_rules():
    assert set(ALL_RULES) <= set(REGISTRY)


def test_shipped_tree_is_clean():
    result = run_rules(Project(REPO_ROOT, ["flink_ml_tpu"]))
    assert result.findings == [], "\n".join(f.render() for f in result.findings)
    assert result.exit_code == 0
    assert result.files_checked > 100  # the sweep actually covered the package


def test_lock_order_models_all_five_lock_sites():
    graph = lock_order.build_lock_graph(Project(REPO_ROOT, ["flink_ml_tpu"]))
    assert set(graph.nodes) >= {
        "flink_ml_tpu.serving.batcher.MicroBatcher._lock",
        "flink_ml_tpu.serving.registry.ModelRegistry._lock",
        "flink_ml_tpu.serving.server.InferenceServer._template_lock",
        "flink_ml_tpu.metrics.Histogram._lock",
        "flink_ml_tpu.metrics.MetricsRegistry._lock",
    }
    # The known cross-module hold: batcher metrics calls under its queue lock.
    assert (
        "flink_ml_tpu.serving.batcher.MicroBatcher._lock",
        "flink_ml_tpu.metrics.MetricsRegistry._lock",
    ) in graph.edges
    assert graph.cycles() == []


# -----------------------------------------------------------------------------
# 2. layer-deps
# -----------------------------------------------------------------------------


def test_layer_deps_flags_upward_import(tmp_path):
    result = run_on(
        tmp_path,
        {
            "flink_ml_tpu/serving/bad.py": """
                from flink_ml_tpu.iteration import Iterations
            """,
        },
        rules=["layer-deps"],
    )
    (f,) = result.findings
    assert f.rule == "layer-deps" and f.line == 1
    assert "iteration" in f.message and "upward" in f.message


def test_layer_deps_catches_lazy_function_local_imports(tmp_path):
    result = run_on(
        tmp_path,
        {
            "flink_ml_tpu/servable/lazy.py": """
                def transform(df):
                    from flink_ml_tpu.models.linear import LinearModel
                    return LinearModel
            """,
        },
        rules=["layer-deps"],
    )
    assert [f.line for f in result.findings] == [2]


def test_layer_deps_allows_downward_and_same_layer(tmp_path):
    result = run_on(
        tmp_path,
        {
            "flink_ml_tpu/serving/ok.py": """
                from flink_ml_tpu.checkpoint import scan_numbered_dirs
                from flink_ml_tpu.metrics import metrics
                from flink_ml_tpu.servable.api import load_servable
                import numpy as np
            """,
            "flink_ml_tpu/models/ok.py": """
                from flink_ml_tpu.iteration import Iterations
                from flink_ml_tpu.servable.api import load_servable
            """,
        },
        rules=["layer-deps"],
    )
    assert result.findings == []


def test_layer_deps_module_overrides_beat_package_layer(tmp_path):
    # ops is L1, but ops.optimizer is runtime-coupled (L2): only the latter
    # is forbidden from the servable tier.
    result = run_on(
        tmp_path,
        {
            "flink_ml_tpu/servable/kern.py": """
                from flink_ml_tpu.ops.kernels import compute_dots
                from flink_ml_tpu.ops.optimizer import SGD
            """,
        },
        rules=["layer-deps"],
    )
    (f,) = result.findings
    assert f.line == 2 and "ops.optimizer" in f.message


def test_layer_deps_covers_serving_plan_at_l1(tmp_path):
    """The serving fast path (serving/plan.py) sits at L1: composing servable
    kernel specs and ops kernels is allowed, pulling the runtime/library
    tiers into a fused executable is an upward import."""
    from tools.graftcheck.rules.layer_deps import layer_of

    assert layer_of("serving.plan") == 1
    result = run_on(
        tmp_path,
        {
            "flink_ml_tpu/serving/plan.py": """
                from flink_ml_tpu.servable.kernel_spec import KernelSpec
                from flink_ml_tpu.ops.kernels import scale_fn
                from flink_ml_tpu.models.clustering import KMeansModel
            """,
        },
        rules=["layer-deps"],
    )
    (f,) = result.findings
    assert f.line == 3 and "models" in f.message and "upward" in f.message


def test_layer_deps_flags_unmapped_package(tmp_path):
    result = run_on(
        tmp_path,
        {
            "flink_ml_tpu/linalg/x.py": """
                from flink_ml_tpu.brand_new_pkg import thing
            """,
        },
        rules=["layer-deps"],
    )
    (f,) = result.findings
    assert "not in the layer map" in f.message


def test_servable_shim_contract(tmp_path):
    """The absorbed check_servable_imports semantics stay intact."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def transform(df):\n"
        "    from flink_ml_tpu.models.linear import compute_dots\n"
        "    import flink_ml_tpu.iteration.datacache as dc\n"
        "    from flink_ml_tpu import builder\n"
        "    return compute_dots\n"
    )
    found = sorted(m for _, m in layer_deps.servable_violations_in_file(str(bad)))
    assert found == [
        "flink_ml_tpu.builder",
        "flink_ml_tpu.iteration.datacache",
        "flink_ml_tpu.models.linear",
    ]


# -----------------------------------------------------------------------------
# 3. jit-purity
# -----------------------------------------------------------------------------

JIT_BAD = """
    import time
    import numpy as np
    import jax
    from functools import partial

    @jax.jit
    def f(x):
        print("tracing")
        t = time.time()
        y = np.asarray(x)
        return x.sum().item() + float(x)

    @partial(jax.jit, static_argnums=0)
    def g(n, x):
        return x * np.random.uniform()

    def wrapped(x):
        print("hi")
        return x

    fast = jax.jit(wrapped)
"""

JIT_CLEAN = """
    import time
    import numpy as np
    import jax
    import jax.numpy as jnp

    started = time.time()          # host code: fine
    print("module import")         # host code: fine

    @jax.jit
    def f(x, key):
        y = jnp.asarray(x)
        noise = jax.random.normal(key, x.shape)
        scale = np.float32(2.0)    # numpy on a static constant: fine
        return y * noise * scale

    def host_helper(arr):
        return float(np.asarray(arr).sum())   # never jitted: fine
"""


def test_jit_purity_flags_host_syncs_and_impurities(tmp_path):
    result = run_on(tmp_path, {"flink_ml_tpu/ops/bad.py": JIT_BAD}, rules=["jit-purity"])
    hits = {(f.line, kind) for f in result.findings for kind in [f.message.split(":")[1].strip().split(" ")[0]]}
    msgs = "\n".join(f.render() for f in result.findings)
    assert any("print()" in f.message for f in result.findings), msgs
    assert any("time.time()" in f.message for f in result.findings), msgs
    assert any("np.asarray(x)" in f.message for f in result.findings), msgs
    assert any(".item()" in f.message for f in result.findings), msgs
    assert any("float(x)" in f.message for f in result.findings), msgs
    assert any("np.random.uniform" in f.message for f in result.findings), msgs
    # the function passed *by name* to jit is also in scope
    assert any("`wrapped`" in f.message for f in result.findings), msgs
    assert len(hits) >= 6


def test_jit_purity_clean_file_and_out_of_scope_package(tmp_path):
    result = run_on(tmp_path, {"flink_ml_tpu/ops/clean.py": JIT_CLEAN}, rules=["jit-purity"])
    assert result.findings == []
    # same bad source outside the scoped packages is out of scope
    result = run_on(tmp_path, {"flink_ml_tpu/utils/elsewhere.py": JIT_BAD}, rules=["jit-purity"])
    assert result.findings == []


def test_jit_purity_covers_servable_and_serving(tmp_path):
    """The serving fast path fuses servable kernel specs into AOT programs,
    so an impure jitted fn in servable/ or serving/ is in scope."""
    for i, rel in enumerate(("flink_ml_tpu/servable/bad.py", "flink_ml_tpu/serving/bad.py")):
        root = tmp_path / f"tree{i}"
        root.mkdir()
        result = run_on(root, {rel: JIT_BAD}, rules=["jit-purity"])
        assert any(".item()" in f.message for f in result.findings), rel


def test_jit_purity_covers_builder(tmp_path):
    """The batch fast path (builder/batch_plan.py) AOT-compiles kernel specs
    per chunk signature — builder/ is in scope."""
    result = run_on(tmp_path, {"flink_ml_tpu/builder/bad.py": JIT_BAD}, rules=["jit-purity"])
    assert any(".item()" in f.message for f in result.findings)


# -----------------------------------------------------------------------------
# 3b. kernel-spec-consistency
# -----------------------------------------------------------------------------

SPEC_CLEAN = """
    from flink_ml_tpu.ops.kernels import binarize_fn, binarize_kernel

    class Binarizerish:
        def transform(self, df):
            return binarize_kernel(0.5)(df)

        def kernel_spec(self):
            def kernel_fn(model, cols):
                return {"out": binarize_fn(cols["in"], 0.5)}
            return object()
"""

SPEC_DRIFT = """
    from flink_ml_tpu.ops.kernels import binarize_kernel, normalize_fn

    class Drifted:
        def transform(self, df):
            return binarize_kernel(0.5)(df)

        def kernel_spec(self):
            def kernel_fn(model, cols):
                return {"out": normalize_fn(cols["in"], 2.0)}
            return object()
"""

SPEC_HANDROLLED = """
    import jax.numpy as jnp

    class HandRolled:
        def transform(self, df):
            return df

        def kernel_spec(self):
            def kernel_fn(model, cols):
                return {"out": jnp.tanh(cols["in"])}
            return object()
"""

SPEC_ALIASED = """
    from flink_ml_tpu.ops.kernels import kmeans_assign_fn, kmeans_predict_kernel

    class KMeansish:
        def transform(self, df):
            return kmeans_predict_kernel("euclidean")(df, df)

        def kernel_spec(self):
            assign = kmeans_assign_fn("euclidean")
            def kernel_fn(model, cols):
                return {"out": assign(cols["in"], model["centroids"])}
            return object()
"""

SPEC_DEFAULT_HOOK = """
    class Base:
        def transform(self, df):
            return df

        def kernel_spec(self):
            return None
"""


def test_kernel_spec_consistency_clean_pairing(tmp_path):
    result = run_on(
        tmp_path,
        {"flink_ml_tpu/models/feature/ok.py": SPEC_CLEAN},
        rules=["kernel-spec-consistency"],
    )
    assert result.findings == []


def test_kernel_spec_consistency_flags_drift(tmp_path):
    result = run_on(
        tmp_path,
        {"flink_ml_tpu/models/feature/drift.py": SPEC_DRIFT},
        rules=["kernel-spec-consistency"],
    )
    assert len(result.findings) == 1
    assert "'normalize'" in result.findings[0].message


def test_kernel_spec_consistency_flags_hand_rolled_math(tmp_path):
    result = run_on(
        tmp_path,
        {"flink_ml_tpu/models/feature/hand.py": SPEC_HANDROLLED},
        rules=["kernel-spec-consistency"],
    )
    assert len(result.findings) == 1
    assert "references no ops/kernels.py body" in result.findings[0].message


def test_kernel_spec_consistency_resolves_fn_factory_aliases(tmp_path):
    """kmeans_predict_kernel jits kmeans_assign_fn — the alias table pairs
    them, so the historical naming does not flag."""
    result = run_on(
        tmp_path,
        {"flink_ml_tpu/models/clustering/km.py": SPEC_ALIASED},
        rules=["kernel-spec-consistency"],
    )
    assert result.findings == []


def test_kernel_spec_consistency_skips_declaration_only_hooks(tmp_path):
    result = run_on(
        tmp_path,
        {"flink_ml_tpu/servable/base.py": SPEC_DEFAULT_HOOK},
        rules=["kernel-spec-consistency"],
    )
    assert result.findings == []


def test_kernel_spec_consistency_shipped_transformers_all_pair():
    """Every shipped kernel_spec composes a body its transform path jits —
    the batch fast path's no-drift guarantee, as a tier-1 gate."""
    project = Project(REPO_ROOT, ["flink_ml_tpu"])
    result = run_rules(project, rules=["kernel-spec-consistency"])
    assert result.findings == [], "\n".join(f.render() for f in result.findings)


SPARSE_SPEC_DRIFT = """
    from flink_ml_tpu.ops.kernels import sparse_combine_kernel, sparse_dot_fn

    class SparseDrifted:
        def transform(self, df):
            return sparse_combine_kernel()(df)

        def sparse_kernel_spec(self, known):
            def kernel_fn(model, cols):
                return {"o": sparse_dot_fn(cols["v"], cols["i"], model["c"])}
            return object()
"""

SPARSE_SPEC_CLEAN = """
    from flink_ml_tpu.ops.kernels import sparse_combine_fn, sparse_combine_kernel

    class SparseCombiner:
        def transform(self, df):
            return sparse_combine_kernel()(df)

        def sparse_kernel_spec(self, known):
            def kernel_fn(model, cols):
                return {"o": sparse_combine_fn(cols["v"], cols["i"], cols["z"])}
            return object()
"""


def test_kernel_spec_consistency_covers_sparse_specs(tmp_path):
    """The sparse convention's ``sparse_kernel_spec`` hook is held to the
    same shared-body contract as ``kernel_spec``: a sparse spec composing a
    segment-reduce body the per-stage path never jits is drift."""
    result = run_on(
        tmp_path,
        {"flink_ml_tpu/models/feature/sdrift.py": SPARSE_SPEC_DRIFT},
        rules=["kernel-spec-consistency"],
    )
    assert len(result.findings) == 1
    assert "'sparse_dot'" in result.findings[0].message
    clean = run_on(
        tmp_path / "clean",
        {"flink_ml_tpu/models/feature/sok.py": SPARSE_SPEC_CLEAN},
        rules=["kernel-spec-consistency"],
    )
    assert clean.findings == []


# -----------------------------------------------------------------------------
# 4. lock-order
# -----------------------------------------------------------------------------

LOCK_CYCLE = """
    import threading

    class A:
        def __init__(self):
            self._lock = threading.Lock()

        def m1(self):
            with self._lock:
                b.m2()

    class B:
        def __init__(self):
            self._lock = threading.Lock()

        def m2(self):
            with self._lock:
                a.m1()

    a = A()
    b = B()
"""

LOCK_SELF_DEADLOCK = """
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()

        def outer(self):
            with self._lock:
                self.inner()

        def inner(self):
            with self._lock:
                return 1
"""

LOCK_CLEAN = """
    import threading

    class Outer:
        def __init__(self):
            self._lock = threading.Lock()
            self._inner = Inner()

        def step(self):
            with self._lock:
                self._inner.bump()

    class Inner:
        def __init__(self):
            self._lock = threading.Lock()

        def bump(self):
            with self._lock:
                return 1
"""


def test_lock_order_detects_synthetic_ab_ba_cycle(tmp_path):
    result = run_on(
        tmp_path, {"flink_ml_tpu/serving/cycle.py": LOCK_CYCLE}, rules=["lock-order"]
    )
    (f,) = result.findings
    assert "cycle" in f.message
    assert "A._lock" in f.message and "B._lock" in f.message


def test_lock_order_detects_self_deadlock(tmp_path):
    result = run_on(
        tmp_path,
        {"flink_ml_tpu/serving/selfdead.py": LOCK_SELF_DEADLOCK},
        rules=["lock-order"],
    )
    (f,) = result.findings
    assert "C._lock -> " in f.message and "C._lock" in f.message


def test_lock_order_consistent_ordering_is_clean(tmp_path):
    result = run_on(
        tmp_path, {"flink_ml_tpu/serving/ordered.py": LOCK_CLEAN}, rules=["lock-order"]
    )
    assert result.findings == []
    graph = lock_order.build_lock_graph(Project(str(tmp_path), ["flink_ml_tpu"]))
    assert (
        "flink_ml_tpu.serving.ordered.Outer._lock",
        "flink_ml_tpu.serving.ordered.Inner._lock",
    ) in graph.edges


def test_lock_order_condition_aliases_its_lock(tmp_path):
    result = run_on(
        tmp_path,
        {
            "flink_ml_tpu/serving/cond.py": """
                import threading

                class D:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._cond = threading.Condition(self._lock)

                    def wait_then_self_lock(self):
                        with self._cond:
                            self.reenter()

                    def reenter(self):
                        with self._lock:
                            return 1
            """
        },
        rules=["lock-order"],
    )
    # entering the condition IS acquiring _lock -> reenter() self-deadlocks
    (f,) = result.findings
    assert "D._lock" in f.message


# -----------------------------------------------------------------------------
# 5. fault-points
# -----------------------------------------------------------------------------

FAULTS_FIXTURE = {
    "flink_ml_tpu/faults.py": """
        FAULT_POINTS = {
            "demo.tripped": "has a site and a test",
            "demo.dead": "registered, never tripped",
        }

        class _F:
            def trip(self, name, **kw):
                pass

        faults = _F()
    """,
    "flink_ml_tpu/runtime.py": """
        from flink_ml_tpu.faults import faults

        def step():
            faults.trip("demo.tripped")
            faults.trip("demo.typo")
    """,
    "tests/test_demo.py": """
        def test_demo():
            assert "demo.tripped"
    """,
}


def test_fault_points_rule_on_seeded_fixture(tmp_path):
    result = run_on(tmp_path, FAULTS_FIXTURE, rules=["fault-points"])
    msgs = [f.message for f in result.findings]
    assert any("'demo.dead'" in m and "no" in m and "call site" in m for m in msgs)
    assert any("'demo.dead'" in m and "not exercised" in m for m in msgs)
    assert any("'demo.typo'" in m and "unregistered" in m for m in msgs)
    assert not any("'demo.tripped'" in m for m in msgs)
    # the typo finding anchors at its call site
    typo = next(f for f in result.findings if "typo" in f.message)
    assert typo.path == "flink_ml_tpu/runtime.py" and typo.line == 5


def test_fault_points_rule_skips_trees_without_a_registry(tmp_path):
    result = run_on(
        tmp_path, {"flink_ml_tpu/x.py": "VALUE = 1\n"}, rules=["fault-points"]
    )
    assert result.findings == []


# -----------------------------------------------------------------------------
# 6. error-hygiene
# -----------------------------------------------------------------------------

HYGIENE_FIXTURE = """
    def bad_bare():
        try:
            work()
        except:
            return None

    def bad_silent():
        try:
            work()
        except Exception:
            pass

    def ok_narrow():
        try:
            work()
        except (ValueError, TypeError):
            pass

    def ok_handled():
        try:
            work()
        except Exception as e:
            log(e)

    class Holder:
        def __del__(self):
            try:
                self.close()
            except Exception:
                pass
"""


def test_error_hygiene_rule(tmp_path):
    result = run_on(
        tmp_path, {"flink_ml_tpu/utils/h.py": HYGIENE_FIXTURE}, rules=["error-hygiene"]
    )
    assert [(f.line, "bare" in f.message) for f in result.findings] == [
        (4, True),
        (10, False),
    ]


# -----------------------------------------------------------------------------
# 7. framework: suppressions, severities, JSON schema, CLI
# -----------------------------------------------------------------------------


def test_parse_suppressions():
    src = "x = 1\ny = 2  # graftcheck: disable=jit-purity, lock-order\nz = 3  # graftcheck: disable=all\n"
    assert parse_suppressions(src) == {
        2: {"jit-purity", "lock-order"},
        3: {"all"},
    }


def test_suppression_comment_silences_the_finding(tmp_path):
    files = {
        "flink_ml_tpu/serving/sup.py": """
            from flink_ml_tpu.iteration import Iterations  # graftcheck: disable=layer-deps
        """
    }
    result = run_on(tmp_path, files, rules=["layer-deps"])
    assert result.findings == [] and len(result.suppressed) == 1
    assert result.exit_code == 0
    # a different rule's tag would NOT have silenced it
    files2 = {
        "flink_ml_tpu/serving/sup2.py": """
            from flink_ml_tpu.iteration import Iterations  # graftcheck: disable=jit-purity
        """
    }
    result2 = run_on(tmp_path, files2, rules=["layer-deps"])
    assert len(result2.findings) == 1


def test_severity_override_downgrades_exit_code(tmp_path):
    files = {
        "flink_ml_tpu/serving/sev.py": """
            from flink_ml_tpu.iteration import Iterations
        """
    }
    result = run_on(
        tmp_path, files, rules=["layer-deps"], severity_overrides={"layer-deps": "warning"}
    )
    assert len(result.findings) == 1
    assert result.findings[0].severity == "warning"
    assert result.exit_code == 0


def test_unknown_rule_raises():
    with pytest.raises(KeyError):
        run_rules(Project(REPO_ROOT, ["tools/graftcheck/__init__.py"]), rules=["nope"])


def test_json_output_schema(tmp_path):
    files = {
        "flink_ml_tpu/serving/j.py": """
            from flink_ml_tpu.models import linear
        """
    }
    result = run_on(tmp_path, files)
    payload = result.to_json()
    assert payload["version"] == JSON_SCHEMA_VERSION
    assert {r["name"] for r in payload["rules"]} == set(ALL_RULES)
    for rule in payload["rules"]:
        assert set(rule) == {"name", "severity", "granularity", "description"}
        assert rule["severity"] in ("error", "warning")
        assert rule["granularity"] in ("project", "file")
    assert payload["summary"]["files_checked"] >= 1
    assert set(payload["summary"]["rule_times_ms"]) == set(ALL_RULES)
    assert all(t >= 0 for t in payload["summary"]["rule_times_ms"].values())
    assert payload["summary"]["findings"] == len(payload["findings"]) == 1
    assert payload["summary"]["by_rule"] == {"layer-deps": 1}
    (f,) = payload["findings"]
    assert set(f) == {"rule", "path", "line", "message", "severity"}
    assert f["path"] == "flink_ml_tpu/serving/j.py" and f["line"] == 1
    json.dumps(payload)  # round-trippable


def _cli(*args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftcheck", *args],
        cwd=cwd,
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_cli_shipped_tree_exits_zero():
    proc = _cli("flink_ml_tpu")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 error(s)" in proc.stdout


def test_cli_seeded_violation_exits_nonzero_with_rule_tags(tmp_path):
    write_tree(
        tmp_path,
        {
            "flink_ml_tpu/serving/bad.py": "from flink_ml_tpu.models import linear\n",
            "flink_ml_tpu/ops/bad.py": JIT_BAD,
        },
    )
    proc = _cli("--root", str(tmp_path), "flink_ml_tpu")
    assert proc.returncode == 1
    assert "[layer-deps]" in proc.stdout and "[jit-purity]" in proc.stdout
    proc_json = _cli("--root", str(tmp_path), "flink_ml_tpu", "--format", "json")
    assert proc_json.returncode == 1
    payload = json.loads(proc_json.stdout)
    assert payload["summary"]["errors"] > 0


def test_cli_list_rules_and_usage_errors():
    proc = _cli("--list-rules")
    assert proc.returncode == 0
    for rule in ALL_RULES:
        assert rule in proc.stdout
    assert _cli("no_such_dir").returncode == 2
    assert _cli("--rules", "bogus", "flink_ml_tpu").returncode == 2


# -----------------------------------------------------------------------------
# fusion-tier: exact partitions never span a reduction; Pallas behind fast only
# -----------------------------------------------------------------------------

FUSION_PLANNER_CLEAN = """
    PLAN_FUSED = "fused"

    def _partition_exact(specs):
        runs, i = [], 0
        while i < len(specs):
            j = i + 1
            if specs[i].elementwise:
                while j < len(specs) and specs[j].elementwise:
                    j += 1
            runs.append((i, j))
            i = j
        return runs

    def _partition_fast(specs):
        return [(0, len(specs))]

    def _fast_megakernels(programs):
        from flink_ml_tpu.servable.megakernels import build_megakernel_fn
        return {0: build_megakernel_fn(programs)}

    class FusedSegment:
        def __init__(self, specs, fusion=None):
            if fusion is not None and fusion.fast:
                self.runs = _partition_fast(specs)
                if fusion.megakernel:
                    self.mega = _fast_megakernels(self.runs)
            else:
                self.runs = _partition_exact(specs)
"""

FUSION_MEGAKERNELS = """
    from jax.experimental import pallas as pl

    def build_megakernel_fn(programs):
        return pl.pallas_call
"""


def test_fusion_tier_clean_fixture_passes(tmp_path):
    result = run_on(
        tmp_path,
        {
            "flink_ml_tpu/servable/planner.py": FUSION_PLANNER_CLEAN,
            "flink_ml_tpu/servable/megakernels.py": FUSION_MEGAKERNELS,
        },
        rules=["fusion-tier"],
    )
    assert result.findings == [], "\n".join(f.render() for f in result.findings)


def test_fusion_tier_flags_pallas_outside_megakernels(tmp_path):
    result = run_on(
        tmp_path,
        {
            "flink_ml_tpu/servable/planner.py": FUSION_PLANNER_CLEAN,
            "flink_ml_tpu/serving/plan.py": """
                from jax.experimental import pallas as pl
            """,
        },
        rules=["fusion-tier"],
    )
    assert len(result.findings) == 1
    assert result.findings[0].path == "flink_ml_tpu/serving/plan.py"
    assert "Pallas import in the plan tier" in result.findings[0].message


def test_fusion_tier_flags_exact_partition_merging_on_fusable(tmp_path):
    dirty = FUSION_PLANNER_CLEAN.replace(
        "if specs[i].elementwise:", "if specs[i].fusable:"
    ).replace(
        "while j < len(specs) and specs[j].elementwise:",
        "while j < len(specs) and specs[j].fusable:",
    )
    result = run_on(
        tmp_path,
        {"flink_ml_tpu/servable/planner.py": dirty},
        rules=["fusion-tier"],
    )
    msgs = [f.message for f in result.findings]
    assert any("never tests .elementwise" in m for m in msgs)
    assert any(".fusable" in m for m in msgs)


def test_fusion_tier_flags_missing_exact_partition(tmp_path):
    result = run_on(
        tmp_path,
        {"flink_ml_tpu/servable/planner.py": "def build(): pass\n"},
        rules=["fusion-tier"],
    )
    assert any("no _partition_exact" in f.message for f in result.findings)


def test_fusion_tier_flags_module_level_megakernel_import(tmp_path):
    dirty = (
        "from flink_ml_tpu.servable.megakernels import build_megakernel_fn\n"
        + textwrap.dedent(FUSION_PLANNER_CLEAN).lstrip("\n")
    )
    result = run_on(
        tmp_path,
        {"flink_ml_tpu/servable/planner.py": dirty},
        rules=["fusion-tier"],
    )
    msgs = [f.message for f in result.findings]
    assert any("import must be function-local" in m for m in msgs)


def test_fusion_tier_flags_unguarded_fast_machinery(tmp_path):
    dirty = FUSION_PLANNER_CLEAN.replace(
        """            if fusion is not None and fusion.fast:
                self.runs = _partition_fast(specs)
                if fusion.megakernel:
                    self.mega = _fast_megakernels(self.runs)
            else:
                self.runs = _partition_exact(specs)""",
        """            self.runs = _partition_fast(specs)
            self.mega = _fast_megakernels(self.runs)""",
    )
    assert "_partition_exact(specs)" not in dirty.split("class FusedSegment")[1]
    result = run_on(
        tmp_path,
        {"flink_ml_tpu/servable/planner.py": dirty},
        rules=["fusion-tier"],
    )
    unguarded = [
        f for f in result.findings if "outside a fusion-fast guard" in f.message
    ]
    assert len(unguarded) == 2  # _partition_fast and _fast_megakernels


def test_fusion_tier_shipped_tree_contract():
    """The real planner satisfies the rule with ZERO suppressions, and the
    real megakernel module is the plan tier's only Pallas user."""
    result = run_rules(Project(REPO_ROOT, ["flink_ml_tpu"]), rules=["fusion-tier"])
    assert result.findings == [], "\n".join(f.render() for f in result.findings)
    assert result.suppressed == []
