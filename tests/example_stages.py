"""Hand-written toy stages that lock the Stage/Pipeline contract.

Mirrors flink-ml-core/src/test/.../api/ExampleStages.java (SumEstimator/SumModel used
by PipelineTest/GraphTest).
"""
import numpy as np

from flink_ml_tpu.api import DataFrame, DataTypes
from flink_ml_tpu.api.core import AlgoOperator, Estimator, Model, Transformer
from flink_ml_tpu.params.param import StringParam
from flink_ml_tpu.utils import read_write as rw


class SumModel(Model):
    """Adds a learned delta to the input column. Ref ExampleStages.SumModel."""

    INPUT_COL = StringParam("inputCol", "Input column.", "input")

    def __init__(self, delta: float = 0.0, **kwargs):
        super().__init__(**kwargs)
        self.delta = float(delta)

    def transform(self, df: DataFrame) -> DataFrame:
        col = self.get(self.INPUT_COL)
        return df.with_column(col, df.scalars(col) + self.delta)

    def set_model_data(self, model_data: DataFrame) -> "SumModel":
        self.delta = float(model_data.scalars("delta")[0])
        return self

    def get_model_data(self):
        return [DataFrame.from_dict({"delta": np.array([self.delta])})]

    def save(self, path: str) -> None:
        rw.save_metadata(self, path)
        rw.save_model_arrays(path, {"delta": np.array([self.delta])})

    @classmethod
    def load(cls, path: str) -> "SumModel":
        metadata = rw.load_metadata(path, rw.stage_class_name(cls))
        arrays = rw.load_model_arrays(path)
        model = cls(delta=float(arrays["delta"][0]))
        model.load_param_map_from_json(metadata["paramMap"])
        return model


class SumEstimator(Estimator):
    """Learns delta = sum of the input column. Ref ExampleStages.SumEstimator."""

    INPUT_COL = StringParam("inputCol", "Input column.", "input")

    def fit(self, df: DataFrame) -> SumModel:
        model = SumModel(delta=float(df.scalars(self.get(self.INPUT_COL)).sum()))
        model.set(SumModel.INPUT_COL, self.get(self.INPUT_COL))
        return model


class DoubleTransformer(Transformer):
    """Stateless transformer that doubles the input column."""

    INPUT_COL = StringParam("inputCol", "Input column.", "input")

    def transform(self, df: DataFrame) -> DataFrame:
        col = self.get(self.INPUT_COL)
        return df.with_column(col, df.scalars(col) * 2.0)
