"""Iteration-runtime tests.

Parity targets (SURVEY.md §4): ``BoundedAllRoundStreamIterationITCase`` /
``UnboundedStreamIterationITCase`` semantics — epoch counting, criteria-driven
termination, listener callbacks, feedback of device arrays — plus datacache tests
(``DataCacheWriter``/``DataCacheSnapshot``) and window/stream slicing.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from flink_ml_tpu.iteration import (
    DeviceDataCache,
    HostDataCache,
    IterationBodyResult,
    IterationConfig,
    IterationListener,
    TerminateOnMaxIter,
    TerminateOnMaxIterOrTol,
    iterate_bounded_until_termination,
    iterate_unbounded,
)
from flink_ml_tpu.iteration.stream import rebatch, window_stream
from flink_ml_tpu.ops.windows import CountTumblingWindows, EventTimeTumblingWindows, GlobalWindows
from flink_ml_tpu.parallel import MeshContext


class _EpochRecorder(IterationListener):
    def __init__(self):
        self.epochs = []
        self.terminated = False

    def on_epoch_watermark_incremented(self, epoch, context):
        self.epochs.append(epoch)

    def on_iteration_terminated(self, context):
        self.terminated = True


def test_bounded_iteration_max_iter_criteria():
    """x <- x + 1 for exactly max_iter epochs (TerminateOnMaxIter semantics)."""
    crit = TerminateOnMaxIter(5)
    rec = _EpochRecorder()

    def body(variables, epoch):
        (x,) = variables
        x = x + 1.0
        return IterationBodyResult([x], outputs=[x], termination_criteria=crit(epoch))

    outs = iterate_bounded_until_termination([jnp.zeros(())], body, listeners=[rec])
    assert rec.epochs == [0, 1, 2, 3, 4]
    assert rec.terminated
    assert float(outs[0]) == 5.0


def test_bounded_iteration_tol_criteria():
    """Terminates early when loss drops below tol (TerminateOnMaxIterOrTol.java:34)."""
    crit = TerminateOnMaxIterOrTol(max_iter=100, tol=0.1)

    def body(variables, epoch):
        (x,) = variables
        x = x * 0.5
        return IterationBodyResult(
            [x], outputs=[x], termination_criteria=crit(epoch, loss=x)
        )

    outs = iterate_bounded_until_termination([jnp.asarray(1.0)], body)
    assert float(outs[0]) < 0.1
    # 1.0 * 0.5^4 = 0.0625 is the first value < 0.1
    assert float(outs[0]) == 0.0625


def test_bounded_iteration_empty_feedback_terminates():
    def body(variables, epoch):
        if epoch >= 2:
            return IterationBodyResult(None, outputs=[epoch])
        return IterationBodyResult([variables[0]], outputs=[epoch])

    outs = iterate_bounded_until_termination([0], body)
    assert outs == [2]


def test_bounded_iteration_max_epochs_safety_bound():
    def body(variables, epoch):
        return IterationBodyResult([variables[0] + 1])

    config = IterationConfig(max_epochs=3)
    iterate_bounded_until_termination([0], body, config=config)  # must not hang


class TestPerRoundLifecycle:
    """OperatorLifeCycle.PER_ROUND — the forEachRound contract
    (BoundedPerRoundStreamIterationITCase shape): the body factory builds a
    FRESH epoch body per round, so per-instance state never leaks across
    rounds; cross-round state flows only through the feedback variables."""

    class _StatefulBody:
        """A body whose instance state would corrupt results if reused."""

        def __init__(self, per_instance_calls):
            self.calls = 0  # fresh per PER_ROUND instance
            self._log = per_instance_calls

        def __call__(self, variables, epoch, streams=None):
            self.calls += 1
            self._log.append(self.calls)
            (x,) = variables
            # `calls` enters the math: an ALL_ROUND-style reuse would add
            # 1, 2, 3, ... instead of 1 every round.
            x = x + float(self.calls)
            return IterationBodyResult([x], outputs=[x])

    def test_bounded_per_round_builds_fresh_body_each_epoch(self):
        from flink_ml_tpu.iteration import OperatorLifeCycle

        log = []
        factory = lambda: self._StatefulBody(log)  # noqa: E731
        config = IterationConfig(
            operator_life_cycle=OperatorLifeCycle.PER_ROUND, max_epochs=4
        )
        outs = iterate_bounded_until_termination([0.0], factory, config=config)
        assert log == [1, 1, 1, 1]  # every round saw a fresh instance
        assert float(outs[0]) == 4.0  # 0 + 1 + 1 + 1 + 1

    def test_all_round_keeps_one_body_instance(self):
        log = []
        body = self._StatefulBody(log)
        config = IterationConfig(max_epochs=4)  # default ALL_ROUND
        outs = iterate_bounded_until_termination([0.0], body, config=config)
        assert log == [1, 2, 3, 4]  # the same instance accumulated state
        assert float(outs[0]) == 10.0

    def test_unbounded_per_round_builds_fresh_body_each_batch(self):
        from flink_ml_tpu.iteration import OperatorLifeCycle

        log = []
        batches = [{"x": np.full(2, float(i))} for i in range(3)]

        def factory():
            inner = self._StatefulBody(log)

            def body(variables, batch, epoch):
                inner.calls += 1
                log.append(inner.calls)
                (total,) = variables
                return IterationBodyResult(
                    [total + batch["x"].sum()], outputs=[float(total)]
                )

            return body

        config = IterationConfig(operator_life_cycle=OperatorLifeCycle.PER_ROUND)
        outs = list(iterate_unbounded([0.0], iter(batches), factory, config=config))
        assert log == [1, 1, 1]
        assert outs == [0.0, 0.0, 2.0]

    def test_per_round_rejects_non_factory_body(self):
        from flink_ml_tpu.iteration import OperatorLifeCycle

        config = IterationConfig(
            operator_life_cycle=OperatorLifeCycle.PER_ROUND, max_epochs=2
        )
        with pytest.raises(TypeError, match="zero-arg factory"):
            iterate_bounded_until_termination(
                [0.0], lambda: 42, config=config  # factory returns a non-callable
            )


def test_unbounded_iteration_yields_per_batch():
    """Model-as-stream: one output per arriving window (UnboundedStreamIterationITCase)."""
    batches = [{"x": np.full(4, float(i))} for i in range(3)]

    def body(variables, batch, epoch):
        (total,) = variables
        total = total + batch["x"].sum()
        return IterationBodyResult([total], outputs=[float(total)])

    outs = list(iterate_unbounded([0.0], iter(batches), body))
    assert outs == [0.0, 4.0, 12.0]


# --- data caches -------------------------------------------------------------


def test_device_data_cache_shards_and_masks():
    ctx = MeshContext(n_data=8)
    cache = DeviceDataCache({"x": np.arange(10.0)[:, None]}, ctx=ctx)
    assert cache.n_valid == 10
    assert cache.n_padded == 16
    assert cache.local_rows == 2
    mask = np.asarray(cache.mask)
    assert mask.sum() == 10.0


def test_host_data_cache_rebatch_and_snapshot(tmp_path):
    cache = HostDataCache(memory_budget_bytes=200, spill_dir=str(tmp_path / "spill"))
    for i in range(5):
        cache.append({"x": np.full(7, i, np.float64), "y": np.arange(7.0) + i})
    cache.finish()
    assert cache.num_rows == 35
    batches = list(cache.iter_minibatches(batch_size=10))
    assert [len(b["x"]) for b in batches] == [10, 10, 10, 5]
    np.testing.assert_array_equal(
        np.concatenate([b["x"] for b in batches]),
        np.concatenate([np.full(7, i) for i in range(5)]),
    )
    # snapshot round-trip (DataCacheSnapshot.writeTo/recover)
    snap = str(tmp_path / "snap")
    cache.snapshot(snap)
    recovered = HostDataCache.recover(snap)
    assert recovered.num_rows == 35
    np.testing.assert_array_equal(
        np.concatenate([b["y"] for b in recovered.iter_minibatches(35)]),
        np.concatenate([b["y"] for b in cache.iter_minibatches(35)]),
    )


# --- streams / windows -------------------------------------------------------


def test_rebatch_exact_sizes():
    stream = [{"x": np.arange(i, i + 3, dtype=np.float64)} for i in range(0, 12, 3)]
    out = list(rebatch(iter(stream), 5))
    assert [len(b["x"]) for b in out] == [5, 5, 2]
    np.testing.assert_array_equal(
        np.concatenate([b["x"] for b in out]),
        np.concatenate([b["x"] for b in stream]),
    )


def test_count_tumbling_windows_drop_partial():
    stream = [{"x": np.arange(10.0)}]
    out = list(window_stream(iter(stream), CountTumblingWindows.of(4)))
    assert [len(b["x"]) for b in out] == [4, 4]


def test_global_windows_single_window():
    stream = [{"x": np.arange(3.0)}, {"x": np.arange(2.0)}]
    out = list(window_stream(iter(stream), GlobalWindows.get_instance()))
    assert len(out) == 1 and len(out[0]["x"]) == 5


def test_event_time_tumbling_windows():
    stream = [{"t": np.array([0, 5, 10, 15, 25], np.float64), "x": np.arange(5.0)}]
    out = list(window_stream(iter(stream), EventTimeTumblingWindows.of(10), timestamp_column="t"))
    assert [list(b["x"]) for b in out] == [[0.0, 1.0], [2.0, 3.0], [4.0]]


class TestReplayableDataStreams:
    """Ref ReplayableDataStreamList semantics: replayed sources re-materialize
    every epoch (from the cache, incl. disk spill); non-replayed sources are
    empty after epoch 0."""

    def test_replay_from_spilling_cache_every_epoch(self, tmp_path):
        from flink_ml_tpu.iteration import (
            HostDataCache,
            IterationBodyResult,
            IterationConfig,
            ReplayableDataStreamList,
            iterate_bounded_until_termination,
        )

        cache = HostDataCache(memory_budget_bytes=200, spill_dir=str(tmp_path))
        for a in range(0, 40, 10):
            cache.append({"x": np.arange(a, a + 10, dtype=np.float64)})
        cache.finish()
        assert any("files" in e for e in cache._log), "budget should force spill"

        data = ReplayableDataStreamList(
            replay={"train": cache},
            no_replay={"init": {"x": np.asarray([100.0])}},
        )
        per_epoch_sums = []
        init_seen = []

        def body(variables, epoch, streams):
            total = sum(float(np.sum(c["x"])) for c in streams["train"])
            per_epoch_sums.append(total)
            init_seen.append(sum(float(np.sum(c["x"])) for c in streams["init"]))
            (acc,) = variables
            return IterationBodyResult([acc + total], outputs=[acc + total])

        (out,) = iterate_bounded_until_termination(
            [0.0], body, config=IterationConfig(max_epochs=3), data=data
        )
        assert per_epoch_sums == [780.0, 780.0, 780.0]  # sum(0..39) each epoch
        assert init_seen == [100.0, 0.0, 0.0]  # non-replayed: epoch 0 only
        assert out == 3 * 780.0

    def test_replay_factory_and_dataframe_sources(self):
        from flink_ml_tpu.api.dataframe import DataFrame
        from flink_ml_tpu.iteration import (
            IterationBodyResult,
            IterationConfig,
            ReplayableDataStreamList,
            iterate_bounded_until_termination,
        )

        calls = []

        def factory():
            calls.append(1)
            return iter([{"x": np.asarray([1.0, 2.0])}])

        df = DataFrame.from_dict({"y": np.asarray([5.0, 7.0])})
        data = ReplayableDataStreamList(replay={"f": factory, "df": df})

        def body(variables, epoch, streams):
            sx = sum(float(np.sum(c["x"])) for c in streams["f"])
            sy = sum(float(np.sum(c["y"])) for c in streams["df"])
            assert (sx, sy) == (3.0, 12.0)
            return IterationBodyResult(variables)

        iterate_bounded_until_termination(
            [0.0], body, config=IterationConfig(max_epochs=2), data=data
        )
        assert len(calls) == 2, "factory re-invoked per epoch"

    def test_overlapping_names_rejected(self):
        import pytest

        from flink_ml_tpu.iteration import ReplayableDataStreamList

        with pytest.raises(ValueError, match="both replay"):
            ReplayableDataStreamList(replay={"a": 1}, no_replay={"a": 2})

    def test_one_shot_iterator_rejected_loudly(self):
        import pytest

        from flink_ml_tpu.iteration import ReplayableDataStreamList

        data = ReplayableDataStreamList(replay={"g": iter([{"x": np.zeros(1)}])})
        with pytest.raises(TypeError, match="not replayable"):
            data.epoch_view(0)

    def test_list_of_chunks_replays(self):
        from flink_ml_tpu.iteration import ReplayableDataStreamList

        data = ReplayableDataStreamList(
            replay={"train": [{"x": np.asarray([1.0])}, {"x": np.asarray([2.0])}]}
        )
        for epoch in range(2):
            chunks = list(data.epoch_view(epoch)["train"])
            assert [float(c["x"][0]) for c in chunks] == [1.0, 2.0]

    def test_no_replay_accepts_one_shot_iterator(self):
        from flink_ml_tpu.iteration import ReplayableDataStreamList

        data = ReplayableDataStreamList(
            no_replay={"init": iter([{"x": np.asarray([3.0])}])}
        )
        chunks = list(data.epoch_view(0)["init"])
        assert [float(c["x"][0]) for c in chunks] == [3.0]
        assert list(data.epoch_view(1)["init"]) == []


class TestUnboundedStreamPositionResume:
    """iterate_unbounded checkpoints the stream position (epoch == batches
    consumed) with the variables; resume skips the replayed source to the
    offset — the source-offset contract the reference gets from
    Checkpoints.java + SGD's batch-offset state (VERDICT r4 missing #2)."""

    @staticmethod
    def _batches(n=10):
        return [{"x": np.asarray(float(i + 1))} for i in range(n)]

    @staticmethod
    def _body(variables, batch, epoch):
        (acc,) = variables
        acc = acc + float(batch["x"])
        return IterationBodyResult([acc], outputs=[float(acc)])

    def test_resume_skips_consumed_prefix(self, tmp_path):
        import itertools

        from flink_ml_tpu.checkpoint import CheckpointManager

        batches = self._batches(10)
        clean = list(iterate_unbounded([np.asarray(0.0)], iter(batches), self._body))

        mgr = CheckpointManager(str(tmp_path / "unb"))
        config = IterationConfig(checkpoint_interval=1, checkpoint_manager=mgr)
        # "kill": abandon the generator after 5 epochs
        partial = list(
            itertools.islice(
                iterate_unbounded([np.asarray(0.0)], iter(batches), self._body, config=config),
                5,
            )
        )
        assert partial == clean[:5]
        assert mgr.all_steps()

        # resume: replayed-from-zero source; consumed prefix must be skipped
        resumed = list(
            iterate_unbounded([np.asarray(0.0)], iter(batches), self._body, config=config)
        )
        assert resumed[-1] == clean[-1] == sum(range(1, 11))
        # exactly-once at interval=1: the snapshot is taken BEFORE an epoch's
        # outputs are yielded, so nothing the consumer saw is ever re-emitted
        assert resumed == clean[5:]

    def test_resume_uses_seekable_skip_when_available(self, tmp_path):
        from flink_ml_tpu.checkpoint import CheckpointManager

        class SeekableSource:
            """A source with skip(n): resume must seek, not re-read."""

            def __init__(self, batches):
                self._batches = batches
                self._pos = 0
                self.skipped_to = None

            def skip(self, n):
                self._pos = n
                self.skipped_to = n

            def __iter__(self):
                return self

            def __next__(self):
                if self._pos >= len(self._batches):
                    raise StopIteration
                item = self._batches[self._pos]
                self._pos += 1
                return item

        import itertools

        batches = self._batches(8)
        mgr = CheckpointManager(str(tmp_path / "seek"))
        config = IterationConfig(checkpoint_interval=1, checkpoint_manager=mgr)
        list(
            itertools.islice(
                iterate_unbounded([np.asarray(0.0)], iter(batches), self._body, config=config),
                4,
            )
        )
        src = SeekableSource(batches)
        out = list(iterate_unbounded([np.asarray(0.0)], src, self._body, config=config))
        assert src.skipped_to is not None and src.skipped_to >= 3
        assert out[-1] == sum(range(1, 9))

    def test_replay_shorter_than_offset_raises(self, tmp_path):
        import itertools

        from flink_ml_tpu.checkpoint import CheckpointManager

        batches = self._batches(8)
        mgr = CheckpointManager(str(tmp_path / "short"))
        config = IterationConfig(checkpoint_interval=1, checkpoint_manager=mgr)
        list(
            itertools.islice(
                iterate_unbounded([np.asarray(0.0)], iter(batches), self._body, config=config),
                5,
            )
        )
        with pytest.raises(ValueError, match="before the checkpointed offset"):
            list(
                iterate_unbounded(
                    [np.asarray(0.0)], iter(batches[:3]), self._body, config=config
                )
            )
