"""Pod-scale sharded training tests (docs/distributed_training.md).

The ROADMAP item 4 contract: training epochs sharded over the device mesh
through the deterministic mapreduce tier must be BIT-identical across mesh
widths 1/2/4/8 (same blocks, same fold tree at every width), sharded epoch
state must kill/resume through per-shard checkpoints, and a sharded trainer
must publish straight into serving with no extra serving-path work.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flink_ml_tpu.checkpoint import (
    CheckpointManager,
    MeshMismatchError,
    ShardedCheckpointManager,
)
from flink_ml_tpu.config import Options, config
from flink_ml_tpu.ops import SGD, BinaryLogisticLoss, LeastSquareLoss
from flink_ml_tpu.parallel import (
    BLOCK_ROWS,
    ShardedTrainCache,
    TrainSharding,
    mapreduce_sum,
    resolve_train_sharding,
    tree_fold_sum,
)

WIDTHS = (1, 2, 4, 8)


@pytest.fixture
def train_mesh():
    """Set train.mesh for the test body, always unset afterwards."""

    def _set(width):
        config.set(Options.TRAIN_MESH, width)

    yield _set
    config.unset(Options.TRAIN_MESH)
    config.unset(Options.TRAIN_MESH_MODEL)


def _sgd_data(n=300, d=5, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = np.linspace(1.0, -1.0, d).astype(np.float32)
    y = (X @ w > 0).astype(np.float32)
    return {"features": X, "labels": y}


class TestCollectives:
    def test_mapreduce_matches_numpy_sum(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 3)).astype(np.float32)
        got = jax.jit(lambda a: mapreduce_sum(a))(x)
        np.testing.assert_allclose(np.asarray(got), x.sum(axis=0), rtol=1e-5)

    def test_tree_fold_trailing_zero_blocks_are_inert(self):
        """The width-invariance lemma: zero pad blocks (a wider mesh pads the
        same rows to a larger quantum) never change the fold result — zeros
        stay exactly zero at every fold level and x + 0.0 == x."""
        rng = np.random.default_rng(1)
        blocks = jnp.asarray(rng.normal(size=(5, 4)).astype(np.float32))
        base = np.asarray(tree_fold_sum(blocks))
        for pad in (1, 3, 11):
            padded = jnp.concatenate([blocks, jnp.zeros((pad, 4), jnp.float32)])
            np.testing.assert_array_equal(np.asarray(tree_fold_sum(padded)), base)

    @pytest.mark.parametrize("width", WIDTHS)
    def test_sharded_reduce_bit_equals_width_one(self, width):
        """mapreduce_sum under the block-cyclic deal == the width-1 fold of
        the same rows, bitwise, at every mesh width."""
        rng = np.random.default_rng(2)
        rows = rng.normal(size=(16 * BLOCK_ROWS, 3)).astype(np.float32)
        ref = np.asarray(
            jax.jit(lambda a: mapreduce_sum(a))(rows)
        )
        ts = TrainSharding(width)
        cache = ts.deal_cache({"x": rows})
        from jax.sharding import PartitionSpec as P

        prog = jax.jit(
            jax.shard_map(
                lambda a: mapreduce_sum(a, ts.data_axes, ts.n_data),
                mesh=ts.mesh,
                in_specs=(P(ts.data_axes),),
                out_specs=P(),
            )
        )
        np.testing.assert_array_equal(np.asarray(prog(cache["x"])), ref)

    def test_empty_shard_contributes_zero_identity(self):
        """A shard whose rows are all padding (mask 0) folds to exactly the
        zero identity — the semantics the host reduce's ``identity`` kwarg
        now mirrors."""
        ts = TrainSharding(4)
        rows = np.ones((BLOCK_ROWS, 2), np.float32)  # one real block, 3 shards padded
        cache = ts.deal_cache({"x": rows})
        from jax.sharding import PartitionSpec as P

        prog = jax.jit(
            jax.shard_map(
                lambda a, m: mapreduce_sum(a * m[:, None], ts.data_axes, ts.n_data),
                mesh=ts.mesh,
                in_specs=(P(ts.data_axes), P(ts.data_axes)),
                out_specs=P(),
            )
        )
        got = np.asarray(prog(cache["x"], cache.mask))
        np.testing.assert_array_equal(got, np.full(2, BLOCK_ROWS, np.float32))

    def test_host_reduce_identity_matches_collective_on_empty(self):
        """Satellite regression: the thread-belt reduce with ``identity`` and
        the device collective agree on the empty-partition identity."""
        from flink_ml_tpu.parallel import reduce as ds_reduce
        from flink_ml_tpu.parallel.mesh import MeshContext

        ctx = MeshContext(n_data=4)  # 4 partitions over fewer rows
        cols = {"v": np.asarray([[1.0, 2.0]], np.float64)}  # 1 row, 3 empty parts
        fn = lambda a, b: {"v": a["v"] + b["v"]}
        identity = {"v": np.zeros((1, 2), np.float64)}
        got = ds_reduce(cols, fn, ctx=ctx, identity=identity)
        np.testing.assert_array_equal(got["v"], cols["v"])
        # all-empty input returns the identity itself, like a fully masked mesh
        empty = {"v": np.zeros((0, 2), np.float64)}
        got = ds_reduce(empty, fn, ctx=ctx, identity=identity)
        np.testing.assert_array_equal(got["v"], identity["v"])
        # legacy default (no identity) keeps the empty-columns contract
        got = ds_reduce(empty, fn, ctx=ctx)
        assert got["v"].shape == (0, 2)


class TestTrainShardingSurface:
    def test_resolution(self, train_mesh):
        assert resolve_train_sharding() is None  # unset -> legacy paths
        train_mesh(2)
        ts = resolve_train_sharding()
        assert ts is not None and ts.key == (2, 1)
        config.set(Options.TRAIN_MESH, 0)
        assert resolve_train_sharding() is None  # 0 = explicit off
        config.set(Options.TRAIN_MESH, 99)
        with pytest.raises(ValueError, match="devices"):
            resolve_train_sharding()

    def test_deal_round_trips_rows(self):
        """The block-cyclic deal is a permutation: gather-unpermute restores
        the original global row order (what mapreduce_sum relies on)."""
        ts = TrainSharding(4)
        n = 4 * BLOCK_ROWS * 3
        perm = ts.deal_permutation(n)
        assert sorted(perm.tolist()) == list(range(n))
        rows = np.arange(n, dtype=np.float32)[:, None]
        cache = ts.deal_cache({"x": rows})
        assert cache.n_padded == n and cache.local_rows == n // 4
        # global window [s, s+B) lands contiguous-local on every shard
        B = ts.round_batch(64)
        assert B % ts.row_quantum == 0

    def test_cache_rejects_ragged_columns(self):
        ts = TrainSharding(2)
        with pytest.raises(ValueError, match="inconsistent"):
            ShardedTrainCache(
                {"a": np.zeros(8), "b": np.zeros(9)}, ts, ts.row_quantum
            )

    def test_batch_quantum_enforced(self):
        ts = TrainSharding(4)
        assert ts.round_batch(1) == ts.row_quantum
        assert ts.round_batch(33) == 2 * ts.row_quantum
        with pytest.raises(ValueError, match="quantum"):
            ts.padded_rows(100, 7)


class TestBitIdentityAcrossWidths:
    def test_sgd_epochs_bit_stable(self):
        """SGD fits are bit-identical across mesh widths 1/2/4/8 under the
        8·N row-remainder discipline (global batch a multiple of 8·8)."""
        data = _sgd_data()
        outs = {}
        for w in WIDTHS:
            coef = SGD(
                max_iter=23,
                learning_rate=0.1,
                global_batch_size=64,
                tol=0.0,
                reg=0.01,
                elastic_net=0.3,
                sharding=TrainSharding(w),
            ).optimize(np.zeros(5), data, BinaryLogisticLoss.INSTANCE)
            outs[w] = np.asarray(coef)
        for w in WIDTHS[1:]:
            np.testing.assert_array_equal(outs[w], outs[1])

    def test_sgd_deterministic_close_to_legacy(self):
        """Same data, legacy vs deterministic tier: different (but both
        correct) minibatch schedules — trajectories agree loosely."""
        data = _sgd_data()
        legacy = np.asarray(
            SGD(max_iter=23, learning_rate=0.1, global_batch_size=64, tol=0.0)
            .optimize(np.zeros(5), data, BinaryLogisticLoss.INSTANCE)
        )
        det = np.asarray(
            SGD(
                max_iter=23,
                learning_rate=0.1,
                global_batch_size=64,
                tol=0.0,
                sharding=TrainSharding(2),
            ).optimize(np.zeros(5), data, BinaryLogisticLoss.INSTANCE)
        )
        np.testing.assert_allclose(det, legacy, atol=0.1)

    def test_sgd_rejects_ctx_and_sharding(self):
        from flink_ml_tpu.parallel.mesh import MeshContext

        with pytest.raises(ValueError, match="not both"):
            SGD(ctx=MeshContext(n_data=1), sharding=TrainSharding(1))

    def test_kmeans_fit_bit_stable(self, train_mesh):
        from flink_ml_tpu.api.dataframe import DataFrame
        from flink_ml_tpu.models.clustering.kmeans import KMeans

        rng = np.random.default_rng(7)
        pts = np.concatenate(
            [rng.normal(c, 0.5, (47, 3)) for c in (-2.0, 2.0)]
        )
        df = DataFrame.from_dict({"features": list(pts)})
        outs = {}
        for w in WIDTHS:
            train_mesh(w)
            model = KMeans().set_k(2).set_seed(5).set_max_iter(9).fit(df)
            outs[w] = (np.asarray(model.centroids), np.asarray(model.weights))
        for w in WIDTHS[1:]:
            np.testing.assert_array_equal(outs[w][0], outs[1][0])
            np.testing.assert_array_equal(outs[w][1], outs[1][1])

    def test_kmeans_fit_stream_bit_stable(self, train_mesh):
        from flink_ml_tpu.iteration.datacache import HostDataCache
        from flink_ml_tpu.models.clustering.kmeans import KMeans

        rng = np.random.default_rng(8)
        pts = np.concatenate(
            [rng.normal(c, 0.5, (61, 2)) for c in (-3.0, 0.0, 3.0)]
        ).astype(np.float32)

        def run(w):
            train_mesh(w)
            cache = HostDataCache()
            cache.append({"features": pts})
            cache.finish()
            model = (
                KMeans().set_k(3).set_seed(2).set_max_iter(7)
                .fit_stream(cache, chunk_rows=48)
            )
            return np.asarray(model.centroids), np.asarray(model.weights)

        outs = {w: run(w) for w in WIDTHS}
        for w in WIDTHS[1:]:
            np.testing.assert_array_equal(outs[w][0], outs[1][0])
            np.testing.assert_array_equal(outs[w][1], outs[1][1])

    def test_online_kmeans_bit_stable(self, train_mesh):
        from flink_ml_tpu.api.dataframe import DataFrame
        from flink_ml_tpu.models.clustering.online_kmeans import OnlineKMeans

        rng = np.random.default_rng(9)
        pts = rng.normal(size=(96, 2)).astype(np.float64)
        df = DataFrame.from_dict({"features": list(pts)})

        def run(w):
            train_mesh(w)
            model = (
                OnlineKMeans()
                .set_k(2)
                .set_seed(4)
                .set_global_batch_size(32)
                .set_decay_factor(0.6)
                .set_random_initial_model_data(2)
                .fit(df)
            )
            return np.asarray(model.centroids), np.asarray(model.weights)

        outs = {w: run(w) for w in WIDTHS}
        for w in WIDTHS[1:]:
            np.testing.assert_array_equal(outs[w][0], outs[1][0])
            np.testing.assert_array_equal(outs[w][1], outs[1][1])

    def test_mlp_trains_on_train_mesh(self, train_mesh):
        """MLP rides train.mesh as a topology knob (psum reduction — outside
        the bit-stability contract, but the fit must work and count)."""
        from flink_ml_tpu.api.dataframe import DataFrame
        from flink_ml_tpu.metrics import MLMetrics, metrics
        from flink_ml_tpu.models.classification.mlp_classifier import MLPClassifier

        train_mesh(2)
        rng = np.random.default_rng(11)
        X = rng.normal(size=(64, 3))
        y = (X.sum(axis=1) > 0).astype(np.float64)
        df = DataFrame.from_dict({"features": list(X), "label": y})
        before = metrics.get(MLMetrics.TRAIN_GROUP, MLMetrics.TRAIN_SHARDED_FITS) or 0
        model = (
            MLPClassifier()
            .set_hidden_layers(8)
            .set_max_iter(5)
            .set_seed(1)
            .fit(df)
        )
        assert model.params
        after = metrics.get(MLMetrics.TRAIN_GROUP, MLMetrics.TRAIN_SHARDED_FITS)
        assert after == before + 1


class TestShardedCheckpoint:
    def _state(self, ts):
        rng = np.random.default_rng(0)
        w = rng.normal(size=(16, 6)).astype(np.float32)
        return {
            "w": jax.device_put(w, ts.ctx.sharding(None, "model")),
            "cent": ts.replicate(rng.normal(size=(4, 3)).astype(np.float32)),
            "epoch": np.int64(7),
        }, w

    def test_round_trip_model_sharded_leaves(self, tmp_path):
        ts = TrainSharding(4, 2)
        state, w_host = self._state(ts)
        mgr = ShardedCheckpointManager(str(tmp_path), sharding=ts, fingerprint="fp")
        mgr.save(3, state)
        step, got = mgr.restore_latest()
        assert step == 3
        np.testing.assert_array_equal(np.asarray(got["w"]), w_host)
        np.testing.assert_array_equal(
            np.asarray(got["cent"]), np.asarray(state["cent"])
        )
        # per-shard pieces on disk, deduped to distinct shard indices
        import json

        meta = json.load(open(tmp_path / "ckpt-3" / "META.json"))
        descs = [d for d in meta["leaves"] if d is not None]
        assert len(descs) == 1 and len(descs[0]["pieces"]) == 2

    def test_mesh_mismatch_is_fatal(self, tmp_path):
        ts = TrainSharding(4, 2)
        state, _ = self._state(ts)
        ShardedCheckpointManager(
            str(tmp_path), sharding=ts, fingerprint="fp"
        ).save(1, state)
        other = ShardedCheckpointManager(
            str(tmp_path), sharding=(2, 4), fingerprint="fp"
        )
        with pytest.raises(MeshMismatchError):
            other.restore_latest()  # fatal, never quarantined

    def test_replicated_snapshot_restores_on_any_mesh(self, tmp_path):
        ts = TrainSharding(2)
        cent = np.arange(12, dtype=np.float32).reshape(4, 3)
        ShardedCheckpointManager(
            str(tmp_path), sharding=ts, fingerprint="fp"
        ).save(1, {"cent": ts.replicate(cent), "epoch": np.int64(2)})
        wider = ShardedCheckpointManager(
            str(tmp_path), sharding=(8, 1), fingerprint="fp"
        )
        step, got = wider.restore_latest()
        assert step == 1
        np.testing.assert_array_equal(np.asarray(got["cent"]), cent)

    def test_corrupt_piece_quarantines_and_falls_back(self, tmp_path):
        ts = TrainSharding(4, 2)
        state, _ = self._state(ts)
        mgr = ShardedCheckpointManager(str(tmp_path), sharding=ts, fingerprint="fp")
        mgr.save(1, state)
        mgr.save(2, state)
        npz = tmp_path / "ckpt-2" / "arrays.npz"
        raw = bytearray(npz.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        npz.write_bytes(bytes(raw))
        step, _ = mgr.restore_latest()
        assert step == 1
        assert (tmp_path / "ckpt-2.corrupt").exists()

    def test_reads_plain_format_snapshots(self, tmp_path):
        """A directory that started on the flat manager stays restorable."""
        plain = CheckpointManager(str(tmp_path), fingerprint="fp")
        plain.save(9, {"a": np.ones(3)})
        sharded = ShardedCheckpointManager(
            str(tmp_path), sharding=TrainSharding(2), fingerprint="fp"
        )
        step, got = sharded.restore_latest()
        assert step == 9
        np.testing.assert_array_equal(got["a"], np.ones(3))


class TestKillResume:
    def _supervisor(self, name):
        from flink_ml_tpu.execution import FixedDelayRestartStrategy, Supervisor

        return Supervisor(
            FixedDelayRestartStrategy(3, 0.0), name=name, sleep=lambda s: None
        )

    def _pts(self):
        rng = np.random.default_rng(13)
        return np.concatenate(
            [rng.normal(c, 0.5, (53, 2)) for c in (-3.0, 3.0)]
        ).astype(np.float32)

    def _fit(self, pts, mgr=None):
        from flink_ml_tpu.iteration.datacache import HostDataCache
        from flink_ml_tpu.models.clustering.kmeans import KMeans

        cache = HostDataCache()
        cache.append({"features": pts})
        cache.finish()
        kw = (
            {"checkpoint_manager": mgr, "checkpoint_interval": 1}
            if mgr is not None
            else {}
        )
        return (
            KMeans().set_k(2).set_seed(3).set_max_iter(8)
            .fit_stream(cache, chunk_rows=32, **kw)
        )

    def test_sharded_epoch_kill_and_resume(self, tmp_path, train_mesh):
        """A sharded fit killed mid-epoch resumes from the sharded-manager
        checkpoint in a supervised rerun and lands on the identical model."""
        from flink_ml_tpu.faults import faults

        train_mesh(2)
        pts = self._pts()
        clean = self._fit(pts)
        mgr = ShardedCheckpointManager(
            str(tmp_path / "ck"), sharding=TrainSharding(2)
        )
        faults.arm("iteration.epoch", at=5)
        try:
            sup = self._supervisor("sharded-km")
            model = sup.run(lambda: self._fit(pts, mgr))
        finally:
            faults.reset()
        assert sup.restarts == 1
        np.testing.assert_array_equal(model.centroids, clean.centroids)
        np.testing.assert_array_equal(model.weights, clean.weights)

    def test_kill_on_width_2_resume_on_width_4(self, tmp_path, train_mesh):
        """The tier fingerprint is width-invariant: a run killed at mesh=2
        restores its (replicated) snapshot at mesh=4 and — epochs being
        bit-identical across widths — lands on the identical model."""
        from flink_ml_tpu.faults import faults

        pts = self._pts()
        train_mesh(2)
        clean = self._fit(pts)
        mgr = ShardedCheckpointManager(str(tmp_path / "ck"))
        faults.arm("iteration.epoch", at=4)
        try:
            with pytest.raises(Exception):
                self._fit(pts, mgr)
        finally:
            faults.reset()
        assert mgr.all_steps()
        train_mesh(4)
        model = self._fit(pts, mgr)
        np.testing.assert_array_equal(model.centroids, clean.centroids)
        np.testing.assert_array_equal(model.weights, clean.weights)


class TestContinuousPublishFromShardedTrainer:
    def test_publish_zero_serving_path_work(self, tmp_path, train_mesh):
        """Tentpole (e): a sharded OnlineKMeans inside ContinuousTrainer
        publishes versions with ZERO serving-path compiles — the publish is
        host arrays out of mesh-resident state, never a serving-tier build.
        The publish telemetry carries the train-mesh provenance."""
        import flink_ml_tpu.telemetry as telemetry
        from flink_ml_tpu.loop import ContinuousTrainer
        from flink_ml_tpu.metrics import MLMetrics, metrics
        from flink_ml_tpu.models.clustering.online_kmeans import OnlineKMeans
        from flink_ml_tpu.models.online import QueueBatchStream

        train_mesh(4)
        rng = np.random.default_rng(21)
        stream = QueueBatchStream()
        for _ in range(4):
            stream.add({"features": rng.normal(size=(32, 2))})
        stream.close()

        est = (
            OnlineKMeans()
            .set_k(2)
            .set_seed(6)
            .set_global_batch_size(32)
            .set_random_initial_model_data(2)
        )
        scope = f"{MLMetrics.LOOP_GROUP}[sharded-pub]"
        trainer = ContinuousTrainer(
            est, stream, str(tmp_path / "pub"),
            publish_every_versions=2, scope=scope,
        )
        compiles_before = metrics.get(
            MLMetrics.SERVING_GROUP, MLMetrics.SERVING_FASTPATH_COMPILES, 0
        )
        rec = telemetry.configure(str(tmp_path / "journal"))
        try:
            trainer.start()
            trained, published = trainer.process()
            rec.flush(10.0)
            records = telemetry.read_journal(str(tmp_path / "journal"))
        finally:
            telemetry.configure(None)
        assert trained == 4 and published == [2, 4]
        compiles_after = metrics.get(
            MLMetrics.SERVING_GROUP, MLMetrics.SERVING_FASTPATH_COMPILES, 0
        )
        assert compiles_after == compiles_before
        publishes = [r for r in records if r["kind"] == "loop.publish"]
        assert publishes and all(
            r["data"]["train_mesh"] == 4 for r in publishes
        )
