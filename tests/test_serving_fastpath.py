"""Serving fast path (serving/plan.py) — the compiled-plan contract:

- **bit-exact fusion**: a pure pipeline's fused per-bucket executable produces
  results bit-identical to the per-stage ``transform`` chain, for depth-1,
  multi-stage, and mixed (fallback) pipelines, and across a hot swap;
- **zero hot-path cost**: after warmup the serving path never XLA-compiles
  and never ``device_put``s model arrays — weights are committed device
  buffers from publish time;
- **per-batch fallback**: a batch the compiled signature cannot take (sparse
  features) silently serves through the per-stage path, bit-exactly, and is
  counted;
- **pipelined dispatch**: a two-deep dispatch window returns the same results
  as strict sequential execution under concurrent load.
"""
import threading
import time

import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.linalg.vectors import SparseVector
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.servable import (
    KMeansModelServable,
    LogisticRegressionModelServable,
    PipelineModelServable,
    StandardScalerModelServable,
)
from flink_ml_tpu.servable.api import TransformerServable
from flink_ml_tpu.serving import (
    CompiledServingPlan,
    InferenceServer,
    ServingConfig,
    pad_to,
    power_of_two_buckets,
)

RNG = np.random.default_rng(23)
DIM = 6  # distinctive width so jit-cache assertions don't collide with other tests


def _scaler(seed=0, dim=DIM):
    rng = np.random.default_rng(seed)
    sc = StandardScalerModelServable().set_input_col("features").set_output_col("scaled")
    sc.mean = rng.normal(size=dim)
    sc.std = np.abs(rng.normal(size=dim)) + 0.5
    sc.std[1] = 0.0  # exercise the zero-std guard in both paths
    sc.set_with_mean(True)
    return sc


def _lr(seed=1, features_col="scaled", dim=DIM):
    rng = np.random.default_rng(seed)
    lr = LogisticRegressionModelServable().set_features_col(features_col)
    lr.coefficient = rng.normal(size=dim)
    return lr


def _kmeans(seed=2, features_col="scaled", dim=DIM):
    rng = np.random.default_rng(seed)
    km = KMeansModelServable().set_features_col(features_col).set_prediction_col("cluster")
    km.centroids = rng.normal(size=(3, dim))
    km.weights = np.ones(3)
    return km


class _Echo(TransformerServable):
    """Spec-less stage — forces a fallback segment in mixed pipelines."""

    def transform(self, df):
        return df.clone()


def _features(n, seed=3):
    return DataFrame.from_dict(
        {"features": np.random.default_rng(seed).normal(size=(n, DIM))}
    )


def _assert_frames_bitexact(a: DataFrame, b: DataFrame):
    assert a.get_column_names() == b.get_column_names()
    for name in a.get_column_names():
        ca, cb = np.asarray(a[name]), np.asarray(b[name])
        assert ca.dtype == cb.dtype, name
        np.testing.assert_array_equal(ca, cb, err_msg=name)


# ---------------------------------------------------------------------------
# plan-level parity
# ---------------------------------------------------------------------------
class TestPlanParity:
    BUCKETS = power_of_two_buckets(16)

    def _check(self, servable, df):
        plan = CompiledServingPlan.build(servable, scope="ml.serving[t-parity]")
        assert plan is not None
        plan.warmup(df.take([0]), self.BUCKETS)
        for bucket in self.BUCKETS:
            padded = pad_to(df, bucket) if bucket >= len(df) else df.take(
                np.arange(bucket)
            )
            _assert_frames_bitexact(servable.transform(padded), plan.execute(padded))
        return plan

    def test_depth1_pipelines_each_servable(self):
        df = _features(8)
        self._check(_scaler(), df)
        self._check(_lr(features_col="features"), df)
        self._check(_kmeans(features_col="features"), df)

    def test_pure_pipeline_fuses_to_one_segment(self):
        pipe = PipelineModelServable([_scaler(), _lr(), _kmeans()])
        df = _features(8)
        plan = self._check(pipe, df)
        assert len(plan.segments) == 1  # all three stages in ONE executable chain
        assert metrics.get("ml.serving[t-parity]", MLMetrics.SERVING_FUSED_STAGES) == 3

    @pytest.mark.parametrize("dim", [8, 16, 256])
    def test_parity_at_reduction_sensitive_widths(self, dim):
        """Regression for the whole-chain-program design: at widths >= 8 XLA
        fuses a scaler's elementwise math into a following dot reduction and
        moves the margin by 100s of ulps. The per-stage executable chain must
        stay bit-exact at exactly those widths."""
        pipe = PipelineModelServable(
            [_scaler(dim=dim), _lr(dim=dim), _kmeans(dim=dim)]
        )
        df = DataFrame.from_dict(
            {"features": np.random.default_rng(dim).normal(size=(16, dim))}
        )
        plan = CompiledServingPlan.build(pipe, scope=f"ml.serving[t-ulp{dim}]")
        plan.warmup(df.take([0]), (4, 16))
        for bucket in (4, 16):
            padded = df.take(np.arange(bucket))
            _assert_frames_bitexact(pipe.transform(padded), plan.execute(padded))

    def test_mixed_pipeline_falls_back_per_stage(self):
        pipe = PipelineModelServable([_scaler(), _Echo(), _lr()])
        df = _features(8)
        plan = self._check(pipe, df)
        assert len(plan.segments) == 3  # fused / fallback / fused
        scope = "ml.serving[t-parity]"
        assert metrics.get(scope, MLMetrics.SERVING_FUSED_STAGES) == 2
        assert metrics.get(scope, MLMetrics.SERVING_FALLBACK_STAGES) == 1

    def test_speclss_servable_builds_no_plan(self):
        assert CompiledServingPlan.build(_Echo()) is None
        assert CompiledServingPlan.build(PipelineModelServable([_Echo(), _Echo()])) is None

    def test_sparse_batch_falls_back_bitexact(self):
        lr = _lr(features_col="features")
        plan = CompiledServingPlan.build(lr, scope="ml.serving[t-sparse]")
        dense_template = _features(1)
        plan.warmup(dense_template, (1, 4))
        before = metrics.get("ml.serving[t-sparse]", MLMetrics.SERVING_FALLBACK_BATCHES) or 0
        sparse_df = DataFrame.from_dict(
            {"features": [SparseVector(DIM, [0, 3], [1.5, -2.0]) for _ in range(4)]}
        )
        _assert_frames_bitexact(lr.transform(sparse_df), plan.execute(sparse_df))
        after = metrics.get("ml.serving[t-sparse]", MLMetrics.SERVING_FALLBACK_BATCHES)
        assert after == before + 1

    def test_sparse_warmup_template_still_swaps_and_serves(self):
        """A sparse features template must not poison warmup/swap: the fused
        segment warms through the per-stage path and traffic serves via the
        counted per-batch fallback — PR 2's sparse serving keeps working."""
        lr = _lr(features_col="features")
        ref = _lr(features_col="features")
        row = [SparseVector(DIM, [1, 4], [0.5, 2.0])]
        template = DataFrame.from_dict({"features": row})
        cfg = ServingConfig(max_batch_size=4, max_delay_ms=0.0)
        with InferenceServer(lr, name="t-sparse-warm", serving_config=cfg,
                             warmup_template=template) as server:
            resp = server.predict(DataFrame.from_dict({"features": row * 2}))
            expected = ref.transform(
                pad_to(DataFrame.from_dict({"features": row * 2}), resp.bucket)
            ).take([0, 1])
            _assert_frames_bitexact(resp.dataframe, expected)

    def test_warmup_compiles_every_bucket_and_reports(self):
        pipe = PipelineModelServable([_scaler(), _lr()])
        plan = CompiledServingPlan.build(pipe, scope="ml.serving[t-warm]")
        plan.warmup(_features(1), self.BUCKETS)
        seg = plan.segments[0]
        assert set(seg.compiled) == set(self.BUCKETS)
        assert metrics.get("ml.serving[t-warm]", MLMetrics.SERVING_WARMUP_COMPILE_MS) > 0


# ---------------------------------------------------------------------------
# server-level: the zero-cost hot path
# ---------------------------------------------------------------------------
class TestHotPathIsCold:
    def test_zero_compiles_and_zero_weight_uploads_after_warmup(self, monkeypatch):
        """After warmup the fast path must never trace/compile an executable
        nor device_put weights: compiles are blocked outright and
        ``jax.device_put`` is poisoned for the whole traffic phase."""
        import jax

        pipe = PipelineModelServable([_scaler(), _lr()])
        ref = PipelineModelServable([_scaler(), _lr()])  # untouched reference
        cfg = ServingConfig(max_batch_size=16, max_delay_ms=0.0, queue_capacity_rows=256)
        X = np.asarray(_features(16)["features"])
        with InferenceServer(
            pipe, name="t-cold", serving_config=cfg,
            warmup_template=_features(1),
        ) as server:
            plan = pipe._fastpath_plan
            assert plan is not None

            def no_compile(*a, **k):
                raise AssertionError("XLA compile on the hot path after warmup")

            for segment in plan.segments:
                for prog in segment.programs:
                    monkeypatch.setattr(prog.jitted, "lower", no_compile, raising=False)

            def no_device_put(*a, **k):
                raise AssertionError("device_put on the hot path after warmup")

            monkeypatch.setattr(jax, "device_put", no_device_put)

            for n in list(range(1, 17)) + list(range(1, 17)):
                df = DataFrame.from_dict({"features": X[:n]})
                resp = server.predict(df)
                expected = ref.transform(pad_to(df, resp.bucket)).take(
                    np.arange(n)
                )
                _assert_frames_bitexact(resp.dataframe, expected)
            scope = server.scope
        assert not metrics.get(scope, MLMetrics.SERVING_FASTPATH_COMPILES)
        assert metrics.get(scope, MLMetrics.SERVING_FUSED_BATCHES) >= 32

    def test_fastpath_off_serves_identically(self):
        pipe = PipelineModelServable([_scaler(), _lr()])
        df = _features(5)
        cfg_off = ServingConfig(max_batch_size=8, max_delay_ms=0.0, fastpath=False)
        cfg_on = ServingConfig(max_batch_size=8, max_delay_ms=0.0, fastpath=True)
        with InferenceServer(pipe, name="t-off", serving_config=cfg_off,
                             warmup_template=df.take([0])) as off:
            resp_off = off.predict(df)
        with InferenceServer(pipe, name="t-on", serving_config=cfg_on,
                             warmup_template=df.take([0])) as on:
            resp_on = on.predict(df)
        assert resp_off.bucket == resp_on.bucket
        _assert_frames_bitexact(resp_off.dataframe, resp_on.dataframe)


# ---------------------------------------------------------------------------
# pipelined dispatch window
# ---------------------------------------------------------------------------
class TestPipelinedDispatch:
    @pytest.mark.parametrize("depth", [1, 2, 3])
    def test_depth_sweep_same_results(self, depth):
        pipe = PipelineModelServable([_scaler(), _lr()])
        ref = PipelineModelServable([_scaler(), _lr()])
        cfg = ServingConfig(
            max_batch_size=8, max_delay_ms=1.0, queue_capacity_rows=1024,
            pipeline_depth=depth, default_timeout_ms=60_000,
        )
        X = np.asarray(_features(64, seed=depth)["features"])
        results = {}
        errors = []
        with InferenceServer(pipe, name=f"t-depth{depth}", serving_config=cfg,
                             warmup_template=_features(1)) as server:

            def client(tid):
                try:
                    for i in range(24):
                        j = (tid * 17 + i * 5) % X.shape[0]
                        results[(tid, i)] = (j, server.predict(
                            DataFrame.from_dict({"features": X[j : j + 1]})
                        ))
                except BaseException as e:  # noqa: BLE001 — surfaced below
                    errors.append(e)

            threads = [threading.Thread(target=client, args=(t,)) for t in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert not errors, errors
        assert len(results) == 96
        for j, resp in results.values():
            expected = ref.transform(
                pad_to(DataFrame.from_dict({"features": X[j : j + 1]}), resp.bucket)
            ).take([0])
            _assert_frames_bitexact(resp.dataframe, expected)

    def test_inflight_gauge_drains_to_zero(self):
        pipe = PipelineModelServable([_scaler(), _lr()])
        cfg = ServingConfig(max_batch_size=4, max_delay_ms=0.0, pipeline_depth=2)
        with InferenceServer(pipe, name="t-inflight", serving_config=cfg,
                             warmup_template=_features(1)) as server:
            for _ in range(8):
                server.predict(_features(2))
            scope = server.scope
        assert metrics.get(scope, MLMetrics.SERVING_INFLIGHT_DEPTH) == 0


# ---------------------------------------------------------------------------
# publish → serve for whole trained pipelines
# ---------------------------------------------------------------------------
class TestPublishedPipelineServes:
    def test_trained_pipeline_publishes_loads_and_fuses(self, tmp_path):
        """``publish_servable(pipeline_model, dir)`` must round-trip into a
        servable pipeline (PipelineModel.load_servable) whose kernel-spec
        stages fuse on the fast path."""
        from flink_ml_tpu.builder.pipeline import Pipeline
        from flink_ml_tpu.models.classification.logistic_regression import (
            LogisticRegression,
        )
        from flink_ml_tpu.models.feature.standard_scaler import StandardScaler
        from flink_ml_tpu.serving import publish_servable

        rng = np.random.default_rng(4)
        X = rng.normal(size=(64, DIM))
        y = (X @ np.ones(DIM) > 0).astype(np.float64)
        train = DataFrame.from_dict({"features": X, "label": y})
        model = Pipeline(
            [
                StandardScaler().set_input_col("features").set_output_col("scaled"),
                LogisticRegression()
                .set_features_col("scaled")
                .set_max_iter(3)
                .set_global_batch_size(64),
            ]
        ).fit(train)
        d = str(tmp_path / "models")
        publish_servable(model, d)
        with InferenceServer(name="t-pub-pipe",
                             warmup_template=DataFrame.from_dict({"features": X[:1]})
                             ) as server:
            poller = server.attach_poller(d, start=False)
            assert poller.poll_once() == 1, poller.failed
            resp = server.predict(DataFrame.from_dict({"features": X[:2]}))
            served = PipelineModelServable.load(f"{d}/v-1")
            assert isinstance(served, PipelineModelServable)
            expected = served.transform(
                pad_to(DataFrame.from_dict({"features": X[:2]}), resp.bucket)
            ).take([0, 1])
            _assert_frames_bitexact(resp.dataframe, expected)
            assert metrics.get(server.scope, MLMetrics.SERVING_FUSED_STAGES) == 2


# ---------------------------------------------------------------------------
# hot swap mid-traffic against the fused path
# ---------------------------------------------------------------------------
class TestFusedHotSwapSoak:
    N_THREADS = 6
    REQUESTS_PER_THREAD = 30

    def test_fused_soak_with_hot_swap(self):
        pipe_v1 = PipelineModelServable([_scaler(seed=10), _lr(seed=11)])
        pipe_v2 = PipelineModelServable([_scaler(seed=20), _lr(seed=21)])
        refs = {
            1: PipelineModelServable([_scaler(seed=10), _lr(seed=11)]),
            2: PipelineModelServable([_scaler(seed=20), _lr(seed=21)]),
        }
        X = np.asarray(_features(64, seed=9)["features"])
        cfg = ServingConfig(
            max_batch_size=16, max_delay_ms=2.0, queue_capacity_rows=4096,
            default_timeout_ms=60_000, pipeline_depth=2,
        )
        server = InferenceServer(pipe_v1, name="t-fused-soak", serving_config=cfg,
                                 warmup_template=_features(1))
        responses = {}
        errors = []
        started = threading.Barrier(self.N_THREADS + 1)

        def client(tid):
            try:
                started.wait()
                for i in range(self.REQUESTS_PER_THREAD):
                    j = (tid * 37 + i * 13) % X.shape[0]
                    responses[(tid, i)] = (j, server.predict(
                        DataFrame.from_dict({"features": X[j : j + 1]})
                    ))
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=client, args=(t,)) for t in range(self.N_THREADS)]
        try:
            for t in threads:
                t.start()
            started.wait()
            deadline = time.perf_counter() + 30.0
            while len(responses) < self.N_THREADS and time.perf_counter() < deadline:
                time.sleep(0.001)
            server.swap(2, pipe_v2)  # warms + AOT-compiles, then flips
            for k in range(4):
                j = (k * 31) % X.shape[0]
                responses[("post-swap", k)] = (j, server.predict(
                    DataFrame.from_dict({"features": X[j : j + 1]})
                ))
                assert responses[("post-swap", k)][1].model_version == 2
            for t in threads:
                t.join()
        finally:
            server.close()
        assert not errors, errors
        assert len(responses) == self.N_THREADS * self.REQUESTS_PER_THREAD + 4
        versions = {r.model_version for _, r in responses.values()}
        assert versions == {1, 2}
        for tid in range(self.N_THREADS):
            seen = [responses[(tid, i)][1].model_version
                    for i in range(self.REQUESTS_PER_THREAD)]
            assert seen == sorted(seen)
        # bit-exact against the matching version's PER-STAGE transform at the
        # response's bucket — the fused/hot-swap parity contract
        for j, resp in responses.values():
            expected = refs[resp.model_version].transform(
                pad_to(DataFrame.from_dict({"features": X[j : j + 1]}), resp.bucket)
            ).take([0])
            _assert_frames_bitexact(resp.dataframe, expected)
