"""Tests for NaiveBayes, Knn, BinaryClassificationEvaluator, stats tests, Swing,
AgglomerativeClustering (reference test shape per SURVEY.md §4)."""
import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.classification.knn import Knn, KnnModel
from flink_ml_tpu.models.classification.naive_bayes import NaiveBayes, NaiveBayesModel
from flink_ml_tpu.models.clustering.agglomerative_clustering import AgglomerativeClustering
from flink_ml_tpu.models.evaluation.binary_classification_evaluator import (
    BinaryClassificationEvaluator,
)
from flink_ml_tpu.models.recommendation.swing import Swing
from flink_ml_tpu.models.stats.tests import ANOVATest, ChiSqTest, FValueTest

RNG = np.random.default_rng(55)


class TestNaiveBayes:
    def _df(self):
        X = np.asarray(
            [[0.0, 0.0], [0.0, 1.0], [1.0, 0.0], [1.0, 1.0], [1.0, 1.0], [1.0, 0.0]]
        )
        y = np.asarray([0.0, 0.0, 1.0, 1.0, 1.0, 1.0])
        return DataFrame.from_dict({"features": X, "label": y}), X, y

    def test_defaults(self):
        nb = NaiveBayes()
        assert nb.get_smoothing() == 1.0
        assert nb.get_model_type() == "multinomial"

    def test_fit_predict_training_data(self):
        df, X, y = self._df()
        model = NaiveBayes().fit(df)
        pred = model.transform(df)["prediction"]
        assert (pred == y).mean() >= 5 / 6  # overlapping row [0,1]/[1,1] may flip

    def test_pi_formula(self):
        df, X, y = self._df()
        model = NaiveBayes().set_smoothing(1.0).fit(df)
        n, d, L = 6, 2, 2
        pi_log = np.log(n * d + L * 1.0)
        np.testing.assert_allclose(
            model.pi, [np.log(2 * d + 1) - pi_log, np.log(4 * d + 1) - pi_log]
        )

    def test_save_load(self, tmp_path):
        df, X, y = self._df()
        model = NaiveBayes().fit(df)
        model.save(str(tmp_path / "nb"))
        loaded = NaiveBayesModel.load(str(tmp_path / "nb"))
        np.testing.assert_array_equal(
            loaded.transform(df)["prediction"], model.transform(df)["prediction"]
        )

    def test_set_model_data_unseen_value_floor(self):
        # default_log must ride through get/set_model_data so a model built via
        # set_model_data scores unseen feature values exactly like fit/save-load.
        df, X, y = self._df()
        model = NaiveBayes().fit(df)
        (md,) = model.get_model_data()
        fresh = NaiveBayesModel()
        for p in model.get_param_map():
            fresh.set(p, model.get(p))
        fresh.set_model_data(md)
        np.testing.assert_allclose(fresh.default_log, model.default_log)
        unseen = DataFrame.from_dict({"features": np.asarray([[7.0, 9.0]])})
        np.testing.assert_array_equal(
            fresh.transform(unseen)["prediction"], model.transform(unseen)["prediction"]
        )

    def test_non_integer_label_rejected(self):
        df = DataFrame.from_dict(
            {"features": np.zeros((2, 2)), "label": np.asarray([0.5, 1.0])}
        )
        with pytest.raises(ValueError, match="indexed number"):
            NaiveBayes().fit(df)


class TestKnn:
    def test_fit_predict(self):
        X = np.concatenate([RNG.normal(0, 0.3, (30, 2)), RNG.normal(5, 0.3, (30, 2))])
        y = np.concatenate([np.zeros(30), np.ones(30)])
        df = DataFrame.from_dict({"features": X, "label": y})
        model = Knn().fit(df)
        assert model.get_k() == 5
        pred = model.transform(df)["prediction"]
        np.testing.assert_array_equal(pred, y)
        # far-away query follows its blob
        q = DataFrame.from_dict({"features": np.asarray([[5.2, 4.9]])})
        assert model.transform(q)["prediction"][0] == 1.0

    def test_save_load(self, tmp_path):
        X = RNG.normal(size=(10, 2))
        y = (np.arange(10) % 2).astype(np.float64)
        model = Knn().set_k(3).fit(DataFrame.from_dict({"features": X, "label": y}))
        model.save(str(tmp_path / "knn"))
        loaded = KnnModel.load(str(tmp_path / "knn"))
        df = DataFrame.from_dict({"features": X})
        np.testing.assert_array_equal(
            loaded.transform(df)["prediction"], model.transform(df)["prediction"]
        )


class TestBinaryClassificationEvaluator:
    def test_perfect_classifier(self):
        y = np.asarray([0.0, 0.0, 1.0, 1.0])
        raw = np.asarray([[0.9, 0.1], [0.8, 0.2], [0.2, 0.8], [0.1, 0.9]])
        df = DataFrame.from_dict({"label": y, "rawPrediction": raw})
        out = BinaryClassificationEvaluator().set_metrics_names(
            "areaUnderROC", "areaUnderPR", "ks"
        ).transform(df)
        assert out["areaUnderROC"][0] == 1.0
        assert out["areaUnderPR"][0] == 1.0
        assert out["ks"][0] == 1.0

    def test_random_scores_auc_half(self):
        n = 4000
        y = (RNG.random(n) > 0.5).astype(np.float64)
        scores = RNG.random(n)
        df = DataFrame.from_dict({"label": y, "rawPrediction": scores})
        out = BinaryClassificationEvaluator().transform(df)
        assert abs(out["areaUnderROC"][0] - 0.5) < 0.05

    def test_known_auc(self):
        """Hand-computable: scores [.1 .4 .35 .8], labels [0 0 1 1] → AUC 0.75."""
        df = DataFrame.from_dict(
            {
                "label": np.asarray([0.0, 0.0, 1.0, 1.0]),
                "rawPrediction": np.asarray([0.1, 0.4, 0.35, 0.8]),
            }
        )
        out = BinaryClassificationEvaluator().transform(df)
        np.testing.assert_allclose(out["areaUnderROC"][0], 0.75)

    def test_single_class_rejected(self):
        df = DataFrame.from_dict(
            {"label": np.ones(4), "rawPrediction": RNG.random(4)}
        )
        with pytest.raises(ValueError):
            BinaryClassificationEvaluator().transform(df)


class TestStatsTests:
    def test_chi_sq_independent_and_dependent(self):
        n = 300
        label = RNG.integers(0, 2, n).astype(np.float64)
        dependent = label.copy()  # perfectly dependent
        independent = RNG.integers(0, 2, n).astype(np.float64)
        df = DataFrame.from_dict(
            {"features": np.column_stack([dependent, independent]), "label": label}
        )
        out = ChiSqTest().transform(df)
        p = np.asarray(out["pValues"][0])
        assert p[0] < 1e-6 and p[1] > 0.01
        flat = ChiSqTest().set_flatten(True).transform(df)
        assert flat.get_column_names() == [
            "featureIndex",
            "pValue",
            "degreeOfFreedom",
            "statistic",
        ]
        assert len(flat) == 2

    def test_anova_test(self):
        n = 150
        label = RNG.integers(0, 3, n).astype(np.float64)
        informative = label * 2 + RNG.normal(0, 0.1, n)
        noise = RNG.normal(size=n)
        df = DataFrame.from_dict(
            {"features": np.column_stack([informative, noise]), "label": label}
        )
        out = ANOVATest().transform(df)
        p = np.asarray(out["pValues"][0])
        assert p[0] < 1e-8 and p[1] > 0.01
        assert out["degreesOfFreedom"][0][0] == n - 1  # dfBetween + dfWithin

    def test_fvalue_test(self):
        n = 200
        y = RNG.normal(size=n)
        informative = y * 3 + RNG.normal(0, 0.1, n)
        noise = RNG.normal(size=n)
        df = DataFrame.from_dict(
            {"features": np.column_stack([informative, noise]), "label": y}
        )
        out = FValueTest().transform(df)
        p = np.asarray(out["pValues"][0])
        assert p[0] < 1e-8 and p[1] > 0.01


class TestSwing:
    def test_similarity_output(self):
        # users 0..5 all buy items 10 and 11 → strong 10↔11 similarity
        users, items = [], []
        for u in range(6):
            for i in (10, 11):
                users.append(u)
                items.append(i)
        # one extra item bought by user 0 only
        users.append(0)
        items.append(12)
        df = DataFrame.from_dict(
            {"user": np.asarray(users, np.int64), "item": np.asarray(items, np.int64)}
        )
        swing = Swing().set_min_user_behavior(1).set_max_user_behavior(10)
        out = swing.transform(df)
        by_item = dict(zip(out["item"], out["output"]))
        assert 10 in by_item and 11 in by_item
        top10 = by_item[10].split(";")[0]
        assert top10.split(",")[0] == "11"
        # output format "item,score"
        float(top10.split(",")[1])

    def test_behavior_bounds_filtering(self):
        df = DataFrame.from_dict(
            {
                "user": np.asarray([0, 0, 1], np.int64),
                "item": np.asarray([1, 2, 1], np.int64),
            }
        )
        # minUserBehavior=2 drops user 1; no co-purchases remain → empty output
        out = Swing().set_min_user_behavior(2).transform(df)
        assert len(out) <= 2

    def test_invalid_bounds(self):
        with pytest.raises(ValueError, match="maxUserBehavior"):
            Swing().set_min_user_behavior(5).set_max_user_behavior(2).transform(
                DataFrame.from_dict(
                    {"user": np.asarray([0], np.int64), "item": np.asarray([1], np.int64)}
                )
            )

    def test_encode_topk_matches_f_string_loop(self):
        from flink_ml_tpu.models.recommendation.swing import encode_topk

        rng = np.random.default_rng(5)
        I, k = 200, 8
        i_ids = rng.choice(10_000, I, replace=False).astype(np.int64)
        vals = np.round(rng.random((I, k)) - 0.3, 6)  # some rows all-negative
        vals[vals < 0] = 0.0
        inds = rng.integers(0, I, size=(I, k))
        items, strs = encode_topk(i_ids, vals, inds)
        want_items, want_strs = [], []
        for i in range(I):
            pos = vals[i] > 0.0
            if not np.any(pos):
                continue
            want_items.append(int(i_ids[i]))
            want_strs.append(
                ";".join(
                    f"{int(i_ids[j])},{s}" for j, s in zip(inds[i][pos], vals[i][pos])
                )
            )
        np.testing.assert_array_equal(items, want_items)
        assert strs == want_strs

    def test_encode_topk_million_items_within_budget(self):
        import time

        from flink_ml_tpu.models.recommendation.swing import encode_topk

        rng = np.random.default_rng(6)
        I, k = 1_000_000, 10
        i_ids = np.arange(I, dtype=np.int64)
        vals = rng.random((I, k))
        inds = rng.integers(0, I, size=(I, k))
        t0 = time.perf_counter()
        items, strs = encode_topk(i_ids, vals, inds)
        elapsed = time.perf_counter() - t0
        assert len(items) == I and len(strs) == I
        # numpy string kernels: ~35s unloaded on the 1-core box (the f-string
        # loop was many minutes); ceiling leaves room for shared-box load
        assert elapsed < 120.0, f"1M-item encode took {elapsed:.1f}s"

    @staticmethod
    def _brute_force_scores(users, items, min_b, max_b, alpha1, alpha2, beta):
        """The Swing.java pair loops, literally (the semantics the device
        matmul formulation must reproduce)."""
        user_items = {}
        for u in np.unique(users):
            its = np.unique(items[users == u])
            if min_b <= len(its) <= max_b:
                user_items[int(u)] = its
        weights = {u: 1.0 / (alpha1 + len(its)) ** beta for u, its in user_items.items()}
        item_users = {}
        for u, its in user_items.items():
            for i in its:
                item_users.setdefault(int(i), []).append(u)
        all_scores = {}
        for item, purchasers in item_users.items():
            scores = {}
            for a in range(len(purchasers)):
                for b in range(a + 1, len(purchasers)):
                    u, v = purchasers[a], purchasers[b]
                    common = np.intersect1d(user_items[u], user_items[v], assume_unique=True)
                    if len(common) == 0:
                        continue
                    sim = weights[u] * weights[v] / (alpha2 + len(common))
                    for j in common:
                        if int(j) != item:
                            scores[int(j)] = scores.get(int(j), 0.0) + sim
            if scores:
                all_scores[item] = scores
        return all_scores

    def test_device_scores_match_pair_loops(self):
        rng = np.random.default_rng(17)
        n = 400
        users = rng.integers(0, 25, n).astype(np.int64)
        items = rng.integers(0, 12, n).astype(np.int64)
        args = dict(min_b=2, max_b=50, alpha1=15, alpha2=0, beta=0.3)
        want = self._brute_force_scores(users, items, **args)
        out = (
            Swing()
            .set_min_user_behavior(2)
            .set_max_user_behavior(50)
            .set_k(12)
            .transform(DataFrame.from_dict({"user": users, "item": items}))
        )
        got = {}
        for item, s in zip(out["item"], out["output"]):
            got[int(item)] = {
                int(t.split(",")[0]): float(t.split(",")[1]) for t in s.split(";")
            }
        assert set(got) == set(want)
        for item in want:
            assert set(got[item]) == set(want[item])
            for j, score in want[item].items():
                np.testing.assert_allclose(got[item][j], score, rtol=1e-5)

    def test_scale_1m_interactions(self):
        # 1M interactions through the fully-vectorized host prep (sorted-rank
        # ELL build + one-sort cap sampling — no per-user/per-item Python
        # loops) with an active purchaser cap, under a wall-clock budget.
        import time

        rng = np.random.default_rng(3)
        n = 1_000_000
        users = rng.integers(0, 20_000, n).astype(np.int64)
        items = rng.integers(0, 2_000, n).astype(np.int64)
        df = DataFrame.from_dict({"user": users, "item": items})
        t0 = time.perf_counter()
        out = (
            Swing()
            .set_min_user_behavior(1)
            .set_max_user_behavior(20_000)
            .set_max_user_num_per_item(64)  # the cap path, at scale
            .set_k(10)
            .transform(df)
        )
        elapsed = time.perf_counter() - t0
        assert len(out) == 2_000, "every item should have scored neighbors at this density"
        assert elapsed < 60, f"1M-interaction Swing took {elapsed:.1f}s"
        top = out["output"][0].split(";")
        assert len(top) == 10 and all("," in t for t in top)


class TestAgglomerativeClustering:
    def _blobs(self):
        return np.concatenate(
            [RNG.normal(0, 0.2, (15, 2)), RNG.normal(6, 0.2, (15, 2))]
        )

    @pytest.mark.parametrize("linkage", ["ward", "complete", "average", "single"])
    def test_two_blobs(self, linkage):
        X = self._blobs()
        df = DataFrame.from_dict({"features": X})
        ac = AgglomerativeClustering().set_linkage(linkage)
        out, merges = ac.transform(df)
        pred = out["prediction"]
        assert len(set(pred[:15])) == 1 and len(set(pred[15:])) == 1
        assert pred[0] != pred[-1]

    def test_distance_threshold(self):
        X = self._blobs()
        df = DataFrame.from_dict({"features": X})
        ac = (
            AgglomerativeClustering()
            .set_num_clusters(None)
            .set_distance_threshold(3.0)
            .set_linkage("single")
        )
        out, merges = ac.transform(df)
        assert len(set(out["prediction"])) == 2

    def test_full_tree_merges(self):
        X = self._blobs()
        df = DataFrame.from_dict({"features": X})
        ac = AgglomerativeClustering().set_compute_full_tree(True)
        out, merges = ac.transform(df)
        assert len(merges) == len(X) - 1  # full dendrogram
        assert merges["sizeOfMergedCluster"][-1] == len(X)

    def test_mutually_exclusive_params(self):
        df = DataFrame.from_dict({"features": self._blobs()})
        with pytest.raises(ValueError, match="Exactly one"):
            AgglomerativeClustering().set_distance_threshold(1.0).transform(df)


def test_evaluator_empty_input_raises():
    df = DataFrame.from_dict({"label": np.empty(0), "rawPrediction": np.empty(0)})
    with pytest.raises(ValueError, match="positive and negative"):
        BinaryClassificationEvaluator().transform(df)


class TestKnnBlockwise:
    """Streaming top-k over reference blocks must agree with the full
    [q, m] distance-matrix kernel (which it replaces past _BLOCK_ROWS)."""

    def test_blockwise_matches_full(self, monkeypatch):
        from flink_ml_tpu.models.classification import knn as knn_mod
        from flink_ml_tpu.models.classification.knn import Knn, KnnModel

        rng = np.random.default_rng(5)
        mx = rng.normal(size=(1000, 4)).astype(np.float32)
        my = rng.integers(0, 3, 1000).astype(np.float64)
        q = rng.normal(size=(64, 4)).astype(np.float32)
        df_train = DataFrame.from_dict({"features": mx, "label": my})
        df_q = DataFrame.from_dict({"features": q})

        model = Knn().set_k(7).fit(df_train)
        want = model.transform(df_q)["prediction"]
        monkeypatch.setattr(knn_mod, "_BLOCK_ROWS", 128)  # 1000 rows -> 8 blocks + pad
        got = model.transform(df_q)["prediction"]
        np.testing.assert_array_equal(got, want)

    def test_blockwise_index_parity(self, monkeypatch):
        from flink_ml_tpu.models.classification import knn as knn_mod

        rng = np.random.default_rng(6)
        mx = rng.normal(size=(300, 3)).astype(np.float32)
        q = rng.normal(size=(20, 3)).astype(np.float32)
        full = knn_mod._nearest_indices(q, mx, 5)
        monkeypatch.setattr(knn_mod, "_BLOCK_ROWS", 64)
        blocked = knn_mod._nearest_indices(q, mx, 5)
        # same neighbor sets (order may differ on exact distance ties)
        for a, b in zip(full, blocked):
            assert set(a.tolist()) == set(b.tolist())


class TestEvaluatorStream:
    def test_streamed_auc_identical_to_in_ram(self, tmp_path):
        # The north-star contract: metrics from the out-of-core path (tiny
        # memory budget, many sort buckets, spilled inputs) match transform's
        # in-RAM result on the same rows.
        from flink_ml_tpu.iteration import HostDataCache
        from flink_ml_tpu.models.evaluation.binary_classification_evaluator import (
            BinaryClassificationEvaluator,
        )

        rng = np.random.default_rng(11)
        n = 30_000
        y = (rng.random(n) > 0.5).astype(np.float64)
        # correlated scores with deliberate ties (quantized)
        scores = np.round((y * 0.6 + rng.random(n)) * 50) / 50
        w = rng.random(n) + 0.5

        ev = BinaryClassificationEvaluator().set_weight_col("weight").set_metrics_names(
            "areaUnderROC", "areaUnderPR", "ks", "areaUnderLorenz"
        )
        want = ev.transform(
            DataFrame.from_dict(
                {"label": y, "rawPrediction": scores, "weight": w}
            )
        )

        # input cache: 120 KB budget for ~720 KB of columns -> mostly spilled
        cache = HostDataCache(
            memory_budget_bytes=120_000, spill_dir=str(tmp_path / "in")
        )
        for a in range(0, n, 1111):
            cache.append(
                {
                    "label": y[a : a + 1111],
                    "rawPrediction": scores[a : a + 1111],
                    "weight": w[a : a + 1111],
                }
            )
        cache.finish()
        got = ev.evaluate_stream(
            cache, bucket_rows=2048, spill_dir=str(tmp_path / "sort")
        )
        for name in ("areaUnderROC", "areaUnderPR", "ks", "areaUnderLorenz"):
            np.testing.assert_allclose(
                got[name][0], want[name][0], rtol=1e-9, atol=1e-12
            )

    def test_streamed_single_class_raises(self, tmp_path):
        from flink_ml_tpu.iteration import HostDataCache
        from flink_ml_tpu.models.evaluation.binary_classification_evaluator import (
            BinaryClassificationEvaluator,
        )

        cache = HostDataCache(memory_budget_bytes=1024, spill_dir=str(tmp_path))
        cache.append({"label": np.ones(50), "rawPrediction": np.random.default_rng(0).random(50)})
        cache.finish()
        with pytest.raises(ValueError, match="positive and negative"):
            BinaryClassificationEvaluator().evaluate_stream(cache)
