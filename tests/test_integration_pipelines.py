"""End-to-end composition tests: multi-stage Pipelines whose intermediate
columns cross representation boundaries (strings → tokens → SparseVector →
sparse training), plus save/load of the whole fitted chain — the
PipelineTest/GraphTest integration tier of the reference."""
import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.builder.pipeline import Pipeline
from flink_ml_tpu.utils.read_write import load_stage


def _text_data(n_per_class=40, seed=0):
    """Two topics with overlapping vocabulary; labels follow the topic."""
    rng = np.random.default_rng(seed)
    sports = "game team score win goal match play season league cup".split()
    cooking = "bake oven recipe flour sugar stir dough taste dish salt".split()
    common = "the a and with for very really today".split()
    texts, labels = [], []
    for words, label in ((sports, 0.0), (cooking, 1.0)):
        for _ in range(n_per_class):
            picks = list(rng.choice(words, 5)) + list(rng.choice(common, 3))
            rng.shuffle(picks)
            texts.append(" ".join(picks))
            labels.append(label)
    order = rng.permutation(len(texts))
    return [texts[i] for i in order], np.asarray(labels)[order]


class TestTextClassificationPipeline:
    def _build(self):
        from flink_ml_tpu.models.classification.logistic_regression import (
            LogisticRegression,
        )
        from flink_ml_tpu.models.feature.hashing_tf import HashingTF
        from flink_ml_tpu.models.feature.tokenizer import Tokenizer

        return Pipeline(
            [
                Tokenizer().set_input_col("text").set_output_col("tokens"),
                HashingTF()
                .set_input_col("tokens")
                .set_output_col("features")
                .set_num_features(1 << 16),
                LogisticRegression()
                .set_features_col("features")
                .set_max_iter(60)
                .set_learning_rate(1.0)
                .set_global_batch_size(32)
                .set_tol(0.0),
            ]
        )

    def test_fit_predict_and_save_load(self, tmp_path):
        texts, labels = _text_data()
        df = DataFrame(["text", "label"], None, [texts, labels])
        model = self._build().fit(df)

        # HashingTF emits SparseVector columns, so training went through the
        # padded-CSR path with a 2^16-dim coefficient — never densified.
        lr_model = model.stages[-1]
        assert lr_model.coefficient.shape == (1 << 16,)

        scored = model.transform(df)
        acc = float(np.mean(scored["prediction"] == labels))
        assert acc > 0.95, f"text pipeline failed to learn: {acc}"

        # whole-chain persistence: load_stage gives back a PipelineModel that
        # scores raw text identically
        path = str(tmp_path / "text-pipe")
        model.save(path)
        reloaded = load_stage(path)
        again = reloaded.transform(df)
        np.testing.assert_array_equal(again["prediction"], scored["prediction"])

    def test_unseen_text_generalizes(self):
        texts, labels = _text_data(seed=1)
        df = DataFrame(["text", "label"], None, [texts, labels])
        model = self._build().fit(df)
        queries = DataFrame(
            ["text"],
            None,
            [["the team won the big match today", "stir the flour and sugar in the dish"]],
        )
        pred = model.transform(queries)["prediction"]
        np.testing.assert_array_equal(pred, [0.0, 1.0])


class TestNumericPipeline:
    def test_scaler_into_kmeans(self, tmp_path):
        from flink_ml_tpu.models.clustering.kmeans import KMeans
        from flink_ml_tpu.models.feature.standard_scaler import StandardScaler

        rng = np.random.default_rng(3)
        # one dimension dominates unscaled distances; scaling must fix that
        X = np.concatenate(
            [
                np.column_stack([rng.normal(0, 1, 50), rng.normal(0.0, 800, 50)]),
                np.column_stack([rng.normal(6, 1, 50), rng.normal(0.0, 800, 50)]),
            ]
        )
        df = DataFrame.from_dict({"features": X})
        pipe = Pipeline(
            [
                StandardScaler()
                .set_input_col("features")
                .set_output_col("scaled")
                .set_with_mean(True),
                KMeans().set_features_col("scaled").set_k(2).set_seed(0).set_max_iter(20),
            ]
        )
        model = pipe.fit(df)
        pred = model.transform(df)["prediction"]
        # scaling makes the blobs separable along dim 0 (a couple of boundary
        # points may flip): majorities must differ with high purity
        maj_a = np.round(np.mean(pred[:50]))
        maj_b = np.round(np.mean(pred[50:]))
        assert maj_a != maj_b
        assert np.mean(pred[:50] == maj_a) > 0.9
        assert np.mean(pred[50:] == maj_b) > 0.9

        model.save(str(tmp_path / "numeric-pipe"))
        reloaded = load_stage(str(tmp_path / "numeric-pipe"))
        np.testing.assert_array_equal(reloaded.transform(df)["prediction"], pred)

    def test_pipeline_of_pipelines(self):
        """A Pipeline is itself a Stage, so pipelines nest (ref Pipeline being
        an Estimator in PipelineTest)."""
        from flink_ml_tpu.models.feature.scalers import MinMaxScaler
        from flink_ml_tpu.models.feature.standard_scaler import StandardScaler

        rng = np.random.default_rng(4)
        X = rng.normal(5.0, 3.0, size=(40, 2))
        df = DataFrame.from_dict({"features": X})
        inner = Pipeline(
            [
                StandardScaler()
                .set_input_col("features")
                .set_output_col("std")
                .set_with_mean(True)
            ]
        )
        outer = Pipeline(
            [inner, MinMaxScaler().set_input_col("std").set_output_col("out")]
        )
        out = outer.fit(df).transform(df)["out"]
        assert out.min() >= -1e-7 and out.max() <= 1.0 + 1e-7
