"""Flight recorder, incident bundles, live endpoint (flink_ml_tpu.telemetry).

The contract under test (docs/observability.md):

- the journal is append-only JSONL with monotone sequence numbers, written
  ONLY by the dedicated writer thread — the hot path pays one enqueue;
- a hard kill mid-write (the ``telemetry.journal`` fault point) leaves a
  torn tail the reader tolerates, and a new incarnation resumes the
  sequence without reuse and emits a crash-resume incident bundle;
- incident bundles are self-contained (journal window + metrics + config +
  lineage), rate-limited per kind, bounded-retention, and renderable by
  ``tools/traceview.py incident`` with exit 0;
- /metrics, /healthz and /events answer during live traffic, with 503 on
  drain/closed;
- runtime decisions (swap, rollback, controller action, fault trip,
  supervisor restart, plan choice) each land in the journal exactly once.
"""
from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import flink_ml_tpu.telemetry as telemetry
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.faults import InjectedFault, faults
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.servable.api import TransformerServable
from flink_ml_tpu.serving import InferenceServer, ServingConfig
from flink_ml_tpu.telemetry import FlightRecorder


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _wait_writer_dead(rec: FlightRecorder, timeout_s: float = 5.0) -> None:
    deadline = time.monotonic() + timeout_s
    while rec._alive() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not rec._alive(), "writer thread should have died on the injected fault"


class Echo(TransformerServable):
    def transform(self, df):
        return df.clone()


def _df(rows: int = 2, width: int = 4) -> DataFrame:
    return DataFrame.from_dict({"x": np.ones((rows, width), np.float32)})


# ---------------------------------------------------------------------------
# journal basics
# ---------------------------------------------------------------------------


class TestJournal:
    def test_emit_flush_read_roundtrip(self, tmp_path):
        rec = FlightRecorder(str(tmp_path))
        try:
            assert rec.emit("serving.swap", "ml.serving[t]", {"version": 3})
            assert rec.emit("controller.action", "ml.serving[t]", {"action": "shed"})
            assert rec.flush(10.0)
            records = telemetry.read_journal(str(tmp_path))
            kinds = [r["kind"] for r in records]
            assert kinds == ["recorder.start", "serving.swap", "controller.action"]
            seqs = [r["seq"] for r in records]
            assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
            swap = records[1]
            assert swap["data"] == {"version": 3}
            assert swap["scope"] == "ml.serving[t]"
            assert swap["inc"] == 1
            # monotonic + wall timestamps and the emitting thread ride along
            assert isinstance(swap["t"], float) and isinstance(swap["wall"], float)
            assert swap["thread"]
        finally:
            rec.close()

    def test_clean_close_writes_stop_marker(self, tmp_path):
        rec = FlightRecorder(str(tmp_path))
        rec.emit("a")
        rec.close()
        records = telemetry.read_journal(str(tmp_path))
        assert records[-1]["kind"] == "recorder.stop"

    def test_disabled_recorder_is_inert(self, tmp_path):
        rec = FlightRecorder(str(tmp_path / "j"), enabled=False)
        assert not rec.emit("a")
        assert not rec.incident("b")
        assert rec._thread is None
        assert not (tmp_path / "j").exists()

    def test_queue_overflow_drops_and_counts(self, tmp_path):
        rec = FlightRecorder(str(tmp_path), queue_capacity=4)
        try:
            assert rec.flush(10.0)  # writer started; now stall it artificially
            with rec._cond:  # hold the queue lock so nothing drains
                for i in range(10):
                    if len(rec._queue) >= rec.queue_capacity:
                        rec._dropped += 1
                    else:
                        rec._queue.append({"kind": f"e{i}", "t": 0.0, "wall": 0.0, "thread": "t"})
                        rec._enqueued += 1
            assert rec.dropped == 6
        finally:
            rec.close()

    def test_overflow_through_emit(self, tmp_path):
        # Arm the fault so the writer dies, then overfill through emit():
        # drop-and-count with zero blocking is the hot-path contract.
        rec = FlightRecorder(str(tmp_path), queue_capacity=8)
        try:
            faults.arm("telemetry.journal", at=1)
            rec.emit("killer")
            _wait_writer_dead(rec)
            for i in range(20):
                rec.emit(f"e{i}")
            assert rec.dropped >= 12
            assert not rec.flush(0.2)  # dead writer: flush reports failure
        finally:
            rec.close(timeout_s=0.5)

    def test_rotation_keeps_bounded_files_and_monotone_seq(self, tmp_path):
        rec = FlightRecorder(str(tmp_path), max_bytes=400, keep_files=3)
        try:
            for i in range(50):
                rec.emit("event", "ml.t", {"i": i, "pad": "x" * 40})
            assert rec.flush(10.0)
            files = telemetry.journal_files(str(tmp_path))
            assert 1 < len(files) <= 3
            records = telemetry.read_journal(str(tmp_path))
            seqs = [r["seq"] for r in records]
            assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
            assert records[-1]["data"]["i"] == 49  # the newest records survive
        finally:
            rec.close()

    def test_span_causal_id_links_to_graftscope(self, tmp_path):
        from flink_ml_tpu import trace

        rec = FlightRecorder(str(tmp_path))
        try:
            with trace.capture():
                with trace.tracer.span("loop.step", "productive", scope="ml.loop[t]") as sp:
                    rec.emit("loop.swap", "ml.loop[t]", {"version": 2})
                    span_id = sp.span_id
            assert rec.flush(10.0)
            swap = [r for r in telemetry.read_journal(str(tmp_path)) if r["kind"] == "loop.swap"][0]
            assert swap["span"] == span_id
        finally:
            rec.close()


# ---------------------------------------------------------------------------
# crash recovery: kill mid-write, torn tail, sequence resume, incident
# ---------------------------------------------------------------------------


class TestCrashRecovery:
    def test_kill_mid_write_leaves_torn_tail_reader_tolerates(self, tmp_path):
        rec = FlightRecorder(str(tmp_path))
        rec.emit("a", "ml.t", {"n": 1})
        assert rec.flush(10.0)
        faults.arm("telemetry.journal", at=1)
        rec.emit("b", "ml.t", {"n": 2})
        _wait_writer_dead(rec)
        faults.reset()
        # The file ends in a torn (half-written) line...
        path = telemetry.journal_files(str(tmp_path))[-1][2]
        with open(path, encoding="utf-8") as f:
            raw = f.read()
        assert not raw.endswith("\n")
        torn = raw.rsplit("\n", 1)[-1]
        with pytest.raises(ValueError):
            json.loads(torn)
        # ...and the reader returns every intact record, skipping the tail.
        records = telemetry.read_journal(str(tmp_path))
        assert [r["kind"] for r in records] == ["recorder.start", "a"]

    def test_new_incarnation_resumes_sequence_and_emits_incident(self, tmp_path):
        rec = FlightRecorder(str(tmp_path))
        rec.emit("a")
        assert rec.flush(10.0)
        faults.arm("telemetry.journal", at=1)
        rec.emit("b")
        _wait_writer_dead(rec)
        faults.reset()
        pre = telemetry.read_journal(str(tmp_path))
        max_seq = max(r["seq"] for r in pre)

        rec2 = FlightRecorder(str(tmp_path))
        try:
            rec2.emit("after-resume")
            assert rec2.flush(10.0)
            assert rec2.crash_resumed
            records = telemetry.read_journal(str(tmp_path))
            seqs = [r["seq"] for r in records]
            # monotone across incarnations, no reuse of a durable seq
            assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
            assert min(s for s in seqs if s > max_seq) == max_seq + 1
            assert rec2.incarnation == 2
            resume = [r for r in records if r["kind"] == "recorder.resume"][0]
            assert resume["data"]["prior_incarnation"] == 1
            assert resume["data"]["clean_shutdown"] is False
            assert resume["data"]["torn_tail"] is True
            # crash-resume itself produced an incident bundle...
            bundles = [
                b for b in telemetry.list_bundles(rec2.incident_dir)
                if b.endswith("crash-resume")
            ]
            assert len(bundles) == 1
            manifest = telemetry.load_bundle(bundles[0])["manifest"]
            assert manifest["kind"] == "crash-resume"
            assert manifest["config"]  # resolved runtime config snapshotted
            # ...that traceview renders as a postmortem with exit 0.
            import tools.traceview as traceview

            assert traceview.main(["incident", bundles[0], "--top", "5"]) == 0
        finally:
            rec2.close()

    def test_clean_restart_is_not_a_crash(self, tmp_path):
        rec = FlightRecorder(str(tmp_path))
        rec.emit("a")
        rec.close()
        rec2 = FlightRecorder(str(tmp_path))
        try:
            assert rec2.flush(10.0)
            assert not rec2.crash_resumed
            assert rec2.incarnation == 2
            assert not telemetry.list_bundles(rec2.incident_dir)
            resume = [
                r for r in telemetry.read_journal(str(tmp_path))
                if r["kind"] == "recorder.resume"
            ][0]
            assert resume["data"]["clean_shutdown"] is True
        finally:
            rec2.close()


# ---------------------------------------------------------------------------
# incidents: bundle contents, rate limit, retention
# ---------------------------------------------------------------------------


class TestIncidents:
    def test_bundle_contents_and_lineage(self, tmp_path):
        rec = FlightRecorder(str(tmp_path))
        try:
            rec.emit("loop.publish", "ml.loop[t]", {"version": 1})
            rec.emit("serving.swap", "ml.serving[t]", {"version": 1})
            rec.emit("serving.rollback", "ml.serving[t]", {"version": 1, "from": 2})
            rec.incident("rollback", "ml.loop[t]", {"from_version": 2, "restored": 1})
            assert rec.flush(10.0)
            bundle = telemetry.list_bundles(rec.incident_dir)[0]
            names = sorted(os.listdir(bundle))
            assert "incident.json" in names and "journal.jsonl" in names
            assert "metrics.prom" in names
            loaded = telemetry.load_bundle(bundle)
            assert loaded["manifest"]["kind"] == "rollback"
            assert loaded["manifest"]["context"]["restored"] == 1
            lineage = loaded["manifest"]["lineage"]
            assert [e["kind"] for e in lineage] == [
                "loop.publish", "serving.swap", "serving.rollback",
            ]
            # the bundle's journal window includes the incident's own record
            assert loaded["records"][-1]["kind"] == "incident"
        finally:
            rec.close()

    def test_rate_limit_is_per_kind(self, tmp_path):
        rec = FlightRecorder(str(tmp_path), incident_min_interval_s=3600.0)
        try:
            assert rec.incident("shed-episode", context={"n": 1})
            assert not rec.incident("shed-episode", context={"n": 2})  # suppressed
            assert rec.incident("swap-failure", context={"n": 3})  # different kind
            assert rec.flush(10.0)
            kinds = [os.path.basename(b) for b in telemetry.list_bundles(rec.incident_dir)]
            assert len(kinds) == 2
            assert any(k.endswith("shed-episode") for k in kinds)
            assert any(k.endswith("swap-failure") for k in kinds)
            assert rec.incidents_suppressed == 1
        finally:
            rec.close()

    def test_retention_bound(self, tmp_path):
        rec = FlightRecorder(
            str(tmp_path), incident_min_interval_s=0.0, incident_keep=2
        )
        try:
            for i in range(5):
                rec.incident(f"kind-{i}", context={"i": i})
                assert rec.flush(10.0)
            bundles = telemetry.list_bundles(rec.incident_dir)
            assert len(bundles) == 2
            assert bundles[-1].endswith("kind-4")  # newest retained
        finally:
            rec.close()


# ---------------------------------------------------------------------------
# the hot path: enqueue only — zero journal writes on the dispatch path
# ---------------------------------------------------------------------------


class TestHotPathIsEnqueueOnly:
    def test_all_file_writes_happen_on_the_writer_thread(self, tmp_path):
        rec = telemetry.configure(str(tmp_path))
        try:
            write_threads = []
            original = FlightRecorder._write_record

            def tracking(self, record):
                write_threads.append(threading.current_thread().name)
                return original(self, record)

            FlightRecorder._write_record = tracking
            try:
                server = InferenceServer(
                    Echo(),
                    name="telemetry-hot",
                    serving_config=ServingConfig(max_batch_size=8, max_delay_ms=0.0),
                    warmup_template=_df(1),
                )
                try:
                    for _ in range(10):
                        server.predict(_df(2))
                    server.swap(2, Echo())
                finally:
                    server.close()
                assert rec.flush(10.0)
            finally:
                FlightRecorder._write_record = original
            assert write_threads, "serving decisions should have been journaled"
            assert all(t.startswith("flight-recorder") for t in set(write_threads)), (
                f"journal writes leaked off the writer thread: {set(write_threads)}"
            )
            # and the decisions themselves landed exactly once each
            records = telemetry.read_journal(str(tmp_path))
            swaps = [r for r in records if r["kind"] == "serving.swap"]
            assert [s["data"]["version"] for s in swaps] == [1, 2]
        finally:
            telemetry.configure(None)

    def test_emit_does_not_touch_the_filesystem_on_the_caller_thread(self, tmp_path):
        rec = FlightRecorder(str(tmp_path))
        gate = threading.Event()
        try:
            assert rec.flush(10.0)
            before = os.stat(telemetry.journal_files(str(tmp_path))[-1][2]).st_size

            def gated(record):  # freeze the writer (outside every lock)
                gate.wait(timeout=10.0)

            rec._write_record = gated
            t0 = time.perf_counter()
            for i in range(100):
                assert rec.emit("e", "ml.t", {"i": i})
            emit_s = time.perf_counter() - t0
            after = os.stat(telemetry.journal_files(str(tmp_path))[-1][2]).st_size
            assert after == before  # nothing hit disk: emits only enqueued
            assert emit_s < 1.0  # and none of them blocked on the writer
        finally:
            gate.set()
            rec.close()


# ---------------------------------------------------------------------------
# the live endpoint
# ---------------------------------------------------------------------------


def _get(url: str):
    with urllib.request.urlopen(url, timeout=5.0) as resp:
        return resp.status, resp.read().decode("utf-8")


class TestHttpEndpoint:
    def test_metrics_healthz_events_during_live_traffic(self, tmp_path):
        rec = telemetry.configure(str(tmp_path))
        server = InferenceServer(
            Echo(),
            name="telemetry-http",
            serving_config=ServingConfig(
                max_batch_size=8, max_delay_ms=0.0, http_port=0
            ),
            warmup_template=_df(1),
        )
        try:
            url = server.telemetry.url
            for _ in range(5):
                server.predict(_df(2))
            status, body = _get(url + "/metrics")
            assert status == 200
            assert "# TYPE ml_serving_requests_total counter" in body
            assert 'ml_serving_requests_total{scope="ml.serving[telemetry-http]"}' in body
            status, body = _get(url + "/healthz")
            payload = json.loads(body)
            assert status == 200
            assert payload["status"] == "serving"
            assert payload["version"] == 1
            assert payload["queue_capacity_rows"] == server.config.queue_capacity_rows
            assert "controller" in payload
            rec.flush(10.0)
            status, body = _get(url + "/events?n=3")
            events = json.loads(body)
            assert status == 200 and 1 <= len(events) <= 3
            assert all("kind" in e and "seq" in e for e in events)
        finally:
            server.close()
            telemetry.configure(None)

    def test_healthz_503_on_drain_and_closed(self, tmp_path):
        release = threading.Event()

        class Gated(TransformerServable):
            def transform(self, df):
                release.wait(timeout=10.0)
                return df.clone()

        rec = telemetry.configure(str(tmp_path))
        server = InferenceServer(
            Gated(),
            name="telemetry-drain",
            serving_config=ServingConfig(
                max_batch_size=4, max_delay_ms=0.0, http_port=0,
                default_timeout_ms=30_000,
            ),
        )
        url = server.telemetry.url
        saw_503 = False
        try:
            handle = server.submit(_df(1))  # in-flight work to drain
            closer = threading.Thread(target=server.close, daemon=True)
            closer.start()
            # While draining (the batch is gated on `release`), /healthz
            # must answer 503 with the draining status in the payload.
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and not saw_503:
                try:
                    _get(url + "/healthz")
                except urllib.error.HTTPError as e:
                    assert e.code == 503
                    payload = json.loads(e.read().decode("utf-8"))
                    assert payload["status"] in ("draining", "closed")
                    saw_503 = True
                except (urllib.error.URLError, OSError):
                    break  # endpoint stopped — close() already completed
                else:
                    time.sleep(0.01)
            release.set()
            closer.join(timeout=10.0)
            handle.result()  # the drained request still completed exactly once
        finally:
            release.set()
            server.close()
            telemetry.configure(None)
        assert saw_503, "draining server should have answered /healthz with 503"

    def test_404_on_unknown_path(self, tmp_path):
        rec = FlightRecorder(str(tmp_path))
        try:
            with telemetry.TelemetryServer(0, recorder=rec) as ts:
                with pytest.raises(urllib.error.HTTPError) as e:
                    _get(ts.url + "/nope")
                assert e.value.code == 404
                status, _ = _get(ts.url + "/healthz")  # bare server: 200 up
                assert status == 200
        finally:
            rec.close()


# ---------------------------------------------------------------------------
# hook integration: runtime decisions land in the journal
# ---------------------------------------------------------------------------


class TestDecisionHooks:
    def test_fault_trip_observer_journals_fires(self, tmp_path):
        rec = telemetry.configure(str(tmp_path))
        try:
            faults.arm("serving.admit", at=1)
            server = InferenceServer(
                Echo(),
                name="telemetry-trip",
                serving_config=ServingConfig(max_batch_size=4, max_delay_ms=0.0),
                warmup_template=_df(1),
            )
            try:
                with pytest.raises(InjectedFault):
                    server.predict(_df(1))
            finally:
                server.close()
            assert rec.flush(10.0)
            trips = [
                r for r in telemetry.read_journal(str(tmp_path))
                if r["kind"] == "fault.trip"
            ]
            assert len(trips) == 1
            assert trips[0]["data"]["point"] == "serving.admit"
        finally:
            telemetry.configure(None)

    def test_supervisor_restart_journals_and_bundles(self, tmp_path):
        from flink_ml_tpu.execution import Supervisor

        rec = telemetry.configure(str(tmp_path))
        try:
            calls = {"n": 0}

            def flaky():
                calls["n"] += 1
                if calls["n"] < 3:
                    raise OSError("spill file lost")  # retryable by contract
                return "done"

            assert Supervisor(name="telemetry-sup").run(flaky) == "done"
            assert rec.flush(10.0)
            records = telemetry.read_journal(str(tmp_path))
            restarts = [r for r in records if r["kind"] == "execution.restart"]
            assert len(restarts) == 2
            assert restarts[0]["data"]["error"] == "OSError"
            assert restarts[0]["scope"] == "ml.execution[telemetry-sup]"
            bundles = [
                b for b in telemetry.list_bundles(rec.incident_dir)
                if b.endswith("supervisor-restart")
            ]
            assert len(bundles) == 1  # rate-limited: one bundle per episode kind
        finally:
            telemetry.configure(None)

    def test_controller_action_carries_ledger_evidence(self, tmp_path):
        from flink_ml_tpu.serving.controller import AdaptiveController

        rec = telemetry.configure(str(tmp_path))
        try:
            clock = {"t": 0.0}
            ctrl = AdaptiveController(
                "ml.serving[t-ledger]", 64, 8,
                shed_sustain_ms=0.0, clock=lambda: clock["t"],
            )
            ctrl.observe_batch(8, 8, 0.5)
            ctrl.note_queue(60)
            clock["t"] += 1.0
            assert ctrl.should_shed(1, 60)
            ctrl.record_shed(1, 60)
            assert rec.flush(10.0)
            actions = [
                r for r in telemetry.read_journal(str(tmp_path))
                if r["kind"] == "controller.action"
            ]
            assert len(actions) == 1
            assert actions[0]["data"]["action"] == "shed"
            assert actions[0]["data"]["ledger_ms"].get("productive") == 500.0
            # the shed episode also requested an incident bundle
            bundles = [
                b for b in telemetry.list_bundles(rec.incident_dir)
                if b.endswith("shed-episode")
            ]
            assert len(bundles) == 1
        finally:
            telemetry.configure(None)

    def test_fusion_plan_choice_is_journaled(self, tmp_path):
        from flink_ml_tpu.servable.fusion import plan_recorder

        rec = telemetry.configure(str(tmp_path))
        try:
            on_plan = plan_recorder("ml.serving[t-plan]")
            on_plan("fused", 1234.5)
            assert rec.flush(10.0)
            plans = [
                r for r in telemetry.read_journal(str(tmp_path))
                if r["kind"] == "fusion.plan"
            ]
            assert len(plans) == 1
            assert plans[0]["data"] == {"choice": "fused", "score": 1234.5}
        finally:
            telemetry.configure(None)


# ---------------------------------------------------------------------------
# traceview --json (machine-readable attribution for CI)
# ---------------------------------------------------------------------------


class TestTraceviewJson:
    def _trace_file(self, tmp_path) -> str:
        from flink_ml_tpu import trace

        with trace.capture() as recorder:
            server = InferenceServer(
                Echo(),
                name="t-tvjson",
                serving_config=ServingConfig(max_batch_size=8, max_delay_ms=0.0),
                warmup_template=_df(1),
            )
            try:
                for _ in range(3):
                    server.predict(_df(2))
            finally:
                server.close()
            path = str(tmp_path / "trace.json")
            recorder.export_chrome_trace(path)
        return path

    def test_summarize_data_matches_live_attribution(self, tmp_path):
        import tools.traceview as traceview

        path = self._trace_file(tmp_path)
        spans = traceview.load_spans(path)
        data = traceview.summarize_data(spans)
        scope = "ml.serving[t-tvjson]"
        assert scope in data["scopes"]
        entry = data["scopes"][scope]
        assert entry["wall_ms"] > 0.0
        assert 0.0 <= entry["goodput_fraction"] <= 1.0
        # categories sum to the wall (the exact-attribution invariant)
        total = sum(c["ms"] for c in entry["categories"].values())
        assert total == pytest.approx(entry["wall_ms"], rel=1e-6)
        names = {s["name"] for s in entry["spans"]}
        assert "serving.request" in names and "serving.batch" in names
        for stat in entry["spans"]:
            assert set(stat) == {"name", "count", "p50_ms", "p99_ms", "total_ms", "share"}

    def test_cli_json_flag(self, tmp_path, capsys):
        import tools.traceview as traceview

        path = self._trace_file(tmp_path)
        assert traceview.main([path, "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["spans"] > 0
        assert "overall_goodput_fraction" in payload


# ---------------------------------------------------------------------------
# bench_trend (informational CI step)
# ---------------------------------------------------------------------------


class TestBenchTrend:
    def _write_rounds(self, tmp_path, old_row, new_row):
        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps({"workloads": [old_row]}), encoding="utf-8"
        )
        (tmp_path / "BENCH_r02.json").write_text(
            json.dumps({"workloads": [new_row]}), encoding="utf-8"
        )

    def test_regression_warns_but_exits_zero(self, tmp_path, capsys):
        import tools.bench_trend as bench_trend

        self._write_rounds(
            tmp_path,
            {"name": "row", "latency_p50_ms": 1.0, "rows_per_sec": 1000.0},
            {"name": "row", "latency_p50_ms": 1.5, "rows_per_sec": 800.0},
        )
        assert bench_trend.main(["--dir", str(tmp_path)]) == 0  # informational
        out = capsys.readouterr().out
        assert "REGRESSION" in out and "WARN" in out
        assert "latency_p50_ms" in out and "rows_per_sec" in out

    def test_strict_mode_fails_on_regression(self, tmp_path, capsys):
        import tools.bench_trend as bench_trend

        self._write_rounds(
            tmp_path,
            {"name": "row", "latency_p50_ms": 1.0},
            {"name": "row", "latency_p50_ms": 2.0},
        )
        assert bench_trend.main(["--dir", str(tmp_path), "--strict"]) == 1

    def test_within_threshold_is_quiet(self, tmp_path, capsys):
        import tools.bench_trend as bench_trend

        self._write_rounds(
            tmp_path,
            {"name": "row", "latency_p50_ms": 1.0, "rows_per_sec": 1000.0,
             "sweep": [{"latency_p999_ms": 5.0}]},
            {"name": "row", "latency_p50_ms": 1.05, "rows_per_sec": 980.0,
             "sweep": [{"latency_p999_ms": 5.2}]},
        )
        assert bench_trend.main(["--dir", str(tmp_path), "--strict"]) == 0
        assert "REGRESSION" not in capsys.readouterr().out

    def test_fewer_than_two_rounds_is_a_noop(self, tmp_path):
        import tools.bench_trend as bench_trend

        assert bench_trend.main(["--dir", str(tmp_path)]) == 0

    def test_new_metrics_and_rows_reported_informationally(self, tmp_path, capsys):
        import tools.bench_trend as bench_trend

        (tmp_path / "BENCH_r01.json").write_text(
            json.dumps({"workloads": [{"name": "row", "latency_p50_ms": 1.0}]}),
            encoding="utf-8",
        )
        (tmp_path / "BENCH_r02.json").write_text(
            json.dumps({"workloads": [
                {"name": "row", "latency_p50_ms": 1.0, "bf16_latency_p50_ms": 0.7},
                {"name": "precision_sweep", "latency_p50_ms": 0.5},
            ]}),
            encoding="utf-8",
        )
        assert bench_trend.main(["--dir", str(tmp_path), "--strict"]) == 0
        out = capsys.readouterr().out
        assert "+ new row precision_sweep" in out
        assert "bf16_latency_p50_ms" in out and "(NEW)" in out
        assert "WARN" not in out and "REGRESSION" not in out
