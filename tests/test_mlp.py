"""MLPClassifier tests — the JAX-native deep-model flagship (no reference
counterpart; standard quartet: defaults, fit/transform, save/load, model data)."""
import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.models.classification.mlp_classifier import (
    MLPClassifier,
    MLPClassifierModel,
)

RNG = np.random.default_rng(77)


def _xor(n=512):
    X = RNG.normal(size=(n, 2))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(np.float64)
    return DataFrame.from_dict({"features": X, "label": y}), y


def _fit(df, **kw):
    m = (
        MLPClassifier()
        .set_hidden_layers(32, 32)
        .set_max_iter(kw.pop("max_iter", 300))
        .set_learning_rate(0.01)
        .set_global_batch_size(512)
        .set_tol(0.0)
        .set_seed(1)
    )
    return m.fit(df)


def test_defaults():
    m = MLPClassifier()
    assert m.get_hidden_layers() == [64]
    assert m.get_max_iter() == 20
    assert m.get_learning_rate() == 0.1


def test_solves_nonlinear_problem():
    df, y = _xor()
    model = _fit(df)
    out = model.transform(df)
    assert (out["prediction"] == y).mean() > 0.95
    probs = out["rawPrediction"]
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)


def test_multiclass_labels_preserved():
    n = 300
    X = RNG.normal(size=(n, 2))
    # three wedges by angle; labels are non-contiguous values
    angle = np.arctan2(X[:, 1], X[:, 0])
    y = np.select([angle < -1.0, angle < 1.0], [10.0, 20.0], 30.0)
    df = DataFrame.from_dict({"features": X, "label": y})
    model = _fit(df, max_iter=400)
    out = model.transform(df)
    assert set(np.unique(out["prediction"])) <= {10.0, 20.0, 30.0}
    assert (out["prediction"] == y).mean() > 0.9
    assert out["rawPrediction"].shape[1] == 3


def test_save_load_round_trip(tmp_path):
    df, y = _xor(128)
    model = _fit(df, max_iter=50)
    path = str(tmp_path / "mlp")
    model.save(path)
    loaded = MLPClassifierModel.load(path)
    out1, out2 = model.transform(df), loaded.transform(df)
    np.testing.assert_array_equal(out1["prediction"], out2["prediction"])
    np.testing.assert_allclose(out1["rawPrediction"], out2["rawPrediction"], atol=1e-6)


def test_seed_reproducible():
    df, _ = _xor(128)
    m1, m2 = _fit(df, max_iter=20), _fit(df, max_iter=20)
    for (w1, b1), (w2, b2) in zip(m1.params, m2.params):
        np.testing.assert_array_equal(w1, w2)


def test_bfloat16_compute_type_trains():
    # Mixed precision (bf16 matmuls, f32 params/loss) must still solve the
    # nonlinear problem and keep the default path exact-f32.
    df, y = _xor()
    m = (
        MLPClassifier()
        .set_hidden_layers(32, 32)
        .set_max_iter(300)
        .set_learning_rate(0.01)
        .set_global_batch_size(512)
        .set_tol(0.0)
        .set_seed(1)
        .set_compute_type("bfloat16")
    )
    assert m.get_compute_type() == "bfloat16"
    model = m.fit(df)
    out = model.transform(df)
    assert (out["prediction"] == y).mean() > 0.95
    # params stay float32 (mixed precision, not a bf16 model)
    assert all(np.asarray(W).dtype == np.float32 for W, _ in model.params)


def test_compute_type_validation():
    with pytest.raises(ValueError):
        MLPClassifier().set_compute_type("float16")
