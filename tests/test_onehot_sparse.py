"""The one-hot matmul sparse path (linalg/onehot_sparse.py).

The path must reproduce the scatter gradient to split-bf16 precision
(~2^-16 relative): same per-batch gradient, same loss trajectory, same
tail-batch/window clamping semantics — only the execution strategy differs
(dense one-hot algebra instead of serialized gather/scatter instructions).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_ml_tpu.iteration import DeviceDataCache
from flink_ml_tpu.linalg.onehot_sparse import (
    BLOCK,
    OneHotSparseLayout,
    dot_crossing_pallas,
    dot_crossing_premat_pallas,
    dot_crossing_premat_xla,
    dot_crossing_xla,
    mult_crossing_pallas,
    mult_crossing_premat_pallas,
    mult_crossing_premat_xla,
    mult_crossing_xla,
    onehot_batch_step,
    premat_bytes,
    premat_row_onehots,
)
from flink_ml_tpu.ops import SGD, BinaryLogisticLoss
from flink_ml_tpu.parallel.mesh import MeshContext, mesh_context


def _scatter_reference(idx, val, coef, yb, wb):
    """Numpy rendition of the scatter path's batch math."""
    dot = np.sum(val * coef[idx], axis=1)
    ys = 2.0 * yb - 1.0
    z = dot * ys
    loss = np.sum(wb * np.log1p(np.exp(-z)))
    mult = wb * (-ys / (1.0 + np.exp(z)))
    grad = np.zeros(coef.shape[0], np.float64)
    np.add.at(grad, idx.ravel(), (val * mult[:, None]).ravel())
    return grad, loss


class TestLayout:
    def test_coef_permute_round_trip(self):
        rng = np.random.default_rng(0)
        idx = rng.integers(0, 500, size=(64, 4)).astype(np.int32)
        val = np.ones((64, 4), np.float32)
        lay = OneHotSparseLayout.build(idx, val, 500, 1, 32)
        coef = rng.normal(size=500).astype(np.float32)
        np.testing.assert_array_equal(lay.unpermute_coef(lay.permute_coef(coef)), coef)

    def test_coef_permute_round_trip_tp(self):
        # Shard-major TP layout: round-trip through every model width.
        rng = np.random.default_rng(30)
        idx = rng.integers(0, 500, size=(64, 4)).astype(np.int32)
        val = np.ones((64, 4), np.float32)
        coef = rng.normal(size=500).astype(np.float32)
        for nm in (1, 2, 4):
            lay = OneHotSparseLayout.build(idx, val, 500, 1, 32, n_model=nm)
            assert lay.plan.n_model == nm
            np.testing.assert_array_equal(
                lay.unpermute_coef(lay.permute_coef(coef)), coef
            )

    def test_tp_shards_carry_identical_class_meta_and_all_entries(self):
        # Round-robin deal: every model shard gets the same local meta; the
        # union of shards' stacks carries every nonzero entry exactly once.
        rng = np.random.default_rng(31)
        idx = rng.integers(0, 2000, size=(128, 6)).astype(np.int32)
        val = rng.normal(size=(128, 6)).astype(np.float32)
        lay1 = OneHotSparseLayout.build(idx, val, 2000, 1, 128, n_model=1)
        lay2 = OneHotSparseLayout.build(idx, val, 2000, 1, 128, n_model=2)
        total1 = np.sort(lay1.lvals[lay1.lvals != 0.0])
        total2 = np.sort(lay2.lvals[lay2.lvals != 0.0])
        np.testing.assert_array_equal(total1, total2)
        assert lay2.lvals.shape[1] == 2  # model-shard axis

    def test_padding_bounded_by_pow2_classes(self):
        rng = np.random.default_rng(1)
        idx = rng.integers(0, 4096, size=(512, 8)).astype(np.int32)
        val = np.ones((512, 8), np.float32)
        lay = OneHotSparseLayout.build(idx, val, 4096, 1, 128)
        # pow2 classes bound padding to < 2x per (window, sub) unit; the max
        # across units adds at most another factor over the per-unit bound
        assert lay.padding_ratio() < 4.5

    def test_out_of_range_raises(self):
        idx = np.array([[0, 99]], np.int32)
        val = np.ones((1, 2), np.float32)
        with pytest.raises(ValueError, match="out of range"):
            OneHotSparseLayout.build(idx, val, 50, 1, 1)

    def test_all_padding_raises(self):
        idx = np.zeros((4, 2), np.int32)
        val = np.zeros((4, 2), np.float32)
        with pytest.raises(ValueError, match="no nonzero"):
            OneHotSparseLayout.build(idx, val, 10, 1, 4)


class TestBatchStep:
    @pytest.mark.parametrize("sub_rows", [64, 100, 512])
    def test_matches_scatter_reference(self, sub_rows):
        rng = np.random.default_rng(2)
        n, d, K, lb = 700, 1000, 6, 256
        idx = rng.integers(0, d, size=(n, K)).astype(np.int32)
        val = rng.normal(size=(n, K)).astype(np.float32)
        val[rng.random((n, K)) < 0.2] = 0.0  # padding slots
        y = (rng.random(n) > 0.5).astype(np.float32)
        w = rng.random(n).astype(np.float32)
        lay = OneHotSparseLayout.build(idx, val, d, 1, lb, sub_rows=sub_rows)
        coef = rng.normal(size=d).astype(np.float32)
        cp = jnp.asarray(lay.permute_coef(coef))
        pad = lay.n_sub * lay.sub_batch - lay.local_batch
        for wi, w0 in enumerate(lay.window_starts):
            rows = slice(w0, w0 + lay.local_batch)
            grad_p, ls, ws = onehot_batch_step(
                cp,
                jnp.asarray(lay.lidx[0, 0, wi]), jnp.asarray(lay.rowid[0, 0, wi]),
                jnp.asarray(lay.lvals[0, 0, wi]),
                jnp.asarray(np.pad(y[rows], (0, pad))),
                jnp.asarray(np.pad(w[rows], (0, pad))),
                BinaryLogisticLoss.INSTANCE, lay.class_meta, lay.nblk_local,
                lay.sub_batch, lay.row_hi, use_pallas=False,
            )
            ref_grad, ref_loss = _scatter_reference(
                idx[rows], val[rows], coef, y[rows], w[rows]
            )
            np.testing.assert_allclose(
                lay.unpermute_coef(np.asarray(grad_p)), ref_grad, rtol=2e-4, atol=2e-4
            )
            np.testing.assert_allclose(float(ls), ref_loss, rtol=1e-4)
            np.testing.assert_allclose(float(ws), w[rows].sum(), rtol=1e-5)


class TestCrossings:
    def test_pallas_interpret_matches_xla(self):
        rng = np.random.default_rng(3)
        n_sub, n, row_hi = 3, 5000, 4  # 512-row space per sub-batch
        rhi = jnp.asarray(rng.integers(0, row_hi, (n_sub, n), dtype=np.int32))
        rlo = jnp.asarray(rng.integers(0, 128, (n_sub, n), dtype=np.int32))
        q = jnp.asarray(rng.normal(size=(n_sub, n)).astype(np.float32))
        m3 = jnp.asarray(rng.normal(size=(n_sub, row_hi, 128)).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(dot_crossing_pallas(q, rhi, rlo, row_hi, interpret=True)),
            np.asarray(dot_crossing_xla(q, rhi, rlo, row_hi)),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(mult_crossing_pallas(m3, rhi, rlo, row_hi, interpret=True)),
            np.asarray(mult_crossing_xla(m3, rhi, rlo, row_hi)),
            rtol=1e-5, atol=1e-5,
        )


class TestPrematCrossings:
    """The precomputed-one-hot (premat) crossing path: same contraction with
    the row one-hots materialized once instead of rebuilt per minibatch —
    output must match the build-form kernels (bit-identical on the XLA form
    when no entry padding is involved)."""

    def _ids(self, rng, n_sub, n, row_hi):
        rhi = rng.integers(0, row_hi, (n_sub, n), dtype=np.int32)
        rlo = rng.integers(0, 128, (n_sub, n), dtype=np.int32)
        rowid = (rhi * 128 + rlo).astype(np.int16)
        return jnp.asarray(rhi), jnp.asarray(rlo), jnp.asarray(rowid)

    @pytest.mark.parametrize("n", [5000, 4096])  # padded and tile-exact
    def test_premat_matches_build_xla(self, n):
        rng = np.random.default_rng(40)
        n_sub, row_hi = 3, 4
        rhi, rlo, rowid = self._ids(rng, n_sub, n, row_hi)
        q = jnp.asarray(rng.normal(size=(n_sub, n)).astype(np.float32))
        m3 = jnp.asarray(rng.normal(size=(n_sub, row_hi, 128)).astype(np.float32))
        oh_hi, oh_lo = premat_row_onehots(rowid, row_hi)
        assert oh_hi.shape[1] % min(4096, n) == 0  # padded to the tile
        np.testing.assert_allclose(
            np.asarray(dot_crossing_premat_xla(q, oh_hi, oh_lo)),
            np.asarray(dot_crossing_xla(q, rhi, rlo, row_hi)),
            rtol=1e-6, atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(mult_crossing_premat_xla(m3, oh_hi, oh_lo))[:, :n],
            np.asarray(mult_crossing_xla(m3, rhi, rlo, row_hi)),
            rtol=1e-6, atol=1e-6,
        )

    def test_premat_pallas_interpret_matches_xla(self):
        rng = np.random.default_rng(41)
        n_sub, n, row_hi = 2, 5000, 4
        rhi, rlo, rowid = self._ids(rng, n_sub, n, row_hi)
        q = jnp.asarray(rng.normal(size=(n_sub, n)).astype(np.float32))
        m3 = jnp.asarray(rng.normal(size=(n_sub, row_hi, 128)).astype(np.float32))
        oh_hi, oh_lo = premat_row_onehots(rowid, row_hi)
        np.testing.assert_allclose(
            np.asarray(dot_crossing_premat_pallas(q, oh_hi, oh_lo, interpret=True)),
            np.asarray(dot_crossing_xla(q, rhi, rlo, row_hi)),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(
                mult_crossing_premat_pallas(m3, oh_hi, oh_lo, interpret=True)
            )[:, :n],
            np.asarray(mult_crossing_xla(m3, rhi, rlo, row_hi)),
            rtol=1e-5, atol=1e-5,
        )

    def test_padded_entries_contribute_nothing(self):
        # Padded oh rows are all-zero, so garbage q on the padded slots must
        # not leak into the dot crossing.
        rng = np.random.default_rng(42)
        n_sub, n, row_hi = 1, 5000, 4
        rhi, rlo, rowid = self._ids(rng, n_sub, n, row_hi)
        oh_hi, oh_lo = premat_row_onehots(rowid, row_hi)
        n_pad = oh_hi.shape[1]
        q_pad = jnp.asarray(rng.normal(size=(n_sub, n_pad)).astype(np.float32))
        ref = dot_crossing_xla(q_pad[:, :n], rhi, rlo, row_hi)
        np.testing.assert_allclose(
            np.asarray(dot_crossing_premat_xla(q_pad, oh_hi, oh_lo)),
            np.asarray(ref), rtol=1e-6, atol=1e-6,
        )

    def test_premat_bytes_counts_padding(self):
        assert premat_bytes(2, 4096, 4) == 2 * 2 * 4096 * (4 + 128)
        assert premat_bytes(1, 5000, 4) == 2 * 8192 * (4 + 128)


class TestPrematSgd:
    def _cols(self, rng, n, d, K):
        idx = rng.integers(0, d, size=(n, K)).astype(np.int32)
        val = rng.normal(size=(n, K)).astype(np.float32)
        y = (rng.random(n) > 0.5).astype(np.float32)
        return {
            "indices": idx, "values": val, "labels": y,
            "weights": np.ones(n, np.float32),
        }

    def _fit(self, cols, d, ctx, premat, **kw):
        sgd = SGD(
            max_iter=8, global_batch_size=128, tol=0.0, learning_rate=0.3,
            reg=0.01, elastic_net=0.5, ctx=ctx, sparse_kernel="onehot",
            onehot_premat=premat, **kw,
        )
        coef = sgd.optimize(
            np.zeros(d, np.float32),
            DeviceDataCache(dict(cols), ctx=ctx),
            BinaryLogisticLoss.INSTANCE,
        )
        return coef, sgd

    def test_premat_on_off_identical(self):
        # No entry padding at these shapes -> the XLA premat contraction is
        # the build contraction with the one-hots hoisted: bit-identical.
        rng = np.random.default_rng(43)
        cols = self._cols(rng, 512, 800, 8)
        with mesh_context(MeshContext(n_data=2, n_model=1)) as ctx:
            c_on, sgd_on = self._fit(cols, 800, ctx, "on")
            c_off, sgd_off = self._fit(cols, 800, ctx, "off")
            assert sgd_on.onehot_premat_active
            assert not sgd_off.onehot_premat_active
            np.testing.assert_array_equal(c_on, c_off)
            np.testing.assert_array_equal(
                sgd_on.loss_history, sgd_off.loss_history
            )

    def test_premat_composes_with_tp(self):
        rng = np.random.default_rng(44)
        cols = self._cols(rng, 512, 800, 8)
        with mesh_context(MeshContext(n_data=4, n_model=2)) as ctx:
            c_on, sgd_on = self._fit(cols, 800, ctx, "on")
            c_off, _ = self._fit(cols, 800, ctx, "off")
            assert sgd_on.onehot_premat_active
            np.testing.assert_array_equal(c_on, c_off)

    def test_premat_composes_with_multislice(self):
        with mesh_context(
            MeshContext(devices=jax.devices()[:8], n_data=4, n_model=1, n_slices=2)
        ) as ctx:
            rng = np.random.default_rng(45)
            cols = self._cols(rng, 512, 800, 8)
            c_on, sgd_on = self._fit(cols, 800, ctx, "on")
            c_off, _ = self._fit(cols, 800, ctx, "off")
            assert sgd_on.onehot_premat_active
            np.testing.assert_array_equal(c_on, c_off)

    def test_auto_gate_rejects_over_budget(self, monkeypatch):
        import flink_ml_tpu.ops.optimizer as opt

        monkeypatch.setattr(opt, "_hbm_bytes_limit", lambda ctx=None: 1024)
        rng = np.random.default_rng(46)
        cols = self._cols(rng, 256, 600, 4)
        with mesh_context(MeshContext(n_data=2, n_model=1)) as ctx:
            _, sgd = self._fit(cols, 600, ctx, "auto")
            assert not sgd.onehot_premat_active  # fell back to build form
            # 'on' overrides the budget (tests, known-good shapes)
            _, sgd_forced = self._fit(cols, 600, ctx, "on")
            assert sgd_forced.onehot_premat_active

    def _streamed_fit(self, cols, d, ctx, premat, window=256):
        from flink_ml_tpu.iteration import HostDataCache

        sgd = SGD(
            max_iter=4, global_batch_size=128, tol=0.0, learning_rate=0.3,
            ctx=ctx, sparse_kernel="onehot", onehot_premat=premat,
            stream_window_rows=window,
        )
        cache = HostDataCache()
        n = len(cols["labels"])
        for a in range(0, n, 64):
            cache.append({k: v[a : a + 64] for k, v in cols.items()})
        cache.finish()
        coef = sgd.optimize(
            np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE
        )
        return coef, sgd

    def test_streamed_premat_matches_build(self):
        # The streamed (larger-than-HBM) route materializes each window's
        # one-hots ON DEVICE from the shipped packed stacks (bounded at the
        # two prefetch-live windows; ingest unchanged) — results must be
        # bit-identical to the streamed build-form kernels.
        rng = np.random.default_rng(47)
        cols = self._cols(rng, 512, 1 << 16, 4)
        with mesh_context(MeshContext(n_data=2, n_model=1)) as ctx:
            c_on, sgd_on = self._streamed_fit(cols, 1 << 16, ctx, "on")
            c_off, sgd_off = self._streamed_fit(cols, 1 << 16, ctx, "off")
            assert sgd_on.onehot_premat_active
            assert not sgd_off.onehot_premat_active
            np.testing.assert_array_equal(c_on, c_off)
            np.testing.assert_array_equal(
                sgd_on.loss_history, sgd_off.loss_history
            )

    def test_streamed_premat_auto_gates_on_budget(self, monkeypatch):
        import flink_ml_tpu.ops.optimizer as opt

        rng = np.random.default_rng(48)
        cols = self._cols(rng, 512, 1 << 16, 4)
        with mesh_context(MeshContext(n_data=2, n_model=1)) as ctx:
            monkeypatch.setattr(opt, "_hbm_bytes_limit", lambda ctx=None: 1024)
            _, sgd = self._streamed_fit(cols, 1 << 16, ctx, "auto")
            assert not sgd.onehot_premat_active
            monkeypatch.setattr(
                opt, "_hbm_bytes_limit", lambda ctx=None: 16 << 30
            )
            _, sgd2 = self._streamed_fit(cols, 1 << 16, ctx, "auto")
            assert sgd2.onehot_premat_active

    def test_invalid_param_raises(self):
        with pytest.raises(ValueError, match="onehot_premat"):
            SGD(onehot_premat="yes")


class TestSgdIntegration:
    def _cols(self, rng, n, d, K):
        idx = rng.integers(0, d, size=(n, K)).astype(np.int32)
        val = rng.normal(size=(n, K)).astype(np.float32)
        y = (rng.random(n) > 0.5).astype(np.float32)
        return {
            "indices": idx, "values": val, "labels": y,
            "weights": np.ones(n, np.float32),
        }

    @pytest.mark.parametrize("n_data", [1, 4])
    def test_onehot_path_matches_scatter_path(self, n_data):
        rng = np.random.default_rng(4)
        n, d, K = 512, 800, 8
        cols = self._cols(rng, n, d, K)
        with mesh_context(MeshContext(n_data=n_data, n_model=1)) as ctx:
            def fit(kernel):
                sgd = SGD(
                    max_iter=30, global_batch_size=128, tol=0.0,
                    learning_rate=0.3, reg=0.01, elastic_net=0.5,
                    ctx=ctx, sparse_kernel=kernel,
                )
                coef = sgd.optimize(
                    np.zeros(d, np.float32),
                    DeviceDataCache(cols, ctx=ctx),
                    BinaryLogisticLoss.INSTANCE,
                )
                return coef, sgd.loss_history

            coef_oh, hist_oh = fit("onehot")
            coef_sc, hist_sc = fit("scatter")
            np.testing.assert_allclose(coef_oh, coef_sc, rtol=1e-3, atol=1e-4)
            np.testing.assert_allclose(hist_oh, hist_sc, rtol=1e-3)

    def test_tol_stops_both_paths_on_same_epoch(self):
        rng = np.random.default_rng(5)
        cols = self._cols(rng, 256, 600, 4)
        with mesh_context(MeshContext(n_data=2, n_model=1)) as ctx:
            hist = {}
            for kernel in ("onehot", "scatter"):
                sgd = SGD(
                    max_iter=200, global_batch_size=128, tol=0.55,
                    learning_rate=0.5, ctx=ctx, sparse_kernel=kernel,
                )
                sgd.optimize(
                    np.zeros(600, np.float32),
                    DeviceDataCache(cols, ctx=ctx),
                    BinaryLogisticLoss.INSTANCE,
                )
                hist[kernel] = sgd.loss_history
            assert len(hist["onehot"]) == len(hist["scatter"])
            np.testing.assert_allclose(hist["onehot"], hist["scatter"], rtol=1e-3)

    def test_layout_memoized_across_fits(self):
        rng = np.random.default_rng(6)
        cols = self._cols(rng, 128, 300, 4)
        with mesh_context(MeshContext(n_data=2, n_model=1)) as ctx:
            cache = DeviceDataCache(cols, ctx=ctx)
            for _ in range(2):
                SGD(
                    max_iter=3, global_batch_size=64, ctx=ctx,
                    sparse_kernel="onehot",
                ).optimize(
                    np.zeros(300, np.float32), cache, BinaryLogisticLoss.INSTANCE
                )
            assert cache._onehot_memo is not None
            memo = cache._onehot_memo
            SGD(
                max_iter=3, global_batch_size=64, ctx=ctx, sparse_kernel="onehot"
            ).optimize(np.zeros(300, np.float32), cache, BinaryLogisticLoss.INSTANCE)
            assert cache._onehot_memo is memo  # same tuple: built once

    def test_auto_gate_prefers_scatter_for_small_models(self):
        rng = np.random.default_rng(7)
        cols = self._cols(rng, 128, 300, 4)
        with mesh_context(MeshContext(n_data=1, n_model=1)) as ctx:
            cache = DeviceDataCache(cols, ctx=ctx)
            SGD(max_iter=2, global_batch_size=64, ctx=ctx).optimize(
                np.zeros(300, np.float32), cache, BinaryLogisticLoss.INSTANCE
            )
            assert getattr(cache, "_onehot_memo", None) is None

    def test_forced_onehot_raises_when_infeasible(self):
        rng = np.random.default_rng(8)
        cols = self._cols(rng, 128, 300, 4)
        with mesh_context(MeshContext(n_data=2, n_model=1)) as ctx:
            cache = DeviceDataCache(cols, ctx=ctx)
            cache.host_columns = {}  # no host copies -> layout unbuildable
            with pytest.raises(ValueError, match="onehot"):
                SGD(
                    max_iter=2, global_batch_size=64, ctx=ctx, sparse_kernel="onehot"
                ).optimize(np.zeros(300, np.float32), cache, BinaryLogisticLoss.INSTANCE)
        # f64: the split-bf16 crossings reconstruct f32, not f64
        with mesh_context(MeshContext(n_data=2, n_model=1)) as ctx:
            with pytest.raises(ValueError, match="f32"):
                SGD(
                    max_iter=2, global_batch_size=64, ctx=ctx,
                    sparse_kernel="onehot", dtype=np.float64,
                ).optimize(
                    np.zeros(300, np.float64),
                    DeviceDataCache(
                        {
                            **{k: v for k, v in cols.items() if k != "values"},
                            "values": np.asarray(cols["values"], np.float64),
                        },
                        ctx=ctx,
                    ),
                    BinaryLogisticLoss.INSTANCE,
                )

    def test_onehot_tp_matches_scatter_tp(self):
        # The round-4 composition: one-hot kernel on a (data x model) mesh.
        # Occupancy-class blocks deal round-robin over the model axis and the
        # crossing dot psums over it; result must match the scatter-TP path.
        rng = np.random.default_rng(20)
        n, d, K = 512, 800, 8
        cols = self._cols(rng, n, d, K)
        with mesh_context(MeshContext(n_data=4, n_model=2)) as ctx:
            def fit(kernel):
                sgd = SGD(
                    max_iter=25, global_batch_size=128, tol=0.0,
                    learning_rate=0.3, reg=0.01, elastic_net=0.5,
                    ctx=ctx, sparse_kernel=kernel,
                )
                coef = sgd.optimize(
                    np.zeros(d, np.float32),
                    DeviceDataCache(cols, ctx=ctx),
                    BinaryLogisticLoss.INSTANCE,
                )
                return coef, sgd.loss_history

            coef_oh, hist_oh = fit("onehot")
            coef_sc, hist_sc = fit("scatter")
            np.testing.assert_allclose(coef_oh, coef_sc, rtol=1e-3, atol=1e-4)
            np.testing.assert_allclose(hist_oh, hist_sc, rtol=1e-3)

    def test_onehot_multislice_matches_scatter(self):
        # Round-5 composition (VERDICT r4 missing #3): the one-hot kernel on
        # a (2 slices x 4 chips) mesh. Stacks/crossings stay intra-slice; the
        # final gradient psum reduces hierarchically over (slice, data) —
        # the result must match the scatter kernel on the same mesh.
        rng = np.random.default_rng(22)
        n, d, K = 512, 800, 8
        cols = self._cols(rng, n, d, K)
        with mesh_context(
            MeshContext(devices=jax.devices()[:8], n_data=4, n_model=1, n_slices=2)
        ) as ctx:
            def fit(kernel):
                sgd = SGD(
                    max_iter=25, global_batch_size=128, tol=0.0,
                    learning_rate=0.3, reg=0.01, elastic_net=0.5,
                    ctx=ctx, sparse_kernel=kernel,
                )
                coef = sgd.optimize(
                    np.zeros(d, np.float32),
                    DeviceDataCache(cols, ctx=ctx),
                    BinaryLogisticLoss.INSTANCE,
                )
                return coef, sgd.loss_history

            coef_oh, hist_oh = fit("onehot")
            coef_sc, hist_sc = fit("scatter")
            np.testing.assert_allclose(coef_oh, coef_sc, rtol=1e-3, atol=1e-4)
            np.testing.assert_allclose(hist_oh, hist_sc, rtol=1e-3)

    def test_onehot_multislice_tp_matches_flat(self):
        # The full composition: (slice=2, data=2, model=2). The model axis is
        # innermost (its crossing psum never leaves a slice); results must
        # match the flat (data=4, model=2) mesh.
        rng = np.random.default_rng(23)
        cols = self._cols(rng, 256, 600, 4)

        def fit(ctx):
            with mesh_context(ctx):
                return SGD(
                    max_iter=10, global_batch_size=64, tol=0.0,
                    learning_rate=0.4, ctx=ctx, sparse_kernel="onehot",
                ).optimize(
                    np.zeros(600, np.float32),
                    DeviceDataCache(cols, ctx=ctx),
                    BinaryLogisticLoss.INSTANCE,
                )

        devices = jax.devices()[:8]
        flat = fit(MeshContext(devices=devices, n_data=4, n_model=2))
        hier = fit(MeshContext(devices=devices, n_data=2, n_model=2, n_slices=2))
        np.testing.assert_allclose(hier, flat, rtol=1e-5, atol=1e-6)

    def test_onehot_tp_invariant_in_model_width(self):
        # Widening the model axis must not change the result (the data axis
        # legitimately changes minibatch composition via per-shard cycling,
        # so n_data is held fixed).
        rng = np.random.default_rng(21)
        cols = self._cols(rng, 256, 600, 4)
        results = {}
        for nd, nm in [(2, 1), (2, 2), (2, 4)]:
            with mesh_context(MeshContext(n_data=nd, n_model=nm)) as ctx:
                results[(nd, nm)] = SGD(
                    max_iter=10, global_batch_size=64, tol=0.0,
                    learning_rate=0.4, ctx=ctx, sparse_kernel="onehot",
                ).optimize(
                    np.zeros(600, np.float32),
                    DeviceDataCache(cols, ctx=ctx),
                    BinaryLogisticLoss.INSTANCE,
                )
        for key, coef in results.items():
            np.testing.assert_allclose(
                coef, results[(2, 1)], rtol=2e-3, atol=1e-4, err_msg=str(key)
            )

    def test_auto_gate_picks_onehot_for_wide_models(self):
        rng = np.random.default_rng(9)
        n, d, K = 1 << 14, 1 << 15, 8  # wide coef, >= 2^16 nnz, few windows
        cols = self._cols(rng, n, d, K)
        with mesh_context(MeshContext(n_data=2, n_model=1)) as ctx:
            cache = DeviceDataCache(cols, ctx=ctx)
            SGD(max_iter=2, global_batch_size=n, ctx=ctx).optimize(
                np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE
            )
            assert getattr(cache, "_onehot_memo", None) is not None  # auto engaged

    def test_forced_onehot_on_dense_data_raises(self):
        rng = np.random.default_rng(10)
        X = rng.normal(size=(64, 8)).astype(np.float32)
        y = (rng.random(64) > 0.5).astype(np.float32)
        with mesh_context(MeshContext(n_data=2, n_model=1)) as ctx:
            with pytest.raises(ValueError, match="dense"):
                SGD(
                    max_iter=2, global_batch_size=32, ctx=ctx, sparse_kernel="onehot"
                ).optimize(
                    np.zeros(8, np.float32),
                    {"features": X, "labels": y},
                    BinaryLogisticLoss.INSTANCE,
                )

    def test_forced_onehot_on_dense_data_raises_on_streamed_path(self):
        from flink_ml_tpu.iteration import HostDataCache

        rng = np.random.default_rng(14)
        cache = HostDataCache()
        cache.append({
            "features": rng.normal(size=(64, 8)).astype(np.float32),
            "labels": (rng.random(64) > 0.5).astype(np.float32),
        })
        with mesh_context(MeshContext(n_data=2, n_model=1)) as ctx:
            with pytest.raises(ValueError, match="dense"):
                SGD(
                    max_iter=2, global_batch_size=32, ctx=ctx, sparse_kernel="onehot"
                ).optimize(np.zeros(8, np.float32), cache, BinaryLogisticLoss.INSTANCE)

    def test_forced_onehot_on_dense_data_raises_with_listeners(self):
        # The misconfiguration must fail on the host-loop path too, not just
        # where the fused path consults the kernel choice.
        from flink_ml_tpu.iteration import IterationListener

        rng = np.random.default_rng(11)
        X = rng.normal(size=(64, 8)).astype(np.float32)
        y = (rng.random(64) > 0.5).astype(np.float32)
        with mesh_context(MeshContext(n_data=2, n_model=1)) as ctx:
            with pytest.raises(ValueError, match="dense"):
                SGD(
                    max_iter=2, global_batch_size=32, ctx=ctx,
                    sparse_kernel="onehot", listeners=[IterationListener()],
                ).optimize(
                    np.zeros(8, np.float32),
                    {"features": X, "labels": y},
                    BinaryLogisticLoss.INSTANCE,
                )

    def test_auto_gate_falls_back_when_stacks_exceed_hbm(self, monkeypatch):
        # A dataset whose one-hot stacks (7 B/slot packed) would overrun HBM must
        # stay on the scatter path under 'auto' instead of OOMing.
        import flink_ml_tpu.ops.optimizer as opt_mod

        rng = np.random.default_rng(12)
        n, d, K = 1 << 14, 1 << 15, 8
        cols = self._cols(rng, n, d, K)
        monkeypatch.setattr(opt_mod, "_hbm_bytes_limit", lambda ctx=None: 1 << 20)
        with mesh_context(MeshContext(n_data=2, n_model=1)) as ctx:
            cache = DeviceDataCache(cols, ctx=ctx)
            coef = SGD(max_iter=2, global_batch_size=n, ctx=ctx).optimize(
                np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE
            )
            memo = getattr(cache, "_onehot_memo", None)
            assert memo is not None and memo[2] is None  # layout judged, stacks skipped
            assert np.all(np.isfinite(coef))  # scatter fallback trained
            # forcing 'onehot' overrides the budget (caller takes the risk)
            SGD(
                max_iter=2, global_batch_size=n, ctx=ctx, sparse_kernel="onehot"
            ).optimize(np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE)
            assert cache._onehot_memo[2] is not None

    def test_onehot_output_dtype_matches_scatter_for_f64_init(self):
        # Auto-selection must not change the caller-visible dtype: both sparse
        # kernels return self.dtype (f32) for a float64 init_model.
        rng = np.random.default_rng(13)
        cols = self._cols(rng, 256, 600, 4)
        with mesh_context(MeshContext(n_data=2, n_model=1)) as ctx:
            dtypes = {}
            for kernel in ("onehot", "scatter"):
                cache = DeviceDataCache(cols, ctx=ctx)
                coef = SGD(
                    max_iter=2, global_batch_size=64, ctx=ctx, sparse_kernel=kernel
                ).optimize(
                    np.zeros(600, np.float64), cache, BinaryLogisticLoss.INSTANCE
                )
                dtypes[kernel] = coef.dtype
            assert dtypes["onehot"] == dtypes["scatter"] == np.float32
