"""Retrieval tier (docs/retrieval.md) — device-resident top-K serving.

The acceptance contract:

- **parity**: the fused top-K head reproduces a plain-numpy reference scorer
  exactly — ids AND scores — at K=10 and K=100, and the LSH head reproduces
  ``MinHashLSHModel.approx_nearest_neighbors`` (bucket-share prune → exact
  1 − Jaccard rank, stable ascending ties) row for row;
- **ladder**: per-request K compiles at power-of-two rungs, off-ladder K
  falls back per-stage reason-labelled, and a rung-wide result trimmed to a
  smaller K is bit-identical to the smaller rung's answer (prefix stability);
- **lifecycle**: an index publishes/loads/swaps through the same
  registry/poller machinery model versions use; serving across a hot index
  swap is bit-exact per version with zero post-warmup compiles;
- **sharding**: mesh widths 1/2/4 produce bit-identical rankings;
- **typed empties**: empty histories, unknown items, bucket-less LSH queries
  and empty candidate sets all produce typed empty results, never errors.
"""
import os
import tempfile

import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.config import Options, config
from flink_ml_tpu.linalg.vectors import SparseVector
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.retrieval import CandidateIndex, RetrievalClient
from flink_ml_tpu.servable.api import load_servable
from flink_ml_tpu.servable.planner import IneligibleBatch
from flink_ml_tpu.servable.retrieval import (
    HASH_PRIME,
    LSHTopKServable,
    SwingTopKServable,
    minhash_values,
)
from flink_ml_tpu.servable.shapes import k_rung, resolve_warm_ks, shape_name
from flink_ml_tpu.serving import InferenceServer, ServingConfig, publish_servable
from flink_ml_tpu.serving.batcher import pad_to
from flink_ml_tpu.serving.plan import CompiledServingPlan

RNG = np.random.default_rng(171)


@pytest.fixture(autouse=True)
def _reset_retrieval_config():
    yield
    for opt in (
        Options.RETRIEVAL_K_CAP_MAX,
        Options.RETRIEVAL_WARMUP_KS,
        Options.RETRIEVAL_LSH_PRUNE_CAP,
        Options.SPARSE_WARMUP_CAPS,
        Options.SPARSE_NNZ_CAP_MAX,
    ):
        config.unset(opt)


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------
def _swing_index(n_items=60, base=100, seed=21, max_nbrs=8, output_col="rec"):
    """A swing CandidateIndex distilled from a synthetic similarity table."""
    rng = np.random.default_rng(seed)
    items = np.arange(base, base + n_items, dtype=np.int64)
    encs = []
    for it in items:
        nbrs = rng.choice(
            np.setdiff1d(items, [it]), size=rng.integers(2, max_nbrs + 1), replace=False
        )
        scores = rng.random(len(nbrs)).round(4)
        encs.append(";".join(f"{n},{s}" for n, s in zip(nbrs, scores)))
    df = DataFrame(["item", "output"], None, [items, encs])
    idx = CandidateIndex.from_swing_output(df, item_col="item", output_col="output")
    idx.set_output_col(output_col)
    return idx


def _histories(idx, n, seed, max_len=5):
    rng = np.random.default_rng(seed)
    items = idx.item_ids
    return [
        [
            (int(items[rng.integers(0, len(items))]), float(rng.random()) + 0.1)
            for _ in range(rng.integers(1, max_len))
        ]
        for _ in range(n)
    ]


def numpy_swing_reference(idx, history, k):
    """The plain-numpy reference scorer the fused head must reproduce
    EXACTLY: f32 scatter-add over each history row's neighbor list in slot
    order, consumed candidates masked, stable descending argsort."""
    vocab = idx.item_ids
    simv = np.asarray(idx.arrays["sim_values"], np.float32)
    simi = np.asarray(idx.arrays["sim_ids"], np.int64)
    row_of = {int(v): r for r, v in enumerate(vocab)}
    C = len(vocab)
    scores = np.zeros(C, np.float32)
    hit = np.zeros(C, bool)
    agg = {}
    for item, w in history:
        r = row_of.get(int(item))
        if r is not None:
            agg[r] = agg.get(r, 0.0) + w
    for r in sorted(agg):  # slot order == sorted candidate rows
        hit[r] = True
        for j in range(simv.shape[1]):
            if simv[r, j] != 0.0:
                scores[simi[r, j]] += np.float32(np.float32(agg[r]) * simv[r, j])
    if not agg:
        return np.empty(0, np.int64), np.empty(0, np.float64)
    out = scores.astype(np.float64)
    out[hit] = -np.inf
    order = np.argsort(-out, kind="stable")[:k]
    keep = np.isfinite(out[order])
    return vocab[order[keep]], out[order[keep]]


def _lsh_fixture(D=40, C=30, T=3, F=2, seed=7):
    """A fitted-model stand-in + candidate frame + CandidateIndex."""
    rng = np.random.default_rng(seed)

    class _Fam:
        coeff_a = rng.integers(1, 10_000, T * F).astype(np.int64)
        coeff_b = rng.integers(0, 10_000, T * F).astype(np.int64)

        def get_num_hash_tables(self):
            return T

        def get_num_hash_functions_per_table(self):
            return F

        def get_input_col(self):
            return "vec"

    cands = []
    for _ in range(C):
        nz = np.sort(rng.choice(D, size=rng.integers(1, 8), replace=False))
        cands.append(SparseVector(D, nz.astype(np.int64), np.ones(len(nz))))
    cdf = DataFrame(
        ["id", "vec"], None, [np.arange(500, 500 + C, dtype=np.int64), cands]
    )
    idx = CandidateIndex.from_lsh_model(_Fam(), cdf, id_col="id")
    idx.set_output_col("nn")
    return _Fam(), cdf, idx, rng


def numpy_lsh_reference(idx, query, k, T, F):
    """Reference two-phase retrieval: full-bucket share prune, exact
    1 − Jaccard rank, stable ascending (ties to the lowest candidate row)."""
    coeff_a = np.asarray(idx.arrays["coeff_a"], np.int64)
    coeff_b = np.asarray(idx.arrays["coeff_b"], np.int64)
    cand_ids = np.asarray(idx.arrays["cand_ids"], np.int64)
    cand_nnz = np.asarray(idx.arrays["cand_nnz"], np.int64)
    qs = np.asarray(query.indices, np.int64)
    if qs.size == 0:
        return np.empty(0, np.int64), np.empty(0, np.float64)
    qh = minhash_values(qs, coeff_a, coeff_b).reshape(T, F)
    rows, dists = [], []
    for r in range(cand_ids.shape[0]):
        cs = cand_ids[r, : cand_nnz[r]]
        ch = minhash_values(cs, coeff_a, coeff_b).reshape(T, F)
        if not (qh == ch).all(axis=1).any():
            continue
        inter = len(np.intersect1d(qs, cs))
        union = len(np.union1d(qs, cs))
        rows.append(r)
        dists.append(1.0 - inter / max(union, 1))
    order = np.argsort(np.asarray(dists), kind="stable")[:k]
    rows = np.asarray(rows, np.int64)[order] if rows else np.empty(0, np.int64)
    return idx.item_ids[rows], np.asarray(dists, np.float64)[order]


# ---------------------------------------------------------------------------
# the K ladder
# ---------------------------------------------------------------------------
class TestKLadder:
    def test_k_rung_rounds_to_powers_of_two(self):
        assert [k_rung(k) for k in (1, 2, 3, 10, 16, 100)] == [1, 2, 4, 16, 16, 128]

    def test_warm_ks_default_ladder_and_override(self):
        config.set(Options.RETRIEVAL_K_CAP_MAX, 16)
        assert resolve_warm_ks() == (1, 2, 4, 8, 16)
        config.set(Options.RETRIEVAL_WARMUP_KS, "10,16")
        assert resolve_warm_ks() == (16,)  # 10 rounds up to its rung

    def test_off_ladder_k_is_ineligible(self):
        idx = _swing_index(n_items=20)
        config.set(Options.RETRIEVAL_K_CAP_MAX, 8)
        plan = CompiledServingPlan.build(
            idx.servable(), scope="t-ret-offladder",
            sparse={"history": idx.candidate_count},
        )
        seg = plan.segments[0]
        df = DataFrame(["k"], None, [np.asarray([64], np.int64)])
        with pytest.raises(IneligibleBatch) as ei:
            seg.gather_shape(df, ["k"], cap_max=8)
        assert ei.value.reason == "off_ladder"

    def test_prefix_stability_across_rungs(self):
        """The top-10 of a row is bit-for-bit the first 10 of its top-16 —
        what lets the client trim a rung-wide result to the requested K."""
        idx = _swing_index(n_items=40, seed=33)
        head = idx.servable()
        hist = RetrievalClient(head, idx).history_vector(
            _histories(idx, 1, seed=34)[0]
        )
        lo = head.transform(
            DataFrame(["history", "k"], None, [[hist], np.asarray([10], np.int64)])
        )
        hi = head.transform(
            DataFrame(["history", "k"], None, [[hist], np.asarray([16], np.int64)])
        )
        np.testing.assert_array_equal(
            np.asarray(lo.column("rec_rows"))[0][:10],
            np.asarray(hi.column("rec_rows"))[0][:10],
        )
        np.testing.assert_array_equal(
            np.asarray(lo.column("rec_scores"))[0][:10],
            np.asarray(hi.column("rec_scores"))[0][:10],
        )


# ---------------------------------------------------------------------------
# swing parity vs the numpy reference
# ---------------------------------------------------------------------------
class TestSwingParity:
    @pytest.mark.parametrize("k", [10, 100])
    def test_fused_matches_numpy_reference(self, k):
        idx = _swing_index(n_items=150, seed=41)
        head = idx.servable()
        client = RetrievalClient(head, idx)
        histories = _histories(idx, 12, seed=42)
        for hist, (ids, scores) in zip(histories, client.query(histories, k)):
            rid, rsc = numpy_swing_reference(idx, hist, k)
            np.testing.assert_array_equal(ids, rid)
            np.testing.assert_array_equal(scores, rsc)

    def test_empty_and_unknown_histories_are_typed_empty(self):
        idx = _swing_index(n_items=20, seed=43)
        client = RetrievalClient(idx.servable(), idx)
        res = client.query([[], [(999_999, 1.0)]], 5)
        for ids, scores in res:
            assert ids.dtype == np.int64 and len(ids) == 0
            assert scores.dtype == np.float64 and len(scores) == 0

    def test_consumed_candidates_never_recommended(self):
        idx = _swing_index(n_items=30, seed=44)
        client = RetrievalClient(idx.servable(), idx)
        histories = _histories(idx, 8, seed=45)
        for hist, (ids, _) in zip(histories, client.query(histories, 30)):
            assert not set(int(i) for i, _ in hist) & set(ids.tolist())


# ---------------------------------------------------------------------------
# LSH parity vs the reference prune→rank semantics
# ---------------------------------------------------------------------------
class TestLSHParity:
    def test_fused_matches_reference_prune_rank(self):
        fam, cdf, idx, rng = _lsh_fixture()
        client = RetrievalClient(idx.servable(), idx)
        D = 40
        queries = []
        for _ in range(10):
            nz = np.sort(rng.choice(D, size=rng.integers(1, 6), replace=False))
            queries.append(SparseVector(D, nz.astype(np.int64), np.ones(len(nz))))
        for q, (ids, dist) in zip(queries, client.query(queries, 5)):
            rid, rdist = numpy_lsh_reference(idx, q, 5, T=3, F=2)
            np.testing.assert_array_equal(ids, rid)
            np.testing.assert_allclose(dist, rdist, rtol=0, atol=1e-6)

    def test_matches_model_approx_nearest_neighbors(self):
        """The served head and the reference-semantics host path agree row
        for row — including distance ties (stable, lowest row first)."""
        from flink_ml_tpu.models.feature.lsh import MinHashLSH

        D, C = 30, 20
        rng = np.random.default_rng(5)
        vecs = []
        for _ in range(C):
            nz = np.sort(rng.choice(D, size=rng.integers(1, 6), replace=False))
            vecs.append(SparseVector(D, nz.astype(np.int64), np.ones(len(nz))))
        df = DataFrame(["id", "vec"], None, [np.arange(C, dtype=np.int64), vecs])
        model = (
            MinHashLSH()
            .set_input_col("vec")
            .set_output_col("h")
            .set_num_hash_tables(2)
            .set_num_hash_functions_per_table(2)
            .set_seed(11)
            .fit(df)
        )
        idx = CandidateIndex.from_lsh_model(model, df, id_col="id")
        idx.set_output_col("nn")
        client = RetrievalClient(idx.servable(), idx)
        key = SparseVector(D, np.asarray([1, 5, 9], np.int64), np.ones(3))
        ids, dist = client.query([key], 5)[0]
        ref = model.approx_nearest_neighbors(df, key, 5)
        np.testing.assert_array_equal(ids, np.asarray(ref.column("id"), np.int64))
        np.testing.assert_allclose(dist, np.asarray(ref.column("distCol")), atol=1e-6)

    def test_empty_query_and_no_bucket_share_are_typed_empty(self):
        _, _, idx, _ = _lsh_fixture()
        client = RetrievalClient(idx.servable(), idx)
        D = 40
        empty = SparseVector(D, np.asarray([], np.int64), np.asarray([], np.float64))
        res = client.query([empty], 5)
        assert len(res[0][0]) == 0 and len(res[0][1]) == 0

    def test_approx_nearest_neighbors_skips_unhashable_rows(self):
        """Satellite fix: all-zero candidate rows are skipped (the reference
        raised) and an empty candidate set returns typed empty results."""
        from flink_ml_tpu.models.feature.lsh import MinHashLSH

        D = 20
        vecs = [
            SparseVector(D, np.asarray([1, 3], np.int64), np.ones(2)),
            SparseVector(D, np.asarray([], np.int64), np.asarray([], np.float64)),
        ]
        df = DataFrame(["id", "vec"], None, [np.arange(2, dtype=np.int64), vecs])
        model = (
            MinHashLSH().set_input_col("vec").set_output_col("h").set_seed(3).fit(df)
        )
        key = SparseVector(D, np.asarray([1, 3], np.int64), np.ones(2))
        out = model.approx_nearest_neighbors(df, key, 3)
        assert np.asarray(out.column("id")).tolist() == [0]
        # all-empty dataset → typed empty frame, distCol present
        empties = DataFrame(["id", "vec"], None, [np.asarray([7], np.int64), [vecs[1]]])
        out2 = model.approx_nearest_neighbors(empties, key, 3)
        assert len(out2) == 0 and "distCol" in out2.column_names

    def test_hash_prime_single_source(self):
        from flink_ml_tpu.models.feature import lsh as lsh_mod

        assert lsh_mod.HASH_PRIME is HASH_PRIME


# ---------------------------------------------------------------------------
# index lifecycle: save / load / publish / load_servable hooks
# ---------------------------------------------------------------------------
class TestIndexLifecycle:
    def test_save_load_servable_round_trip_bit_exact(self, tmp_path):
        idx = _swing_index(seed=51)
        path = str(tmp_path / "idx")
        idx.save(path)
        head = load_servable(path)  # className dispatch from metadata
        assert isinstance(head, SwingTopKServable)
        assert head.get_output_col() == "rec"
        client_a = RetrievalClient(head, idx)
        client_b = RetrievalClient(idx.servable(), idx)
        hist = _histories(idx, 3, seed=52)
        for (ia, sa), (ib, sb) in zip(client_a.query(hist, 7), client_b.query(hist, 7)):
            np.testing.assert_array_equal(ia, ib)
            np.testing.assert_array_equal(sa, sb)

    def test_model_class_hooks_load_the_heads(self, tmp_path):
        from flink_ml_tpu.models.feature.lsh import MinHashLSHModel
        from flink_ml_tpu.models.recommendation.swing import Swing

        sw_idx = _swing_index(seed=53)
        p1 = str(tmp_path / "sw")
        sw_idx.save(p1)
        assert isinstance(Swing.load_servable(p1), SwingTopKServable)
        _, _, lsh_idx, _ = _lsh_fixture(seed=54)
        p2 = str(tmp_path / "lsh")
        lsh_idx.save(p2)
        assert isinstance(MinHashLSHModel.load_servable(p2), LSHTopKServable)

    def test_publish_through_registry_machinery(self, tmp_path):
        idx = _swing_index(seed=55)
        root = str(tmp_path / "versions")
        vpath = publish_servable(idx, root)
        assert vpath == os.path.join(root, "v-1")
        head = load_servable(vpath)
        assert isinstance(head, SwingTopKServable)
        assert head.candidate_count == idx.candidate_count

    def test_index_load_round_trip(self, tmp_path):
        idx = _swing_index(seed=56)
        path = str(tmp_path / "idx")
        idx.save(path)
        idx2 = CandidateIndex.load(path)
        assert idx2.get_index_kind() == "swing"
        np.testing.assert_array_equal(idx2.item_ids, idx.item_ids)
        np.testing.assert_array_equal(
            idx2.arrays["sim_values"], idx.arrays["sim_values"]
        )


# ---------------------------------------------------------------------------
# Swing structured output (satellite)
# ---------------------------------------------------------------------------
class TestSwingStructuredOutput:
    def _train_frame(self, seed=3, n_users=40, n_items=15, n_rows=600):
        rng = np.random.default_rng(seed)
        return DataFrame(
            ["user", "item"],
            None,
            [
                rng.integers(0, n_users, n_rows).astype(np.int64),
                rng.integers(0, n_items, n_rows).astype(np.int64),
            ],
        )

    def test_structured_columns_agree_with_string_encoding(self):
        from flink_ml_tpu.models.recommendation.swing import Swing

        out = (
            Swing()
            .set_min_user_behavior(3)
            .set_max_user_behavior(100)
            .set_k(5)
            .set_structured_output(True)
            .transform(self._train_frame())
        )
        assert set(out.column_names) >= {"output", "output_ids", "output_scores"}
        ids_mat = np.asarray(out.column("output_ids"))
        sc_mat = np.asarray(out.column("output_scores"))
        for s, nid, sc in zip(out.column("output"), ids_mat, sc_mat):
            pairs = [p.split(",") for p in s.split(";") if p]
            keep = nid >= 0
            np.testing.assert_array_equal(
                np.asarray([int(i) for i, _ in pairs], np.int64), nid[keep]
            )
            np.testing.assert_allclose(
                np.asarray([float(v) for _, v in pairs]), sc[keep]
            )

    def test_index_identical_from_either_encoding(self):
        from flink_ml_tpu.models.recommendation.swing import Swing

        out = (
            Swing()
            .set_min_user_behavior(3)
            .set_max_user_behavior(100)
            .set_k(5)
            .set_structured_output(True)
            .transform(self._train_frame(seed=9))
        )
        idx_struct = CandidateIndex.from_swing_output(out)
        idx_str = CandidateIndex.from_swing_output(out.select(["item", "output"]))
        np.testing.assert_array_equal(idx_struct.item_ids, idx_str.item_ids)
        np.testing.assert_array_equal(
            idx_struct.arrays["sim_ids"], idx_str.arrays["sim_ids"]
        )
        np.testing.assert_allclose(
            idx_struct.arrays["sim_values"], idx_str.arrays["sim_values"]
        )

    def test_empty_output_carries_structured_columns(self):
        from flink_ml_tpu.models.recommendation.swing import Swing

        empty_in = DataFrame(
            ["user", "item"],
            None,
            [np.asarray([], np.int64), np.asarray([], np.int64)],
        )
        out = Swing().set_structured_output(True).transform(empty_in)
        assert set(out.column_names) >= {"output_ids", "output_scores"}
        assert len(out) == 0


# ---------------------------------------------------------------------------
# the served path: fused plan, zero compiles, hot swap, shape-key affinity
# ---------------------------------------------------------------------------
class TestServedPath:
    def _server_config(self):
        config.set(Options.SPARSE_WARMUP_CAPS, "4")
        config.set(Options.SPARSE_NNZ_CAP_MAX, 8)
        config.set(Options.RETRIEVAL_WARMUP_KS, "16")
        config.set(Options.RETRIEVAL_K_CAP_MAX, 16)

    def _template(self, idx):
        hist = SparseVector(
            idx.candidate_count,
            np.asarray([0, 3], np.int64),
            np.asarray([1.0, 2.0]),
        )
        return DataFrame(["history", "k"], None, [[hist], np.asarray([10], np.int64)])

    def test_served_fused_zero_postwarmup_compiles(self, monkeypatch):
        self._server_config()
        idx = _swing_index(seed=61)
        cfg = ServingConfig(max_batch_size=8, max_delay_ms=0.0)
        with InferenceServer(
            idx.servable(),
            name="t-ret-zc",
            serving_config=cfg,
            warmup_template=self._template(idx),
        ) as server:
            scope = "ml.serving[t-ret-zc]"
            client = RetrievalClient(server, idx)
            histories = _histories(idx, 6, seed=62)
            fused0 = metrics.get(scope, MLMetrics.SERVING_FUSED_BATCHES, 0)
            compiles0 = metrics.get(scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0)
            import flink_ml_tpu.servable.planner as planner_mod

            def poisoned(lowered):
                raise AssertionError("XLA compile after warmup")

            monkeypatch.setattr(planner_mod, "_compile_lowered", poisoned)
            res = client.query(histories, 10)
            assert metrics.get(scope, MLMetrics.SERVING_FUSED_BATCHES, 0) > fused0
            assert (
                metrics.get(scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0) == compiles0
            )
            for hist, (ids, scores) in zip(histories, res):
                rid, rsc = numpy_swing_reference(idx, hist, 10)
                np.testing.assert_array_equal(ids, rid)
                np.testing.assert_array_equal(scores, rsc)

    def test_per_request_k_trimmed_exactly(self):
        self._server_config()
        idx = _swing_index(seed=63)
        cfg = ServingConfig(max_batch_size=8, max_delay_ms=5.0)
        with InferenceServer(
            idx.servable(),
            name="t-ret-k",
            serving_config=cfg,
            warmup_template=self._template(idx),
        ) as server:
            client = RetrievalClient(server, idx)
            histories = _histories(idx, 4, seed=64)
            ks = [3, 7, 10, 16]
            for (ids, scores), k, hist in zip(
                client.query(histories, ks), ks, histories
            ):
                rid, rsc = numpy_swing_reference(idx, hist, k)
                assert len(ids) <= k
                np.testing.assert_array_equal(ids, rid)
                np.testing.assert_array_equal(scores, rsc)

    def test_hot_index_swap_bit_exact_per_version(self, monkeypatch):
        self._server_config()
        v1 = _swing_index(seed=65)
        v2 = _swing_index(seed=66)  # same catalog shape, different similarities
        cfg = ServingConfig(max_batch_size=8, max_delay_ms=0.0)
        with InferenceServer(
            v1.servable(),
            name="t-ret-swap",
            serving_config=cfg,
            warmup_template=self._template(v1),
        ) as server:
            scope = "ml.serving[t-ret-swap]"
            histories = _histories(v1, 4, seed=67)
            client = RetrievalClient(server, v1)
            res1 = client.query(histories, 10)
            server.swap(2, v2.servable())
            compiles0 = metrics.get(scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0)
            import flink_ml_tpu.servable.planner as planner_mod

            monkeypatch.setattr(
                planner_mod,
                "_compile_lowered",
                lambda lowered: (_ for _ in ()).throw(
                    AssertionError("compile across hot swap")
                ),
            )
            res2 = RetrievalClient(server, v2).query(histories, 10)
            assert (
                metrics.get(scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0) == compiles0
            )
            for hist, (i1, s1), (i2, s2) in zip(histories, res1, res2):
                r1 = numpy_swing_reference(v1, hist, 10)
                r2 = numpy_swing_reference(v2, hist, 10)
                np.testing.assert_array_equal(i1, r1[0])
                np.testing.assert_array_equal(s1, r1[1])
                np.testing.assert_array_equal(i2, r2[0])
                np.testing.assert_array_equal(s2, r2[1])

    def test_shape_key_affinity_never_mixes_rungs(self):
        """Requests headed for different K rungs never coalesce into one
        batch (purely an optimization — checked at the batcher seam)."""
        import threading

        from flink_ml_tpu.serving.batcher import MicroBatcher

        seen = []

        def execute(df):
            seen.append(sorted(set(np.asarray(df.column("k"), np.int64).tolist())))
            out = df.clone()
            return out, 1

        class _Resp:
            def __init__(self, df, version, latency_ms, bucket):
                self.dataframe = df

        batcher = MicroBatcher(
            execute,
            max_batch_size=8,
            max_delay_ms=60.0,
            queue_capacity_rows=64,
            scope="t-ret-affinity",
            response_factory=_Resp,
        )
        try:
            frames = []
            for k in (4, 64, 4, 64):
                frames.append(
                    DataFrame(["k"], None, [np.asarray([k], np.int64)])
                )
            handles = [
                batcher.submit(df, timeout_s=5.0, shape_key=f"k{k_rung(int(df.column('k')[0]))}")
                for df in frames
            ]
            for h in handles:
                h.result()
        finally:
            batcher.close()
        for ks in seen:
            rungs = {k_rung(int(k)) for k in ks}
            assert len(rungs) == 1, f"mixed K rungs in one batch: {ks}"


# ---------------------------------------------------------------------------
# mesh sharding: widths 1/2/4 bit-identical
# ---------------------------------------------------------------------------
class TestShardedRetrieval:
    @pytest.mark.parametrize("mesh", [2, 4])
    def test_mesh_width_bit_stable(self, mesh):
        import jax

        from flink_ml_tpu.servable.sharding import PlanSharding

        if mesh > len(jax.devices()):
            pytest.skip(f"needs {mesh} devices, host exposes {len(jax.devices())}")
        config.set(Options.SPARSE_WARMUP_CAPS, "4")
        config.set(Options.RETRIEVAL_WARMUP_KS, "16")
        idx = _swing_index(seed=71)
        C = idx.candidate_count
        rows = mesh * 4
        client = RetrievalClient(idx.servable(), idx)
        hists = [client.history_vector(h) for h in _histories(idx, rows, seed=72)]
        df = DataFrame(
            ["history", "k"],
            None,
            [hists, np.full(rows, 10, np.int64)],
        )
        single = CompiledServingPlan.build(
            idx.servable(), scope=f"t-ret-m1-{mesh}", sparse={"history": C}
        )
        sharded = CompiledServingPlan.build(
            idx.servable(),
            scope=f"t-ret-mN-{mesh}",
            sharding=PlanSharding(mesh),
            sparse={"history": C},
        )
        out1 = single.execute(pad_to(df, rows))
        outN = sharded.execute(pad_to(df, rows))
        np.testing.assert_array_equal(
            np.asarray(out1.column("rec_rows")), np.asarray(outN.column("rec_rows"))
        )
        np.testing.assert_array_equal(
            np.asarray(out1.column("rec_scores")).view(np.int64),
            np.asarray(outN.column("rec_scores")).view(np.int64),
        )


# ---------------------------------------------------------------------------
# offline batch tier: shape-kind columns fall back per-stage
# ---------------------------------------------------------------------------
class TestBatchTierGuard:
    def test_shape_kind_falls_back_reason_labelled(self):
        from flink_ml_tpu.builder.batch_plan import CompiledBatchPlan

        idx = _swing_index(n_items=20, seed=73)
        C = idx.candidate_count
        head = idx.servable()
        plan = CompiledBatchPlan.build(
            [head], scope="retguard", sparse={"history": C}
        )
        if plan is None:
            pytest.skip("no fused segment built for a lone retrieval head")
        client = RetrievalClient(head, idx)
        hists = [client.history_vector(h) for h in _histories(idx, 4, seed=74)]
        df = DataFrame(["history", "k"], None, [hists, np.full(4, 5, np.int64)])
        scope = plan.scope
        reason = MLMetrics.fallback_reason("batch", "shape_kind")
        before = metrics.get(scope, reason, 0)
        out = plan.transform(df)
        assert metrics.get(scope, reason, 0) == before + 1
        # the per-stage fallback still answers correctly
        for hist, rows in zip(
            _histories(idx, 4, seed=74), np.asarray(out.column("rec_rows"), np.int64)
        ):
            rid, _ = numpy_swing_reference(idx, hist, 5)
            got = idx.item_ids[rows[:5][rows[:5] >= 0]]
            np.testing.assert_array_equal(got, rid)
