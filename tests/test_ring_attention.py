"""Ring attention (parallel/ring.py): sequence-parallel blockwise attention
must match dense single-device attention exactly (up to float tolerance),
causal and not, on the 8-device mesh."""
import numpy as np
import pytest

from flink_ml_tpu.parallel.ring import ring_attention_sharded


def _dense_attention(q, k, v, causal):
    B, T, H, D = q.shape
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    if causal:
        mask = np.tril(np.ones((T, T), bool))
        s = np.where(mask[None, None], s, -np.inf)
    s = s - s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(axis=-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_dense_attention(causal):
    rng = np.random.default_rng(0)
    B, T, H, D = 2, 64, 2, 8  # T sharded 8 ways -> 8 ring steps
    q = rng.standard_normal((B, T, H, D)).astype(np.float32)
    k = rng.standard_normal((B, T, H, D)).astype(np.float32)
    v = rng.standard_normal((B, T, H, D)).astype(np.float32)
    got = np.asarray(ring_attention_sharded(q, k, v, causal=causal))
    want = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_long_sequence_runs():
    # 16k tokens on the virtual mesh: the [T, T] score matrix (256M floats)
    # never materializes; per-shard peak is O(T_local^2) per ring step.
    rng = np.random.default_rng(1)
    B, T, H, D = 1, 16_384, 1, 16
    q = rng.standard_normal((B, T, H, D)).astype(np.float32)
    out = np.asarray(ring_attention_sharded(q, q, q, causal=True))
    assert out.shape == (B, T, H, D)
    assert np.all(np.isfinite(out))
    # position 0 attends only to itself under causal masking
    np.testing.assert_allclose(out[0, 0, 0], q[0, 0, 0], rtol=1e-5)


def test_uneven_sequence_rejected():
    q = np.zeros((1, 10, 1, 4), np.float32)
    with pytest.raises(ValueError, match="divisible"):
        ring_attention_sharded(q, q, q)


def test_flash_gate_rejects_unverified_boundary_shapes():
    # Shapes past T_local=8192 stage VMEM the scoped limit does not cover
    # (e.g. T=16384, D=64: a [256, 16384] f32 score buffer plus full KV) and
    # were never compile-verified on chip — the gate must refuse them so the
    # caller falls back to the jnp fold rather than fail Mosaic compilation.
    from flink_ml_tpu.parallel.flash import TQ_TILE, flash_available

    class FakeTpu:
        device_kind = "TPU v5 lite"

    devs = [FakeTpu()]
    assert flash_available(8192, 128, devs)  # the hardware-measured shape
    assert not flash_available(16384, 64, devs)  # boundary: rejected
    assert not flash_available(8192, 256, devs)  # KV budget still enforced
    assert not flash_available(TQ_TILE - 1, 64, devs)  # tiling still enforced


def test_padded_sequence_with_n_valid_matches_dense():
    rng = np.random.default_rng(2)
    B, T_real, H, D = 1, 50, 2, 8
    T_pad = 56  # next multiple of the 8-way mesh
    q = rng.standard_normal((B, T_real, H, D)).astype(np.float32)
    k = rng.standard_normal((B, T_real, H, D)).astype(np.float32)
    v = rng.standard_normal((B, T_real, H, D)).astype(np.float32)
    pad = ((0, 0), (0, T_pad - T_real), (0, 0), (0, 0))
    got = np.asarray(
        ring_attention_sharded(
            np.pad(q, pad), np.pad(k, pad), np.pad(v, pad), n_valid=T_real
        )
    )[:, :T_real]
    want = _dense_attention(q, k, v, causal=False)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_gradients_match_dense_attention():
    """jax.grad flows through the ring schedule (scan + ppermute are
    differentiable), matching dense-attention gradients — the property a
    sequence-model trainer would rely on."""
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.parallel.mesh import get_mesh_context
    from flink_ml_tpu.parallel.ring import _sharded_program

    rng = np.random.default_rng(3)
    B, T, H, D = 1, 32, 2, 4
    q = jnp.asarray(rng.standard_normal((B, T, H, D)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, T, H, D)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, T, H, D)).astype(np.float32))
    ctx = get_mesh_context()
    program = _sharded_program(ctx.mesh, True, False, False)

    def ring_loss(q, k, v):
        return jnp.sum(program(q, k, v) ** 2)

    def dense_loss(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        return jnp.sum(out ** 2)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), rtol=2e-4, atol=2e-5)


class TestFlashFold:
    """The fused Pallas fold (parallel/flash.py) must reproduce the jnp fold
    and, through the ring, dense attention — in interpret mode on any
    backend (compiled on TPU)."""

    def test_flash_ring_matches_dense(self):
        import jax.numpy as jnp
        from jax.experimental.pallas import tpu as pltpu

        from flink_ml_tpu.parallel.mesh import get_mesh_context
        from flink_ml_tpu.parallel.ring import _sharded_program

        rng = np.random.default_rng(4)
        ctx = get_mesh_context()
        T = 256 * ctx.n_data  # T_local = one Q tile per shard
        B, H, D = 1, 2, 8
        q = rng.standard_normal((B, T, H, D)).astype(np.float32)
        k = rng.standard_normal((B, T, H, D)).astype(np.float32)
        v = rng.standard_normal((B, T, H, D)).astype(np.float32)
        with pltpu.force_tpu_interpret_mode():
            got = np.asarray(
                _sharded_program(ctx.mesh, True, False, True)(q, k, v)
            )
        want = _dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_flash_ring_padded_n_valid(self):
        import jax.numpy as jnp
        from jax.experimental.pallas import tpu as pltpu

        from flink_ml_tpu.parallel.mesh import get_mesh_context
        from flink_ml_tpu.parallel.ring import _sharded_program

        rng = np.random.default_rng(5)
        ctx = get_mesh_context()
        T = 256 * ctx.n_data
        n_real = T - 100
        B, H, D = 1, 1, 8
        q = rng.standard_normal((B, T, H, D)).astype(np.float32)
        k = rng.standard_normal((B, T, H, D)).astype(np.float32)
        v = rng.standard_normal((B, T, H, D)).astype(np.float32)
        with pltpu.force_tpu_interpret_mode():
            got = np.asarray(
                _sharded_program(ctx.mesh, False, True, True)(
                    q, k, v, jnp.asarray(n_real, jnp.int32)
                )
            )
        want = _dense_attention(
            q[:, :n_real], k[:, :n_real], v[:, :n_real], causal=False
        )
        np.testing.assert_allclose(got[:, :n_real], want, rtol=2e-4, atol=2e-5)

    def test_fused_fold_grads_match_reference(self):
        import jax
        import jax.numpy as jnp

        from flink_ml_tpu.parallel.flash import fused_fold, reference_fold

        rng = np.random.default_rng(6)
        B, H, Tq, Tk, D = 1, 2, 256, 256, 8
        q = jnp.asarray(rng.standard_normal((B, H, Tq, D)).astype(np.float32))
        kb = jnp.asarray(rng.standard_normal((B, H, Tk, D)).astype(np.float32))
        vb = jnp.asarray(rng.standard_normal((B, H, Tk, D)).astype(np.float32))
        m0 = jnp.full((B, H, Tq), -jnp.inf)
        l0 = jnp.zeros((B, H, Tq))
        a0 = jnp.zeros((B, H, Tq, D))
        scale = 1.0 / np.sqrt(D)

        def loss_fused(q, kb, vb):
            m, l, a = fused_fold(
                q, kb, vb, m0, l0, a0, jnp.int32(0), jnp.int32(0), True,
                False, jnp.int32(0), scale, True,
            )
            return jnp.sum(a / jnp.maximum(l, 1e-30)[..., None] * 0.1)

        def loss_ref(q, kb, vb):
            m, l, a = reference_fold(
                q, kb, vb, m0, l0, a0, 0, 0, True, None, scale
            )
            return jnp.sum(a / jnp.maximum(l, 1e-30)[..., None] * 0.1)

        gf = jax.grad(loss_fused, argnums=(0, 1, 2))(q, kb, vb)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, kb, vb)
        for a_, b_ in zip(gf, gr):
            np.testing.assert_allclose(
                np.asarray(a_), np.asarray(b_), rtol=1e-5, atol=1e-5
            )

    @pytest.mark.parametrize(
        "case", ["first-fold", "mid-fold", "masked", "fully-masked", "plain"]
    )
    def test_hand_derived_fold_bwd_matches_ad(self, case):
        import jax
        import jax.numpy as jnp

        from flink_ml_tpu.parallel.flash import (
            _fold_bwd_pallas,
            reference_fold,
            reference_fold_bwd,
        )

        rng = np.random.default_rng(7)
        B, H, Tq, Tk, D = 1, 2, 256, 256, 8
        scale = 1.0 / np.sqrt(D)
        r = lambda *sh: jnp.asarray(rng.normal(size=sh).astype(np.float32))
        causal, nv, qp, kp = {
            "first-fold": (True, None, 0, 0),
            "mid-fold": (True, None, 512, 256),
            "masked": (False, 300, 0, 256),  # keys 256-299 valid, rest masked
            "fully-masked": (False, 10, 0, 128),  # rows with nothing attendable
            "plain": (False, None, 0, 0),
        }[case]
        q, kb, vb = r(B, H, Tq, D), r(B, H, Tk, D), r(B, H, Tk, D)
        if case in ("first-fold", "fully-masked"):
            m = jnp.full((B, H, Tq), -jnp.inf)
            l = jnp.zeros((B, H, Tq))
            acc = jnp.zeros((B, H, Tq, D))
        else:
            m, l, acc = r(B, H, Tq) * 0.5, jnp.abs(r(B, H, Tq)) + 0.5, r(B, H, Tq, D)
        dm, dl, dacc = r(B, H, Tq), r(B, H, Tq), r(B, H, Tq, D)

        _, vjp = jax.vjp(
            lambda q_, k_, v_, m_, l_, a_: reference_fold(
                q_, k_, v_, m_, l_, a_, qp, kp, causal, nv, scale
            ),
            q, kb, vb, m, l, acc,
        )
        want = vjp((dm, dl, dacc))
        got_ref = reference_fold_bwd(
            q, kb, vb, m, l, acc, qp, kp, causal, nv, scale, dm, dl, dacc
        )
        got_pl = _fold_bwd_pallas(
            q, kb, vb, m, l, acc, qp, kp, causal, nv, scale, dm, dl, dacc,
            interpret=True,
        )
        for w, gr, gp, name in zip(want, got_ref, got_pl, ["dq", "dk", "dv", "dm", "dl", "dacc"]):
            np.testing.assert_allclose(
                np.asarray(gr), np.asarray(w), rtol=2e-5, atol=2e-5,
                err_msg=f"{case}/{name} reference_fold_bwd",
            )
            np.testing.assert_allclose(
                np.asarray(gp), np.asarray(w), rtol=2e-5, atol=2e-5,
                err_msg=f"{case}/{name} pallas bwd",
            )
