"""Ring attention (parallel/ring.py): sequence-parallel blockwise attention
must match dense single-device attention exactly (up to float tolerance),
causal and not, on the 8-device mesh."""
import numpy as np
import pytest

from flink_ml_tpu.parallel.ring import ring_attention_sharded


def _dense_attention(q, k, v, causal):
    B, T, H, D = q.shape
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    if causal:
        mask = np.tril(np.ones((T, T), bool))
        s = np.where(mask[None, None], s, -np.inf)
    s = s - s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(axis=-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_dense_attention(causal):
    rng = np.random.default_rng(0)
    B, T, H, D = 2, 64, 2, 8  # T sharded 8 ways -> 8 ring steps
    q = rng.standard_normal((B, T, H, D)).astype(np.float32)
    k = rng.standard_normal((B, T, H, D)).astype(np.float32)
    v = rng.standard_normal((B, T, H, D)).astype(np.float32)
    got = np.asarray(ring_attention_sharded(q, k, v, causal=causal))
    want = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_long_sequence_runs():
    # 16k tokens on the virtual mesh: the [T, T] score matrix (256M floats)
    # never materializes; per-shard peak is O(T_local^2) per ring step.
    rng = np.random.default_rng(1)
    B, T, H, D = 1, 16_384, 1, 16
    q = rng.standard_normal((B, T, H, D)).astype(np.float32)
    out = np.asarray(ring_attention_sharded(q, q, q, causal=True))
    assert out.shape == (B, T, H, D)
    assert np.all(np.isfinite(out))
    # position 0 attends only to itself under causal masking
    np.testing.assert_allclose(out[0, 0, 0], q[0, 0, 0], rtol=1e-5)


def test_uneven_sequence_rejected():
    q = np.zeros((1, 10, 1, 4), np.float32)
    with pytest.raises(ValueError, match="divisible"):
        ring_attention_sharded(q, q, q)


def test_padded_sequence_with_n_valid_matches_dense():
    rng = np.random.default_rng(2)
    B, T_real, H, D = 1, 50, 2, 8
    T_pad = 56  # next multiple of the 8-way mesh
    q = rng.standard_normal((B, T_real, H, D)).astype(np.float32)
    k = rng.standard_normal((B, T_real, H, D)).astype(np.float32)
    v = rng.standard_normal((B, T_real, H, D)).astype(np.float32)
    pad = ((0, 0), (0, T_pad - T_real), (0, 0), (0, 0))
    got = np.asarray(
        ring_attention_sharded(
            np.pad(q, pad), np.pad(k, pad), np.pad(v, pad), n_valid=T_real
        )
    )[:, :T_real]
    want = _dense_attention(q, k, v, causal=False)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_gradients_match_dense_attention():
    """jax.grad flows through the ring schedule (scan + ppermute are
    differentiable), matching dense-attention gradients — the property a
    sequence-model trainer would rely on."""
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.parallel.mesh import get_mesh_context
    from flink_ml_tpu.parallel.ring import _sharded_program

    rng = np.random.default_rng(3)
    B, T, H, D = 1, 32, 2, 4
    q = jnp.asarray(rng.standard_normal((B, T, H, D)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, T, H, D)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, T, H, D)).astype(np.float32))
    ctx = get_mesh_context()
    program = _sharded_program(ctx.mesh, True, False)

    def ring_loss(q, k, v):
        return jnp.sum(program(q, k, v) ** 2)

    def dense_loss(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        return jnp.sum(out ** 2)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), rtol=2e-4, atol=2e-5)
