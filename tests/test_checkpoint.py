"""Checkpoint/resume tests.

Parity target (SURVEY.md §4): ``BoundedAllRoundCheckpointITCase`` — a job that fails
mid-training (FailingMap after N records), restarts from the last checkpoint, and
must converge to the identical result. Here the "job" is the iteration driver /
SGD, the fault is a listener that raises at a chosen epoch, and restart = rerunning
with the same CheckpointManager.
"""
import numpy as np
import pytest

from flink_ml_tpu.checkpoint import CheckpointManager
from flink_ml_tpu.iteration import (
    IterationBodyResult,
    IterationConfig,
    IterationListener,
    TerminateOnMaxIter,
    iterate_bounded_until_termination,
)
from flink_ml_tpu.ops import SGD, LeastSquareLoss


class FailAtEpoch(IterationListener):
    """The FailingMap analogue: blow up once a given epoch is reached."""

    def __init__(self, epoch: int):
        self.fail_epoch = epoch

    def on_epoch_watermark_incremented(self, epoch, context):
        if epoch == self.fail_epoch:
            raise RuntimeError(f"injected failure at epoch {epoch}")


def test_manager_round_trip_and_prune(tmp_path):
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
    assert mgr.restore_latest() is None
    state = [np.arange(4.0), {"nested": np.ones((2, 2)), "n": np.asarray(3)}]
    for step in (1, 2, 3):
        mgr.save(step, state)
    assert mgr.all_steps() == [2, 3]  # pruned to max_to_keep
    step, restored = mgr.restore_latest()
    assert step == 3
    np.testing.assert_array_equal(restored[0], state[0])
    np.testing.assert_array_equal(restored[1]["nested"], state[1]["nested"])
    assert int(restored[1]["n"]) == 3


def test_driver_kill_and_resume(tmp_path):
    """x += epoch for 10 epochs, killed at epoch 6, resumed: same result."""

    crit = TerminateOnMaxIter(10)

    def body(variables, epoch):
        (x,) = variables
        x = x + float(epoch)
        return IterationBodyResult([x], outputs=[x], termination_criteria=crit(epoch))

    clean = iterate_bounded_until_termination([np.asarray(0.0)], body)

    mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
    config = IterationConfig(checkpoint_interval=1, checkpoint_manager=mgr)
    with pytest.raises(RuntimeError, match="injected failure"):
        iterate_bounded_until_termination(
            [np.asarray(0.0)], body, config=config, listeners=[FailAtEpoch(6)]
        )
    assert mgr.all_steps()  # something was snapshotted before the crash
    resumed = iterate_bounded_until_termination([np.asarray(0.0)], body, config=config)
    assert float(resumed[0]) == float(clean[0]) == sum(range(10))


def test_sgd_kill_and_resume_identical_result(tmp_path):
    """The BoundedAllRoundCheckpointITCase contract: restart-from-checkpoint training
    lands on the identical coefficients as the uninterrupted run."""
    rng = np.random.default_rng(4)
    X = rng.normal(size=(128, 3)).astype(np.float32)
    y = X @ np.asarray([1.0, -2.0, 0.5], np.float32)
    data = {"features": X, "labels": y}

    def make_sgd(**kw):
        return SGD(
            max_iter=30, learning_rate=0.05, global_batch_size=32, tol=0.0, **kw
        )

    coef_clean = make_sgd().optimize(np.zeros(3), data, LeastSquareLoss.INSTANCE)

    mgr = CheckpointManager(str(tmp_path / "sgd_ck"), max_to_keep=2)
    with pytest.raises(RuntimeError, match="injected failure"):
        make_sgd(
            checkpoint_manager=mgr, checkpoint_interval=5, listeners=[FailAtEpoch(17)]
        ).optimize(np.zeros(3), data, LeastSquareLoss.INSTANCE)

    coef_resumed = make_sgd(
        checkpoint_manager=mgr, checkpoint_interval=5
    ).optimize(np.zeros(3), data, LeastSquareLoss.INSTANCE)
    np.testing.assert_array_equal(coef_resumed, coef_clean)


def test_save_is_atomic_against_partial_state(tmp_path):
    """A leftover .tmp dir from a killed save is ignored and overwritten."""
    mgr = CheckpointManager(str(tmp_path), max_to_keep=3)
    mgr.save(1, [np.ones(2)])
    # simulate a kill mid-save: stale tmp dir for step 2
    import os

    os.makedirs(str(tmp_path / "ckpt-2.tmp"))
    assert mgr.all_steps() == [1]
    step, state = mgr.restore_latest()
    assert step == 1
    mgr.save(2, [np.zeros(2)])
    assert mgr.all_steps() == [1, 2]


def test_fingerprint_mismatch_refuses_restore(tmp_path):
    # A different run/config pointed at an existing directory must fail loudly
    # instead of silently resuming stale state.
    mgr_a = CheckpointManager(str(tmp_path), fingerprint="run-a")
    mgr_a.save(5, [np.arange(3.0)])
    same = CheckpointManager(str(tmp_path), fingerprint="run-a")
    step, _ = same.restore_latest()
    assert step == 5
    other = CheckpointManager(str(tmp_path), fingerprint="run-b")
    with pytest.raises(ValueError, match="different\\s+run"):
        other.restore_latest()
    # Managers with no fingerprint keep the permissive legacy behavior.
    legacy = CheckpointManager(str(tmp_path))
    assert legacy.restore_latest()[0] == 5


def test_sgd_installs_config_fingerprint(tmp_path):
    from flink_ml_tpu.ops import SGD, BinaryLogisticLoss

    rng = np.random.default_rng(3)
    X = rng.normal(size=(64, 4)).astype(np.float32)
    y = (rng.random(64) > 0.5).astype(np.float32)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    sgd = SGD(max_iter=3, global_batch_size=32, checkpoint_manager=mgr, checkpoint_interval=1)
    sgd.optimize(np.zeros(4), {"features": X, "labels": y}, BinaryLogisticLoss.INSTANCE)
    fp = mgr.fingerprint
    assert fp is not None
    # A config change yields a different fingerprint, so resume is refused.
    mgr2 = CheckpointManager(str(tmp_path / "ck"))
    sgd2 = SGD(max_iter=9, global_batch_size=32, checkpoint_manager=mgr2, checkpoint_interval=1)
    with pytest.raises(ValueError, match="different\\s+run"):
        sgd2.optimize(np.zeros(4), {"features": X, "labels": y}, BinaryLogisticLoss.INSTANCE)


def test_reused_manager_across_configs_refuses(tmp_path):
    # One manager instance reused for two differently-configured runs: the
    # second run's auto fingerprint must overwrite the first and trip the guard.
    from flink_ml_tpu.ops import SGD, BinaryLogisticLoss

    rng = np.random.default_rng(4)
    X = rng.normal(size=(64, 4)).astype(np.float32)
    y = (rng.random(64) > 0.5).astype(np.float32)
    mgr = CheckpointManager(str(tmp_path))
    SGD(max_iter=3, global_batch_size=32, checkpoint_manager=mgr, checkpoint_interval=1).optimize(
        np.zeros(4), {"features": X, "labels": y}, BinaryLogisticLoss.INSTANCE
    )
    with pytest.raises(ValueError, match="different\\s+run"):
        SGD(
            max_iter=9, global_batch_size=32, checkpoint_manager=mgr, checkpoint_interval=1
        ).optimize(np.zeros(4), {"features": X, "labels": y}, BinaryLogisticLoss.INSTANCE)


def test_sgd_tp_kill_and_resume_identical_result(tmp_path):
    """The same BoundedAllRoundCheckpointITCase contract on a 4x2 mesh: the
    model-sharded coefficient must checkpoint/restore on every path, like the
    reference snapshots every training path (SGD.java:308-363)."""
    import jax

    from flink_ml_tpu.parallel.mesh import MeshContext, mesh_context

    rng = np.random.default_rng(5)
    d = 5  # not divisible by n_model=2: exercises coef/column padding
    X = rng.normal(size=(128, d)).astype(np.float32)
    y = X @ np.asarray([1.0, -2.0, 0.5, 0.0, 2.0], np.float32)

    sp_idx = np.tile(np.arange(d, dtype=np.int32), (128, 1))
    datasets = {
        "dense": {"features": X, "labels": y},
        "sparse": {"indices": sp_idx, "values": X, "labels": y},
    }
    with mesh_context(
        MeshContext(devices=jax.devices()[:8], n_data=4, n_model=2)
    ) as ctx:
        for name, data in datasets.items():
            def make_sgd(**kw):
                return SGD(
                    max_iter=30, learning_rate=0.05, global_batch_size=32,
                    tol=0.0, ctx=ctx, **kw
                )

            coef_clean = make_sgd().optimize(np.zeros(d), data, LeastSquareLoss.INSTANCE)
            mgr = CheckpointManager(str(tmp_path / f"tp_ck_{name}"), max_to_keep=2)
            with pytest.raises(RuntimeError, match="injected failure"):
                make_sgd(
                    checkpoint_manager=mgr, checkpoint_interval=5,
                    listeners=[FailAtEpoch(17)],
                ).optimize(np.zeros(d), data, LeastSquareLoss.INSTANCE)
            coef_resumed = make_sgd(
                checkpoint_manager=mgr, checkpoint_interval=5
            ).optimize(np.zeros(d), data, LeastSquareLoss.INSTANCE)
            assert coef_resumed.shape == (d,)
            np.testing.assert_array_equal(coef_resumed, coef_clean)
