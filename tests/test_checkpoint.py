"""Checkpoint/resume tests.

Parity target (SURVEY.md §4): ``BoundedAllRoundCheckpointITCase`` — a job that fails
mid-training (FailingMap after N records), restarts from the last checkpoint, and
must converge to the identical result. Here the "job" is the iteration driver /
SGD, the fault is a listener that raises at a chosen epoch, and restart = rerunning
with the same CheckpointManager.
"""
import numpy as np
import pytest

from flink_ml_tpu.checkpoint import (
    CheckpointCorruptError,
    CheckpointManager,
    FingerprintMismatchError,
)
from flink_ml_tpu.iteration import (
    IterationBodyResult,
    IterationConfig,
    IterationListener,
    TerminateOnMaxIter,
    iterate_bounded_until_termination,
)
from flink_ml_tpu.ops import SGD, LeastSquareLoss


class FailAtEpoch(IterationListener):
    """The FailingMap analogue: blow up once a given epoch is reached."""

    def __init__(self, epoch: int):
        self.fail_epoch = epoch

    def on_epoch_watermark_incremented(self, epoch, context):
        if epoch == self.fail_epoch:
            raise RuntimeError(f"injected failure at epoch {epoch}")


def test_manager_round_trip_and_prune(tmp_path):
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
    assert mgr.restore_latest() is None
    state = [np.arange(4.0), {"nested": np.ones((2, 2)), "n": np.asarray(3)}]
    for step in (1, 2, 3):
        mgr.save(step, state)
    assert mgr.all_steps() == [2, 3]  # pruned to max_to_keep
    step, restored = mgr.restore_latest()
    assert step == 3
    np.testing.assert_array_equal(restored[0], state[0])
    np.testing.assert_array_equal(restored[1]["nested"], state[1]["nested"])
    assert int(restored[1]["n"]) == 3


def test_driver_kill_and_resume(tmp_path):
    """x += epoch for 10 epochs, killed at epoch 6, resumed: same result."""

    crit = TerminateOnMaxIter(10)

    def body(variables, epoch):
        (x,) = variables
        x = x + float(epoch)
        return IterationBodyResult([x], outputs=[x], termination_criteria=crit(epoch))

    clean = iterate_bounded_until_termination([np.asarray(0.0)], body)

    mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
    config = IterationConfig(checkpoint_interval=1, checkpoint_manager=mgr)
    with pytest.raises(RuntimeError, match="injected failure"):
        iterate_bounded_until_termination(
            [np.asarray(0.0)], body, config=config, listeners=[FailAtEpoch(6)]
        )
    assert mgr.all_steps()  # something was snapshotted before the crash
    resumed = iterate_bounded_until_termination([np.asarray(0.0)], body, config=config)
    assert float(resumed[0]) == float(clean[0]) == sum(range(10))


def test_sgd_kill_and_resume_identical_result(tmp_path):
    """The BoundedAllRoundCheckpointITCase contract: restart-from-checkpoint training
    lands on the identical coefficients as the uninterrupted run."""
    rng = np.random.default_rng(4)
    X = rng.normal(size=(128, 3)).astype(np.float32)
    y = X @ np.asarray([1.0, -2.0, 0.5], np.float32)
    data = {"features": X, "labels": y}

    def make_sgd(**kw):
        return SGD(
            max_iter=30, learning_rate=0.05, global_batch_size=32, tol=0.0, **kw
        )

    coef_clean = make_sgd().optimize(np.zeros(3), data, LeastSquareLoss.INSTANCE)

    mgr = CheckpointManager(str(tmp_path / "sgd_ck"), max_to_keep=2)
    with pytest.raises(RuntimeError, match="injected failure"):
        make_sgd(
            checkpoint_manager=mgr, checkpoint_interval=5, listeners=[FailAtEpoch(17)]
        ).optimize(np.zeros(3), data, LeastSquareLoss.INSTANCE)

    coef_resumed = make_sgd(
        checkpoint_manager=mgr, checkpoint_interval=5
    ).optimize(np.zeros(3), data, LeastSquareLoss.INSTANCE)
    np.testing.assert_array_equal(coef_resumed, coef_clean)


def test_save_is_atomic_against_partial_state(tmp_path):
    """A leftover .tmp dir from a killed save is ignored and overwritten."""
    mgr = CheckpointManager(str(tmp_path), max_to_keep=3)
    mgr.save(1, [np.ones(2)])
    # simulate a kill mid-save: stale tmp dir for step 2
    import os

    os.makedirs(str(tmp_path / "ckpt-2.tmp"))
    assert mgr.all_steps() == [1]
    step, state = mgr.restore_latest()
    assert step == 1
    mgr.save(2, [np.zeros(2)])
    assert mgr.all_steps() == [1, 2]


def test_fingerprint_mismatch_refuses_restore(tmp_path):
    # A different run/config pointed at an existing directory must fail loudly
    # instead of silently resuming stale state.
    mgr_a = CheckpointManager(str(tmp_path), fingerprint="run-a")
    mgr_a.save(5, [np.arange(3.0)])
    same = CheckpointManager(str(tmp_path), fingerprint="run-a")
    step, _ = same.restore_latest()
    assert step == 5
    other = CheckpointManager(str(tmp_path), fingerprint="run-b")
    with pytest.raises(ValueError, match="different\\s+run"):
        other.restore_latest()
    # Managers with no fingerprint keep the permissive legacy behavior.
    legacy = CheckpointManager(str(tmp_path))
    assert legacy.restore_latest()[0] == 5


def test_sgd_installs_config_fingerprint(tmp_path):
    from flink_ml_tpu.ops import SGD, BinaryLogisticLoss

    rng = np.random.default_rng(3)
    X = rng.normal(size=(64, 4)).astype(np.float32)
    y = (rng.random(64) > 0.5).astype(np.float32)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    sgd = SGD(max_iter=3, global_batch_size=32, checkpoint_manager=mgr, checkpoint_interval=1)
    sgd.optimize(np.zeros(4), {"features": X, "labels": y}, BinaryLogisticLoss.INSTANCE)
    fp = mgr.fingerprint
    assert fp is not None
    # A config change yields a different fingerprint, so resume is refused.
    mgr2 = CheckpointManager(str(tmp_path / "ck"))
    sgd2 = SGD(max_iter=9, global_batch_size=32, checkpoint_manager=mgr2, checkpoint_interval=1)
    with pytest.raises(ValueError, match="different\\s+run"):
        sgd2.optimize(np.zeros(4), {"features": X, "labels": y}, BinaryLogisticLoss.INSTANCE)


def test_reused_manager_across_configs_refuses(tmp_path):
    # One manager instance reused for two differently-configured runs: the
    # second run's auto fingerprint must overwrite the first and trip the guard.
    from flink_ml_tpu.ops import SGD, BinaryLogisticLoss

    rng = np.random.default_rng(4)
    X = rng.normal(size=(64, 4)).astype(np.float32)
    y = (rng.random(64) > 0.5).astype(np.float32)
    mgr = CheckpointManager(str(tmp_path))
    SGD(max_iter=3, global_batch_size=32, checkpoint_manager=mgr, checkpoint_interval=1).optimize(
        np.zeros(4), {"features": X, "labels": y}, BinaryLogisticLoss.INSTANCE
    )
    with pytest.raises(ValueError, match="different\\s+run"):
        SGD(
            max_iter=9, global_batch_size=32, checkpoint_manager=mgr, checkpoint_interval=1
        ).optimize(np.zeros(4), {"features": X, "labels": y}, BinaryLogisticLoss.INSTANCE)


def test_sgd_tp_kill_and_resume_identical_result(tmp_path):
    """The same BoundedAllRoundCheckpointITCase contract on a 4x2 mesh: the
    model-sharded coefficient must checkpoint/restore on every path, like the
    reference snapshots every training path (SGD.java:308-363)."""
    import jax

    from flink_ml_tpu.parallel.mesh import MeshContext, mesh_context

    rng = np.random.default_rng(5)
    d = 5  # not divisible by n_model=2: exercises coef/column padding
    X = rng.normal(size=(128, d)).astype(np.float32)
    y = X @ np.asarray([1.0, -2.0, 0.5, 0.0, 2.0], np.float32)

    sp_idx = np.tile(np.arange(d, dtype=np.int32), (128, 1))
    datasets = {
        "dense": {"features": X, "labels": y},
        "sparse": {"indices": sp_idx, "values": X, "labels": y},
    }
    with mesh_context(
        MeshContext(devices=jax.devices()[:8], n_data=4, n_model=2)
    ) as ctx:
        for name, data in datasets.items():
            def make_sgd(**kw):
                return SGD(
                    max_iter=30, learning_rate=0.05, global_batch_size=32,
                    tol=0.0, ctx=ctx, **kw
                )

            coef_clean = make_sgd().optimize(np.zeros(d), data, LeastSquareLoss.INSTANCE)
            mgr = CheckpointManager(str(tmp_path / f"tp_ck_{name}"), max_to_keep=2)
            with pytest.raises(RuntimeError, match="injected failure"):
                make_sgd(
                    checkpoint_manager=mgr, checkpoint_interval=5,
                    listeners=[FailAtEpoch(17)],
                ).optimize(np.zeros(d), data, LeastSquareLoss.INSTANCE)
            coef_resumed = make_sgd(
                checkpoint_manager=mgr, checkpoint_interval=5
            ).optimize(np.zeros(d), data, LeastSquareLoss.INSTANCE)
            assert coef_resumed.shape == (d,)
            np.testing.assert_array_equal(coef_resumed, coef_clean)


# --------------------------------------------------------------------------
# Checkpoint hardening (corruption tolerance) + supervised recovery
# equivalence — the docs/fault_tolerance.md contract.
# --------------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _clean_faults():
    from flink_ml_tpu.faults import faults

    faults.reset()
    yield
    faults.reset()


class TestHardening:
    def test_all_steps_skips_unparsable_entries(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, [np.ones(2)])
        mgr.save(2, [np.ones(2)])
        # entries a crash/quarantine can leave behind must not crash listing
        import os

        os.makedirs(str(tmp_path / "ckpt-3.corrupt" ))
        (tmp_path / "ckpt-3.corrupt" / "META.json").write_text("{}")
        (tmp_path / "ckpt-stray.txt").write_text("not a checkpoint")
        os.makedirs(str(tmp_path / "ckpt-notanumber"))
        assert mgr.all_steps() == [1, 2]
        assert mgr.restore_latest()[0] == 2

    def test_orphan_tmp_swept_on_construction(self, tmp_path):
        import os

        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, [np.ones(2)])
        os.makedirs(str(tmp_path / "ckpt-2.tmp"))
        (tmp_path / "ckpt-2.tmp" / "arrays.npz").write_bytes(b"partial")
        # a new incarnation reclaims the orphan; the real snapshot survives
        mgr2 = CheckpointManager(str(tmp_path))
        assert not os.path.exists(str(tmp_path / "ckpt-2.tmp"))
        assert mgr2.all_steps() == [1]

    def test_restore_missing_step_raises_typed_error(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        with pytest.raises(CheckpointCorruptError) as e:
            mgr.restore(7)
        assert e.value.step == 7
        assert "ckpt-7" in e.value.path

    def test_restore_truncated_snapshot_raises_typed_error(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        path = mgr.save(1, [np.arange(4.0)])
        import os

        os.remove(os.path.join(path, "arrays.npz"))
        with pytest.raises(CheckpointCorruptError, match="unreadable"):
            mgr.restore(1)

    @staticmethod
    def _corrupt_arrays(ckpt_dir):
        """Flip bytes inside arrays.npz (bit rot) without truncating it."""
        import os

        path = os.path.join(ckpt_dir, "arrays.npz")
        blob = bytearray(open(path, "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        blob[-1] ^= 0xFF
        with open(path, "wb") as f:
            f.write(blob)

    def test_corrupt_newest_quarantined_and_fallback(self, tmp_path):
        import os

        from flink_ml_tpu.metrics import MLMetrics, metrics

        mgr = CheckpointManager(str(tmp_path), max_to_keep=3)
        state1, state2 = [np.arange(4.0)], [np.arange(4.0) * 2]
        mgr.save(1, state1)
        d2 = mgr.save(2, state2)
        self._corrupt_arrays(d2)
        q0 = metrics.get(MLMetrics.CHECKPOINT_GROUP, MLMetrics.CHECKPOINT_QUARANTINED, 0)
        f0 = metrics.get(MLMetrics.CHECKPOINT_GROUP, MLMetrics.CHECKPOINT_FALLBACKS, 0)
        step, state = mgr.restore_latest()  # must NOT raise
        assert step == 1
        np.testing.assert_array_equal(state[0], state1[0])
        assert os.path.isdir(str(tmp_path / "ckpt-2.corrupt")), "quarantined, not deleted"
        assert not os.path.exists(str(tmp_path / "ckpt-2"))
        assert metrics.get(MLMetrics.CHECKPOINT_GROUP, MLMetrics.CHECKPOINT_QUARANTINED) == q0 + 1
        assert metrics.get(MLMetrics.CHECKPOINT_GROUP, MLMetrics.CHECKPOINT_FALLBACKS) == f0 + 1

    def test_all_snapshots_corrupt_returns_none(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        self._corrupt_arrays(mgr.save(1, [np.ones(3)]))
        self._corrupt_arrays(mgr.save(2, [np.ones(3)]))
        assert mgr.restore_latest() is None

    def test_fingerprint_mismatch_is_typed_and_does_not_fall_back(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), fingerprint="run-a")
        mgr.save(1, [np.ones(2)])
        mgr.save(2, [np.ones(2)])
        other = CheckpointManager(str(tmp_path), fingerprint="run-b")
        with pytest.raises(FingerprintMismatchError):
            other.restore_latest()
        # nothing was quarantined: the snapshots are intact, just foreign
        assert other.all_steps() == [1, 2]

    def test_meta_corruption_falls_back_too(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), max_to_keep=3)
        mgr.save(1, [np.ones(2)])
        d2 = mgr.save(2, [np.ones(2)])
        import os

        with open(os.path.join(d2, "META.json"), "w") as f:
            f.write('{"step": 2, "num_le')  # truncated mid-write
        step, _ = mgr.restore_latest()
        assert step == 1

    def test_checkpoint_save_fault_point(self, tmp_path):
        from flink_ml_tpu.faults import InjectedFault, faults

        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, [np.ones(2)])
        faults.arm("checkpoint.save", at=1)
        with pytest.raises(InjectedFault, match="checkpoint.save"):
            mgr.save(2, [np.ones(2)])
        # the fault hit before any write: step 1 is still the newest snapshot
        assert mgr.all_steps() == [1]
        mgr.save(2, [np.ones(2)])
        assert mgr.all_steps() == [1, 2]


class TestSupervisedRecoveryEquivalence:
    """Kill-at-any-epoch via injected fault -> Supervisor restart -> resume
    must land on the bit-identical model (the BoundedAllRoundCheckpointITCase
    contract, now driven end-to-end through execution/ + faults.py)."""

    def _supervisor(self, name):
        from flink_ml_tpu.execution import FixedDelayRestartStrategy, Supervisor

        return Supervisor(
            FixedDelayRestartStrategy(3, 0.0), name=name, sleep=lambda s: None
        )

    @pytest.mark.parametrize("fail_epoch", [1, 7, 17])
    def test_supervised_sgd_identical_result(self, tmp_path, fail_epoch):
        from flink_ml_tpu.faults import faults

        rng = np.random.default_rng(4)
        X = rng.normal(size=(128, 3)).astype(np.float32)
        y = X @ np.asarray([1.0, -2.0, 0.5], np.float32)
        data = {"features": X, "labels": y}

        def make_sgd(**kw):
            return SGD(max_iter=30, learning_rate=0.05, global_batch_size=32, tol=0.0, **kw)

        coef_clean = make_sgd().optimize(np.zeros(3), data, LeastSquareLoss.INSTANCE)

        mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
        faults.arm("iteration.epoch", at=fail_epoch + 1)  # hit N = epoch N-1
        sup = self._supervisor(f"sgd-{fail_epoch}")
        coef = sup.run(
            lambda: make_sgd(
                checkpoint_manager=mgr, checkpoint_interval=1
            ).optimize(np.zeros(3), data, LeastSquareLoss.INSTANCE)
        )
        assert sup.restarts == 1, "exactly one injected failure, one restart"
        np.testing.assert_array_equal(coef, coef_clean)

    def test_supervised_kmeans_stream_identical_result(self, tmp_path):
        from flink_ml_tpu.faults import faults
        from flink_ml_tpu.iteration.datacache import HostDataCache
        from flink_ml_tpu.models.clustering.kmeans import KMeans

        rng = np.random.default_rng(9)
        X = np.concatenate(
            [rng.normal(loc=c, size=(40, 2)) for c in (-3.0, 0.0, 3.0)]
        ).astype(np.float32)

        def make_cache():
            cache = HostDataCache()
            cache.append({"features": X})
            cache.finish()
            return cache

        def make_est():
            return KMeans().set_k(3).set_seed(5).set_max_iter(8)

        clean = make_est().fit_stream(make_cache())

        mgr = CheckpointManager(str(tmp_path / "km"), max_to_keep=2)
        faults.arm("iteration.epoch", at=5)  # dies before epoch 4's update
        sup = self._supervisor("kmeans")
        model = sup.run(
            lambda: make_est().fit_stream(
                make_cache(), checkpoint_manager=mgr, checkpoint_interval=1
            )
        )
        assert sup.restarts == 1
        np.testing.assert_array_equal(model.centroids, clean.centroids)
        np.testing.assert_array_equal(model.weights, clean.weights)

    def test_supervised_online_lr_identical_result(self, tmp_path):
        """The unbounded analogue (UnboundedStreamCheckpointITCase): an online
        fit killed mid-stream by an injected fault, supervised-restarted with
        a replaying source, lands on the identical coefficient."""
        from flink_ml_tpu.api.dataframe import DataFrame
        from flink_ml_tpu.faults import faults
        from flink_ml_tpu.models.classification.online_logistic_regression import (
            OnlineLogisticRegression,
        )
        from flink_ml_tpu.models.online import QueueBatchStream

        rng = np.random.default_rng(12)
        batches = []
        for _ in range(6):
            X = rng.normal(size=(16, 2))
            batches.append({"features": X, "label": (X.sum(axis=1) > 0).astype(np.float64)})

        def feed():
            s = QueueBatchStream()
            for b in batches:
                s.add(b)
            return s.close()

        def make_est(mgr=None):
            init = DataFrame.from_dict(
                {"coefficient": np.zeros((1, 2)), "modelVersion": np.asarray([0])}
            )
            est = (
                OnlineLogisticRegression()
                .set_initial_model_data(init)
                .set_global_batch_size(16)
            )
            if mgr is not None:
                est.set_checkpoint(mgr, 1)
            return est

        clean = make_est().fit(feed())
        clean.advance()
        assert clean.model_version == 6

        faults.arm("online.step", at=4)

        def attempt():
            # a restart is a NEW incarnation: fresh estimator + manager over
            # the same checkpoint dir, source replaying from batch 0
            mgr = CheckpointManager(str(tmp_path / "olr"))
            model = make_est(mgr).fit(feed())
            model.advance()
            return model

        sup = self._supervisor("online-lr")
        model = sup.run(attempt)
        assert sup.restarts == 1
        assert model.model_version == 6
        np.testing.assert_array_equal(model.coefficient, clean.coefficient)
