"""The per-stage benchmark config suite (flink_ml_tpu/benchmark/configs/).

Reference: ``flink-ml-benchmark/src/main/resources/*-benchmark.json`` — one
config per stage beyond the demo. Two guarantees here: the suite on disk
cannot drift from its generator table (regenerate-and-diff, like the
operator docs), and every config actually executes end-to-end through the
harness (at reduced row counts — the configs themselves target the real
chip).
"""
import copy
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG_DIR = os.path.join(REPO, "flink_ml_tpu", "benchmark", "configs")
sys.path.insert(0, os.path.join(REPO, "tools"))

TEST_ROWS = 1500


def _configs():
    from gen_benchmark_configs import build_configs

    return build_configs()


def test_suite_matches_generator_table():
    want = _configs()
    have = sorted(os.listdir(CONFIG_DIR))
    assert have == sorted(want), "configs on disk out of sync: rerun tools/gen_benchmark_configs.py"
    for fname, config in want.items():
        with open(os.path.join(CONFIG_DIR, fname)) as f:
            assert json.load(f) == config, f"{fname} drifted: rerun tools/gen_benchmark_configs.py"


def test_suite_covers_reference_breadth():
    # the reference ships 35 per-stage configs; ours must not shrink
    assert len(_configs()) >= 35


@pytest.mark.parametrize("fname", sorted(_configs()))
def test_config_executes(fname):
    from flink_ml_tpu.benchmark.benchmark import run_benchmark

    config = _configs()[fname]
    for name, entry in config.items():
        if name == "version":
            continue
        entry = copy.deepcopy(entry)
        gen = entry["inputData"]["paramMap"]
        gen["numValues"] = min(gen["numValues"], TEST_ROWS)
        stage_params = entry["stage"].setdefault("paramMap", {})
        if "maxIter" in stage_params:
            stage_params["maxIter"] = min(stage_params["maxIter"], 3)
        if "globalBatchSize" in stage_params:
            stage_params["globalBatchSize"] = min(
                stage_params["globalBatchSize"], TEST_ROWS
            )
        result = run_benchmark(name, entry)
        assert result["outputRecordNum"] > 0
        assert result["outputThroughput"] > 0


def test_vector_assembler_infers_sizes_from_vector_lists():
    # inputSizes left unset: sizes come from the data, including the
    # list-stored vector column form (reference default is null too)
    import numpy as np

    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.linalg import SparseVector
    from flink_ml_tpu.models.feature.vector_assembler import VectorAssembler

    vecs = [SparseVector(3, [0, 2], [1.0, 2.0]), SparseVector(3, [1], [5.0])]
    df = DataFrame.from_dict({"v": vecs, "x": np.asarray([7.0, 8.0])})
    out = VectorAssembler().set_input_cols("v", "x").set_output_col("out").transform(df)
    np.testing.assert_allclose(
        np.asarray(out.column("out")), [[1.0, 0.0, 2.0, 7.0], [0.0, 5.0, 0.0, 8.0]]
    )


def test_malformed_sparse_vector_param_names_missing_keys():
    from flink_ml_tpu.models.feature.elementwise_product import ElementwiseProduct

    stage = ElementwiseProduct()
    with pytest.raises(ValueError, match="missing \\['size'\\]"):
        stage.set(
            stage.SCALING_VEC,
            stage.SCALING_VEC.json_decode({"indices": [0], "values": [1.0]}),
        )
