"""Test configuration: force an 8-device virtual CPU mesh.

The analogue of the reference's Flink MiniCluster (SURVEY.md section 4): an in-process
multi-device "cluster" so DP/allreduce semantics are unit-testable without TPUs.

The container boots every interpreter through an axon sitecustomize that registers a
TPU-tunnel PJRT plugin and sets ``JAX_PLATFORMS=axon``. JAX backend *initialization* is
lazy, though — so overriding the platform + XLA flags here, before the first device
lookup, is sufficient to put the whole test run on 8 virtual CPU devices.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

assert jax.devices()[0].platform == "cpu" and len(jax.devices()) >= 8, (
    "tests require the 8-device virtual CPU mesh; got " + repr(jax.devices())
)
