"""Test configuration: force an 8-device virtual CPU mesh.

The analogue of the reference's Flink MiniCluster (SURVEY.md section 4): an in-process
multi-device "cluster" so DP/allreduce semantics are unit-testable without TPUs.

The container boots every interpreter through an axon sitecustomize that registers a
TPU-tunnel PJRT plugin and sets ``JAX_PLATFORMS=axon``. JAX backend *initialization* is
lazy, though — so overriding the platform + XLA flags here, before the first device
lookup, is sufficient to put the whole test run on 8 virtual CPU devices.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()


def _xla_accepts(candidate_flags: str) -> bool:
    """Whether this jaxlib's XLA parses ``candidate_flags``.

    XLA hard-aborts the *process* (SIGABRT from parse_flags_from_env.cc) on
    any unknown XLA_FLAGS entry, so support must be probed in a throwaway
    subprocess — jaxlib builds differ in which xla_cpu_collective_call_*
    flags exist, and an unsupported flag would otherwise kill the whole test
    session before pytest prints a single line.
    """
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS=candidate_flags)
    try:
        return (
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                env=env,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                timeout=120,
            ).returncode
            == 0
        )
    except Exception:
        return False


if "xla_cpu_collective_call_terminate_timeout_seconds" not in _flags:
    # XLA CPU's collective rendezvous hard-aborts the PROCESS when a
    # participant misses it (8 SPMD participants on however few cores the
    # box grants — CI observed nproc=1). The stall is a genuine runtime
    # deadlock — raising the bound to 600 s only delayed the abort, and
    # neither the (removed) legacy-runtime flag nor synchronous dispatch
    # avoided it — so keep the bound moderate: transient starvation under
    # 2 minutes survives, and a true deadlock aborts quickly enough for
    # the isolated-retry harness (test_attention_isolated.py) to retry.
    # Only applied when this jaxlib's XLA knows the flags (see _xla_accepts).
    _timeout_flags = (
        " --xla_cpu_collective_call_warn_stuck_timeout_seconds=30"
        " --xla_cpu_collective_call_terminate_timeout_seconds=120"
    )
    if _xla_accepts(_flags + _timeout_flags):
        _flags += _timeout_flags
os.environ["XLA_FLAGS"] = _flags

# The only place the deadlock has ever been observed (dozens of runs) is
# test_attention_classifier.py's long collective fits — thousands of ring
# ppermute rendezvous per fit, where every other test runs a handful.
# Run the file in its own process on a 2-device mesh (see
# test_attention_isolated.py): two rendezvous participants on one core
# collapse the deadlock odds that eight have, the file tests STAGE
# behavior (mesh-width SP semantics live in test_parallel/test_flash),
# and an abort kills a retryable child instead of the whole suite.
_ISOLATED = os.environ.get("FLINK_ML_TPU_ISOLATED", "") not in ("", "0", "false")
collect_ignore = [] if _ISOLATED else ["test_attention_classifier.py"]

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

_MIN_DEVICES = 2 if _ISOLATED else 8
assert jax.devices()[0].platform == "cpu" and len(jax.devices()) >= _MIN_DEVICES, (
    "tests require the virtual CPU mesh; got " + repr(jax.devices())
)


def pytest_configure(config):
    # tier-1 deselects with `-m 'not slow'`; register the marker so strict
    # marker settings and -W error runs stay clean.
    config.addinivalue_line(
        "markers",
        "slow: environment-sensitive or long-running; excluded from tier-1 "
        "(run explicitly with -m slow)",
    )
