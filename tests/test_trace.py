"""graftscope (flink_ml_tpu/trace.py) — the tracing + goodput contract:

- **disabled is free**: zero spans recorded, the shared no-op span, no
  per-request span allocation on the serving path (the structural half of
  bench.py's ``tracing_overhead`` row);
- **span model**: thread-local nesting, manual begin/end, retro recording,
  parent-ID integrity across the MicroBatcher thread handoff, ring-buffer
  wraparound under a multi-threaded soak;
- **serving tree**: one request → queue → batch → pad/dispatch/readback/
  respond, children nested inside their parents;
- **goodput**: per-scope category totals sum to root-span wall time,
  padding split from rows vs bucket, ``ml.goodput.*`` gauges;
- **exporters**: Chrome trace-event JSON schema, Prometheus text exposition
  (golden), ``Histogram.quantiles`` single-sort batch, and the
  ``tools/traceview.py`` CLI (exit codes + summary) on a seeded trace.
"""
import json
import threading

import numpy as np
import pytest

from flink_ml_tpu import trace
from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.config import Options, config
from flink_ml_tpu.metrics import Histogram, MetricsRegistry, MLMetrics, metrics
from flink_ml_tpu.trace import (
    CAT_COMPILE,
    CAT_PADDING,
    CAT_PRODUCTIVE,
    CAT_QUEUE,
    CAT_READBACK,
    CATEGORIES,
    GoodputReport,
    Span,
    SpanRecorder,
    Tracer,
    tracer,
)

from tools.traceview import main as traceview_main


@pytest.fixture(autouse=True)
def _tracer_off():
    """Every test starts and ends with the tracer in its default state."""
    tracer.disable()
    yield
    tracer.disable()


def _span(name, category, scope, start, end, span_id, parent_id=None, attrs=None):
    s = Span(name, category, scope, start, span_id, parent_id, 1, "t")
    s.end = end
    if attrs:
        s.attrs = dict(attrs)
    return s


def _serve(n_requests=6, rows=3, name="t-trace", threads=1, max_batch=8):
    """Drive a tiny logistic servable through the real serving path."""
    from flink_ml_tpu.servable.lib import LogisticRegressionModelServable
    from flink_ml_tpu.serving import InferenceServer, ServingConfig

    rng = np.random.default_rng(3)
    dim = 8
    servable = LogisticRegressionModelServable().set_features_col("features")
    servable.coefficient = rng.standard_normal(dim).astype(np.float32)
    X = rng.standard_normal((64, dim)).astype(np.float32)
    server = InferenceServer(
        servable,
        name=name,
        serving_config=ServingConfig(
            max_batch_size=max_batch, max_delay_ms=0.5, default_timeout_ms=60_000
        ),
        warmup_template=DataFrame.from_dict({"features": X[:1]}),
    )
    try:
        if threads == 1:
            for i in range(n_requests):
                server.predict(
                    DataFrame.from_dict({"features": X[i * rows : (i + 1) * rows]})
                )
        else:
            def client(tid):
                for i in range(n_requests):
                    j = (tid * 17 + i * rows) % (X.shape[0] - rows)
                    server.predict(DataFrame.from_dict({"features": X[j : j + rows]}))

            ts = [threading.Thread(target=client, args=(t,)) for t in range(threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
    finally:
        server.close()
    return server


# ---------------------------------------------------------------------------
# disabled path: zero spans, zero allocation
# ---------------------------------------------------------------------------


class TestDisabledPath:
    def test_span_returns_the_shared_noop(self):
        assert not tracer.enabled
        a = tracer.span("x", CAT_QUEUE, scope="s")
        b = tracer.span("y")
        assert a is b is trace._NOOP_SPAN  # same object — no allocation
        with a as sp:
            assert sp.set_attr("k", 1) is sp

    def test_begin_returns_none_and_end_is_none_safe(self):
        assert tracer.begin("x") is None
        tracer.end(None)  # no-op
        tracer.record("x", CAT_QUEUE, "s", 0.0, 1.0)  # dropped
        assert len(tracer.recorder) == 0

    def test_serving_path_records_nothing_and_allocates_no_request_span(self):
        before = tracer.recorder.recorded
        from flink_ml_tpu.servable.lib import LogisticRegressionModelServable
        from flink_ml_tpu.serving import InferenceServer, ServingConfig

        rng = np.random.default_rng(0)
        servable = LogisticRegressionModelServable().set_features_col("features")
        servable.coefficient = rng.standard_normal(4).astype(np.float32)
        X = rng.standard_normal((8, 4)).astype(np.float32)
        server = InferenceServer(
            servable,
            name="t-trace-off",
            serving_config=ServingConfig(max_batch_size=4, max_delay_ms=0.2),
            warmup_template=DataFrame.from_dict({"features": X[:1]}),
        )
        try:
            handle = server.submit(DataFrame.from_dict({"features": X[:2]}))
            assert handle.trace is None  # no per-request span allocation
            handle.result()
        finally:
            server.close()
        assert tracer.recorder.recorded == before  # zero spans recorded

    def test_config_option_defaults_off(self):
        assert config.get(Options.OBSERVABILITY_TRACE) is False
        assert config.get(Options.OBSERVABILITY_TRACE_CAPACITY) == 65_536


# ---------------------------------------------------------------------------
# span model
# ---------------------------------------------------------------------------


class TestSpanModel:
    def test_context_manager_nesting_sets_parent_ids(self):
        with trace.capture() as rec:
            with tracer.span("outer", CAT_PRODUCTIVE, scope="s") as outer:
                with tracer.span("inner", CAT_COMPILE, scope="s") as inner:
                    assert tracer.current() is inner
                assert tracer.current() is outer
        spans = {s.name: s for s in rec.snapshot()}
        assert spans["inner"].parent_id == spans["outer"].span_id
        assert spans["outer"].parent_id is None
        assert spans["inner"].end >= spans["inner"].start
        assert spans["inner"].category == CAT_COMPILE

    def test_manual_begin_end_and_explicit_parent(self):
        with trace.capture() as rec:
            root = tracer.begin("root", CAT_PRODUCTIVE, scope="s")
            with tracer.span("child", CAT_QUEUE, scope="s", parent=root):
                pass
            tracer.end(root)
            tracer.end(root)  # idempotent: second end does not re-record
        spans = rec.snapshot()
        assert [s.name for s in spans] == ["child", "root"]
        assert spans[0].parent_id == spans[1].span_id

    def test_record_retro_inherits_parent_thread_identity(self):
        with trace.capture() as rec:
            root = tracer.begin("root", CAT_PRODUCTIVE, scope="s")
            captured = {}

            def other_thread():
                tracer.record("q", CAT_QUEUE, "s", 1.0, 2.0, parent=root)
                captured["tid"] = threading.get_ident()

            t = threading.Thread(target=other_thread)
            t.start()
            t.join()
            tracer.end(root)
        q = [s for s in rec.snapshot() if s.name == "q"][0]
        assert q.thread_id == root.thread_id != captured["tid"]
        assert q.parent_id == root.span_id
        assert (q.start, q.end) == (1.0, 2.0)

    def test_exception_exit_records_error_attr(self):
        with trace.capture() as rec:
            with pytest.raises(ValueError):
                with tracer.span("boom", scope="s"):
                    raise ValueError("x")
        (s,) = rec.snapshot()
        assert s.attrs["error"] == "ValueError"

    def test_ring_wraparound_keeps_newest(self):
        with trace.capture(capacity=8) as rec:
            for i in range(20):
                with tracer.span(f"s{i}", scope="s"):
                    pass
        assert len(rec) == 8
        assert rec.recorded == 20
        assert rec.dropped == 12
        assert [s.name for s in rec.snapshot()] == [f"s{i}" for i in range(12, 20)]

    def test_recorder_rejects_degenerate_capacity(self):
        with pytest.raises(ValueError):
            SpanRecorder(0)

    def test_multithreaded_soak_ring_and_parent_integrity(self):
        n_threads, per_thread = 8, 120
        with trace.capture(capacity=n_threads * per_thread * 2) as rec:
            barrier = threading.Barrier(n_threads)

            def worker(tid):
                barrier.wait()
                for i in range(per_thread):
                    with tracer.span(f"outer-{tid}", scope=f"s{tid}") as outer:
                        with tracer.span(f"inner-{tid}", scope=f"s{tid}") as inner:
                            assert inner.parent_id == outer.span_id

            ts = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        spans = rec.snapshot()
        assert len(spans) == n_threads * per_thread * 2
        by_id = {s.span_id: s for s in spans}
        ids = set(by_id)
        assert len(ids) == len(spans)  # unique ids across threads
        for s in spans:
            if s.name.startswith("inner"):
                parent = by_id[s.parent_id]
                # the parent is the same thread's outer span, same scope
                assert parent.name == f"outer-{s.name.split('-')[1]}"
                assert parent.thread_id == s.thread_id
            assert s.end is not None and s.end >= s.start


# ---------------------------------------------------------------------------
# the serving span tree (acceptance: queue → pad → dispatch → readback)
# ---------------------------------------------------------------------------


class TestServingSpanTree:
    def test_request_tree_and_thread_handoff(self):
        with trace.capture() as rec:
            _serve(n_requests=5, rows=3, name="t-trace-tree")
        spans = rec.snapshot()
        by_id = {s.span_id: s for s in spans}
        children = {}
        for s in spans:
            if s.parent_id is not None:
                children.setdefault(s.parent_id, []).append(s)
        requests = [s for s in spans if s.name == "serving.request"]
        assert len(requests) == 5
        main_tid = threading.get_ident()
        batch_names_seen = set()
        for req in requests:
            kid_names = {c.name for c in children.get(req.span_id, [])}
            assert "serving.queue" in kid_names
            # the request root carries the CLIENT thread identity; its queue
            # child (recorded by the batcher thread) inherits it — the
            # parent-ID handoff across the MicroBatcher boundary
            assert req.thread_id == main_tid
            for c in children.get(req.span_id, []):
                if c.name == "serving.queue":
                    assert c.thread_id == main_tid
                    assert c.category == CAT_QUEUE
        batches = [s for s in spans if s.name == "serving.batch"]
        assert batches
        for b in batches:
            assert by_id[b.parent_id].name == "serving.request"
            assert b.thread_id != main_tid  # executed on the batcher thread
            kid_names = {c.name for c in children.get(b.span_id, [])}
            batch_names_seen |= kid_names
            assert "serving.pad" in kid_names
        # across the run the full phase vocabulary appears (fastpath on:
        # dispatch + deferred readback; respond always)
        assert {"serving.pad", "serving.dispatch", "serving.readback",
                "serving.respond"} <= batch_names_seen

    def test_children_nest_inside_parents(self):
        with trace.capture() as rec:
            _serve(n_requests=8, rows=2, name="t-trace-nest", threads=2)
        spans = rec.snapshot()
        children = {}
        for s in spans:
            if s.parent_id is not None:
                children.setdefault(s.parent_id, []).append(s)
        checked = 0
        for s in spans:
            kids = [c for c in children.get(s.span_id, []) if c.scope == s.scope]
            if not kids:
                continue
            checked += 1
            for c in kids:
                assert c.start >= s.start - 1e-6
                assert c.end <= s.end + 1e-6
            # summed child time fits within the parent span
            assert sum(c.duration for c in kids) <= s.duration + 1e-6
        assert checked > 0

    def test_warmup_and_swap_spans_are_compile_and_swap(self):
        with trace.capture() as rec:
            _serve(n_requests=1, rows=1, name="t-trace-warm")
        names = {s.name: s for s in rec.snapshot()}
        assert names["serving.warmup"].category == CAT_COMPILE
        assert names["serving.swap"].category == "swap"
        assert names["serving.plan.warmup"].category == CAT_COMPILE
        # warmup nests under the swap that triggered it
        assert names["serving.warmup"].parent_id == names["serving.swap"].span_id


# ---------------------------------------------------------------------------
# goodput attribution
# ---------------------------------------------------------------------------


class TestGoodputReport:
    def test_self_time_attribution_sums_to_root_wall(self):
        spans = [
            _span("root", CAT_PRODUCTIVE, "s", 0.0, 10.0, 1),
            _span("queue", CAT_QUEUE, "s", 0.0, 2.0, 2, parent_id=1),
            _span("exec", CAT_PRODUCTIVE, "s", 2.0, 9.0, 3, parent_id=1),
            _span("readback", CAT_READBACK, "s", 6.0, 9.0, 4, parent_id=3),
        ]
        report = GoodputReport.from_spans(spans)
        totals = report.totals["s"]
        # root self 1.0 + exec self 4.0 productive; queue 2.0; readback 3.0
        assert totals[CAT_PRODUCTIVE] == pytest.approx(5.0)
        assert totals[CAT_QUEUE] == pytest.approx(2.0)
        assert totals[CAT_READBACK] == pytest.approx(3.0)
        assert report.wall_s("s") == pytest.approx(10.0)  # == root duration
        assert report.fraction("s") == pytest.approx(0.5)

    def test_padding_split_from_rows_vs_bucket(self):
        spans = [
            _span("exec", CAT_PRODUCTIVE, "s", 0.0, 4.0, 1, attrs={"rows": 3, "bucket": 4}),
        ]
        totals = GoodputReport.from_spans(spans).totals["s"]
        assert totals[CAT_PRODUCTIVE] == pytest.approx(3.0)
        assert totals[CAT_PADDING] == pytest.approx(1.0)

    def test_full_bucket_has_no_padding(self):
        spans = [
            _span("exec", CAT_PRODUCTIVE, "s", 0.0, 4.0, 1, attrs={"rows": 4, "bucket": 4}),
        ]
        totals = GoodputReport.from_spans(spans).totals["s"]
        assert CAT_PADDING not in totals

    def test_cross_scope_children_do_not_subtract(self):
        spans = [
            _span("loop.swap", "swap", "loop", 0.0, 5.0, 1),
            _span("serving.warmup", CAT_COMPILE, "serving", 1.0, 4.0, 2, parent_id=1),
        ]
        report = GoodputReport.from_spans(spans)
        assert report.totals["loop"]["swap"] == pytest.approx(5.0)
        assert report.totals["serving"][CAT_COMPILE] == pytest.approx(3.0)

    def test_publish_writes_goodput_gauges(self):
        registry = MetricsRegistry()
        GoodputReport({"sc": {CAT_PRODUCTIVE: 0.3, CAT_QUEUE: 0.1}}).publish(registry)
        assert registry.get("sc", MLMetrics.goodput_ms(CAT_PRODUCTIVE)) == pytest.approx(300.0)
        assert registry.get("sc", MLMetrics.goodput_ms(CAT_QUEUE)) == pytest.approx(100.0)
        assert registry.get("sc", MLMetrics.GOODPUT_FRACTION) == pytest.approx(0.75)

    def test_serving_categories_sum_to_traced_wall(self):
        with trace.capture() as rec:
            _serve(n_requests=6, rows=3, name="t-trace-goodput")
        spans = rec.snapshot()
        scope = "ml.serving[t-trace-goodput]"
        report = GoodputReport.from_spans(spans)
        # roots of the scope = spans without an in-scope parent
        ids = {s.span_id for s in spans if s.scope == scope}
        roots = [
            s for s in spans
            if s.scope == scope and (s.parent_id is None or s.parent_id not in ids)
        ]
        assert report.wall_s(scope) == pytest.approx(
            sum(r.duration for r in roots), rel=1e-9
        )
        assert 0.0 < report.fraction(scope) < 1.0
        # the padded remainder of partially-filled buckets was attributed
        assert report.category_s(scope, CAT_PADDING) > 0.0


# ---------------------------------------------------------------------------
# exporters: chrome trace + prometheus + quantiles
# ---------------------------------------------------------------------------


class TestChromeTraceExport:
    def test_schema_and_metadata(self, tmp_path):
        path = str(tmp_path / "trace.json")
        with trace.capture() as rec:
            _serve(n_requests=3, rows=2, name="t-trace-export")
            n = rec.export_chrome_trace(path)
        assert n == rec.recorded == len(rec.snapshot())
        payload = json.loads(open(path).read())
        events = payload["traceEvents"]
        xs = [e for e in events if e.get("ph") == "X"]
        assert len(xs) == n
        for e in xs:
            assert set(e) >= {"ph", "pid", "tid", "name", "cat", "ts", "dur", "args"}
            assert e["cat"] in CATEGORIES
            assert e["dur"] >= 0.0
            assert "span_id" in e["args"]
        procs = [
            e for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"
        ]
        proc_names = {e["args"]["name"] for e in procs}
        assert "ml.serving[t-trace-export]" in proc_names
        # one pid per scope
        assert len({e["pid"] for e in procs}) == len(procs)
        threads = [
            e for e in events
            if e.get("ph") == "M" and e.get("name") == "thread_name"
        ]
        assert threads  # tid metadata present

    def test_empty_recorder_exports_valid_file(self, tmp_path):
        path = str(tmp_path / "empty.json")
        rec = SpanRecorder(16)
        assert rec.export_chrome_trace(path) == 0
        assert json.loads(open(path).read())["traceEvents"] == []


class TestPrometheusExposition:
    def test_golden_rendering(self):
        registry = MetricsRegistry()
        registry.gauge("ml.serving[a]", "ml.serving.queue.depth", 3)
        registry.counter("ml.serving[a]", "ml.serving.requests", 7)
        registry.gauge("ml.loop[l]", "ml.loop.goodput.fraction", 0.75)
        hist = registry.histogram("ml.serving[a]", "ml.serving.latency.ms")
        for v in [1.0, 2.0, 3.0, 4.0]:
            hist.observe(v)
        golden = (
            '# TYPE ml_loop_goodput_fraction gauge\n'
            'ml_loop_goodput_fraction{scope="ml.loop[l]"} 0.75\n'
            '# TYPE ml_serving_latency_ms summary\n'
            'ml_serving_latency_ms{scope="ml.serving[a]",quantile="0.5"} 3\n'
            'ml_serving_latency_ms{scope="ml.serving[a]",quantile="0.9"} 4\n'
            'ml_serving_latency_ms{scope="ml.serving[a]",quantile="0.99"} 4\n'
            'ml_serving_latency_ms_count{scope="ml.serving[a]"} 4\n'
            'ml_serving_latency_ms_sum{scope="ml.serving[a]"} 10\n'
            '# TYPE ml_serving_queue_depth gauge\n'
            'ml_serving_queue_depth{scope="ml.serving[a]"} 3\n'
            '# TYPE ml_serving_requests_total counter\n'
            'ml_serving_requests_total{scope="ml.serving[a]"} 7\n'
        )
        assert registry.render_prometheus() == golden

    def test_skips_non_numeric_and_escapes_labels(self):
        registry = MetricsRegistry()
        registry.gauge('scope"with\\quotes', "m.x", 1)
        registry.gauge("s", "m.y", "not-a-number")
        out = registry.render_prometheus()
        assert 'scope="scope\\"with\\\\quotes"' in out
        assert "m_y" not in out

    def test_global_registry_renders_after_serving(self):
        _serve(n_requests=2, rows=2, name="t-trace-prom")
        out = metrics.render_prometheus()
        assert '# TYPE ml_serving_requests_total counter' in out
        assert 'ml_serving_requests_total{scope="ml.serving[t-trace-prom]"}' in out
        assert 'ml_serving_latency_ms{scope="ml.serving[t-trace-prom]",quantile="0.5"}' in out


class TestHistogramQuantiles:
    def test_batch_matches_single_quantiles(self):
        hist = Histogram(window=64)
        rng = np.random.default_rng(5)
        for v in rng.normal(size=50):
            hist.observe(float(v))
        qs = (0.0, 0.25, 0.5, 0.99, 1.0)
        assert hist.quantiles(qs) == [hist.quantile(q) for q in qs]

    def test_empty_and_validation(self):
        hist = Histogram(window=4)
        assert hist.quantiles((0.5, 0.99)) == [None, None]
        with pytest.raises(ValueError):
            hist.quantiles((0.5, 1.5))


# ---------------------------------------------------------------------------
# the other instrumented tiers
# ---------------------------------------------------------------------------


class TestOtherTiers:
    def test_batch_plan_chunk_spans(self):
        from flink_ml_tpu.builder import PipelineModel
        from flink_ml_tpu.models.feature.standard_scaler import StandardScalerModel

        rng = np.random.default_rng(2)
        d = 8
        m = StandardScalerModel().set_input_col("input").set_output_col("output")
        m.set_with_mean(True)
        m.mean = rng.normal(size=d)
        m.std = np.abs(rng.normal(size=d)) + 0.5
        model = PipelineModel([m])
        df = DataFrame.from_dict({"input": rng.normal(size=(64, d))})
        config.set(Options.BATCH_CHUNK_ROWS, 16)
        try:
            with trace.capture() as rec:
                model.transform(df)
        finally:
            config.unset(Options.BATCH_CHUNK_ROWS)
        spans = rec.snapshot()
        names = [s.name for s in spans if s.scope == "ml.batch[plan]"]
        assert names.count("batch.ingest") == 4  # 64 rows / 16-row chunks
        assert names.count("batch.chunk") == 4
        assert "batch.readback" in names
        assert "batch.transform" in names
        readbacks = [s for s in spans if s.name == "batch.readback"]
        assert all(s.category == CAT_READBACK for s in readbacks)
        umbrella = [s for s in spans if s.name == "batch.transform"][0]
        chunks = [s for s in spans if s.name == "batch.chunk"]
        assert all(c.parent_id == umbrella.span_id for c in chunks)

    def test_iteration_epoch_spans(self):
        from flink_ml_tpu.iteration import (
            IterationBodyResult,
            IterationConfig,
            iterate_bounded_until_termination,
        )

        def body(variables, epoch):
            return IterationBodyResult(
                feedback_variables=[variables[0] + 1], outputs=[variables[0]]
            )

        with trace.capture() as rec:
            iterate_bounded_until_termination(
                [0], body, IterationConfig(max_epochs=3)
            )
        epochs = [s for s in rec.snapshot() if s.name == "iteration.epoch"]
        assert [s.attrs["epoch"] for s in epochs] == [0, 1, 2]
        assert all(s.scope == "ml.iteration[bounded]" for s in epochs)

    def test_supervisor_attempt_and_recovery_spans(self):
        from flink_ml_tpu.execution import Supervisor

        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")  # retryable per DEFAULT_CLASSIFIER
            return "ok"

        with trace.capture() as rec:
            assert Supervisor(name="t-trace-sup").run(flaky) == "ok"
        spans = rec.snapshot()
        scope = "ml.execution[t-trace-sup]"
        attempts = [s for s in spans if s.name == "execution.attempt"]
        recoveries = [s for s in spans if s.name == "execution.recovery"]
        assert len(attempts) == 3 and len(recoveries) == 2
        assert all(s.scope == scope for s in attempts + recoveries)
        assert all(s.category == "recovery" for s in recoveries)
        assert attempts[0].attrs["error"] == "OSError"
        assert "error" not in (attempts[-1].attrs or {})


# ---------------------------------------------------------------------------
# tools/traceview.py
# ---------------------------------------------------------------------------


class TestTraceviewCLI:
    def _export(self, tmp_path):
        path = str(tmp_path / "trace.json")
        with trace.capture() as rec:
            _serve(n_requests=4, rows=2, name="t-trace-cli")
            rec.export_chrome_trace(path)
        return path

    def test_summary_on_seeded_trace(self, tmp_path, capsys):
        path = self._export(tmp_path)
        assert traceview_main([path]) == 0
        out = capsys.readouterr().out
        assert "ml.serving[t-trace-cli]" in out
        assert "goodput fraction" in out
        assert "serving.request" in out
        assert "compile" in out  # the warmup slice shows up per category

    def test_scope_filter_and_top(self, tmp_path, capsys):
        path = self._export(tmp_path)
        assert traceview_main([path, "--scope", "ml.serving", "--top", "3"]) == 0
        out = capsys.readouterr().out
        assert out.count("scope ml.serving[t-trace-cli]") == 1

    def test_missing_file_exits_2(self, tmp_path, capsys):
        assert traceview_main([str(tmp_path / "nope.json")]) == 2
        assert "cannot load" in capsys.readouterr().err

    def test_invalid_json_exits_2(self, tmp_path, capsys):
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert traceview_main([str(bad)]) == 2

    def test_empty_trace_exits_2(self, tmp_path, capsys):
        empty = tmp_path / "empty.json"
        empty.write_text('{"traceEvents": []}')
        assert traceview_main([str(empty)]) == 2
        assert "no spans" in capsys.readouterr().err

    def test_roundtrip_matches_live_goodput(self, tmp_path):
        """The offline analyzer reproduces the live report's attribution."""
        from tools.traceview import load_spans

        path = str(tmp_path / "trace.json")
        with trace.capture() as rec:
            _serve(n_requests=4, rows=3, name="t-trace-rt")
            rec.export_chrome_trace(path)
            live = rec.goodput_report()
        offline = GoodputReport.from_spans(load_spans(path))
        scope = "ml.serving[t-trace-rt]"
        assert offline.fraction(scope) == pytest.approx(live.fraction(scope), rel=1e-6)
        for cat in CATEGORIES:
            assert offline.category_s(scope, cat) == pytest.approx(
                live.category_s(scope, cat), rel=1e-6, abs=1e-9
            )


# ---------------------------------------------------------------------------
# tracer lifecycle
# ---------------------------------------------------------------------------


class TestLifecycle:
    def test_capture_restores_previous_state(self):
        assert not tracer.enabled
        outer_recorder = tracer.recorder
        with trace.capture(capacity=4) as rec:
            assert tracer.enabled and tracer.recorder is rec
        assert not tracer.enabled
        assert tracer.recorder is outer_recorder

    def test_enable_disable(self):
        trace.enable(capacity=16)
        try:
            assert tracer.enabled and tracer.recorder.capacity == 16
        finally:
            trace.disable()
        assert not tracer.enabled

    def test_independent_tracer_instances(self):
        t = Tracer(SpanRecorder(8), enabled=True)
        with t.span("x", scope="s"):
            pass
        assert len(t.recorder) == 1
        assert len(tracer.recorder) == 0 or tracer.recorder is not t.recorder
