"""Runs test_attention_classifier.py in its own process on a 2-device
mesh, with retry.

XLA CPU's collective rendezvous intermittently deadlocks and then
hard-aborts the process (SIGABRT) on this box: N virtual SPMD
participants must each get a thread through one core, and the attention
classifier's fits run THOUSANDS of ring-ppermute rendezvous per test
where every other test runs a handful — observed killing ~1-in-2 full
suite runs at 8 devices, surviving neither a 600 s timeout, the legacy
runtime flag (a no-op now), nor synchronous dispatch. Mitigation, in
order of effect: a 2-participant mesh (the deadlock odds collapse; the
file tests STAGE behavior — mesh-width SP semantics live in
test_parallel/test_flash), process isolation (an abort kills a
retryable child, not the suite), and retries. A real test failure
reproduces deterministically in the child and is reported with its
output. ``conftest.collect_ignore`` keeps the file out of the
in-process run; the env var lets the child collect it normally and
relaxes conftest's 8-device assertion.
"""
import os
import re
import subprocess
import sys

import pytest

_RETRIES = 3


def test_attention_classifier_suite_isolated():
    here = os.path.dirname(os.path.abspath(__file__))
    target = os.path.join(here, "test_attention_classifier.py")
    env = dict(os.environ, FLINK_ML_TPU_ISOLATED="1")
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        env.get("XLA_FLAGS", ""),
    )
    env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=2").strip()
    last = None
    for _ in range(1 + _RETRIES):
        try:
            last = subprocess.run(
                [sys.executable, "-m", "pytest", target, "-q", "-p", "no:cacheprovider"],
                capture_output=True,
                text=True,
                env=env,
                cwd=os.path.dirname(here),
                # a stall OUTSIDE a collective rendezvous (which the XLA
                # terminate flag does not cover) must become a retry, not
                # an invisible suite hang; normal child runs take ~30 s
                timeout=600,
            )
        except subprocess.TimeoutExpired as e:
            last = subprocess.CompletedProcess(
                e.cmd,
                -9,
                e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or ""),
                e.stderr.decode() if isinstance(e.stderr, bytes) else (e.stderr or ""),
            )
            continue  # hang: retry like an abort
        if last.returncode == 0:
            return
        if last.returncode not in (-6, 134):
            break  # a real test failure: deterministic, no point retrying
    pytest.fail(
        f"isolated attention suite failed (rc={last.returncode}):\n"
        f"{last.stdout[-4000:]}\n{last.stderr[-2000:]}"
    )
