"""Runs test_attention_classifier.py in its own process on a 2-device
mesh, with retry.

XLA CPU's collective rendezvous intermittently deadlocks and then
hard-aborts the process (SIGABRT) on this box: N virtual SPMD
participants must each get a thread through one core, and the attention
classifier's fits run THOUSANDS of ring-ppermute rendezvous per test
where every other test runs a handful — observed killing ~1-in-2 full
suite runs at 8 devices, surviving neither a 600 s timeout, the legacy
runtime flag (a no-op now), nor synchronous dispatch. Mitigation, in
order of effect: a 2-participant mesh (the deadlock odds collapse; the
file tests STAGE behavior — mesh-width SP semantics live in
test_parallel/test_flash), process isolation (an abort kills a
retryable child, not the suite), and retries. A real test failure
reproduces deterministically in the child and is reported with its
output. ``conftest.collect_ignore`` keeps the file out of the
in-process run; the env var lets the child collect it normally and
relaxes conftest's 8-device assertion.
"""
import os
import sys

from tests._isolation import run_contained, two_device_env


def test_attention_classifier_suite_isolated():
    here = os.path.dirname(os.path.abspath(__file__))
    target = os.path.join(here, "test_attention_classifier.py")
    run_contained(
        [sys.executable, "-m", "pytest", target, "-q", "-p", "no:cacheprovider"],
        env=two_device_env({"FLINK_ML_TPU_ISOLATED": "1"}),
        cwd=os.path.dirname(here),
        what="isolated attention suite",
    )
