"""Registry-wide coverage: every stage's param JSON round-trip, every
estimator's fit → save → load → identical-transform contract, sparse-input
parity for vector transforms, weighted evaluation, and empty-input errors.

The reference tests each algorithm in its own *Test.java with the same
quartet (defaults/param-set/fit-transform/save-load); this file pins the two
contracts that are uniform across stages so no stage can silently miss them.
"""
import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.linalg.vectors import SparseVector
from flink_ml_tpu.models import STAGE_REGISTRY, get_stage_class
from flink_ml_tpu.utils.read_write import load_stage

RNG = np.random.default_rng(101)


# --------------------------------------------------------------------------- #
# 1. Param JSON round-trip for every registered stage
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize("name", sorted(set(STAGE_REGISTRY)))
def test_param_json_round_trip(name):
    cls = get_stage_class(name)
    stage = cls()
    payload = stage.param_map_to_json()
    fresh = cls()
    fresh.load_param_map_from_json(payload)
    for p in stage.get_param_map():
        got = fresh.get(p)
        want = stage.get(p)
        if isinstance(got, float) and isinstance(want, float):
            assert got == want or (np.isnan(got) and np.isnan(want)), (name, p.name)
        else:
            assert got == want or (got is None and want is None), (name, p.name)


# --------------------------------------------------------------------------- #
# 2. fit -> save -> load -> identical transform for every Estimator family
# --------------------------------------------------------------------------- #
def _vec_df(n=24, d=4, seed=3):
    return DataFrame.from_dict({"input": RNG.normal(size=(n, d))})


def _labeled_df(n=32, d=4):
    X = RNG.normal(size=(n, d))
    y = (X @ np.linspace(1.0, -1.0, d) > 0).astype(np.float64)
    return DataFrame.from_dict({"features": X, "label": y})


def _docs_df():
    docs = [["a", "b", "c"], ["a", "b"], ["c", "d"], ["a", "c", "c"]]
    return DataFrame(["input"], None, [docs])


ESTIMATOR_CASES = {
    "CountVectorizer": (lambda c: c(), _docs_df),
    "IDF": (lambda c: c(), _vec_df),
    "Imputer": (
        lambda c: c().set_input_cols("a").set_output_cols("out"),
        lambda: DataFrame.from_dict({"a": np.asarray([1.0, np.nan, 3.0, 4.0])}),
    ),
    "KBinsDiscretizer": (lambda c: c().set_num_bins(3), _vec_df),
    "KMeans": (lambda c: c().set_k(2).set_seed(0), lambda: DataFrame.from_dict({"features": RNG.normal(size=(20, 3))})),
    "Knn": (lambda c: c().set_k(3), _labeled_df),
    "LinearRegression": (lambda c: c().set_max_iter(5), _labeled_df),
    "LinearSVC": (lambda c: c().set_max_iter(5), _labeled_df),
    "LogisticRegression": (lambda c: c().set_max_iter(5), _labeled_df),
    "MLPClassifier": (
        lambda c: c().set_max_iter(5).set_hidden_layers(4).set_seed(1),
        _labeled_df,
    ),
    "MaxAbsScaler": (lambda c: c(), _vec_df),
    "MinHashLSH": (
        lambda c: c().set_input_col("vec").set_num_hash_tables(3).set_seed(7),
        lambda: DataFrame(
            ["vec"],
            None,
            [[SparseVector(10, [0, 1], [1.0, 1.0]), SparseVector(10, [2, 3], [1.0, 1.0])]],
        ),
    ),
    "MinMaxScaler": (lambda c: c(), _vec_df),
    "NaiveBayes": (
        lambda c: c(),
        lambda: DataFrame.from_dict(
            {
                "features": RNG.integers(0, 3, size=(24, 3)).astype(np.float64),
                "label": RNG.integers(0, 2, 24).astype(np.float64),
            }
        ),
    ),
    "OneHotEncoder": (
        lambda c: c().set_input_cols("c").set_output_cols("vec"),
        lambda: DataFrame.from_dict({"c": np.asarray([0.0, 1.0, 2.0, 1.0])}),
    ),
    "RobustScaler": (lambda c: c(), _vec_df),
    "SelfAttentionClassifier": (
        lambda c: c().set_max_iter(2).set_embedding_dim(8).set_num_heads(2).set_seed(1),
        lambda: DataFrame.from_dict(
            {
                "features": RNG.integers(0, 6, size=(8, 16)).astype(np.float64),
                "label": RNG.integers(0, 2, 8).astype(np.float64),
            }
        ),
    ),
    "StandardScaler": (lambda c: c().set_with_mean(True), _vec_df),
    "StringIndexer": (
        lambda c: c().set_input_cols("s").set_output_cols("idx"),
        lambda: DataFrame(["s"], None, [["b", "a", "b", "c"]]),
    ),
    "UnivariateFeatureSelector": (
        lambda c: c()
        .set_feature_type("continuous")
        .set_label_type("categorical")
        .set_selection_threshold(2),
        _labeled_df,
    ),
    "VarianceThresholdSelector": (lambda c: c(), _vec_df),
    "VectorIndexer": (
        lambda c: c().set_max_categories(3),
        lambda: DataFrame.from_dict(
            {"input": np.stack([RNG.integers(0, 2, 20).astype(np.float64), RNG.normal(size=20)], axis=1)}
        ),
    ),
}


def _outputs_equal(a: DataFrame, b: DataFrame):
    assert a.get_column_names() == b.get_column_names()
    for name in a.get_column_names():
        ca, cb = a.column(name), b.column(name)
        if isinstance(ca, np.ndarray) and ca.dtype.kind in "biufc":
            np.testing.assert_allclose(ca, np.asarray(cb, ca.dtype), rtol=1e-6, atol=1e-7)
        else:
            for va, vb in zip(ca, cb):
                if hasattr(va, "to_array"):
                    np.testing.assert_allclose(va.to_array(), vb.to_array(), rtol=1e-6)
                else:
                    assert np.array_equal(va, vb) if isinstance(va, np.ndarray) else va == vb


@pytest.mark.parametrize("name", sorted(ESTIMATOR_CASES))
def test_estimator_save_load_transform_identity(name, tmp_path):
    configure, make_df = ESTIMATOR_CASES[name]
    est = configure(get_stage_class(name))
    df = make_df()
    model = est.fit(df)
    want = model.transform(df)
    path = str(tmp_path / name)
    model.save(path)
    loaded = load_stage(path)
    assert type(loaded) is type(model)
    got = loaded.transform(df)
    _outputs_equal(want, got)


def test_every_estimator_family_in_cases():
    """The case table must cover every fitting Estimator in the registry
    (online estimators train on streams and are covered in test_online.py)."""
    from flink_ml_tpu.api.core import Estimator

    skip = {
        "OnlineKMeans",
        "OnlineLogisticRegression",
        "OnlineStandardScaler",
        "Swing",  # AlgoOperator
        "AgglomerativeClustering",  # AlgoOperator
    }
    missing = []
    for name in sorted(set(STAGE_REGISTRY)):
        cls = get_stage_class(name)
        if not isinstance(cls, type) or not issubclass(cls, Estimator):
            continue
        if name in skip or name in ESTIMATOR_CASES:
            continue
        missing.append(name)
    assert not missing, f"estimators without a save/load case: {missing}"


# --------------------------------------------------------------------------- #
# 3. Sparse-input parity for dense-vector transforms
# --------------------------------------------------------------------------- #
def _to_sparse(X):
    rows = []
    for r in X:
        nz = np.nonzero(r)[0]
        rows.append(SparseVector(len(r), nz, r[nz]))
    return rows


@pytest.mark.parametrize("stage_name", ["Normalizer", "DCT", "PolynomialExpansion"])
def test_sparse_input_matches_densified(stage_name):
    X = RNG.normal(size=(12, 4))
    X[RNG.random(X.shape) < 0.5] = 0.0
    stage = get_stage_class(stage_name)()
    dense_out = stage.transform(DataFrame.from_dict({"input": X}))["output"]
    sparse_out = stage.transform(DataFrame(["input"], None, [_to_sparse(X)]))["output"]
    np.testing.assert_allclose(np.asarray(sparse_out), np.asarray(dense_out), rtol=1e-6)


def test_fitted_scaler_sparse_input_matches_densified():
    from flink_ml_tpu.models.feature.scalers import MinMaxScaler

    X = RNG.normal(size=(16, 3))
    X[RNG.random(X.shape) < 0.4] = 0.0
    model = MinMaxScaler().fit(DataFrame.from_dict({"input": X}))
    dense_out = model.transform(DataFrame.from_dict({"input": X}))["output"]
    sparse_out = model.transform(DataFrame(["input"], None, [_to_sparse(X)]))["output"]
    np.testing.assert_allclose(np.asarray(sparse_out), np.asarray(dense_out), rtol=1e-6)


# --------------------------------------------------------------------------- #
# 4. Weighted evaluation (ref BinaryClassificationEvaluator weightCol)
# --------------------------------------------------------------------------- #
def test_evaluator_weight_col_changes_auc():
    y = np.asarray([0.0, 0.0, 1.0, 1.0])
    score = np.asarray([0.1, 0.6, 0.4, 0.8])  # one inversion: (0.6 neg > 0.4 pos)
    from flink_ml_tpu.models.evaluation.binary_classification_evaluator import (
        BinaryClassificationEvaluator,
    )

    df = DataFrame.from_dict({"label": y, "rawPrediction": score})
    auc = BinaryClassificationEvaluator().transform(df)["areaUnderROC"][0]
    np.testing.assert_allclose(auc, 0.75)  # 3 of 4 pairs ordered correctly

    # Upweighting the correctly-ordered negative (0.1, w=3) raises weighted
    # AUC: correctly ordered pair weight = (0.4,0.1):1*3 + (0.8,0.1):1*3 +
    # (0.8,0.6):1*1 = 7 over W_pos*W_neg = 2*4 = 8.
    w = np.asarray([3.0, 1.0, 1.0, 1.0])
    df_w = DataFrame.from_dict({"label": y, "rawPrediction": score, "weight": w})
    auc_w = (
        BinaryClassificationEvaluator()
        .set_weight_col("weight")
        .transform(df_w)["areaUnderROC"][0]
    )
    np.testing.assert_allclose(auc_w, 7.0 / 8.0)


# --------------------------------------------------------------------------- #
# 5. Empty-input error branches
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize("name", ["StandardScaler", "MinMaxScaler", "KMeans"])
def test_empty_training_set_raises(name):
    est = get_stage_class(name)()
    col = "features" if name == "KMeans" else "input"
    empty = DataFrame([col], None, [np.zeros((0, 3))])
    with pytest.raises((RuntimeError, ValueError)):
        est.fit(empty)
