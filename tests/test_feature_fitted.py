"""Tests for the fitted feature stages (reference test shape: defaults,
fit+transform vs hand-computed values, save/load, model data)."""
import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.linalg.vectors import Vectors
from flink_ml_tpu.models.feature.count_vectorizer import CountVectorizer, CountVectorizerModel
from flink_ml_tpu.models.feature.idf import IDF, IDFModel
from flink_ml_tpu.models.feature.imputer import Imputer, ImputerModel
from flink_ml_tpu.models.feature.kbins_discretizer import KBinsDiscretizer
from flink_ml_tpu.models.feature.lsh import JavaRandom, MinHashLSH
from flink_ml_tpu.models.feature.one_hot_encoder import OneHotEncoder
from flink_ml_tpu.models.feature.scalers import (
    MaxAbsScaler,
    MinMaxScaler,
    MinMaxScalerModel,
    RobustScaler,
)
from flink_ml_tpu.models.feature.string_indexer import (
    IndexToStringModel,
    StringIndexer,
    StringIndexerModel,
)
from flink_ml_tpu.models.feature.univariate_feature_selector import UnivariateFeatureSelector
from flink_ml_tpu.models.feature.variance_threshold_selector import VarianceThresholdSelector
from flink_ml_tpu.models.feature.vector_indexer import VectorIndexer, VectorIndexerModel

RNG = np.random.default_rng(44)


def test_max_abs_scaler():
    X = np.asarray([[2.0, -4.0], [-1.0, 2.0]])
    model = MaxAbsScaler().fit(DataFrame.from_dict({"input": X}))
    np.testing.assert_array_equal(model.max_abs, [2.0, 4.0])
    out = model.transform(DataFrame.from_dict({"input": X}))["output"]
    np.testing.assert_allclose(out, [[1.0, -1.0], [-0.5, 0.5]])


def test_min_max_scaler_with_constant_dim():
    X = np.asarray([[0.0, 7.0], [5.0, 7.0], [10.0, 7.0]])
    model = MinMaxScaler().fit(DataFrame.from_dict({"input": X}))
    out = model.transform(DataFrame.from_dict({"input": X}))["output"]
    np.testing.assert_allclose(out[:, 0], [0.0, 0.5, 1.0])
    np.testing.assert_allclose(out[:, 1], 0.5)  # constant dim → midpoint
    # custom range
    model2 = MinMaxScaler().set_min(-1.0).set_max(1.0).fit(DataFrame.from_dict({"input": X}))
    out2 = model2.transform(DataFrame.from_dict({"input": X}))["output"]
    np.testing.assert_allclose(out2[:, 0], [-1.0, 0.0, 1.0])


def test_min_max_scaler_save_load(tmp_path):
    X = RNG.normal(size=(10, 2))
    model = MinMaxScaler().fit(DataFrame.from_dict({"input": X}))
    model.save(str(tmp_path / "mms"))
    loaded = MinMaxScalerModel.load(str(tmp_path / "mms"))
    np.testing.assert_allclose(loaded.e_min, model.e_min)


def test_robust_scaler_iqr():
    # The GK sketch (like the reference's QuantileSummary) returns order
    # statistics at rank ceil(p*n): for 1..100 that is q25=25, q50=50, q75=75 —
    # NOT numpy's linearly interpolated 25.75/50.5/75.25.
    x = np.arange(1.0, 101.0)[:, None]  # 1..100
    model = RobustScaler().fit(DataFrame.from_dict({"input": x}))
    out = model.transform(DataFrame.from_dict({"input": x}))["output"]
    iqr = 75.0 - 25.0
    np.testing.assert_allclose(out[:, 0], x[:, 0] / iqr)
    model_c = RobustScaler().set_with_centering(True).fit(DataFrame.from_dict({"input": x}))
    out_c = model_c.transform(DataFrame.from_dict({"input": x}))["output"]
    np.testing.assert_allclose(out_c[:, 0], (x[:, 0] - 50.0) / iqr)


def test_imputer_strategies(tmp_path):
    x = np.asarray([1.0, 2.0, np.nan, 3.0, 2.0])
    df = DataFrame.from_dict({"a": x})
    for strategy, expected in [("mean", 2.0), ("median", 2.0), ("most_frequent", 2.0)]:
        model = (
            Imputer()
            .set_input_cols("a")
            .set_output_cols("out")
            .set_strategy(strategy)
            .fit(df)
        )
        out = model.transform(df)["out"]
        assert out[2] == expected, strategy
        assert not np.isnan(out).any()
    # custom missing value
    df2 = DataFrame.from_dict({"a": np.asarray([1.0, -1.0, 5.0])})
    m = (
        Imputer()
        .set_input_cols("a")
        .set_output_cols("out")
        .set_missing_value(-1.0)
        .fit(df2)
    )
    np.testing.assert_array_equal(m.transform(df2)["out"], [1.0, 3.0, 5.0])
    m.save(str(tmp_path / "imp"))
    loaded = ImputerModel.load(str(tmp_path / "imp"))
    np.testing.assert_array_equal(loaded.surrogates, m.surrogates)


def test_idf_formula():
    X = np.asarray([[1.0, 0.0], [1.0, 1.0]])
    df = DataFrame.from_dict({"input": X})
    model = IDF().fit(df)
    # idf = log((n+1)/(df+1)): dim0 df=2 -> log(3/3)=0; dim1 df=1 -> log(3/2)
    np.testing.assert_allclose(model.idf, [0.0, np.log(1.5)], atol=1e-9)
    out = model.transform(df)["output"]
    np.testing.assert_allclose(out[:, 1], [0.0, np.log(1.5)])
    # minDocFreq filters dims
    model2 = IDF().set_min_doc_freq(2).fit(df)
    assert model2.idf[1] == 0.0


def test_count_vectorizer():
    docs = [["a", "b", "c"], ["a", "b", "b", "c"], ["a", "b"]]
    df = DataFrame(["input"], None, [docs])
    model = CountVectorizer().fit(df)
    assert model.vocabulary[0] == "b"  # most frequent first (b: 4, a: 3, c: 2)
    out = model.transform(df)["output"]
    v1 = out[1]
    assert v1.size() == 3
    np.testing.assert_array_equal(sorted(v1.values.tolist()), [1.0, 1.0, 2.0])
    # minDF as absolute count
    model2 = CountVectorizer().set_min_df(3.0).fit(df)
    assert set(model2.vocabulary) == {"a", "b"}
    # binary + minTF
    model3 = CountVectorizer().set_binary(True).fit(df)
    outb = model3.transform(df)["output"]
    assert set(outb[1].values.tolist()) == {1.0}


def test_count_vectorizer_save_load(tmp_path):
    docs = [["x", "y"], ["y"]]
    model = CountVectorizer().fit(DataFrame(["input"], None, [docs]))
    model.save(str(tmp_path / "cv"))
    loaded = CountVectorizerModel.load(str(tmp_path / "cv"))
    assert loaded.vocabulary == model.vocabulary


def test_string_indexer_orders_and_handle_invalid(tmp_path):
    df = DataFrame(["s"], None, [["b", "a", "b", "c", "b", "a"]])
    si = StringIndexer().set_input_cols("s").set_output_cols("idx")
    m = si.set_string_order_type("frequencyDesc").fit(df)
    assert m.string_arrays[0] == ["b", "a", "c"]
    np.testing.assert_array_equal(m.transform(df)["idx"], [0, 1, 0, 2, 0, 1])
    m2 = si.set_string_order_type("alphabetAsc").fit(df)
    assert m2.string_arrays[0] == ["a", "b", "c"]
    # handleInvalid on unseen
    df_new = DataFrame(["s"], None, [["a", "zzz"]])
    with pytest.raises(ValueError, match="unseen"):
        m2.transform(df_new)
    np.testing.assert_array_equal(
        m2.set_handle_invalid("keep").transform(df_new)["idx"], [0.0, 3.0]
    )
    assert len(m2.set_handle_invalid("skip").transform(df_new)) == 1
    # save/load + IndexToString inverse
    m2.save(str(tmp_path / "si"))
    loaded = StringIndexerModel.load(str(tmp_path / "si"))
    assert loaded.string_arrays == m2.string_arrays
    its = IndexToStringModel().set_input_cols("idx").set_output_cols("s2")
    its.string_arrays = m2.string_arrays
    round_trip = its.transform(
        DataFrame.from_dict({"idx": np.asarray([0.0, 1.0, 2.0])})
    )["s2"]
    assert round_trip == ["a", "b", "c"]


def test_one_hot_encoder():
    df = DataFrame.from_dict({"c": np.asarray([0.0, 1.0, 2.0])})
    model = OneHotEncoder().set_input_cols("c").set_output_cols("vec").fit(df)
    np.testing.assert_array_equal(model.category_sizes, [3])
    out = model.transform(df)["vec"]
    np.testing.assert_array_equal(out[0].to_array(), [1.0, 0.0])  # dropLast: len 2
    np.testing.assert_array_equal(out[2].to_array(), [0.0, 0.0])  # last → all zeros
    model.set_drop_last(False)
    out2 = model.transform(df)["vec"]
    np.testing.assert_array_equal(out2[2].to_array(), [0.0, 0.0, 1.0])
    # unseen index
    df_bad = DataFrame.from_dict({"c": np.asarray([5.0])})
    with pytest.raises(ValueError, match="invalid index"):
        model.transform(df_bad)
    kept = model.set_handle_invalid("keep").transform(df_bad)["vec"]
    assert kept[0].size() == 4  # 3 categories + 1 invalid bucket


def test_kbins_strategies():
    x = np.concatenate([np.arange(10.0), [100.0]])[:, None]
    df = DataFrame.from_dict({"input": x})
    uni = KBinsDiscretizer().set_strategy("uniform").set_num_bins(2).fit(df)
    out_u = uni.transform(df)["output"][:, 0]
    assert out_u[:-1].max() == 0.0 and out_u[-1] == 1.0  # wide uniform bins
    qua = KBinsDiscretizer().set_strategy("quantile").set_num_bins(2).fit(df)
    out_q = qua.transform(df)["output"][:, 0]
    assert (out_q[:5] == 0.0).all() and (out_q[-3:] == 1.0).all()
    km = KBinsDiscretizer().set_strategy("kmeans").set_num_bins(2).fit(df)
    out_k = km.transform(df)["output"][:, 0]
    assert out_k[-1] == out_k.max() and out_k[0] == 0.0
    # out-of-range values clamp into edge bins
    out_clamp = uni.transform(DataFrame.from_dict({"input": np.asarray([[-99.0]])}))
    assert out_clamp["output"][0, 0] == 0.0


def test_kbins_constant_dimension_bins_to_zero():
    df = DataFrame.from_dict({"input": np.full((6, 1), 5.0)})
    for strategy in ("uniform", "quantile", "kmeans"):
        model = KBinsDiscretizer().set_strategy(strategy).set_num_bins(4).fit(df)
        out = model.transform(df)["output"]
        np.testing.assert_array_equal(out, 0.0), strategy


def test_variance_threshold_selector():
    X = np.stack([np.ones(10), np.arange(10.0), np.arange(10.0) * 5], axis=1)
    df = DataFrame.from_dict({"input": X})
    model = VarianceThresholdSelector().fit(df)
    np.testing.assert_array_equal(model.indices, [1, 2])  # constant dim dropped
    model2 = VarianceThresholdSelector().set_variance_threshold(50.0).fit(df)
    np.testing.assert_array_equal(model2.indices, [2])
    out = model2.transform(df)["output"]
    np.testing.assert_array_equal(out[:, 0], X[:, 2])


def test_vector_indexer():
    X = np.asarray([[0.0, 1.5], [2.0, 2.5], [0.0, 3.5], [2.0, 4.5], [1.0, 5.5]])
    df = DataFrame.from_dict({"input": X})
    model = VectorIndexer().set_max_categories(3).fit(df)
    assert 0 in model.category_maps and 1 not in model.category_maps
    assert model.category_maps[0] == {0.0: 0, 1.0: 1, 2.0: 2}
    out = model.transform(df)["output"]
    np.testing.assert_array_equal(out[:, 0], [0, 2, 0, 2, 1])
    np.testing.assert_array_equal(out[:, 1], X[:, 1])  # continuous untouched
    # unseen categorical value
    df_bad = DataFrame.from_dict({"input": np.asarray([[7.0, 1.0]])})
    with pytest.raises(ValueError, match="unseen"):
        model.transform(df_bad)
    kept = model.set_handle_invalid("keep").transform(df_bad)["output"]
    assert kept[0, 0] == 3.0


def test_vector_indexer_save_load(tmp_path):
    X = np.asarray([[0.0], [1.0], [0.0]])
    model = VectorIndexer().fit(DataFrame.from_dict({"input": X}))
    model.save(str(tmp_path / "vi"))
    loaded = VectorIndexerModel.load(str(tmp_path / "vi"))
    assert loaded.category_maps == model.category_maps


def test_univariate_feature_selector_modes():
    rng = np.random.default_rng(0)
    n = 200
    y = rng.integers(0, 2, n).astype(np.float64)
    informative = y * 2.0 + rng.normal(0, 0.1, n)
    noise = rng.normal(size=(n, 3))
    X = np.column_stack([informative, noise])
    df = DataFrame.from_dict({"features": X, "label": y})
    sel = (
        UnivariateFeatureSelector()
        .set_feature_type("continuous")
        .set_label_type("categorical")
        .set_selection_threshold(1)
    )
    model = sel.fit(df)
    np.testing.assert_array_equal(model.indices, [0])
    out = model.transform(df)["output"]
    np.testing.assert_allclose(out[:, 0], informative)
    # fpr mode keeps only significant features
    sel_fpr = (
        UnivariateFeatureSelector()
        .set_feature_type("continuous")
        .set_label_type("categorical")
        .set_selection_mode("fpr")
        .set_selection_threshold(0.01)
    )
    assert 0 in sel_fpr.fit(df).indices.tolist()


def test_java_random_parity():
    """Raw 32-bit draws match java.util.Random's documented outputs."""

    def next_int(seed):
        r = JavaRandom(seed)
        v = r._next(32)
        return v - (1 << 32) if v >= (1 << 31) else v

    assert next_int(42) == -1170105035  # new Random(42).nextInt()
    assert next_int(0) == -1155484576  # new Random(0).nextInt()


def test_minhash_lsh_jaccard_and_neighbors():
    a = Vectors.sparse(10, [0, 1, 2], [1.0, 1.0, 1.0])
    b = Vectors.sparse(10, [1, 2, 3], [1.0, 1.0, 1.0])
    c = Vectors.sparse(10, [7, 8, 9], [1.0, 1.0, 1.0])
    df = DataFrame(["vec", "id"], None, [[a, b, c], [0, 1, 2]])
    lsh = (
        MinHashLSH()
        .set_input_col("vec")
        .set_output_col("hashes")
        .set_num_hash_tables(10)
        .set_seed(2022)
    )
    model = lsh.fit(df)
    assert model.key_distance(a, b) == pytest.approx(1 - 2 / 4)
    out = model.transform(df)
    assert out["hashes"][0].shape == (10, 1)
    nn = model.approx_nearest_neighbors(df, a, k=2)
    assert list(nn["id"]) == [0, 1]  # exact self-match then the overlapping set
    join = model.approx_similarity_join(df, df, threshold=0.6, id_col="id")
    pairs = {(int(x), int(y)) for x, y in zip(join["idA"], join["idB"])}
    assert (0, 1) in pairs and (0, 0) in pairs and (0, 2) not in pairs


def test_standard_scaler_large_mean_numerical_stability():
    # Regression: the naive (sqSum - n*mean^2)/(n-1) finalization cancels
    # catastrophically in f32 when |mean| >> std; the centered kernel must not.
    from flink_ml_tpu.models.feature.standard_scaler import StandardScaler

    rng = np.random.default_rng(7)
    X = (1.0e6 + rng.normal(0.0, 1.0, size=(4096, 3))).astype(np.float64)
    model = StandardScaler().set_input_col("input").set_with_mean(True).fit(
        DataFrame.from_dict({"input": X})
    )
    np.testing.assert_allclose(model.std, X.std(axis=0, ddof=1), rtol=0.05)
    np.testing.assert_allclose(model.mean, X.mean(axis=0), rtol=1e-6)
    out = model.transform(DataFrame.from_dict({"input": X}))["output"]
    assert abs(np.std(out, ddof=1) - 1.0) < 0.1
