"""DataFrame tests mirroring DataFrameTest semantics
(flink-ml-servable-core/src/test/.../servable/api/)."""
import numpy as np
import pytest

from flink_ml_tpu.api import DataFrame, DataTypes, Row
from flink_ml_tpu.linalg import DenseVector, Vectors


def make_df():
    return DataFrame.from_rows(
        ["id", "features", "label", "name"],
        [
            [0, Vectors.dense(1.0, 2.0), 1.0, "a"],
            [1, Vectors.dense(3.0, 4.0), 0.0, "b"],
            [2, Vectors.dense(5.0, 6.0), 1.0, "c"],
        ],
    )


class TestDataFrame:
    def test_schema(self):
        df = make_df()
        assert df.get_column_names() == ["id", "features", "label", "name"]
        assert df.get_index("label") == 2
        assert df.num_rows == 3

    def test_columnar_storage(self):
        df = make_df()
        feats = df.vectors("features")
        assert feats.shape == (3, 2)
        assert df.scalars("label").tolist() == [1.0, 0.0, 1.0]

    def test_collect_rows(self):
        rows = make_df().collect()
        assert len(rows) == 3
        assert rows[0].get(0) == 0
        assert rows[1].get(1) == DenseVector([3.0, 4.0])
        assert rows[2].get(3) == "c"

    def test_add_column(self):
        df = make_df()
        df.add_column("pred", DataTypes.DOUBLE, np.array([0.1, 0.2, 0.3]))
        assert "pred" in df.get_column_names()
        assert df.scalars("pred").tolist() == [0.1, 0.2, 0.3]

    def test_add_column_length_mismatch(self):
        with pytest.raises(ValueError):
            make_df().add_column("bad", DataTypes.DOUBLE, np.array([1.0]))

    def test_with_column_functional(self):
        df = make_df()
        df2 = df.with_column("pred", np.array([1.0, 2.0, 3.0]))
        assert "pred" not in df.get_column_names()
        assert "pred" in df2.get_column_names()

    def test_select_drop_take(self):
        df = make_df()
        assert df.select(["id", "label"]).get_column_names() == ["id", "label"]
        assert df.drop("name").get_column_names() == ["id", "features", "label"]
        sub = df.take([2, 0])
        assert sub.scalars("id", np.int64).tolist() == [2, 0]
        assert sub.collect()[0].get(3) == "c"

    def test_from_dict(self):
        df = DataFrame.from_dict({"x": np.arange(4), "y": ["a", "b", "c", "d"]})
        assert df.num_rows == 4
        assert df.column("y") == ["a", "b", "c", "d"]

    def test_sparse_column_stays_ragged(self):
        df = DataFrame.from_rows(
            ["v"], [[Vectors.sparse(4, [0], [1.0])], [Vectors.sparse(4, [1], [2.0])]]
        )
        dense = df.vectors("v")
        assert dense.shape == (2, 4)
        assert dense[1].tolist() == [0.0, 2.0, 0.0, 0.0]

    def test_inconsistent_lengths_rejected(self):
        with pytest.raises(ValueError):
            DataFrame(["a", "b"], None, [np.arange(3), np.arange(4)])

    def test_row_equality(self):
        assert Row([1, "a"]) == Row([1, "a"])
        assert Row([1]) != Row([2])
