"""Executes every example script (ref ExamplesTest.java — each example must
run end-to-end and produce output)."""
import io
import os
import pathlib
import runpy
import sys
from contextlib import redirect_stdout

import pytest

from tests._isolation import run_contained, two_device_env

EXAMPLES_DIR = pathlib.Path(__file__).resolve().parent.parent / "examples"
EXAMPLES = sorted(EXAMPLES_DIR.rglob("*_example.py"))

# Collective-heavy examples (thousands of ring-ppermute rendezvous per
# fit) run in their own 2-device subprocess with retry — the same XLA CPU
# rendezvous-deadlock containment as test_attention_isolated.py; every
# other example runs in-process for speed.
_ISOLATED_EXAMPLES = {"self_attention_classifier_example.py"}


def _run_isolated(path):
    root = EXAMPLES_DIR.parent
    # The repo must ride the child's path explicitly: sys.path[0] of
    # ``python examples/.../x.py`` is the example's own directory.
    pythonpath = (
        f"{root}{os.pathsep}{os.environ['PYTHONPATH']}"
        if os.environ.get("PYTHONPATH")
        else str(root)
    )
    done = run_contained(
        [sys.executable, str(path)],
        env=two_device_env({"PYTHONPATH": pythonpath}),
        cwd=str(root),
        what=f"isolated example {path.name}",
    )
    assert done.stdout.strip(), f"{path.name} produced no output"


def test_examples_cover_every_family():
    families = {p.parent.name for p in EXAMPLES}
    assert {
        "classification",
        "clustering",
        "evaluation",
        "feature",
        "recommendation",
        "regression",
        "stats",
    } <= families
    assert len(EXAMPLES) >= 45


@pytest.mark.parametrize("path", EXAMPLES, ids=lambda p: str(p.relative_to(EXAMPLES_DIR)))
def test_example_runs(path):
    if path.name in _ISOLATED_EXAMPLES:
        _run_isolated(path)
        return
    buf = io.StringIO()
    with redirect_stdout(buf):
        runpy.run_path(str(path), run_name="__main__")
    assert buf.getvalue().strip(), f"{path.name} produced no output"


def test_docs_internal_links_resolve():
    """Every relative link in docs/*.md and the README points at a real file."""
    import re

    root = EXAMPLES_DIR.parent
    for md in [root / "README.md", *sorted((root / "docs").rglob("*.md"))]:
        text = md.read_text()
        for target in re.findall(r"\]\((?!https?://|#)([^)]+)\)", text):
            resolved = (md.parent / target.split("#")[0]).resolve()
            assert resolved.exists(), f"{md.name} links to missing {target}"
