"""Executes every example script (ref ExamplesTest.java — each example must
run end-to-end and produce output)."""
import io
import pathlib
import runpy
from contextlib import redirect_stdout

import pytest

EXAMPLES_DIR = pathlib.Path(__file__).resolve().parent.parent / "examples"
EXAMPLES = sorted(EXAMPLES_DIR.rglob("*_example.py"))


def test_examples_cover_every_family():
    families = {p.parent.name for p in EXAMPLES}
    assert {
        "classification",
        "clustering",
        "evaluation",
        "feature",
        "recommendation",
        "regression",
        "stats",
    } <= families
    assert len(EXAMPLES) >= 45


@pytest.mark.parametrize("path", EXAMPLES, ids=lambda p: str(p.relative_to(EXAMPLES_DIR)))
def test_example_runs(path):
    buf = io.StringIO()
    with redirect_stdout(buf):
        runpy.run_path(str(path), run_name="__main__")
    assert buf.getvalue().strip(), f"{path.name} produced no output"


def test_docs_internal_links_resolve():
    """Every relative link in docs/*.md and the README points at a real file."""
    import re

    root = EXAMPLES_DIR.parent
    for md in [root / "README.md", *sorted((root / "docs").rglob("*.md"))]:
        text = md.read_text()
        for target in re.findall(r"\]\((?!https?://|#)([^)]+)\)", text):
            resolved = (md.parent / target.split("#")[0]).resolve()
            assert resolved.exists(), f"{md.name} links to missing {target}"
