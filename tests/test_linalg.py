"""Linalg tests mirroring the reference's BLASTest / DenseVectorTest / SparseVectorTest
semantics (flink-ml-servable-core/src/test/.../linalg/)."""
import numpy as np
import pytest

from flink_ml_tpu.linalg import DenseMatrix, DenseVector, SparseVector, Vectors, blas


class TestDenseVector:
    def test_basic(self):
        v = Vectors.dense(1.0, 2.0, 3.0)
        assert v.size() == 3
        assert v.get(1) == 2.0
        v.set(1, 5.0)
        assert v.get(1) == 5.0
        assert np.array_equal(v.to_array(), [1.0, 5.0, 3.0])

    def test_clone_independent(self):
        v = Vectors.dense(1.0, 2.0)
        c = v.clone()
        c.set(0, 9.0)
        assert v.get(0) == 1.0

    def test_to_sparse(self):
        v = Vectors.dense(0.0, 2.0, 0.0, 3.0)
        s = v.to_sparse()
        assert s.indices.tolist() == [1, 3]
        assert s.values.tolist() == [2.0, 3.0]
        assert s.size() == 4

    def test_equality_and_iter(self):
        assert Vectors.dense(1.0, 2.0) == Vectors.dense(1.0, 2.0)
        assert list(Vectors.dense(1.0, 2.0)) == [1.0, 2.0]


class TestSparseVector:
    def test_sorted_invariant(self):
        s = Vectors.sparse(5, [3, 1], [30.0, 10.0])
        assert s.indices.tolist() == [1, 3]
        assert s.values.tolist() == [10.0, 30.0]

    def test_duplicate_index_rejected(self):
        with pytest.raises(ValueError):
            SparseVector(5, [1, 1], [1.0, 2.0])

    def test_out_of_range_rejected(self):
        with pytest.raises(ValueError):
            SparseVector(3, [3], [1.0])

    def test_get_set(self):
        s = Vectors.sparse(5, [1, 3], [10.0, 30.0])
        assert s.get(1) == 10.0
        assert s.get(2) == 0.0
        s.set(2, 20.0)
        assert s.get(2) == 20.0
        assert s.indices.tolist() == [1, 2, 3]

    def test_to_dense_roundtrip(self):
        s = Vectors.sparse(4, [0, 2], [1.0, 3.0])
        assert np.array_equal(s.to_array(), [1.0, 0.0, 3.0, 0.0])
        assert s.to_dense().to_sparse() == s


class TestDenseMatrix:
    def test_zeros(self):
        m = DenseMatrix(2, 3)
        assert m.num_rows == 2 and m.num_cols == 3
        assert m.get(1, 2) == 0.0

    def test_column_major_flat_values(self):
        # Ref DenseMatrix.java: flat values are column-major.
        m = DenseMatrix(2, 2, [1.0, 2.0, 3.0, 4.0])
        assert m.get(0, 0) == 1.0
        assert m.get(1, 0) == 2.0
        assert m.get(0, 1) == 3.0
        assert m.get(1, 1) == 4.0


class TestBLAS:
    """Values mirror BLASTest.java expectations."""

    def setup_method(self):
        self.x = DenseVector([1.0, -2.0, 3.0, 4.0])
        self.y = DenseVector([2.0, 2.0, 2.0, 2.0])

    def test_asum(self):
        assert float(blas.asum(self.x)) == pytest.approx(10.0)

    def test_axpy(self):
        r = np.asarray(blas.axpy(2.0, self.x, self.y))
        assert r.tolist() == [4.0, -2.0, 8.0, 10.0]

    def test_dot(self):
        assert float(blas.dot(self.x, self.y)) == pytest.approx(12.0)

    def test_hdot(self):
        r = np.asarray(blas.hdot(self.x, self.y))
        assert r.tolist() == [2.0, -4.0, 6.0, 8.0]

    def test_norm2(self):
        assert float(blas.norm2(self.x)) == pytest.approx(np.sqrt(30.0))

    def test_norm_inf(self):
        assert float(blas.norm(self.x, float("inf"))) == pytest.approx(4.0)

    def test_scal(self):
        r = np.asarray(blas.scal(2.0, self.x))
        assert r.tolist() == [2.0, -4.0, 6.0, 8.0]

    def test_gemv(self):
        m = DenseMatrix(values=[[1.0, 2.0, 3.0, 4.0], [5.0, 6.0, 7.0, 8.0]])
        y = DenseVector([1.0, 1.0])
        r = np.asarray(blas.gemv(1.0, m, False, self.x, 0.5, y))
        # M @ x = [1-4+9+16, 5-12+21+32] = [22, 46]; + 0.5*y
        assert r.tolist() == [22.5, 46.5]

    def test_sq_dist_batch(self):
        xs = np.array([[0.0, 0.0], [1.0, 1.0]])
        cs = np.array([[0.0, 0.0], [3.0, 4.0]])
        d = np.asarray(blas.sq_dist_batch(xs, cs))
        assert d[0].tolist() == [0.0, 25.0]
        assert d[1].tolist() == pytest.approx([2.0, 13.0])


class TestBlasAgainstNumpy:
    """Every BLAS kernel against its numpy definition (ref BLASTest values)."""

    def test_kernels(self):
        rng = np.random.default_rng(9)
        x = rng.standard_normal(16)
        y = rng.standard_normal(16)
        np.testing.assert_allclose(blas.asum(x), np.abs(x).sum(), rtol=1e-6)
        np.testing.assert_allclose(blas.dot(x, y), x @ y, rtol=1e-6)
        np.testing.assert_allclose(blas.hdot(x, y), x * y, rtol=1e-6)
        np.testing.assert_allclose(blas.norm2(x), np.linalg.norm(x), rtol=1e-6)
        np.testing.assert_allclose(blas.norm(x, 1.0), np.abs(x).sum(), rtol=1e-6)
        np.testing.assert_allclose(blas.norm(x, np.inf), np.abs(x).max(), rtol=1e-6)
        np.testing.assert_allclose(blas.scal(2.5, x), 2.5 * x, rtol=1e-6)
        np.testing.assert_allclose(blas.axpy(0.5, x, y), 0.5 * x + y, rtol=1e-6)

    def test_gemv_both_orientations(self):
        rng = np.random.default_rng(10)
        A = rng.standard_normal((4, 6))
        x6, x4 = rng.standard_normal(6), rng.standard_normal(4)
        y4, y6 = rng.standard_normal(4), rng.standard_normal(6)
        np.testing.assert_allclose(
            blas.gemv(2.0, A, False, x6, 0.5, y4), 2.0 * A @ x6 + 0.5 * y4, rtol=1e-5
        )
        np.testing.assert_allclose(
            blas.gemv(1.0, A, True, x4, 0.0, y6), A.T @ x4, rtol=1e-5, atol=1e-6
        )

    def test_batched_kernels(self):
        rng = np.random.default_rng(11)
        X = rng.standard_normal((8, 5))
        y = rng.standard_normal(5)
        C = rng.standard_normal((3, 5))
        np.testing.assert_allclose(blas.dots_batch(X, y), X @ y, rtol=1e-5)
        want = ((X[:, None, :] - C[None, :, :]) ** 2).sum(-1)
        np.testing.assert_allclose(blas.sq_dist_batch(X, C), want, rtol=1e-4, atol=1e-4)


class TestVectorInvariants:
    def test_sparse_rejects_bad_indices(self):
        with pytest.raises(ValueError):
            SparseVector(3, [0, 3], [1.0, 2.0])  # out of range
        with pytest.raises(ValueError):
            SparseVector(3, [1, 1], [1.0, 2.0])  # duplicate
        with pytest.raises(ValueError):
            SparseVector(3, [0], [1.0, 2.0])  # shape mismatch

    def test_sparse_constructor_sorts_pairs(self):
        v = SparseVector(5, [4, 0, 2], [40.0, 0.5, 20.0])
        np.testing.assert_array_equal(v.indices, [0, 2, 4])
        np.testing.assert_array_equal(v.values, [0.5, 20.0, 40.0])
        assert v.get(2) == 20.0 and v.get(1) == 0.0

    def test_sparse_set_inserts_and_updates(self):
        v = SparseVector(5, [1], [1.0])
        v.set(3, 9.0)  # insert keeps sorted order
        np.testing.assert_array_equal(v.indices, [1, 3])
        v.set(1, 5.0)  # update in place
        assert v.get(1) == 5.0
        with pytest.raises(IndexError):
            v.set(5, 1.0)

    def test_dense_sparse_round_trip(self):
        d = DenseVector([0.0, 3.0, 0.0, 4.0])
        s = d.to_sparse()
        np.testing.assert_array_equal(s.indices, [1, 3])
        np.testing.assert_array_equal(s.to_dense().values, d.values)


class TestDenseMatrix:
    def test_get_set_clone_eq(self):
        from flink_ml_tpu.linalg import DenseMatrix

        m = DenseMatrix(2, 3, np.arange(6.0))
        assert (m.num_rows, m.num_cols) == (2, 3)
        m2 = m.clone()
        m2.set(1, 2, 99.0)
        assert m.get(1, 2) != 99.0 and m2.get(1, 2) == 99.0
        assert m == m.clone() and m != m2
