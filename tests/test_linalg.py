"""Linalg tests mirroring the reference's BLASTest / DenseVectorTest / SparseVectorTest
semantics (flink-ml-servable-core/src/test/.../linalg/)."""
import numpy as np
import pytest

from flink_ml_tpu.linalg import DenseMatrix, DenseVector, SparseVector, Vectors, blas


class TestDenseVector:
    def test_basic(self):
        v = Vectors.dense(1.0, 2.0, 3.0)
        assert v.size() == 3
        assert v.get(1) == 2.0
        v.set(1, 5.0)
        assert v.get(1) == 5.0
        assert np.array_equal(v.to_array(), [1.0, 5.0, 3.0])

    def test_clone_independent(self):
        v = Vectors.dense(1.0, 2.0)
        c = v.clone()
        c.set(0, 9.0)
        assert v.get(0) == 1.0

    def test_to_sparse(self):
        v = Vectors.dense(0.0, 2.0, 0.0, 3.0)
        s = v.to_sparse()
        assert s.indices.tolist() == [1, 3]
        assert s.values.tolist() == [2.0, 3.0]
        assert s.size() == 4

    def test_equality_and_iter(self):
        assert Vectors.dense(1.0, 2.0) == Vectors.dense(1.0, 2.0)
        assert list(Vectors.dense(1.0, 2.0)) == [1.0, 2.0]


class TestSparseVector:
    def test_sorted_invariant(self):
        s = Vectors.sparse(5, [3, 1], [30.0, 10.0])
        assert s.indices.tolist() == [1, 3]
        assert s.values.tolist() == [10.0, 30.0]

    def test_duplicate_index_rejected(self):
        with pytest.raises(ValueError):
            SparseVector(5, [1, 1], [1.0, 2.0])

    def test_out_of_range_rejected(self):
        with pytest.raises(ValueError):
            SparseVector(3, [3], [1.0])

    def test_get_set(self):
        s = Vectors.sparse(5, [1, 3], [10.0, 30.0])
        assert s.get(1) == 10.0
        assert s.get(2) == 0.0
        s.set(2, 20.0)
        assert s.get(2) == 20.0
        assert s.indices.tolist() == [1, 2, 3]

    def test_to_dense_roundtrip(self):
        s = Vectors.sparse(4, [0, 2], [1.0, 3.0])
        assert np.array_equal(s.to_array(), [1.0, 0.0, 3.0, 0.0])
        assert s.to_dense().to_sparse() == s


class TestDenseMatrix:
    def test_zeros(self):
        m = DenseMatrix(2, 3)
        assert m.num_rows == 2 and m.num_cols == 3
        assert m.get(1, 2) == 0.0

    def test_column_major_flat_values(self):
        # Ref DenseMatrix.java: flat values are column-major.
        m = DenseMatrix(2, 2, [1.0, 2.0, 3.0, 4.0])
        assert m.get(0, 0) == 1.0
        assert m.get(1, 0) == 2.0
        assert m.get(0, 1) == 3.0
        assert m.get(1, 1) == 4.0


class TestBLAS:
    """Values mirror BLASTest.java expectations."""

    def setup_method(self):
        self.x = DenseVector([1.0, -2.0, 3.0, 4.0])
        self.y = DenseVector([2.0, 2.0, 2.0, 2.0])

    def test_asum(self):
        assert float(blas.asum(self.x)) == pytest.approx(10.0)

    def test_axpy(self):
        r = np.asarray(blas.axpy(2.0, self.x, self.y))
        assert r.tolist() == [4.0, -2.0, 8.0, 10.0]

    def test_dot(self):
        assert float(blas.dot(self.x, self.y)) == pytest.approx(12.0)

    def test_hdot(self):
        r = np.asarray(blas.hdot(self.x, self.y))
        assert r.tolist() == [2.0, -4.0, 6.0, 8.0]

    def test_norm2(self):
        assert float(blas.norm2(self.x)) == pytest.approx(np.sqrt(30.0))

    def test_norm_inf(self):
        assert float(blas.norm(self.x, float("inf"))) == pytest.approx(4.0)

    def test_scal(self):
        r = np.asarray(blas.scal(2.0, self.x))
        assert r.tolist() == [2.0, -4.0, 6.0, 8.0]

    def test_gemv(self):
        m = DenseMatrix(values=[[1.0, 2.0, 3.0, 4.0], [5.0, 6.0, 7.0, 8.0]])
        y = DenseVector([1.0, 1.0])
        r = np.asarray(blas.gemv(1.0, m, False, self.x, 0.5, y))
        # M @ x = [1-4+9+16, 5-12+21+32] = [22, 46]; + 0.5*y
        assert r.tolist() == [22.5, 46.5]

    def test_sq_dist_batch(self):
        xs = np.array([[0.0, 0.0], [1.0, 1.0]])
        cs = np.array([[0.0, 0.0], [3.0, 4.0]])
        d = np.asarray(blas.sq_dist_batch(xs, cs))
        assert d[0].tolist() == [0.0, 25.0]
        assert d[1].tolist() == pytest.approx([2.0, 13.0])
