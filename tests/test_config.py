"""Runtime configuration tier (config.py) — the IterationOptions analogue:
set() > environment > default resolution, and consumption by the caches,
mesh, and streamed trainer."""
import os

import numpy as np
import pytest

from flink_ml_tpu.config import ConfigOption, Configuration, Options, config


@pytest.fixture(autouse=True)
def _clean_config():
    yield
    for opt in Options.all().values():
        config.unset(opt)


def test_resolution_order(monkeypatch):
    opt = Options.TRAIN_STREAM_WINDOW_ROWS
    assert config.get(opt) == 65_536  # default
    monkeypatch.setenv(opt.env_var, "1234")
    assert config.get(opt) == 1234  # env beats default
    config.set(opt, 99)
    assert config.get(opt) == 99  # set beats env
    config.unset(opt)
    assert config.get(opt) == 1234


def test_env_var_naming_and_typing(monkeypatch):
    assert Options.DATACACHE_SPILL_DIR.env_var == "FLINK_ML_TPU_DATACACHE_SPILL_DIR"
    monkeypatch.setenv("FLINK_ML_TPU_DATACACHE_MEMORY_BUDGET_BYTES", "2048")
    assert config.get(Options.DATACACHE_MEMORY_BUDGET_BYTES) == 2048
    monkeypatch.setenv("FLINK_ML_TPU_NATIVE_DATACACHE_ENABLED", "false")
    assert config.get(Options.NATIVE_DATACACHE_ENABLED) is False


def test_host_cache_consumes_config(tmp_path):
    from flink_ml_tpu.iteration import HostDataCache

    config.set(Options.DATACACHE_SPILL_DIR, str(tmp_path / "spill"))
    config.set(Options.DATACACHE_MEMORY_BUDGET_BYTES, 100)
    cache = HostDataCache()  # no constructor args: config decides
    assert cache.spill_dir == str(tmp_path / "spill")
    assert cache.memory_budget == 100
    cache.append({"x": np.arange(100.0)})
    cache.append({"x": np.arange(100.0)})
    cache.finish()
    assert any("files" in e for e in cache._log), "configured budget should spill"
    # explicit constructor args still win
    explicit = HostDataCache(memory_budget_bytes=1 << 20, spill_dir=str(tmp_path / "o"))
    assert explicit.memory_budget == 1 << 20


def test_streamed_sgd_consumes_window_config():
    from flink_ml_tpu.ops import SGD

    config.set(Options.TRAIN_STREAM_WINDOW_ROWS, 4)
    assert SGD().stream_window_rows == 4
    assert SGD(stream_window_rows=16).stream_window_rows == 16


def test_mesh_consumes_axis_config():
    from flink_ml_tpu.parallel.mesh import MeshContext

    config.set(Options.MESH_DATA_AXIS_SIZE, 2)
    config.set(Options.MESH_MODEL_AXIS_SIZE, 2)
    ctx = MeshContext()
    assert ctx.n_data == 2 and ctx.n_model == 2
    # explicit args still win
    ctx2 = MeshContext(n_data=4, n_model=1)
    assert ctx2.n_data == 4 and ctx2.n_model == 1


def test_capacity_cache_factory_respects_toggle():
    from flink_ml_tpu.iteration import HostDataCache, create_capacity_cache

    config.set(Options.NATIVE_DATACACHE_ENABLED, False)
    assert isinstance(create_capacity_cache(), HostDataCache)
    config.set(Options.NATIVE_DATACACHE_ENABLED, True)
    cache = create_capacity_cache()
    from flink_ml_tpu.native import native_available

    if native_available():
        from flink_ml_tpu.native.cache import NativeDataCache

        assert isinstance(cache, NativeDataCache)
    else:
        assert isinstance(cache, HostDataCache)


def test_to_dict_lists_every_option():
    d = config.to_dict()
    assert set(d) == set(Options.all())


def test_set_none_behaves_like_unset(monkeypatch):
    opt = Options.DATACACHE_MEMORY_BUDGET_BYTES
    monkeypatch.setenv(opt.env_var, "123")
    config.set(opt, 555)
    assert config.get(opt) == 555
    config.set(opt, None)  # no override: env (then default) shows through
    assert config.get(opt) == 123


def test_serving_options_resolve_through_config_tier(monkeypatch):
    """ServingConfig consumes the serving.* options: set() > env > default —
    a deployment tunes the server without code changes (docs/serving.md)."""
    from flink_ml_tpu.serving import ServingConfig

    assert ServingConfig().max_batch_size == 64  # defaults
    assert ServingConfig().queue_capacity_rows == 1024

    monkeypatch.setenv(Options.SERVING_MAX_BATCH_SIZE.env_var, "32")
    monkeypatch.setenv(Options.SERVING_MAX_DELAY_MS.env_var, "7.5")
    resolved = ServingConfig()
    assert resolved.max_batch_size == 32
    assert resolved.max_delay_ms == 7.5

    config.set(Options.SERVING_MAX_BATCH_SIZE, 8)
    try:
        assert ServingConfig().max_batch_size == 8  # set() beats env
        assert ServingConfig(max_batch_size=4).max_batch_size == 4  # arg beats all
    finally:
        config.unset(Options.SERVING_MAX_BATCH_SIZE)
