"""Open-loop load harness + SLO-adaptive serving (flink_ml_tpu/loadgen/,
serving/controller.py).

The acceptance contract of the robustness PR:

- determinism: same seed ⇒ byte-identical arrival schedule and request-size
  sequence; replay of a recorded schedule reproduces identical shed/miss
  counters (proven under a virtual clock — no wall-clock flake);
- structured rejection: overload/shed/deadline errors carry queue depth,
  capacity, phase, and retry-after context; the deadline is re-checked
  immediately before dispatch so an expired request never burns a device slot;
- fault points: ``serving.admit``, ``serving.dispatch``, ``loadgen.tick``
  fire deterministically and the serving loop / harness survive each;
- the control loop: under a seeded open-loop ramp past saturation, low
  priorities shed before any high-priority deadline miss, at least one
  controller action fires from the live goodput signal, and post-fault
  goodput recovers to the pre-fault fraction — with graftscope's per-category
  attribution summing to traced wall time throughout.
"""
import time

import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.faults import InjectedFault, faults
from flink_ml_tpu.loadgen import (
    BurstyArrivals,
    FixedSizes,
    OpenLoopLoadGenerator,
    PoissonArrivals,
    Schedule,
    StepStats,
    ZipfSizes,
    ramp_schedule,
)
from flink_ml_tpu.metrics import MLMetrics, metrics
from flink_ml_tpu.servable.api import TransformerServable
from flink_ml_tpu.serving import (
    AdaptiveController,
    GoodputLedger,
    InferenceServer,
    ServingConfig,
    ServingDeadlineError,
    ServingOverloadedError,
)
from flink_ml_tpu.serving.batcher import MicroBatcher, PendingRequest
from flink_ml_tpu.serving.batcher import _CLAIMED  # noqa: F401 — state seam
from flink_ml_tpu.trace import CAT_PRODUCTIVE, CAT_QUEUE
from flink_ml_tpu import trace


class _SlowEcho(TransformerServable):
    """Clones its input after a fixed per-batch delay — a deterministic
    service time, so saturation is computable: max_batch_size/delay rows/s."""

    def __init__(self, delay_s: float):
        super().__init__()
        self.delay_s = delay_s

    def transform(self, df):
        time.sleep(self.delay_s)
        return df.clone()


def _echo_server(name, *, delay_s=0.004, max_batch=8, capacity=32, **cfg_kwargs):
    cfg = ServingConfig(
        max_batch_size=max_batch,
        max_delay_ms=0.5,
        queue_capacity_rows=capacity,
        default_timeout_ms=30_000,
        **cfg_kwargs,
    )
    return InferenceServer(
        _SlowEcho(delay_s),
        name=name,
        serving_config=cfg,
        warmup_template=DataFrame.from_dict({"x": np.zeros((1, 2))}),
    )


def _req(rows):
    return DataFrame.from_dict({"x": np.ones((rows, 2), np.float32)})


# ---------------------------------------------------------------------------
# schedules: seeded determinism + serialization
# ---------------------------------------------------------------------------
class TestScheduleDeterminism:
    STEPS = [(200.0, 0.25), (1000.0, 0.25)]

    def test_same_seed_byte_identical_schedule(self):
        a = ramp_schedule(self.STEPS, priority_mix={0: 0.7, 1: 0.3}, seed=42)
        b = ramp_schedule(self.STEPS, priority_mix={0: 0.7, 1: 0.3}, seed=42)
        assert a.to_json() == b.to_json()  # byte-identical, not just equal
        assert [e.rows for e in a] == [e.rows for e in b]  # size sequence
        assert [e.t for e in a] == [e.t for e in b]  # arrival times
        assert [e.priority for e in a] == [e.priority for e in b]

    def test_different_seeds_differ(self):
        a = ramp_schedule(self.STEPS, seed=1)
        b = ramp_schedule(self.STEPS, seed=2)
        assert a.to_json() != b.to_json()

    def test_bursty_process_deterministic_and_bursty(self):
        a = ramp_schedule(self.STEPS, process="bursty", seed=9)
        b = ramp_schedule(self.STEPS, process="bursty", seed=9)
        assert a.to_json() == b.to_json()
        # burstiness: max arrivals in any 50 ms window far exceeds the
        # average-rate expectation for that window
        times = [e.t for e in a if e.step == 0]
        if len(times) >= 4:
            best = max(
                sum(1 for t in times if t0 <= t < t0 + 0.05) for t0 in times
            )
            assert best >= 2

    def test_roundtrip_is_identity(self, tmp_path):
        a = ramp_schedule(self.STEPS, priority_mix={0: 0.5, 2: 0.5}, seed=5)
        path = str(tmp_path / "sched.json")
        a.save(path)
        b = Schedule.load(path)
        assert a.to_json() == b.to_json()
        assert b.meta["seed"] == 5
        assert b.n_steps == a.n_steps

    def test_schedule_step_accounting(self):
        s = ramp_schedule([(500.0, 0.2)], sizes=FixedSizes(4), seed=3)
        assert s.n_steps == 1
        assert s.offered_rows(0) == 4 * len(s)
        assert all(e.rows == 4 for e in s)

    def test_zipf_sizes_heavy_tailed(self):
        import random

        sizes = ZipfSizes((1, 2, 4, 8, 16), alpha=1.5)
        rng = random.Random(0)
        draws = [sizes.draw(rng) for _ in range(4000)]
        assert set(draws) <= {1, 2, 4, 8, 16}
        assert draws.count(1) > len(draws) * 0.4  # head dominates
        assert 16 in draws  # but the tail is real
        assert 1.0 < sizes.mean_rows < 8.0

    def test_invalid_args_raise(self):
        with pytest.raises(ValueError):
            PoissonArrivals(0.0)
        with pytest.raises(ValueError):
            BurstyArrivals(10.0, burst_factor=1.0)
        with pytest.raises(ValueError):
            ZipfSizes(())
        with pytest.raises(ValueError):
            ramp_schedule([])
        with pytest.raises(ValueError):
            ramp_schedule([(10, 1)], process="constant")
        with pytest.raises(ValueError):
            Schedule.from_json('{"version": 99, "entries": []}')


# ---------------------------------------------------------------------------
# replay determinism under a virtual clock
# ---------------------------------------------------------------------------
class _ManualClock:
    """Virtual time: ``sleep`` jumps it forward, nothing else moves it."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def sleep(self, dt: float) -> None:
        self.t += max(0.0, dt)


class _VirtualResponse:
    __slots__ = ("latency_ms",)

    def __init__(self, latency_ms):
        self.latency_ms = latency_ms


class _VirtualHandle:
    __slots__ = ("_response", "_error")

    def __init__(self, response=None, error=None):
        self._response = response
        self._error = error

    def result(self):
        if self._error is not None:
            raise self._error
        return self._response


class _VirtualRequest:
    """Payload stub: the generator only needs ``len``."""

    __slots__ = ("rows",)

    def __init__(self, rows):
        self.rows = rows

    def __len__(self):
        return self.rows


class _VirtualServer:
    """Deterministic virtual-time server: fixed drain rate, bounded backlog.
    Every decision is a pure function of (arrival time, backlog), so a
    replayed schedule produces identical shed/miss counters."""

    def __init__(self, clock, *, rows_per_s=200.0, capacity_rows=16):
        self._clock = clock
        self.rate = rows_per_s
        self.capacity = capacity_rows
        self._busy_until = 0.0

    def submit(self, df, timeout_ms, priority):
        now = self._clock()
        backlog_rows = max(0.0, self._busy_until - now) * self.rate
        if backlog_rows + len(df) > self.capacity:
            raise ServingOverloadedError(
                int(backlog_rows), self.capacity,
                shed=priority > 0, priority=priority,
                retry_after_ms=1000.0 * backlog_rows / self.rate,
            )
        self._busy_until = max(now, self._busy_until) + len(df) / self.rate
        latency_ms = (self._busy_until - now) * 1000.0
        if latency_ms > timeout_ms:
            return _VirtualHandle(error=ServingDeadlineError(
                "virtual deadline", phase="queued", queued_ms=latency_ms,
            ))
        return _VirtualHandle(response=_VirtualResponse(latency_ms))


class TestReplayDeterminism:
    def _run(self, schedule):
        clock = _ManualClock()
        server = _VirtualServer(clock)
        gen = OpenLoopLoadGenerator(
            schedule,
            _VirtualRequest,
            timeout_ms={0: 500.0, 1: 60.0},
            collectors=4,
            clock=clock,
            sleep=clock.sleep,
        )
        return gen.run(server)

    def _counters(self, report):
        return [
            (s.arrivals, s.completed, s.shed, s.rejected,
             s.deadline_miss_queued, s.deadline_miss_dispatch,
             s.first_shed_at_s, tuple(sorted(s.latencies_ms)))
            for s in report.steps
        ]

    def test_replay_reproduces_identical_counters(self, tmp_path):
        sched = ramp_schedule(
            [(100.0, 0.5), (600.0, 0.5), (100.0, 0.5)],
            priority_mix={0: 0.6, 1: 0.4},
            sizes=ZipfSizes((1, 2, 4)),
            seed=17,
        )
        first = self._run(sched)
        # recorded → saved → reloaded → replayed: identical counters, to the
        # latency sample
        path = str(tmp_path / "recorded.json")
        sched.save(path)
        second = self._run(Schedule.load(path))
        assert self._counters(first) == self._counters(second)
        assert first.fully_resolved() and second.fully_resolved()
        # the ramp actually overloads the virtual server mid-run
        assert first.step(1).shed + first.step(1).rejected > 0
        assert first.step(1).first_shed_at_s is not None

    def test_virtual_run_never_lags(self):
        sched = ramp_schedule([(300.0, 0.3)], seed=23)
        clock = _ManualClock()
        gen = OpenLoopLoadGenerator(
            sched, _VirtualRequest, timeout_ms=1000.0,
            clock=clock, sleep=clock.sleep,
        )
        report = gen.run(_VirtualServer(clock))
        assert report.steps[0].max_lag_s < 1e-9
        assert report.wall_s >= sched.duration_s - 1e-9


# ---------------------------------------------------------------------------
# structured rejection context
# ---------------------------------------------------------------------------
class TestErrorContext:
    def test_overload_error_carries_backoff_context(self):
        e = ServingOverloadedError(48, 64, retry_after_ms=120.0)
        assert e.queued_rows == 48 and e.queue_depth == 48
        assert e.capacity_rows == 64
        assert e.retry_after_ms == 120.0
        assert not e.shed
        assert "retry after" in str(e)

    def test_shed_error_is_distinguishable(self):
        e = ServingOverloadedError(40, 64, retry_after_ms=80.0, shed=True, priority=2)
        assert e.shed and e.priority == 2
        assert "shed" in str(e)

    def test_deadline_error_carries_phase_and_wait(self):
        e = ServingDeadlineError("x", phase="dispatch", queued_ms=12.5, retry_after_ms=9.0)
        assert e.phase == "dispatch"
        assert e.queued_ms == 12.5
        assert e.retry_after_ms == 9.0
        assert isinstance(e, TimeoutError)

    def test_live_hard_reject_carries_depth_capacity_and_estimate(self):
        server = _echo_server("t-ctx-reject", delay_s=0.05, max_batch=1, capacity=4)
        try:
            blocker = server.submit(_req(1))
            deadline = time.perf_counter() + 5.0
            while server._batcher._queued_rows and time.perf_counter() < deadline:
                time.sleep(0.005)
            handles = [server.submit(_req(1)) for _ in range(4)]
            with pytest.raises(ServingOverloadedError) as exc:
                server.submit(_req(1))
            assert exc.value.capacity_rows == 4
            assert exc.value.queued_rows == 4
            assert not exc.value.shed
            # once a batch has been observed the controller has a drain-rate
            # estimate, so the NEXT hard reject carries retry-after context
            blocker.result()
            with pytest.raises(ServingOverloadedError) as exc2:
                for _ in range(8):
                    server.submit(_req(1))
            assert exc2.value.retry_after_ms is not None
            assert exc2.value.retry_after_ms > 0.0
        finally:
            server.close()

    def test_queued_deadline_error_has_context(self):
        server = _echo_server("t-ctx-deadline", delay_s=0.08, max_batch=1, capacity=16)
        try:
            blocker = server.submit(_req(1), timeout_ms=30_000)
            deadline = time.perf_counter() + 5.0
            while server._batcher._queued_rows and time.perf_counter() < deadline:
                time.sleep(0.005)
            victim = server.submit(_req(1), timeout_ms=20)
            with pytest.raises(ServingDeadlineError) as exc:
                victim.result()
            assert exc.value.phase == "queued"
            assert exc.value.queued_ms is not None and exc.value.queued_ms >= 0.0
            assert blocker.result() is not None
        finally:
            server.close()


# ---------------------------------------------------------------------------
# the pre-dispatch deadline re-check
# ---------------------------------------------------------------------------
class TestDispatchDeadlineRecheck:
    def _batcher(self, executed):
        def execute(padded_df):
            executed.append(len(padded_df))
            return padded_df.clone(), 1

        class _Resp:
            def __init__(self, df, version, latency_ms, bucket):
                self.dataframe = df
                self.model_version = version
                self.latency_ms = latency_ms
                self.bucket = bucket

        return MicroBatcher(
            execute,
            max_batch_size=4,
            max_delay_ms=0.0,
            queue_capacity_rows=64,
            scope="ml.serving[t-recheck]",
            response_factory=_Resp,
        )

    def test_expired_claimed_request_fails_fast_without_device_slot(self):
        """A request that expired in the pad/scatter window (claimed but past
        deadline at dispatch time) fails with phase='dispatch' and is NOT
        executed; live requests in the same claim still serve."""
        executed = []
        batcher = self._batcher(executed)
        try:
            now = time.perf_counter()
            expired = PendingRequest(_req(1), deadline=now - 0.01)
            live = PendingRequest(_req(1), deadline=now + 30.0)
            for r in (expired, live):
                r._state = _CLAIMED
                batcher._install_abandon(r)
            before = metrics.get(batcher.scope, MLMetrics.SERVING_DEADLINE_DISPATCH) or 0
            batcher._run_batch([expired, live])
            assert isinstance(expired.error, ServingDeadlineError)
            assert expired.error.phase == "dispatch"
            assert expired.error.queued_ms is not None
            assert live.error is None and live.response is not None
            # the expired request's row never reached the device: the batch
            # executed at bucket 1, not 2
            assert executed == [1]
            after = metrics.get(batcher.scope, MLMetrics.SERVING_DEADLINE_DISPATCH)
            assert after == before + 1
        finally:
            batcher.close()

    def test_all_expired_skips_execution_entirely(self):
        executed = []
        batcher = self._batcher(executed)
        try:
            now = time.perf_counter()
            reqs = [PendingRequest(_req(1), deadline=now - 0.01) for _ in range(3)]
            for r in reqs:
                r._state = _CLAIMED
                batcher._install_abandon(r)
            assert batcher._run_batch(list(reqs)) is None
            assert executed == []
            for r in reqs:
                assert isinstance(r.error, ServingDeadlineError)
                assert r.error.phase == "dispatch"
        finally:
            batcher.close()


# ---------------------------------------------------------------------------
# fault points: serving.admit / serving.dispatch / loadgen.tick
# ---------------------------------------------------------------------------
class TestServingFaultPoints:
    def test_serving_admit_fault_fails_synchronously_queue_stays_consistent(self):
        server = _echo_server("t-fault-admit", delay_s=0.001)
        faults.reset()
        try:
            faults.arm("serving.admit", at=2)
            assert server.predict(_req(1)) is not None  # hit 1: passes
            with pytest.raises(InjectedFault):
                server.predict(_req(1))  # hit 2: fails at the queue door
            # nothing half-admitted: the queue drains and later traffic serves
            assert server.predict(_req(2)) is not None
            assert server._batcher._queued_rows == 0
        finally:
            faults.reset()
            server.close()

    def test_serving_dispatch_fault_fails_batch_typed_then_recovers(self):
        server = _echo_server("t-fault-dispatch", delay_s=0.001)
        faults.reset()
        try:
            assert server.predict(_req(1)) is not None
            faults.arm("serving.dispatch", at=1)
            with pytest.raises(InjectedFault):
                server.predict(_req(1))  # the claimed batch dies post-pad
            # exactly-once: the next batch serves normally — no deadlock, no
            # stuck claim
            assert server.predict(_req(1)) is not None
        finally:
            faults.reset()
            server.close()

    def test_loadgen_tick_fault_drops_one_arrival_and_run_continues(self):
        sched = ramp_schedule([(400.0, 0.1)], sizes=FixedSizes(1), seed=31)
        assert len(sched) >= 5
        clock = _ManualClock()
        server = _VirtualServer(clock, rows_per_s=10_000.0, capacity_rows=1 << 20)
        gen = OpenLoopLoadGenerator(
            sched, _VirtualRequest, timeout_ms=10_000.0,
            clock=clock, sleep=clock.sleep,
        )
        faults.reset()
        try:
            faults.arm("loadgen.tick", at=3)
            report = gen.run(server)
        finally:
            faults.reset()
        stats = report.steps[0]
        assert stats.injected == 1  # the dropped arrival, accounted
        assert stats.completed == stats.arrivals - 1  # the rest stayed on time
        assert report.fully_resolved()


# ---------------------------------------------------------------------------
# controller units
# ---------------------------------------------------------------------------
class TestGoodputLedger:
    def test_window_eviction(self):
        clock = _ManualClock()
        ledger = GoodputLedger(window_s=1.0, clock=clock)
        ledger.add(CAT_QUEUE, 0.5)
        clock.t = 0.5
        ledger.add(CAT_PRODUCTIVE, 0.25)
        totals = ledger.totals()
        assert totals[CAT_QUEUE] == pytest.approx(0.5)
        assert totals[CAT_PRODUCTIVE] == pytest.approx(0.25)
        clock.t = 1.2  # the first event falls out of the window
        totals = ledger.totals()
        assert CAT_QUEUE not in totals
        assert totals[CAT_PRODUCTIVE] == pytest.approx(0.25)

    def test_share_and_report(self):
        clock = _ManualClock()
        ledger = GoodputLedger(window_s=10.0, clock=clock)
        assert ledger.share(CAT_QUEUE) is None
        ledger.add(CAT_QUEUE, 3.0)
        ledger.add(CAT_PRODUCTIVE, 1.0)
        assert ledger.share(CAT_QUEUE) == pytest.approx(0.75)
        report = ledger.report("ml.serving[t-ledger]")
        assert report.fraction("ml.serving[t-ledger]") == pytest.approx(0.25)
        assert report.wall_s("ml.serving[t-ledger]") == pytest.approx(4.0)


class TestAdaptiveControllerUnits:
    def _controller(self, clock, **kw):
        kw.setdefault("shed_watermark", 0.5)
        kw.setdefault("shed_sustain_ms", 100.0)
        kw.setdefault("shed_priority", 1)
        kw.setdefault("window_ms", 10_000.0)
        kw.setdefault("queue_fraction", 0.5)
        kw.setdefault("depth_max", 4)
        kw.setdefault("deadline_safety", 2.0)
        return AdaptiveController(
            "ml.serving[t-ctrl]", 100, 16, base_depth=1, clock=clock, **kw
        )

    def test_shed_requires_sustained_overload_and_sheddable_priority(self):
        clock = _ManualClock()
        c = self._controller(clock)
        c.note_queue(80)  # above the 50-row watermark
        assert not c.should_shed(1, 80)  # not sustained yet
        clock.t = 0.2  # 200 ms > the 100 ms hold-down
        assert c.should_shed(1, 80)
        assert not c.should_shed(0, 80)  # priority 0 is never shed
        c.note_queue(10)  # drained below the watermark: overload over
        clock.t = 1.0
        assert not c.should_shed(1, 80)

    def test_retry_after_tracks_drain_rate(self):
        clock = _ManualClock()
        c = self._controller(clock)
        assert c.retry_after_ms(50) is None  # no batches observed yet
        c.observe_batch(16, 16, 0.1)  # 160 rows/s
        est = c.retry_after_ms(32)
        assert est == pytest.approx(1000.0 * 32 / 160.0, rel=0.01)

    def test_bucket_cap_downshifts_to_affordable_bucket(self):
        clock = _ManualClock()
        c = self._controller(clock)
        buckets = (1, 2, 4, 8, 16)
        assert c.bucket_cap(0.05, buckets) is None  # no estimates yet
        for b, s in ((1, 0.002), (2, 0.004), (4, 0.008), (8, 0.016), (16, 0.032)):
            for _ in range(4):
                c.observe_batch(b, b, s)
        # 20 ms remaining, safety 2 → needs est*2 <= 0.020 → bucket 4 (0.008*2)
        assert c.bucket_cap(0.020, buckets) == 4
        # plenty of time → no cap
        assert c.bucket_cap(10.0, buckets) is None
        # hopeless deadline still allows the smallest bucket (starvation guard)
        assert c.bucket_cap(0.001, buckets) == 1

    def test_depth_steps_up_down_and_recommends_mesh_at_ceiling(self):
        clock = _ManualClock()
        c = self._controller(clock, depth_max=3)  # 10 s window → 2.5 s cooldown
        c.ledger.add(CAT_QUEUE, 3.0)
        c.ledger.add(CAT_PRODUCTIVE, 1.0)
        a1 = c.maybe_step(1)
        assert a1 is not None and a1.kind == "depth" and a1.value == 2
        # cooldown: an immediate second call does nothing
        assert c.maybe_step(2) is None
        clock.t = 3.0  # past the cooldown, still inside the ledger window
        a2 = c.maybe_step(2)
        assert a2 is not None and a2.kind == "depth" and a2.value == 3
        clock.t = 6.0
        a3 = c.maybe_step(3)  # at the ceiling → mesh recommendation
        assert a3 is not None and a3.kind == "mesh.recommend" and a3.value == 2
        assert metrics.get(c.scope, MLMetrics.SERVING_CONTROLLER_MESH_RECOMMEND) == 2
        # queueing subsides (old window evicted, fresh productive-only signal)
        # → step back down toward base depth
        clock.t = 25.0
        c.ledger.add(CAT_PRODUCTIVE, 1.0)
        a4 = c.maybe_step(3)
        assert a4 is not None and a4.kind == "depth" and a4.value == 2


# ---------------------------------------------------------------------------
# the closed control loop under open-loop overload (the acceptance scenario)
# ---------------------------------------------------------------------------
class TestAdaptiveServingUnderLoad:
    """Seeded open-loop ramp to ≥2x saturation with faults armed at the
    serving seams. _SlowEcho(4 ms) at max_batch 2 saturates at
    2/0.004 = 500 rows/s; 1-row requests at 1100 rps offer ~2.2x that."""

    def test_ramp_sheds_low_priority_before_high_priority_misses(self):
        server = _echo_server(
            "t-ramp-priority", delay_s=0.004, max_batch=2, capacity=24,
            shed_sustain_ms=5.0, shed_watermark=0.6,
        )
        sched = ramp_schedule(
            # the 2 rps step between overload and recovery lets the bounded
            # queue drain so the recovery step starts below the watermark
            [(80.0, 0.3), (1100.0, 0.8), (2.0, 0.4), (80.0, 0.3)],
            priority_mix={0: 0.5, 1: 0.5},
            sizes=FixedSizes(1),
            seed=101,
        )
        gen = OpenLoopLoadGenerator(
            sched, _req,
            # generous deadline for guaranteed traffic, tight for best-effort
            timeout_ms={0: 30_000.0, 1: 2_000.0},
        )
        faults.reset()
        try:
            report = gen.run(server)
        finally:
            faults.reset()
            server.close()
        assert report.fully_resolved()
        assert not report.unexpected
        overload = report.step(1)
        # the ramp actually overloaded: sheds happened, and they happened to
        # the sheddable priority only
        assert overload.shed > 0
        assert overload.by_priority[1]["shed"] == overload.shed
        assert overload.by_priority.get(0, {}).get("shed", 0) == 0
        assert overload.first_shed_at_s is not None
        # low-priority shed BEFORE any high-priority deadline miss: priority-0
        # traffic met every deadline end to end
        p0 = {k: v for s in report.steps for k, v in s.by_priority.get(0, {}).items()}
        assert sum(
            s.by_priority.get(0, {}).get("deadline_miss", 0) for s in report.steps
        ) == 0, p0
        # recovery step is clean again
        recovery = report.step(3)
        assert recovery.shed == 0 and recovery.rejected == 0
        # shed counter is observable
        assert metrics.get(server.scope, MLMetrics.SERVING_SHED) >= overload.shed

    def test_controller_action_fires_from_live_goodput_signal(self):
        server = _echo_server(
            "t-ramp-action", delay_s=0.004, max_batch=2, capacity=64,
            shed_sustain_ms=5.0, controller_window_ms=400.0,
            controller_queue_fraction=0.4,
        )
        sched = ramp_schedule(
            [(1100.0, 0.8)], sizes=FixedSizes(1), seed=7,
            priority_mix={0: 0.5, 1: 0.5},
        )
        gen = OpenLoopLoadGenerator(
            sched, _req, timeout_ms={0: 30_000.0, 1: 1_000.0},
        )
        faults.reset()
        try:
            report = gen.run(server)
            controller = server.controller
            # the queue category dominated the live ledger under the ramp and
            # at least one control action fired off it (depth step up — the
            # queue share gate — or a deadline-driven bucket downshift)
            stepped = controller.actions_of("depth") + controller.actions_of("bucket")
            assert stepped, controller.actions
            if controller.actions_of("depth"):
                assert metrics.get(server.scope, MLMetrics.SERVING_CONTROLLER_DEPTH) >= 2
            assert metrics.get(server.scope, MLMetrics.SERVING_CONTROLLER_ACTIONS) >= 1
        finally:
            faults.reset()
            server.close()
        assert report.fully_resolved()

    def test_chaos_ramp_recovers_goodput_with_exact_attribution(self):
        """Faults armed at the serving seams DURING a live open-loop ramp:
        typed-error-only failures, no deadlock, and post-fault goodput within
        10% of the pre-fault baseline — with graftscope's per-category
        attribution summing to traced wall time in every phase."""
        server = _echo_server(
            "t-chaos", delay_s=0.004, max_batch=2, capacity=24,
            shed_sustain_ms=5.0,
        )

        def phase(steps, seed):
            sched = ramp_schedule(
                steps, sizes=FixedSizes(1), seed=seed, priority_mix={0: 0.6, 1: 0.4}
            )
            gen = OpenLoopLoadGenerator(
                sched, _req, timeout_ms={0: 30_000.0, 1: 1_500.0},
            )
            with trace.capture() as recorder:
                report = gen.run(server)
            spans = recorder.snapshot()
            gp = recorder.goodput_report()
            return report, spans, gp

        faults.reset()
        try:
            baseline_report, base_spans, base_gp = phase([(100.0, 0.5)], seed=1)
            # chaos: overload ramp past saturation with both serving seams
            # armed probabilistically (seeded — the run is reproducible)
            faults.arm("serving.dispatch", prob=0.05, seed=3)
            faults.arm("serving.admit", prob=0.02, seed=4)
            chaos_report, _, _ = phase([(1100.0, 0.8)], seed=2)
            faults.reset()
            recovery_report, rec_spans, rec_gp = phase([(100.0, 0.5)], seed=5)
        finally:
            faults.reset()
            server.close()

        # no deadlock, nothing lost, nothing untyped — in every phase
        for report in (baseline_report, chaos_report, recovery_report):
            assert report.fully_resolved()
            assert not report.unexpected, report.unexpected
        # the chaos phase actually failed work through the armed seams
        assert chaos_report.step(0).injected > 0
        assert chaos_report.step(0).shed + chaos_report.step(0).rejected > 0
        # exact attribution invariant: per-scope category totals sum to the
        # scope's root-span wall time (graftscope's contract), both phases
        for spans, gp in ((base_spans, base_gp), (rec_spans, rec_gp)):
            by_scope = {}
            ids_by_scope = {}
            for s in spans:
                ids_by_scope.setdefault(s.scope, set()).add(s.span_id)
            for s in spans:
                if s.parent_id is None or s.parent_id not in ids_by_scope[s.scope]:
                    by_scope[s.scope] = by_scope.get(s.scope, 0.0) + s.duration
            for scope, root_wall in by_scope.items():
                assert gp.wall_s(scope) == pytest.approx(root_wall, rel=1e-6)
        # goodput recovered: the post-fault fraction is within 10% of the
        # pre-fault baseline at the same offered load
        scope = server.scope
        base_fraction = base_gp.fraction(scope)
        rec_fraction = rec_gp.fraction(scope)
        assert base_fraction is not None and rec_fraction is not None
        assert rec_fraction >= 0.9 * base_fraction, (base_fraction, rec_fraction)


# -----------------------------------------------------------------------------
# shared-state-guard regression: StepStats aggregates are lock-consistent
# -----------------------------------------------------------------------------


class TestStepStatsConcurrency:
    def test_aggregate_reads_are_exact_under_concurrent_writers(self):
        """graftcheck v3 regression: `resolved` / `deadline_misses` used to
        sum the counters without the lock the writers hold — an
        inconsistent-lockset torn read. With every access locked, hammering
        the counters from collector-like threads while the main thread reads
        must end in exact totals and never a mid-flight impossibility."""
        import threading as _threading

        stats = StepStats(0, 100.0, 1.0)
        n_threads, per_thread = 4, 500
        start = _threading.Barrier(n_threads + 1)

        def writer():
            start.wait()
            for _ in range(per_thread):
                stats.note_completed(0, 1.0)
                stats.note_injected()
                stats.note_deadline(1, ServingDeadlineError("x", phase="dispatch"))

        threads = [_threading.Thread(target=writer, daemon=True) for _ in range(n_threads)]
        for t in threads:
            t.start()
        start.wait()
        for _ in range(200):  # concurrent aggregate reads: locked snapshots
            snapshot = stats.resolved
            assert 0 <= snapshot <= n_threads * per_thread * 3
        for t in threads:
            t.join()
        assert stats.completed == n_threads * per_thread
        assert stats.injected == n_threads * per_thread
        assert stats.deadline_misses == n_threads * per_thread
        assert stats.resolved == n_threads * per_thread * 3
        assert stats.by_priority[1]["deadline_miss"] == n_threads * per_thread
