"""Online serving runtime tests (flink_ml_tpu/serving/).

The acceptance contract of the serving pillar:

- soak: ≥8 concurrent client threads with a hot swap mid-run — every request
  gets exactly one response, bit-identical to the serving version's transform
  at the response's bucket shape, and ``ml.model.version`` only advances;
- shape stability: a 1..max-batch request-size sweep executes only padded
  power-of-two buckets and compiles at most one executable per bucket;
- overload: the bounded queue rejects with the typed ``ServingOverloadedError``
  (never blocks, never deadlocks) and everything is observable through
  ``MetricsRegistry`` under ``ml.serving[<name>]``.
"""
import io
import json
import os
import threading
import time

import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.faults import InjectedFault, faults
from flink_ml_tpu.metrics import Histogram, MLMetrics, metrics
from flink_ml_tpu.servable.api import TransformerServable, load_servable
from flink_ml_tpu.serving import (
    InferenceServer,
    ModelRegistry,
    ModelVersionPoller,
    NoModelError,
    ServingClosedError,
    ServingConfig,
    ServingDeadlineError,
    ServingOverloadedError,
    bucket_for,
    pad_to,
    power_of_two_buckets,
    publish_servable,
)

RNG = np.random.default_rng(11)
DIM = 5  # distinctive width so jit-cache assertions don't collide with other tests


def _fit_lr(max_iter=10):
    X = RNG.normal(size=(96, DIM))
    y = (X @ np.arange(1.0, DIM + 1.0) > 0).astype(np.float64)
    df = DataFrame.from_dict({"features": X, "label": y})
    from flink_ml_tpu.models.classification.logistic_regression import LogisticRegression

    return LogisticRegression().set_max_iter(max_iter).set_global_batch_size(96).fit(df), X


def _servable(model):
    from flink_ml_tpu.servable import LogisticRegressionModelServable

    buf = io.BytesIO()
    np.savez(buf, coefficient=model.coefficient)
    buf.seek(0)
    return LogisticRegressionModelServable().set_model_data(buf)


def _row(X, i):
    return DataFrame.from_dict({"features": X[i : i + 1]})


class _SlowEcho(TransformerServable):
    """Clones its input after a fixed delay — the knob for queue-pressure tests."""

    def __init__(self, delay_s: float = 0.0):
        super().__init__()
        self.delay_s = delay_s

    def transform(self, df):
        if self.delay_s:
            time.sleep(self.delay_s)
        return df.clone()


# ---------------------------------------------------------------------------
# bucketing primitives
# ---------------------------------------------------------------------------
class TestBuckets:
    def test_power_of_two_buckets(self):
        assert power_of_two_buckets(64) == (1, 2, 4, 8, 16, 32, 64)
        assert power_of_two_buckets(1) == (1,)

    def test_non_power_of_two_max_is_its_own_bucket(self):
        assert power_of_two_buckets(48) == (1, 2, 4, 8, 16, 32, 48)

    def test_bucket_for(self):
        buckets = power_of_two_buckets(16)
        assert [bucket_for(n, buckets) for n in (1, 2, 3, 9, 16)] == [1, 2, 4, 16, 16]
        with pytest.raises(ValueError):
            bucket_for(17, buckets)

    def test_pad_to_repeats_row_zero(self):
        df = DataFrame.from_dict({"features": np.arange(6.0).reshape(2, 3)})
        padded = pad_to(df, 4)
        assert len(padded) == 4
        np.testing.assert_array_equal(padded["features"][2], padded["features"][0])
        np.testing.assert_array_equal(padded["features"][:2], df["features"])


# ---------------------------------------------------------------------------
# single-server behavior
# ---------------------------------------------------------------------------
class TestInferenceServer:
    def test_single_request_matches_direct_transform(self):
        model, X = _fit_lr()
        sv = _servable(model)
        with InferenceServer(sv, name="t-single") as server:
            resp = server.predict(_row(X, 0))
            assert resp.model_version == 1
            direct = sv.transform(pad_to(_row(X, 0), resp.bucket))
            np.testing.assert_array_equal(
                resp.dataframe["rawPrediction"], direct.take([0])["rawPrediction"]
            )

    def test_concurrent_requests_coalesce_into_buckets(self):
        model, X = _fit_lr()
        sv = _servable(model)
        cfg = ServingConfig(max_batch_size=16, max_delay_ms=10, queue_capacity_rows=256)
        with InferenceServer(sv, name="t-coalesce", serving_config=cfg) as server:
            results = {}

            def client(i):
                results[i] = server.predict(_row(X, i))

            threads = [threading.Thread(target=client, args=(i,)) for i in range(32)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(results) == 32
            executed = server.executed_batch_sizes
            assert all(b in server._batcher.buckets for _, b in executed)
            # coalescing happened: fewer batches than requests
            assert len(executed) < 32

    def test_no_model_is_a_typed_error(self):
        with InferenceServer(name="t-nomodel") as server:
            with pytest.raises(NoModelError):
                server.predict(DataFrame.from_dict({"features": np.zeros((1, DIM))}))

    def test_oversized_request_rejected(self):
        model, X = _fit_lr()
        cfg = ServingConfig(max_batch_size=4)
        with InferenceServer(_servable(model), name="t-oversize", serving_config=cfg) as server:
            with pytest.raises(ValueError, match="max_batch_size"):
                server.predict(DataFrame.from_dict({"features": X[:5]}))

    def test_closed_server_rejects(self):
        model, X = _fit_lr()
        server = InferenceServer(_servable(model), name="t-closed")
        server.close()
        with pytest.raises(ServingClosedError):
            server.predict(_row(X, 0))


# ---------------------------------------------------------------------------
# shape stability: the recompile bound
# ---------------------------------------------------------------------------
class TestShapeStability:
    def test_sweep_executes_only_buckets_and_compiles_once_per_bucket(self):
        from flink_ml_tpu.ops.kernels import dot_kernel

        model, X = _fit_lr()
        sv = _servable(model)
        cfg = ServingConfig(max_batch_size=16, max_delay_ms=0.0, queue_capacity_rows=256)
        buckets = power_of_two_buckets(16)
        with InferenceServer(sv, name="t-shapes", serving_config=cfg) as server:
            before = dot_kernel()._cache_size()

            def sweep():
                for n in range(1, 17):
                    df = DataFrame.from_dict({"features": X[:n]})
                    resp = server.predict(df)
                    assert len(resp.dataframe) == n

            sweep()
            after_first = dot_kernel()._cache_size()
            # at most one executable per bucket, for the whole 1..16 sweep
            assert after_first - before <= len(buckets)
            sweep()
            # a second identical sweep compiles NOTHING new
            assert dot_kernel()._cache_size() == after_first
            executed = {b for _, b in server.executed_batch_sizes}
            assert executed <= set(buckets)
            # fast path: the whole sweep ran on fused executables with ZERO
            # post-warmup XLA compiles (ml.serving.fastpath.compiles is the
            # lazy-compile alarm; the first batch builds the plan lazily —
            # no warmup template was given — and every later batch hits the
            # compiled per-bucket cache)
            fused = metrics.get(server.scope, MLMetrics.SERVING_FUSED_BATCHES)
            lazy = metrics.get(server.scope, MLMetrics.SERVING_FASTPATH_COMPILES) or 0
            assert fused == len(server.executed_batch_sizes)
            assert lazy <= len(buckets)  # at most the first hit of each bucket
            before_recompiles = lazy
            sweep()
            # steady state: repeating the sweep compiles nothing on the fast path
            assert (metrics.get(server.scope, MLMetrics.SERVING_FASTPATH_COMPILES) or 0) \
                == before_recompiles

    def test_swap_warms_every_bucket_before_serving(self):
        from flink_ml_tpu.ops.kernels import dot_kernel

        model, X = _fit_lr()
        model2, _ = _fit_lr(max_iter=25)
        cfg = ServingConfig(max_batch_size=8, max_delay_ms=0.0, queue_capacity_rows=64)
        with InferenceServer(_servable(model), name="t-warm", serving_config=cfg,
                             warmup_template=_row(X, 0)) as server:
            server.predict(_row(X, 0))  # compile through the serving path
            for n in range(1, 9):
                server.predict(DataFrame.from_dict({"features": X[:n]}))
            before = dot_kernel()._cache_size()
            server.swap(2, _servable(model2))
            # same shapes, same kernels: the swap (incl. its warmup) must not
            # have compiled any new executable
            assert dot_kernel()._cache_size() == before
            resp = server.predict(_row(X, 1))
            assert resp.model_version == 2


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------
class TestAdmissionControl:
    def test_overload_rejects_typed_and_never_deadlocks(self):
        cfg = ServingConfig(
            max_batch_size=1, max_delay_ms=0.0, queue_capacity_rows=4,
            default_timeout_ms=30_000,
        )
        server = InferenceServer(
            _SlowEcho(delay_s=0.15), name="t-overload", serving_config=cfg,
            warmup_template=DataFrame.from_dict({"x": np.zeros((1, 2))}),
        )
        try:
            one = DataFrame.from_dict({"x": np.ones((1, 2))})
            first = server.submit(one)  # claimed into the executing batch
            deadline = time.perf_counter() + 5.0
            while server._batcher._queued_rows and time.perf_counter() < deadline:
                time.sleep(0.005)
            handles = [server.submit(one) for _ in range(4)]  # fills capacity
            with pytest.raises(ServingOverloadedError) as exc:
                server.submit(one)
            assert exc.value.capacity_rows == 4
            assert metrics.get(server.scope, MLMetrics.SERVING_REJECTED) == 1
            # no deadlock: everything admitted completes
            assert first.result() is not None
            for h in handles:
                assert h.result() is not None
        finally:
            server.close()

    def test_queued_request_past_deadline_gets_deadline_error(self):
        cfg = ServingConfig(max_batch_size=1, max_delay_ms=0.0, queue_capacity_rows=16)
        server = InferenceServer(
            _SlowEcho(delay_s=0.25), name="t-deadline", serving_config=cfg,
            warmup_template=DataFrame.from_dict({"x": np.zeros((1, 2))}),
        )
        try:
            one = DataFrame.from_dict({"x": np.ones((1, 2))})
            blocker = server.submit(one, timeout_ms=30_000)
            deadline = time.perf_counter() + 5.0
            while server._batcher._queued_rows and time.perf_counter() < deadline:
                time.sleep(0.005)
            victim = server.submit(one, timeout_ms=30)  # expires while queued
            with pytest.raises(ServingDeadlineError):
                victim.result()
            assert blocker.result() is not None
            assert metrics.get(server.scope, MLMetrics.SERVING_TIMEOUTS) >= 1
        finally:
            server.close()

    def test_graceful_drain_serves_queued_requests(self):
        cfg = ServingConfig(max_batch_size=2, max_delay_ms=0.0, queue_capacity_rows=64)
        server = InferenceServer(
            _SlowEcho(delay_s=0.02), name="t-drain", serving_config=cfg,
            warmup_template=DataFrame.from_dict({"x": np.zeros((1, 2))}),
        )
        one = DataFrame.from_dict({"x": np.ones((1, 2))})
        handles = [server.submit(one) for _ in range(8)]
        server.close(drain=True)
        for h in handles:
            assert h.result() is not None
        with pytest.raises(ServingClosedError):
            server.predict(one)

    def test_hard_close_fails_queued_requests(self):
        cfg = ServingConfig(max_batch_size=1, max_delay_ms=0.0, queue_capacity_rows=64)
        server = InferenceServer(
            _SlowEcho(delay_s=0.1), name="t-hardclose", serving_config=cfg,
            warmup_template=DataFrame.from_dict({"x": np.zeros((1, 2))}),
        )
        one = DataFrame.from_dict({"x": np.ones((1, 2))})
        server.submit(one)
        deadline = time.perf_counter() + 5.0
        while server._batcher._queued_rows and time.perf_counter() < deadline:
            time.sleep(0.005)
        queued = [server.submit(one) for _ in range(3)]
        server.close(drain=False)
        failed = 0
        for h in queued:
            try:
                h.result()
            except ServingClosedError:
                failed += 1
        assert failed == 3


# ---------------------------------------------------------------------------
# versioned hot swap
# ---------------------------------------------------------------------------
class TestHotSwap:
    def test_registry_requires_monotonic_versions(self):
        registry = ModelRegistry("ml.serving[t-monotonic]")
        registry.swap(3, object())
        with pytest.raises(ValueError, match="advance"):
            registry.swap(3, object())
        with pytest.raises(ValueError, match="advance"):
            registry.swap(2, object())

    def test_publish_servable_versions_and_refuses_overwrite(self, tmp_path):
        model, _ = _fit_lr()
        d = str(tmp_path / "pub")
        p1 = publish_servable(model, d)
        p2 = publish_servable(model, d)
        assert os.path.basename(p1) == "v-1" and os.path.basename(p2) == "v-2"
        with pytest.raises(FileExistsError):
            publish_servable(model, d, version=2)
        # published dirs are loadable servables
        assert load_servable(p1) is not None

    def test_poller_skips_corrupt_and_falls_back_to_newest_intact(self, tmp_path):
        model, X = _fit_lr()
        d = str(tmp_path / "models")
        publish_servable(model, d)  # v-1, intact
        # v-2: present, marker exists, but unloadable (truncated metadata)
        os.makedirs(os.path.join(d, "v-2"))
        with open(os.path.join(d, "v-2", "metadata"), "w") as f:
            f.write("{not json")
        # noise the scan must ignore
        os.makedirs(os.path.join(d, "v-3.tmp"))
        os.makedirs(os.path.join(d, "v-9.corrupt"))
        registry = ModelRegistry("ml.serving[t-fallback]")
        poller = ModelVersionPoller(d, registry, interval_ms=10)
        assert poller.poll_once() == 1  # v-2 rejected, fell back to v-1
        assert registry.version == 1
        assert set(poller.failed) == {2}
        assert metrics.get(registry.scope, MLMetrics.SERVING_SWAP_FAILURES) == 1
        # a newer intact publish still swaps in
        publish_servable(model, d, version=4)
        assert poller.poll_once() == 4
        assert registry.version == 4

    def test_serving_swap_fault_point_falls_back(self, tmp_path):
        """An injected load failure (the 'serving.swap' seam) must leave the
        in-service model untouched and fall back to an older intact version."""
        model, _ = _fit_lr()
        d = str(tmp_path / "models")
        publish_servable(model, d)  # v-1
        publish_servable(model, d)  # v-2
        registry = ModelRegistry("ml.serving[t-fault]")
        poller = ModelVersionPoller(d, registry, interval_ms=10)
        faults.reset()
        try:
            faults.arm("serving.swap", at=1)
            assert poller.poll_once() == 1  # v-2 load injected to fail → v-1
            assert registry.version == 1
            assert 2 in poller.failed and isinstance(poller.failed[2], InjectedFault)
        finally:
            faults.reset()

    def test_swap_requires_loaded_model_data(self):
        """A half-loaded servable (params but no model data) must fail closed
        at warmup — before it ever becomes the serving version."""
        from flink_ml_tpu.servable import LogisticRegressionModelServable

        model, X = _fit_lr()
        with InferenceServer(_servable(model), name="t-halfload",
                             warmup_template=_row(X, 0)) as server:
            empty = LogisticRegressionModelServable()  # no set_model_data
            with pytest.raises(RuntimeError, match="set_model_data"):
                server.swap(2, empty)
            assert server.model_version == 1  # still serving v1
            assert server.predict(_row(X, 0)).model_version == 1


# ---------------------------------------------------------------------------
# poller scan-failure backoff (fleet satellite: a replica must not hammer a
# dead publish dir, and its backoff posture must be visible from /healthz)
# ---------------------------------------------------------------------------
class TestPollerScanBackoff:
    def test_consecutive_errors_back_off_exponentially_capped(self, tmp_path):
        registry = ModelRegistry("ml.serving[t-backoff]")
        poller = ModelVersionPoller(
            str(tmp_path), registry, interval_ms=10, backoff_max_ms=35, backoff_seed=3
        )
        assert poller.backoff_state()["backing_off"] is False
        waits = []
        for _ in range(5):
            poller._note_scan_error()
            waits.append(poller.backoff_state()["next_wait_s"])
        # jittered-exponential: each wait in [base, min(1.5*base, cap)]
        for i, w in enumerate(waits):
            base = min(0.010 * 2**i, 0.035)
            assert base <= w <= 0.035 + 1e-9
        assert waits[-1] == pytest.approx(0.035)  # pinned at the cap
        state = poller.backoff_state()
        assert state["consecutive_errors"] == 5 and state["backing_off"] is True
        poller._note_scan_ok()  # one clean scan resets fully
        state = poller.backoff_state()
        assert state["consecutive_errors"] == 0
        assert state["next_wait_s"] == pytest.approx(0.010)

    def test_loop_backs_off_on_scan_errors_and_recovers(self, tmp_path):
        registry = ModelRegistry("ml.serving[t-backoff-loop]")
        poller = ModelVersionPoller(str(tmp_path), registry, interval_ms=1)
        healthy_poll = poller.poll_once

        def broken_poll():
            raise OSError("publish dir unreadable")

        poller.poll_once = broken_poll
        errors_before = metrics.get(registry.scope, MLMetrics.SERVING_POLL_ERRORS, 0)
        poller.start()
        try:
            deadline = time.monotonic() + 5.0
            while (
                poller.backoff_state()["consecutive_errors"] < 3
                and time.monotonic() < deadline
            ):
                time.sleep(0.005)
            assert poller.backoff_state()["consecutive_errors"] >= 3
            assert metrics.get(registry.scope, MLMetrics.SERVING_POLL_ERRORS, 0) > errors_before
            poller.poll_once = healthy_poll  # the dir comes back
            deadline = time.monotonic() + 5.0
            while (
                poller.backoff_state()["backing_off"]
                and time.monotonic() < deadline
            ):
                time.sleep(0.005)
            assert poller.backoff_state()["backing_off"] is False
        finally:
            poller.stop()

    def test_backoff_state_surfaces_in_healthz_payload(self, tmp_path):
        with InferenceServer(_SlowEcho(), name="t-backoff-hz") as server:
            ok, payload = server.health()
            assert payload["poller"] is None  # no poller attached yet
            poller = server.attach_poller(str(tmp_path), interval_ms=5, start=False)
            poller._note_scan_error()
            ok, payload = server.health()
            assert payload["poller"]["consecutive_errors"] == 1
            assert payload["poller"]["backing_off"] is True


# ---------------------------------------------------------------------------
# the soak: concurrent traffic + hot swap mid-run
# ---------------------------------------------------------------------------
class TestConcurrentSoak:
    N_THREADS = 8
    REQUESTS_PER_THREAD = 40

    def test_soak_with_hot_swap_mid_traffic(self, tmp_path):
        m1, X = _fit_lr(max_iter=8)
        m2, _ = _fit_lr(max_iter=30)
        assert not np.array_equal(m1.coefficient, m2.coefficient)
        d = str(tmp_path / "models")
        publish_servable(m1, d)  # v-1
        cfg = ServingConfig(
            max_batch_size=16, max_delay_ms=2, queue_capacity_rows=4096,
            default_timeout_ms=60_000,
        )
        server = InferenceServer(name="t-soak", serving_config=cfg,
                                 warmup_template=_row(X, 0))
        poller = server.attach_poller(d, interval_ms=5, start=False)
        assert poller.poll_once() == 1
        servables = {1: load_servable(os.path.join(d, "v-1"))}

        responses = {}  # (thread, i) -> ServingResponse
        errors = []
        started = threading.Barrier(self.N_THREADS + 1)

        def client(tid):
            try:
                started.wait()
                for i in range(self.REQUESTS_PER_THREAD):
                    j = (tid * 37 + i * 13) % X.shape[0]
                    responses[(tid, i)] = (j, server.predict(_row(X, j)))
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=client, args=(t,)) for t in range(self.N_THREADS)]
        for t in threads:
            t.start()
        started.wait()
        # hot swap mid-run: publish v2 while the 8 threads hammer the server.
        # The fused fast path both serves faster and AOT-compiles the incoming
        # version at swap, so guarantee v1/v2 traffic structurally instead of
        # by sleep: swap after some v1 responses exist, then drive a few
        # requests from this thread strictly after the flip.
        deadline = time.perf_counter() + 30.0
        while len(responses) < self.N_THREADS and time.perf_counter() < deadline:
            time.sleep(0.001)
        publish_servable(m2, d)  # v-2
        assert poller.poll_once() == 2
        servables[2] = load_servable(os.path.join(d, "v-2"))
        for k in range(4):  # post-swap traffic: must all be v2
            j = (k * 29) % X.shape[0]
            responses[("post-swap", k)] = (j, server.predict(_row(X, j)))
            assert responses[("post-swap", k)][1].model_version == 2
        for t in threads:
            t.join()
        server.close()

        assert not errors, errors
        # exactly one response per request — nothing lost, nothing duplicated
        assert len(responses) == self.N_THREADS * self.REQUESTS_PER_THREAD + 4
        versions = {r.model_version for _, r in responses.values()}
        assert versions == {1, 2}, f"expected traffic on both versions, saw {versions}"
        # per-thread version monotonicity: the swap is one-way
        for tid in range(self.N_THREADS):
            seen = [responses[(tid, i)][1].model_version
                    for i in range(self.REQUESTS_PER_THREAD)]
            assert seen == sorted(seen)
        # every response is bit-identical to the serving version's transform
        # at the response's bucket shape — no half-loaded, no mixed versions
        for j, resp in responses.values():
            ref = servables[resp.model_version].transform(pad_to(_row(X, j), resp.bucket))
            np.testing.assert_array_equal(
                resp.dataframe["rawPrediction"], ref.take([0])["rawPrediction"]
            )
            np.testing.assert_array_equal(
                resp.dataframe["prediction"], ref.take([0])["prediction"]
            )
            # and the hard decision agrees with the plain unbatched transform
            np.testing.assert_array_equal(
                resp.dataframe["prediction"],
                servables[resp.model_version].transform(_row(X, j))["prediction"],
            )
        # the version gauge advanced and is scrapeable like any online model's
        assert metrics.get(server.scope, MLMetrics.VERSION) == 2
        assert metrics.get(server.scope, MLMetrics.SERVING_SWAPS) == 2


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------
class TestServingMetrics:
    def test_histogram_quantiles(self):
        h = Histogram(window=100)
        assert h.quantile(0.5) is None
        for v in range(1, 101):
            h.observe(float(v))
        assert h.count == 100
        assert h.quantile(0.0) == 1.0
        assert h.quantile(1.0) == 100.0
        assert 45.0 <= h.quantile(0.5) <= 55.0
        assert h.quantile(0.99) >= 99.0
        with pytest.raises(ValueError):
            h.quantile(1.5)

    def test_histogram_window_drops_oldest(self):
        h = Histogram(window=4)
        for v in (1.0, 2.0, 3.0, 4.0, 100.0):
            h.observe(v)
        assert h.count == 5  # lifetime count
        assert sorted(h.values()) == [2.0, 3.0, 4.0, 100.0]

    def test_serving_scope_is_scrapeable(self):
        model, X = _fit_lr()
        cfg = ServingConfig(max_batch_size=8, max_delay_ms=1, queue_capacity_rows=64)
        with InferenceServer(_servable(model), name="t-metrics", serving_config=cfg) as server:
            for i in range(12):
                server.predict(_row(X, i))
            scraped = metrics.scope(server.scope)
        assert scraped[MLMetrics.SERVING_REQUESTS] == 12
        assert scraped[MLMetrics.SERVING_QUEUE_DEPTH] == 0
        assert scraped[MLMetrics.SERVING_BATCHES] >= 1
        assert scraped[MLMetrics.VERSION] == 1
        lat = scraped[MLMetrics.SERVING_LATENCY_MS]
        assert isinstance(lat, Histogram) and lat.count == 12
        assert scraped[MLMetrics.SERVING_LATENCY_P50_MS] > 0
        assert scraped[MLMetrics.SERVING_LATENCY_P99_MS] >= scraped[MLMetrics.SERVING_LATENCY_P50_MS]
        sizes = scraped[MLMetrics.SERVING_BATCH_SIZE]
        assert isinstance(sizes, Histogram) and sum(sizes.values()) == 12


class TestLocksetRegressions:
    """graftcheck v3 shared-state-guard regressions: the registry snapshot,
    the poller's failed-version map, and the warmup template all moved onto
    consistent locksets — these tests pin the observable contracts."""

    def test_registry_snapshot_pairs_version_and_servable_under_swaps(self):
        registry = ModelRegistry("ml.serving[t-lockset]")
        registry.swap(1, "servable-1")
        stop = threading.Event()

        def swapper():
            version = 2
            while not stop.is_set() and version < 400:
                registry.swap(version, f"servable-{version}")
                version += 1

        thread = threading.Thread(target=swapper, daemon=True)
        thread.start()
        try:
            for _ in range(1000):
                version, servable = registry.current()  # one locked snapshot
                assert servable == f"servable-{version}"
                v = registry.version
                assert v is not None and v >= 1
        finally:
            stop.set()
            thread.join(timeout=10.0)

    def test_poller_failed_map_is_lock_guarded_and_skips_known_bad(self, tmp_path):
        registry = ModelRegistry("ml.serving[t-failedmap]")
        poller = ModelVersionPoller(
            str(tmp_path), registry, loader=lambda path: object(), interval_ms=5.0
        )
        err = RuntimeError("bad version")
        poller._record_failed(7, err)
        assert poller.known_failed(7)
        assert not poller.known_failed(8)
        assert poller.failed[7] is err  # introspection surface unchanged

    def test_warmup_template_is_set_once_and_never_overwritten(self):
        X = np.arange(8 * DIM, dtype=np.float64).reshape(8, DIM)
        server = InferenceServer(_SlowEcho(), name="t-template-once")
        try:
            first = _row(X, 3)
            server._remember_template(first)
            again = _row(X, 5)
            server._remember_template(again)  # must not replace the first
            with server._template_lock:
                template = server._warmup_template
            assert template is not None and len(template) == 1
            np.testing.assert_array_equal(
                np.asarray(template["features"]), np.asarray(first["features"])
            )
        finally:
            server.close()
