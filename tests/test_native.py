"""Native (C++) chunk-store tests: build, spill behavior, parity with the Python
HostDataCache, and on-disk snapshot interchange."""
import numpy as np
import pytest

from flink_ml_tpu.native import NativeChunkStore, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain available"
)


def test_chunk_store_round_trip(tmp_path):
    store = NativeChunkStore(memory_budget_bytes=1 << 20, spill_dir=str(tmp_path))
    payloads = [bytes([i]) * (100 + i) for i in range(5)]
    for p in payloads:
        store.append(p)
    assert len(store) == 5
    for i, p in enumerate(payloads):
        assert store.read(i) == p
    assert store.spilled_chunks == 0
    store.close()


def test_chunk_store_spills_over_budget(tmp_path):
    store = NativeChunkStore(memory_budget_bytes=300, spill_dir=str(tmp_path / "spill"))
    big = b"x" * 200
    store.append(big)  # resident (200 <= 300)
    store.append(big)  # over budget → spilled
    store.append(b"y" * 50)  # fits again (200 + 50 <= 300)
    assert store.spilled_chunks == 1
    assert store.memory_bytes == 250
    # spilled chunk reads back identically, order preserved
    assert store.read(0) == big and store.read(1) == big and store.read(2) == b"y" * 50
    store.close()


def test_chunk_store_out_of_range(tmp_path):
    store = NativeChunkStore(1 << 20)
    store.append(b"abc")
    with pytest.raises(IndexError):
        store.read(7)
    store.close()


def test_native_cache_matches_python_cache(tmp_path):
    from flink_ml_tpu.iteration.datacache import HostDataCache
    from flink_ml_tpu.native.cache import NativeDataCache

    rng = np.random.default_rng(0)
    chunks = [
        {"x": rng.normal(size=(7, 3)), "y": rng.integers(0, 5, 7)} for _ in range(4)
    ]
    native = NativeDataCache(memory_budget_bytes=500, spill_dir=str(tmp_path / "n"))
    python = HostDataCache(memory_budget_bytes=500, spill_dir=str(tmp_path / "p"))
    for c in chunks:
        native.append(c)
        python.append(c)
    native.finish()
    python.finish()
    assert native.num_rows == python.num_rows == 28
    assert native.spilled_chunks > 0  # budget forces the native tier to spill
    for nb, pb in zip(native.iter_minibatches(10), python.iter_minibatches(10)):
        np.testing.assert_array_equal(nb["x"], pb["x"])
        np.testing.assert_array_equal(nb["y"], pb["y"])
    native.close()


def test_native_snapshot_interchanges_with_python(tmp_path):
    """A native snapshot restores into the Python cache and vice versa."""
    from flink_ml_tpu.iteration.datacache import HostDataCache
    from flink_ml_tpu.native.cache import NativeDataCache

    native = NativeDataCache()
    native.append({"x": np.arange(6.0)})
    native.finish()
    snap = str(tmp_path / "snap")
    native.snapshot(snap)
    recovered = HostDataCache.recover(snap)
    np.testing.assert_array_equal(
        next(recovered.iter_minibatches(6))["x"], np.arange(6.0)
    )
    snap2 = str(tmp_path / "snap2")
    recovered.snapshot(snap2)
    native2 = NativeDataCache.recover(snap2)
    np.testing.assert_array_equal(
        next(native2.iter_minibatches(6))["x"], np.arange(6.0)
    )
    native.close()
    native2.close()
