"""Streamed (larger-than-HBM) sparse SGD on the one-hot matmul kernel.

The north-star combination (BASELINE.json): Criteo-shape sparse LR streamed
from a host-tier cache, running the fast one-hot kernel instead of serialized
scatter/gather. The contract: a global ``OneHotSparsePlan`` built from one
counting pass serves every window with ONE compiled program, and the result
matches both the resident one-hot path and the streamed scatter path.
"""
import numpy as np
import pytest

from flink_ml_tpu.iteration import DeviceDataCache, HostDataCache
from flink_ml_tpu.ops import SGD, BinaryLogisticLoss
from flink_ml_tpu.parallel.mesh import MeshContext, mesh_context


def _sparse_data(n, d, K, seed=0):
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, d, size=(n, K)).astype(np.int32)
    val = rng.normal(size=(n, K)).astype(np.float32)
    val[rng.random((n, K)) < 0.15] = 0.0  # padding slots
    y = (rng.random(n) > 0.5).astype(np.float32)
    return {"indices": idx, "values": val, "labels": y}


def _fill(cache, cols, chunk=40):
    n = len(cols["labels"])
    for a in range(0, n, chunk):
        cache.append({k: v[a : a + chunk] for k, v in cols.items()})
    cache.finish()
    return cache


KW = dict(max_iter=12, global_batch_size=128, tol=0.0, learning_rate=0.3)


def test_streamed_onehot_matches_streamed_scatter(tmp_path):
    cols = _sparse_data(512, 2000, 6, seed=1)
    cache = _fill(
        HostDataCache(memory_budget_bytes=2000, spill_dir=str(tmp_path)), cols
    )
    assert any("files" in e for e in cache._log), "budget should force spill"
    coefs, hists = {}, {}
    for kernel in ("onehot", "scatter"):
        sgd = SGD(stream_window_rows=32, sparse_kernel=kernel, **KW)
        coefs[kernel] = sgd.optimize(
            np.zeros(2000, np.float32), cache, BinaryLogisticLoss.INSTANCE
        )
        hists[kernel] = sgd.loss_history
    np.testing.assert_allclose(coefs["onehot"], coefs["scatter"], rtol=1e-3, atol=1e-5)
    assert len(hists["onehot"]) == len(hists["scatter"]) == KW["max_iter"]
    np.testing.assert_allclose(hists["onehot"], hists["scatter"], rtol=1e-3)


def test_streamed_onehot_matches_resident_onehot():
    # 512 rows / 8 devices -> m=64; local batch 16 divides m evenly, so the
    # streamed epochs consume exactly the resident rows and weights.
    cols = _sparse_data(512, 2000, 6, seed=2)
    resident = SGD(sparse_kernel="onehot", **KW)
    want = resident.optimize(
        np.zeros(2000, np.float32), dict(cols), BinaryLogisticLoss.INSTANCE
    )
    cache = _fill(HostDataCache(), cols)
    streamed = SGD(stream_window_rows=32, sparse_kernel="onehot", **KW)
    got = streamed.optimize(
        np.zeros(2000, np.float32), cache, BinaryLogisticLoss.INSTANCE
    )
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        streamed.loss_history, resident.loss_history, rtol=1e-4
    )


def test_streamed_onehot_ragged_tail_matches_scatter(tmp_path):
    # 400 rows -> m=50 per shard with global padding; batch 16 does not
    # divide evenly, exercising the masked short-tail epochs.
    cols = _sparse_data(400, 1500, 5, seed=3)
    cache = _fill(
        HostDataCache(memory_budget_bytes=1500, spill_dir=str(tmp_path)), cols
    )
    coefs = {}
    for kernel in ("onehot", "scatter"):
        coefs[kernel] = SGD(
            stream_window_rows=20, sparse_kernel=kernel, **KW
        ).optimize(np.zeros(1500, np.float32), cache, BinaryLogisticLoss.INSTANCE)
    np.testing.assert_allclose(coefs["onehot"], coefs["scatter"], rtol=1e-3, atol=1e-5)


def test_streamed_onehot_tol_stops_like_scatter():
    cols = _sparse_data(512, 2000, 6, seed=4)
    cache = _fill(HostDataCache(), cols)
    hists = {}
    for kernel in ("onehot", "scatter"):
        sgd = SGD(
            stream_window_rows=32, sparse_kernel=kernel,
            max_iter=300, global_batch_size=512, tol=0.5, learning_rate=0.5,
        )
        sgd.optimize(np.zeros(2000, np.float32), cache, BinaryLogisticLoss.INSTANCE)
        hists[kernel] = sgd.loss_history
    assert len(hists["onehot"]) < 300, "tol should stop early"
    assert len(hists["onehot"]) == len(hists["scatter"])
    np.testing.assert_allclose(hists["onehot"], hists["scatter"], rtol=1e-3)


def test_streamed_onehot_checkpoint_resume(tmp_path):
    from flink_ml_tpu.checkpoint import CheckpointManager

    cols = _sparse_data(512, 2000, 6, seed=5)
    cache = _fill(HostDataCache(), cols)
    want = SGD(stream_window_rows=32, sparse_kernel="onehot", **KW).optimize(
        np.zeros(2000, np.float32), cache, BinaryLogisticLoss.INSTANCE
    )

    ckdir = str(tmp_path / "ck")
    got = SGD(
        stream_window_rows=32, sparse_kernel="onehot",
        checkpoint_manager=CheckpointManager(ckdir), checkpoint_interval=2, **KW
    ).optimize(np.zeros(2000, np.float32), cache, BinaryLogisticLoss.INSTANCE)
    np.testing.assert_array_equal(got, want)

    mgr = CheckpointManager(ckdir)
    steps = mgr.all_steps()
    assert len(steps) >= 2, "expected multiple checkpoints"
    import shutil

    shutil.rmtree(f"{ckdir}/ckpt-{steps[-1]}")
    resumed = SGD(
        stream_window_rows=32, sparse_kernel="onehot",
        checkpoint_manager=CheckpointManager(ckdir), checkpoint_interval=2, **KW
    ).optimize(np.zeros(2000, np.float32), cache, BinaryLogisticLoss.INSTANCE)
    np.testing.assert_array_equal(resumed, want)


def test_streamed_auto_picks_onehot_for_wide_models(monkeypatch):
    import flink_ml_tpu.ops.optimizer as om

    calls = []
    orig = om.SGD._optimize_streaming_onehot

    def spy(self, *a, **k):
        calls.append(1)
        return orig(self, *a, **k)

    monkeypatch.setattr(om.SGD, "_optimize_streaming_onehot", spy)
    n, d, K = 2048, 1 << 15, 32  # n*K = 2^16, d >= 2^14
    cols = _sparse_data(n, d, K, seed=6)
    cache = _fill(HostDataCache(), cols, chunk=256)
    coef = SGD(stream_window_rows=256, max_iter=3, global_batch_size=512, tol=0.0).optimize(
        np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE
    )
    assert calls, "auto should engage the one-hot kernel on the streamed path"
    assert np.all(np.isfinite(coef))


def test_streamed_auto_narrow_stays_on_scatter(monkeypatch):
    import flink_ml_tpu.ops.optimizer as om

    calls = []
    monkeypatch.setattr(
        om.SGD, "_optimize_streaming_onehot",
        lambda self, *a, **k: calls.append(1) or None,
    )
    cols = _sparse_data(256, 500, 4, seed=7)  # narrow: scatter territory
    cache = _fill(HostDataCache(), cols)
    SGD(stream_window_rows=16, max_iter=2, global_batch_size=64, tol=0.0).optimize(
        np.zeros(500, np.float32), cache, BinaryLogisticLoss.INSTANCE
    )
    assert not calls


def test_streamed_auto_falls_back_when_stacks_exceed_hbm(monkeypatch):
    import flink_ml_tpu.ops.optimizer as om

    monkeypatch.setattr(om, "_hbm_bytes_limit", lambda ctx=None: 1 << 16)
    n, d, K = 2048, 1 << 15, 32
    cols = _sparse_data(n, d, K, seed=8)
    cache = _fill(HostDataCache(), cols, chunk=256)
    coef = SGD(stream_window_rows=256, max_iter=2, global_batch_size=512, tol=0.0).optimize(
        np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE
    )
    assert np.all(np.isfinite(coef))  # scatter fallback trained


def test_forced_streamed_onehot_infeasible_raises():
    cols = _sparse_data(256, 500, 4, seed=9)
    cache = _fill(HostDataCache(), cols)
    # f64 fit: the MXU split-bf16 crossings reconstruct f32, not f64
    with pytest.raises(ValueError, match="f32"):
        SGD(
            stream_window_rows=16, sparse_kernel="onehot", dtype=np.float64, **KW
        ).optimize(np.zeros(500, np.float64), cache, BinaryLogisticLoss.INSTANCE)


def test_streamed_onehot_tp_matches_streamed_scatter_tp():
    # The full composition: streamed + one-hot + tensor parallelism on a
    # (4 data x 2 model) mesh, vs the streamed scatter-TP path.
    cols = _sparse_data(512, 2000, 6, seed=10)
    cache = _fill(HostDataCache(), cols)
    with mesh_context(MeshContext(n_data=4, n_model=2)) as ctx:
        coefs = {}
        for kernel in ("onehot", "scatter"):
            coefs[kernel] = SGD(
                stream_window_rows=32, sparse_kernel=kernel, ctx=ctx, **KW
            ).optimize(np.zeros(2000, np.float32), cache, BinaryLogisticLoss.INSTANCE)
        np.testing.assert_allclose(
            coefs["onehot"], coefs["scatter"], rtol=1e-3, atol=1e-5
        )


def test_streamed_onehot_multislice_matches_streamed_scatter():
    # Round-5 composition (VERDICT r4 missing #3), streamed flavor: the
    # streamed one-hot kernel on a (2 slices x 4 chips) mesh vs the streamed
    # scatter path on the same mesh — the window stacks stay intra-slice and
    # only the gradient psum crosses DCN.
    import jax

    cols = _sparse_data(512, 2000, 6, seed=11)
    cache = _fill(HostDataCache(), cols)
    with mesh_context(
        MeshContext(devices=jax.devices()[:8], n_data=4, n_model=1, n_slices=2)
    ) as ctx:
        coefs = {}
        for kernel in ("onehot", "scatter"):
            coefs[kernel] = SGD(
                stream_window_rows=32, sparse_kernel=kernel, ctx=ctx, **KW
            ).optimize(np.zeros(2000, np.float32), cache, BinaryLogisticLoss.INSTANCE)
        np.testing.assert_allclose(
            coefs["onehot"], coefs["scatter"], rtol=1e-3, atol=1e-5
        )
