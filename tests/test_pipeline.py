"""Pipeline/PipelineModel contract tests, mirroring PipelineTest.java
(flink-ml-core/src/test/.../api/PipelineTest.java)."""
import numpy as np

from flink_ml_tpu.api import DataFrame
from flink_ml_tpu.builder import Pipeline, PipelineModel
from flink_ml_tpu.utils import read_write as rw

from tests.example_stages import DoubleTransformer, SumEstimator, SumModel


def data(values):
    return DataFrame.from_dict({"input": np.asarray(values, dtype=np.float64)})


class TestPipeline:
    def test_fit_chains_stages(self):
        # Ref PipelineTest: estimator trained on previous stage's transformed output.
        pipeline = Pipeline([DoubleTransformer(), SumEstimator()])
        model = pipeline.fit(data([1.0, 2.0, 3.0]))
        assert isinstance(model, PipelineModel)
        # doubled: [2,4,6]; SumEstimator delta = 12
        sum_model = model.stages[1]
        assert isinstance(sum_model, SumModel)
        assert sum_model.delta == 12.0

    def test_model_transform_chains(self):
        model = PipelineModel([DoubleTransformer(), SumModel(delta=10.0)])
        out = model.transform(data([1.0, 2.0]))
        assert out.scalars("input").tolist() == [12.0, 14.0]

    def test_pipeline_with_trailing_estimator_output(self):
        pipeline = Pipeline([SumEstimator()])
        model = pipeline.fit(data([1.0, 2.0]))
        out = model.transform(data([0.0]))
        assert out.scalars("input").tolist() == [3.0]

    def test_save_load_roundtrip(self, tmp_path):
        model = PipelineModel([DoubleTransformer(), SumModel(delta=5.0)])
        p = str(tmp_path / "pm")
        model.save(p)
        loaded = PipelineModel.load(p)
        out = loaded.transform(data([1.0]))
        assert out.scalars("input").tolist() == [7.0]

    def test_pipeline_save_load(self, tmp_path):
        pipeline = Pipeline([DoubleTransformer(), SumEstimator()])
        p = str(tmp_path / "pl")
        pipeline.save(p)
        loaded = Pipeline.load(p)
        assert len(loaded.stages) == 2
        model = loaded.fit(data([1.0, 2.0, 3.0]))
        assert model.stages[1].delta == 12.0

    def test_generic_load_stage_dispatch(self, tmp_path):
        # Ref ReadWriteUtils.loadStage:268 className dispatch.
        m = SumModel(delta=3.0)
        p = str(tmp_path / "m")
        m.save(p)
        loaded = rw.load_stage(p)
        assert isinstance(loaded, SumModel)
        assert loaded.delta == 3.0

    def test_get_set_model_data(self):
        model = PipelineModel([DoubleTransformer(), SumModel(delta=5.0)])
        md = model.get_model_data()
        assert len(md) == 1
        model2 = PipelineModel([DoubleTransformer(), SumModel(delta=0.0)])
        model2.set_model_data(*md)
        assert model2.stages[1].delta == 5.0

    def test_double_save_rejected(self, tmp_path):
        m = SumModel(delta=1.0)
        p = str(tmp_path / "m")
        m.save(p)
        try:
            m.save(p)
            assert False, "expected IOError"
        except IOError:
            pass
