"""Graph API tests — parity with GraphTest semantics (SURVEY.md §4 API contract
tests): DAG wiring, fit/transform execution order, model-data wiring, save/load."""
import numpy as np

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.builder.graph import Graph, GraphBuilder, GraphModel
from flink_ml_tpu.models.classification.logistic_regression import LogisticRegression
from flink_ml_tpu.models.feature.standard_scaler import StandardScaler
from flink_ml_tpu.models.feature.sql_transformer import SQLTransformer

RNG = np.random.default_rng(66)


def _data(n=128, d=3):
    X = RNG.normal(size=(n, d))
    y = (X @ np.arange(1.0, d + 1.0) > 0).astype(np.float64)
    return DataFrame.from_dict({"features": X, "label": y}), y


def test_graph_chained_estimators():
    """scaler -> LR built as one Estimator via GraphBuilder (buildEstimator:286)."""
    builder = GraphBuilder()
    source = builder.create_table_id()
    scaler = StandardScaler().set_input_col("features").set_output_col("features")
    scaled = builder.add_estimator(scaler, source)
    lr = LogisticRegression().set_max_iter(30).set_global_batch_size(128)
    predicted = builder.add_estimator(lr, scaled[0])
    graph = builder.build_estimator([source], predicted[:1])

    df, y = _data()
    model = graph.fit(df)
    assert isinstance(model, GraphModel)
    out = model.transform(df)
    assert (out["prediction"] == y).mean() > 0.9


def test_graph_algo_operator_dag():
    """Pure transform DAG (buildAlgoOperator:359) with a fan-out node."""
    builder = GraphBuilder()
    source = builder.create_table_id()
    double_it = SQLTransformer().set_statement("SELECT v * 2 AS v FROM __THIS__")
    add_one = SQLTransformer().set_statement("SELECT v + 1 AS v FROM __THIS__")
    mid = builder.add_algo_operator(double_it, source)
    out_id = builder.add_algo_operator(add_one, mid[0])
    dag = builder.build_algo_operator([source], out_id[:1])
    df = DataFrame.from_dict({"v": np.asarray([1.0, 2.0])})
    out = dag.transform(df)
    np.testing.assert_array_equal(out["v"], [3.0, 5.0])


def test_graph_model_data_wiring():
    """getModelDataFromEstimator → setModelDataOnModel across the DAG."""
    builder = GraphBuilder()
    source = builder.create_table_id()
    lr = LogisticRegression().set_max_iter(10)
    predicted = builder.add_estimator(lr, source)
    model_data = builder.get_model_data_from_estimator(lr)
    graph = builder.build_estimator([source], predicted[:1] + model_data)
    df, y = _data(64)
    model = graph.fit(df)
    pred_df, md_df = model.transform(df)
    assert "coefficient" in md_df.get_column_names()


def test_graph_save_load(tmp_path):
    builder = GraphBuilder()
    source = builder.create_table_id()
    scaler = StandardScaler().set_input_col("features").set_output_col("features")
    scaled = builder.add_estimator(scaler, source)
    lr = LogisticRegression().set_max_iter(20).set_global_batch_size(64)
    predicted = builder.add_estimator(lr, scaled[0])
    graph = builder.build_estimator([source], predicted[:1])

    path = str(tmp_path / "graph")
    graph.save(path)
    loaded = Graph.load(path)
    df, y = _data(64)
    out = loaded.fit(df).transform(df)
    assert (out["prediction"] == y).mean() > 0.85


def test_graph_model_save_load(tmp_path):
    builder = GraphBuilder()
    source = builder.create_table_id()
    lr = LogisticRegression().set_max_iter(20).set_global_batch_size(64)
    predicted = builder.add_estimator(lr, source)
    graph = builder.build_estimator([source], predicted[:1])
    df, y = _data(64)
    model = graph.fit(df)
    out1 = model.transform(df)
    path = str(tmp_path / "gm")
    model.save(path)
    loaded = GraphModel.load(path)
    out2 = loaded.transform(df)
    np.testing.assert_array_equal(out1["prediction"], out2["prediction"])


def test_graph_duplicate_stage_rejected():
    import pytest
    from flink_ml_tpu.models.feature.sql_transformer import SQLTransformer

    builder = GraphBuilder()
    t = builder.create_table_id()
    op = SQLTransformer().set_statement("SELECT * FROM __THIS__")
    builder.add_algo_operator(op, t)
    with pytest.raises(ValueError, match="already been added"):
        builder.add_algo_operator(op, t)


def test_graph_multi_output_stage():
    """Multi-output stages get enough TableIds (maxOutputTableNum allocation)."""
    from flink_ml_tpu.models.clustering.agglomerative_clustering import AgglomerativeClustering

    builder = GraphBuilder()
    t = builder.create_table_id()
    outs = builder.add_algo_operator(AgglomerativeClustering().set_linkage("single"), t)
    dag = builder.build_algo_operator([t], outs[:2])
    pts = np.concatenate([RNG.normal(0, 0.1, (8, 2)), RNG.normal(5, 0.1, (8, 2))])
    clustered, merges = dag.transform(DataFrame.from_dict({"features": pts}))
    assert len(set(clustered["prediction"])) == 2
    assert "distance" in merges.get_column_names()


def test_stage_cannot_be_added_twice():
    import pytest

    builder = GraphBuilder()
    scaler = StandardScaler().set_input_col("features").set_output_col("scaled")
    inp = builder.create_table_id()
    builder.add_estimator(scaler, inp)
    with pytest.raises(Exception, match="already been added"):
        builder.add_estimator(scaler, inp)


def test_graph_model_data_roundtrip_through_save(tmp_path):
    """A graph that extracts model data from a fitted estimator and feeds it to
    a downstream model must survive save/load with identical predictions
    (GraphBuilder.getModelDataFromEstimator / setModelDataOnModel wiring)."""
    from flink_ml_tpu.models.classification.logistic_regression import (
        LogisticRegressionModel,
    )
    from flink_ml_tpu.utils.read_write import load_stage

    df, y = _data()
    builder = GraphBuilder()
    inp = builder.create_table_id()
    lr = LogisticRegression().set_max_iter(20).set_tol(0.0)
    builder.add_estimator(lr, inp)
    model_data = builder.get_model_data_from_estimator(lr)
    serving = LogisticRegressionModel()
    served = builder.add_algo_operator(serving, inp)
    builder.set_model_data_on_model(serving, *model_data)
    graph = builder.build_estimator([inp], served[:1])

    fitted = graph.fit(df)
    out = fitted.transform(df)
    acc = float(np.mean(out["prediction"] == y))
    assert acc > 0.9

    path = str(tmp_path / "g")
    fitted.save(path)
    reloaded = load_stage(path)
    again = reloaded.transform(df)
    np.testing.assert_array_equal(again["prediction"], out["prediction"])


def test_diamond_dag_joins_two_branches():
    """A true diamond: two branches diverge from one input and rejoin at a
    two-parent join node — execution must feed the join BOTH branch outputs."""
    from flink_ml_tpu.api.core import AlgoOperator
    from flink_ml_tpu.api.types import DataTypes

    class JoinOp(AlgoOperator):
        def transform(self, *inputs):
            a, b = inputs
            out = a.clone()
            out.add_column("joined", DataTypes.DOUBLE, np.asarray(a["l1"]) + np.asarray(b["l2"]))
            return out

        def save(self, path):  # not exercised here
            raise NotImplementedError

    df, _ = _data()
    builder = GraphBuilder()
    inp = builder.create_table_id()
    left = builder.add_algo_operator(
        SQLTransformer().set_statement("SELECT *, (label + 1) AS l1 FROM __THIS__"), inp
    )
    right = builder.add_algo_operator(
        SQLTransformer().set_statement("SELECT *, (label + 2) AS l2 FROM __THIS__"), inp
    )
    joined = builder.add_algo_operator(JoinOp(), left[0], right[0])
    graph = builder.build_algo_operator([inp], joined[:1])
    out = graph.transform(df)
    np.testing.assert_array_equal(out["joined"], 2 * out["label"] + 3)
