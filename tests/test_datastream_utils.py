"""Tests for the DataStreamUtils belt (parallel/datastream_utils.py) and the
GK QuantileSummary (parallel/quantile.py), on the 8-device mesh."""
import numpy as np
import pytest

from flink_ml_tpu.api.dataframe import DataFrame
from flink_ml_tpu.parallel import (
    QuantileSummary,
    aggregate,
    co_group,
    distributed_quantiles,
    distributed_sort,
    map_partition,
    reduce,
    sample,
)

RNG = np.random.default_rng(77)


class TestQuantileSummary:
    def test_exact_below_compress_threshold(self):
        x = RNG.normal(size=2000)
        s = QuantileSummary(relative_error=0.001)
        s.insert_all(x).compress()
        for p in (0.25, 0.5, 0.75):
            # exact rank within 1 of numpy's nearest-rank quantile
            got = s.query(p)
            rank = np.searchsorted(np.sort(x), got)
            assert abs(rank - p * len(x)) <= 2

    def test_relative_error_bound_large(self):
        x = RNG.normal(size=200_000)
        eps = 0.01
        s = QuantileSummary(relative_error=eps)
        # feed in chunks like a stream
        for chunk in np.array_split(x, 7):
            s.insert_all(chunk)
        s.compress()
        xs = np.sort(x)
        for p in (0.1, 0.5, 0.9):
            got = s.query(p)
            rank = np.searchsorted(xs, got) / len(x)
            assert abs(rank - p) <= 2 * eps, (p, rank)

    def test_merge_matches_single_sketch_error(self):
        x = RNG.normal(size=50_000)
        eps = 0.01
        parts = np.array_split(x, 8)
        sketches = [QuantileSummary(eps).insert_all(part).compress() for part in parts]
        merged = sketches[0]
        for other in sketches[1:]:
            merged = merged.merge(other)
        assert merged.count == len(x)
        xs = np.sort(x)
        for p in (0.25, 0.5, 0.75):
            rank = np.searchsorted(xs, merged.query(p)) / len(x)
            assert abs(rank - p) <= 2 * eps

    def test_single_insert_and_scalar_query(self):
        s = QuantileSummary(0.001)
        for v in [5.0, 1.0, 3.0]:
            s.insert(v)
        s.compress()
        assert s.query(0.5) == 3.0
        assert s.query(0.0) == 1.0
        assert s.query(1.0) == 5.0

    def test_query_uncompressed_raises(self):
        s = QuantileSummary(0.001)
        s.insert(1.0)
        with pytest.raises(ValueError, match="compress"):
            s.query(0.5)
        with pytest.raises(ValueError, match="without any records"):
            QuantileSummary(0.001).query(0.5)

    def test_invalid_params(self):
        with pytest.raises(ValueError, match="relative error"):
            QuantileSummary(1.5)
        s = QuantileSummary(0.001).insert(1.0).compress()
        with pytest.raises(ValueError, match="range"):
            s.query(1.5)

    @pytest.mark.parametrize("n,eps,seed", [(1, 0.01, 0), (7, 0.01, 1),
                                            (5000, 0.01, 2), (60_000, 0.001, 3)])
    def test_compress_matches_scalar_scan(self, n, eps, seed):
        # The searchsorted-run compression must reproduce the reference's
        # right-to-left greedy scan tuple for tuple.
        def scalar_compress(values, g, delta, merge_threshold):
            n = len(values)
            keep = []
            head = n - 1
            head_g = int(g[head])
            for i in range(n - 2, 0, -1):
                if g[i] + head_g + delta[head] < merge_threshold:
                    head_g += int(g[i])
                else:
                    keep.append((head, head_g))
                    head, head_g = i, int(g[i])
            keep.append((head, head_g))
            keep.reverse()
            idx = np.asarray([k[0] for k in keep], np.int64)
            gs = np.asarray([k[1] for k in keep], np.int64)
            if values[0] <= values[idx[0]] and n > 1:
                idx = np.concatenate([[0], idx])
                gs = np.concatenate([[g[0]], gs])
            return values[idx], gs, delta[idx]

        rng = np.random.default_rng(seed)
        s = QuantileSummary(relative_error=eps)
        s.insert_all(rng.normal(size=n))
        s._flush_head()
        want = scalar_compress(
            s.values.copy(), s.g.copy(), s.delta.copy(),
            2.0 * s.relative_error * s.count,
        )
        s._compress_internal(2.0 * s.relative_error * s.count)
        np.testing.assert_array_equal(s.values, want[0])
        np.testing.assert_array_equal(s.g, want[1])
        np.testing.assert_array_equal(s.delta, want[2])

    @pytest.mark.parametrize("parallel", [False, True])
    def test_map_partition_parallel_matches_sequential(self, parallel):
        # The thread-pool branch must return identical per-partition results
        # in partition order, and propagate fn exceptions.
        rng = np.random.default_rng(12)
        cols = {"x": rng.normal(size=10_000), "y": rng.normal(size=10_000)}
        got = map_partition(
            cols, lambda p: (len(p["x"]), float(p["x"].sum())), parallel=parallel
        )
        want = map_partition(
            cols, lambda p: (len(p["x"]), float(p["x"].sum())), parallel=False
        )
        assert got == want
        assert sum(c for c, _ in got) == 10_000

        def boom(p):
            raise RuntimeError("partition failed")

        with pytest.raises(RuntimeError, match="partition failed"):
            map_partition(cols, boom, parallel=parallel)

    def test_map_partition_forced_parallel_runs_threads_on_one_core(self, monkeypatch):
        # The thread contract must be exercisable on a 1-core host: with
        # cpu_count pinned >1 and parallel=True, at least two partitions run
        # CONCURRENTLY (proven by a barrier only two simultaneous workers can
        # pass), matching how a reference mapPartition UDF sees concurrent
        # subtasks.
        import threading

        import flink_ml_tpu.parallel.datastream_utils as dsu

        monkeypatch.setattr(dsu.os, "cpu_count", lambda: 4)
        barrier = threading.Barrier(2, timeout=30.0)
        passed = []

        def fn(part):
            try:
                barrier.wait()
                passed.append(True)
            except threading.BrokenBarrierError:  # pragma: no cover - failure mode
                passed.append(False)
            return float(part["x"].sum())

        cols = {"x": np.arange(64.0)}
        parts = map_partition(cols, fn, parallel=True)
        assert sum(parts) == cols["x"].sum()
        assert passed and all(passed), "partitions never overlapped in time"

    def test_map_partition_forced_parallel_shared_state_synchronized(self, monkeypatch):
        # The documented contract: an fn mutating shared state must
        # synchronize. A lock-guarded accumulator through the forced-thread
        # belt lands on exactly the sequential total.
        import threading

        import flink_ml_tpu.parallel.datastream_utils as dsu

        monkeypatch.setattr(dsu.os, "cpu_count", lambda: 4)
        total = [0.0]
        lock = threading.Lock()

        def fn(part):
            s = float(part["x"].sum())
            with lock:
                total[0] += s
            return None

        cols = {"x": np.arange(10_000.0)}
        map_partition(cols, fn, parallel=True)
        assert total[0] == cols["x"].sum()

    def test_reduce_partial_stage_is_per_partition(self, monkeypatch):
        # Stage 1 must fold each partition's OWN rows (record-level fn on
        # one-row dicts), not hand whole partitions through untouched: every
        # fn input is single-row until the final cross-partition fold over
        # 8 one-row partials, and the total matches.
        import flink_ml_tpu.parallel.datastream_utils as dsu

        monkeypatch.setattr(dsu.os, "cpu_count", lambda: 4)
        seen_rows = []

        def add(a, b):
            seen_rows.append((len(a["x"]), len(b["x"])))
            return {"x": a["x"] + b["x"]}

        cols = {"x": np.arange(64.0)}
        out = reduce(cols, add, parallel=True)
        assert out["x"].shape == (1,)
        assert float(out["x"][0]) == cols["x"].sum()
        assert all(la == 1 and lb == 1 for la, lb in seen_rows)
        # 8 partitions x (8 rows - 1) partial folds + 7 final folds
        assert len(seen_rows) == 8 * 7 + 7

    def test_reduce_more_partitions_than_rows(self):
        # 3 rows over the 8-way belt: empty partitions contribute no partial.
        cols = {"x": np.asarray([1.0, 2.0, 3.0])}
        out = reduce(cols, lambda a, b: {"x": a["x"] + b["x"]})
        assert float(out["x"][0]) == 6.0

    def test_reduce_empty_input_returns_empty(self):
        cols = {"x": np.empty(0)}
        out = reduce(cols, lambda a, b: {"x": a["x"] + b["x"]})
        assert out["x"].shape == (0,)

    def test_aggregate_parallel_quantiles_match(self, monkeypatch):
        # distributed_quantiles through the FORCED-parallel belt equals the
        # forced-sequential result bit for bit: same sketches, same merge
        # order. cpu_count is monkeypatched so the thread-pool branch
        # genuinely runs even on a 1-core host.
        import flink_ml_tpu.parallel.datastream_utils as dsu

        rng = np.random.default_rng(13)
        X = rng.normal(size=(50_000, 2))
        monkeypatch.setattr(dsu.os, "cpu_count", lambda: 1)
        seq = distributed_quantiles(X, [0.25, 0.5, 0.75])
        monkeypatch.setattr(dsu.os, "cpu_count", lambda: 4)
        par = distributed_quantiles(X, [0.25, 0.5, 0.75])
        np.testing.assert_array_equal(np.asarray(seq), np.asarray(par))

    def test_ten_million_row_quantiles_within_budget(self):
        # The compression rewrite makes 10M-row sketching a few seconds of
        # host work (the scalar scan was O(rows) Python steps). Generous
        # ceiling for the shared 1-core box; the point is the complexity
        # class, not the constant.
        import time

        rng = np.random.default_rng(9)
        x = rng.normal(size=(10_000_000, 1))
        t0 = time.perf_counter()
        q = distributed_quantiles(x, [0.1, 0.5, 0.9], relative_error=0.001)
        elapsed = time.perf_counter() - t0
        assert elapsed < 60.0, f"10M-row quantiles took {elapsed:.1f}s"
        for p, got in zip((0.1, 0.5, 0.9), np.asarray(q).ravel()):
            want = np.quantile(x, p)
            assert abs(got - want) < 0.02, (p, got, want)


class TestDistributedSort:
    def test_parity_with_np_sort(self):
        keys = RNG.normal(size=10_001)
        vals = {"v": np.arange(10_001, dtype=np.float64)}
        buckets = distributed_sort(keys, vals)
        got = np.concatenate([b["__key__"] for b in buckets])
        np.testing.assert_array_equal(got, np.sort(keys))
        # values travel with their keys
        got_v = np.concatenate([b["v"] for b in buckets])
        np.testing.assert_array_equal(keys[got_v.astype(int)], got)

    def test_descending_and_ties_confined(self):
        keys = RNG.integers(0, 20, size=5000).astype(np.float64)  # heavy ties
        buckets = distributed_sort(keys, descending=True)
        got = np.concatenate([b["__key__"] for b in buckets])
        np.testing.assert_array_equal(got, np.sort(keys)[::-1])
        seen = set()
        for b in buckets:
            uniq = set(np.unique(b["__key__"]).tolist())
            assert not (uniq & seen), "tie group split across buckets"
            seen |= uniq

    def test_empty(self):
        out = distributed_sort(np.empty(0))
        assert sum(len(b["__key__"]) for b in out) == 0


class TestBeltPrimitives:
    def test_map_partition_covers_all_rows(self):
        cols = {"x": np.arange(100.0)}
        parts = map_partition(cols, lambda p: p["x"].sum())
        assert len(parts) == 8
        assert sum(parts) == cols["x"].sum()

    def test_aggregate_two_stage(self):
        cols = {"x": RNG.normal(size=1000)}
        mean = aggregate(
            cols,
            create_accumulator=lambda: (0.0, 0),
            add=lambda acc, part: (acc[0] + part["x"].sum(), acc[1] + len(part["x"])),
            merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
            get_result=lambda acc: acc[0] / acc[1],
        )
        np.testing.assert_allclose(mean, cols["x"].mean())

    def test_reduce(self):
        cols = {"x": np.arange(32.0)}
        out = reduce(cols, lambda a, b: {"x": np.concatenate([a["x"], b["x"]])})
        np.testing.assert_array_equal(np.sort(out["x"]), cols["x"])

    def test_sample_uniformity_and_determinism(self):
        cols = {"x": np.arange(100_000.0)}
        s1 = sample(cols, 1000, seed=3)
        s2 = sample(cols, 1000, seed=3)
        np.testing.assert_array_equal(s1["x"], s2["x"])
        assert len(np.unique(s1["x"])) == 1000
        # uniform: mean of sampled indices near the population mean
        assert abs(s1["x"].mean() - 50_000) < 5_000

    def test_sample_small_input_returns_all(self):
        cols = {"x": np.arange(5.0)}
        assert len(sample(cols, 10)["x"]) == 5

    def test_co_group_parity_with_dict_join(self):
        lk = RNG.integers(0, 30, size=200)
        rk = RNG.integers(10, 40, size=150)
        got = {k: (set(li.tolist()), set(ri.tolist())) for k, li, ri in co_group(lk, rk)}
        for key in np.union1d(lk, rk):
            li, ri = got[key]
            assert li == set(np.nonzero(lk == key)[0].tolist())
            assert ri == set(np.nonzero(rk == key)[0].tolist())


class TestDistributedQuantiles:
    def test_matches_numpy_on_small_input(self):
        X = RNG.normal(size=(3000, 4))
        q = distributed_quantiles(X, [0.25, 0.5, 0.75])
        # GK is exact (rank-wise) below the compress threshold; nearest-rank vs
        # numpy linear interpolation differ by at most one order statistic.
        expected = np.quantile(X, [0.25, 0.5, 0.75], axis=0)
        np.testing.assert_allclose(q, expected, atol=np.ptp(X) / 100)

    def test_rewired_robust_scaler_matches_exact(self):
        from flink_ml_tpu.models.feature.scalers import RobustScaler

        X = RNG.normal(size=(4000, 3)) * 5 + 2
        model = RobustScaler().set_input_col("input").fit(DataFrame.from_dict({"input": X}))
        exact = np.quantile(X, [0.25, 0.5, 0.75], axis=0)
        np.testing.assert_allclose(model.medians, exact[1], atol=np.ptp(X) / 200)
        np.testing.assert_allclose(model.ranges, exact[2] - exact[0], atol=np.ptp(X) / 100)

    def test_rewired_evaluator_matches_host_argsort(self):
        from flink_ml_tpu.models.evaluation.binary_classification_evaluator import (
            BinaryClassificationEvaluator,
        )

        n = 5000
        y = (RNG.random(n) > 0.4).astype(np.float64)
        # quantized scores force heavy ties across shard boundaries
        scores = np.round(RNG.random(n) * 50) / 50 * 0.8 + y * 0.1
        w = RNG.random(n) + 0.5
        df = DataFrame.from_dict({"label": y, "rawPrediction": scores, "weight": w})
        ev = (
            BinaryClassificationEvaluator()
            .set_weight_col("weight")
            .set_metrics_names("areaUnderROC", "areaUnderPR", "ks", "areaUnderLorenz")
        )
        out = ev.transform(df)

        # reference single-sort computation
        order = np.argsort(-scores, kind="stable")
        y_s, w_s, s_s = y[order], w[order], scores[order]
        pos = np.sum(w_s * (y_s == 1.0))
        neg = np.sum(w_s * (y_s != 1.0))
        boundary = np.nonzero(np.diff(s_s))[0]
        cut = np.concatenate([boundary, [n - 1]])
        tp = np.cumsum(w_s * (y_s == 1.0))[cut]
        fp = np.cumsum(w_s * (y_s != 1.0))[cut]
        tot = np.cumsum(w_s)[cut]
        tpr = np.concatenate([[0.0], tp / pos])
        fpr = np.concatenate([[0.0], fp / neg])
        precision = np.concatenate([[1.0], tp / (tp + fp)])
        pop = np.concatenate([[0.0], tot / (pos + neg)])
        np.testing.assert_allclose(out["areaUnderROC"][0], np.trapezoid(tpr, fpr), rtol=1e-12)
        np.testing.assert_allclose(out["areaUnderPR"][0], np.trapezoid(precision, tpr), rtol=1e-12)
        np.testing.assert_allclose(out["ks"][0], np.max(np.abs(tpr - fpr)), rtol=1e-12)
        np.testing.assert_allclose(out["areaUnderLorenz"][0], np.trapezoid(tpr, pop), rtol=1e-12)


class TestDistributedSortCache:
    """Out-of-core external sort (DataStreamUtils.java:409 + sort/ package)."""

    def _cache(self, keys, tmp_path, extra=None, chunk=97):
        from flink_ml_tpu.iteration import HostDataCache

        cache = HostDataCache(memory_budget_bytes=1024, spill_dir=str(tmp_path / "in"))
        for a in range(0, len(keys), chunk):
            c = {"k": keys[a : a + chunk]}
            if extra is not None:
                c.update({name: col[a : a + chunk] for name, col in extra.items()})
            cache.append(c)
        cache.finish()
        return cache

    @pytest.mark.parametrize("descending", [False, True])
    def test_matches_np_sort(self, tmp_path, descending):
        from flink_ml_tpu.parallel.datastream_utils import distributed_sort_cache

        rng = np.random.default_rng(5)
        keys = rng.normal(size=2003)
        payload = np.arange(2003, dtype=np.int64)
        cache = self._cache(keys, tmp_path, extra={"v": payload})
        got_k, got_v = [], []
        for b in distributed_sort_cache(
            cache, "k", ["v"], descending=descending, bucket_rows=256,
            spill_dir=str(tmp_path / "sort"),
        ):
            got_k.append(b["__key__"])
            got_v.append(b["v"])
        got_k = np.concatenate(got_k)
        order = np.argsort(keys)
        if descending:
            order = order[::-1]
        np.testing.assert_array_equal(got_k, keys[order])
        # payload rides along: re-sorting by payload recovers the keys
        got_v = np.concatenate(got_v)
        np.testing.assert_array_equal(keys[got_v], got_k)

    def test_ties_confined_to_one_bucket(self, tmp_path):
        from flink_ml_tpu.parallel.datastream_utils import distributed_sort_cache

        rng = np.random.default_rng(6)
        keys = rng.integers(0, 12, size=1500).astype(np.float64)  # heavy ties
        cache = self._cache(keys, tmp_path)
        seen = {}
        for i, b in enumerate(
            distributed_sort_cache(cache, "k", bucket_rows=128,
                                   spill_dir=str(tmp_path / "sort"))
        ):
            for v in np.unique(b["__key__"]):
                assert v not in seen, f"key {v} split across buckets {seen[v]} and {i}"
                seen[v] = i
        assert sorted(seen) == sorted(np.unique(keys))

    def test_empty_cache_yields_nothing(self, tmp_path):
        from flink_ml_tpu.iteration import HostDataCache
        from flink_ml_tpu.parallel.datastream_utils import distributed_sort_cache

        cache = HostDataCache(memory_budget_bytes=1024, spill_dir=str(tmp_path))
        cache.finish()
        assert list(distributed_sort_cache(cache, "k")) == []


class TestCacheStreamingBelt:
    """sample/co_group over the capacity tier — the out-of-core analogues."""

    @staticmethod
    def _fill(cache, cols, chunk=97):
        n = len(next(iter(cols.values())))
        for a in range(0, n, chunk):
            cache.append({k: v[a : a + chunk] for k, v in cols.items()})
        cache.finish()
        return cache

    def test_sample_cache_uniform_and_distinct(self, tmp_path):
        from flink_ml_tpu.iteration import HostDataCache
        from flink_ml_tpu.parallel import sample_cache

        n = 40_000
        cache = self._fill(
            HostDataCache(memory_budget_bytes=4096, spill_dir=str(tmp_path / "s")),
            {"x": np.arange(float(n)), "y": np.arange(n, dtype=np.int64) * 2},
        )
        got = sample_cache(cache, 500, seed=3)
        assert len(got["x"]) == 500
        assert len(np.unique(got["x"])) == 500  # reservoir rows are distinct
        np.testing.assert_array_equal(got["y"], got["x"].astype(np.int64) * 2)  # rows stay aligned
        assert abs(got["x"].mean() - n / 2) < n / 10  # uniform over the stream

    def test_sample_cache_small_input_returns_all(self, tmp_path):
        from flink_ml_tpu.iteration import HostDataCache
        from flink_ml_tpu.parallel import sample_cache

        cache = self._fill(
            HostDataCache(memory_budget_bytes=0, spill_dir=str(tmp_path / "s")),
            {"x": np.arange(7.0)},
        )
        got = sample_cache(cache, 100, seed=0)
        np.testing.assert_array_equal(np.sort(got["x"]), np.arange(7.0))

    def test_co_group_cache_parity_with_in_ram(self, tmp_path):
        from flink_ml_tpu.iteration import HostDataCache
        from flink_ml_tpu.parallel import co_group, co_group_cache

        rng = np.random.default_rng(11)
        lk = rng.integers(0, 50, size=1200).astype(np.float64)
        rk = rng.integers(25, 75, size=900).astype(np.float64)
        lv = np.arange(1200, dtype=np.int64)
        rv = np.arange(900, dtype=np.int64)
        left = self._fill(
            HostDataCache(memory_budget_bytes=2048, spill_dir=str(tmp_path / "l")),
            {"k": lk, "v": lv},
        )
        right = self._fill(
            HostDataCache(memory_budget_bytes=2048, spill_dir=str(tmp_path / "r")),
            {"k": rk, "v": rv},
        )
        # tiny buckets force the multi-bucket path
        got = {
            k: (set(lrows["v"].tolist()), set(rrows["v"].tolist()))
            for k, lrows, rrows in co_group_cache(
                left, right, "k", ["v"], ["v"],
                bucket_rows=256, spill_dir=str(tmp_path / "cg"),
            )
        }
        want = {
            k: (set(lv[li].tolist()), set(rv[ri].tolist()))
            for k, li, ri in co_group(lk, rk)
        }
        assert got == want
        assert list(got) == sorted(got)  # global key order

    def test_co_group_cache_empty_side_keeps_dtype(self, tmp_path):
        from flink_ml_tpu.iteration import HostDataCache
        from flink_ml_tpu.parallel import co_group_cache

        # right keys all land in the upper bucket, so the lower bucket's right
        # side is entirely empty — its yielded empties must still carry the
        # column's real dtype, not a float64 placeholder.
        left = self._fill(
            HostDataCache(memory_budget_bytes=0, spill_dir=str(tmp_path / "l")),
            {"k": np.arange(600, dtype=np.float64), "v": np.arange(600, dtype=np.int64)},
        )
        right = self._fill(
            HostDataCache(memory_budget_bytes=0, spill_dir=str(tmp_path / "r")),
            {"k": np.full(300, 599.0), "v": np.arange(300, dtype=np.int64)},
        )
        dtypes = {
            rrows["v"].dtype
            for _, lrows, rrows in co_group_cache(
                left, right, "k", ["v"], ["v"],
                bucket_rows=256, spill_dir=str(tmp_path / "cg"),
            )
        }
        assert dtypes == {np.dtype(np.int64)}
