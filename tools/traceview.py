#!/usr/bin/env python
"""traceview — offline analysis of a graftscope Chrome trace export.

Loads the trace-event JSON written by
``flink_ml_tpu.trace.SpanRecorder.export_chrome_trace`` and prints, per scope
(= trace-event pid, named by ``process_name`` metadata):

- the goodput breakdown: attributed milliseconds and share of traced wall
  time per category (productive / queue / padding / compile / swap /
  recovery / readback — the ML Productivity Goodput buckets), plus the
  goodput fraction;
- per-span-name latency stats: count, p50, p99, total ms, % of the scope's
  wall time;
- per-shard attribution when the scope served through a mesh
  (``serving.mesh``/``batch.mesh`` > 1): spans carrying a ``shards`` attr
  split their device time evenly across the mesh's data axis (SPMD shards
  run in lock-step), so the report shows how many device-milliseconds each
  shard absorbed and what per-shard goodput looks like.

The same span self-time attribution as the live ``GoodputReport`` (parents
minus same-scope children), reconstructed from the ``span_id``/``parent_id``
the exporter stashes under each event's ``args`` — so the offline numbers
match what ``ml.goodput.*`` gauges would have read.

Usage:
    python tools/traceview.py /tmp/trace.json [--scope ml.serving] [--top 20]

Exit codes: 0 = analyzed, 2 = unreadable/invalid/empty trace.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from flink_ml_tpu.trace import CATEGORIES, GoodputReport, Span  # noqa: E402

__all__ = ["load_spans", "summarize", "main"]


def load_spans(path: str) -> List[Span]:
    """Reconstruct Span records from a Chrome trace-event export."""
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    events = payload.get("traceEvents", payload if isinstance(payload, list) else None)
    if not isinstance(events, list):
        raise ValueError("not a trace-event file: no traceEvents array")
    scope_of_pid: Dict[Any, str] = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            scope_of_pid[ev.get("pid")] = ev.get("args", {}).get("name", str(ev.get("pid")))
    spans: List[Span] = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = dict(ev.get("args", {}))
        start_s = float(ev.get("ts", 0.0)) / 1e6
        span = Span(
            name=ev.get("name", "?"),
            category=ev.get("cat", "productive"),
            scope=scope_of_pid.get(ev.get("pid"), str(ev.get("pid"))),
            start=start_s,
            span_id=args.pop("span_id", len(spans) + 1),
            parent_id=args.pop("parent_id", None),
            thread_id=ev.get("tid", 0),
            thread_name=str(ev.get("tid", 0)),
        )
        span.end = start_s + float(ev.get("dur", 0.0)) / 1e6
        if args:
            span.attrs = args
        spans.append(span)
    return spans


def _quantile(ordered: List[float], q: float) -> float:
    return ordered[min(int(q * len(ordered)), len(ordered) - 1)]


def _shard_summary(scope_spans: List[Span]) -> List[str]:
    """Per-shard device-time attribution for a mesh-sharded scope: spans
    carrying a ``shards`` attr (serving dispatch/exec, batch chunks) ran SPMD
    with rows split over the data axis — lock-step shards, so each shard's
    share of the span is 1/shards of its wall. Returns [] for unsharded
    scopes (no such spans)."""
    sharded = [
        s for s in scope_spans
        if s.attrs and isinstance(s.attrs.get("shards"), int) and s.attrs["shards"] > 1
    ]
    if not sharded:
        return []
    widths = sorted({s.attrs["shards"] for s in sharded})
    total_ms = sum(s.duration for s in sharded) * 1000.0
    rows = sum(
        s.attrs.get("shard_rows", 0) * s.attrs["shards"]
        for s in sharded
        if isinstance(s.attrs.get("shard_rows"), int)
    )
    lines = [
        f"  shards: mesh width(s) {'/'.join(str(w) for w in widths)} — "
        f"{len(sharded)} sharded spans, {total_ms:.3f} ms device time"
    ]
    for w in widths:
        ms = sum(s.duration for s in sharded if s.attrs["shards"] == w) * 1000.0
        lines.append(
            f"    {w}-way: {ms:.3f} ms total, {ms / w:.3f} ms per shard"
        )
    if rows:
        lines.append(f"    sharded rows (padded): {rows}")
    return lines


def summarize(spans: List[Span], scope_filter: Optional[str] = None, top: int = 20) -> str:
    """The human report (one string, printed by main)."""
    if scope_filter:
        spans = [s for s in spans if s.scope.startswith(scope_filter)]
    report = GoodputReport.from_spans(spans)
    lines: List[str] = []
    for scope in report.scopes():
        wall_ms = report.wall_s(scope) * 1000.0
        lines.append(f"scope {scope} — traced wall {wall_ms:.3f} ms")
        fraction = report.fraction(scope)
        if fraction is not None:
            lines.append(f"  goodput fraction: {fraction:.4f}")
        lines.append(f"  {'category':<12} {'ms':>12} {'% wall':>8}")
        for category in CATEGORIES:
            ms = report.category_s(scope, category) * 1000.0
            if ms <= 0.0:
                continue
            pct = 100.0 * ms / wall_ms if wall_ms > 0.0 else 0.0
            lines.append(f"  {category:<12} {ms:>12.3f} {pct:>7.1f}%")
        by_name: Dict[str, List[float]] = {}
        for s in spans:
            if s.scope == scope:
                by_name.setdefault(s.name, []).append(s.duration * 1000.0)
        lines.append(
            f"  {'span':<24} {'count':>7} {'p50 ms':>10} {'p99 ms':>10} "
            f"{'total ms':>12} {'% wall':>8}"
        )
        ranked = sorted(by_name.items(), key=lambda kv: -sum(kv[1]))[:top]
        for name, durs in ranked:
            ordered = sorted(durs)
            total = sum(durs)
            pct = 100.0 * total / wall_ms if wall_ms > 0.0 else 0.0
            lines.append(
                f"  {name:<24} {len(durs):>7} {_quantile(ordered, 0.5):>10.3f} "
                f"{_quantile(ordered, 0.99):>10.3f} {total:>12.3f} {pct:>7.1f}%"
            )
        shard_lines = _shard_summary(
            [s for s in spans if s.scope == scope]
        )
        lines.extend(shard_lines)
        lines.append("")
    overall = report.fraction()
    if overall is not None:
        lines.append(f"overall goodput fraction: {overall:.4f}")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="graftscope trace analyzer")
    parser.add_argument("trace", help="Chrome trace-event JSON (SpanRecorder.export_chrome_trace)")
    parser.add_argument("--scope", help="only scopes with this prefix (e.g. ml.serving)")
    parser.add_argument("--top", type=int, default=20, help="span names per scope (by total time)")
    args = parser.parse_args(argv)
    try:
        spans = load_spans(args.trace)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"traceview: cannot load {args.trace}: {e}", file=sys.stderr)
        return 2
    if not spans:
        print(f"traceview: {args.trace} contains no spans", file=sys.stderr)
        return 2
    try:
        print(f"{args.trace}: {len(spans)} spans")
        print(summarize(spans, scope_filter=args.scope, top=args.top))
    except BrokenPipeError:  # e.g. `traceview t.json | head` — a clean exit
        return 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
