#!/usr/bin/env python
"""traceview — offline analysis of graftscope traces and incident bundles.

**Trace mode** loads the trace-event JSON written by
``flink_ml_tpu.trace.SpanRecorder.export_chrome_trace`` and prints, per scope
(= trace-event pid, named by ``process_name`` metadata):

- the goodput breakdown: attributed milliseconds and share of traced wall
  time per category (productive / queue / padding / compile / swap /
  recovery / readback — the ML Productivity Goodput buckets), plus the
  goodput fraction;
- per-span-name latency stats: count, p50, p99, total ms, % of the scope's
  wall time;
- per-shard attribution when the scope served through a mesh
  (``serving.mesh``/``batch.mesh`` > 1): spans carrying a ``shards`` attr
  split their device time evenly across the mesh's data axis (SPMD shards
  run in lock-step), so the report shows how many device-milliseconds each
  shard absorbed and what per-shard goodput looks like.

The same span self-time attribution as the live ``GoodputReport`` (parents
minus same-scope children), reconstructed from the ``span_id``/``parent_id``
the exporter stashes under each event's ``args`` — so the offline numbers
match what ``ml.goodput.*`` gauges would have read. ``--json`` emits the
summary and per-category sections machine-readable so CI can assert on
attribution numbers without screen-scraping.

**Incident mode** renders a flight-recorder incident bundle
(``flink_ml_tpu.telemetry``, docs/observability.md) as a postmortem
timeline: the journal's decision records interleaved with the bundle's span
categories on one monotonic clock (they share the ``time.perf_counter``
timebase by construction), plus the trigger context and version lineage.

Usage:
    python tools/traceview.py /tmp/trace.json [--scope ml.serving] [--top 20] [--json]
    python tools/traceview.py incident /path/to/incident-000004-rollback [--json]

Exit codes: 0 = analyzed, 2 = unreadable/invalid/empty input.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from flink_ml_tpu.trace import CATEGORIES, GoodputReport, Span  # noqa: E402

__all__ = [
    "load_spans",
    "summarize",
    "summarize_data",
    "incident_timeline",
    "summarize_incident",
    "main",
]


def load_spans(path: str) -> List[Span]:
    """Reconstruct Span records from a Chrome trace-event export."""
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    events = payload.get("traceEvents", payload if isinstance(payload, list) else None)
    if not isinstance(events, list):
        raise ValueError("not a trace-event file: no traceEvents array")
    scope_of_pid: Dict[Any, str] = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            scope_of_pid[ev.get("pid")] = ev.get("args", {}).get("name", str(ev.get("pid")))
    spans: List[Span] = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = dict(ev.get("args", {}))
        start_s = float(ev.get("ts", 0.0)) / 1e6
        span = Span(
            name=ev.get("name", "?"),
            category=ev.get("cat", "productive"),
            scope=scope_of_pid.get(ev.get("pid"), str(ev.get("pid"))),
            start=start_s,
            span_id=args.pop("span_id", len(spans) + 1),
            parent_id=args.pop("parent_id", None),
            thread_id=ev.get("tid", 0),
            thread_name=str(ev.get("tid", 0)),
        )
        span.end = start_s + float(ev.get("dur", 0.0)) / 1e6
        if args:
            span.attrs = args
        spans.append(span)
    return spans


def _quantile(ordered: List[float], q: float) -> float:
    return ordered[min(int(q * len(ordered)), len(ordered) - 1)]


def _shard_summary(scope_spans: List[Span]) -> List[str]:
    """Per-shard device-time attribution for a mesh-sharded scope: spans
    carrying a ``shards`` attr (serving dispatch/exec, batch chunks) ran SPMD
    with rows split over the data axis — lock-step shards, so each shard's
    share of the span is 1/shards of its wall. Returns [] for unsharded
    scopes (no such spans)."""
    sharded = [
        s for s in scope_spans
        if s.attrs and isinstance(s.attrs.get("shards"), int) and s.attrs["shards"] > 1
    ]
    if not sharded:
        return []
    widths = sorted({s.attrs["shards"] for s in sharded})
    total_ms = sum(s.duration for s in sharded) * 1000.0
    rows = sum(
        s.attrs.get("shard_rows", 0) * s.attrs["shards"]
        for s in sharded
        if isinstance(s.attrs.get("shard_rows"), int)
    )
    lines = [
        f"  shards: mesh width(s) {'/'.join(str(w) for w in widths)} — "
        f"{len(sharded)} sharded spans, {total_ms:.3f} ms device time"
    ]
    for w in widths:
        ms = sum(s.duration for s in sharded if s.attrs["shards"] == w) * 1000.0
        lines.append(
            f"    {w}-way: {ms:.3f} ms total, {ms / w:.3f} ms per shard"
        )
    if rows:
        lines.append(f"    sharded rows (padded): {rows}")
    return lines


def summarize_data(
    spans: List[Span], scope_filter: Optional[str] = None, top: int = 20
) -> Dict[str, Any]:
    """The machine-readable form of :func:`summarize` — same attribution,
    as a JSON-safe dict (``--json``): per scope the traced wall ms, goodput
    fraction, per-category ms + share, and the ranked per-span stats; plus
    the overall goodput fraction. CI asserts on these numbers instead of
    screen-scraping the human report."""
    if scope_filter:
        spans = [s for s in spans if s.scope.startswith(scope_filter)]
    report = GoodputReport.from_spans(spans)
    scopes: Dict[str, Any] = {}
    for scope in report.scopes():
        wall_ms = report.wall_s(scope) * 1000.0
        categories: Dict[str, Any] = {}
        for category in CATEGORIES:
            ms = report.category_s(scope, category) * 1000.0
            if ms <= 0.0:
                continue
            categories[category] = {
                "ms": round(ms, 6),
                "share": round(ms / wall_ms, 6) if wall_ms > 0.0 else 0.0,
            }
        by_name: Dict[str, List[float]] = {}
        for s in spans:
            if s.scope == scope:
                by_name.setdefault(s.name, []).append(s.duration * 1000.0)
        ranked = sorted(by_name.items(), key=lambda kv: -sum(kv[1]))[:top]
        span_stats = []
        for name, durs in ranked:
            ordered = sorted(durs)
            total = sum(durs)
            span_stats.append(
                {
                    "name": name,
                    "count": len(durs),
                    "p50_ms": round(_quantile(ordered, 0.5), 6),
                    "p99_ms": round(_quantile(ordered, 0.99), 6),
                    "total_ms": round(total, 6),
                    "share": round(total / wall_ms, 6) if wall_ms > 0.0 else 0.0,
                }
            )
        fraction = report.fraction(scope)
        scopes[scope] = {
            "wall_ms": round(wall_ms, 6),
            "goodput_fraction": round(fraction, 6) if fraction is not None else None,
            "categories": categories,
            "spans": span_stats,
        }
    overall = report.fraction()
    return {
        "spans": len(spans),
        "scopes": scopes,
        "overall_goodput_fraction": round(overall, 6) if overall is not None else None,
    }


def summarize(spans: List[Span], scope_filter: Optional[str] = None, top: int = 20) -> str:
    """The human report (one string, printed by main)."""
    if scope_filter:
        spans = [s for s in spans if s.scope.startswith(scope_filter)]
    report = GoodputReport.from_spans(spans)
    lines: List[str] = []
    for scope in report.scopes():
        wall_ms = report.wall_s(scope) * 1000.0
        lines.append(f"scope {scope} — traced wall {wall_ms:.3f} ms")
        fraction = report.fraction(scope)
        if fraction is not None:
            lines.append(f"  goodput fraction: {fraction:.4f}")
        lines.append(f"  {'category':<12} {'ms':>12} {'% wall':>8}")
        for category in CATEGORIES:
            ms = report.category_s(scope, category) * 1000.0
            if ms <= 0.0:
                continue
            pct = 100.0 * ms / wall_ms if wall_ms > 0.0 else 0.0
            lines.append(f"  {category:<12} {ms:>12.3f} {pct:>7.1f}%")
        by_name: Dict[str, List[float]] = {}
        for s in spans:
            if s.scope == scope:
                by_name.setdefault(s.name, []).append(s.duration * 1000.0)
        lines.append(
            f"  {'span':<24} {'count':>7} {'p50 ms':>10} {'p99 ms':>10} "
            f"{'total ms':>12} {'% wall':>8}"
        )
        ranked = sorted(by_name.items(), key=lambda kv: -sum(kv[1]))[:top]
        for name, durs in ranked:
            ordered = sorted(durs)
            total = sum(durs)
            pct = 100.0 * total / wall_ms if wall_ms > 0.0 else 0.0
            lines.append(
                f"  {name:<24} {len(durs):>7} {_quantile(ordered, 0.5):>10.3f} "
                f"{_quantile(ordered, 0.99):>10.3f} {total:>12.3f} {pct:>7.1f}%"
            )
        shard_lines = _shard_summary(
            [s for s in spans if s.scope == scope]
        )
        lines.extend(shard_lines)
        lines.append("")
    overall = report.fraction()
    if overall is not None:
        lines.append(f"overall goodput fraction: {overall:.4f}")
    return "\n".join(lines)


def incident_timeline(bundle: Dict[str, Any]) -> List[Dict[str, Any]]:
    """One merged timeline of a loaded incident bundle: journal decision
    records and span intervals (by start time), sorted on the shared
    monotonic clock. Each entry: ``{"t", "source": "journal"|"span",
    "label", "category"|None, "detail"}``."""
    current_inc = bundle.get("manifest", {}).get("incarnation", 0)
    entries: List[Dict[str, Any]] = []
    for rec in bundle.get("records", []):
        detail = dict(rec.get("data") or {})
        entries.append(
            {
                "t": float(rec.get("t", 0.0)),
                "inc": rec.get("inc", current_inc),
                "source": "journal",
                "label": rec.get("kind", "?"),
                "seq": rec.get("seq"),
                "scope": rec.get("scope"),
                "category": None,
                "detail": detail,
            }
        )
    for ev in bundle.get("trace_events", []):
        if ev.get("ph") != "X":
            continue
        entries.append(
            {
                "t": float(ev.get("ts", 0.0)) / 1e6,
                "inc": current_inc,
                "source": "span",
                "label": ev.get("name", "?"),
                "seq": None,
                "scope": None,
                "category": ev.get("cat"),
                "detail": {"dur_ms": round(float(ev.get("dur", 0.0)) / 1e3, 3)},
            }
        )
    # Monotonic clocks are per-process: order by incarnation first (a
    # crash-resume bundle carries the prior life's tail), then by time —
    # comparable within one incarnation by construction.
    entries.sort(key=lambda e: (e["inc"], e["t"]))
    return entries


def summarize_incident(bundle: Dict[str, Any], top: int = 200) -> str:
    """The human postmortem: trigger header, version lineage, then the
    interleaved journal/span timeline (relative seconds from the first
    entry; span entries grouped per category)."""
    manifest = bundle.get("manifest", {})
    lines: List[str] = []
    lines.append(
        f"incident {manifest.get('kind', '?')} — seq {manifest.get('seq')}, "
        f"incarnation {manifest.get('incarnation')}"
    )
    context = manifest.get("context") or {}
    if context:
        lines.append(f"  context: {json.dumps(context, default=str)}")
    lineage = manifest.get("lineage") or []
    if lineage:
        lines.append("  version lineage:")
        for entry in lineage:
            version = entry.get("version")
            lines.append(
                f"    seq {entry.get('seq'):>6}  {entry.get('kind'):<22}"
                + (f" v{version}" if version is not None else "")
            )
    timeline = incident_timeline(bundle)
    if timeline:
        t0 = timeline[0]["t"]
        cat_ms: Dict[str, float] = {}
        for e in timeline:
            if e["source"] == "span" and e["category"]:
                cat_ms[e["category"]] = cat_ms.get(e["category"], 0.0) + e["detail"].get("dur_ms", 0.0)
        if cat_ms:
            lines.append("  span categories in the window:")
            for cat in CATEGORIES:
                if cat in cat_ms:
                    lines.append(f"    {cat:<12} {cat_ms[cat]:>12.3f} ms")
        lines.append(f"  timeline ({len(timeline)} entries):")
        shown = timeline if len(timeline) <= top else timeline[-top:]
        if len(shown) < len(timeline):
            lines.append(f"    ... {len(timeline) - len(shown)} earlier entries elided ...")
        # Relative seconds restart per incarnation: monotonic clocks are
        # per-process, so cross-incarnation offsets are meaningless.
        inc_t0: Dict[Any, float] = {}
        for e in timeline:
            inc_t0.setdefault(e["inc"], e["t"])
        last_inc = None
        for e in shown:
            if e["inc"] != last_inc:
                if last_inc is not None or len(inc_t0) > 1:
                    lines.append(f"    -- incarnation {e['inc']} --")
                last_inc = e["inc"]
            rel = e["t"] - inc_t0[e["inc"]]
            if e["source"] == "journal":
                detail = json.dumps(e["detail"], default=str) if e["detail"] else ""
                lines.append(f"    +{rel:9.4f}s  [journal #{e['seq']}] {e['label']} {detail}")
            else:
                lines.append(
                    f"    +{rel:9.4f}s  [span:{e['category']}] {e['label']} "
                    f"({e['detail'].get('dur_ms', 0.0):.3f} ms)"
                )
    return "\n".join(lines)


def _main_incident(argv: List[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="traceview incident", description="flight-recorder incident postmortem"
    )
    parser.add_argument("bundle", help="incident-<seq>-<kind>/ directory (telemetry bundles)")
    parser.add_argument("--json", action="store_true", help="machine-readable timeline + manifest")
    parser.add_argument("--top", type=int, default=200, help="timeline entries shown (newest kept)")
    args = parser.parse_args(argv)
    from flink_ml_tpu.telemetry import load_bundle  # noqa: E402 — repo-root path set above

    try:
        bundle = load_bundle(args.bundle)
    except (OSError, ValueError, KeyError) as e:
        print(f"traceview: cannot load incident bundle {args.bundle}: {e}", file=sys.stderr)
        return 2
    if not bundle.get("records"):
        print(f"traceview: {args.bundle} contains no journal records", file=sys.stderr)
        return 2
    try:
        if args.json:
            print(
                json.dumps(
                    {
                        "manifest": bundle["manifest"],
                        "timeline": incident_timeline(bundle),
                    },
                    indent=1,
                    default=str,
                )
            )
        else:
            print(summarize_incident(bundle, top=args.top))
    except BrokenPipeError:
        return 0
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "incident":
        return _main_incident(argv[1:])
    parser = argparse.ArgumentParser(description="graftscope trace analyzer")
    parser.add_argument("trace", help="Chrome trace-event JSON (SpanRecorder.export_chrome_trace)")
    parser.add_argument("--scope", help="only scopes with this prefix (e.g. ml.serving)")
    parser.add_argument("--top", type=int, default=20, help="span names per scope (by total time)")
    parser.add_argument(
        "--json", action="store_true",
        help="machine-readable summary (summarize_data) instead of the human report",
    )
    args = parser.parse_args(argv)
    try:
        spans = load_spans(args.trace)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"traceview: cannot load {args.trace}: {e}", file=sys.stderr)
        return 2
    if not spans:
        print(f"traceview: {args.trace} contains no spans", file=sys.stderr)
        return 2
    try:
        if args.json:
            print(json.dumps(summarize_data(spans, scope_filter=args.scope, top=args.top), indent=1))
        else:
            print(f"{args.trace}: {len(spans)} spans")
            print(summarize(spans, scope_filter=args.scope, top=args.top))
    except BrokenPipeError:  # e.g. `traceview t.json | head` — a clean exit
        return 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
