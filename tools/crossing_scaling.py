"""Measured per-chip cost of the one-hot sparse program vs data parallelism.

The sparse roofline's scaling claim (docs/benchmarks.md): the crossing term —
the two-level one-hot contractions reindexing entries between feature-grouped
and row-grouped orders — costs ~``local_batch * sub_batch * nnz_pad`` MACs
per chip, so p-way DP (which divides both the per-shard entry count and,
once below the 16384 cap, the sub-batch row space) drives it down ~1/p².

This module turns that argument into a *measured artifact*: it compiles the
actual ``_fused_onehot_program`` over a p-way mesh for each p and reads the
per-chip FLOP/byte counts from XLA's compiled-cost analysis
(``jit(...).lower(...).compile().cost_analysis()`` — under SPMD partitioning
the compiled executable IS the per-device program, so these are per-chip
numbers). The XLA (non-Pallas) crossings are measured: same contraction
structure, and Mosaic kernels are opaque to XLA cost analysis anyway.

Run on the 8-device virtual CPU mesh:

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/crossing_scaling.py

``tests/test_crossing_scaling.py`` asserts the superlinear falloff on a
smaller shape every CI run.
"""
from __future__ import annotations

import numpy as np

__all__ = ["measure_scaling", "markdown_table"]


def measure_scaling(p_list, global_batch, dim, nnz, K, seed=0, time_steps=0):
    """Compile the fused one-hot SGD program at each DP width and return
    ``[{p, local_batch, sub_batch, n_flat, flops_per_chip, bytes_per_chip}]``.

    One window, one epoch per chunk (chunk_len=1): the numbers are one
    minibatch step's per-chip cost, the unit the scaling claim is about.

    With ``time_steps > 0`` each row additionally carries wall-clock columns
    from running the compiled program ``time_steps`` times (median of 3
    loops, outputs chained back as inputs to respect buffer donation):
    ``wall_ms_per_step`` and ``per_chip_ms`` — the latter estimated as
    ``wall * min(cores, p) / p``, since on a host with fewer cores than
    virtual devices the p shards serialize onto the cores (wall ≈ p × the
    per-chip time), while with enough cores they run concurrently (wall ≈
    the per-chip time). Relative falloff across p is the meaningful number;
    absolute CPU milliseconds are not TPU milliseconds.
    """
    import os
    import time

    import jax

    from flink_ml_tpu.iteration import DeviceDataCache
    from flink_ml_tpu.linalg.onehot_sparse import OneHotSparseLayout
    from flink_ml_tpu.ops import BinaryLogisticLoss
    from flink_ml_tpu.ops.optimizer import _fused_onehot_program
    from flink_ml_tpu.parallel.mesh import (
        DATA_AXIS,
        MODEL_AXIS,
        MeshContext,
        mesh_context,
    )

    rng = np.random.default_rng(seed)
    n = global_batch  # one window: the dataset IS one global minibatch
    idx = rng.integers(0, dim, size=(n, K), dtype=np.int32)
    vals = np.ones((n, K), np.float32)
    vals[:, nnz:] = 0.0
    y = (rng.random(n) > 0.5).astype(np.float32)
    w = np.ones(n, np.float32)

    rows = []
    for p in p_list:
        with mesh_context(MeshContext(n_data=p, n_model=1)) as ctx:
            local_batch = global_batch // p
            lay = OneHotSparseLayout.build(idx, vals, dim, p, local_batch)
            cache = DeviceDataCache(
                {"indices": idx, "values": vals, "labels": y, "weights": w},
                ctx=ctx,
            )
            program = _fused_onehot_program(
                ctx, BinaryLogisticLoss.INSTANCE, lay, 1, 0.1, 0.0, 0.0, None,
                use_pallas=False,
            )
            sh = ctx.sharding(DATA_AXIS, MODEL_AXIS)
            stacks = (
                jax.device_put(lay.lidx, sh),
                jax.device_put(lay.rowid, sh),
                jax.device_put(np.asarray(lay.lvals, np.float32), sh),
            )
            args = (
                ctx.replicate(lay.permute_coef(np.zeros(dim, np.float32))),
                ctx.replicate(np.asarray(False)),
                np.zeros(1, np.int32),
                np.zeros(1, np.int32),
                np.ones(1, bool),
                *stacks,
                cache["labels"],
                cache["weights"],
                cache.mask.astype(np.float32),
            )
            cost = program.lower(*args).compile().cost_analysis()
            if isinstance(cost, (list, tuple)):  # some backends wrap in a list
                cost = cost[0]
            row = {
                "p": p,
                "local_batch": local_batch,
                "sub_batch": lay.sub_batch,
                "n_sub": lay.n_sub,
                "n_flat": lay.n_flat,
                "flops_per_chip": float(cost.get("flops", float("nan"))),
                "bytes_per_chip": float(
                    cost.get("bytes accessed", float("nan"))
                ),
            }
            if time_steps:
                coef, done, *rest = args
                coef, done, _, _ = program(coef, done, *rest)  # warmup compile
                jax.block_until_ready(coef)
                loops = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    for _ in range(time_steps):
                        # chain outputs -> inputs: coef/done are donated
                        coef, done, _, _ = program(coef, done, *rest)
                    jax.block_until_ready(coef)
                    loops.append((time.perf_counter() - t0) / time_steps)
                wall_ms = sorted(loops)[1] * 1e3
                cores = os.cpu_count() or 1
                row["wall_ms_per_step"] = wall_ms
                row["per_chip_ms"] = wall_ms * min(cores, p) / p
            rows.append(row)
    return rows


def markdown_table(rows) -> str:
    timed = "per_chip_ms" in rows[0]
    head = (
        "| p (DP chips) | local batch | sub batch | n_flat/unit | "
        "per-chip GFLOP/step | x fall vs p=1 | p x fall (superlinear > 1/p) |"
        + (" measured per-chip ms | time fall vs p=1 |" if timed else "")
        + "\n|---|---|---|---|---|---|---|"
        + ("---|---|" if timed else "")
        + "\n"
    )
    base = rows[0]["flops_per_chip"]
    t_base = rows[0].get("per_chip_ms")
    lines = []
    for r in rows:
        fall = base / r["flops_per_chip"] if r["flops_per_chip"] else float("nan")
        line = (
            f"| {r['p']} | {r['local_batch']} | {r['sub_batch']} | {r['n_flat']} "
            f"| {r['flops_per_chip'] / 1e9:.2f} | {fall:.1f}x "
            f"| {fall / r['p']:.2f} |"
        )
        if timed:
            t_fall = t_base / r["per_chip_ms"] if r["per_chip_ms"] else float("nan")
            line += f" {r['per_chip_ms']:.2f} | {t_fall:.1f}x |"
        lines.append(line)
    return head + "\n".join(lines)


if __name__ == "__main__":
    rows = measure_scaling(
        [1, 2, 4, 8], global_batch=65_536, dim=1 << 20, nnz=39, K=40,
        time_steps=3,
    )
    print(markdown_table(rows))
