#!/usr/bin/env python
"""CI guard: the serving tier must stay runtime-free.

Thin shim over the graftcheck ``layer-deps`` rule (tools/graftcheck/rules/
layer_deps.py), which owns the layer map this guarantee is one slice of:
nothing under ``flink_ml_tpu/servable/`` or ``flink_ml_tpu/serving/`` may
import the training stack (iteration / execution / builder / models), lazy
function-local imports included. Kept for its entry point and its ``check()``
/ ``_violations_in_file()`` contract — ``tests/test_servable_imports.py`` and
muscle memory both call it; new invariants belong in graftcheck rules, not
here.

Run directly (``python tools/check_servable_imports.py``) or via
``python -m tools.graftcheck`` (the full suite).
"""
from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.graftcheck.rules.layer_deps import (  # noqa: E402
    FORBIDDEN_PREFIXES,
    RUNTIME_FREE_PACKAGES,
    servable_check,
    servable_violations_in_file,
)

__all__ = ["FORBIDDEN_PREFIXES", "RUNTIME_FREE_PACKAGES", "check", "main"]


def _violations_in_file(path: str):
    return servable_violations_in_file(path)


def check(repo_root: str = REPO_ROOT):
    """Returns (problems, checked_files) — empty problems list means pass."""
    return servable_check(repo_root)


def main() -> int:
    problems, checked = check()
    if problems:
        print("check_servable_imports: FAIL")
        for p in problems:
            print(f"  - {p}")
        return 1
    print(f"check_servable_imports: OK ({len(checked)} files runtime-free)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
