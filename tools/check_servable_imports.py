#!/usr/bin/env python
"""CI guard: the serving tier must stay runtime-free.

The reference's L1 layer (``flink-ml-servable-core``/``-lib``) exists so a
model can serve online traffic without the training runtime on the classpath.
Our analogue: nothing under ``flink_ml_tpu/servable/`` or
``flink_ml_tpu/serving/`` may import the training stack —

    flink_ml_tpu.iteration   (iteration drivers, data caches)
    flink_ml_tpu.execution   (supervisor, restart strategies)
    flink_ml_tpu.builder     (pipeline/graph estimators)
    flink_ml_tpu.models      (the algorithm library)

The check is AST-based so function-local (lazy) imports are caught too — a
deferred ``from flink_ml_tpu.models.linear import ...`` still drags the
training stack into a serving process the first time a request arrives, which
is exactly when it must not happen.

Run directly (``python tools/check_servable_imports.py``) or through the
tier-1 suite via ``tests/test_servable_imports.py``.
"""
from __future__ import annotations

import ast
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Packages whose files must honor the guarantee.
RUNTIME_FREE_PACKAGES = ("flink_ml_tpu/servable", "flink_ml_tpu/serving")

#: Training-stack roots, as dotted module prefixes.
FORBIDDEN_PREFIXES = (
    "flink_ml_tpu.iteration",
    "flink_ml_tpu.execution",
    "flink_ml_tpu.builder",
    "flink_ml_tpu.models",
)


def _forbidden(module: str) -> bool:
    return any(
        module == p or module.startswith(p + ".") for p in FORBIDDEN_PREFIXES
    )


def _violations_in_file(path: str):
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if _forbidden(alias.name):
                    yield node.lineno, alias.name
        elif isinstance(node, ast.ImportFrom):
            module = node.module or ""
            if node.level:  # relative import: resolve against the package
                continue  # servable/serving have no training-stack subpackages
            if _forbidden(module):
                yield node.lineno, module
            elif module == "flink_ml_tpu":
                # ``from flink_ml_tpu import models`` style
                for alias in node.names:
                    if _forbidden(f"flink_ml_tpu.{alias.name}"):
                        yield node.lineno, f"flink_ml_tpu.{alias.name}"


def check(repo_root: str = REPO_ROOT):
    """Returns (problems, checked_files) — empty problems list means pass."""
    problems = []
    checked = []
    for package in RUNTIME_FREE_PACKAGES:
        pkg_dir = os.path.join(repo_root, package)
        for dirpath, _, filenames in os.walk(pkg_dir):
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                rel = os.path.relpath(path, repo_root)
                checked.append(rel)
                for lineno, module in _violations_in_file(path):
                    problems.append(
                        f"{rel}:{lineno} imports {module} — the serving tier "
                        "must not depend on the training stack (L1 "
                        "runtime-free guarantee)"
                    )
    if not checked:
        problems.append("no files checked — package layout changed?")
    return problems, checked


def main() -> int:
    problems, checked = check()
    if problems:
        print("check_servable_imports: FAIL")
        for p in problems:
            print(f"  - {p}")
        return 1
    print(f"check_servable_imports: OK ({len(checked)} files runtime-free)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
