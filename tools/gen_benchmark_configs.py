#!/usr/bin/env python
"""Generate the per-stage benchmark config suite.

Reference: ``flink-ml-benchmark/src/main/resources/*-benchmark.json`` — one
JSON config per stage (34 beyond the demo), each pairing the stage with a
data generator. This script emits the same suite for this framework into
``flink_ml_tpu/benchmark/configs/`` using the identical schema and the
reference's fully-qualified Java class names (they resolve through the
stage/generator registries — config compatibility is the point).

Row counts are scaled to ``ROW_CAP`` (the reference's 10M-100M rows target
multi-TaskManager clusters; these configs must run on one chip / the CI
mesh), with the stage-relevant shape parameters (vector dims, arities,
array sizes, splits) kept verbatim. ``tests/test_benchmark_configs.py``
regenerates and diffs on every CI run so the suite cannot drift from this
table, and executes each config end-to-end at further-reduced row counts.
"""
from __future__ import annotations

import json
import os
import sys

ROW_CAP = 100_000

_F5 = ["f0", "f1", "f2", "f3", "f4"]
_F15 = [f"f{i}" for i in range(15)]
_OUT5 = [f"outputCol{i}" for i in range(5)]
_OUT15 = [f"outputCol{i}" for i in range(15)]

# (config name, entry name, stage className suffix, stage paramMap,
#  generator className suffix, generator paramMap) — mirrors the reference
# suite's pairings (flink-ml-benchmark/src/main/resources).
TABLE = [
    ("agglomerativeclustering", "AgglomerativeClustering",
     "clustering.agglomerativeclustering.AgglomerativeClustering",
     {"distanceMeasure": "euclidean", "numClusters": 10, "linkage": "ward"},
     "DenseVectorGenerator",
     {"seed": 2, "colNames": [["features"]], "numValues": 1000, "vectorDim": 100}),
    ("binarizer", "Binarizer", "feature.binarizer.Binarizer",
     {"inputCols": _F5, "outputCols": _OUT5, "thresholds": [0.5, 0.3, 0.3, 0.6, 0.8]},
     "DoubleGenerator", {"colNames": [_F5], "seed": 2, "numValues": ROW_CAP}),
    ("bucketizer", "Bucketizer", "feature.bucketizer.Bucketizer",
     {"outputCols": ["outputCol0"], "handleInvalid": "skip", "inputCols": ["col0"],
      "splitsArray": [[-1.0, 0.0, 0.5, 1.0, 2.0]]},
     "DoubleGenerator", {"colNames": [["col0"]], "seed": 2, "numValues": ROW_CAP}),
    ("countvectorizer", "CountVectorizer", "feature.countvectorizer.CountVectorizer",
     {},
     "RandomStringArrayGenerator",
     {"colNames": [["input"]], "seed": 2, "numValues": 20_000, "arraySize": 100,
      "numDistinctValues": 100}),
    ("dct", "DCT", "feature.dct.DCT", {},
     "DenseVectorGenerator",
     {"colNames": [["input"]], "seed": 2, "numValues": ROW_CAP, "vectorDim": 100}),
    ("elementwiseproduct", "ElementwiseProduct",
     "feature.elementwiseproduct.ElementwiseProduct",
     {"scalingVec": {"values": [1.0, 2.0, 3.0, 4.0, 5.0]}},
     "DenseVectorGenerator",
     {"vectorDim": 5, "colNames": [["input"]], "seed": 2, "numValues": ROW_CAP}),
    ("featurehasher", "FeatureHasher", "feature.featurehasher.FeatureHasher",
     {"inputCols": _F5, "categoricalCols": ["f0", "f1", "f2"], "numFeatures": 1000},
     "DoubleGenerator", {"colNames": [_F5], "seed": 2, "numValues": ROW_CAP}),
    ("hashingtf", "HashingTF", "feature.hashingtf.HashingTF", {"binary": False},
     "RandomStringArrayGenerator",
     {"seed": 2, "arraySize": 10, "colNames": [["input"]], "numValues": ROW_CAP}),
    ("idf", "IDF", "feature.idf.IDF", {"minDocFreq": 0},
     "DenseVectorGenerator",
     {"seed": 2, "colNames": [["input"]], "numValues": ROW_CAP, "vectorDim": 10}),
    ("imputer", "Imputer", "feature.imputer.Imputer",
     {"inputCols": _F15, "outputCols": _OUT15},
     "DoubleGenerator",
     {"colNames": [_F15], "seed": 2, "arity": 100, "numValues": ROW_CAP}),
    ("interaction", "Interaction", "feature.interaction.Interaction",
     {"inputCols": _F5},
     "DoubleGenerator", {"colNames": [_F5], "seed": 2, "numValues": ROW_CAP}),
    ("kbinsdiscretizer", "KBinsDiscretizer", "feature.kbinsdiscretizer.KBinsDiscretizer",
     {"strategy": "uniform", "numBins": 5},
     "DenseVectorGenerator",
     {"seed": 2, "colNames": [["input"]], "numValues": ROW_CAP, "vectorDim": 10}),
    ("kmeans", "KMeans", "clustering.kmeans.KMeans", {"maxIter": 10, "k": 10},
     "DenseVectorGenerator",
     {"seed": 2, "colNames": [["features"]], "numValues": ROW_CAP, "vectorDim": 100}),
    ("linearregression", "LinearRegression",
     "regression.linearregression.LinearRegression",
     {"maxIter": 20, "reg": 0.0, "elasticNet": 0.0, "learningRate": 0.1,
      "globalBatchSize": ROW_CAP, "tol": 1e-06},
     "LabeledPointWithWeightGenerator",
     {"colNames": [["features", "label", "weight"]], "featureArity": 0,
      "labelArity": 10, "numValues": ROW_CAP, "vectorDim": 100}),
    ("linearsvc", "LinearSVC", "classification.linearsvc.LinearSVC",
     {"maxIter": 20, "reg": 0.0, "elasticNet": 0.0, "learningRate": 0.1,
      "globalBatchSize": ROW_CAP, "tol": 1e-06},
     "LabeledPointWithWeightGenerator",
     {"colNames": [["features", "label", "weight"]], "featureArity": 0,
      "labelArity": 2, "numValues": ROW_CAP, "vectorDim": 100}),
    ("logisticregression", "LogisticRegression",
     "classification.logisticregression.LogisticRegression",
     {"maxIter": 20, "reg": 0.0, "elasticNet": 0.0, "learningRate": 0.1,
      "globalBatchSize": ROW_CAP, "tol": 1e-06},
     "LabeledPointWithWeightGenerator",
     {"colNames": [["features", "label", "weight"]], "featureArity": 0,
      "labelArity": 2, "numValues": ROW_CAP, "vectorDim": 100}),
    # Beyond the reference's 35: the throughput-mode MLP serving shape
    # (BENCH mlp_serving_throughput / mlp_forward's 256->512->512->8 network)
    # reproducible from the benchmark CLI alone — fit + batch transform at the
    # served architecture (VERDICT r6 item 8).
    ("mlpclassifier", "MLPClassifier", "classification.mlp_classifier.MLPClassifier",
     {"hiddenLayers": [512, 512], "maxIter": 10, "globalBatchSize": 4096},
     "LabeledPointWithWeightGenerator",
     {"colNames": [["features", "label", "weight"]], "featureArity": 0,
      "labelArity": 8, "numValues": ROW_CAP, "vectorDim": 256}),
    ("maxabsscaler", "MaxAbsScaler", "feature.maxabsscaler.MaxAbsScaler", {},
     "DenseVectorGenerator",
     {"vectorDim": 100, "colNames": [["input"]], "seed": 2, "numValues": ROW_CAP}),
    ("minmaxscaler", "MinMaxScaler", "feature.minmaxscaler.MinMaxScaler", {},
     "DenseVectorGenerator",
     {"vectorDim": 100, "colNames": [["input"]], "seed": 2, "numValues": ROW_CAP}),
    ("naivebayes", "NaiveBayes", "classification.naivebayes.NaiveBayes", {},
     "LabeledPointWithWeightGenerator",
     {"colNames": [["features", "label", "weight"]], "featureArity": 20,
      "labelArity": 10, "numValues": ROW_CAP, "vectorDim": 100}),
    ("ngram", "NGram", "feature.ngram.NGram", {},
     "RandomStringArrayGenerator",
     {"seed": 2, "arraySize": 10, "colNames": [["input"]], "numValues": ROW_CAP}),
    ("normalizer", "Normalizer", "feature.normalizer.Normalizer", {"p": 2.0},
     "DenseVectorGenerator",
     {"vectorDim": 5, "colNames": [["input"]], "seed": 2, "numValues": ROW_CAP}),
    ("onehotencoder", "OneHotEncoder", "feature.onehotencoder.OneHotEncoder",
     {"inputCols": ["input"], "outputCols": ["output"]},
     "DoubleGenerator",
     {"colNames": [["input"]], "arity": 10, "numValues": ROW_CAP}),
    ("polynomialexpansion", "PolynomialExpansion",
     "feature.polynomialexpansion.PolynomialExpansion", {"degree": 2},
     "DenseVectorGenerator",
     {"vectorDim": 5, "colNames": [["input"]], "seed": 2, "numValues": ROW_CAP}),
    ("regextokenizer", "RegexTokenizer", "feature.regextokenizer.RegexTokenizer",
     {"pattern": "1+"},
     "RandomStringGenerator",
     {"seed": 2, "numDistinctValues": 100, "colNames": [["input"]],
      "numValues": ROW_CAP}),
    ("robustscaler", "RobustScaler", "feature.robustscaler.RobustScaler",
     {"withCentering": True, "withScaling": True},
     "DenseVectorGenerator",
     {"vectorDim": 100, "colNames": [["input"]], "seed": 2, "numValues": ROW_CAP}),
    ("sqltransformer", "SQLTransformer", "feature.sqltransformer.SQLTransformer",
     {"statement": "SELECT *, ABS(v1) AS v2 FROM __THIS__"},
     "DoubleGenerator", {"colNames": [["v1"]], "seed": 2, "numValues": ROW_CAP}),
    ("standardscaler", "StandardScaler", "feature.standardscaler.StandardScaler",
     {"withMean": True, "withStd": True},
     "DenseVectorGenerator",
     {"vectorDim": 100, "colNames": [["input"]], "seed": 2, "numValues": ROW_CAP}),
    ("stopwordsremover", "StopWordsRemover", "feature.stopwordsremover.StopWordsRemover",
     {"inputCols": ["input"], "outputCols": ["output"]},
     "RandomStringArrayGenerator",
     {"colNames": [["input"]], "seed": 2, "numValues": 20_000,
      "numDistinctValues": 100, "arraySize": 100}),
    ("stringindexer", "StringIndexer", "feature.stringindexer.StringIndexer",
     {"outputCols": ["outputCol0"], "handleInvalid": "skip", "inputCols": ["col0"],
      "stringOrderType": "arbitrary"},
     "RandomStringGenerator",
     {"colNames": [["col0"]], "seed": 2, "numValues": ROW_CAP,
      "numDistinctValues": 100}),
    ("tokenizer", "Tokenizer", "feature.tokenizer.Tokenizer", {},
     "RandomStringGenerator",
     {"seed": 2, "numDistinctValues": 100, "colNames": [["input"]],
      "numValues": ROW_CAP}),
    ("univariatefeatureselector", "UnivariateFeatureSelector",
     "feature.univariatefeatureselector.UnivariateFeatureSelector",
     {"featuresCol": "features", "labelCol": "label", "featureType": "continuous",
      "labelType": "categorical"},
     "LabeledPointWithWeightGenerator",
     {"colNames": [["features", "label", "weight"]], "labelArity": 10,
      "numValues": ROW_CAP, "vectorDim": 100}),
    ("variancethresholdselector", "VarianceThresholdSelector",
     "feature.variancethresholdselector.VarianceThresholdSelector", {},
     "DenseVectorGenerator",
     {"vectorDim": 100, "colNames": [["input"]], "seed": 2, "numValues": ROW_CAP}),
    ("vectorassembler", "VectorAssembler", "feature.vectorassembler.VectorAssembler",
     {"outputCol": "outputCol", "inputCols": _F15},
     "DoubleGenerator", {"colNames": [_F15], "seed": 2, "numValues": ROW_CAP}),
    ("vectorindexer", "VectorIndexer", "feature.vectorindexer.VectorIndexer",
     {"maxCategories": 20, "handleInvalid": "skip"},
     "DenseVectorGenerator",
     {"seed": 2, "colNames": [["input"]], "numValues": ROW_CAP, "vectorDim": 10}),
    ("vectorslicer", "VectorSlicer", "feature.vectorslicer.VectorSlicer",
     {"indices": [1, 3, 5, 7]},
     "DenseVectorGenerator",
     {"vectorDim": 10, "colNames": [["input"]], "seed": 2, "numValues": ROW_CAP}),
]

_PREFIX = "org.apache.flink.ml."
_GEN_PREFIX = "org.apache.flink.ml.benchmark.datagenerator.common."


def build_configs() -> dict:
    """{file name: config dict} for the whole suite."""
    out = {}
    for fname, entry, stage_cls, stage_params, gen_cls, gen_params in TABLE:
        config = {"version": 1, entry: {
            "stage": {"className": _PREFIX + stage_cls},
            "inputData": {
                "className": _GEN_PREFIX + gen_cls,
                "paramMap": gen_params,
            },
        }}
        if stage_params:
            config[entry]["stage"]["paramMap"] = stage_params
        out[f"{fname}-benchmark.json"] = config
    return out


def main(out_dir: str) -> None:
    os.makedirs(out_dir, exist_ok=True)
    for fname, config in build_configs().items():
        with open(os.path.join(out_dir, fname), "w") as f:
            json.dump(config, f, indent=2)
            f.write("\n")
    print(f"wrote {len(TABLE)} configs to {out_dir}")


if __name__ == "__main__":
    main(
        sys.argv[1]
        if len(sys.argv) > 1
        else os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "flink_ml_tpu", "benchmark", "configs",
        )
    )
