#!/usr/bin/env python
"""CI precision smoke: the low-precision serving tier end to end.

Publishes the same trained pipeline twice — v-1 plain f32 and v-2 with
post-training int8 weight quantization (``publish_servable(...,
precision="int8")``, manifest audited) — then serves a burst through every
precision tier and checks (any failure exits 1):

- ZERO ``ml.serving.fastpath.compiles`` after warmup in EACH tier (f32,
  bf16, int8) — warmup coverage includes the lowp plan AND its warm f32
  fallback twin;
- f32-tier responses are bit-identical per row to the per-stage reference
  transform (the precision axis must not perturb the default path);
- bf16-tier responses stay inside the documented cross-tier deviation
  envelope (``PRECISION_TIER_DEVIATION['scale_logistic']``,
  docs/precision.md) with the class labels unmoved;
- a drift regression injected mid-burst (a DriftMonitor verdict on scored
  tail traffic) triggers the automatic fallback to the WARM f32 plan of the
  same version: every in-flight and subsequent request resolves exactly
  once, zero compiles appear, and post-fallback answers are bit-identical
  to the f32 tier's.

Driven by tools/ci/run_tests.sh after the fusion smoke.
"""
from __future__ import annotations

import os
import sys
import tempfile
from concurrent.futures import ThreadPoolExecutor

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def main() -> int:
    import numpy as np

    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.loop import DriftMonitor, auc
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.models.classification.logistic_regression import (
        LogisticRegression,
    )
    from flink_ml_tpu.servable.api import load_servable
    from flink_ml_tpu.servable.precision import (
        PRECISION_MANIFEST,
        PRECISION_TIER_DEVIATION,
        tier_ulp_diff,
    )
    from flink_ml_tpu.serving import InferenceServer, ServingConfig
    from flink_ml_tpu.serving.registry import publish_servable

    dim = 32
    rng = np.random.default_rng(23)
    X = rng.normal(size=(96, dim))
    y = (X @ np.linspace(1.0, -1.0, dim) > 0).astype(np.float64)
    train = DataFrame.from_dict({"features": X, "label": y})
    model = LogisticRegression().set_max_iter(10).set_global_batch_size(96).fit(train)

    burst = DataFrame.from_dict({"features": rng.normal(size=(4, dim))})
    template = burst.take([0])

    with tempfile.TemporaryDirectory() as registry:
        # --- publish: v-1 f32, v-2 int8 (quantization at publish ONLY) -----
        p_f32 = publish_servable(model, registry)
        p_int8 = publish_servable(model, registry, precision="int8")
        if os.path.exists(os.path.join(p_f32, PRECISION_MANIFEST)):
            print("FAIL: the f32 artifact grew a precision manifest")
            return 1
        if not os.path.exists(os.path.join(p_int8, PRECISION_MANIFEST)):
            print("FAIL: the int8 artifact has no precision manifest")
            return 1

        reference = load_servable(p_f32)
        ref_out = reference.transform(burst)

        # --- serve a burst per tier: zero post-warmup compiles each --------
        tier_outs = {}
        for mode, artifact in (("f32", p_f32), ("bf16", p_f32), ("int8", p_int8)):
            servable = load_servable(artifact)
            with InferenceServer(
                servable,
                name=f"precision-smoke-{mode}",
                serving_config=ServingConfig(max_delay_ms=0.1, precision_mode=mode),
                warmup_template=template,
            ) as server:
                before = metrics.get(server.scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0)
                outs = [server.predict(burst) for _ in range(16)]
                compiles = (
                    metrics.get(server.scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0)
                    - before
                )
                if compiles:
                    print(
                        f"FAIL: {compiles} fast-path compiles after warmup in "
                        f"precision.mode={mode}"
                    )
                    return 1
                tier_outs[mode] = outs[0].dataframe

        # f32 bit-exact vs the per-stage reference
        for col in ("prediction", "rawPrediction"):
            if not np.array_equal(
                np.asarray(tier_outs["f32"].column(col)), np.asarray(ref_out.column(col))
            ):
                print(f"FAIL: f32 tier not bit-identical on {col}")
                return 1
        # bf16 inside the documented cross-tier envelope, labels unmoved
        envelope = PRECISION_TIER_DEVIATION[("scale_logistic", "bf16")]
        moved = tier_ulp_diff(
            tier_outs["f32"].column("rawPrediction"),
            tier_outs["bf16"].column("rawPrediction"),
        )
        if moved > envelope:
            print(f"FAIL: bf16 tier moved {moved} ulps (envelope {envelope})")
            return 1
        if not np.array_equal(
            np.asarray(tier_outs["f32"].column("prediction")),
            np.asarray(tier_outs["bf16"].column("prediction")),
        ):
            print("FAIL: bf16 tier flipped a class label on the burst")
            return 1
        # int8 (quantized weights + bf16 transport): labels still agree
        agree = np.mean(
            np.asarray(tier_outs["f32"].column("prediction"))
            == np.asarray(tier_outs["int8"].column("prediction"))
        )
        if agree < 1.0:
            print(f"FAIL: int8 tier label agreement {agree:.2%} on the burst")
            return 1

        # --- drift regression mid-burst -> automatic f32 fallback ----------
        servable = load_servable(p_f32)
        with InferenceServer(
            servable,
            name="precision-smoke-drift",
            # one request per device batch (no cross-request coalescing), so
            # every response is bucket-4 and bit-comparable against the two
            # tiers' reference answers
            serving_config=ServingConfig(
                max_batch_size=4, max_delay_ms=0.1, precision_mode="bf16"
            ),
            warmup_template=template,
        ) as server:
            scope = server.scope
            before = metrics.get(scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0)
            bf16_head = np.asarray(server.predict(burst).dataframe.column("rawPrediction"))

            # the injected regression: a healthy baseline window, then scored
            # tail traffic collapsing to chance — the DriftMonitor verdict is
            # the trigger, exactly as the continuous loop wires it
            monitor = DriftMonitor(
                window=2, rel_threshold=0.2, min_scores=1,
                higher_is_better=True, scope=scope,
            )
            monitor.observe(0, auc(y, y))  # baseline version: perfect tail AUC
            monitor.observe(1, 0.5)  # live version: chance — regressed
            if not monitor.regressed(1, 0):
                print("FAIL: injected drift did not produce a regressed verdict")
                return 1

            results = []
            with ThreadPoolExecutor(max_workers=4) as pool:
                futures = [pool.submit(server.predict, burst) for _ in range(16)]
                # flip mid-burst, while requests are in flight
                if not server.precision_fallback("drift"):
                    print("FAIL: precision_fallback did not engage")
                    return 1
                futures += [pool.submit(server.predict, burst) for _ in range(16)]
                for f in futures:
                    results.append(f.result())  # raises -> CI fail

            if len(results) != 32 or any(len(r.dataframe) != len(burst) for r in results):
                print("FAIL: a burst request was lost or truncated across the fallback")
                return 1
            compiles = (
                metrics.get(scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0) - before
            )
            if compiles:
                print(f"FAIL: {compiles} compiles appeared across the fallback flip")
                return 1
            if not server.precision_fallback_active:
                print("FAIL: fallback did not stay active")
                return 1
            # every response is one tier or the other, bit-for-bit; once the
            # flip settled, responses are the f32 tier's
            f32_head = np.asarray(tier_outs["f32"].column("rawPrediction"))
            for r in results:
                head = np.asarray(r.dataframe.column("rawPrediction"))
                if not (np.array_equal(head, bf16_head) or np.array_equal(head, f32_head)):
                    print("FAIL: a mid-burst response matches neither tier bit-for-bit")
                    return 1
            post = np.asarray(server.predict(burst).dataframe.column("rawPrediction"))
            if not np.array_equal(post, f32_head):
                print("FAIL: post-fallback answers are not the f32 tier's")
                return 1
            if metrics.get(scope, MLMetrics.PRECISION_FALLBACKS) != 1:
                print("FAIL: fallback counter != 1")
                return 1

    print(
        "precision smoke OK: f32/int8 published, all tiers warm-covered "
        "(0 compiles), f32 bit-identical, bf16 inside the deviation envelope, "
        "drift fallback landed on the warm f32 plan mid-burst with every "
        "request resolved exactly once"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
