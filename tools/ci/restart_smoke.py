#!/usr/bin/env python
"""restart_smoke — hard-kill → zero-compile resume from the persistent plan
cache, across a REAL process boundary (docs/plancache.md).

Incarnation 1 builds the serving head (scaler → logistic, fixed seeds),
AOT-warms every bucket — populating the plan cache — serves one request per
bucket, records the raw response bytes, then dies by ``os._exit(1)`` (a hard
kill: no atexit, no graceful close — the supervisor-restart analogue).

A second **sparse leg** (docs/sparse.md) does the same for the sparse
calling convention: an IDF → logistic servable chain over SparseVector
features, warmed across the nnz-cap ladder (caps 1/2/4), served at every
rung — its segment executables (values/ids/nnz triple programs) must
serialize and restore through the same plan cache.

Incarnation 2 starts over the same cache directory with the chain executor's
ONE XLA-compile seam (``servable.planner._compile_lowered``) poisoned to
raise. It must warm every bucket — dense AND every sparse (bucket, nnz-cap)
rung — and answer every request purely from the serialized executables:

- zero plan-cache misses and zero serving-path compiles (the counters), the
  poisoned seam never reached (the hard proof);
- every response bit-identical to incarnation 1's recorded bytes;
- inside the smoke deadline — the O(load)-not-O(XLA) cold-start contract.

Run: ``python tools/ci/restart_smoke.py`` (wired into tools/ci/run_tests.sh).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

#: Wall-clock bound on the RESUMING incarnation (spawn → exit, jax import
#: included). Generous for a loaded 1-core CI box — the point is O(load)
#: cold start, not a microbenchmark; the per-phase timings print below.
RESUME_DEADLINE_S = 120.0

DIM = 24
BUCKET_CAP = 16  # buckets 1/2/4/8/16


def _build_servable():
    import numpy as np

    from flink_ml_tpu.servable import (
        LogisticRegressionModelServable,
        PipelineModelServable,
        StandardScalerModelServable,
    )

    rng = np.random.default_rng(42)
    sc = StandardScalerModelServable().set_input_col("features").set_output_col("scaled")
    sc.mean = rng.normal(size=DIM)
    sc.std = np.abs(rng.normal(size=DIM)) + 0.5
    sc.set_with_mean(True)
    lr = LogisticRegressionModelServable().set_features_col("scaled")
    lr.coefficient = rng.normal(size=DIM)
    return PipelineModelServable([sc, lr])


def _requests():
    import numpy as np

    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.serving import power_of_two_buckets

    rng = np.random.default_rng(7)
    out = []
    for bucket in power_of_two_buckets(BUCKET_CAP):
        out.append(
            (bucket, DataFrame.from_dict({"features": rng.normal(size=(bucket, DIM))}))
        )
    return out


def _serve_all(workdir: str, incarnation: int):
    import numpy as np

    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.config import Options, config
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.serving import InferenceServer, ServingConfig

    config.set(Options.PLANCACHE_DIR, os.path.join(workdir, "plancache"))
    template = DataFrame.from_dict(
        {"features": np.random.default_rng(3).normal(size=(1, DIM))}
    )
    t0 = time.perf_counter()
    server = InferenceServer(
        _build_servable(),
        name=f"restart-smoke-{incarnation}",
        serving_config=ServingConfig(max_batch_size=BUCKET_CAP, max_delay_ms=0.1),
        warmup_template=template,
    )
    responses = {}
    first_response_s = None
    for bucket, df in _requests():
        r = server.predict(df)
        if first_response_s is None:
            first_response_s = time.perf_counter() - t0
        assert r.bucket == bucket, f"request of {bucket} rows ran at bucket {r.bucket}"
        raw = np.asarray(
            [np.asarray(v, np.float64) for v in r.dataframe.column("rawPrediction")]
        )
        pred = np.asarray(r.dataframe.column("prediction"), np.float64)
        responses[str(bucket)] = (raw, pred)
    stats = {
        "publish_to_first_response_s": round(first_response_s, 3),
        "warmup_compile_ms": metrics.get(
            server.scope, MLMetrics.SERVING_WARMUP_COMPILE_MS, 0.0
        ),
        "warmup_cache_load_ms": metrics.get(
            server.scope, MLMetrics.SERVING_WARMUP_CACHE_LOAD_MS, 0.0
        ),
        "serving_path_compiles": metrics.get(
            server.scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0
        ),
        "plancache": dict(metrics.scope(MLMetrics.PLANCACHE_GROUP)),
    }
    stats["plancache"].pop("ml.plancache.load.ms", None)  # histogram: not JSON
    return server, responses, stats


SPARSE_DIM = 20
SPARSE_CAPS = "1,2,4"


def _build_sparse_servable():
    import numpy as np

    from flink_ml_tpu.models.feature.idf import IDFModel
    from flink_ml_tpu.servable import (
        LogisticRegressionModelServable,
        PipelineModelServable,
    )

    rng = np.random.default_rng(17)
    idf_m = IDFModel().set_input_col("features").set_output_col("scaled")
    idf_m.idf = np.abs(rng.standard_normal(SPARSE_DIM))
    idf_m.doc_freq = np.ones(SPARSE_DIM)
    idf_m.num_docs = np.asarray([8])
    lr = (
        LogisticRegressionModelServable()
        .set_features_col("scaled")
        .set_prediction_col("pred")
        .set_raw_prediction_col("raw")
    )
    lr.coefficient = rng.standard_normal(SPARSE_DIM).astype(np.float32)
    return PipelineModelServable([idf_m, lr])


def _sparse_rows(n, max_nnz, seed):
    import numpy as np

    from flink_ml_tpu.linalg.vectors import SparseVector

    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n):
        k = int(rng.integers(0, max_nnz + 1))
        idx = np.sort(rng.choice(SPARSE_DIM, size=k, replace=False))
        rows.append(SparseVector(SPARSE_DIM, idx, rng.standard_normal(k)))
    return rows


def _serve_sparse(workdir: str, incarnation: int):
    """The sparse leg: one request per nnz-cap rung, compiled chains keyed
    (bucket, cap) and — on resume — loaded, never compiled."""
    import numpy as np

    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.config import Options, config
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.serving import InferenceServer, ServingConfig

    config.set(Options.PLANCACHE_DIR, os.path.join(workdir, "plancache"))
    config.set(Options.SPARSE_WARMUP_CAPS, SPARSE_CAPS)
    config.set(Options.SPARSE_NNZ_CAP_MAX, 4)
    template = DataFrame.from_dict({"features": _sparse_rows(1, 2, seed=5)})
    server = InferenceServer(
        _build_sparse_servable(),
        name=f"restart-smoke-sparse-{incarnation}",
        serving_config=ServingConfig(max_batch_size=8, max_delay_ms=0.1),
        warmup_template=template,
    )
    responses = {}
    for max_nnz in (1, 2, 4):
        df = DataFrame.from_dict({"features": _sparse_rows(8, max_nnz, seed=max_nnz)})
        r = server.predict(df)
        raw = np.asarray(
            [np.asarray(v, np.float64) for v in r.dataframe.column("raw")]
        )
        pred = np.asarray(r.dataframe.column("pred"), np.float64)
        responses[f"sparse{max_nnz}"] = (raw, pred)
    compiles = metrics.get(server.scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0)
    fused = metrics.get(server.scope, MLMetrics.SERVING_FUSED_BATCHES, 0)
    assert fused == len(responses), (
        f"sparse leg served {fused} fused batches, expected {len(responses)} — "
        "sparse traffic fell off the fast path"
    )
    assert compiles == 0, f"sparse leg compiled on the serving path: {compiles}"
    return server, responses


def incarnation_1(workdir: str) -> None:
    import numpy as np

    _server, responses, stats = _serve_all(workdir, 1)
    _sserver, sresponses = _serve_sparse(workdir, 1)
    responses.update(sresponses)
    np.savez(
        os.path.join(workdir, "responses1.npz"),
        **{
            f"{k}.{part}": arr
            for k, (raw, pred) in responses.items()
            for part, arr in (("raw", raw), ("pred", pred))
        },
    )
    assert stats["plancache"].get("ml.plancache.stores", 0) > 0, (
        "incarnation 1 stored nothing — the cache never engaged"
    )
    with open(os.path.join(workdir, "inc1.json"), "w") as f:
        json.dump(stats, f)
    print(f"[inc1] served {len(responses)} buckets, stats: {stats}", flush=True)
    # Hard kill: no drain, no close, no atexit — the supervisor-kill shape.
    os._exit(1)


def incarnation_2(workdir: str) -> None:
    import numpy as np

    import flink_ml_tpu.servable.planner as planner

    def blocked(lowered):
        raise AssertionError(
            "XLA compile reached in the resuming incarnation — the plan "
            "cache failed the zero-compile-resume contract"
        )

    planner._compile_lowered = blocked

    server, responses, stats = _serve_all(workdir, 2)
    sserver, sresponses = _serve_sparse(workdir, 2)
    responses.update(sresponses)
    saved = np.load(os.path.join(workdir, "responses1.npz"))
    for key, (raw, pred) in responses.items():
        assert np.array_equal(saved[f"{key}.raw"], raw), f"bucket {key}: raw differs"
        assert np.array_equal(saved[f"{key}.pred"], pred), f"bucket {key}: pred differs"
    assert stats["serving_path_compiles"] == 0, stats
    pc = stats["plancache"]
    assert pc.get("ml.plancache.misses", 0) == 0, f"live compiles on resume: {pc}"
    assert pc.get("ml.plancache.quarantined", 0) == 0, pc
    assert pc.get("ml.plancache.hits", 0) > 0, pc
    server.close()
    sserver.close()
    with open(os.path.join(workdir, "inc2.json"), "w") as f:
        json.dump(stats, f)
    print(
        f"[inc2] zero-compile resume OK: {len(responses)} buckets bit-identical, "
        f"stats: {stats}",
        flush=True,
    )


def main() -> int:
    import tempfile

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    with tempfile.TemporaryDirectory(prefix="restart-smoke-") as workdir:
        print("=== incarnation 1: compile, serve, populate cache, hard-kill ===")
        p1 = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--incarnation", "1", workdir],
            env=env,
            timeout=600,
        )
        if p1.returncode != 1 or not os.path.exists(os.path.join(workdir, "inc1.json")):
            print(f"FAIL: incarnation 1 rc={p1.returncode} (expected the hard-kill 1)")
            return 1
        print("=== incarnation 2: resume with the XLA compile seam poisoned ===")
        t0 = time.perf_counter()
        p2 = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--incarnation", "2", workdir],
            env=env,
            timeout=600,
        )
        resume_wall = time.perf_counter() - t0
        if p2.returncode != 0 or not os.path.exists(os.path.join(workdir, "inc2.json")):
            print(f"FAIL: incarnation 2 rc={p2.returncode}")
            return 1
        if resume_wall > RESUME_DEADLINE_S:
            print(
                f"FAIL: resume took {resume_wall:.1f}s > deadline {RESUME_DEADLINE_S}s"
            )
            return 1
        with open(os.path.join(workdir, "inc1.json")) as f:
            s1 = json.load(f)
        with open(os.path.join(workdir, "inc2.json")) as f:
            s2 = json.load(f)
        print(
            f"restart_smoke OK: resume wall {resume_wall:.1f}s "
            f"(deadline {RESUME_DEADLINE_S:.0f}s); publish->first-response "
            f"{s1['publish_to_first_response_s']}s cold vs "
            f"{s2['publish_to_first_response_s']}s warm; warm split "
            f"compile {s2['warmup_compile_ms']:.1f}ms / "
            f"cache {s2['warmup_cache_load_ms']:.1f}ms"
        )
    return 0


if __name__ == "__main__":
    if "--incarnation" in sys.argv:
        idx = sys.argv.index("--incarnation")
        which, workdir = sys.argv[idx + 1], sys.argv[idx + 2]
        (incarnation_1 if which == "1" else incarnation_2)(workdir)
        sys.exit(0)
    sys.exit(main())
