#!/usr/bin/env python
"""retrieval_smoke — the retrieval tier end to end (docs/retrieval.md).

The scenario:

1. Distill two swing ``CandidateIndex`` versions (same catalog, different
   similarity tables) and publish both through the standard registry
   (``publish_servable`` → ``v-1``/``v-2`` — the model-version machinery,
   unchanged).
2. ``load_servable(v-1)`` → ``InferenceServer`` with a retrieval warmup
   template: the sparse nnz ladder × the K rung ladder AOT-warms up front.
3. Drive a concurrent top-K burst through ``RetrievalClient`` with mixed
   per-request K, and hot-swap to v-2 **mid-burst**.
4. Assert: every request resolved exactly once, each answer is bit-exact
   (ids AND scores) against a plain-numpy reference for whichever index
   version served it, every answer respects its request's K, and traffic
   never XLA-compiles — zero fast-path compiles outside the two warmup
   windows (boot and swap).

Run: ``python tools/ci/retrieval_smoke.py`` (wired into tools/ci/run_tests.sh).
"""
from __future__ import annotations

import os
import shutil
import sys
import tempfile
import threading

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

N_ITEMS = 120
BURST_THREADS = 4
QUERIES_PER_THREAD = 8
KS = (3, 10, 16)  # mixed per-request K: rungs 4 and 16, both warmed


def _swing_index(seed):
    import numpy as np

    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.retrieval import CandidateIndex

    rng = np.random.default_rng(seed)
    items = np.arange(1000, 1000 + N_ITEMS, dtype=np.int64)
    encs = []
    for it in items:
        nbrs = rng.choice(np.setdiff1d(items, [it]), size=6, replace=False)
        scores = rng.random(6).round(4)
        encs.append(";".join(f"{n},{s}" for n, s in zip(nbrs, scores)))
    idx = CandidateIndex.from_swing_output(
        DataFrame(["item", "output"], None, [items, encs]),
        item_col="item",
        output_col="output",
    )
    idx.set_output_col("rec")
    return idx


def _histories(idx, n, seed):
    import numpy as np

    rng = np.random.default_rng(seed)
    return [
        [
            (int(idx.item_ids[rng.integers(0, N_ITEMS)]), float(rng.random()) + 0.1)
            for _ in range(rng.integers(1, 5))
        ]
        for _ in range(n)
    ]


def _reference(idx, history, k):
    """Plain-numpy mirror of the fused swing kernel: f32 scatter-add in
    sorted-row slot order, consumed rows masked, stable descending sort."""
    import numpy as np

    vocab = idx.item_ids
    simv = np.asarray(idx.arrays["sim_values"], np.float32)
    simi = np.asarray(idx.arrays["sim_ids"], np.int64)
    row_of = {int(v): r for r, v in enumerate(vocab)}
    scores = np.zeros(len(vocab), np.float32)
    hit = np.zeros(len(vocab), bool)
    agg = {}
    for item, w in history:
        r = row_of.get(int(item))
        if r is not None:
            agg[r] = agg.get(r, 0.0) + w
    for r in sorted(agg):
        hit[r] = True
        for j in range(simv.shape[1]):
            if simv[r, j] != 0.0:
                scores[simi[r, j]] += np.float32(np.float32(agg[r]) * simv[r, j])
    out = scores.astype(np.float64)
    out[hit] = -np.inf
    order = np.argsort(-out, kind="stable")[:k]
    keep = np.isfinite(out[order])
    return vocab[order[keep]], out[order[keep]]


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.config import Options, config
    from flink_ml_tpu.linalg.vectors import SparseVector
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.retrieval import CandidateIndex, RetrievalClient
    from flink_ml_tpu.servable.api import load_servable
    from flink_ml_tpu.servable.shapes import k_rung
    from flink_ml_tpu.serving import InferenceServer, ServingConfig, publish_servable

    failed = []

    def check(ok, msg):
        print(("  OK  " if ok else "  FAIL") + f" {msg}", flush=True)
        if not ok:
            failed.append(msg)

    workdir = tempfile.mkdtemp(prefix="retrieval-smoke-")
    publish_dir = os.path.join(workdir, "publish")
    # Executables key on (bucket, nnz cap, K rung); traffic is single-row
    # requests with 1-4 history items, so warm the FULL cap ladder (unset
    # warmup.caps = every power of two up to the max) and both K rungs.
    config.set(Options.SPARSE_NNZ_CAP_MAX, 4)
    config.set(Options.RETRIEVAL_WARMUP_KS, "4,16")
    config.set(Options.RETRIEVAL_K_CAP_MAX, 16)

    print("=== retrieval_smoke: publishing index v-1 and v-2 ===", flush=True)
    v1_idx, v2_idx = _swing_index(seed=1), _swing_index(seed=2)
    p1 = publish_servable(v1_idx, publish_dir)
    p2 = publish_servable(v2_idx, publish_dir)
    check(
        os.path.basename(p1) == "v-1" and os.path.basename(p2) == "v-2",
        f"indices published through the registry ({p1}, {p2})",
    )
    indices = {1: v1_idx, 2: v2_idx}

    template = DataFrame(
        ["history", "k"],
        None,
        [
            [
                SparseVector(
                    N_ITEMS, np.asarray([0, 3], np.int64), np.asarray([1.0, 2.0])
                )
            ],
            np.asarray([10], np.int64),
        ],
    )
    scope = "ml.serving[retrieval-smoke]"
    cfg = ServingConfig(max_batch_size=8, max_delay_ms=0.5)
    print("=== serving v-1: warmup = nnz ladder x K rung ladder ===", flush=True)
    with InferenceServer(
        load_servable(p1),
        name="retrieval-smoke",
        serving_config=cfg,
        warmup_template=template,
    ) as server:

        class _Recorder:
            """predict() shim that pins each reply to the version it rode."""

            def __init__(self):
                self.lock = threading.Lock()

            def predict(self, df, shape_key=None, **kw):
                return server.predict(df, shape_key=shape_key, **kw)

        recorder = _Recorder()
        results = []  # (history, k, version, ids, scores)
        errors = []
        results_lock = threading.Lock()
        swap_gate = threading.Barrier(BURST_THREADS + 1)

        def burst(tid):
            client = RetrievalClient(recorder, v1_idx)
            histories = _histories(v1_idx, QUERIES_PER_THREAD, seed=100 + tid)
            try:
                for qi, hist in enumerate(histories):
                    if qi == QUERIES_PER_THREAD // 2:
                        swap_gate.wait()  # let the swap land mid-burst
                        swap_gate.wait()
                    k = KS[(tid + qi) % len(KS)]
                    df = client._request_frame([client.history_vector(hist)],
                                               np.asarray([k], np.int64))
                    resp = recorder.predict(df, shape_key=f"k{k_rung(k)}")
                    (ids, scores), = client._trim(resp.dataframe,
                                                  np.asarray([k], np.int64))
                    with results_lock:
                        results.append((hist, k, resp.model_version, ids, scores))
            except Exception as exc:  # noqa: BLE001 — smoke surfaces everything
                with results_lock:
                    errors.append(exc)
                # don't deadlock the swap gate on failure
                swap_gate.abort()

        compiles_boot = metrics.get(scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0)
        threads = [
            threading.Thread(target=burst, args=(t,)) for t in range(BURST_THREADS)
        ]
        print(
            f"=== burst: {BURST_THREADS} threads x {QUERIES_PER_THREAD} queries, "
            f"K in {KS}, swap to v-2 mid-burst ===",
            flush=True,
        )
        for t in threads:
            t.start()
        try:
            swap_gate.wait()  # all threads paused at their midpoint
            compiles_pre_swap = metrics.get(
                scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0
            )
            server.swap(2, load_servable(p2))
            compiles_post_swap = metrics.get(
                scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0
            )
            swap_gate.wait()  # release the second half of the burst
        except threading.BrokenBarrierError:
            pass
        for t in threads:
            t.join()
        compiles_end = metrics.get(scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0)

        check(not errors, f"every request resolved typed ({errors[:3]})")
        expected_n = BURST_THREADS * QUERIES_PER_THREAD
        check(
            len(results) == expected_n,
            f"every request resolved exactly once ({len(results)}/{expected_n})",
        )
        versions = sorted({v for _, _, v, _, _ in results})
        check(versions == [1, 2], f"both versions served across the swap ({versions})")

        mismatches = 0
        over_k = 0
        for hist, k, version, ids, scores in results:
            rid, rsc = _reference(indices[version], hist, k)
            if len(ids) > k:
                over_k += 1
            if not (
                np.array_equal(ids, rid)
                and np.array_equal(
                    np.asarray(scores).view(np.int64),
                    np.asarray(rsc).view(np.int64),
                )
            ):
                mismatches += 1
        check(over_k == 0, f"every answer respects its request's K ({over_k} over)")
        check(
            mismatches == 0,
            f"bit-exact ids+scores vs the numpy reference, per served version "
            f"({mismatches}/{len(results)} mismatched)",
        )
        check(
            compiles_pre_swap == compiles_boot,
            f"zero compiles between warmup and swap "
            f"({compiles_pre_swap - compiles_boot})",
        )
        check(
            compiles_end == compiles_post_swap,
            f"zero compiles on post-swap traffic "
            f"({compiles_end - compiles_post_swap})",
        )
        fused = metrics.get(scope, MLMetrics.SERVING_FUSED_BATCHES, 0)
        check(fused > 0, f"traffic rode the fused fast path ({fused} fused batches)")

    for opt in (
        Options.SPARSE_WARMUP_CAPS,
        Options.SPARSE_NNZ_CAP_MAX,
        Options.RETRIEVAL_WARMUP_KS,
        Options.RETRIEVAL_K_CAP_MAX,
    ):
        config.unset(opt)

    if failed:
        print(
            f"retrieval_smoke FAIL ({len(failed)} assertion(s)); workdir kept at "
            f"{workdir}"
        )
        return 1
    shutil.rmtree(workdir, ignore_errors=True)
    print(
        "retrieval_smoke OK: registry-published index served fused, hot-swapped "
        "mid-burst, bit-exact per version, per-request K honored, zero "
        "post-warmup compiles"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
