#!/usr/bin/env python
"""CI fusion smoke: both fusion tiers built and served end to end.

Builds the same published pipeline (scaler → logistic head) under
``fusion.mode=exact`` and ``fusion.mode=fast`` (megakernels forced hot so the
Pallas lowering is on the exercised path), warms each, serves a burst, and
checks (any failure exits 1):

- ZERO ``ml.serving.fastpath.compiles`` after warmup in EACH tier — warmup
  coverage holds for exact programs, cross-reduction fused programs, and
  megakernels alike;
- exact-tier responses are bit-identical per row to the per-stage reference
  transform at the response bucket (the PR 4 contract, unchanged by the
  fusion planner);
- fast-tier responses stay inside the documented ulp envelope of the exact
  tier's (``fusion.ULP_ENVELOPE['scale_logistic']``, docs/fusion.md), and the
  megakernel program counter actually moved — the fast tier really ran the
  hand-fused kernel, not a silent fallback.

Driven by tools/ci/run_tests.sh after the sharded smoke.
"""
from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def main() -> int:
    import numpy as np

    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.servable import (
        LogisticRegressionModelServable,
        PipelineModelServable,
        StandardScalerModelServable,
    )
    from flink_ml_tpu.servable.fusion import ULP_ENVELOPE, ulp_diff
    from flink_ml_tpu.serving import InferenceServer, ServingConfig, pad_to

    dim = 32
    rng = np.random.default_rng(23)
    sc = StandardScalerModelServable().set_input_col("features").set_output_col("scaled")
    sc.set_with_mean(True)
    sc.mean = rng.standard_normal(dim)
    sc.std = np.abs(rng.standard_normal(dim)) + 0.5
    lr = LogisticRegressionModelServable().set_features_col("scaled")
    lr.coefficient = rng.standard_normal(dim)
    reference = PipelineModelServable([sc, lr])

    template = DataFrame.from_dict({"features": rng.standard_normal((1, dim))})
    requests = [
        DataFrame.from_dict({"features": rng.standard_normal((4, dim))})
        for _ in range(16)
    ]

    from flink_ml_tpu.config import Options, config

    config.set(Options.FUSION_MEGAKERNEL_MIN_SCORE, 1.0)  # force megakernels hot
    try:
        results = {}
        for mode in ("exact", "fast"):
            # fresh servable per tier so each carries its own compiled plan
            servable = PipelineModelServable([sc, lr])
            with InferenceServer(
                servable,
                name=f"fusion-smoke-{mode}",
                serving_config=ServingConfig(max_delay_ms=0.1, fusion_mode=mode),
                warmup_template=template,
            ) as server:
                before = metrics.get(server.scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0)
                outs = [server.predict(req) for req in requests]
                compiles = (
                    metrics.get(server.scope, MLMetrics.SERVING_FASTPATH_COMPILES, 0)
                    - before
                )
                if compiles:
                    print(
                        f"FAIL: {compiles} fast-path compiles after warmup in "
                        f"fusion.mode={mode}"
                    )
                    return 1
                results[mode] = outs
            if mode == "fast":
                megas = metrics.get(
                    server.scope, MLMetrics.FUSION_PROGRAMS_MEGAKERNEL, 0
                )
                if not megas:
                    print("FAIL: fast tier never compiled a megakernel program")
                    return 1

        envelope = ULP_ENVELOPE["scale_logistic"]
        for req, exact_out, fast_out in zip(requests, results["exact"], results["fast"]):
            ref = reference.transform(pad_to(req, exact_out.bucket))
            for col in ("prediction", "rawPrediction"):
                got = np.asarray(exact_out.dataframe.column(col))
                want = np.asarray(ref.column(col))[: len(req)]
                if not np.array_equal(got, want):
                    print(f"FAIL: exact tier not bit-identical on {col}")
                    return 1
                moved = ulp_diff(
                    fast_out.dataframe.column(col), exact_out.dataframe.column(col)
                )
                if moved > envelope:
                    print(
                        f"FAIL: fast tier moved {moved} ulps on {col} "
                        f"(envelope {envelope})"
                    )
                    return 1
    finally:
        config.unset(Options.FUSION_MEGAKERNEL_MIN_SCORE)

    print(
        "fusion smoke OK: both tiers warm-covered (0 compiles), exact "
        "bit-identical, fast inside the ulp envelope, megakernels exercised"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
