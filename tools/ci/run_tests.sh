#!/usr/bin/env bash
# CI test controller (ref tools/ci/java_test_controller.sh): runs the whole
# verification surface on the 8-device virtual CPU mesh.
set -euo pipefail

ci_path="$(cd -- "$(dirname "$0")" >/dev/null 2>&1; pwd -P)"
root_path="$(cd "${ci_path}/../.."; pwd -P)"
cd "$root_path"

export JAX_PLATFORMS=cpu
# Collective-rendezvous abort bound (see tests/conftest.py): transient
# starvation on this few-core box survives, a true stall fails fast.
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8 --xla_cpu_collective_call_warn_stuck_timeout_seconds=30 --xla_cpu_collective_call_terminate_timeout_seconds=120"
export PYTHONPATH="${root_path}${PYTHONPATH:+:$PYTHONPATH}"

# Static analysis first: an import-layer leak or lock-order cycle should fail
# the build in seconds, not after the full suite has run.
"${ci_path}/run_static_analysis.sh"

echo "=== unit + integration tests (8-device virtual mesh) ==="
python -m pytest tests/ -q

echo "=== multi-chip dryrun compile check ==="
python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

echo "=== benchmark smoke (demo config) ==="
python -m flink_ml_tpu.benchmark.benchmark \
    flink_ml_tpu/benchmark/benchmark-demo.json \
    --output-file /tmp/ci-bench-results.json
python bin/benchmark-results-visualize.py /tmp/ci-bench-results.json \
    --output /tmp/ci-bench-results.png

# Trace smoke: serve a burst with tracing on, export a Chrome trace, run the
# offline analyzer on it. TRACE_ARTIFACT overrides the export path (the CI
# annotation artifact, mirroring GRAFTCHECK_SARIF).
echo "=== trace smoke (graftscope burst + traceview) ==="
trace_artifact="${TRACE_ARTIFACT:-/tmp/ci-trace.json}"
python tools/ci/trace_smoke.py "${trace_artifact}"
python tools/traceview.py "${trace_artifact}"

# Sharded smoke: publish → warm → serve burst → hot swap on a mesh=4 grid,
# bit-exact vs the per-stage reference with zero serving-path compiles, and
# traceview showing the per-shard attribution section on the exported trace.
echo "=== sharded smoke (mesh=4 fan-out + per-shard traceview) ==="
sharded_artifact="${SHARDED_TRACE_ARTIFACT:-/tmp/ci-sharded-trace.json}"
python tools/ci/sharded_smoke.py "${sharded_artifact}"
python tools/traceview.py "${sharded_artifact}" --scope ml.serving | grep -A 3 "shards:"

# Fusion smoke: build and serve BOTH fusion tiers (exact + fast with
# megakernels forced hot), assert zero fast-path compiles after warmup in
# each, exact bit-identical to the per-stage reference, fast inside the
# documented ulp envelope (docs/fusion.md).
echo "=== fusion smoke (exact + fast tiers, zero post-warmup compiles) ==="
python tools/ci/fusion_smoke.py

# Precision smoke: publish f32 + int8 artifacts, serve a burst through every
# precision tier with zero post-warmup compiles, f32 bit-identical to the
# per-stage reference, bf16 inside the documented cross-tier deviation
# envelope — then inject a drift regression mid-burst and prove the
# automatic fallback to the warm f32 plan of the same version with every
# request resolved exactly once (docs/precision.md).
echo "=== precision smoke (f32/bf16/int8 tiers + drift fallback mid-burst) ==="
python tools/ci/precision_smoke.py

# Chaos smoke: a seeded open-loop ramp to ~2.2x saturation with
# serving.dispatch + serving.swap armed against a live server — no deadlock,
# typed-error-only failures with retry context, priority sheds before any
# high-priority deadline miss, at least one adaptive-controller action from
# the live goodput ledger, and recovery to within 10% of the pre-fault
# goodput fraction (docs/serving.md "Load shedding & adaptive control").
# Runs with the flight recorder pointed at a scratch journal: every
# controller action, swap and fault trip must land in the journal exactly
# once, and the armed-swap episode must yield one incident bundle that
# `traceview incident` renders (docs/observability.md).
echo "=== chaos smoke (open-loop ramp past saturation, faults armed) ==="
python tools/ci/chaos_smoke.py

# Restart smoke: serve → hard-kill (os._exit) → a new incarnation over the
# same plan-cache directory resumes with the XLA compile seam POISONED and
# answers every bucket bit-identically from the serialized executables,
# inside the smoke deadline — the zero-compile-resume contract
# (docs/plancache.md).
echo "=== restart smoke (hard-kill -> zero-compile resume from plan cache) ==="
python tools/ci/restart_smoke.py

# Fleet smoke: 3 process-isolated replicas behind the retrying router with
# a running supervisor; one replica hard-killed mid-ramp — every arrival
# resolved exactly once with typed errors only and bounded goodput/p999
# movement, the killed slot respawned and re-admitted with ZERO serving-path
# compiles (plan-cache-warmed — O(load) not O(XLA)), a deliberately
# regressed canary held inside its hard traffic slice and quarantined by the
# live drift score, and the full eject/respawn/readmit/canary decision
# timeline reconstructed from the merged journals by tools/fleetview.py
# (docs/fleet.md).
echo "=== fleet smoke (replica kill -> respawn -> canary quarantine) ==="
python tools/ci/fleet_smoke.py

# Retrieval smoke: a registry-published CandidateIndex served as a fused
# top-K head — concurrent mixed-K burst, hot swap to v-2 mid-burst, every
# request resolved exactly once and bit-exact (ids + scores) against the
# numpy reference for whichever index version served it, per-request K
# honored, and zero fast-path compiles outside the boot/swap warmup windows
# (docs/retrieval.md).
echo "=== retrieval smoke (index hot swap mid-burst, zero-compile top-K) ==="
python tools/ci/retrieval_smoke.py

# Train smoke: sharded-training kill → resume across a real process
# boundary — a sharded KMeans fit_stream at train.mesh=2 hard-killed
# (os._exit) mid-epoch by an armed fault, then resumed at train.mesh=4 from
# the per-shard snapshots and required to land BIT-identical to a clean run
# — the width-invariant resume contract (docs/distributed_training.md).
echo "=== train smoke (sharded fit hard-kill -> cross-width resume) ==="
python tools/ci/train_smoke.py

# Bench trend (informational): diff the two newest BENCH_r*.json rounds and
# warn on >10% p50 / rows-per-second movement — directional on shared CI
# boxes, so the step never fails the build (tools/bench_trend.py --strict
# exists for local perf work).
echo "=== bench trend (informational) ==="
python tools/bench_trend.py || true

echo "CI OK"
