#!/usr/bin/env python
"""CI chaos smoke: a seeded open-loop ramp past saturation with faults armed
at the serving seams, proving the server sheds gracefully, never deadlocks,
and recovers to its pre-fault goodput.

Three phases against ONE live server (flink_ml_tpu/loadgen driving the real
``InferenceServer.submit`` path):

1. **baseline** — low offered load, tracing on; records the goodput fraction.
2. **chaos** — a Poisson ramp to >= 2x saturation with a heavy-tailed size
   mix and a 50/50 priority split, while ``serving.dispatch`` (seeded
   probabilistic) and ``serving.swap`` (one-shot, against a live publish)
   are armed — the PR 1/PR 2 fault machinery under real offered load.
3. **recovery** — baseline load again, faults disarmed.

Asserted:

- no deadlock / nothing lost: every arrival resolves into exactly one bin;
- typed-error-only failures: the ``unexpected`` bin is empty in every phase —
  all rejected work failed with ServingError subtypes or InjectedFault, and
  overload rejections carried retry-after context;
- priority discipline: sheds happened, all of them to the sheddable
  priority, and priority-0 traffic missed zero deadlines;
- the control loop acted: at least one controller action (depth step or
  bucket downshift) fired from the live goodput ledger;
- the armed swap failed typed and serving kept answering on the old version;
- recovery: the post-fault goodput fraction is within 10% of baseline, and
  graftscope's per-category attribution sums to traced wall time in the
  traced phases;
- flight recorder (always on, pointed at a scratch journal): zero dropped
  records, every controller action / swap / fired fault appears in the
  journal exactly once with strictly increasing sequence numbers, and the
  armed-swap episode yields exactly one well-formed ``swap-failure``
  incident bundle that ``tools/traceview.py incident`` renders with exit 0.

Exit codes: 0 = all invariants hold, 1 = any violated.
"""
from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv

    import tempfile
    import time

    import numpy as np

    from flink_ml_tpu import telemetry, trace
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.faults import faults
    from flink_ml_tpu.loadgen import OpenLoopLoadGenerator, ZipfSizes, ramp_schedule
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.servable.api import TransformerServable
    from flink_ml_tpu.serving import InferenceServer, ServingConfig

    # The recorder is on by default; point it at a scratch journal so this
    # run's decisions are assertable (and the incident bundles land here).
    journal_dir = tempfile.mkdtemp(prefix="chaos-smoke-journal-")
    recorder = telemetry.configure(journal_dir)

    failures = []

    def check(ok: bool, what: str) -> None:
        print(f"  {'ok ' if ok else 'FAIL'} {what}")
        if not ok:
            failures.append(what)

    class SlowEcho(TransformerServable):
        """Deterministic 4 ms service time → saturation is computable."""

        def __init__(self, delay_s: float = 0.004):
            super().__init__()
            self.delay_s = delay_s

        def transform(self, df):
            time.sleep(self.delay_s)
            return df.clone()

    max_batch = 4
    delay_s = 0.004
    saturation_rows_per_s = max_batch / delay_s  # 1000 rows/s
    cfg = ServingConfig(
        max_batch_size=max_batch,
        max_delay_ms=0.5,
        queue_capacity_rows=48,
        default_timeout_ms=30_000,
        shed_sustain_ms=5.0,
    )
    server = InferenceServer(
        SlowEcho(delay_s),
        name="chaos-smoke",
        serving_config=cfg,
        warmup_template=DataFrame.from_dict({"x": np.zeros((1, 4))}),
    )

    def request(rows: int):
        return DataFrame.from_dict({"x": np.ones((rows, 4), np.float32)})

    sizes = ZipfSizes((1, 2, 4), alpha=1.5)  # heavy-tailed, bucket-aligned

    def run_phase(steps, seed, traced):
        sched = ramp_schedule(
            steps, sizes=sizes, priority_mix={0: 0.5, 1: 0.5}, seed=seed
        )
        gen = OpenLoopLoadGenerator(
            sched, request, timeout_ms={0: 30_000.0, 1: 1_500.0}
        )
        if not traced:
            return gen.run(server), None, None
        with trace.capture() as recorder:
            report = gen.run(server)
        return report, recorder.snapshot(), recorder.goodput_report()

    # mean Zipf size ~1.5 rows → offered rows/s ~= rps * 1.5
    base_rps = 0.2 * saturation_rows_per_s / sizes.mean_rows
    chaos_rps = 2.2 * saturation_rows_per_s / sizes.mean_rows
    print(
        f"chaos smoke: saturation ~{saturation_rows_per_s:.0f} rows/s, "
        f"baseline {base_rps:.0f} rps, chaos ramp to {chaos_rps:.0f} rps "
        f"(~2.2x saturation, mean {sizes.mean_rows:.2f} rows/request)"
    )

    faults.reset()
    try:
        print("phase 1: baseline (traced)")
        base_report, base_spans, base_gp = run_phase([(base_rps, 0.8)], seed=11, traced=True)

        print("phase 2: chaos ramp with serving.dispatch + serving.swap armed")
        # A published v-2 the armed swap seam will reject mid-ramp: the
        # poller must record it failed and the in-service v1 must keep
        # answering (only the atomic-publish layout matters here — the
        # armed seam fires before the loader ever runs).
        pub_dir = tempfile.mkdtemp(prefix="chaos-smoke-models-")
        v2_dir = os.path.join(pub_dir, "v-2")
        os.makedirs(v2_dir)
        with open(os.path.join(v2_dir, "metadata"), "w", encoding="utf-8") as f:
            f.write("{}")
        poller = server.attach_poller(
            pub_dir, loader=lambda path: SlowEcho(delay_s), start=False
        )
        faults.arm("serving.dispatch", prob=0.03, seed=23)
        faults.arm("serving.swap", at=1)
        chaos_report, _, _ = run_phase(
            [(0.8 * chaos_rps / 2.2, 0.3), (chaos_rps, 1.0)], seed=13, traced=False
        )
        swapped = poller.poll_once()  # the armed seam fires in here
        dispatch_fires = faults.fires("serving.dispatch")
        swap_fires = faults.fires("serving.swap")
        faults.reset()

        print("phase 3: recovery (traced)")
        rec_report, rec_spans, rec_gp = run_phase([(base_rps, 0.8)], seed=17, traced=True)
    finally:
        faults.reset()
        server.close()

    # -- invariants -----------------------------------------------------------
    print("invariants:")
    for name, report in (
        ("baseline", base_report), ("chaos", chaos_report), ("recovery", rec_report)
    ):
        check(report.fully_resolved(),
              f"{name}: every arrival resolved exactly once "
              f"({report.total_resolved}/{report.total_arrivals})")
        check(not report.unexpected,
              f"{name}: typed-error-only failures (unexpected={report.unexpected!r})")

    overload = chaos_report.steps[-1]
    check(overload.shed > 0, f"chaos: sheds happened ({overload.shed})")
    check(overload.first_shed_at_s is not None,
          f"chaos: time-to-first-shed recorded ({overload.first_shed_at_s})")
    shed_p0 = sum(s.by_priority.get(0, {}).get("shed", 0) for s in chaos_report.steps)
    check(shed_p0 == 0, "chaos: priority-0 traffic was never shed")
    miss_p0 = sum(
        s.by_priority.get(0, {}).get("deadline_miss", 0)
        for r in (base_report, chaos_report, rec_report) for s in r.steps
    )
    check(miss_p0 == 0, "priority-0 traffic missed zero deadlines, all phases")
    check(overload.injected > 0,
          f"chaos: armed serving.dispatch actually fired ({overload.injected} typed fault failures)")

    controller = server.controller
    acted = controller.actions_of("depth") + controller.actions_of("bucket")
    check(bool(acted),
          f"controller acted from the live goodput signal ({[a.kind for a in acted][:4]})")

    from flink_ml_tpu.faults import InjectedFault

    check(
        swapped is None
        and server.model_version == 1
        and isinstance(poller.failed.get(2), InjectedFault),
        f"armed serving.swap rejected v-2 typed, serving stayed on v{server.model_version}",
    )

    rejected_with_context = metrics.get(server.scope, MLMetrics.SERVING_SHED) or 0
    check(rejected_with_context >= overload.shed, "sheds observable in ml.serving.shed")

    # graftscope's exact-attribution invariant in both traced phases
    for name, spans, gp in (("baseline", base_spans, base_gp), ("recovery", rec_spans, rec_gp)):
        roots = {}
        ids = {s.span_id for s in spans}
        for s in spans:
            if s.parent_id is None or s.parent_id not in ids:
                roots[s.scope] = roots.get(s.scope, 0.0) + s.duration
        ok = all(abs(gp.wall_s(scope) - wall) <= 1e-6 * max(wall, 1.0)
                 for scope, wall in roots.items())
        check(ok, f"{name}: per-category goodput sums to traced wall time")

    base_fraction = base_gp.fraction(server.scope)
    rec_fraction = rec_gp.fraction(server.scope)
    check(
        base_fraction is not None and rec_fraction is not None
        and rec_fraction >= 0.9 * base_fraction,
        f"recovery goodput within 10% of pre-fault baseline "
        f"({base_fraction:.3f} -> {rec_fraction:.3f})",
    )

    # -- flight-recorder invariants (the journal saw everything, exactly once)
    check(recorder.flush(15.0), "journal flushed to disk")
    check(recorder.dropped == 0, f"zero journal records dropped ({recorder.dropped})")
    records = telemetry.read_journal(journal_dir)
    seqs = [r["seq"] for r in records]
    check(
        seqs == sorted(seqs) and len(set(seqs)) == len(seqs),
        f"journal sequence strictly increasing ({len(seqs)} records)",
    )
    journal_actions = [r for r in records if r["kind"] == "controller.action"]
    counted_actions = metrics.get(server.scope, MLMetrics.SERVING_CONTROLLER_ACTIONS) or 0
    check(
        len(journal_actions) == counted_actions,
        f"every controller action journaled exactly once "
        f"({len(journal_actions)} == {counted_actions}), each with its ledger evidence",
    )
    check(
        all(a.get("data", {}).get("ledger_ms") is not None for a in journal_actions),
        "controller-action records carry the justifying ledger snapshot",
    )
    journal_swaps = [r for r in records if r["kind"] == "serving.swap"]
    check(
        len(journal_swaps) == 1 and journal_swaps[0]["data"]["version"] == 1,
        "the one completed swap (v1 install) journaled exactly once",
    )
    journal_trips = [r for r in records if r["kind"] == "fault.trip"]
    check(
        len(journal_trips) == dispatch_fires + swap_fires,
        f"every fired fault journaled exactly once "
        f"({len(journal_trips)} == {dispatch_fires} dispatch + {swap_fires} swap)",
    )
    swap_failed = [r for r in records if r["kind"] == "serving.swap.failed"]
    check(
        len(swap_failed) == 1 and swap_failed[0]["data"]["version"] == 2,
        "the armed-swap rejection journaled exactly once",
    )
    shed_records = [r for r in records if r["kind"] == "controller.action"
                    and r["data"]["action"] == "shed"]
    check(bool(shed_records), f"shed episodes journaled ({len(shed_records)})")

    bundles = telemetry.list_bundles(recorder.incident_dir)
    swap_bundles = [b for b in bundles if b.endswith("swap-failure")]
    check(
        len(swap_bundles) == 1,
        f"armed-swap episode yielded exactly one incident bundle ({swap_bundles})",
    )
    if swap_bundles:
        import contextlib
        import io

        import tools.traceview as traceview

        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            code = traceview.main(["incident", swap_bundles[0], "--top", "12"])
        check(code == 0, f"traceview incident renders the bundle (exit {code})")
        check("swap-failure" in out.getvalue(), "incident summary names the episode")
    recorder.close()

    if failures:
        print(f"chaos smoke FAILED: {len(failures)} invariant(s) violated", file=sys.stderr)
        return 1
    p999 = overload.latency_ms(0.999)
    print(
        f"chaos smoke OK: {chaos_report.total_arrivals} chaos arrivals, "
        f"{overload.shed} shed / {overload.rejected} hard-rejected / "
        f"{overload.deadline_misses} missed, p99 "
        f"{overload.latency_ms(0.99):.1f} ms, p999 {p999:.1f} ms, "
        f"goodput {base_fraction:.3f} -> {rec_fraction:.3f}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
