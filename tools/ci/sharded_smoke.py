#!/usr/bin/env python
"""CI sharded smoke: the pod-scale fan-out pillar exercised end to end on a
mesh=4 virtual device grid — publish a trained pipeline, warm + serve a
traffic burst through the SPMD fast path, hot-swap a second version, and
prove the trace carries per-shard attribution.

Checks (any failure exits 1):
- responses are bit-identical per row to the per-stage reference transform
  at the response bucket, before AND after the swap;
- zero ``ml.serving.fastpath.compiles`` — every (version, bucket, mesh)
  executable was AOT-compiled at swap time, off the serving path;
- buckets ride the mesh ladder (multiples of MIN_SHARD_ROWS * 4);
- the exported Chrome trace contains dispatch/exec spans with ``shards``
  attrs, and ``tools/traceview.py`` (run by run_tests.sh on the artifact)
  shows the per-shard section.

Driven by tools/ci/run_tests.sh after the trace smoke; artifact path in
argv[1] (SHARDED_TRACE_ARTIFACT resolves it, mirroring TRACE_ARTIFACT).
"""
from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

MESH = 4


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: sharded_smoke.py <artifact-path>", file=sys.stderr)
        return 1
    artifact = argv[0]

    import numpy as np

    from flink_ml_tpu import trace
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.metrics import MLMetrics, metrics
    from flink_ml_tpu.servable import (
        LogisticRegressionModelServable,
        PipelineModelServable,
        StandardScalerModelServable,
    )
    from flink_ml_tpu.servable.sharding import MIN_SHARD_ROWS
    from flink_ml_tpu.serving import InferenceServer, ServingConfig, pad_to

    rng = np.random.default_rng(11)
    dim = 32

    def make_pipe(seed):
        r = np.random.default_rng(seed)
        sc = StandardScalerModelServable().set_input_col("features").set_output_col("scaled")
        sc.mean = r.standard_normal(dim)
        sc.std = np.abs(r.standard_normal(dim)) + 0.5
        sc.set_with_mean(True)
        lr = LogisticRegressionModelServable().set_features_col("scaled")
        lr.coefficient = r.standard_normal(dim)
        return PipelineModelServable([sc, lr])

    pipe_v1, pipe_v2 = make_pipe(1), make_pipe(2)
    refs = {1: make_pipe(1), 2: make_pipe(2)}
    X = rng.standard_normal((256, dim))

    failures = []
    with trace.capture() as recorder:
        server = InferenceServer(
            pipe_v1,
            name="sharded-smoke",
            serving_config=ServingConfig(
                max_batch_size=64,
                max_delay_ms=0.5,
                default_timeout_ms=60_000,
                mesh=MESH,
            ),
            warmup_template=DataFrame.from_dict({"features": X[:1]}),
        )
        try:
            def burst(n_requests):
                for i in range(n_requests):
                    j = (i * 37) % (X.shape[0] - 4)
                    req = DataFrame.from_dict({"features": X[j : j + 3]})
                    resp = server.predict(req)
                    if resp.bucket % (MIN_SHARD_ROWS * MESH):
                        failures.append(f"bucket {resp.bucket} off the mesh ladder")
                    expected = refs[resp.model_version].transform(
                        pad_to(req, resp.bucket)
                    ).take([0, 1, 2])
                    for name in expected.get_column_names():
                        if not np.array_equal(
                            np.asarray(resp.dataframe[name]), np.asarray(expected[name])
                        ):
                            failures.append(
                                f"v{resp.model_version} column {name} not bit-exact"
                            )

            burst(12)
            server.swap(2, pipe_v2)  # AOT per (version, bucket, mesh), then flip
            burst(12)
            scope = server.scope
        finally:
            server.close()
        exported = recorder.export_chrome_trace(artifact)

    spans = recorder.snapshot()
    sharded = [
        s for s in spans
        if s.name in ("serving.dispatch", "serving.exec") and s.attrs
        and s.attrs.get("shards") == MESH
    ]
    if not sharded:
        failures.append("no dispatch/exec spans carrying the shards attr")
    compiles = metrics.get(scope, MLMetrics.SERVING_FASTPATH_COMPILES)
    if compiles:
        failures.append(f"{compiles} serving-path compiles (warmup coverage broken)")
    if metrics.get(scope, MLMetrics.SERVING_SHARD_COUNT) != MESH:
        failures.append("ml.serving.shard.count gauge missing")
    if exported == 0:
        failures.append("trace export wrote no spans")

    if failures:
        print("sharded smoke FAILED:\n  " + "\n  ".join(failures), file=sys.stderr)
        return 1
    print(
        f"sharded smoke: mesh={MESH}, {exported} spans -> {artifact}; "
        f"{len(sharded)} per-shard spans, 0 serving-path compiles, "
        f"shard rows {metrics.get(scope, MLMetrics.SERVING_SHARD_ROWS)}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
