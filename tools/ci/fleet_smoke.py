#!/usr/bin/env python
"""fleet_smoke — a 3-replica process fleet survives a hard kill mid-ramp and
quarantines a regressed canary, end to end (docs/fleet.md).

The scenario:

1. Publish v-1 (a trained logistic head) and v-2 (DELIBERATELY regressed —
   trained on flipped labels) into one publish dir.
2. Spawn three ``ProcessReplica`` workers over a shared plan-cache dir, each
   with its own journal and /healthz endpoint; front them with a
   ``FleetRouter`` (client-side ``RetryPolicy`` on the load harness) and a
   running ``ReplicaSupervisor``.
3. Drive an open-loop ramp (pre-kill / kill / recovery steps) and hard-kill
   one replica mid-ramp (``SIGKILL``, no drain — the crash the fleet must
   survive).
4. Assert: every arrival resolved exactly once, the untyped-error bin EMPTY,
   goodput and p999 movement bounded across the kill;
5. the supervisor ejects, respawns and re-admits the killed slot, and the
   respawned worker reports ZERO serving-path compiles and ZERO plan-cache
   misses — the O(load)-not-O(XLA) respawn contract (docs/plancache.md);
6. the canary controller runs v-2 on a bounded slice (the counter-gate
   invariant ``canary <= slice * total`` checked against live counts), scores
   it on labelled tail traffic through pinned router dispatches, and
   QUARANTINES it (``v-2.quarantined``) with the fleet version untouched;
7. ``tools/fleetview.py`` reconstructs every decision — eject, respawn,
   readmit, canary start, quarantine — from the merged journals alone.

Run: ``python tools/ci/fleet_smoke.py`` (wired into tools/ci/run_tests.sh).
"""
from __future__ import annotations

import os
import shutil
import sys
import tempfile
import threading
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

DIM = 8
REPLICAS = 3
KILL_SLOT = 1  # middle slot: the canary designation (last slot) stays clean
STEP_S = 2.0
RATE_RPS = 25.0
READMIT_DEADLINE_S = 300.0


def _true_weights():
    import numpy as np

    return np.linspace(1.0, -1.0, DIM)


def _labelled(n, seed, flip=False):
    import numpy as np

    from flink_ml_tpu.api.dataframe import DataFrame

    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, DIM))
    y = (X @ _true_weights() > 0).astype(np.float64)
    if flip:
        y = 1.0 - y
    return DataFrame.from_dict({"features": X, "label": y})


def _fit(df):
    from flink_ml_tpu.models.classification.logistic_regression import (
        LogisticRegression,
    )

    return LogisticRegression().set_max_iter(10).set_global_batch_size(128).fit(df)


def _publish_versions(publish_dir):
    """v-1: a good head. v-2: trained on FLIPPED labels — confidently wrong,
    so its live logloss regresses hard against the v-1 baseline."""
    from flink_ml_tpu.serving import publish_servable

    publish_servable(_fit(_labelled(128, seed=1)), publish_dir, version=1)
    publish_servable(_fit(_labelled(128, seed=1, flip=True)), publish_dir, version=2)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    import flink_ml_tpu.telemetry as telemetry
    import tools.fleetview as fleetview
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.fleet import (
        CanaryController,
        FleetConfig,
        FleetRouter,
        ProcessReplica,
        ReplicaPool,
        ReplicaSupervisor,
    )
    from flink_ml_tpu.loadgen import (
        FixedSizes,
        OpenLoopLoadGenerator,
        RetryPolicy,
        ramp_schedule,
    )
    from flink_ml_tpu.metrics import MLMetrics

    workdir = tempfile.mkdtemp(prefix="fleet-smoke-")
    publish_dir = os.path.join(workdir, "publish")
    rec = telemetry.configure(os.path.join(workdir, "journal"))
    worker_env = {
        "JAX_PLATFORMS": "cpu",
        "FLINK_ML_TPU_PLANCACHE_DIR": os.path.join(workdir, "plancache"),
        # Small bucket ladder: the smoke proves zero-compile respawn, not
        # warmup breadth — 4 buckets keep each worker's first boot short.
        "FLINK_ML_TPU_SERVING_MAX_BATCH_SIZE": "8",
        "FLINK_ML_TPU_SERVING_MAX_DELAY_MS": "0.5",
    }
    rng = np.random.default_rng(23)
    template = DataFrame.from_dict({"features": rng.normal(size=(1, DIM))})

    def factory(index, name, version):
        rep_dir = os.path.join(workdir, name)
        ready = os.path.join(rep_dir, "ready.json")
        if os.path.exists(ready):
            os.remove(ready)  # a respawn must wait for the NEW worker's barrier
        return ProcessReplica.spawn(
            name,
            rep_dir,
            publish_dir=publish_dir,
            load_version=version if version is not None else 1,
            template=template,
            env=worker_env,
        )

    print("=== fleet_smoke: publishing v-1 (good) + v-2 (regressed) ===", flush=True)
    _publish_versions(publish_dir)

    print(f"=== spawning {REPLICAS} process replicas (shared plan cache) ===", flush=True)
    t0 = time.perf_counter()
    pool = ReplicaPool(
        factory,
        REPLICAS,
        name="smoke",
        fleet_config=FleetConfig(
            replicas=REPLICAS,
            canary_slice=0.25,
            canary_min_scores=2,
            health_interval_ms=100.0,
            health_failures=2,
        ),
        initial_version=1,
    )
    print(f"fleet up in {time.perf_counter() - t0:.1f}s", flush=True)

    supervisor = ReplicaSupervisor(pool)
    router = FleetRouter(pool, policy="least_loaded")
    killed_name = pool.slot(KILL_SLOT).name
    old_replica = pool.replica(KILL_SLOT)
    failed = []

    try:
        supervisor.start()

        # -- the ramp, with a hard kill mid-step-2 ----------------------------
        sched = ramp_schedule(
            [(RATE_RPS, STEP_S)] * 3, sizes=FixedSizes(2), seed=11
        )
        gen = OpenLoopLoadGenerator(
            sched,
            lambda rows: DataFrame.from_dict(
                {"features": rng.normal(size=(rows, DIM))}
            ),
            collectors=8,
            retry=RetryPolicy(3, backoff_ms=5.0),
        )
        killer = threading.Timer(1.5 * STEP_S, old_replica.kill)
        killer.start()
        print(f"=== ramp: 3x {STEP_S}s @ {RATE_RPS} rps, killing "
              f"{killed_name} at {1.5 * STEP_S:.1f}s ===", flush=True)
        report = gen.run(router)
        killer.cancel()

        def check(ok, msg):
            print(("  OK  " if ok else "  FAIL") + f" {msg}", flush=True)
            if not ok:
                failed.append(msg)

        check(report.fully_resolved(),
              f"every arrival resolved exactly once "
              f"({report.total_resolved}/{report.total_arrivals})")
        check(not report.unexpected,
              f"untyped-error bin empty ({[type(e).__name__ for e in report.unexpected][:5]})")
        pre, kill, rec_step = report.step(0), report.step(1), report.step(2)
        goodputs = [
            (s.completed / s.arrivals) if s.arrivals else 0.0
            for s in (pre, kill, rec_step)
        ]
        p999s = [s.latency_ms(0.999) or 0.0 for s in (pre, kill, rec_step)]
        print(f"  goodput pre/kill/recovery: "
              f"{goodputs[0]:.3f}/{goodputs[1]:.3f}/{goodputs[2]:.3f}; "
              f"p999 {p999s[0]:.1f}/{p999s[1]:.1f}/{p999s[2]:.1f} ms; "
              f"retries {sum(s.retries for s in report.steps)}, "
              f"failovers routed typed", flush=True)
        check(all(g >= 0.95 for g in goodputs),
              f"goodput movement bounded across the kill ({goodputs})")
        check(p999s[1] <= 2000.0 and p999s[2] <= max(10.0 * p999s[0], 250.0),
              f"p999 movement bounded across the kill ({p999s})")

        # -- respawn: re-admitted with zero serving-path compiles -------------
        print("=== waiting for eject -> respawn -> readmit of "
              f"{killed_name} ===", flush=True)
        deadline = time.monotonic() + READMIT_DEADLINE_S
        while time.monotonic() < deadline:
            if (pool.states()[killed_name] == "serving"
                    and pool.replica(KILL_SLOT) is not old_replica):
                break
            time.sleep(0.25)
        readmitted = (pool.states()[killed_name] == "serving"
                      and pool.replica(KILL_SLOT) is not old_replica)
        check(readmitted, f"killed replica re-admitted within {READMIT_DEADLINE_S:.0f}s")
        if readmitted:
            resp = router.predict(template, pin=KILL_SLOT)
            check(resp.model_version == 1, "respawned replica serves the fleet version")
            stats = pool.replica(KILL_SLOT).stats()
            compiles = stats["serving"].get(MLMetrics.SERVING_FASTPATH_COMPILES, 0)
            misses = stats["plancache"].get("ml.plancache.misses", 0)
            hits = stats["plancache"].get("ml.plancache.hits", 0)
            check(compiles == 0, f"zero serving-path compiles on respawn ({compiles})")
            check(misses == 0 and hits > 0,
                  f"respawn warmed purely from the plan cache "
                  f"(misses={misses}, hits={hits})")

        # -- canary: regressed v-2 on a bounded slice, then quarantine --------
        print("=== canary: v-2 on a 25% slice, drift-scored live ===", flush=True)
        ctl = CanaryController(pool, router, publish_dir, min_scores=2)
        started = ctl.maybe_start()
        check(started == 2, f"canary started on v-2 (got {started})")
        hash_router = FleetRouter(pool, policy="hash")
        slice_ok = True
        canary_seen = 0
        for i in range(120):
            hash_router.predict(
                DataFrame.from_dict({"features": rng.normal(size=(1, DIM))}),
                key=f"slice-{i}",
            )
            total, canary = pool.dispatch_counts()
            slice_ok = slice_ok and canary <= 0.25 * total
        total, canary_seen = pool.dispatch_counts()
        check(slice_ok and canary_seen > 0,
              f"canary stayed inside its slice at every instant "
              f"({canary_seen}/{total} <= 25%)")
        for round_ in range(2):
            # Eval batches must fit the workers' bucket ladder (max batch 8).
            ctl.observe(_labelled(8, seed=100 + round_))
        verdict = ctl.verdict()
        check(verdict == "quarantine", f"regressed canary verdict ({verdict})")
        if verdict == "quarantine":
            restored = ctl.quarantine()
            check(restored == 1, f"canary replica rolled back to v-1 (got {restored})")
        check(os.path.isdir(os.path.join(publish_dir, "v-2.quarantined")),
              "v-2 quarantined on disk")
        check(pool.fleet_version == 1 and pool.canary_version is None,
              "fleet version untouched by the bad canary")
        final_total, final_canary = pool.dispatch_counts()
        check(final_canary <= 0.25 * final_total,
              f"slice invariant holds at the end ({final_canary}/{final_total})")

        # -- fleetview: the merged decision timeline --------------------------
        supervisor.stop()
        rec.flush()
        summary = fleetview.aggregate(workdir)
        kinds = summary["by_kind"]
        for kind in ("fleet.eject", "fleet.respawn", "fleet.readmit",
                     "fleet.canary.start", "fleet.canary.score",
                     "fleet.quarantine"):
            check(kinds.get(kind, 0) >= 1, f"fleetview reconstructs {kind}")
        check(len(summary["journals"]) >= 1 + REPLICAS,
              f"fleetview merged parent + replica journals "
              f"({sorted(summary['journals'])})")
        print(fleetview.render(summary, tail=12), flush=True)
    finally:
        supervisor.stop()
        pool.close()
        telemetry.configure(None)

    if failed:
        print(f"fleet_smoke FAIL ({len(failed)} assertion(s)); workdir kept at "
              f"{workdir}")
        return 1
    shutil.rmtree(workdir, ignore_errors=True)
    print("fleet_smoke OK: kill survived typed-only, zero-compile respawn, "
          "canary bounded + quarantined, decisions reconstructed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
