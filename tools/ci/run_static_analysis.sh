#!/usr/bin/env bash
# Static-analysis gate: graftcheck over the library tree, failing fast with
# the human-readable report before any test process spins up a device mesh.
# Runs every registered rule — the v3 concurrency suite (shared-state-guard,
# check-then-act, lock-order, blocking-under-lock) and the v4 contract
# dataflow suite (plan-key-completeness, typed-error-escape,
# registry-consistency) — and the SARIF artifact carries their findings like
# any other rule's. The per-rule wall-time breakdown (--timings) prints after
# the report so a rule that starts eating the CI budget is visible the day it
# happens, not when the gate turns slow.
# See docs/static_analysis.md for the rule catalogue and suppression policy.
#
# The FULL-TREE run is (and stays) the CI gate. For the local pre-commit
# loop, pass --changed-only (or set GRAFTCHECK_CHANGED_ONLY=1): the analysis
# still runs whole-program, but reporting and the exit code narrow to files
# touched per `git status`, and the warm index cache (.graftcheck/) makes the
# run sub-second.
#
# Set GRAFTCHECK_SARIF=<path> to also emit a SARIF 2.1.0 report for CI
# annotation UIs (GitHub code scanning et al.); the second run rides the
# cache written by the first.
set -euo pipefail

ci_path="$(cd -- "$(dirname "$0")" >/dev/null 2>&1; pwd -P)"
root_path="$(cd "${ci_path}/../.."; pwd -P)"
cd "$root_path"

extra_args=()
if [[ "${GRAFTCHECK_CHANGED_ONLY:-0}" == "1" ]]; then
    extra_args+=(--changed-only)
fi

echo "=== graftcheck static analysis ==="
python -m tools.graftcheck --timings "${extra_args[@]}" "$@"

if [[ -n "${GRAFTCHECK_SARIF:-}" ]]; then
    python -m tools.graftcheck --format sarif "${extra_args[@]}" "$@" \
        > "${GRAFTCHECK_SARIF}"
    echo "graftcheck: SARIF report written to ${GRAFTCHECK_SARIF}"
fi
