#!/usr/bin/env bash
# Static-analysis gate: graftcheck over the library tree, failing fast with
# the human-readable report before any test process spins up a device mesh.
# See docs/static_analysis.md for the rule catalogue and suppression policy.
set -euo pipefail

ci_path="$(cd -- "$(dirname "$0")" >/dev/null 2>&1; pwd -P)"
root_path="$(cd "${ci_path}/../.."; pwd -P)"
cd "$root_path"

echo "=== graftcheck static analysis ==="
python -m tools.graftcheck flink_ml_tpu "$@"
