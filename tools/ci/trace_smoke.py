#!/usr/bin/env python
"""CI trace smoke: serve a tiny traffic burst with tracing on, export the
Chrome trace, sanity-check the span tree, print the goodput fraction.

Driven by tools/ci/run_tests.sh after the benchmark smoke; the artifact path
comes in as argv[1] (the script's caller resolves the ``TRACE_ARTIFACT`` env
var, mirroring GRAFTCHECK_SARIF), and run_tests.sh then runs
``tools/traceview.py`` on the export — the end-to-end proof that the
instrumentation, the exporter and the offline analyzer agree.

Exit codes: 0 = trace exported and structurally sound, 1 = no spans / no
request tree / export failed.
"""
from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: trace_smoke.py <artifact-path>", file=sys.stderr)
        return 1
    artifact = argv[0]

    import threading

    import numpy as np

    from flink_ml_tpu import trace
    from flink_ml_tpu.api.dataframe import DataFrame
    from flink_ml_tpu.servable.lib import LogisticRegressionModelServable
    from flink_ml_tpu.serving import InferenceServer, ServingConfig

    rng = np.random.default_rng(11)
    dim = 32
    servable = LogisticRegressionModelServable().set_features_col("features")
    servable.coefficient = rng.standard_normal(dim).astype(np.float32)
    X = rng.standard_normal((256, dim)).astype(np.float32)

    with trace.capture() as recorder:
        server = InferenceServer(
            servable,
            name="trace-smoke",
            serving_config=ServingConfig(
                max_batch_size=16,
                max_delay_ms=0.5,
                default_timeout_ms=60_000,
            ),
            warmup_template=DataFrame.from_dict({"features": X[:1]}),
        )
        try:
            def client(tid: int) -> None:
                for i in range(20):
                    j = (tid * 37 + i * 5) % (X.shape[0] - 4)
                    server.predict(DataFrame.from_dict({"features": X[j : j + 4]}))

            threads = [threading.Thread(target=client, args=(t,)) for t in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            server.close()
        exported = recorder.export_chrome_trace(artifact)
        report = recorder.goodput_report()

    spans = recorder.snapshot()
    names = {s.name for s in spans}
    required = {"serving.request", "serving.queue", "serving.batch", "serving.pad"}
    missing = required - names
    if exported == 0 or missing:
        print(f"trace smoke FAILED: {exported} spans, missing {sorted(missing)}", file=sys.stderr)
        return 1
    scope = "ml.serving[trace-smoke]"
    print(
        f"trace smoke: {exported} spans -> {artifact}; "
        f"goodput fraction {report.fraction(scope):.4f} "
        f"(wall {report.wall_s(scope) * 1000.0:.1f} ms)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
