#!/usr/bin/env python
"""train_smoke — sharded training kill → resume across a REAL process
boundary (docs/distributed_training.md).

Three legs, each its own subprocess on a forced 8-device host platform
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``):

1. **clean** — a sharded KMeans ``fit_stream`` at ``train.mesh=2`` runs to
   completion; the model (centroids + weights) is recorded.
2. **kill** — the same fit with a ``ShardedCheckpointManager`` and a
   deterministic fault armed at epoch 5 dies by ``os._exit(1)`` (a hard
   kill: no atexit, no graceful close), leaving per-shard snapshots behind.
3. **resume** — the same fit over the same checkpoint directory at
   ``train.mesh=4`` (the deterministic tier's fingerprint is
   width-invariant) restores the newest snapshot, finishes the remaining
   epochs, and must land BIT-identical to the clean leg — the
   bit-identity-across-widths contract, through a crash.

Run: ``python tools/ci/train_smoke.py`` (wired into tools/ci/run_tests.sh).
"""
from __future__ import annotations

import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

N_POINTS = 53
K = 2
MAX_ITER = 8
KILL_AT_EPOCH = 5
CHUNK_ROWS = 32


def _points():
    import numpy as np

    rng = np.random.default_rng(13)
    return np.concatenate(
        [rng.normal(c, 0.5, (N_POINTS, 2)) for c in (-3.0, 3.0)]
    ).astype(np.float32)


def _fit(workdir: str, mesh: int, with_manager: bool):
    from flink_ml_tpu.checkpoint import ShardedCheckpointManager
    from flink_ml_tpu.config import Options, config
    from flink_ml_tpu.iteration.datacache import HostDataCache
    from flink_ml_tpu.models.clustering.kmeans import KMeans

    config.set(Options.TRAIN_MESH, mesh)
    cache = HostDataCache()
    cache.append({"features": _points()})
    cache.finish()
    kw = {}
    if with_manager:
        kw = {
            "checkpoint_manager": ShardedCheckpointManager(
                os.path.join(workdir, "ck")
            ),
            "checkpoint_interval": 1,
        }
    return (
        KMeans().set_k(K).set_seed(3).set_max_iter(MAX_ITER)
        .fit_stream(cache, chunk_rows=CHUNK_ROWS, **kw)
    )


def _save(workdir: str, name: str, model) -> None:
    import numpy as np

    np.savez(
        os.path.join(workdir, name),
        centroids=np.asarray(model.centroids),
        weights=np.asarray(model.weights),
    )


def leg_clean(workdir: str) -> None:
    _save(workdir, "clean.npz", _fit(workdir, mesh=2, with_manager=False))


def leg_kill(workdir: str) -> None:
    from flink_ml_tpu.faults import faults

    faults.arm("iteration.epoch", at=KILL_AT_EPOCH)
    try:
        _fit(workdir, mesh=2, with_manager=True)
    except Exception:
        os._exit(1)  # hard kill mid-fit; snapshots already fsync'd
    print("FAIL: the armed fault never fired")
    os._exit(2)


def leg_resume(workdir: str) -> None:
    _save(workdir, "resumed.npz", _fit(workdir, mesh=4, with_manager=True))


def main() -> int:
    import tempfile

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")

    def run_leg(leg: str) -> int:
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--leg", leg, workdir],
            env=env,
            timeout=600,
        ).returncode

    with tempfile.TemporaryDirectory(prefix="train-smoke-") as workdir:
        print(f"=== leg 1: clean sharded fit_stream (train.mesh=2, {MAX_ITER} epochs) ===")
        if run_leg("clean") != 0:
            print("FAIL: clean leg did not complete")
            return 1
        print(f"=== leg 2: kill — fault at epoch {KILL_AT_EPOCH}, os._exit(1) ===")
        if run_leg("kill") != 1:
            print("FAIL: kill leg did not hard-kill (expected rc 1)")
            return 1
        snaps = [d for d in os.listdir(os.path.join(workdir, "ck")) if d.startswith("ckpt-")]
        if not snaps:
            print("FAIL: the killed fit left no sharded snapshots behind")
            return 1
        print(f"=== leg 3: resume at train.mesh=4 from {sorted(snaps)} ===")
        t0 = time.perf_counter()
        if run_leg("resume") != 0:
            print("FAIL: resume leg did not complete")
            return 1
        resume_wall = time.perf_counter() - t0

        import numpy as np

        clean = np.load(os.path.join(workdir, "clean.npz"))
        resumed = np.load(os.path.join(workdir, "resumed.npz"))
        for key in ("centroids", "weights"):
            if not np.array_equal(clean[key], resumed[key]):
                print(f"FAIL: resumed {key} differ from the clean run (not bit-identical)")
                return 1
        print(
            f"train_smoke OK: kill@epoch{KILL_AT_EPOCH} mesh=2 -> resume mesh=4 "
            f"bit-identical to clean mesh=2 run "
            f"({len(snaps)} snapshots; resume wall {resume_wall:.1f}s)"
        )
    return 0


if __name__ == "__main__":
    if "--leg" in sys.argv:
        idx = sys.argv.index("--leg")
        leg, workdir = sys.argv[idx + 1], sys.argv[idx + 2]
        {"clean": leg_clean, "kill": leg_kill, "resume": leg_resume}[leg](workdir)
        sys.exit(0)
    sys.exit(main())
