#!/usr/bin/env python
"""CI guard: fault-injection seams must not silently rot.

Asserts, for every fault point registered in ``flink_ml_tpu.faults.FAULT_POINTS``:

  1. the runtime has at least one ``faults.trip("<name>", ...)`` call site
     under ``flink_ml_tpu/`` (a registered point nobody trips is dead), and
  2. at least one test under ``tests/`` names the point (arming it or firing
     it) — recovery paths that CI never exercises are recovery paths that
     don't work.

And conversely: every ``faults.trip(...)`` call site in the runtime names a
registered point (a typo'd name would raise LookupError only when reached).

Run directly (``python tools/check_fault_points.py``) or through the tier-1
suite via ``tests/test_fault_points.py``.
"""
from __future__ import annotations

import os
import re
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_TRIP_RE = re.compile(r"""faults\.trip\(\s*["']([^"']+)["']""")


def _py_files(root: str):
    for dirpath, _, filenames in os.walk(root):
        for name in filenames:
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def check(repo_root: str = REPO_ROOT):
    """Returns (problems, trip_sites) — empty problems list means pass."""
    sys.path.insert(0, repo_root)
    try:
        from flink_ml_tpu.faults import FAULT_POINTS
    finally:
        sys.path.pop(0)

    src_root = os.path.join(repo_root, "flink_ml_tpu")
    test_root = os.path.join(repo_root, "tests")

    trip_sites = {}  # point -> [file, ...]
    for path in _py_files(src_root):
        if os.path.basename(path) == "faults.py":
            continue  # the framework itself (docstrings mention trip("<name>"))
        with open(path, encoding="utf-8") as f:
            for point in _TRIP_RE.findall(f.read()):
                trip_sites.setdefault(point, []).append(os.path.relpath(path, repo_root))

    tested = set()
    for path in _py_files(test_root):
        with open(path, encoding="utf-8") as f:
            content = f.read()
        for point in FAULT_POINTS:
            if point in content:
                tested.add(point)

    problems = []
    for point in sorted(FAULT_POINTS):
        if point not in trip_sites:
            problems.append(
                f"fault point {point!r} is registered but has no "
                f"faults.trip() call site under flink_ml_tpu/"
            )
        if point not in tested:
            problems.append(
                f"fault point {point!r} is not exercised by any test under "
                f"tests/ — its recovery path is unproven"
            )
    for point in sorted(trip_sites):
        if point not in FAULT_POINTS:
            problems.append(
                f"faults.trip({point!r}) at {trip_sites[point]} names an "
                f"unregistered fault point (typo?)"
            )
    return problems, trip_sites


def main() -> int:
    problems, trip_sites = check()
    if problems:
        print("check_fault_points: FAIL")
        for p in problems:
            print(f"  - {p}")
        return 1
    print(f"check_fault_points: OK ({len(trip_sites)} fault points, all tripped and tested)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
