#!/usr/bin/env python
"""CI guard: fault-injection seams must not silently rot.

Thin shim over the graftcheck ``fault-points`` rule (tools/graftcheck/rules/
fault_points.py): every point in ``flink_ml_tpu.faults.FAULT_POINTS`` needs a
runtime ``faults.trip()`` call site and a test naming it, and every trip site
must name a registered point. Kept for its entry point and ``check()``
contract — ``tests/test_fault_points.py`` calls it; new invariants belong in
graftcheck rules, not here.

Run directly (``python tools/check_fault_points.py``) or via
``python -m tools.graftcheck`` (the full suite).
"""
from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.graftcheck.rules import fault_points as _rule  # noqa: E402

__all__ = ["check", "main"]


def check(repo_root: str = REPO_ROOT):
    """Returns (problems, trip_sites) — empty problems list means pass."""
    return _rule.check(repo_root)


def main() -> int:
    problems, trip_sites = check()
    if problems:
        print("check_fault_points: FAIL")
        for p in problems:
            print(f"  - {p}")
        return 1
    print(f"check_fault_points: OK ({len(trip_sites)} fault points, all tripped and tested)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
