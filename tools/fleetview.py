#!/usr/bin/env python
"""fleetview — one timeline over a fleet's many flight recorders.

A fleet run leaves decision records in several journals: the parent process
(pool membership — ejects, readmits, deaths; router failfasts; canary
start/score/promote/quarantine; respawns) and one journal per replica worker
(``<workdir>/<replica>/journal`` — swaps, rollbacks, shed windows, its own
up/down markers). Each journal is consistent on its own; the *fleet's* story
only exists merged. This tool walks a fleet workdir, reads every journal
(``flink_ml_tpu.telemetry.read_journal`` — torn tails tolerated), tags each
record with its source, merges on wall-clock timestamp, and renders the
decision timeline plus a per-kind summary — the "every eject / respawn /
canary / promote / quarantine decision is reconstructible" contract of
docs/fleet.md.

Usage:
    python tools/fleetview.py <fleet-workdir> [--all] [--json] [--tail N]

``--all`` includes every record (per-request noise and all); the default
keeps decision kinds only. ``--json`` emits the merged timeline
machine-readable so CI can assert on it (tools/ci/fleet_smoke.py does).

Exit codes: 0 = journals found and merged, 2 = no journal records under the
given directory.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from flink_ml_tpu.telemetry import read_journal  # noqa: E402

__all__ = ["collect_journals", "aggregate", "render", "main"]

#: Record kinds that are fleet/serving *decisions* (the default filter).
#: Prefix match — "fleet." covers eject/readmit/dead/respawn/canary.*/
#: promote/quarantine/failfast and the replica up/down markers.
DECISION_PREFIXES = (
    "fleet.",
    "serving.swap",
    "serving.rollback",
    "serving.quarantine",
    "loop.rollback",
    "loop.quarantine",
    "execution.restart",
    "execution.exhausted",
    "incident",
)


def collect_journals(workdir: str) -> Dict[str, str]:
    """``{source_name: journal_dir}`` for every journal under ``workdir``:
    the top-level one (source ``fleet``) plus any ``<sub>/journal`` dir one
    level down (source = the subdirectory, i.e. the replica name)."""
    journals: Dict[str, str] = {}
    top = os.path.join(workdir, "journal")
    if os.path.isdir(top):
        journals["fleet"] = top
    try:
        entries = sorted(os.listdir(workdir))
    except OSError:
        return journals
    for entry in entries:
        sub = os.path.join(workdir, entry, "journal")
        if os.path.isdir(sub):
            journals[entry] = sub
    # A workdir may itself BE a journal dir (journal-*.jsonl files directly).
    if not journals and read_journal(workdir):
        journals["fleet"] = workdir
    return journals


def aggregate(workdir: str, *, decisions_only: bool = True) -> Dict[str, Any]:
    """Merge every journal under ``workdir`` into one timeline (sorted by
    wall timestamp, source-tagged) with per-kind and per-source counts."""
    journals = collect_journals(workdir)
    timeline: List[Dict[str, Any]] = []
    for source, directory in journals.items():
        for rec in read_journal(directory):
            kind = str(rec.get("kind", ""))
            if decisions_only and not kind.startswith(DECISION_PREFIXES):
                continue
            tagged = dict(rec)
            tagged["source"] = source
            timeline.append(tagged)
    timeline.sort(key=lambda r: (r.get("wall") or r.get("ts") or 0.0, r.get("seq", 0)))
    by_kind: Dict[str, int] = {}
    by_source: Dict[str, int] = {}
    for rec in timeline:
        by_kind[rec.get("kind", "?")] = by_kind.get(rec.get("kind", "?"), 0) + 1
        by_source[rec["source"]] = by_source.get(rec["source"], 0) + 1
    return {
        "workdir": workdir,
        "journals": journals,
        "records": len(timeline),
        "by_kind": dict(sorted(by_kind.items())),
        "by_source": dict(sorted(by_source.items())),
        "timeline": timeline,
    }


def render(summary: Dict[str, Any], tail: int = 0) -> str:
    lines: List[str] = []
    lines.append(f"fleetview: {summary['workdir']}")
    lines.append(
        f"  {len(summary['journals'])} journal(s), {summary['records']} decision record(s)"
    )
    lines.append("  by kind:")
    for kind, count in summary["by_kind"].items():
        lines.append(f"    {kind:<28} {count}")
    lines.append("  by source:")
    for source, count in summary["by_source"].items():
        lines.append(f"    {source:<28} {count}")
    timeline = summary["timeline"]
    if tail:
        timeline = timeline[-tail:]
    lines.append("  timeline:")
    for rec in timeline:
        wall = rec.get("wall") or rec.get("ts") or 0.0
        data = rec.get("data") or {}
        detail = ", ".join(f"{k}={v}" for k, v in list(data.items())[:6])
        lines.append(
            f"    [{wall:>16.6f}] {rec.get('source', '?'):<12} "
            f"{rec.get('kind', '?'):<24} {detail}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="merge a fleet's journals into one timeline")
    parser.add_argument("workdir", help="fleet workdir (parent journal + <replica>/journal)")
    parser.add_argument("--all", action="store_true", help="include non-decision records")
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    parser.add_argument("--tail", type=int, default=0, help="only the newest N timeline rows (text mode)")
    args = parser.parse_args(argv)
    summary = aggregate(args.workdir, decisions_only=not args.all)
    if summary["records"] == 0:
        print(f"fleetview: no journal records under {args.workdir}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(summary, indent=2, default=str))
    else:
        print(render(summary, tail=args.tail))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
