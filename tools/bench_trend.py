#!/usr/bin/env python
"""bench_trend — compare the two newest BENCH_r0*.json rounds.

The repo records one ``BENCH_r<NN>.json`` per PR round (bench.py). This tool
diffs the newest round against its predecessor, per workload row, and prints
the per-metric deltas — flagging (non-fatally) any latency p50 that grew or
any rows-per-second that shrank by more than the threshold (default 10%).

It is wired into ``tools/ci/run_tests.sh`` as an *informational* step: a
regression prints a WARN block and the build stays green — bench numbers on
shared CI boxes are directional, not contractual (the honest-1-core-box
notes in the BENCH files); the gate is a human reading the warning in the
log. ``--strict`` turns warnings into exit 1 for local perf work.

Matching: workloads pair by their ``name`` field (rows without one are
skipped); within a pair, every numeric field whose key contains ``p50`` /
``p99`` / ``p999`` counts as a latency (lower is better) and every field
containing ``rows_per_sec`` / ``rows_per_s`` / ``per_sec`` as a throughput
(higher is better). Nested dicts are walked with dotted key paths; lists of
dicts (offered-load sweeps) are walked by index.

Usage:
    python tools/bench_trend.py [--dir REPO_ROOT] [--threshold 0.10] [--strict]
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_BENCH_RE = re.compile(r"^BENCH_r(\d+)\.json$")

#: Key-substring → direction ("lower" | "higher" is better).
_LATENCY_KEYS = ("p50", "p99", "p999")
_THROUGHPUT_KEYS = ("rows_per_sec", "rows_per_s", "per_sec")

__all__ = ["bench_rounds", "compare_workloads", "flatten_numeric", "main"]


def bench_rounds(directory: str) -> List[Tuple[int, str]]:
    """Sorted (round number, path) of the BENCH_r*.json files."""
    out = []
    for name in os.listdir(directory):
        m = _BENCH_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(out)


def flatten_numeric(obj: Any, prefix: str = "") -> Dict[str, float]:
    """Dotted-path → value for every numeric leaf (bools excluded)."""
    flat: Dict[str, float] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            flat.update(flatten_numeric(v, f"{prefix}{k}."))
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            flat.update(flatten_numeric(v, f"{prefix}{i}."))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        flat[prefix[:-1]] = float(obj)
    return flat


def _direction(key: str) -> Optional[str]:
    leaf = key.rsplit(".", 1)[-1]
    if any(t in leaf for t in _THROUGHPUT_KEYS):
        return "higher"
    if any(t in leaf for t in _LATENCY_KEYS):
        return "lower"
    return None


def compare_workloads(
    old: Dict[str, Any], new: Dict[str, Any], threshold: float
) -> Tuple[List[str], List[str]]:
    """(report lines, warnings) for one workload pair."""
    lines: List[str] = []
    warnings: List[str] = []
    old_flat = flatten_numeric(old)
    new_flat = flatten_numeric(new)
    # Metrics present only in the newer round (a workload grew a column —
    # e.g. a new precision tier's latency leg) are reported informationally
    # as NEW: there is no baseline to regress against, so never a warning.
    for key in sorted(set(new_flat) - set(old_flat)):
        if _direction(key) is None:
            continue
        lines.append(f"    {key:<48} {'—':>12} -> {new_flat[key]:>12.4g} (NEW)")
    for key in sorted(set(old_flat) & set(new_flat)):
        direction = _direction(key)
        if direction is None:
            continue
        before, after = old_flat[key], new_flat[key]
        if before == 0.0:
            continue
        rel = (after - before) / abs(before)
        marker = ""
        regressed = (direction == "lower" and rel > threshold) or (
            direction == "higher" and rel < -threshold
        )
        if regressed:
            marker = "  <-- REGRESSION"
        lines.append(f"    {key:<48} {before:>12.4g} -> {after:>12.4g} ({rel:+.1%}){marker}")
        if regressed:
            warnings.append(
                f"{new.get('name', '?')}: {key} {before:.4g} -> {after:.4g} "
                f"({rel:+.1%}, {'latency grew' if direction == 'lower' else 'throughput fell'})"
            )
    return lines, warnings


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="compare the two newest BENCH rounds")
    parser.add_argument("--dir", default=REPO_ROOT, help="directory holding BENCH_r*.json")
    parser.add_argument("--threshold", type=float, default=0.10,
                        help="relative regression bound before warning (default 0.10)")
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 on any regression (default: informational, exit 0)")
    args = parser.parse_args(argv)

    rounds = bench_rounds(args.dir)
    if len(rounds) < 2:
        print(f"bench_trend: fewer than two BENCH rounds under {args.dir} — nothing to compare")
        return 0
    (old_n, old_path), (new_n, new_path) = rounds[-2], rounds[-1]
    try:
        with open(old_path, encoding="utf-8") as f:
            old = json.load(f)
        with open(new_path, encoding="utf-8") as f:
            new = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_trend: cannot load rounds: {e}", file=sys.stderr)
        return 0 if not args.strict else 1

    old_rows = {w["name"]: w for w in old.get("workloads", []) if isinstance(w, dict) and "name" in w}
    new_rows = {w["name"]: w for w in new.get("workloads", []) if isinstance(w, dict) and "name" in w}
    shared = sorted(set(old_rows) & set(new_rows))
    print(f"bench_trend: r{old_n:02d} -> r{new_n:02d}, {len(shared)} shared workload row(s)")
    for name in sorted(set(new_rows) - set(old_rows)):
        print(f"  + new row {name}")
    for name in sorted(set(old_rows) - set(new_rows)):
        print(f"  - dropped row {name}")

    all_warnings: List[str] = []
    for name in shared:
        lines, warnings = compare_workloads(old_rows[name], new_rows[name], args.threshold)
        if lines:
            print(f"  {name}:")
            for line in lines:
                print(line)
        all_warnings.extend(warnings)

    if all_warnings:
        print(f"\nbench_trend WARN: {len(all_warnings)} metric(s) regressed past "
              f"{args.threshold:.0%} (informational — see the honest-box notes in the BENCH files):")
        for w in all_warnings:
            print(f"  ! {w}")
        return 1 if args.strict else 0
    print("bench_trend: no regressions past the threshold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
