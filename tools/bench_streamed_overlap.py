"""Tunnel-free streamed-training overlap measurement (run as a subprocess by
bench.py on an 8-device virtual CPU mesh).

The real-chip streamed benchmark is ingest-bound behind the dev box's
~25 MB/s tunnel — compute_share there says nothing about the streaming
machinery. This run takes the tunnel out: host->device transfers are local
memcpys, so the ingest half (cache read + per-window one-hot layout fill)
and the compute half (the fused one-hot program) are the same order of
magnitude, and the prefetch overlap in ``run_windows`` is actually
measurable. The streamed regime is enforced by a spilling host cache (RAM
budget << dataset, windows read back off disk) — the CPU mesh has no HBM to
overflow, so the window:dataset ratio stands in for the HBM:dataset ratio.

Also exercises checkpoint+resume mid-run on the streamed one-hot path (the
fit checkpoints every other window run; a resume from the second-to-last
snapshot must land on the identical coefficient).

Prints one JSON object on stdout.
"""
import json
import shutil
import sys
import tempfile
import time

import numpy as np


def main():
    import jax

    from flink_ml_tpu.checkpoint import CheckpointManager
    from flink_ml_tpu.iteration import HostDataCache
    from flink_ml_tpu.iteration.streaming import WindowSchedule
    from flink_ml_tpu.linalg.onehot_sparse import SUB_ROWS
    from flink_ml_tpu.ops import SGD, BinaryLogisticLoss
    from flink_ml_tpu.ops.optimizer import _OneHotWindowStream, streamed_onehot_plan
    from flink_ml_tpu.parallel.mesh import get_mesh_context

    n, d, K = 196_608, 1 << 18, 16
    batch = 32_768
    epochs = 6
    # window << per-shard rows: multiple window runs per fit, so the
    # checkpoint-at-run-boundary machinery and the prefetch both engage
    window = 8_192
    rng = np.random.default_rng(11)

    with tempfile.TemporaryDirectory() as tmp:
        # RAM budget 4 MB vs a ~25 MB dataset: most chunks spill to disk and
        # every window read comes back off the spill files.
        cache = HostDataCache(memory_budget_bytes=4 << 20, spill_dir=tmp)
        for lo in range(0, n, 32_768):
            m = min(32_768, n - lo)
            idx = rng.integers(0, d, size=(m, K), dtype=np.int32)
            vals = np.ones((m, K), np.float32)
            cache.append(
                {
                    "indices": idx,
                    "values": vals,
                    "labels": (rng.random(m) > 0.5).astype(np.float32),
                    "weights": np.ones(m, np.float32),
                }
            )
        cache.finish()
        spilled = sum(1 for e in cache._log if "files" in e)

        last_fit = {}

        def fit(mgr=None, interval=0):
            sgd = SGD(
                max_iter=epochs, global_batch_size=batch, tol=0.0,
                learning_rate=0.5, stream_window_rows=window,
                sparse_kernel="onehot", checkpoint_manager=mgr,
                checkpoint_interval=interval,
            )
            coef = sgd.optimize(
                np.zeros(d, np.float32), cache, BinaryLogisticLoss.INSTANCE
            )
            last_fit["premat"] = sgd.onehot_premat_active
            return coef

        fit()  # warm-up: plan + program compile
        t0 = time.perf_counter()
        want = fit()
        wall = time.perf_counter() - t0

        # Pure ingest: load the windows the run actually loads (dedup
        # consecutive same-window runs — run_windows keeps those resident),
        # no compute; the fit's in-band counting pass is timed apart and
        # excluded from the windows-phase wall.
        from flink_ml_tpu.linalg.onehot_sparse import BLOCK

        ctx = get_mesh_context()
        m_shard = -(-n // ctx.n_data)
        b_local = -(-batch // ctx.n_data)
        sub = min(SUB_ROWS, b_local)
        W = WindowSchedule(m_shard, b_local, window, epochs).window
        t0 = time.perf_counter()
        plan = streamed_onehot_plan(cache, n, ctx.n_data, W, b_local, d)
        plan_s = time.perf_counter() - t0
        n_sub = -(-b_local // sub)
        flops = 4.0 * n_sub * plan.n_flat * (sub + 2 * BLOCK)
        sched = WindowSchedule(
            m_shard, b_local, window, epochs, flops_per_epoch=flops
        )
        # The probe must exercise the SAME load() path the fit used (with
        # premat, load() also materializes the window's one-hots on device).
        stream = _OneHotWindowStream(
            cache, ctx, plan, sched.window, b_local, n_sub, m_shard, n,
            premat=last_fit.get("premat", False),
        )
        visited = [j for j, _ in sched.runs]
        loads = [j for i, j in enumerate(visited) if i == 0 or j != visited[i - 1]]
        t0 = time.perf_counter()
        for j in loads:
            buf = stream.load(j)
            jax.block_until_ready(buf.get("oh", buf["labels"]))
        ingest_s = time.perf_counter() - t0

        # Checkpoint + resume mid-run: identical coefficient required.
        ckdir = f"{tmp}/ck"
        got_ck = fit(CheckpointManager(ckdir), interval=2)
        steps = CheckpointManager(ckdir).all_steps()
        resume_ok = False
        if len(steps) >= 2:
            shutil.rmtree(f"{ckdir}/ckpt-{steps[-1]}")
            resumed = fit(CheckpointManager(ckdir), interval=2)
            resume_ok = bool(
                np.array_equal(got_ck, want) and np.array_equal(resumed, want)
            )

    # windows-phase wall: the fit repeats the counting pass in-band; it is
    # neither window ingest nor device compute, so take it out of the split
    wall_train = max(wall - plan_s, 1e-9)
    compute_s = max(wall_train - ingest_s, 0.0)  # whatever ingest can't explain
    out = {
        "name": "streamed_overlap_cpu_mesh_196k_d256k",
        "backend": "cpu x 8 (virtual mesh, no tunnel)",
        "rows": n,
        "window_rows": window,
        "epochs": epochs,
        "spilled_chunks": spilled,
        "onehot_premat_active": last_fit.get("premat", False),
        "wall_time_s": round(wall, 2),
        "plan_pass_s": round(plan_s, 2),
        "ingest_s": round(ingest_s, 2),
        "compute_share": round(compute_s / wall_train, 4),
        "ingest_share": round(ingest_s / wall_train, 4),
        "e2e_rows_per_sec": round(epochs * batch / wall, 1),
        "checkpoint_resume_identical": resume_ok,
        "note": "tunnel-free: ingest (spill read + layout fill + transfer) vs "
        "the fused one-hot compute; compute_share = fraction of wall not "
        "explained by pure ingest (prefetch hides ingest behind compute when "
        "compute dominates)",
    }
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
