"""Thread-topology inference — graftcheck v3's whole-program concurrency map.

The runtime rebuilt Flink's managed concurrency on raw Python threads: the
micro-batcher loop, the model-version poller, the loadgen driver's collector
pool, the batch-plan readback pool, plus every client thread calling the
public API. Which *thread role* a function can run on is a whole-program
property — a spawn site in one module, the target resolved through the call
graph into five others — and it is the input every lockset question needs:
two accesses only race when two different threads (or two instances of a
multi-threaded role) can make them.

This module derives, from the shared project index
(``tools/graftcheck/index.py``), with **no new parsing**:

- **Roles** — one per resolved spawn site (``threading.Thread(target=...)``,
  ``Timer``, executor ``submit``/``map``), named from the thread's literal
  name prefix (``name=f"micro-batcher[{scope}]"`` → ``micro-batcher``), the
  module's ``ThreadPoolExecutor(thread_name_prefix=...)`` for pool workers,
  or the target function as a fallback. A role is ``multi`` when the spawn
  site can create several threads sharing state (spawned in a loop or
  comprehension, or any pool) — a multi role races with *itself*. The
  implicit ``main`` role is every caller thread entering through the public
  API.
- **fn_roles** — for every function, the set of roles it can run on:
  spawn-target reachability over the resolved call graph (markers like
  ``cold``/``readback`` do NOT stop this traversal — a cold function called
  from the poller thread still runs on the poller), plus ``main``
  reachability seeded from every un-called, un-spawned top-level function
  (the public API surface). A function no traversal reaches defaults to
  ``main`` — everything is at least caller-callable.
- **Lock context** — for every function, the set of locks *definitely held*
  at every resolved call site reaching it (the RacerD-style interprocedural
  lockset): a helper only ever invoked under ``with self._lock`` inherits
  that lock for its own attribute accesses. Computed as the greatest
  fixpoint of ``ctx(f) = ⋂ over call sites (locks held at site ∪
  ctx(caller))``; a function with no resolved callers (an entry point) has
  an empty context.

Known blind spots (documented, deliberately unhandled): targets stored in
callable attributes (``self._execute = execute``) don't propagate roles
through the callback, module-level globals are outside the per-class lockset
analysis, and ``fn`` parameters handed to a pool stay unresolved (reported in
``unresolved_spawns``).
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Set, Tuple

from tools.graftcheck.index import ProjectIndex

__all__ = ["MAIN_ROLE", "ThreadRole", "ThreadTopology", "build_topology", "topology_for", "lock_context"]

#: The implicit role of every caller thread entering through the public API.
MAIN_ROLE = "main"

_ROLE_STRIP = re.compile(r"[^A-Za-z0-9_]+$")


class ThreadRole:
    """One spawn site's thread role."""

    __slots__ = ("name", "rel", "line", "target", "kind", "multi")

    def __init__(self, name: str, rel: str, line: int, target: Optional[str], kind: str, multi: bool):
        self.name = name
        self.rel = rel  # file containing the spawn site
        self.line = line
        self.target = target  # resolved call-graph node, or None
        self.kind = kind  # "thread" | "pool"
        self.multi = multi

    def __repr__(self) -> str:
        return f"ThreadRole({self.name!r}, target={self.target!r}, multi={self.multi})"


class ThreadTopology:
    """Resolved whole-program thread map: roles, per-function role sets."""

    def __init__(
        self,
        roles: Dict[str, ThreadRole],
        fn_roles: Dict[str, Set[str]],
        unresolved_spawns: List[Tuple[str, int, list]],
    ):
        self.roles = roles
        self.fn_roles = fn_roles
        #: spawn sites whose target could not be resolved: (rel, line, ref)
        self.unresolved_spawns = unresolved_spawns

    def roles_of(self, node: str) -> Set[str]:
        """Role names a call-graph node can run on (``{"main"}`` default)."""
        return self.fn_roles.get(node, {MAIN_ROLE})

    def is_multi(self, role_name: str) -> bool:
        role = self.roles.get(role_name)
        return role.multi if role is not None else False

    def describe(self, names) -> str:
        """Human form of a role set for findings: sorted, multi-instance
        roles marked ``xN``-style with ``(pool)``."""
        out = []
        for name in sorted(names):
            out.append(f"{name}(multi)" if self.is_multi(name) else name)
        return ", ".join(out)


def _clean_role(hint: str) -> str:
    return _ROLE_STRIP.sub("", hint.strip())


def _role_name(
    kind: str,
    hint: Optional[str],
    target: Optional[str],
    module: str,
    pool_prefixes: List[str],
) -> str:
    if hint:
        cleaned = _clean_role(hint)
        if cleaned:
            return cleaned
    if kind == "pool":
        if len(set(pool_prefixes)) == 1:
            return _clean_role(pool_prefixes[0]) or f"pool[{module.split('.')[-1]}]"
        return f"pool[{module.split('.')[-1]}]"
    if target is not None:
        qual = target.partition(":")[2]
        return f"thread:{qual.split('.<locals>.')[-1]}"
    return f"thread[{module.split('.')[-1]}]"


def build_topology(index: ProjectIndex) -> ThreadTopology:
    roles: Dict[str, ThreadRole] = {}
    unresolved: List[Tuple[str, int, list]] = []
    target_roles: Dict[str, List[str]] = {}  # target node -> role names

    for rel in sorted(index.files):
        f = index.files[rel]
        module = f["module"]
        prefixes = f.get("pool_name_prefixes", [])
        for qual in sorted(f["functions"]):
            ff = f["functions"][qual]
            for kind, line, ref, hint, multi in ff.get("spawns", []):
                target = (
                    index.resolve_ref(module, ff["cls"], qual, ref)
                    if ref is not None
                    else None
                )
                if target is None:
                    unresolved.append((rel, line, ref))
                    continue
                name = _role_name(kind, hint, target, module, prefixes)
                existing = roles.get(name)
                if existing is None:
                    roles[name] = ThreadRole(name, rel, line, target, kind, multi)
                else:
                    # Same role name spawned twice (a second site or a loop
                    # re-spawn): merge conservatively — it is multi now.
                    existing.multi = existing.multi or multi or existing.target != target
                target_roles.setdefault(target, []).append(name)

    # Spawn-target reachability per role. Stop marks do NOT apply: thread
    # identity follows calls regardless of hot/cold annotations.
    fn_roles: Dict[str, Set[str]] = {}
    for target, names in target_roles.items():
        for node in index.reachable([target], stop_marks=()):
            fn_roles.setdefault(node, set()).update(names)

    # The main role: everything reachable from an entry point — a top-level
    # function nobody (resolved) calls and nothing spawns. Spawn targets are
    # excluded as seeds but not as traversal interior: a directly *called*
    # spawn target also runs on the caller's thread.
    has_in_edge: Set[str] = set()
    for outs in index.edges.values():
        for tgt, _line in outs:
            has_in_edge.add(tgt)
    spawn_targets = set(target_roles)
    seeds = [
        node
        for _f, node, ff in index.iter_functions()
        if ff["parent"] is None and node not in has_in_edge and node not in spawn_targets
    ]
    for node in index.reachable(seeds, stop_marks=()):
        fn_roles.setdefault(node, set()).add(MAIN_ROLE)

    # Anything no traversal reached is still caller-callable.
    for _f, node, _ff in index.iter_functions():
        fn_roles.setdefault(node, {MAIN_ROLE})

    return ThreadTopology(roles, fn_roles, unresolved)


def lock_context(index: ProjectIndex, lock_id) -> Dict[str, Set[str]]:
    """Locks definitely held at *every* resolved call site reaching each
    function — greatest fixpoint of ``ctx(f) = ⋂ (site held ∪ ctx(caller))``
    over the call graph. ``lock_id(module, cls, token)`` canonicalizes a
    per-file held token (``self._lock`` / ``mod.NAME``) to a global lock id.

    A helper only ever invoked under a lock (``MicroBatcher._reap_locked``)
    inherits that lock for its attribute accesses; a function with any
    lock-free resolved caller — or no resolved caller at all — has an empty
    context.
    """
    # call sites per callee: callee -> [(caller node, frozenset(held ids))]
    sites: Dict[str, List[Tuple[str, frozenset]]] = {}
    all_locks: Set[str] = set()
    for rel in index.files:
        f = index.files[rel]
        module = f["module"]
        for qual, ff in f["functions"].items():
            caller = f"{module}:{qual}"
            for ref, _line, held, _guards in ff["calls"]:
                callee = index.resolve_ref(module, ff["cls"], qual, ref)
                if callee is None:
                    continue
                held_ids = frozenset(lock_id(module, ff["cls"], tok) for tok in held)
                all_locks |= held_ids
                sites.setdefault(callee, []).append((caller, held_ids))

    top = frozenset(all_locks)
    ctx: Dict[str, Set[str]] = {callee: set(top) for callee in sites}
    changed = True
    while changed:
        changed = False
        for callee, callers in sites.items():
            new: Optional[Set[str]] = None
            for caller, held_ids in callers:
                inherited = set(held_ids) | ctx.get(caller, set())
                new = inherited if new is None else (new & inherited)
            if new is None:
                new = set()
            if new != ctx[callee]:
                ctx[callee] = new
                changed = True
    return ctx


def topology_for(project) -> ThreadTopology:
    """The project's topology, built once per run and cached on the project."""
    topo = getattr(project, "_topology", None)
    if topo is None:
        topo = build_topology(project.index)
        project._topology = topo
    return topo
