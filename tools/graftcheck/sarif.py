"""SARIF 2.1.0 rendering of a graftcheck run.

SARIF (Static Analysis Results Interchange Format) is what CI systems ingest
to surface findings as inline annotations — GitHub code scanning, Gerrit
checks, VS Code's SARIF viewer all speak it. The mapping is deliberately
minimal and lossless: one ``run``, one ``tool.driver`` with the full rule
catalogue (so a clean run still advertises what was checked), one ``result``
per unsuppressed finding with a single physical location.

Severity mapping: graftcheck ``error`` → SARIF level ``error`` (gates CI),
``warning`` → ``warning``. Suppressed findings are emitted with a
``suppressions`` entry (kind ``inSource``) as the spec prescribes, so the
annotation UI can show them greyed out instead of hiding them.
"""
from __future__ import annotations

from typing import Dict, List

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/Schemata/sarif-schema-2.1.0.json"

__all__ = ["to_sarif"]


def _result(finding, suppressed: bool) -> Dict:
    out: Dict = {
        "ruleId": finding.rule,
        "level": finding.severity,
        "message": {"text": finding.message},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {"uri": finding.path, "uriBaseId": "SRCROOT"},
                    "region": {"startLine": finding.line},
                }
            }
        ],
    }
    if suppressed:
        out["suppressions"] = [{"kind": "inSource"}]
    return out


def to_sarif(result, registry: Dict, *, tool_version: str = "2.0") -> Dict:
    """Render a :class:`~tools.graftcheck.engine.RunResult` as a SARIF log."""
    rules: List[Dict] = []
    for name in result.rules_run:
        rule = registry.get(name)
        if rule is None:
            continue
        rules.append(
            {
                "id": rule.name,
                "shortDescription": {"text": rule.description},
                "defaultConfiguration": {"level": rule.severity},
            }
        )
    results = [_result(f, suppressed=False) for f in result.findings]
    results += [_result(f, suppressed=True) for f in result.suppressed]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftcheck",
                        "version": tool_version,
                        "informationUri": "docs/static_analysis.md",
                        "rules": rules,
                    }
                },
                "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
                "results": results,
            }
        ],
    }
