"""graftcheck — the repo's pluggable AST static-analysis suite.

Usage:
    python -m tools.graftcheck flink_ml_tpu            # human output
    python -m tools.graftcheck --format json           # machine output
    python -m tools.graftcheck --list-rules

Importing this package loads the engine and registers the built-in rules;
``tests/test_graftcheck.py`` runs the whole suite as part of tier-1.
"""
from tools.graftcheck.engine import (  # noqa: F401
    Finding,
    Project,
    REGISTRY,
    Rule,
    register,
    run_rules,
)
from tools.graftcheck import rules  # noqa: F401  (registers built-in rules)
