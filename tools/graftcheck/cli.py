"""graftcheck command line: ``python -m tools.graftcheck [targets ...]``.

Exit codes: 0 clean (warnings allowed), 1 error-severity findings (or any
finding with ``--strict``), 2 usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.graftcheck",
        description="AST static analysis: layer, jit-purity, lock-order, "
        "fault-point and error-hygiene invariants.",
    )
    p.add_argument(
        "targets",
        nargs="*",
        default=["flink_ml_tpu"],
        help="files or directories relative to the repo root (default: flink_ml_tpu)",
    )
    p.add_argument("--root", default=REPO_ROOT, help="repo root (default: autodetected)")
    p.add_argument(
        "--rules",
        help="comma-separated subset of rules to run (default: all registered)",
    )
    p.add_argument(
        "--severity",
        action="append",
        default=[],
        metavar="RULE=LEVEL",
        help="override a rule's severity (error|warning); repeatable",
    )
    p.add_argument("--format", choices=("human", "json"), default="human")
    p.add_argument(
        "--strict", action="store_true", help="warnings also fail (exit 1)"
    )
    p.add_argument("--list-rules", action="store_true", help="print the rule catalogue")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if REPO_ROOT not in sys.path:
        sys.path.insert(0, REPO_ROOT)
    from tools.graftcheck.engine import REGISTRY, Project, run_rules
    import tools.graftcheck.rules  # noqa: F401  (registration)

    if args.list_rules:
        for name in sorted(REGISTRY):
            rule = REGISTRY[name]
            print(f"{name:16s} [{rule.severity}] {rule.description}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    overrides = {}
    for spec in args.severity:
        if "=" not in spec:
            print(f"bad --severity {spec!r} (want RULE=error|warning)", file=sys.stderr)
            return 2
        rule, sev = spec.split("=", 1)
        overrides[rule.strip()] = sev.strip()

    for target in args.targets:
        if not os.path.exists(os.path.join(args.root, target)):
            print(f"target {target!r} not found under {args.root}", file=sys.stderr)
            return 2

    project = Project(args.root, args.targets)
    try:
        result = run_rules(project, rules=rules, severity_overrides=overrides)
    except (KeyError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps(result.to_json(), indent=2, sort_keys=True))
    else:
        print(result.render_human())
    if result.errors:
        return 1
    if args.strict and result.findings:
        return 1
    return 0
