"""graftcheck command line: ``python -m tools.graftcheck [targets ...]``.

Exit codes: 0 clean (warnings allowed), 1 error-severity findings (or any
finding with ``--strict``), 2 usage error.

The index cache (``--cache-dir``, default ``<root>/.graftcheck``) makes the
second consecutive run skip every ``ast.parse``; ``--changed-only`` restricts
*reporting* (and the exit code) to files touched per ``git status`` while the
analysis itself stays whole-program — the local pre-commit loop is
sub-second, the full-tree run stays the CI gate.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional, Set

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.graftcheck",
        description="Whole-program static analysis: layer, jit-purity, lock-order, "
        "fault-point, error-hygiene, recompile-hazard, host-sync, "
        "blocking-under-lock, elementwise-claim, fusion-tier, "
        "shared-state-guard and check-then-act invariants over the inferred "
        "thread topology.",
    )
    p.add_argument(
        "targets",
        nargs="*",
        default=["flink_ml_tpu"],
        help="files or directories relative to the repo root (default: flink_ml_tpu)",
    )
    p.add_argument("--root", default=REPO_ROOT, help="repo root (default: autodetected)")
    p.add_argument(
        "--rules",
        help="comma-separated subset of rules to run (default: all registered)",
    )
    p.add_argument(
        "--severity",
        action="append",
        default=[],
        metavar="RULE=LEVEL",
        help="override a rule's severity (error|warning); repeatable",
    )
    p.add_argument("--format", choices=("human", "json", "sarif"), default="human")
    p.add_argument(
        "--strict", action="store_true", help="warnings also fail (exit 1)"
    )
    p.add_argument("--list-rules", action="store_true", help="print the rule catalogue")
    p.add_argument(
        "--timings",
        action="store_true",
        help="print the per-rule wall-time breakdown after the findings "
        "(human format; json always carries rule_times_ms)",
    )
    p.add_argument(
        "--changed-only",
        action="store_true",
        help="report (and gate) only findings in files changed per git status; "
        "the analysis still runs whole-program",
    )
    p.add_argument(
        "--no-cache",
        action="store_true",
        help="disable the on-disk index cache (always re-extract)",
    )
    p.add_argument(
        "--cache-dir",
        default=None,
        metavar="DIR",
        help="index cache directory (default: <root>/.graftcheck)",
    )
    return p


def _changed_files(root: str) -> Optional[Set[str]]:
    """Repo-relative paths touched per git (staged, unstaged and untracked);
    None when git is unavailable — the caller falls back to full reporting."""
    try:
        proc = subprocess.run(
            ["git", "-C", root, "status", "--porcelain", "--untracked-files=all"],
            capture_output=True,
            text=True,
            timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    out: Set[str] = set()
    for line in proc.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:].strip()
        if " -> " in path:  # rename: report the new side
            path = path.split(" -> ", 1)[1]
        out.add(path.strip('"'))
    return out


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if REPO_ROOT not in sys.path:
        sys.path.insert(0, REPO_ROOT)
    from tools.graftcheck.cache import IndexCache, default_cache_path
    from tools.graftcheck.engine import REGISTRY, Project, run_rules
    from tools.graftcheck.sarif import to_sarif
    import tools.graftcheck.rules  # noqa: F401  (registration)

    if args.list_rules:
        for name in sorted(REGISTRY):
            rule = REGISTRY[name]
            print(
                f"{name:28s} [{rule.severity}/{rule.granularity}] {rule.description}"
            )
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    overrides = {}
    for spec in args.severity:
        if "=" not in spec:
            print(f"bad --severity {spec!r} (want RULE=error|warning)", file=sys.stderr)
            return 2
        rule, sev = spec.split("=", 1)
        overrides[rule.strip()] = sev.strip()

    for target in args.targets:
        if not os.path.exists(os.path.join(args.root, target)):
            print(f"target {target!r} not found under {args.root}", file=sys.stderr)
            return 2

    cache = None
    if not args.no_cache:
        cache_path = (
            os.path.join(args.cache_dir, "cache.json")
            if args.cache_dir
            else default_cache_path(args.root)
        )
        cache = IndexCache(cache_path)

    project = Project(args.root, args.targets, cache=cache)
    try:
        result = run_rules(project, rules=rules, severity_overrides=overrides)
    except (KeyError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 2
    project.save_cache()

    if args.changed_only:
        changed = _changed_files(args.root)
        if changed is not None:
            result = result.restricted_to(changed)

    if args.format == "json":
        print(json.dumps(result.to_json(), indent=2, sort_keys=True))
    elif args.format == "sarif":
        print(json.dumps(to_sarif(result, REGISTRY), indent=2, sort_keys=True))
    else:
        print(result.render_human())
        if args.timings:
            print(result.render_timings())
    if result.errors:
        return 1
    if args.strict and result.findings:
        return 1
    return 0
