"""The shared project index — graftcheck v2's whole-program analysis core.

PR 3's rules each walked file ASTs independently, so every invariant was
per-function syntax. The hazards that actually destroy TPU goodput — silent
recompiles, implicit device→host syncs on the serving path, blocking work
under serving locks — are *cross-module* properties: a `.item()` three calls
below the dispatch loop, a `time.sleep` inside a helper invoked while a lock
is held. This module builds, once per run, everything those rules query:

- a **symbol table**: every module / class / function (methods and nested
  defs included) with its parameters, decorators and graftcheck markers;
- a **resolved import graph**: ``from X import f as g`` bindings, module
  aliases, and re-export chains followed into project modules;
- a **call graph** with method resolution on known classes: ``self.m()``,
  ``self.attr.m()`` on constructor/annotation-typed attributes, module
  singletons (``metrics = MetricsRegistry()``), imported functions and
  singletons, constructors, lexically scoped nested defs, and one level of
  return-type inference (``self.dispatch(x).finalize()`` resolves when every
  ``return`` of ``dispatch`` is ``PlanExecution(...)``);
- **per-file rule facts** extracted in the same AST pass: lock acquisitions
  and calls-while-holding, blocking-operation sites, host-sync sites, jit
  construction / jitted-call sites, branch-on-parameter sites, reduction
  primitives, KernelSpec constructions, fault trip sites, kernels imports,
  **thread spawn sites** (``threading.Thread(target=...)`` / ``Timer`` /
  executor ``submit``/``map``) and **per-``self.X`` attribute accesses**
  with the lexically held locks and lock-region identity at each access —
  the raw material of graftcheck v3's thread-topology inference
  (``tools/graftcheck/topology.py``) and lockset race detection.

Everything per-file is a plain-JSON value keyed by the file's content hash,
which is what makes the on-disk cache (``tools/graftcheck/cache.py``)
incremental: an unchanged file's facts (and its file-local rule findings)
load back without re-parsing, so a warm run never calls ``ast.parse``.

Marker convention (the annotated-hot-root contract, docs/static_analysis.md):

- ``# graftcheck: hot-root`` on a ``def`` line — the function is a serving /
  batch hot region root; everything reachable from it through the call graph
  is "hot" (host-sync and recompile-hazard police it).
- ``# graftcheck: readback`` — the function IS a designated device→host sync
  boundary (the plan's single blocking readback); traversal stops here.
- ``# graftcheck: cold`` — reachable from a hot root only on a lazily-taken
  build/warmup edge (counted by its own metric); excluded from the hot region.
- ``# graftcheck: ingest`` — the function IS a designated host→device ingest
  boundary (the plan tier's blessed ``device_put``, one per chunk/shard);
  ``device_put`` inside it is exempt from host-sync's hot-region flagging,
  everything else still applies.
- ``# graftcheck: serialized`` on a ``class`` line — instances of the class
  cross threads only through an ownership handoff (a queue put/get, an
  ``Event`` wait, the registry's atomic publish) that orders every access;
  the lockset race detector trusts the documented handoff instead of
  demanding a per-instance lock. Inherited by subclasses.
- ``# graftcheck: owned-by=<role>`` on a ``self.X = ...`` line — the field
  is deliberately single-writer: only the named thread role (see
  ``tools/graftcheck/topology.py``) ever writes it after ``__init__``;
  reads from other roles accept benign staleness. The detector *verifies*
  the claim: a write from any other role is an error.
"""
from __future__ import annotations

import ast
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "FACTS_VERSION",
    "KERNELS_MODULE",
    "KERNEL_ALIASES",
    "kernel_base",
    "extract_facts",
    "ProjectIndex",
]

#: Bump whenever the shape/semantics of extracted facts change — it is part of
#: the disk-cache key, so stale caches self-invalidate.
FACTS_VERSION = 6  # 6: low-precision cast sites (the precision-tier boundary contract)

KERNELS_MODULE = "flink_ml_tpu.ops.kernels"

#: fn-name base -> factory-name base for kernel pairs that predate the
#: *_fn/*_kernel naming convention (the factory jits exactly that fn body).
KERNEL_ALIASES = {
    "kmeans_predict": "kmeans_assign",
    "logistic_predict": "logistic_from_dots",
    "dct_basis": "dct",  # the basis builder is part of the dct body pairing
}

#: Cross-element accumulation primitives — anything here inside an
#: ``elementwise=True`` kernel body breaks the PR 5 merge contract.
REDUCTION_PRIMS = {
    "sum", "dot", "mean", "median", "einsum", "matmul", "tensordot", "vdot",
    "cumsum", "cumprod", "prod", "sort", "argsort", "argmax", "argmin",
    "norm", "std", "var",
    # The sparse convention's row segment-sum (ops/kernels.segment_sum): a
    # sequential fold, but a cross-entry accumulation all the same — a
    # sparse reduction spec must never merge into an elementwise run.
    "segment_sum",
}

#: Function names whose bodies define a KernelSpec (the dense protocol and
#: the sparse-convention hook) — kernel-spec-consistency and
#: elementwise-claim treat both identically.
SPEC_DEF_NAMES = ("kernel_spec", "sparse_kernel_spec")

#: Sub-f32 dtype tokens. A cast to one of these inside a kernel body breaks
#: the precision-tier boundary contract (servable/precision.py): kernel math
#: — above all its accumulators — is always f32; the tier's rounding happens
#: at program ingest/stage boundaries in the planner, never in-body.
LOWP_DTYPE_TOKENS = {
    "bfloat16", "float16", "half", "int8", "uint8",
    "float8_e4m3fn", "float8_e5m2",
    "bf16", "fp16", "f16",  # string-literal spellings
}

_LOCK_CTORS = {"Lock", "RLock"}
_TIME_ATTRS = {"time", "perf_counter", "monotonic", "time_ns", "perf_counter_ns"}
_OS_BLOCKING = {
    "listdir", "scandir", "makedirs", "mkdir", "remove", "unlink", "rename",
    "replace", "stat", "rmdir", "walk", "fsync",
}
_MEMO_DECORATORS = {"cache", "lru_cache"}

KNOWN_MARKS = ("hot-root", "readback", "cold", "ingest", "serialized")

#: key=value marker keys (``disable=`` belongs to the engine's suppressions).
OWNED_BY_KEY = "owned-by"

#: Container-method calls on a ``self.X`` attribute that mutate the
#: container — a write for lockset purposes (``self._queue.append(r)``).
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "insert", "add", "discard", "remove",
    "pop", "popleft", "popitem", "clear", "update", "setdefault", "sort",
    "reverse",
}

_MARK_RE = re.compile(r"#\s*graftcheck:\s*([A-Za-z0-9_\-,=\s]+)")


def kernel_base(name: str) -> str:
    """Normalize an ops/kernels.py symbol to its shared-body base."""
    for suffix in ("_kernel", "_fn"):
        if name.endswith(suffix):
            name = name[: -len(suffix)]
            break
    return KERNEL_ALIASES.get(name, name)


def _line_marks(lines: Sequence[str], lineno: int) -> List[str]:
    """graftcheck markers on a source line (1-based); ``disable=`` tokens are
    suppressions and belong to the engine, not the marker set."""
    if not 1 <= lineno <= len(lines):
        return []
    m = _MARK_RE.search(lines[lineno - 1])
    if not m:
        return []
    out = []
    for tok in m.group(1).split(","):
        tok = tok.strip()
        if tok and "=" not in tok and tok in KNOWN_MARKS:
            out.append(tok)
    return out


def _line_kv_marks(lines: Sequence[str], lineno: int) -> Dict[str, str]:
    """``key=value`` graftcheck markers on a source line (``owned-by=role``);
    ``disable=`` tokens are suppressions and belong to the engine."""
    if not 1 <= lineno <= len(lines):
        return {}
    m = _MARK_RE.search(lines[lineno - 1])
    if not m:
        return {}
    out: Dict[str, str] = {}
    for tok in m.group(1).split(","):
        tok = tok.strip()
        if "=" in tok:
            key, _, value = tok.partition("=")
            key, value = key.strip(), value.strip()
            if key and key != "disable" and value:
                out[key] = value
    return out


def _empty_facts(rel: str, module: str) -> Dict[str, Any]:
    return {
        "v": FACTS_VERSION,
        "rel": rel,
        "module": module,
        "parse_error": None,
        "imports": [],  # [line, absolute dotted module] (iter_imports semantics)
        "bindings": {},  # local name -> [source module, original name]
        "module_aliases": {},  # local name -> module ("import x.y as z")
        "singletons": {},  # module-level name -> class simple name
        "module_locks": {},  # module-level name -> def line
        "classes": {},
        "functions": {},
        "jit_passed": {},  # fn name passed to jit(...) -> {"static": bool}
        "jit_bound": {},  # module-level name bound to jit(...) -> {"static": bool}
        "kernels": {"imports": {}, "outside": [], "specs": []},
        "kspec_ctors": [],
        "trip_sites": [],  # [point name, line]
        "pool_name_prefixes": [],  # ThreadPoolExecutor thread_name_prefix literals
        # contract-registry facts (v5): declarations and references of the two
        # string-keyed registries — config options and ml.* metric names.
        "config_options": [],  # [attr, literal key, line]  (X = ConfigOption("key"))
        "option_refs": [],  # [attr, line]  (every Options.X reference, any context)
        "metric_consts": [],  # [attr, value, line]  (class-body X = "ml...")
        "metric_refs": [],  # [attr, line]  (every MLMetrics.X reference)
        "metric_literals": [],  # [value, line]  (inline "ml.*" string constants)
    }


def _ctor_class_name(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _handler_class_names(type_expr: Optional[ast.AST]) -> List[str]:
    """Class names an ``except`` clause catches; ``"*"`` for a bare except."""
    if type_expr is None:
        return ["*"]
    if isinstance(type_expr, ast.Name):
        return [type_expr.id]
    if isinstance(type_expr, ast.Attribute):
        return [type_expr.attr]
    if isinstance(type_expr, ast.Tuple):
        out: List[str] = []
        for elt in type_expr.elts:
            out.extend(_handler_class_names(elt))
        return out
    return ["*"]  # dynamic handler expression: assume it catches


def _handler_reraises(h: ast.ExceptHandler) -> bool:
    """True when the handler re-raises the caught exception (bare ``raise``
    or ``raise e`` of its alias) somewhere in its body — the observe-and-
    rethrow idiom. Such a handler is *transparent* for escape purposes: it
    never swallows, so its classes must not join the lexical catcher set.
    A conditionally-swallowing handler still counts as transparent; that errs
    toward reporting, never toward hiding an escape."""
    for sub in ast.walk(h):
        if isinstance(sub, ast.Raise):
            if sub.exc is None:
                return True
            if isinstance(sub.exc, ast.Name) and h.name and sub.exc.id == h.name:
                return True
    return False


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _annotation_name(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.strip("\"'")
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript):
        # Optional[X] types like X for resolution (None adds no methods).
        base = node.value
        if isinstance(base, ast.Name) and base.id == "Optional":
            return _annotation_name(node.slice)
    return None


def _is_jit_expr(node: ast.AST, jit_names: Set[str]) -> bool:
    if isinstance(node, ast.Name):
        return node.id in jit_names
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return isinstance(node.value, ast.Name)
    return False


def _has_static_args(call: ast.Call) -> bool:
    return any(kw.arg in ("static_argnums", "static_argnames") for kw in call.keywords)


def _static_param_names(fn: ast.AST, dec: ast.Call) -> List[str]:
    """Best-effort names of statically-declared params of a jitted def."""
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    out: List[str] = []
    for kw in dec.keywords:
        if kw.arg == "static_argnums":
            vals = kw.value.elts if isinstance(kw.value, ast.Tuple) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    if 0 <= v.value < len(params):
                        out.append(params[v.value])
        elif kw.arg == "static_argnames":
            vals = kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    out.append(v.value)
    return out


class _ClassInfo:
    __slots__ = (
        "name", "line", "bases", "locks", "aliases", "attr_types",
        "event_attrs", "queue_attrs", "thread_attrs", "marks", "attr_marks",
    )

    def __init__(self, node: ast.ClassDef, lines: Sequence[str]):
        self.name = node.name
        self.line = node.lineno
        self.bases = [b.id for b in node.bases if isinstance(b, ast.Name)]
        self.locks: Dict[str, int] = {}
        self.aliases: Dict[str, str] = {}
        self.attr_types: Dict[str, str] = {}
        self.event_attrs: List[str] = []
        self.queue_attrs: List[str] = []
        self.thread_attrs: List[str] = []
        #: graftcheck flag marks on the ``class`` line ("serialized").
        self.marks = _line_marks(lines, node.lineno)
        #: attr -> owning thread role, from ``# graftcheck: owned-by=<role>``
        #: on any ``self.X = ...`` line in any method.
        self.attr_marks: Dict[str, str] = {}

    def lock_attr(self, attr: str) -> Optional[str]:
        attr = self.aliases.get(attr, attr)
        return attr if attr in self.locks else None

    def to_json(self) -> Dict[str, Any]:
        return {
            "line": self.line,
            "bases": self.bases,
            "locks": self.locks,
            "aliases": self.aliases,
            "attr_types": self.attr_types,
            "event_attrs": self.event_attrs,
            "queue_attrs": self.queue_attrs,
            "thread_attrs": self.thread_attrs,
            "marks": self.marks,
            "attr_marks": self.attr_marks,
        }


def _collect_class_info(tree: ast.AST, lines: Sequence[str]) -> Dict[str, _ClassInfo]:
    """Pre-pass: lock/alias/typed-attr structure of every class, gathered from
    every ``self.X = ...`` assignment in any method (the lock-order pass-1
    semantics, now shared by every rule through the index)."""
    out: Dict[str, _ClassInfo] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        ci = _ClassInfo(node, lines)
        out[node.name] = ci
        for item in node.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            ann = {
                a.arg: _annotation_name(a.annotation)
                for a in item.args.args + item.args.kwonlyargs
            }
            for sub in ast.walk(item):
                if isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                    attr = _self_attr(sub.target)
                    if attr is not None:
                        owner = _line_kv_marks(lines, sub.lineno).get(OWNED_BY_KEY)
                        if owner:
                            ci.attr_marks.setdefault(attr, owner)
                        if isinstance(sub, ast.AnnAssign):
                            # `self.x: Cls = ...` types the attribute like an
                            # annotated-param assignment does.
                            tname = _annotation_name(sub.annotation)
                            if tname:
                                ci.attr_types.setdefault(attr, tname)
                    continue
                if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1):
                    continue
                attr = _self_attr(sub.targets[0])
                if attr is None:
                    continue
                owner = _line_kv_marks(lines, sub.lineno).get(OWNED_BY_KEY)
                if owner:
                    ci.attr_marks.setdefault(attr, owner)
                val = sub.value
                if isinstance(val, ast.Call) and isinstance(val.func, ast.Attribute):
                    ctor = val.func.attr
                    if ctor in _LOCK_CTORS:
                        ci.locks[attr] = sub.lineno
                    elif ctor == "Condition":
                        inner = _self_attr(val.args[0]) if val.args else None
                        if inner is not None:
                            ci.aliases[attr] = inner
                        else:
                            ci.locks[attr] = sub.lineno  # owns its lock
                    elif ctor == "Event":
                        ci.event_attrs.append(attr)
                    elif ctor == "Queue":
                        ci.queue_attrs.append(attr)
                    elif ctor == "Thread":
                        ci.thread_attrs.append(attr)
                elif isinstance(val, ast.Call):
                    ctor = _ctor_class_name(val)
                    if ctor == "Event":
                        ci.event_attrs.append(attr)
                    elif ctor == "Queue":
                        ci.queue_attrs.append(attr)
                    elif ctor == "Thread":
                        ci.thread_attrs.append(attr)
                    elif ctor is not None:
                        ci.attr_types[attr] = ctor
                elif isinstance(val, ast.Name) and ann.get(val.id):
                    ci.attr_types[attr] = ann[val.id]
    return out


class _Extractor:
    """One recursive pass over a parsed module, carrying the context the flat
    ``ast.walk`` rules could never see: enclosing function/class, loop depth,
    and the set of locks lexically held."""

    def __init__(self, rel: str, module: str, source: str, tree: ast.AST):
        self.facts = _empty_facts(rel, module)
        self.module = module
        self.lines = source.splitlines()
        self.tree = tree
        self.classes = _collect_class_info(tree, self.lines)
        self.facts["classes"] = {n: ci.to_json() for n, ci in self.classes.items()}
        # Aliases for numpy / time / jax.jit spellings in this module (first:
        # the module prepass needs the jit spellings for `x = jit(f)` bindings).
        self.np_names: Set[str] = set()
        self.time_names: Set[str] = set()
        self.time_funcs: Set[str] = set()
        self.jit_names: Set[str] = set()
        self.jax_names: Set[str] = set()
        self._alias_prepass(tree)
        self._module_prepass(tree)

    # -- module-level prepasses ----------------------------------------------
    def _module_prepass(self, tree: ast.AST) -> None:
        f = self.facts
        is_init = f["rel"].endswith("/__init__.py")
        parts = self.module.split(".")
        package = parts if is_init else parts[:-1]
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    f["imports"].append([node.lineno, alias.name])
                    f["module_aliases"][alias.asname or alias.name.split(".")[0]] = (
                        alias.name if alias.asname else alias.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = package[: len(package) - (node.level - 1)]
                    mod = ".".join(base + ([node.module] if node.module else []))
                else:
                    mod = node.module or ""
                if not mod:
                    continue
                f["imports"].append([node.lineno, mod])
                for alias in node.names:
                    f["imports"].append([node.lineno, f"{mod}.{alias.name}"])
                    f["bindings"][alias.asname or alias.name] = [mod, alias.name]
                if mod == KERNELS_MODULE:
                    for alias in node.names:
                        f["kernels"]["imports"][alias.asname or alias.name] = kernel_base(
                            alias.name
                        )
        for node in tree.body:
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                cname = _ctor_class_name(node.value)
                for tgt in node.targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    val = node.value
                    if (
                        isinstance(val.func, ast.Attribute)
                        and val.func.attr in _LOCK_CTORS
                    ):
                        f["module_locks"][tgt.id] = node.lineno
                    elif _is_jit_expr(val.func, self.jit_names):
                        f["jit_bound"][tgt.id] = {"static": _has_static_args(val)}
                    elif cname:
                        f["singletons"][tgt.id] = cname

    def _alias_prepass(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    bound = alias.asname or alias.name
                    if alias.name == "numpy":
                        self.np_names.add(bound)
                    elif alias.name == "time":
                        self.time_names.add(bound)
                    elif alias.name == "jax":
                        self.jax_names.add(bound)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "time":
                    for alias in node.names:
                        if alias.name in _TIME_ATTRS:
                            self.time_funcs.add(alias.asname or alias.name)
                elif node.module == "jax":
                    for alias in node.names:
                        if alias.name == "jit":
                            self.jit_names.add(alias.asname or alias.name)

    # -- main walk ------------------------------------------------------------
    def run(self) -> Dict[str, Any]:
        for stmt in self.tree.body:
            self._walk_toplevel(stmt, cls=None)
        self._second_pass_jitted()
        self._registry_pass()
        return self.facts

    def _registry_pass(self) -> None:
        """Flat sweep for the contract registries: ``ConfigOption``/``"ml.*"``
        declarations in class bodies, and every ``Options.X`` /
        ``MLMetrics.X`` / inline ``"ml.*"`` reference anywhere in the module
        (module level included — a read at import time is still a read)."""
        f = self.facts
        const_lines: Set[int] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if not (
                        isinstance(item, ast.Assign)
                        and len(item.targets) == 1
                        and isinstance(item.targets[0], ast.Name)
                    ):
                        continue
                    attr, val = item.targets[0].id, item.value
                    if (
                        isinstance(val, ast.Call)
                        and _ctor_class_name(val) == "ConfigOption"
                        and val.args
                        and isinstance(val.args[0], ast.Constant)
                        and isinstance(val.args[0].value, str)
                    ):
                        f["config_options"].append([attr, val.args[0].value, item.lineno])
                    elif (
                        isinstance(val, ast.Constant)
                        and isinstance(val.value, str)
                        and val.value.startswith("ml.")
                    ):
                        f["metric_consts"].append([attr, val.value, item.lineno])
                        const_lines.add(item.lineno)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
                if node.value.id == "Options":
                    f["option_refs"].append([node.attr, node.lineno])
                elif node.value.id == "MLMetrics":
                    f["metric_refs"].append([node.attr, node.lineno])
            elif (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and node.value.startswith("ml.")
                and node.lineno not in const_lines
            ):
                f["metric_literals"].append([node.value, node.lineno])

    def _walk_toplevel(self, node: ast.AST, cls: Optional[str]) -> None:
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                self._walk_toplevel(item, cls=node.name)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._extract_function(node, cls=cls, parent=None)
            return
        # module-level statements: jit-by-name bindings, trip sites, kernels refs
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._record_module_call(sub)
            elif isinstance(sub, ast.Name) and sub.id in self.facts["kernels"]["imports"]:
                base = self.facts["kernels"]["imports"][sub.id]
                if base not in self.facts["kernels"]["outside"]:
                    self.facts["kernels"]["outside"].append(base)

    def _record_module_call(self, call: ast.Call) -> None:
        if _is_jit_expr(call.func, self.jit_names) and call.args:
            target = call.args[0]
            if isinstance(target, ast.Name):
                self.facts["jit_passed"].setdefault(
                    target.id, {"static": _has_static_args(call)}
                )
        point = _trip_point(call)
        if point is not None:
            self.facts["trip_sites"].append([point, call.lineno])

    # -- per-function extraction ----------------------------------------------
    def _extract_function(
        self, fn: ast.AST, cls: Optional[str], parent: Optional[str]
    ) -> None:
        qual = (
            f"{parent}.<locals>.{fn.name}"
            if parent
            else (f"{cls}.{fn.name}" if cls else fn.name)
        )
        a = fn.args
        params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            params.append(a.vararg.arg)
        if a.kwarg:
            params.append(a.kwarg.arg)
        params = [p for p in params if p != "self"]

        is_jitted = False
        static_names: List[str] = []
        has_static = False
        memoized = False
        for dec in getattr(fn, "decorator_list", []):
            if _is_jit_expr(dec, self.jit_names):
                is_jitted = True
            elif isinstance(dec, ast.Call):
                if _is_jit_expr(dec.func, self.jit_names):
                    is_jitted = True
                    has_static = has_static or _has_static_args(dec)
                    static_names += _static_param_names(fn, dec)
                is_partial = (
                    isinstance(dec.func, ast.Name) and dec.func.id == "partial"
                ) or (isinstance(dec.func, ast.Attribute) and dec.func.attr == "partial")
                if is_partial and any(
                    _is_jit_expr(x, self.jit_names) for x in dec.args
                ):
                    is_jitted = True
                    has_static = has_static or _has_static_args(dec)
                    static_names += _static_param_names(fn, dec)
                if (
                    isinstance(dec.func, ast.Name) and dec.func.id in _MEMO_DECORATORS
                ) or (
                    isinstance(dec.func, ast.Attribute)
                    and dec.func.attr in _MEMO_DECORATORS
                ):
                    memoized = True
            elif isinstance(dec, ast.Name) and dec.id in _MEMO_DECORATORS:
                memoized = True
            elif isinstance(dec, ast.Attribute) and dec.attr in _MEMO_DECORATORS:
                memoized = True

        ff: Dict[str, Any] = {
            "line": fn.lineno,
            "name": fn.name,
            "cls": cls,
            "parent": parent,
            "params": params,
            "is_jitted": is_jitted,
            "has_static": has_static,
            "static_names": sorted(set(static_names)),
            "memoized": memoized,
            "marks": _line_marks(self.lines, fn.lineno),
            "returns_class": None,
            "calls": [],  # [ref, line, [held lock tokens]]
            "acquires": [],  # canonical lock tokens directly acquired
            "nest_edges": [],  # [outer, inner, line]
            "blocking": [],  # [kind, line, detail, [held]]
            "sync_sites": [],  # [kind, line, detail]
            "jit_sites": [],  # [line, form, binding, in_loop]
            "jitted_call_sites": [],  # [callee, line, [loop-var args]]
            "param_branches": [],  # [line, [param names in value-wise branch test]]
            "scalar_loop_vars": [],
            "reductions": [],  # [prim, line]
            "casts": [],  # [lowp dtype token, line] — astype/convert_element_type/dtype=
            "is_kernel_spec": fn.name in SPEC_DEF_NAMES,
            "spec_trivial": True,
            "spec_refs": [],  # kernel bases referenced inside (kernel_spec only)
            "spec_names": [],  # original imported kernel names referenced inside
            "config_reads": [],  # [Options attr, line]  (.get(Options.X) sites)
            "raises": [],  # [class name or None, line, [lexical catcher names], detail]
            "spawns": [],  # [kind, line, target ref or None, name hint or None, multi]
            "attr_accesses": [],  # [attr, "r"|"w"|"m", line, [held], [regions]]
            "local_types": {},  # annotated locals: `x: Cls = ...` -> {"x": "Cls"}
        }
        self.facts["functions"][qual] = ff

        # Annotated parameters type their locals for method resolution,
        # like annotated attrs and `x: Cls = ...` assignments do.
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            tname = _annotation_name(p.annotation)
            if tname and p.arg != "self":
                ff["local_types"].setdefault(p.arg, tname)

        ci = self.classes.get(cls) if cls else None
        returns: List[Optional[str]] = []
        self._body_walk(fn, ff, qual, ci, held=[], loop=0, returns=returns)
        if returns and all(r is not None and r == returns[0] for r in returns):
            ff["returns_class"] = returns[0]
        if ff["is_kernel_spec"]:
            ff["spec_trivial"] = _spec_trivial(fn)

    def _lock_token(self, ci: Optional[_ClassInfo], expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and ci is not None:
            canon = ci.lock_attr(attr)
            if canon is not None:
                return f"self.{canon}"
            return None
        if isinstance(expr, ast.Name) and expr.id in self.facts["module_locks"]:
            return f"mod.{expr.id}"
        return None

    def _body_walk(
        self,
        fn: ast.AST,
        ff: Dict[str, Any],
        qual: str,
        ci: Optional[_ClassInfo],
        held: List[str],
        loop: int,
        returns: List[Optional[str]],
    ) -> None:
        def walk(
            node: ast.AST,
            held: List[str],
            regions: List[str],
            loop: int,
            comp: int,
            guards: List[str],
            handler,
        ) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._extract_function(node, cls=ff["cls"], parent=qual)
                return
            if isinstance(node, ast.Lambda):
                return
            if isinstance(node, ast.Try):
                catchers: List[str] = []
                for h in node.handlers:
                    if not _handler_reraises(h):
                        catchers.extend(_handler_class_names(h.type))
                for stmt in node.body:
                    walk(stmt, held, regions, loop, comp, guards + catchers, handler)
                for h in node.handlers:
                    hcls = _handler_class_names(h.type)
                    for stmt in h.body:
                        walk(stmt, held, regions, loop, comp, guards, (hcls, h.name))
                for stmt in node.orelse + node.finalbody:
                    walk(stmt, held, regions, loop, comp, guards, handler)
                return
            if isinstance(node, ast.Raise):
                self._record_raise(node, ff, guards, handler)
            if isinstance(node, ast.Return):
                val = node.value
                returns.append(
                    _ctor_class_name(val) if isinstance(val, ast.Call) else None
                )
            if isinstance(node, ast.With):
                acquired: List[str] = []
                acquired_regions: List[str] = []
                for item in node.items:
                    token = self._lock_token(ci, item.context_expr)
                    if token is not None:
                        ff["acquires"].append(token)
                        for h in held:
                            ff["nest_edges"].append([h, token, node.lineno])
                        acquired.append(token)
                        acquired_regions.append(f"{token}@{node.lineno}")
                    else:
                        walk(item.context_expr, held, regions, loop, comp, guards, handler)
                for stmt in node.body:
                    walk(stmt, held + acquired, regions + acquired_regions, loop, comp, guards, handler)
                return
            if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
                if isinstance(node, ast.For):
                    self._note_scalar_loop_var(node, ff)
                    walk(node.iter, held, regions, loop, comp, guards, handler)
                    walk(node.target, held, regions, loop, comp, guards, handler)
                elif isinstance(node, ast.While):
                    walk(node.test, held, regions, loop, comp, guards, handler)
                for stmt in node.body + node.orelse:
                    walk(stmt, held, regions, loop + 1, comp, guards, handler)
                return
            if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
                # Comprehensions iterate like loops, but only spawn-site
                # multiplicity cares — the jit-construction loop counter
                # keeps its original (statement-loop) semantics.
                for child in ast.iter_child_nodes(node):
                    walk(child, held, regions, loop, comp + 1, guards, handler)
                return
            if isinstance(node, (ast.If, ast.IfExp)):
                self._note_param_branch(node.test, ff)
            if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                tname = _annotation_name(node.annotation)
                if tname:  # `stats: StepStats = ...` types the local for resolution
                    ff["local_types"].setdefault(node.target.id, tname)
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                # `x = Cls(...)` and `x = self.typed_attr` type the local too
                # (a local binding shadows module singletons either way).
                val = node.value
                tname = None
                if isinstance(val, ast.Call):
                    tname = _ctor_class_name(val)
                else:
                    src_attr = _self_attr(val)
                    if src_attr is not None and ci is not None:
                        tname = ci.attr_types.get(src_attr)
                if tname:
                    ff["local_types"].setdefault(node.targets[0].id, tname)
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
                ff["reductions"].append(["matmul", node.lineno])
            if isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if attr is not None:
                    mode = "w" if isinstance(node.ctx, (ast.Store, ast.Del)) else "r"
                    ff["attr_accesses"].append(
                        [attr, mode, node.lineno, list(held), list(regions)]
                    )
            if isinstance(node, ast.Subscript) and isinstance(node.ctx, (ast.Store, ast.Del)):
                attr = _self_attr(node.value)
                if attr is not None:  # self.X[i] = v mutates the container
                    ff["attr_accesses"].append(
                        [attr, "m", node.lineno, list(held), list(regions)]
                    )
            if isinstance(node, ast.Call):
                self._record_call(node, ff, ci, held, regions, loop, comp, guards)
            for child in ast.iter_child_nodes(node):
                walk(child, held, regions, loop, comp, guards, handler)

        for stmt in fn.body:
            walk(stmt, list(held), [], loop, 0, [], None)

    def _note_scalar_loop_var(self, node: ast.For, ff: Dict[str, Any]) -> None:
        """Loop variables that are definitely Python scalars: ``for i in
        range(...)`` and the counter of ``for i, x in enumerate(...)``."""
        it = node.iter
        if not isinstance(it, ast.Call) or not isinstance(it.func, ast.Name):
            return
        if it.func.id == "range" and isinstance(node.target, ast.Name):
            ff["scalar_loop_vars"].append(node.target.id)
        elif (
            it.func.id == "enumerate"
            and isinstance(node.target, ast.Tuple)
            and node.target.elts
            and isinstance(node.target.elts[0], ast.Name)
        ):
            ff["scalar_loop_vars"].append(node.target.elts[0].id)

    def _note_param_branch(self, test: ast.AST, ff: Dict[str, Any]) -> None:
        """Names a branch test depends on *by value*: bare parameter reads,
        excluding reads that only touch static metadata (``p.shape`` /
        ``p.ndim`` / ``p.dtype`` — legal trace-time constants)."""
        params = set(ff["params"])
        hits: Set[str] = set()
        shape_parents: Set[int] = set()
        for sub in ast.walk(test):
            if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim", "dtype"):
                for inner in ast.walk(sub.value):
                    shape_parents.add(id(inner))
        for sub in ast.walk(test):
            if (
                isinstance(sub, ast.Name)
                and sub.id in params
                and id(sub) not in shape_parents
            ):
                hits.add(sub.id)
        if hits:
            ff["param_branches"].append([test.lineno, sorted(hits)])

    # -- per-call classification ----------------------------------------------
    def _record_raise(
        self, node: ast.Raise, ff: Dict[str, Any], guards: List[str], handler
    ) -> None:
        """Raise-site fact: resolved class name (or None when dynamic), the
        lexically enclosing catcher names, and a detail string for diagnostics.
        A bare ``raise``/``raise e`` inside an except clause re-raises the
        handler's own classes (not re-caught by that same try)."""
        exc = node.exc
        line = node.lineno
        if exc is None or (
            isinstance(exc, ast.Name) and handler is not None and exc.id == handler[1]
        ):
            # Re-raise of the caught exception: the original raise sites (and
            # callee escapes) already carry through, because a re-raising
            # handler is transparent — recording it again would only lose the
            # resolved class. A bare ``raise`` outside any handler is dynamic.
            if handler is None:
                ff["raises"].append([None, line, list(guards), "bare raise"])
            return
        if isinstance(exc, ast.Call):
            func = exc.func
            name = (
                func.id
                if isinstance(func, ast.Name)
                else (func.attr if isinstance(func, ast.Attribute) else None)
            )
            ff["raises"].append([name, line, list(guards), ""])
        elif isinstance(exc, ast.Name):
            typed = ff["local_types"].get(exc.id)
            if typed is not None:  # annotated param/local: `e: ServingError`
                ff["raises"].append([typed, line, list(guards), ""])
            else:
                ff["raises"].append([exc.id, line, list(guards), "name"])
        else:
            detail = ast.unparse(exc) if hasattr(ast, "unparse") else "dynamic"
            ff["raises"].append([None, line, list(guards), detail])

    def _record_call(
        self,
        call: ast.Call,
        ff: Dict[str, Any],
        ci: Optional[_ClassInfo],
        held: List[str],
        regions: List[str],
        loop: int,
        comp: int,
        guards: List[str],
    ) -> None:
        func = call.func
        ref = _call_ref(func)
        if ref is not None:
            ff["calls"].append([ref, call.lineno, list(held), list(guards)])
        # config-option read site: any ``.get(Options.X)`` (the uniform read
        # idiom — ``config.get`` and wrapped configurations alike).
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "get"
            and call.args
            and isinstance(call.args[0], ast.Attribute)
            and isinstance(call.args[0].value, ast.Name)
            and call.args[0].value.id == "Options"
        ):
            ff["config_reads"].append([call.args[0].attr, call.lineno])

        # thread spawn sites + container-mutator writes
        self._classify_spawn(call, ff, loop, comp)
        if isinstance(func, ast.Attribute) and func.attr in MUTATOR_METHODS:
            tattr = _self_attr(func.value)
            if tattr is not None:
                ff["attr_accesses"].append(
                    [tattr, "m", call.lineno, list(held), list(regions)]
                )

        point = _trip_point(call)
        if point is not None:
            self.facts["trip_sites"].append([point, call.lineno])

        # jit construction / jit-by-name sites
        if _is_jit_expr(func, self.jit_names) and (call.args or call.keywords):
            target = call.args[0] if call.args else None
            form = "bare"
            if isinstance(target, ast.Lambda):
                form = "lambda"
            elif isinstance(target, ast.Name):
                form = "named"
                self.facts["jit_passed"].setdefault(
                    target.id, {"static": _has_static_args(call)}
                )
            ff["jit_sites"].append([call.lineno, form, "expr", loop > 0])
        if (
            isinstance(func, ast.Call)
            and _is_jit_expr(func.func, self.jit_names)
        ):
            # jit(f)(args): construct-and-invoke in one expression
            ff["jit_sites"].append([call.lineno, "immediate", "call", loop > 0])

        # blocking-operation classification
        self._classify_blocking(call, ff, ci, held)
        # host-sync classification
        self._classify_sync(call, ff)
        # reduction primitives
        prim = _reduction_prim(call)
        if prim is not None:
            ff["reductions"].append([prim, call.lineno])
        # low-precision cast sites (the precision-tier boundary contract)
        tok = _lowp_cast_token(call)
        if tok is not None:
            ff["casts"].append([tok, call.lineno])

        # jitted-by-name call sites with scalar loop-var args
        if isinstance(func, ast.Name):
            loop_args = [
                arg.id
                for arg in call.args
                if isinstance(arg, ast.Name) and arg.id in ff["scalar_loop_vars"]
            ]
            if loop_args:
                ff["jitted_call_sites"].append([func.id, call.lineno, loop_args])

    def _classify_spawn(self, call: ast.Call, ff: Dict[str, Any], loop: int, comp: int) -> None:
        """Thread spawn sites: ``threading.Thread(target=f)`` / ``Timer``
        constructions and executor ``submit(f, ...)`` / ``map(f, xs)`` calls.
        ``multi`` marks spawn sites that can create several threads sharing
        the same state (inside a loop/comprehension, or any pool)."""
        func = call.func
        multi = loop > 0 or comp > 0
        ctor: Optional[str] = None
        if isinstance(func, ast.Attribute) and func.attr in ("Thread", "Timer"):
            ctor = func.attr
        elif isinstance(func, ast.Name) and func.id in ("Thread", "Timer"):
            ctor = func.id
        if ctor is not None:
            target = None
            hint = None
            for kw in call.keywords:
                if kw.arg == "target":
                    target = _call_ref(kw.value)
                elif kw.arg == "name":
                    hint = _name_literal(kw.value)
            if ctor == "Timer" and target is None and len(call.args) >= 2:
                target = _call_ref(call.args[1])
            ff["spawns"].append(["thread", call.lineno, target, hint, multi])
            return
        if (
            isinstance(func, ast.Attribute)
            and (func.attr == "submit" or (func.attr == "map" and len(call.args) >= 2))
            and call.args
        ):
            target = _call_ref(call.args[0])
            if target is not None:
                ff["spawns"].append(["pool", call.lineno, target, None, True])
        ctor_name = None
        if isinstance(func, ast.Name):
            ctor_name = func.id
        elif isinstance(func, ast.Attribute):
            ctor_name = func.attr
        if ctor_name == "ThreadPoolExecutor":
            for kw in call.keywords:
                if (
                    kw.arg == "thread_name_prefix"
                    and isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)
                    and kw.value.value not in self.facts["pool_name_prefixes"]
                ):
                    self.facts["pool_name_prefixes"].append(kw.value.value)

    def _classify_blocking(
        self,
        call: ast.Call,
        ff: Dict[str, Any],
        ci: Optional[_ClassInfo],
        held: List[str],
    ) -> None:
        func = call.func
        kind: Optional[str] = None
        detail = ""
        if isinstance(func, ast.Name):
            if func.id == "open":
                kind, detail = "io", "open()"
            elif func.id in self.time_funcs and func.id == "sleep":
                kind, detail = "sleep", "sleep()"
            elif func.id == "sleep" and "sleep" in self.facts["bindings"] and (
                self.facts["bindings"]["sleep"][0] == "time"
            ):
                kind, detail = "sleep", "time.sleep()"
            elif func.id == "device_put" and self.facts["bindings"].get(
                "device_put", ["", ""]
            )[0] in ("jax", "jax.numpy"):
                kind, detail = "device", "device_put()"
        elif isinstance(func, ast.Attribute):
            base = func.value
            attr = func.attr
            base_name = base.id if isinstance(base, ast.Name) else None
            if base_name in self.time_names and attr == "sleep":
                kind, detail = "sleep", f"{base_name}.sleep()"
            elif base_name in ("os", "shutil") and attr in _OS_BLOCKING | {
                "copy", "copytree", "rmtree", "move"
            }:
                kind, detail = "io", f"{base_name}.{attr}()"
            elif base_name in self.jax_names and attr in (
                "device_put", "block_until_ready", "device_get"
            ):
                kind, detail = "device", f"{base_name}.{attr}()"
            elif attr in ("compile", "block_until_ready"):
                kind, detail = "device", f".{attr}()"
            elif attr == "result":
                kind, detail = "future", ".result()"
            elif attr == "join":
                tattr = _self_attr(base)
                if tattr is not None and ci is not None and tattr in ci.thread_attrs:
                    kind, detail = "join", f"self.{tattr}.join()"
            elif attr in ("get", "put"):
                tattr = _self_attr(base)
                if tattr is not None and ci is not None and tattr in ci.queue_attrs:
                    kind, detail = "queue", f"self.{tattr}.{attr}()"
            elif attr == "wait":
                tattr = _self_attr(base)
                if tattr is not None and ci is not None:
                    if tattr in ci.event_attrs:
                        kind, detail = "wait", f"self.{tattr}.wait()"
                    else:
                        canon = ci.lock_attr(tattr)
                        if canon is not None:
                            # Condition.wait RELEASES its own lock — only a
                            # wait on a *different* lock's condition blocks.
                            if f"self.{canon}" not in held:
                                kind, detail = "wait", f"self.{tattr}.wait()"
        if kind is not None:
            ff["blocking"].append([kind, call.lineno, detail, list(held)])

    def _classify_sync(self, call: ast.Call, ff: Dict[str, Any]) -> None:
        func = call.func
        params = set(ff["params"])
        if isinstance(func, ast.Attribute):
            if func.attr == "item" and not call.args:
                ff["sync_sites"].append(["item", call.lineno, ".item()"])
            elif func.attr == "block_until_ready" and not call.args:
                ff["sync_sites"].append(
                    ["block", call.lineno, ".block_until_ready()"]
                )
            elif (
                isinstance(func.value, ast.Name)
                and func.value.id in self.jax_names
                and func.attr in ("block_until_ready", "device_get")
            ):
                ff["sync_sites"].append(
                    ["block", call.lineno, f"jax.{func.attr}()"]
                )
            elif (
                isinstance(func.value, ast.Name)
                and func.value.id in self.np_names
                and func.attr in ("asarray", "array")
                and call.args
                and isinstance(call.args[0], ast.Name)
                and call.args[0].id in params
            ):
                ff["sync_sites"].append(
                    [
                        "asarray",
                        call.lineno,
                        f"np.{func.attr}({call.args[0].id})",
                    ]
                )
        elif isinstance(func, ast.Name):
            if (
                func.id in ("float", "int", "bool")
                and len(call.args) == 1
                and isinstance(call.args[0], ast.Name)
                and call.args[0].id in params
            ):
                ff["sync_sites"].append(
                    ["scalar", call.lineno, f"{func.id}({call.args[0].id})"]
                )

    # -- post passes -----------------------------------------------------------
    def _second_pass_jitted(self) -> None:
        """Mark defs passed by name to a ``jit(...)`` call as jitted, record
        kernel-spec name references, and KernelSpec constructions."""
        for qual, ff in self.facts["functions"].items():
            if ff["name"] in self.facts["jit_passed"] and ff["parent"] is None:
                ff["is_jitted"] = True
                if self.facts["jit_passed"][ff["name"]]["static"]:
                    ff["has_static"] = True
        # kernel-spec reference bookkeeping needs node identity, so it runs on
        # the AST directly (cheap: only modules importing ops.kernels).
        kimports = self.facts["kernels"]["imports"]
        spec_defs = [
            node
            for node in ast.walk(self.tree)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name in SPEC_DEF_NAMES
        ]
        spec_nodes: Set[int] = set()
        spec_records = []
        for fn in spec_defs:
            inside_nodes = set(map(id, ast.walk(fn)))
            spec_nodes |= inside_nodes
            inside_bases: Set[str] = set()
            inside_names: Set[str] = set()
            for n in ast.walk(fn):
                if isinstance(n, ast.Name) and n.id in kimports:
                    inside_bases.add(kimports[n.id])
                    inside_names.add(n.id)
            spec_records.append(
                {
                    "line": fn.lineno,
                    "trivial": _spec_trivial(fn),
                    "inside": sorted(inside_bases),
                    "names": sorted(inside_names),
                    "_nodes": inside_nodes,
                }
            )
        if kimports:
            outside: Set[str] = set(self.facts["kernels"]["outside"])
            for n in ast.walk(self.tree):
                if (
                    isinstance(n, ast.Name)
                    and n.id in kimports
                    and id(n) not in spec_nodes
                ):
                    outside.add(kimports[n.id])
            self.facts["kernels"]["outside"] = sorted(outside)
        for rec in spec_records:
            rec.pop("_nodes", None)
        self.facts["kernels"]["specs"] = spec_records
        # KernelSpec(...) constructions, paired with the enclosing spec def's
        # kernel references (elementwise-claim facts).
        for fn in spec_defs:
            for n in ast.walk(fn):
                if (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Name)
                    and n.func.id == "KernelSpec"
                ):
                    ew = False
                    for kw in n.keywords:
                        if kw.arg == "elementwise":
                            ew = bool(
                                isinstance(kw.value, ast.Constant) and kw.value.value
                            )
                    names = sorted(
                        {
                            x.id
                            for x in ast.walk(fn)
                            if isinstance(x, ast.Name) and x.id in kimports
                        }
                    )
                    self.facts["kspec_ctors"].append(
                        {"line": n.lineno, "elementwise": ew, "kernel_names": names}
                    )


def _spec_trivial(fn: ast.AST) -> bool:
    """Declaration-only kernel_spec: every return is bare / ``return None``."""
    returns = [n for n in ast.walk(fn) if isinstance(n, ast.Return)]
    return all(
        r.value is None
        or (isinstance(r.value, ast.Constant) and r.value.value is None)
        for r in returns
    )


def _name_literal(node: ast.AST) -> Optional[str]:
    """Literal (or literal head of an f-string) thread-name hint."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value
    return None


def _trip_point(call: ast.Call) -> Optional[str]:
    func = call.func
    is_trip = (
        isinstance(func, ast.Attribute)
        and func.attr == "trip"
        and isinstance(func.value, ast.Name)
        and func.value.id == "faults"
    ) or (isinstance(func, ast.Name) and func.id == "trip")
    if is_trip and call.args and isinstance(call.args[0], ast.Constant):
        if isinstance(call.args[0].value, str):
            return call.args[0].value
    return None


def _reduction_prim(call: ast.Call) -> Optional[str]:
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in REDUCTION_PRIMS:
        return func.attr
    if isinstance(func, ast.Name) and func.id in REDUCTION_PRIMS:
        return func.id
    return None


def _dtype_token(node: ast.AST) -> Optional[str]:
    """The low-precision dtype a dtype expression names, if any —
    ``jnp.bfloat16`` / bare ``bfloat16`` / the string ``"bfloat16"``."""
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value
    else:
        return None
    return name if name in LOWP_DTYPE_TOKENS else None


def _lowp_cast_token(call: ast.Call) -> Optional[str]:
    """A call site that casts to a sub-f32 dtype: ``x.astype(bf16)``,
    ``lax.convert_element_type(x, bf16)``, or any ``dtype=bf16`` /
    ``new_dtype=`` / ``preferred_element_type=`` keyword."""
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr == "astype" and call.args:
        tok = _dtype_token(call.args[0])
        if tok is not None:
            return tok
    if (
        isinstance(func, ast.Attribute)
        and func.attr == "convert_element_type"
        and len(call.args) >= 2
    ):
        tok = _dtype_token(call.args[1])
        if tok is not None:
            return tok
    for kw in call.keywords:
        if kw.arg in ("dtype", "new_dtype", "preferred_element_type"):
            tok = _dtype_token(kw.value)
            if tok is not None:
                return tok
    return None


def _call_ref(func: ast.AST) -> Optional[list]:
    """Serializable syntactic call reference, resolved by :class:`ProjectIndex`."""
    if isinstance(func, ast.Name):
        return ["n", func.id]
    if isinstance(func, ast.Attribute):
        v = func.value
        if isinstance(v, ast.Name):
            if v.id == "self":
                return ["self", func.attr]
            return ["attr", v.id, func.attr]
        inner_attr = _self_attr(v)
        if inner_attr is not None:
            return ["selfattr", inner_attr, func.attr]
        if isinstance(v, ast.Call):
            inner = _call_ref(v.func)
            if inner is not None:
                return ["resultm", inner, func.attr]
    return None


def extract_facts(rel: str, module: str, source: str, tree: Optional[ast.AST]) -> Dict[str, Any]:
    """Per-file facts for the index. ``tree`` is the parsed AST or ``None``
    (the caller records the parse error separately via ``parse_error``)."""
    if tree is None:
        return _empty_facts(rel, module)
    return _Extractor(rel, module, source, tree).run()


# ---------------------------------------------------------------------------
# ProjectIndex: global resolution over per-file facts
# ---------------------------------------------------------------------------


class ProjectIndex:
    """Resolved whole-program view over per-file facts. Node ids are
    ``"<module>:<qual>"`` (qual ``"f"``, ``"Cls.m"``, ``"Cls.m.<locals>.g"``)."""

    def __init__(self, facts_by_rel: Dict[str, Dict[str, Any]]):
        self.files = facts_by_rel
        self.by_module: Dict[str, Dict[str, Any]] = {
            f["module"]: f for f in facts_by_rel.values()
        }
        #: class simple name -> [(module, class facts dict)]
        self.class_table: Dict[str, List[Tuple[str, Dict[str, Any]]]] = {}
        for f in facts_by_rel.values():
            for cname, cfacts in f["classes"].items():
                self.class_table.setdefault(cname, []).append((f["module"], cfacts))
        #: resolved call graph: node -> [(target node, line)]
        self.edges: Dict[str, List[Tuple[str, int]]] = {}
        #: nested defs: node -> [child node]
        self.children: Dict[str, List[str]] = {}
        for f in facts_by_rel.values():
            module = f["module"]
            for qual, ff in f["functions"].items():
                node = f"{module}:{qual}"
                if ff["parent"]:
                    self.children.setdefault(f"{module}:{ff['parent']}", []).append(node)
                out: List[Tuple[str, int]] = []
                for ref, line, _held, _guards in ff["calls"]:
                    tgt = self.resolve_ref(module, ff["cls"], qual, ref)
                    if tgt is not None:
                        out.append((tgt, line))
                if out:
                    self.edges[node] = out

    # -- lookups ---------------------------------------------------------------
    def function(self, node: str) -> Optional[Dict[str, Any]]:
        module, _, qual = node.partition(":")
        f = self.by_module.get(module)
        return f["functions"].get(qual) if f else None

    def iter_functions(self, prefix: str = "") -> Iterable[Tuple[Dict[str, Any], str, Dict[str, Any]]]:
        """Yield (file facts, node id, function facts), optionally filtered by
        repo-relative path prefix."""
        for rel in sorted(self.files):
            f = self.files[rel]
            if prefix and not rel.startswith(prefix):
                continue
            for qual in sorted(f["functions"]):
                yield f, f"{f['module']}:{qual}", f["functions"][qual]

    def marks(self, node: str) -> List[str]:
        ff = self.function(node)
        return ff["marks"] if ff else []

    def resolve_class(self, name: str, prefer_module: Optional[str] = None) -> Optional[Tuple[str, Dict[str, Any]]]:
        entries = self.class_table.get(name)
        if not entries:
            return None
        if prefer_module is not None:
            for module, cfacts in entries:
                if module == prefer_module:
                    return module, cfacts
        return entries[0]

    def _method_node(self, cls_name: str, method: str, prefer_module: Optional[str]) -> Optional[str]:
        hit = self.resolve_class(cls_name, prefer_module)
        if hit is None:
            return None
        module, _cfacts = hit
        f = self.by_module.get(module)
        if f and f"{cls_name}.{method}" in f["functions"]:
            return f"{module}:{cls_name}.{method}"
        return None

    def _follow_binding(self, module: str, name: str, depth: int = 0):
        """Resolve an imported name to ('fn'|'class'|'singleton', module, name)."""
        if depth > 3:
            return None
        f = self.by_module.get(module)
        if f is None:
            return None
        if name in f["functions"] and f["functions"][name]["parent"] is None and f["functions"][name]["cls"] is None:
            return ("fn", module, name)
        if name in f["classes"]:
            return ("class", module, name)
        if name in f["singletons"]:
            return ("singleton", module, f["singletons"][name])
        if name in f["bindings"]:
            src, orig = f["bindings"][name]
            return self._follow_binding(src, orig, depth + 1)
        return None

    def resolve_ref(
        self, module: str, cls: Optional[str], qual: str, ref: list
    ) -> Optional[str]:
        f = self.by_module.get(module)
        if f is None or not ref:
            return None
        kind = ref[0]
        if kind == "self" and cls is not None:
            if f"{cls}.{ref[1]}" in f["functions"]:
                return f"{module}:{cls}.{ref[1]}"
            return None
        if kind == "n":
            name = ref[1]
            # lexically scoped nested defs: own children, then enclosing chain
            scope = qual
            while scope:
                cand = f"{scope}.<locals>.{name}"
                if cand in f["functions"]:
                    return f"{module}:{cand}"
                ff = f["functions"].get(scope)
                scope = ff["parent"] if ff else None
            if name in f["functions"] and f["functions"][name]["cls"] is None and f["functions"][name]["parent"] is None:
                return f"{module}:{name}"
            if name in f["classes"]:
                return self._method_node(name, "__init__", module)
            if name in f["singletons"]:
                return None
            if name in f["bindings"]:
                hit = self._follow_binding(*f["bindings"][name])
                if hit is None:
                    return None
                hkind, hmod, hname = hit
                if hkind == "fn":
                    return f"{hmod}:{hname}"
                if hkind == "class":
                    return self._method_node(hname, "__init__", hmod)
            return None
        if kind == "selfattr" and cls is not None:
            cfacts = f["classes"].get(cls)
            if not cfacts:
                return None
            tname = cfacts["attr_types"].get(ref[1])
            if tname:
                return self._method_node(tname, ref[2], module)
            return None
        if kind == "attr":
            obj, method = ref[1], ref[2]
            ff = f["functions"].get(qual)
            if ff is not None:
                tname = ff.get("local_types", {}).get(obj)
                if tname:  # annotated locals shadow module-level names
                    return self._method_node(tname, method, module)
            if obj in f["singletons"]:
                return self._method_node(f["singletons"][obj], method, module)
            if obj in f["bindings"]:
                hit = self._follow_binding(*f["bindings"][obj])
                if hit is not None:
                    hkind, hmod, hname = hit
                    if hkind in ("singleton", "class"):
                        return self._method_node(hname, method, hmod)
                    return None
            if obj in f["module_aliases"]:
                target = f["module_aliases"][obj]
                tf = self.by_module.get(target)
                if tf and method in tf["functions"]:
                    return f"{target}:{method}"
            return None
        if kind == "resultm":
            inner = self.resolve_ref(module, cls, qual, ref[1])
            if inner is None:
                return None
            iff = self.function(inner)
            if iff is None or not iff["returns_class"]:
                return None
            imod = inner.partition(":")[0]
            return self._method_node(iff["returns_class"], ref[2], imod)
        return None

    # -- traversals ------------------------------------------------------------
    def reachable(
        self,
        roots: Sequence[str],
        *,
        stop_marks: Sequence[str] = ("readback", "cold"),
        include_nested: bool = True,
    ) -> Dict[str, str]:
        """BFS over the call graph from ``roots``. Returns
        ``{node: root it was first reached from}``. Traversal does not enter
        functions carrying a stop mark (the annotated sync/cold boundaries)."""
        stop = set(stop_marks)
        out: Dict[str, str] = {}
        work: List[Tuple[str, str]] = [(r, r) for r in roots]
        while work:
            node, root = work.pop()
            if node in out:
                continue
            if set(self.marks(node)) & stop and node != root:
                continue
            out[node] = root
            for tgt, _line in self.edges.get(node, []):
                if tgt not in out:
                    work.append((tgt, root))
            if include_nested:
                for child in self.children.get(node, []):
                    if child not in out:
                        work.append((child, root))
        return out

    def transitive_closure(self, direct: Dict[str, Set[str]]) -> Dict[str, Set[str]]:
        """Fixpoint of ``direct`` propagated backwards over call edges: the
        result maps each node to ``direct`` facts reachable through any call
        chain starting at it (lock acquisition, blocking ops, ...)."""
        trans: Dict[str, Set[str]] = {k: set(v) for k, v in direct.items()}
        changed = True
        while changed:
            changed = False
            for node, outs in self.edges.items():
                mine = trans.setdefault(node, set())
                before = len(mine)
                for tgt, _line in outs:
                    mine |= trans.get(tgt, set())
                if len(mine) != before:
                    changed = True
        return trans
