import sys

from tools.graftcheck.cli import main

if __name__ == "__main__":
    sys.exit(main())
