"""host-sync: no implicit device→host syncs reachable from hot-path roots.

jit-purity polices host syncs *inside* jitted functions, but the serving
dispatch loop and the batch chunk loop are hot regions **outside** jit: a
``.item()`` or an eager ``np.asarray`` three calls below
``MicroBatcher._loop`` stalls the host on device work once per batch —
exactly the goodput leak the fast paths' deferred-readback design exists to
avoid (one blocking readback per batch, at the designated point, after the
next batch has been dispatched). "ML Productivity Goodput" (PAPERS.md)
attributes a large slice of fleet waste to precisely these host stalls.

The rule generalizes jit-purity to hot regions via the shared index's call
graph and the **annotated-hot-root convention** (docs/static_analysis.md):

- functions marked ``# graftcheck: hot-root`` (the serving dispatch loop in
  ``serving/``, the batch chunk loop in ``builder/batch_plan.py``, the shared
  chain executor in ``servable/planner.py``) are traversal roots;
- everything reachable from a root through resolved calls — nested defs
  included — is the hot region;
- functions marked ``# graftcheck: readback`` are the designated sync
  boundaries (each plan has exactly one blocking readback); traversal stops
  there and their bodies are exempt;
- functions marked ``# graftcheck: cold`` are build/warmup-time code lazily
  reachable from a hot root (counted by its own metric when taken); excluded.

Flagged inside the hot region:

- ``<x>.item()``                    — device→host sync per call
- ``<x>.block_until_ready()`` / ``jax.block_until_ready`` / ``jax.device_get``
                                    — explicit host stall
- ``np.asarray(p)`` / ``np.array(p)`` on a direct function parameter
                                    — eager host materialization
- ``float(p)`` / ``int(p)`` / ``bool(p)`` on a direct function parameter
                                    — host concretization
- ``jax.device_put(...)``           — host→device upload, unless the function
                                    is marked ``# graftcheck: ingest``: the
                                    plan tier's designated ingest boundaries
                                    (the batch chunk uploader,
                                    ``PlanSharding.put_batch``/``put_replicated``)
                                    are the ONLY places the sharded fast
                                    paths may upload — one ``device_put`` per
                                    chunk, split per shard by the runtime.
                                    Anywhere else in a hot region it is a
                                    per-call transfer the AOT weight-resident
                                    design exists to avoid (weights commit at
                                    swap/build time, request rows ride the
                                    compiled executable's own intake).
- file I/O (``open()``, blocking ``os.*``/``shutil.*``) inside the
  device-adjacent tiers — the persistent plan cache (``servable/plancache.py``)
  put disk reads/writes one call below the chain executor, so the rule now
  proves cache I/O can never be reached from a hot root: ``PlanCache``'s
  load/store surfaces are ``# graftcheck: cold`` (taken only on the
  compile/warmup path, counted by ``ml.plancache.*``), and any OTHER file
  I/O a hot region grows is flagged. Scoped to the device-adjacent tiers by
  the I/O site's own file, like the parameter heuristics: host-side tiers
  (checkpointing, datacache spill) have their own designated I/O seams.

As with jit-purity the numpy/float checks fire on direct parameters only
(numpy on values that are already host-resident is legal and common) — false
negatives are acceptable, false positives are not. For the same reason the
parameter-based checks (``np.asarray`` / ``float``) only report inside the
device-adjacent tiers (``serving/``, ``servable/``, ``builder/``, ``ops/``)
where a parameter plausibly holds a device array; ``.item()`` and
``block_until_ready`` are unambiguous syncs and report anywhere a hot root
reaches (the host-side ``api``/``metrics`` layers take parameters that are
plain host values by contract).
"""
from __future__ import annotations

from typing import List

from tools.graftcheck.engine import Finding, Project, Rule, register

#: Where the parameter-heuristic kinds (asarray/scalar) are trusted.
DEVICE_TIER_PREFIXES = (
    "flink_ml_tpu/serving/",
    "flink_ml_tpu/servable/",
    "flink_ml_tpu/builder/",
    "flink_ml_tpu/ops/",
    # the continuous loop's serve/evaluate turns touch device-backed serving
    # results; its publish/warm/rollback edges are `# graftcheck: cold`
    "flink_ml_tpu/loop/",
    # graftscope span machinery runs inside every hot region; its
    # flush/export surface is `# graftcheck: cold`
    "flink_ml_tpu/trace",
)

_KIND_MESSAGES = {
    "item": "forces a device->host sync on every call",
    "block": "stalls the host on device work",
    "asarray": "eagerly materializes a traced/device value on the host",
    "scalar": "concretizes a value on the host",
}

_PARAM_KINDS = {"asarray", "scalar"}


@register
class HostSyncRule(Rule):
    name = "host-sync"
    severity = "error"
    cache_version = 2  # v2: file I/O flagged in device-tier hot regions
    description = (
        "no device->host syncs (.item(), block_until_ready, np.asarray/float "
        "on parameters), host->device uploads (device_put outside "
        "`# graftcheck: ingest` boundaries), nor device-tier file I/O "
        "(open/os/shutil — plan-cache discipline) reachable from "
        "`# graftcheck: hot-root` functions, outside the designated "
        "`# graftcheck: readback` boundaries"
    )

    def run(self, project: Project) -> List[Finding]:
        index = project.index
        roots = [
            node
            for _facts, node, ff in index.iter_functions()
            if "hot-root" in ff["marks"]
        ]
        if not roots:
            return []
        reach = index.reachable(roots)
        findings: List[Finding] = []
        rel_of = {f["module"]: rel for rel, f in index.files.items()}
        for node in sorted(reach):
            ff = index.function(node)
            if ff is None:
                continue
            module = node.partition(":")[0]
            rel = rel_of.get(module)
            if rel is None:
                continue
            root_display = reach[node].replace(":", ".")
            in_device_tier = any(rel.startswith(p) for p in DEVICE_TIER_PREFIXES)
            for kind, line, detail in ff["sync_sites"]:
                if kind in _PARAM_KINDS and not in_device_tier:
                    continue
                findings.append(
                    self.finding(
                        rel,
                        line,
                        f"hot region (reachable from hot-root {root_display}): "
                        f"{detail} {_KIND_MESSAGES[kind]} — defer it to the "
                        "designated `# graftcheck: readback` boundary or move "
                        "it off the hot path",
                    )
                )
            # Per-device uploads: device_put belongs to the designated
            # `# graftcheck: ingest` boundaries (one per chunk/shard);
            # anywhere else in a hot region it is a per-call host->device
            # transfer the weight-resident AOT design forbids.
            if "ingest" in ff["marks"]:
                continue
            for kind, line, detail, _held in ff["blocking"]:
                if kind == "device" and "device_put" in detail:
                    findings.append(
                        self.finding(
                            rel,
                            line,
                            f"hot region (reachable from hot-root {root_display}): "
                            f"{detail} uploads host data per call — route it "
                            "through a designated `# graftcheck: ingest` "
                            "boundary (one device_put per chunk, split per "
                            "shard) or commit it at build/warmup time",
                        )
                    )
                elif kind == "io" and in_device_tier:
                    # The plan-cache discipline: disk I/O belongs to the
                    # `# graftcheck: cold` load/store surfaces (compile and
                    # warmup paths), never to a hot dispatch region.
                    findings.append(
                        self.finding(
                            rel,
                            line,
                            f"hot region (reachable from hot-root {root_display}): "
                            f"{detail} performs file I/O on a hot path — move "
                            "it behind a `# graftcheck: cold` build/warmup "
                            "surface (the plan-cache load/store discipline)",
                        )
                    )
        return findings
