"""registry-consistency: the string-keyed contract registries cannot drift.

Two registries hold the system's operational contract — the config options
(``config.py`` ``Options``) and the ``ml.*`` metric names (``metrics.py``
``MLMetrics``) — and both are documented in tables that nothing previously
kept honest. This rule runs the three-way diffs every time:

Config options (``config.py`` vs code vs ``docs/configuration.md``):

- **dead option** — declared but no ``Options.X`` reference anywhere in the
  tree (reads inside config.py itself count: ``resolve_cache_config`` is a
  legitimate consumer). Anchored at the declaration.
- **undocumented option** — declared and referenced, but no row in the
  configuration.md table. Anchored at the declaration.
- **ghost row** — a documented key no ``ConfigOption`` declares. Anchored at
  the doc row.

Metric names (``MLMetrics`` vs code vs ``docs/observability.md``):

- **dead metric** — a non-``_GROUP`` constant nothing references. ``_GROUP``
  constants are scope prefixes, not metric names; an unreferenced one is
  still dead weight and flagged the same way.
- **undocumented metric** — a referenced constant with no row in the
  observability.md metric-name registry table.
- **ghost row** — an observability.md row naming neither a declared constant
  nor a dynamic family (``DYNAMIC_FAMILIES`` — names built by
  ``goodput_ms``/``fallback_reason`` style helpers, documented with
  ``<placeholder>`` segments).
- **unregistered literal** — an inline ``"ml.*"`` string in code (outside
  metrics.py) that is neither a declared metric value nor a scope token
  (``ml.<group>`` with an optional ``[qualifier]``) — new metric names must
  enter through the MLMetrics registry, not ad hoc literals.

The doc files are read from the analyzed tree's own root (fixture trees
without them simply skip the doc legs), so the rule stays hermetic.
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Set, Tuple

from tools.graftcheck.engine import Finding, Project, Rule, register

CONFIG_REL = "flink_ml_tpu/config.py"
METRICS_REL = "flink_ml_tpu/metrics.py"
CONFIG_DOC_REL = "docs/configuration.md"
METRICS_DOC_REL = "docs/observability.md"

#: Metric-name families produced by the MLMetrics helper methods — their
#: doc rows use <placeholder> segments. Each entry: (helper attr, row regex).
DYNAMIC_FAMILIES: Tuple[Tuple[str, str], ...] = (
    ("goodput_ms", r"ml\.goodput\.<[a-z_]+>\.ms"),
    ("fallback_reason", r"ml\.<[a-z_]+>\.fastpath\.fallback\.<[a-z_]+>"),
)

#: Inline scope tokens: a group prefix with an optional plan/bounded-style
#: qualifier (``"ml.batch[plan]"``, ``"ml.iteration"``) — scopes, not names.
_SCOPE_RE = re.compile(r"^ml\.[a-z_]+(\[[a-z_]+\])?$")

_DOC_ROW_RE = re.compile(r"^\|\s*`(ml\.[a-z0-9_.<>]+)`")
_CONFIG_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_.\-]+)`\s*\|")


def _doc_rows(project: Project, rel: str, pattern: re.Pattern) -> List[Tuple[str, int]]:
    path = os.path.join(project.repo_root, rel)
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return []
    out: List[Tuple[str, int]] = []
    for i, line in enumerate(lines, 1):
        m = pattern.match(line)
        if m:
            out.append((m.group(1), i))
    return out


@register
class RegistryConsistencyRule(Rule):
    name = "registry-consistency"
    severity = "error"
    granularity = "project"
    cache_version = 1
    description = (
        "config options and ml.* metric names must agree across declaration, "
        "use, and the configuration.md/observability.md tables"
    )

    def run(self, project: Project) -> List[Finding]:
        facts = project.facts()
        findings: List[Finding] = []
        findings += self._config_leg(project, facts)
        findings += self._metrics_leg(project, facts)
        return findings

    # -- config options --------------------------------------------------------
    def _config_leg(self, project: Project, facts) -> List[Finding]:
        cf = facts.get(CONFIG_REL)
        if not cf or not cf["config_options"]:
            return []
        declared: Dict[str, Tuple[str, int]] = {
            attr: (key, line) for attr, key, line in cf["config_options"]
        }
        referenced: Set[str] = set()
        for f in facts.values():
            for attr, _line in f.get("option_refs", ()):
                referenced.add(attr)
        doc = _doc_rows(project, CONFIG_DOC_REL, _CONFIG_ROW_RE)
        doc_keys = {key for key, _ in doc}
        have_doc = bool(doc)

        out: List[Finding] = []
        for attr, (key, line) in sorted(declared.items()):
            if attr not in referenced:
                out.append(self.finding(
                    CONFIG_REL, line,
                    f"option {key!r} ({attr}) is declared but never "
                    "referenced — remove it or wire the consumer",
                ))
            elif have_doc and key not in doc_keys:
                out.append(self.finding(
                    CONFIG_REL, line,
                    f"option {key!r} ({attr}) has no row in "
                    f"{CONFIG_DOC_REL} — document it",
                ))
        declared_keys = {key for key, _ in declared.values()}
        for key, line in doc:
            if key not in declared_keys:
                out.append(self.finding(
                    CONFIG_DOC_REL, line,
                    f"{CONFIG_DOC_REL} documents {key!r} but no ConfigOption "
                    "declares that key — delete the stale row",
                ))
        return out

    # -- metric names ----------------------------------------------------------
    def _metrics_leg(self, project: Project, facts) -> List[Finding]:
        mf = facts.get(METRICS_REL)
        if not mf or not mf["metric_consts"]:
            return []
        declared: Dict[str, Tuple[str, int]] = {
            attr: (value, line) for attr, value, line in mf["metric_consts"]
        }
        values = {value for value, _ in declared.values()}
        referenced: Set[str] = set()
        for f in facts.values():
            for attr, _line in f.get("metric_refs", ()):
                referenced.add(attr)
        doc = _doc_rows(project, METRICS_DOC_REL, _DOC_ROW_RE)
        doc_names = {name for name, _ in doc}
        have_doc = bool(doc)
        family_res = [re.compile(pat + r"$") for _, pat in DYNAMIC_FAMILIES]

        out: List[Finding] = []
        for attr, (value, line) in sorted(declared.items()):
            if attr not in referenced:
                out.append(self.finding(
                    METRICS_REL, line,
                    f"metric constant {attr} = {value!r} is never referenced "
                    "— remove it or wire the emitter",
                ))
            elif (
                have_doc
                and not attr.endswith("_GROUP")  # scopes have no metric row
                and value not in doc_names
            ):
                out.append(self.finding(
                    METRICS_REL, line,
                    f"metric {value!r} ({attr}) is emitted but has no row in "
                    f"the {METRICS_DOC_REL} registry table — document it",
                ))
        for name, line in doc:
            if name in values:
                continue
            if "<" in name and any(r.fullmatch(name) for r in family_res):
                continue
            out.append(self.finding(
                METRICS_DOC_REL, line,
                f"{METRICS_DOC_REL} documents {name!r} but no MLMetrics "
                "constant or dynamic family produces that name — delete or "
                "fix the row",
            ))
        # inline literals outside the registry module
        for rel, f in sorted(facts.items()):
            if rel == METRICS_REL:
                continue
            for value, line in f.get("metric_literals", ()):
                if value in values or _SCOPE_RE.match(value):
                    continue
                out.append(self.finding(
                    rel, line,
                    f"inline metric literal {value!r} is not a registered "
                    "MLMetrics name — declare it in metrics.py and use the "
                    "constant",
                ))
        return out
